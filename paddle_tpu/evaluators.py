"""Evaluators — streaming task metrics.

Reference: paddle/gserver/evaluators/Evaluator.cpp:40-1346
(classification_error, sum, column_sum, precision_recall, pnpair, rankauc,
printers) with start/evalImp/finish accumulation across batches. Same
contract: `start()`, `add_batch(outs, feed)` per batch (device work is one
jnp reduction; accumulation is host floats), `result()`.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.registry import EVALUATORS


class Evaluator:
    """conf: {"name", "type", "input", "label", ...} — evaluator configs
    reference output/label layers by name."""

    def __init__(self, conf: dict):
        self.conf = conf
        self.name = conf.get("name", conf["type"])
        self.start()

    def start(self):
        raise NotImplementedError

    def add_batch(self, outs: dict, feed: dict):
        raise NotImplementedError

    def result(self):
        raise NotImplementedError

    # helpers
    def _get(self, outs, feed, key):
        name = self.conf[key]
        if name in outs:
            return outs[name]
        return feed[name]

    @staticmethod
    def _masked_pairs(pred: Arg, label: Arg):
        """Return flat (pred_rows, label_ids, weight) with padding dropped
        via mask weights (sequence-aware, like the reference's
        sequence-level eval accounting)."""
        p = np.asarray(pred.value)
        l = np.asarray(label.ids if label.ids is not None else label.value)
        if pred.is_seq:
            m = np.asarray(pred.mask())
            p = p.reshape(-1, p.shape[-1])
            l = l.reshape(-1)
            w = m.reshape(-1)
        else:
            p = p.reshape(p.shape[0], -1)
            l = l.reshape(-1)
            w = np.ones(p.shape[0])
        return p, l, w


@EVALUATORS.register("classification_error")
class ClassificationErrorEvaluator(Evaluator):
    """(Evaluator.cpp:172 ClassificationErrorEvaluator)."""

    def start(self):
        self.wrong = 0.0
        self.total = 0.0

    def add_batch(self, outs, feed):
        pred = self._get(outs, feed, "input")
        label = self._get(outs, feed, "label")
        p, l, w = self._masked_pairs(pred, label)
        hit = (np.argmax(p, axis=-1) == l).astype(np.float64)
        self.wrong += float(((1.0 - hit) * w).sum())
        self.total += float(w.sum())

    def result(self):
        return self.wrong / max(self.total, 1.0)


@EVALUATORS.register("sum")
class SumEvaluator(Evaluator):
    """(Evaluator.cpp:40 SumEvaluator)."""

    def start(self):
        self.sum = 0.0
        self.total = 0.0

    def add_batch(self, outs, feed):
        x = self._get(outs, feed, "input")
        v = np.asarray(x.value)
        if x.is_seq:
            m = np.asarray(x.mask()).reshape(v.shape[:2] + (1,) * (v.ndim - 2))
            v = v * m
            self.total += float(np.asarray(x.seq_lens).sum())
        else:
            self.total += v.shape[0]
        self.sum += float(v.sum())

    def result(self):
        return self.sum / max(self.total, 1.0)


@EVALUATORS.register("column_sum")
class ColumnSumEvaluator(Evaluator):
    """(Evaluator.cpp:503 ColumnSumEvaluator)."""

    def start(self):
        self.sum = None
        self.total = 0.0

    def add_batch(self, outs, feed):
        x = self._get(outs, feed, "input")
        v = np.asarray(x.value).reshape(-1, np.asarray(x.value).shape[-1])
        s = v.sum(axis=0)
        self.sum = s if self.sum is None else self.sum + s
        self.total += v.shape[0]

    def result(self):
        return self.sum / max(self.total, 1.0)


@EVALUATORS.register("precision_recall")
class PrecisionRecallEvaluator(Evaluator):
    """(Evaluator.cpp:862 PrecisionRecallEvaluator). Multi-class
    macro-averaged; conf may set "positive_label" for binary."""

    def start(self):
        self.tp = {}
        self.fp = {}
        self.fn = {}

    def add_batch(self, outs, feed):
        pred = self._get(outs, feed, "input")
        label = self._get(outs, feed, "label")
        p, l, w = self._masked_pairs(pred, label)
        yhat = np.argmax(p, axis=-1)
        for c in np.unique(np.concatenate([yhat, l])):
            c = int(c)
            real = w > 0
            self.tp[c] = self.tp.get(c, 0) + int(((yhat == c) & (l == c) & real).sum())
            self.fp[c] = self.fp.get(c, 0) + int(((yhat == c) & (l != c) & real).sum())
            self.fn[c] = self.fn.get(c, 0) + int(((yhat != c) & (l == c) & real).sum())

    def result(self):
        pos = self.conf.get("positive_label")
        classes = [pos] if pos is not None else sorted(self.tp)
        precs, recs = [], []
        for c in classes:
            tp, fp, fn = self.tp.get(c, 0), self.fp.get(c, 0), self.fn.get(c, 0)
            precs.append(tp / max(tp + fp, 1))
            recs.append(tp / max(tp + fn, 1))
        p, r = float(np.mean(precs)), float(np.mean(recs))
        f1 = 2 * p * r / max(p + r, 1e-12)
        return {"precision": p, "recall": r, "F1": f1}


@EVALUATORS.register("pnpair")
class PnpairEvaluator(Evaluator):
    """Positive-negative pair ordering ratio (Evaluator.cpp:995
    PnpairEvaluator): for query-grouped (score, label) pairs, counts
    correctly-ordered pos>neg pairs. conf: input (score), label, query_id."""

    def start(self):
        self.pairs = []  # (qid, score, label)

    def add_batch(self, outs, feed):
        score = self._get(outs, feed, "input")
        label = self._get(outs, feed, "label")
        qid = self._get(outs, feed, "query_id")
        s = np.asarray(score.value).reshape(-1)
        l = np.asarray(label.ids).reshape(-1)
        q = np.asarray(qid.ids).reshape(-1)
        self.pairs.extend(zip(q.tolist(), s.tolist(), l.tolist()))

    def result(self):
        from collections import defaultdict

        by_q = defaultdict(list)
        for q, s, l in self.pairs:
            by_q[q].append((s, l))
        good = bad = 0.0
        for items in by_q.values():
            for i in range(len(items)):
                for j in range(i + 1, len(items)):
                    (si, li), (sj, lj) = items[i], items[j]
                    if li == lj:
                        continue
                    hi, lo = (si, sj) if li > lj else (sj, si)
                    if hi > lo:
                        good += 1
                    elif hi < lo:
                        bad += 1
                    else:
                        good += 0.5
                        bad += 0.5
        return good / max(bad, 1e-12)


@EVALUATORS.register("rankauc")
class AucEvaluator(Evaluator):
    """ROC AUC on binary scores (Evaluator.cpp:584 AucEvaluator),
    histogram-bucketed like the reference."""

    BINS = 4096

    def start(self):
        self.pos = np.zeros(self.BINS)
        self.neg = np.zeros(self.BINS)

    def add_batch(self, outs, feed):
        score = self._get(outs, feed, "input")
        label = self._get(outs, feed, "label")
        s = np.asarray(score.value)
        s = s[..., -1] if s.shape[-1] > 1 else s.reshape(-1)
        s = np.clip(s.reshape(-1), 0.0, 1.0)
        l = np.asarray(label.ids).reshape(-1)
        idx = np.minimum((s * self.BINS).astype(np.int64), self.BINS - 1)
        np.add.at(self.pos, idx[l == 1], 1)
        np.add.at(self.neg, idx[l == 0], 1)

    def result(self):
        # sum over thresholds of trapezoid areas, descending score
        pos_c = np.cumsum(self.pos[::-1])
        neg_c = np.cumsum(self.neg[::-1])
        tot_pos, tot_neg = pos_c[-1], neg_c[-1]
        if tot_pos == 0 or tot_neg == 0:
            return 0.5
        tpr = pos_c / tot_pos
        fpr = neg_c / tot_neg
        return float(np.trapezoid(tpr, fpr))


def create_evaluator(conf: dict) -> Evaluator:
    return EVALUATORS.get(conf["type"])(conf)
