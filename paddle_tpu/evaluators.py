"""Evaluators — streaming task metrics.

Reference: paddle/gserver/evaluators/Evaluator.cpp:40-1346
(classification_error, sum, column_sum, precision_recall, pnpair, rankauc,
printers) with start/evalImp/finish accumulation across batches. Same
contract: `start()`, `add_batch(outs, feed)` per batch (device work is one
jnp reduction; accumulation is host floats), `result()`.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.registry import EVALUATORS


class Evaluator:
    """conf: {"name", "type", "input", "label", ...} — evaluator configs
    reference output/label layers by name."""

    def __init__(self, conf: dict):
        self.conf = conf
        self.name = conf.get("name", conf["type"])
        self.start()

    def start(self):
        raise NotImplementedError

    def add_batch(self, outs: dict, feed: dict):
        raise NotImplementedError

    def result(self):
        raise NotImplementedError

    # helpers
    def _get(self, outs, feed, key):
        name = self.conf[key]
        if name in outs:
            return outs[name]
        return feed[name]

    @staticmethod
    def _masked_pairs(pred: Arg, label: Arg):
        """Return flat (pred_rows, label_ids, weight) with padding dropped
        via mask weights (sequence-aware, like the reference's
        sequence-level eval accounting)."""
        p = np.asarray(pred.value)
        l = np.asarray(label.ids if label.ids is not None else label.value)
        if pred.is_seq:
            if l.ndim >= 2 and l.shape[1] != p.shape[1]:
                # independent padding (a per-subsequence prediction vs
                # the label's own bucket) — align to the prediction's
                # time axis; padding is masked below either way
                tp = p.shape[1]
                if l.shape[1] > tp:
                    l = l[:, :tp]
                else:
                    l = np.pad(l, ((0, 0), (0, tp - l.shape[1])))
            m = np.asarray(pred.mask())
            p = p.reshape(-1, p.shape[-1])
            l = l.reshape(-1)
            w = m.reshape(-1)
        else:
            p = p.reshape(p.shape[0], -1)
            l = l.reshape(-1)
            w = np.ones(p.shape[0])
        return p, l, w


@EVALUATORS.register("classification_error")
class ClassificationErrorEvaluator(Evaluator):
    """(Evaluator.cpp:172 ClassificationErrorEvaluator). conf "top_k"
    (the reference's classification_threshold/num_results family):
    a prediction counts as correct when the label is among the k
    highest-scoring classes (default 1)."""

    def start(self):
        self.wrong = 0.0
        self.total = 0.0

    def add_batch(self, outs, feed):
        pred = self._get(outs, feed, "input")
        label = self._get(outs, feed, "label")
        k = int(self.conf.get("top_k", 1))
        p, l, w = self._masked_pairs(pred, label)
        if k <= 1:
            hit = (np.argmax(p, axis=-1) == l).astype(np.float64)
        else:
            topk = np.argpartition(-p, min(k, p.shape[-1] - 1), axis=-1)[
                :, :k
            ]
            hit = (topk == l[:, None]).any(axis=-1).astype(np.float64)
        self.wrong += float(((1.0 - hit) * w).sum())
        self.total += float(w.sum())

    def result(self):
        return self.wrong / max(self.total, 1.0)


@EVALUATORS.register("sum")
class SumEvaluator(Evaluator):
    """(Evaluator.cpp:40 SumEvaluator)."""

    def start(self):
        self.sum = 0.0
        self.total = 0.0

    def add_batch(self, outs, feed):
        x = self._get(outs, feed, "input")
        v = np.asarray(x.value)
        if x.is_seq:
            m = np.asarray(x.mask()).reshape(v.shape[:2] + (1,) * (v.ndim - 2))
            v = v * m
            self.total += float(np.asarray(x.seq_lens).sum())
        else:
            self.total += v.shape[0]
        self.sum += float(v.sum())

    def result(self):
        return self.sum / max(self.total, 1.0)


@EVALUATORS.register("column_sum")
class ColumnSumEvaluator(Evaluator):
    """(Evaluator.cpp:503 ColumnSumEvaluator)."""

    def start(self):
        self.sum = None
        self.total = 0.0

    def add_batch(self, outs, feed):
        x = self._get(outs, feed, "input")
        v = np.asarray(x.value).reshape(-1, np.asarray(x.value).shape[-1])
        s = v.sum(axis=0)
        self.sum = s if self.sum is None else self.sum + s
        self.total += v.shape[0]

    def result(self):
        return self.sum / max(self.total, 1.0)


@EVALUATORS.register("precision_recall")
class PrecisionRecallEvaluator(Evaluator):
    """(Evaluator.cpp:862 PrecisionRecallEvaluator). Multi-class
    macro-averaged; conf may set "positive_label" for binary."""

    def start(self):
        self.tp = {}
        self.fp = {}
        self.fn = {}

    def add_batch(self, outs, feed):
        pred = self._get(outs, feed, "input")
        label = self._get(outs, feed, "label")
        p, l, w = self._masked_pairs(pred, label)
        yhat = np.argmax(p, axis=-1)
        for c in np.unique(np.concatenate([yhat, l])):
            c = int(c)
            real = w > 0
            self.tp[c] = self.tp.get(c, 0) + int(((yhat == c) & (l == c) & real).sum())
            self.fp[c] = self.fp.get(c, 0) + int(((yhat == c) & (l != c) & real).sum())
            self.fn[c] = self.fn.get(c, 0) + int(((yhat != c) & (l == c) & real).sum())

    def result(self):
        pos = self.conf.get("positive_label")
        classes = [pos] if pos is not None else sorted(self.tp)
        precs, recs = [], []
        for c in classes:
            tp, fp, fn = self.tp.get(c, 0), self.fp.get(c, 0), self.fn.get(c, 0)
            precs.append(tp / max(tp + fp, 1))
            recs.append(tp / max(tp + fn, 1))
        p, r = float(np.mean(precs)), float(np.mean(recs))
        f1 = 2 * p * r / max(p + r, 1e-12)
        return {"precision": p, "recall": r, "F1": f1}


@EVALUATORS.register("pnpair")
class PnpairEvaluator(Evaluator):
    """Positive-negative pair ordering ratio (Evaluator.cpp:995
    PnpairEvaluator): for query-grouped (score, label) pairs, counts
    correctly-ordered pos>neg pairs. conf: input (score), label, query_id."""

    def start(self):
        self.pairs = []  # (qid, score, label)

    def add_batch(self, outs, feed):
        score = self._get(outs, feed, "input")
        label = self._get(outs, feed, "label")
        qid = self._get(outs, feed, "query_id")
        s = np.asarray(score.value).reshape(-1)
        l = np.asarray(label.ids).reshape(-1)
        q = np.asarray(qid.ids).reshape(-1)
        self.pairs.extend(zip(q.tolist(), s.tolist(), l.tolist()))

    def result(self):
        from collections import defaultdict

        by_q = defaultdict(list)
        for q, s, l in self.pairs:
            by_q[q].append((s, l))
        good = bad = 0.0
        for items in by_q.values():
            for i in range(len(items)):
                for j in range(i + 1, len(items)):
                    (si, li), (sj, lj) = items[i], items[j]
                    if li == lj:
                        continue
                    hi, lo = (si, sj) if li > lj else (sj, si)
                    if hi > lo:
                        good += 1
                    elif hi < lo:
                        bad += 1
                    else:
                        good += 0.5
                        bad += 0.5
        return good / max(bad, 1e-12)


@EVALUATORS.register("rankauc")
class AucEvaluator(Evaluator):
    """ROC AUC on binary scores (Evaluator.cpp:584 AucEvaluator),
    histogram-bucketed like the reference."""

    BINS = 4096

    def start(self):
        self.pos = np.zeros(self.BINS)
        self.neg = np.zeros(self.BINS)

    def add_batch(self, outs, feed):
        score = self._get(outs, feed, "input")
        label = self._get(outs, feed, "label")
        s = np.asarray(score.value)
        s = s[..., -1] if s.shape[-1] > 1 else s.reshape(-1)
        s = np.clip(s.reshape(-1), 0.0, 1.0)
        l = np.asarray(label.ids).reshape(-1)
        idx = np.minimum((s * self.BINS).astype(np.int64), self.BINS - 1)
        np.add.at(self.pos, idx[l == 1], 1)
        np.add.at(self.neg, idx[l == 0], 1)

    def result(self):
        # sum over thresholds of trapezoid areas, descending score
        pos_c = np.cumsum(self.pos[::-1])
        neg_c = np.cumsum(self.neg[::-1])
        tot_pos, tot_neg = pos_c[-1], neg_c[-1]
        if tot_pos == 0 or tot_neg == 0:
            return 0.5
        tpr = pos_c / tot_pos
        fpr = neg_c / tot_neg
        return float(np.trapezoid(tpr, fpr))


@EVALUATORS.register("seq_classification_error")
class SequenceClassificationErrorEvaluator(Evaluator):
    """Sequence-level classification error (Evaluator.cpp:135
    SequenceClassificationErrorEvaluator): a sequence counts as wrong if
    ANY frame in it is wrong."""

    def start(self):
        self.wrong = 0.0
        self.total = 0.0

    def add_batch(self, outs, feed):
        pred = self._get(outs, feed, "input")
        label = self._get(outs, feed, "label")
        p = np.asarray(pred.value)  # [B,T,C]
        l = np.asarray(label.ids if label.ids is not None else label.value)
        l = l.reshape(p.shape[0], p.shape[1])
        m = np.asarray(pred.mask())
        frame_err = (np.argmax(p, axis=-1) != l) & (m > 0)
        self.wrong += float((frame_err.any(axis=-1)).sum())
        self.total += p.shape[0]

    def result(self):
        return self.wrong / max(self.total, 1.0)


@EVALUATORS.register("chunk")
class ChunkEvaluator(Evaluator):
    """IOB/IOE/IOBES/plain chunking F1 (ChunkEvaluator.cpp). A chunk is
    correct iff begin, end, and type all match. Label encoding (the
    reference's): tag = label % num_tag_types, type = label // num_tag_types,
    with the "other" type == num_chunk_types. conf: chunk_scheme,
    num_chunk_types, excluded_chunk_types, input (decoded ids), label."""

    SCHEMES = {
        # scheme: (num_tag_types, begin, inside, end, single)
        "plain": (1, -1, -1, -1, -1),
        "IOB": (2, 0, 1, -1, -1),
        "IOE": (2, -1, 0, 1, -1),
        "IOBES": (4, 0, 1, 2, 3),
    }

    def start(self):
        scheme = self.conf.get("chunk_scheme", "IOB")
        (
            self.num_tag,
            self.tag_b,
            self.tag_i,
            self.tag_e,
            self.tag_s,
        ) = self.SCHEMES[scheme]
        self.num_chunk_types = self.conf["num_chunk_types"]
        self.other = self.num_chunk_types
        self.excluded = set(self.conf.get("excluded_chunk_types", ()))
        self.n_label = 0
        self.n_output = 0
        self.n_correct = 0

    # -- chunk boundary rules (ChunkEvaluator.cpp:225-245), data not code --
    def _is_end(self, ptag, ptype, tag, typ):
        if ptype == self.other:
            return False
        if typ == self.other or typ != ptype:
            return True
        if ptag in (self.tag_b, self.tag_i) and ptag >= 0:
            return tag in (self.tag_b, self.tag_s) and tag >= 0
        return ptag in (self.tag_e, self.tag_s) and ptag >= 0

    def _is_begin(self, ptag, ptype, tag, typ):
        if ptype == self.other:
            return typ != self.other
        if typ == self.other:
            return False
        if typ != ptype:
            return True
        if tag == self.tag_b or tag == self.tag_s:
            return True
        if tag in (self.tag_i, self.tag_e) and tag >= 0:
            return ptag in (self.tag_e, self.tag_s) and ptag >= 0
        return False

    def _segments(self, labels):
        segs, in_chunk, start = [], False, 0
        tag, typ = -1, self.other
        for i, lab in enumerate(labels):
            ptag, ptype = tag, typ
            tag, typ = int(lab) % self.num_tag, int(lab) // self.num_tag
            if in_chunk and self._is_end(ptag, ptype, tag, typ):
                segs.append((start, i - 1, ptype))
                in_chunk = False
            if self._is_begin(ptag, ptype, tag, typ):
                start, in_chunk = i, True
        if in_chunk:
            segs.append((start, len(labels) - 1, typ))
        return segs

    def _eval_seq(self, out, lab):
        o, l = self._segments(out), self._segments(lab)
        correct = set(o) & set(l)
        self.n_correct += sum(1 for s in correct if s[2] not in self.excluded)
        self.n_output += sum(1 for s in o if s[2] not in self.excluded)
        self.n_label += sum(1 for s in l if s[2] not in self.excluded)

    def add_batch(self, outs, feed):
        pred = self._get(outs, feed, "input")
        label = self._get(outs, feed, "label")
        p = np.asarray(pred.ids if pred.ids is not None else pred.value)
        p = p.reshape(p.shape[0], -1)
        l = np.asarray(label.ids).reshape(p.shape[0], -1)
        lens = np.asarray(label.seq_lens)
        for b in range(p.shape[0]):
            n = int(lens[b])
            self._eval_seq(p[b, :n], l[b, :n])

    def result(self):
        prec = self.n_correct / max(self.n_output, 1)
        rec = self.n_correct / max(self.n_label, 1)
        f1 = 2 * prec * rec / max(prec + rec, 1e-12)
        return {"precision": prec, "recall": rec, "F1": f1}


def _edit_distance(ref, hyp):
    """Levenshtein with (sub, del, ins) backtrace counts
    (CTCErrorEvaluator.cpp stringAlignment)."""
    n, m = len(ref), len(hyp)
    if n == 0:
        return m, 0, 0, m
    if m == 0:
        return n, 0, n, 0
    ref_a = np.asarray(ref)
    hyp_a = np.asarray(hyp)
    d = np.zeros((n + 1, m + 1), np.int64)
    d[:, 0] = np.arange(n + 1)
    d[0, :] = np.arange(m + 1)
    # vectorized per row; the insertion prefix dependency
    # r[j] = min(best[j], r[j-1]+1) solved with the minimum.accumulate
    # trick on s[j] = r[j] - j
    col = np.arange(1, m + 1)
    for i in range(1, n + 1):
        cost = (hyp_a != ref_a[i - 1]).astype(np.int64)
        best = np.minimum(d[i - 1, :-1] + cost, d[i - 1, 1:] + 1)
        s = np.minimum.accumulate(np.concatenate(([i], best - col)))
        d[i, 1:] = s[1:] + col
        d[i, 0] = i
    subs = dels = ins = 0
    i, j = n, m
    while i and j:
        if d[i, j] == d[i - 1, j - 1] and ref[i - 1] == hyp[j - 1]:
            i, j = i - 1, j - 1
        elif d[i, j] == d[i - 1, j - 1] + 1:
            subs += 1
            i, j = i - 1, j - 1
        elif d[i, j] == d[i - 1, j] + 1:
            dels += 1
            i -= 1
        else:
            ins += 1
            j -= 1
    dels += i
    ins += j
    return int(d[n, m]), subs, dels, ins


@EVALUATORS.register("ctc_edit_distance")
class CTCErrorEvaluator(Evaluator):
    """Sequence edit-distance error for CTC models (CTCErrorEvaluator.cpp):
    per sequence, best-path decode (argmax per frame, collapse — reuses
    ops.ctc.ctc_greedy_decode so train-time and eval-time decode agree),
    then length-normalized Levenshtein vs the label string. conf "blank"
    defaults to 0 like this framework's ctc layer (the reference hardcodes
    blank = C-1; set blank=C-1 in conf for that convention). result: dict
    with avg normalized edit distance plus insertion/deletion/substitution
    rates and whole-seq error rate."""

    def start(self):
        self.total_err = 0.0
        self.ins = self.dels = self.subs = 0.0
        self.seq_err = 0
        self.n_seq = 0

    def add_batch(self, outs, feed):
        from paddle_tpu.ops.ctc import ctc_greedy_decode

        act = self._get(outs, feed, "input")
        label = self._get(outs, feed, "label")
        a = np.asarray(act.value)  # [B,T,C]
        alens = np.asarray(act.seq_lens)
        l = np.asarray(label.ids).reshape(a.shape[0], -1)
        llens = np.asarray(label.seq_lens)
        blank = self.conf.get("blank", 0)
        paths, plens = ctc_greedy_decode(
            jnp.asarray(a), jnp.asarray(alens, jnp.int32), blank=blank
        )
        paths, plens = np.asarray(paths), np.asarray(plens)
        for b in range(a.shape[0]):
            hyp = paths[b, : int(plens[b])].tolist()
            ref = l[b, : int(llens[b])].tolist()
            dist, subs, dels, ins = _edit_distance(ref, hyp)
            mx = max(len(ref), len(hyp), 1)
            self.total_err += dist / mx
            self.subs += subs / mx
            self.dels += dels / mx
            self.ins += ins / mx
            self.seq_err += int(dist != 0)
            self.n_seq += 1

    def result(self):
        n = max(self.n_seq, 1)
        return {
            "edit_distance": self.total_err / n,
            "substitution": self.subs / n,
            "deletion": self.dels / n,
            "insertion": self.ins / n,
            "seq_error": self.seq_err / n,
        }


class _PrinterBase(Evaluator):
    """Printers (Evaluator.cpp:1009-1346) log tensors for debugging; they
    accumulate nothing. Output goes through `emit` (logging by default,
    or a user-supplied `printer` callable / `result_file` in conf)."""

    def start(self):
        self._fh = None

    def emit(self, line: str):
        # stream to the result file (no unbounded in-memory accumulation)
        path = self.conf.get("result_file")
        if path:
            if self._fh is None:
                self._fh = open(path, "a")
            self._fh.write(line + "\n")
        f = self.conf.get("printer")
        if f is not None:
            f(line)
        elif not path:
            import logging

            logging.getLogger("paddle_tpu.eval").info("%s: %s", self.name, line)

    def result(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        return None


@EVALUATORS.register("value_printer")
class ValuePrinter(_PrinterBase):
    def add_batch(self, outs, feed):
        x = self._get(outs, feed, "input")
        v = x.value if x.value is not None else x.ids
        self.emit(np.array2string(np.asarray(v), threshold=64))


@EVALUATORS.register("gradient_printer")
class GradientPrinter(_PrinterBase):
    """The reference prints a layer's output gradient. Gradients here are
    functional (jax.grad over the net) — intermediate output grads are
    recorded into `outs["<name>@GRAD"]` when the trainer is run with
    grad taps; fall back to value stats otherwise."""

    def add_batch(self, outs, feed):
        g = outs.get(self.conf["input"] + "@GRAD")
        if g is not None:
            self.emit(np.array2string(np.asarray(g.value), threshold=64))
        else:
            x = self._get(outs, feed, "input")
            self.emit(
                "[no grad tap] value mean=%.6g std=%.6g"
                % (np.mean(x.value), np.std(np.asarray(x.value)))
            )


@EVALUATORS.register("max_id_printer")
class MaxIdPrinter(_PrinterBase):
    def add_batch(self, outs, feed):
        x = self._get(outs, feed, "input")
        self.emit(str(np.argmax(np.asarray(x.value), axis=-1).tolist()))


@EVALUATORS.register("max_frame_printer")
class MaxFramePrinter(_PrinterBase):
    """Prints, per sequence, the frame with the max value."""

    def add_batch(self, outs, feed):
        x = self._get(outs, feed, "input")
        v = np.asarray(x.value)
        m = np.asarray(x.mask())
        score = (v.max(axis=-1) * m) + (m - 1) * 1e30
        self.emit(str(np.argmax(score, axis=-1).tolist()))


@EVALUATORS.register("seq_text_printer")
class SequenceTextPrinter(_PrinterBase):
    """Prints id sequences as text (Evaluator.cpp:1181). conf: input,
    optional dict_file (one token per line) mapping ids to words."""

    def start(self):
        super().start()
        self.vocab = None
        df = self.conf.get("dict_file")
        if df:
            with open(df) as fh:
                self.vocab = [ln.rstrip("\n") for ln in fh]

    def add_batch(self, outs, feed):
        x = self._get(outs, feed, "input")
        ids = np.asarray(x.ids if x.ids is not None else x.value)
        ids = ids.reshape(ids.shape[0], -1)
        lens = (
            np.asarray(x.seq_lens)
            if x.seq_lens is not None
            else np.full(ids.shape[0], ids.shape[1])
        )
        for b in range(ids.shape[0]):
            seq = ids[b, : int(lens[b])].tolist()
            if self.vocab:
                self.emit(" ".join(self.vocab[i] for i in seq))
            else:
                self.emit(" ".join(str(i) for i in seq))


@EVALUATORS.register("classification_error_printer")
class ClassificationErrorPrinter(_PrinterBase):
    def add_batch(self, outs, feed):
        pred = self._get(outs, feed, "input")
        label = self._get(outs, feed, "label")
        p, l, w = self._masked_pairs(pred, label)
        err = ((np.argmax(p, axis=-1) != l) & (w > 0)).astype(np.int64)
        self.emit(str(err.tolist()))


def create_evaluator(conf: dict) -> Evaluator:
    return EVALUATORS.get(conf["type"])(conf)


@EVALUATORS.register("detection_map")
class DetectionMAPEvaluator(Evaluator):
    """Mean average precision for SSD detection
    (gserver/evaluators/DetectionMAPEvaluator.cpp).

    conf: input = detection_output layer name (rows [label, score, box4]
    per image, score==0 padding), label = gt boxes Arg name ([B,G,4] with
    seq_lens), label_ids = gt label Arg name ([B,G] ids); optional
    overlap_threshold (0.5), ap_type "11point"|"integral",
    background_id (0). Accumulates per-class (score, tp) pairs and
    per-class gt counts on host; result() sweeps each class's detections
    by descending score, greedy-matching each to an unused gt with
    IoU > threshold (true positive) else false positive.
    """

    def start(self):
        from collections import defaultdict

        self.dets = defaultdict(list)  # cls -> [(score, tp)]
        self.n_gt = defaultdict(int)  # cls -> count

    @staticmethod
    def _iou(box, boxes):
        x1 = np.maximum(box[0], boxes[:, 0])
        y1 = np.maximum(box[1], boxes[:, 1])
        x2 = np.minimum(box[2], boxes[:, 2])
        y2 = np.minimum(box[3], boxes[:, 3])
        inter = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
        a = (box[2] - box[0]) * (box[3] - box[1])
        b = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
        return inter / np.maximum(a + b - inter, 1e-10)

    def add_batch(self, outs, feed):
        det = self._get(outs, feed, "input")
        gt_box = self._get(outs, feed, "label")
        gt_label = self._get(outs, feed, "label_ids")
        thr = self.conf.get("overlap_threshold", 0.5)
        d = np.asarray(det.value)
        d = d.reshape(d.shape[0], -1, 6)
        boxes = np.asarray(gt_box.value)
        labels = np.asarray(gt_label.ids)
        lens = np.asarray(gt_box.seq_lens)
        for b in range(d.shape[0]):
            g_box = boxes[b, : lens[b]]
            g_lab = labels[b, : lens[b]]
            for c in np.unique(g_lab):
                self.n_gt[int(c)] += int((g_lab == c).sum())
            rows = d[b]
            rows = rows[rows[:, 1] > 0]
            used = np.zeros(len(g_box), bool)
            for cls, score, *box in rows[np.argsort(-rows[:, 1])]:
                # match to the overall best-overlap gt of this class; a
                # duplicate detection of an already-claimed gt is a FALSE
                # positive (DetectionMAPEvaluator.cpp), not re-matched
                cand = np.where(g_lab == int(cls))[0]
                tp = 0
                if len(cand):
                    ious = self._iou(np.asarray(box), g_box[cand])
                    j = int(np.argmax(ious))
                    if ious[j] > thr and not used[cand[j]]:
                        used[cand[j]] = True
                        tp = 1
                self.dets[int(cls)].append((float(score), tp))

    def result(self):
        ap_type = self.conf.get("ap_type", "11point")
        aps = []
        for c, n in self.n_gt.items():
            if n == 0:
                continue
            pairs = sorted(self.dets.get(c, []), reverse=True)
            tp = np.cumsum([t for _, t in pairs]) if pairs else np.array([])
            if len(tp) == 0:
                aps.append(0.0)
                continue
            fp = np.arange(1, len(tp) + 1) - tp
            rec = tp / n
            prec = tp / np.maximum(tp + fp, 1e-10)
            if ap_type == "11point":
                ap = float(
                    np.mean(
                        [
                            prec[rec >= t].max() if (rec >= t).any() else 0.0
                            for t in np.linspace(0, 1, 11)
                        ]
                    )
                )
            else:  # integral
                ap = float(
                    np.sum(
                        (rec - np.concatenate(([0.0], rec[:-1]))) * prec
                    )
                )
            aps.append(ap)
        return float(np.mean(aps)) if aps else 0.0
