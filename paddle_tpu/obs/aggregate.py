"""Fleet-wide snapshot aggregation + SLO burn-rate monitoring
(ISSUE 17).

Everything per-process observability built so far — registry,
metricz, tracez, flight recorder — answers "how is THIS process
doing". This module is the fleet half: given the registry snapshots
of N replicas (scraped over the serving `metricz` frame), it produces
ONE fleet view, and given the router's stream of per-request
decisions it answers "is the fleet burning its SLO error budget, and
which replica is doing the burning".

Merging rules (`merge_snapshots`):

- counters: summed across replicas (they are monotonic totals);
- gauges: NOT summed — a queue-depth averaged across replicas is a
  lie — each series is kept, relabeled with `replica=<name>`;
- histograms: merged bucket-wise. The per-series le-bucket counts the
  registry snapshot carries (obs/metrics.py) are added slot by slot,
  so fleet p50/p99 (`quantile`) are computed from the MERGED
  distribution; exact count/sum/min/max merge exactly. Mismatched
  bucket boundaries across replicas are a schema conflict and raise
  `SnapshotMergeError`, as does a series name that is (say) a counter
  on one replica and a gauge on another.

`snapshot_delta` / `counter_rates` turn two consecutive merged
scrapes into the between-scrape view (counter deltas with restart
handling, histogram bucket deltas), and `FleetAggregator` keeps the
bounded scrape history an incident bundle stitches in.

`BurnRateMonitor` is the alerting half: multi-window burn-rate
alerting over the router's per-request decisions. An SLO with target
availability A has error budget (1 - A); the burn rate of a window is
(window error fraction) / (1 - A). An alert fires only when BOTH a
short window and its long companion burn faster than the pair's
threshold — the short window gives fast detection, the long window
refuses to page on a blip that already ended (see DESIGN.md). The
same two-window rule gates admitted-p99-over-SLO alerting.

No jax imports anywhere (linted by `check_bench_record.py obs`, and
this module is on the REQUIRED_OBS_MODULES list): fleet aggregation
runs in routers, CLIs and CI boxes with no device runtime.
"""

from __future__ import annotations

import collections
import math
import time
from typing import Optional

from paddle_tpu.analysis.lock_order import named_lock

# the cross-process incident bundle schema (written by
# serving/fleet.py's FleetMonitor, rendered by tools/fleet_view.py,
# linted by tools/check_bench_record.py bundle)
INCIDENT_SCHEMA = "paddle-tpu-fleet-incident/v1"


class SnapshotMergeError(ValueError):
    """Replica snapshots disagree on a series' schema: same name,
    different metric kind or different histogram bucket boundaries.
    Merging would silently produce garbage, so it refuses instead."""


def _split_series(series: str):
    """'name{a=b,c=d}' -> ('name', (('a','b'), ('c','d')))."""
    if series.endswith("}") and "{" in series:
        fam, _, rest = series.partition("{")
        pairs = tuple(
            tuple(p.split("=", 1))
            for p in rest[:-1].split(",") if p
        )
        return fam, pairs
    return series, ()


def _with_label(series: str, key: str, value: str) -> str:
    fam, pairs = _split_series(series)
    pairs = tuple(sorted(pairs + ((key, str(value)),)))
    return fam + "{" + ",".join(f"{k}={v}" for k, v in pairs) + "}"


_KINDS = ("counters", "gauges", "histograms")


def merge_snapshots(snaps: dict) -> dict:
    """Merge `{replica_name: registry_snapshot}` into one fleet view.

    Returns `{"replicas": [...], "counters": {...}, "gauges": {...},
    "histograms": {...}}`. A replica with an empty (or missing-kind)
    snapshot contributes nothing and is legal — a freshly restarted
    process has recorded nothing yet."""
    # kind-conflict scan first: the same series name appearing under
    # two different kinds anywhere in the fleet poisons the merge
    kind_of: dict = {}
    for rep in sorted(snaps):
        snap = snaps[rep]
        if snap is None:
            continue
        if not isinstance(snap, dict):
            raise SnapshotMergeError(
                f"replica {rep!r}: snapshot is {type(snap).__name__}, "
                f"not a dict"
            )
        for kind in _KINDS:
            for name in (snap.get(kind) or {}):
                prev = kind_of.setdefault(name, (kind, rep))
                if prev[0] != kind:
                    raise SnapshotMergeError(
                        f"series {name!r} is a {prev[0][:-1]} on "
                        f"{prev[1]!r} but a {kind[:-1]} on {rep!r}"
                    )
    out = {"replicas": sorted(snaps), "counters": {}, "gauges": {},
           "histograms": {}}
    for rep in sorted(snaps):
        snap = snaps[rep] or {}
        for name, v in (snap.get("counters") or {}).items():
            out["counters"][name] = (
                out["counters"].get(name, 0.0) + float(v)
            )
        for name, v in (snap.get("gauges") or {}).items():
            out["gauges"][_with_label(name, "replica", rep)] = v
        for name, h in (snap.get("histograms") or {}).items():
            _merge_hist(out["histograms"], name, h, rep)
    return out


def _merge_hist(dst: dict, name: str, h: dict, rep: str) -> None:
    bounds = h.get("bounds")
    buckets = h.get("buckets")
    count = int(h.get("count", 0) or 0)
    hsum = float(h.get("sum", 0.0) or 0.0)
    hmin = h.get("min")
    hmax = h.get("max")
    cur = dst.get(name)
    if cur is None:
        dst[name] = {
            "count": count,
            "sum": hsum,
            "min": hmin,
            "max": hmax,
            "avg": hsum / count if count else 0.0,
            "bounds": list(bounds) if bounds is not None else None,
            "buckets": list(buckets) if buckets is not None else None,
        }
        return
    if bounds is not None and cur["bounds"] is not None \
            and list(bounds) != list(cur["bounds"]):
        raise SnapshotMergeError(
            f"histogram {name!r}: replica {rep!r} uses bucket "
            f"boundaries {list(bounds)[:4]}..., the fleet view was "
            f"built on {list(cur['bounds'])[:4]}... — mismatched "
            f"boundaries cannot merge bucket-wise"
        )
    cur["count"] += count
    cur["sum"] += hsum
    if hmin is not None:
        cur["min"] = hmin if cur["min"] is None else min(cur["min"],
                                                         hmin)
    if hmax is not None:
        cur["max"] = hmax if cur["max"] is None else max(cur["max"],
                                                         hmax)
    cur["avg"] = cur["sum"] / cur["count"] if cur["count"] else 0.0
    if buckets is not None and cur["buckets"] is not None \
            and len(buckets) == len(cur["buckets"]):
        cur["buckets"] = [a + b for a, b in zip(cur["buckets"],
                                                buckets)]
    elif buckets is not None and cur["buckets"] is None:
        cur["buckets"] = list(buckets)
        cur["bounds"] = list(bounds) if bounds is not None else None


def family_histogram(histograms: dict, family: str) -> Optional[dict]:
    """Fold every series of one histogram family (all label
    combinations — e.g. the per-model `serving.admitted_latency_s`
    series) into a single merged entry, so a fleet-wide quantile is
    quoted over ONE distribution. None when the family is absent."""
    out: dict = {}
    for name, h in (histograms or {}).items():
        if name.split("{", 1)[0] == family:
            _merge_hist(out, family, h, "<fold>")
    return out.get(family)


def family_total(counters: dict, family: str) -> float:
    """Sum of a counter family across all its label series."""
    return sum(
        float(v) for k, v in (counters or {}).items()
        if k == family or k.startswith(family + "{")
    )


def quantile(hist_entry: Optional[dict], q: float) -> Optional[float]:
    """Upper-bound estimate of the q-quantile from a (merged)
    histogram entry's le-buckets: the boundary of the bucket the
    target rank lands in. Observations in the +inf overflow bucket
    resolve to the tracked exact max. Returns None when the entry has
    no buckets or no observations."""
    if not hist_entry:
        return None
    buckets = hist_entry.get("buckets")
    bounds = hist_entry.get("bounds")
    if not buckets or bounds is None:
        return None
    total = sum(buckets)
    if total <= 0:
        return None
    rank = max(int(math.ceil(q * total)), 1)
    cum = 0
    for i, n in enumerate(buckets):
        cum += n
        if cum >= rank:
            if i < len(bounds):
                return float(bounds[i])
            break
    mx = hist_entry.get("max")
    return float(mx) if mx is not None else float(bounds[-1])


def snapshot_delta(prev: Optional[dict], cur: dict) -> dict:
    """The between-scrape view: counter and histogram deltas from the
    previous merged snapshot to the current one; gauges pass through
    as their current values (a gauge has no meaningful delta). A
    counter or histogram count that DECREASED means a replica
    restarted (its registry reset): the current value is taken as the
    whole delta rather than clamping the progress to zero."""
    prev = prev or {}
    out = {"replicas": list(cur.get("replicas") or []),
           "counters": {}, "gauges": dict(cur.get("gauges") or {}),
           "histograms": {}}
    pc = prev.get("counters") or {}
    for name, v in (cur.get("counters") or {}).items():
        p = float(pc.get(name, 0.0))
        v = float(v)
        out["counters"][name] = v - p if v >= p else v
    ph = prev.get("histograms") or {}
    for name, h in (cur.get("histograms") or {}).items():
        p = ph.get(name)
        if p is None or int(p.get("count", 0) or 0) > \
                int(h.get("count", 0) or 0):
            p = {}
        count = int(h.get("count", 0) or 0) - int(p.get("count", 0)
                                                  or 0)
        hsum = float(h.get("sum", 0.0) or 0.0) - float(
            p.get("sum", 0.0) or 0.0)
        buckets = h.get("buckets")
        pbuckets = p.get("buckets")
        if buckets is not None and pbuckets is not None \
                and len(buckets) == len(pbuckets):
            dbuckets = [max(a - b, 0)
                        for a, b in zip(buckets, pbuckets)]
        else:
            dbuckets = list(buckets) if buckets is not None else None
        out["histograms"][name] = {
            "count": count,
            "sum": max(hsum, 0.0),
            "min": h.get("min"),
            "max": h.get("max"),
            "bounds": h.get("bounds"),
            "buckets": dbuckets,
        }
    return out


def counter_rates(delta: dict, dt_s: float) -> dict:
    """Per-second rates from a `snapshot_delta` counters dict."""
    if dt_s <= 0:
        return {}
    return {name: v / dt_s
            for name, v in (delta.get("counters") or {}).items()}


class FleetAggregator:
    """Scrape-history keeper: feed each round of per-replica registry
    snapshots through `observe()`; it maintains the current merged
    view, the delta and per-second rates against the previous scrape,
    and a bounded history the incident bundle stitches in."""

    def __init__(self, history: int = 16):
        # a known lock (ISSUE 13): instrumented under the faults
        # shard's lock-order checker (analysis/lock_order.py)
        self._lock = named_lock("obs.fleet_agg")
        self._history: collections.deque = collections.deque(
            maxlen=history)
        self.merged: Optional[dict] = None
        self.delta: Optional[dict] = None
        self.rates: Optional[dict] = None
        self._last_ts: Optional[float] = None

    def observe(self, snaps: dict, ts: float = None) -> dict:
        merged = merge_snapshots(snaps)
        now = time.time() if ts is None else ts
        with self._lock:
            prev, prev_ts = self.merged, self._last_ts
            self.merged, self._last_ts = merged, now
            self.delta = (snapshot_delta(prev, merged)
                          if prev is not None else None)
            dt = (now - prev_ts) if prev_ts is not None else 0.0
            self.rates = (counter_rates(self.delta, dt)
                          if self.delta is not None else None)
            self._history.append(
                {"ts": round(now, 6), "merged": merged,
                 "delta": self.delta}
            )
        return merged

    def history(self) -> list:
        with self._lock:
            return list(self._history)


class BurnRateMonitor:
    """Multi-window SLO burn-rate alerting over per-request decisions.

    `record(ok, latency_s=, replica=)` logs one routing decision
    (admitted success vs shed/failure); `evaluate()` returns the
    currently-active alerts and, on each activation edge, bumps the
    `fleet.alerts` counter and emits a `kind="alert"` event — so an
    alert that stays active across 100 poll rounds is counted ONCE.

    `windows` is a tuple of `(short_s, long_s, burn_threshold)`
    pairs. For each pair, an availability alert requires the burn
    rate (error fraction / error budget) to exceed the threshold in
    BOTH windows; with `p99_slo_ms > 0`, a latency alert requires the
    admitted p99 to exceed the SLO in both windows. Each alert names
    the replica contributing the most errors (availability) or the
    most over-SLO requests (latency) in the short window — the
    "which replica and why" half of the fleet question."""

    def __init__(self, availability_target: float = 0.999,
                 p99_slo_ms: float = 0.0,
                 windows=((60.0, 300.0, 14.4), (300.0, 1800.0, 6.0)),
                 min_decisions: int = 20, max_events: int = 65536,
                 registry=None):
        from paddle_tpu.obs import metrics as _metrics

        self.error_budget = max(1.0 - float(availability_target),
                                1e-9)
        self.availability_target = float(availability_target)
        self.p99_slo_ms = float(p99_slo_ms or 0.0)
        self.windows = tuple(tuple(w) for w in windows)
        self.min_decisions = int(min_decisions)
        self._reg = registry or _metrics.get_registry()
        # (ts_mono, ok, latency_s or None, replica or None)
        self._events: collections.deque = collections.deque(
            maxlen=max_events)
        # a known lock (ISSUE 13)
        self._lock = named_lock("obs.burn_monitor")
        self._active: set = set()
        self.alerts_total = 0

    def record(self, ok: bool, latency_s: float = None,
               replica: str = None, now: float = None) -> None:
        t = time.monotonic() if now is None else now
        with self._lock:
            self._events.append((t, bool(ok), latency_s, replica))

    def _window(self, now: float, span_s: float) -> list:
        lo = now - span_s
        return [e for e in self._events if e[0] >= lo]

    @staticmethod
    def _p99_ms(events: list) -> Optional[float]:
        lats = sorted(e[2] for e in events
                      if e[1] and e[2] is not None)
        if not lats:
            return None
        return lats[int(0.99 * (len(lats) - 1))] * 1e3

    def evaluate(self, now: float = None) -> list:
        t = time.monotonic() if now is None else now
        with self._lock:
            events = list(self._events)
        alerts = []
        fired = set()
        for short_s, long_s, threshold in self.windows:
            short = [e for e in events if e[0] >= t - short_s]
            long_ = [e for e in events if e[0] >= t - long_s]
            if len(short) < self.min_decisions \
                    or len(long_) < self.min_decisions:
                continue
            burns = []
            for win in (short, long_):
                err = sum(1 for e in win if not e[1])
                burns.append((err / len(win)) / self.error_budget)
            if all(b > threshold for b in burns):
                key = ("availability_burn", short_s, long_s)
                fired.add(key)
                errs = collections.Counter(
                    e[3] for e in short if not e[1] and e[3]
                )
                alerts.append({
                    "alert": "availability_burn",
                    "short_window_s": short_s,
                    "long_window_s": long_s,
                    "burn_threshold": threshold,
                    "burn_short": round(burns[0], 3),
                    "burn_long": round(burns[1], 3),
                    "availability_target": self.availability_target,
                    "replica": (errs.most_common(1)[0][0]
                                if errs else None),
                })
            if self.p99_slo_ms > 0:
                p99s = [self._p99_ms(short), self._p99_ms(long_)]
                if all(p is not None and p > self.p99_slo_ms
                       for p in p99s):
                    key = ("p99_slo", short_s, long_s)
                    fired.add(key)
                    slo_s = self.p99_slo_ms / 1e3
                    over = collections.Counter(
                        e[3] for e in short
                        if e[1] and e[2] is not None
                        and e[2] > slo_s and e[3]
                    )
                    alerts.append({
                        "alert": "p99_slo",
                        "short_window_s": short_s,
                        "long_window_s": long_s,
                        "p99_slo_ms": self.p99_slo_ms,
                        "p99_short_ms": round(p99s[0], 3),
                        "p99_long_ms": round(p99s[1], 3),
                        "replica": (over.most_common(1)[0][0]
                                    if over else None),
                    })
        with self._lock:
            new = fired - self._active
            self._active = fired
        for key in sorted(new, key=str):
            # rising edge only: a sustained alert is one activation,
            # not one count per poll round
            self.alerts_total += 1
            self._reg.counter("fleet.alerts").inc(alert=key[0])
            a = next(x for x in alerts
                     if (x["alert"], x["short_window_s"],
                         x["long_window_s"]) == key)
            self._reg.event("alert", **a)
        return alerts

    def state(self, now: float = None) -> dict:
        """Point-in-time monitor view for `states()`/fleetz: per
        window pair, decision count, availability and admitted p99."""
        t = time.monotonic() if now is None else now
        with self._lock:
            events = list(self._events)
        out = []
        for short_s, long_s, threshold in self.windows:
            win = [e for e in events if e[0] >= t - short_s]
            n = len(win)
            err = sum(1 for e in win if not e[1])
            p99 = self._p99_ms(win)
            out.append({
                "window_s": short_s,
                "decisions": n,
                "availability": round(1.0 - err / n, 6) if n else None,
                "p99_ms": round(p99, 3) if p99 is not None else None,
            })
        return {"windows": out, "alerts_total": self.alerts_total,
                "active": sorted(k[0] for k in self._active)}


def offending_replica(alerts: list) -> Optional[str]:
    """The replica the active alerts most implicate (majority vote
    over each alert's own attribution)."""
    votes = collections.Counter(
        a.get("replica") for a in alerts if a.get("replica")
    )
    return votes.most_common(1)[0][0] if votes else None
