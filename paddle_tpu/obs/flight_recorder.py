"""Anomaly-triggered flight recorder: a bounded in-memory ring of
recent observability events, dumped as a self-contained JSON bundle
the moment something goes wrong.

The failure mode this closes: a watchdog rung fires, a circuit
breaker opens, the admitted-p99 SLO breaks — and by the time anyone
looks, the evidence (the spans of the slow requests, the step
timeline around the bad batch, the ladder events leading up to the
abort) has scrolled out of the process or died with it. The recorder
taps the SAME `registry.event()` pipe the EventStream reads (spans,
`timeline` samples, `watchdog` rungs, `serving` anomalies,
`preempt_flush`), keeps the last `capacity` of them in a ring, and on
`maybe_dump(reason)` writes everything — ring + registry snapshot +
trigger context — as one bundle file `tools/trace_view.py` and the
`check_bench_record.py bundle` lint understand.

Dump discipline (the "no dump storm" contract, pinned by test):

- rate-limited: at most one bundle per `min_interval_s` — a breaker
  flapping 100 times produces ONE bundle, with the other 99 triggers
  counted on `flight.dumps_suppressed`;
- bounded dir: at most `max_bundles` bundle files are kept; the
  oldest is deleted when a new one lands.

Optional guarded profiler hook (`flight_profiler_capture` flag): a
dump also runs a short jax-profiler capture and feeds the resulting
Chrome trace through `tools/trace_attribution.py`, committing the
`*.attrib.json` next to the bundle. Every step is best-effort and
exception-guarded: on a CPU CI runner without a usable profiler the
bundle path still runs end-to-end and the bundle records
`profile: {"captured": false}`.

No jax at module scope (linted): the profiler import lives inside the
capture function.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Optional

from paddle_tpu.analysis.lock_order import named_lock
from paddle_tpu.core import flags as _flags
from paddle_tpu.obs import metrics as _metrics

BUNDLE_SCHEMA = "paddle-tpu-flight-bundle/v1"


class BoundedBundleDir:
    """The shared dump discipline for bundle writers (flight bundles
    here, fleet incident bundles in serving/fleet.py): rate limiting,
    sequence numbering, atomic writes, and bounded-dir rotation are
    ONE implementation, not a copy per bundle kind.

    Contract (pinned by test):

    - `try_begin()` hands out a sequence number at most once per
      `min_interval_s`; a suppressed trigger returns None (the caller
      counts the suppression on its own counter, so flight and
      incident suppressions stay separately attributable);
    - `write(seq, reason, doc)` lands `{prefix}{seq:05d}-{reason}.json`
      via tmp + `os.replace` (a bundle is complete or absent), then
      prunes the dir down to `max_bundles` files with that prefix —
      oldest first. With no `dump_dir` it returns None (ring-only /
      in-memory mode: the caller keeps the doc itself)."""

    def __init__(self, dump_dir: Optional[str],
                 prefix: str = "flight-",
                 max_bundles: int = 8,
                 min_interval_s: float = 60.0,
                 lock_name: str = "obs.bundle_dir"):
        self.dump_dir = dump_dir
        self.prefix = prefix
        self.max_bundles = int(max_bundles)
        self.min_interval_s = float(min_interval_s)
        # a known lock (ISSUE 13): instrumented under the faults
        # shard's lock-order checker (analysis/lock_order.py)
        self._lock = named_lock(lock_name)
        self._last_mono: Optional[float] = None
        self._seq = 0
        if dump_dir:
            os.makedirs(dump_dir, exist_ok=True)

    def try_begin(self) -> Optional[int]:
        now = time.monotonic()
        with self._lock:
            if (self._last_mono is not None
                    and now - self._last_mono < self.min_interval_s):
                return None
            self._last_mono = now
            self._seq += 1
            return self._seq

    def path_for(self, seq: int, reason: str) -> Optional[str]:
        if not self.dump_dir:
            return None
        return os.path.join(
            self.dump_dir, f"{self.prefix}{seq:05d}-{reason}.json"
        )

    def write(self, seq: int, reason: str, doc: dict) -> Optional[str]:
        path = self.path_for(seq, reason)
        if path is None:
            return None
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, default=str)
        os.replace(tmp, path)  # a bundle is complete or absent
        self.prune()
        return path

    def prune(self) -> None:
        try:
            bundles = sorted(
                f for f in os.listdir(self.dump_dir)
                if f.startswith(self.prefix) and f.endswith(".json")
            )
        except (OSError, TypeError):
            return
        for f in bundles[: max(len(bundles) - self.max_bundles, 0)]:
            try:
                os.remove(os.path.join(self.dump_dir, f))
            except OSError:
                pass


class FlightRecorder:
    """Ring buffer + bundle writer. Attach to a registry with
    `enable_flight_recorder()` (production) or construct privately
    and pass `registry=` (tests)."""

    def __init__(self, dump_dir: Optional[str] = None,
                 capacity: Optional[int] = None,
                 min_interval_s: Optional[float] = None,
                 max_bundles: Optional[int] = None,
                 profiler_capture: Optional[bool] = None,
                 registry=None):
        self.dump_dir = dump_dir
        self.capacity = int(
            capacity if capacity is not None
            else _flags.get_flag("flight_ring_capacity")
        )
        self.profiler_capture = bool(
            profiler_capture if profiler_capture is not None
            else _flags.get_flag("flight_profiler_capture")
        )
        # rate limiting / seq / atomic write / rotation all live in
        # the shared BoundedBundleDir (one dump discipline for flight
        # AND fleet-incident bundles, ISSUE 17 satellite)
        self._dir = BoundedBundleDir(
            dump_dir,
            prefix="flight-",
            max_bundles=int(
                max_bundles if max_bundles is not None
                else _flags.get_flag("flight_max_bundles")
            ),
            min_interval_s=float(
                min_interval_s if min_interval_s is not None
                else _flags.get_flag("flight_min_dump_interval_s")
            ),
        )
        self._reg = registry or _metrics.get_registry()
        self._ring = collections.deque(maxlen=self.capacity)
        # a known lock (ISSUE 13): instrumented under the faults
        # shard's lock-order checker (analysis/lock_order.py)
        self._lock = named_lock("obs.flight_ring")
        self.last_bundle: Optional[dict] = None
        self.last_bundle_path: Optional[str] = None

    @property
    def min_interval_s(self) -> float:
        return self._dir.min_interval_s

    @property
    def max_bundles(self) -> int:
        return self._dir.max_bundles

    # ---- ring (called from registry.event via the recorder tap) ----
    def record(self, obj: dict) -> None:
        with self._lock:
            self._ring.append(obj)

    def snapshot(self) -> list:
        with self._lock:
            return list(self._ring)

    def spans(self) -> list:
        """Just the span events currently in the ring (the bench
        rows' span-split source)."""
        return [e for e in self.snapshot() if e.get("kind") == "span"]

    # ---- dumping ----
    def maybe_dump(self, reason: str, /, **context) -> Optional[str]:
        """Write one bundle for `reason`, unless a bundle was written
        less than `min_interval_s` ago (then: count the suppression,
        return None). Never raises — the recorder must not be able to
        take down the subsystem that tripped it."""
        seq = self._dir.try_begin()
        if seq is None:
            self._reg.counter("flight.dumps_suppressed").inc(
                reason=reason
            )
            return None
        events = self.snapshot()
        try:
            return self._dump(reason, context, events, seq)
        except Exception:
            # an unwritable dump dir / full disk must not cascade
            self._reg.counter("flight.dump_errors").inc()
            return None

    def _dump(self, reason, context, events, seq) -> Optional[str]:
        self._reg.counter("flight.dumps").inc(reason=reason)
        bundle = {
            "schema": BUNDLE_SCHEMA,
            "reason": reason,
            "ts": round(time.time(), 6),
            "pid": os.getpid(),
            "seq": seq,
            "context": context,
            "events": events,
            "metrics": self._reg.snapshot(),
            "profile": {"captured": False},
        }
        path = self._dir.path_for(seq, reason)
        if path is None:
            # ring-only mode (bench rows, tests reading spans()):
            # nothing to write, but the trigger is still counted and
            # the bundle is handed back in-memory via last_bundle
            self.last_bundle = bundle
            return None
        if self.profiler_capture:
            bundle["profile"] = _profiler_capture(path)
        path = self._dir.write(seq, reason, bundle)
        self.last_bundle = bundle
        self.last_bundle_path = path
        return path


def _profiler_capture(bundle_path: str, duration_s: float = 0.5) -> dict:
    """Best-effort jax profiler capture + trace attribution. Returns
    the bundle's `profile` stanza; {"captured": False} on ANY failure
    (no jax, no profiler backend, no trace produced) so the CPU CI
    bundle path never depends on a device runtime."""
    prof_dir = bundle_path + ".profile"
    try:
        import jax

        jax.profiler.start_trace(prof_dir)
        time.sleep(duration_s)
        jax.profiler.stop_trace()
    except Exception:
        return {"captured": False}
    trace = _find_trace(prof_dir)
    out = {"captured": True, "profile_dir": prof_dir,
           "trace": trace, "attrib": None}
    if trace:
        try:
            import subprocess
            import sys

            attrib = bundle_path + ".attrib.json"
            tool = os.path.join(
                os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__)))),
                "tools", "trace_attribution.py",
            )
            r = subprocess.run(
                [sys.executable, tool, trace, "--out", attrib],
                capture_output=True, timeout=120,
            )
            if r.returncode == 0 and os.path.exists(attrib):
                out["attrib"] = attrib
        except Exception:
            pass
    return out


def _find_trace(prof_dir: str) -> Optional[str]:
    newest = None
    for root, _dirs, files in os.walk(prof_dir):
        for f in files:
            if f.endswith(".trace.json.gz") or f == "trace.json.gz":
                p = os.path.join(root, f)
                if newest is None or os.path.getmtime(p) > \
                        os.path.getmtime(newest):
                    newest = p
    return newest


# ---- process-global instance --------------------------------------
_RECORDER: Optional[FlightRecorder] = None
_RECORDER_LOCK = threading.Lock()


def get_flight_recorder() -> Optional[FlightRecorder]:
    return _RECORDER


def enable_flight_recorder(dump_dir: Optional[str] = None,
                           **kw) -> FlightRecorder:
    """Attach a FlightRecorder to the global registry (replacing any
    previous one). `dump_dir=None` runs ring-only (spans are
    collectable, triggers are counted, nothing is written)."""
    global _RECORDER
    with _RECORDER_LOCK:
        rec = FlightRecorder(dump_dir=dump_dir, **kw)
        _RECORDER = rec
        _metrics.get_registry().attach_recorder(rec)
    return rec


def disable_flight_recorder() -> None:
    global _RECORDER
    with _RECORDER_LOCK:
        _RECORDER = None
        _metrics.get_registry().attach_recorder(None)


def enable_from_env() -> Optional[FlightRecorder]:
    """`PADDLE_FLIGHT_DIR=<dir>` turns the recorder on in any
    entrypoint that calls this (serve/train CLIs, the preemptible
    test worker) without new command-line surface."""
    d = os.environ.get("PADDLE_FLIGHT_DIR")
    if not d:
        return None
    return enable_flight_recorder(dump_dir=d)


def maybe_dump(reason: str, /, **context) -> Optional[str]:
    """Module-level convenience: dump on the global recorder if one
    is enabled; silently nothing otherwise (instrumentation call
    sites stay one line)."""
    rec = _RECORDER
    if rec is None:
        return None
    return rec.maybe_dump(reason, **context)
