"""Distributed tracing: spans, context, and cross-process carriers.

PR10's registry answers "how much, in aggregate"; this module answers
"where did THIS request / THIS RPC / THIS bad step spend its time" —
the reference's per-operation timer machinery (utils/Stat.h
REGISTER_TIMER around one operation) generalized to a causally-linked
span tree that survives process boundaries:

- A **span** is one named, timed operation: `trace_id` (shared by the
  whole causal chain), `span_id`, `parent_id`, a wall-clock start
  (`ts`), a duration (`dur_s`), free-form string `labels`, and a
  `status` ("ok" or a failure reason). Finished spans are emitted as
  `kind="span"` events on the registry's JSONL EventStream (and into
  the flight-recorder ring when one is attached) — there is no second
  export pipe to keep alive.

- **Thread-local context** (`span(...)` context manager) nests spans
  automatically within one thread. Code that crosses threads or wants
  to stamp spans post-hoc from timestamps it already measured (the
  serving scheduler, the trainer hot loop) uses the explicit API:
  `new_trace_id()` / `new_span_id()` / `emit_span(...)`.

- The **carrier** is an explicit dict `{"trace_id": ..., "span_id":
  ...}` — small enough to ride any protocol that can carry two
  strings (the serving TCP JSON frame's `trace` field, an env var for
  spawned workers). `inject()` captures the current context into a
  carrier; `attach(carrier)` makes a remote parent the local context
  so this process's spans join the caller's trace.

Sampling is owned by the instrumented subsystems (the trainer samples
on `timeline_sample_period` fence steps; serving traces every
carrier-bearing request plus every `trace_serve_period`-th anonymous
one), not here: emitting a span with no stream and no recorder
attached costs one None check.

No jax imports at module scope (linted by `check_bench_record.py
obs`): tracing must work in the TCP front end, the master client and
data workers without a device runtime.
"""

from __future__ import annotations

import binascii
import os
import threading
import time
from typing import Optional

from paddle_tpu.obs import metrics as _metrics

# env var a parent process sets to make a child's spans join its
# trace (the spawned-worker analogue of the TCP `trace` field)
CARRIER_ENV = "PADDLE_TRACE_CARRIER"


def new_trace_id() -> str:
    """128-bit random hex — collision-safe across processes."""
    return binascii.hexlify(os.urandom(16)).decode()


def new_span_id() -> str:
    """64-bit random hex."""
    return binascii.hexlify(os.urandom(8)).decode()


class _Context(threading.local):
    def __init__(self):
        self.stack = []  # [(trace_id, span_id), ...]


_ctx = _Context()


def current() -> Optional[tuple]:
    """(trace_id, span_id) of the innermost active span/attachment in
    this thread, or None."""
    return _ctx.stack[-1] if _ctx.stack else None


def inject() -> Optional[dict]:
    """Current context as a carrier dict, or None outside any trace."""
    cur = current()
    if cur is None:
        return None
    return {"trace_id": cur[0], "span_id": cur[1]}


def extract(carrier) -> Optional[tuple]:
    """Parse a carrier dict into (trace_id, parent_span_id); None on
    anything malformed — a bad carrier degrades to an untraced
    operation, never an error on the serving path."""
    if not isinstance(carrier, dict):
        return None
    tid, sid = carrier.get("trace_id"), carrier.get("span_id")
    if not isinstance(tid, str) or not tid:
        return None
    if not isinstance(sid, str) or not sid:
        sid = ""
    return tid, sid


class attach:
    """Context manager: make `carrier` the current context WITHOUT
    opening a span — spans created inside become children of the
    remote parent. A None/malformed carrier attaches nothing (the
    body still runs)."""

    def __init__(self, carrier):
        self._parsed = extract(carrier)

    def __enter__(self):
        if self._parsed is not None:
            _ctx.stack.append(self._parsed)
        return self

    def __exit__(self, *exc):
        if self._parsed is not None:
            _ctx.stack.pop()
        return False


def attach_from_env():
    """`attach` using the CARRIER_ENV env var (JSON carrier) — how a
    spawned worker joins the trace of the process that launched it."""
    import json

    raw = os.environ.get(CARRIER_ENV)
    carrier = None
    if raw:
        try:
            carrier = json.loads(raw)
        except ValueError:
            carrier = None
    return attach(carrier)


class Span:
    """One in-flight operation. Created by `span(...)` (context-
    managed, thread-local nesting) or `start_span(...)` (manual;
    caller must call `finish()`). Emission happens at finish()."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "labels",
                 "status", "_t0_mono", "_ts_wall", "_registry",
                 "_finished")

    def __init__(self, name: str, trace_id: str, parent_id: str,
                 labels: Optional[dict] = None, registry=None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = new_span_id()
        self.parent_id = parent_id or ""
        self.labels = dict(labels) if labels else {}
        self.status = "ok"
        self._t0_mono = time.monotonic()
        self._ts_wall = time.time()
        self._registry = registry
        self._finished = False

    def set_label(self, key: str, value) -> None:
        self.labels[str(key)] = value

    def finish(self, status: Optional[str] = None) -> None:
        if self._finished:
            return
        self._finished = True
        if status is not None:
            self.status = status
        emit_span(
            self.name, self.trace_id, self.span_id, self.parent_id,
            dur_s=time.monotonic() - self._t0_mono,
            ts=self._ts_wall, status=self.status, labels=self.labels,
            registry=self._registry,
        )


class span:
    """`with span("master.get_task", op=2) as s:` — child of the
    current thread context (or the root of a brand-new trace), pushed
    while the body runs, emitted on exit; an exception marks status
    "error" and propagates."""

    def __init__(self, name: str, registry=None, **labels):
        self._name = name
        self._labels = labels
        self._registry = registry
        self._span: Optional[Span] = None

    def __enter__(self) -> Span:
        cur = current()
        tid = cur[0] if cur else new_trace_id()
        parent = cur[1] if cur else ""
        self._span = Span(self._name, tid, parent, self._labels,
                          registry=self._registry)
        _ctx.stack.append((tid, self._span.span_id))
        return self._span

    def __exit__(self, exc_type, exc, tb):
        _ctx.stack.pop()
        if exc_type is not None and self._span.status == "ok":
            self._span.status = "error"
        self._span.finish()
        return False


def start_span(name: str, trace_id: Optional[str] = None,
               parent_id: Optional[str] = None, registry=None,
               **labels) -> Span:
    """Manual span: NOT pushed on the thread context (safe to finish
    from another thread). Defaults parent to the current context."""
    if trace_id is None:
        cur = current()
        if cur is not None:
            trace_id, parent_id = cur[0], parent_id or cur[1]
        else:
            trace_id = new_trace_id()
    return Span(name, trace_id, parent_id or "", labels,
                registry=registry)


def emit_span(name: str, trace_id: str, span_id: str, parent_id: str,
              dur_s: float, ts: Optional[float] = None,
              t0_mono: Optional[float] = None, status: str = "ok",
              labels: Optional[dict] = None, registry=None) -> None:
    """Emit one finished span record (post-hoc path: the caller
    already measured the interval). `ts` is the wall-clock START; when
    only a monotonic start `t0_mono` is known, the wall start is
    recovered via the current mono->wall offset (valid within one
    process — exactly where monotonic stamps come from)."""
    if ts is None:
        if t0_mono is not None:
            ts = time.time() - (time.monotonic() - t0_mono)
        else:
            ts = time.time() - dur_s
    reg = registry or _metrics.get_registry()
    reg.event(
        "span",
        name=name,
        trace_id=trace_id,
        span_id=span_id,
        parent_id=parent_id or "",
        ts=round(ts, 6),
        dur_s=round(dur_s, 9),
        status=status,
        labels=labels or {},
    )
