"""Unified telemetry (ISSUE 10).

One process-wide registry of counters / gauges / histograms
(`obs.metrics`) that the trainer hot loop, the watchdog, the serving
stack and the master client all publish into, plus a JSONL event
stream for discrete structured events (watchdog skips/rollbacks,
preemption flushes, per-pass step timelines) and a per-step wall-time
attribution helper (`obs.timeline`).

The reference treated telemetry as a first-class subsystem
(utils/Stat.h StatSet/REGISTER_TIMER feeding the per-pass report,
TrainerInternal.cpp:177); `core/stat.py` is now a view over this
registry, so there is exactly one timer substrate in the process.

HARD CONSTRAINT (linted by `tools/check_bench_record.py obs`): no
module in this package imports `jax` at module top level. The registry
must stay importable in the serving TCP front end, the master client
and data workers without dragging in the device runtime.
"""

from paddle_tpu.obs.metrics import (  # noqa: F401
    MetricsRegistry,
    EventStream,
    enable_event_stream,
    get_registry,
)
from paddle_tpu.obs.timeline import StepTimeline  # noqa: F401
from paddle_tpu.obs import tracing  # noqa: F401
from paddle_tpu.obs.flight_recorder import (  # noqa: F401
    BoundedBundleDir,
    FlightRecorder,
    enable_flight_recorder,
    disable_flight_recorder,
    get_flight_recorder,
)
from paddle_tpu.obs.aggregate import (  # noqa: F401
    BurnRateMonitor,
    FleetAggregator,
    SnapshotMergeError,
    merge_snapshots,
    quantile,
    snapshot_delta,
)
