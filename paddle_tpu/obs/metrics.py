"""Process-wide metrics registry + JSONL event stream.

Three metric kinds, all thread-safe and all supporting labeled series
(a metric is a family; each distinct label set is one series):

- `Counter`   — monotonically increasing float (`inc`).
- `Gauge`     — last-written value (`set`), plus `set_max` for
                high-water marks (serving queue depth).
- `Histogram` — bucketed distribution with exact count/sum/min/max,
                so it doubles as the substrate for `core.stat.StatSet`
                (whose per-pass report needs count/total/avg/max).

Two export paths:

- `MetricsRegistry.snapshot()` / `render_text()` — one-shot dump,
  exposed as `python -m paddle_tpu metrics` and over the serving TCP
  front end as a `{"metricz": true}` request.
- `EventStream` — append-only JSONL of discrete events (watchdog
  rungs, preemption flushes, per-pass timelines), with a periodic
  background flusher, size-based rotation, and an atexit drain so a
  process that exits without closing still leaves a complete stream.
  `enable_event_stream(path)` attaches one to the global registry;
  `registry.event(kind, **fields)` is a no-op until then, so
  instrumented code never pays for an unconfigured stream.

No jax imports anywhere in this module (linted): the registry must be
importable in serving front ends and data workers without pulling in
the device runtime.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Optional

from paddle_tpu.analysis.lock_order import named_lock

# seconds-oriented default buckets: covers a 0.1 ms dispatch floor up
# to a 60 s checkpoint stall
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _series_name(name: str, key: tuple) -> str:
    if not key:
        return name
    return name + "{" + ",".join(f"{k}={v}" for k, v in key) + "}"


class Counter:
    """Monotonic float counter with labeled series."""

    __slots__ = ("name", "_lock", "_series")
    kind = "counter"

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._series: dict = {}

    def inc(self, value: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def get(self, **labels) -> float:
        with self._lock:
            return self._series.get(_label_key(labels), 0.0)

    def reset(self) -> None:
        with self._lock:
            self._series.clear()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                _series_name(self.name, k): v
                for k, v in sorted(self._series.items())
            }


class Gauge:
    """Last-written value with labeled series; `set_max` keeps the
    high-water mark (only writes when the new value is larger)."""

    __slots__ = ("name", "_lock", "_series")
    kind = "gauge"

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._series: dict = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._series[_label_key(labels)] = value

    def set_max(self, value: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            cur = self._series.get(key)
            if cur is None or value > cur:
                self._series[key] = value

    def get(self, default=None, **labels):
        with self._lock:
            return self._series.get(_label_key(labels), default)

    def reset(self) -> None:
        with self._lock:
            self._series.clear()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                _series_name(self.name, k): v
                for k, v in sorted(self._series.items())
            }


class _HistSeries:
    __slots__ = ("count", "sum", "min", "max", "bucket_counts")

    def __init__(self, n_buckets: int):
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = 0.0
        # bucket_counts[i] counts observations v <= bounds[i] (and
        # > bounds[i-1]); the final slot is the +inf overflow
        self.bucket_counts = [0] * (n_buckets + 1)


class Histogram:
    """Bucketed distribution. `bounds` are upper-inclusive ("le")
    boundaries; an observation equal to a boundary lands in that
    boundary's bucket. Also tracks exact count/sum/min/max per series
    so StatSet-style avg/max reports need no bucket approximation."""

    __slots__ = ("name", "bounds", "_lock", "_series")
    kind = "histogram"

    def __init__(self, name: str, buckets=None):
        self.name = name
        self.bounds = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self._lock = threading.Lock()
        self._series: dict = {}

    def _at_locked(self, key: tuple) -> _HistSeries:
        s = self._series.get(key)
        if s is None:
            s = self._series[key] = _HistSeries(len(self.bounds))
        return s

    def observe(self, value: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            s = self._at_locked(key)
            s.count += 1
            s.sum += value
            if value < s.min:
                s.min = value
            if value > s.max:
                s.max = value
            for i, b in enumerate(self.bounds):
                if value <= b:
                    s.bucket_counts[i] += 1
                    break
            else:
                s.bucket_counts[-1] += 1

    # ---- StatSet-view accessors (default = unlabeled series) ----
    def count(self, **labels) -> int:
        with self._lock:
            s = self._series.get(_label_key(labels))
            return s.count if s else 0

    def sum(self, **labels) -> float:
        with self._lock:
            s = self._series.get(_label_key(labels))
            return s.sum if s else 0.0

    def min(self, **labels) -> float:
        with self._lock:
            s = self._series.get(_label_key(labels))
            return s.min if s else float("inf")

    def max(self, **labels) -> float:
        with self._lock:
            s = self._series.get(_label_key(labels))
            return s.max if s else 0.0

    def avg(self, **labels) -> float:
        with self._lock:
            s = self._series.get(_label_key(labels))
            return s.sum / s.count if s and s.count else 0.0

    def buckets(self, **labels) -> dict:
        """{"<=bound": n, ..., "+inf": n} — non-cumulative counts."""
        with self._lock:
            s = self._series.get(_label_key(labels))
            counts = s.bucket_counts if s else [0] * (len(self.bounds) + 1)
            out = {f"<={b:g}": counts[i] for i, b in enumerate(self.bounds)}
            out["+inf"] = counts[-1]
            return out

    def reset(self) -> None:
        with self._lock:
            self._series.clear()

    def snapshot(self) -> dict:
        # bounds + per-bucket counts ride the snapshot (ISSUE 17): a
        # metricz scrape must carry everything obs/aggregate.py needs
        # to merge N replicas' histograms bucket-wise, so fleet
        # p50/p99 come from merged buckets rather than averaged
        # per-replica quantiles (which are not mergeable)
        with self._lock:
            out = {}
            for k, s in sorted(self._series.items()):
                out[_series_name(self.name, k)] = {
                    "count": s.count,
                    "sum": round(s.sum, 9),
                    "min": s.min if s.count else None,
                    "max": s.max,
                    "avg": s.sum / s.count if s.count else 0.0,
                    "bounds": list(self.bounds),
                    "buckets": list(s.bucket_counts),
                }
            return out


class EventStream:
    """Append-only JSONL event sink with periodic flush + rotation.

    - `emit(obj)` buffers one JSON-serializable dict (a `ts` wall
      timestamp is stamped if absent) — cheap under contention.
    - A daemon flusher writes the buffer every `flush_interval_s`.
    - When the file exceeds `rotate_bytes` it is renamed to
      `<path>.1` (one previous generation kept) and a fresh file
      starts — the stream never grows unbounded.
    - `close()` drains and stops; registered with atexit so a process
      that exits without closing still flushes its tail.
    """

    def __init__(self, path: str, flush_interval_s: float = 1.0,
                 rotate_bytes: int = 64 << 20):
        self.path = path
        self.flush_interval_s = flush_interval_s
        self.rotate_bytes = rotate_bytes
        self._buf: list = []
        # a known lock (ISSUE 13): instrumented under the faults
        # shard's lock-order checker (analysis/lock_order.py)
        self._lock = named_lock("obs.event_stream")
        self._closed = False
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._wake = threading.Event()
        self._thread = threading.Thread(
            target=self._flusher, name="obs-events", daemon=True
        )
        self._thread.start()
        atexit.register(self.close)

    def emit(self, obj: dict) -> None:
        if self._closed:
            return
        if "ts" not in obj:
            obj = {"ts": round(time.time(), 6), **obj}
        with self._lock:
            self._buf.append(obj)

    def flush(self) -> None:
        with self._lock:
            buf, self._buf = self._buf, []
        if not buf:
            return
        lines = "".join(json.dumps(o, default=str) + "\n" for o in buf)
        try:
            if (
                os.path.exists(self.path)
                and os.path.getsize(self.path) + len(lines)
                > self.rotate_bytes
            ):
                os.replace(self.path, self.path + ".1")
            with open(self.path, "a") as f:
                f.write(lines)
        except OSError:
            pass  # an unwritable stream must never take down training

    def _flusher(self):
        while not self._closed:
            self._wake.wait(self.flush_interval_s)
            self._wake.clear()
            self.flush()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._wake.set()
        self._thread.join(timeout=5.0)
        self.flush()


class MetricsRegistry:
    """Get-or-create registry of metric families. One per process
    (`get_registry()`); tests may instantiate private ones."""

    def __init__(self):
        # a known lock (ISSUE 13): instrumented under the faults
        # shard's lock-order checker (analysis/lock_order.py)
        self._lock = named_lock("obs.registry")
        self._metrics: dict = {}
        self._stream: Optional[EventStream] = None
        self._recorder = None  # obs.flight_recorder.FlightRecorder

    def _get(self, cls, name: str, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, **kw)
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get(Counter, name)

    def gauge(self, name: str) -> Gauge:
        return self._get(Gauge, name)

    def histogram(self, name: str, buckets=None) -> Histogram:
        # buckets are fixed at first registration; later callers share
        return self._get(Histogram, name, buckets=buckets)

    # ---- event stream ----
    def attach_stream(self, stream: Optional[EventStream]) -> None:
        old, self._stream = self._stream, stream
        if old is not None and old is not stream:
            old.close()

    @property
    def stream(self) -> Optional[EventStream]:
        return self._stream

    def attach_recorder(self, recorder) -> None:
        """Tap every event() into a flight-recorder ring (see
        obs/flight_recorder.py) alongside — or instead of — the
        stream. None detaches."""
        self._recorder = recorder

    @property
    def recorder(self):
        return self._recorder

    def event(self, kind: str, **fields) -> None:
        """Emit one structured event; no-op until a stream or a
        flight recorder is attached, so hot-loop call sites cost two
        None checks."""
        s = self._stream
        r = self._recorder
        if s is None and r is None:
            return
        obj = {"kind": kind, **fields}
        if s is not None:
            s.emit(obj)
        if r is not None:
            if "ts" not in obj:
                obj = {"ts": round(time.time(), 6), **obj}
            r.record(obj)

    # ---- export ----
    def snapshot(self) -> dict:
        with self._lock:
            metrics = list(self._metrics.values())
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for m in metrics:
            out[m.kind + "s"].update(m.snapshot())
        return out

    def render_text(self) -> str:
        snap = self.snapshot()
        lines = []
        for kind in ("counters", "gauges", "histograms"):
            if not snap[kind]:
                continue
            lines.append(f"=== {kind} ===")
            for name, v in snap[kind].items():
                if isinstance(v, dict):
                    lines.append(
                        f"{name:56s} count={v['count']:8d} "
                        f"sum={v['sum']:12.6f} avg={v['avg']:10.6f} "
                        f"max={v['max']:10.6f}"
                    )
                else:
                    lines.append(f"{name:56s} {v:g}")
        return "\n".join(lines) if lines else "(no metrics recorded)"

    def reset_prefix(self, prefix: str) -> None:
        """Zero every metric whose family name starts with `prefix`,
        IN PLACE (objects survive, so held references keep working —
        the StatSet per-pass reset contract)."""
        with self._lock:
            metrics = [
                m for n, m in self._metrics.items()
                if n.startswith(prefix)
            ]
        for m in metrics:
            m.reset()


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY


def enable_event_stream(path: str, flush_interval_s: float = 1.0,
                        rotate_bytes: int = 64 << 20) -> EventStream:
    """Attach a JSONL event stream at `path` to the global registry
    (replacing and closing any previous one). Returns the stream."""
    s = EventStream(path, flush_interval_s=flush_interval_s,
                    rotate_bytes=rotate_bytes)
    _REGISTRY.attach_stream(s)
    return s
