"""Per-step wall-time attribution for training hot loops.

Splits every trained batch's wall time into the four places it can
go, so an input-pipeline stall is a tracked number like MFU instead
of a vibe:

- **data_wait**        — blocking in the reader/feeder before the
                         step could even be dispatched
- **host_dispatch**    — Python + runtime time to *submit* the jitted
                         step (async dispatch: this returns before
                         the device finishes)
- **device_step**      — time blocked waiting on device results (the
                         loss fetch, plus a full `block_until_ready`
                         fence every `sample_period` steps so the
                         parameter-update tail is measured too while
                         steady-state dispatch stays async)
- **checkpoint_stall** — training-thread stalls inside checkpoint
                         saves / preemption flushes

The timeline is pure bookkeeping (no jax — the *trainer* owns the
fencing; `fence_now()` only answers "is this a sampled step").
Totals are mirrored into the process registry as counters under
`<prefix>.`; `fractions()` yields the `data_wait_frac` /
`host_overhead_frac` / `device_frac` fields the bench drivers attach
to every permanent north-star row, and `emit_pass()` writes one
structured `timeline` event per pass to the JSONL stream.
"""

from __future__ import annotations

from paddle_tpu.obs import metrics as _metrics

PARTS = ("data_wait", "host_dispatch", "device_step", "checkpoint_stall")


class StepTimeline:
    def __init__(self, sample_period: int = 16, prefix: str = "trainer",
                 registry=None):
        """`sample_period`: fence (block_until_ready) every Nth step;
        0 disables fencing (device_step then measures only the result
        fetches the loop makes anyway)."""
        self.sample_period = int(sample_period)
        self.prefix = prefix
        self._reg = registry or _metrics.get_registry()
        self._totals = {p: 0.0 for p in PARTS}
        # most recent per-part duration — the step-span emitter reads
        # the split of THIS step after run_step measured it
        self.last = {p: 0.0 for p in PARTS}
        self._steps = 0
        self._fenced = 0

    # ---- accumulation (trainer-side) ----
    def _add(self, part: str, dt: float) -> None:
        self._totals[part] += dt
        self.last[part] = dt
        self._reg.counter(f"{self.prefix}.{part}_s").inc(dt)

    def add_data_wait(self, dt: float) -> None:
        self._add("data_wait", dt)

    def add_dispatch(self, dt: float) -> None:
        self._add("host_dispatch", dt)

    def add_device(self, dt: float) -> None:
        self._add("device_step", dt)

    def add_checkpoint(self, dt: float) -> None:
        self._add("checkpoint_stall", dt)

    def step_done(self) -> None:
        self._steps += 1
        self._reg.counter(f"{self.prefix}.steps").inc()

    def fence_now(self, step_index: int) -> bool:
        """True on sampled steps — the trainer then blocks until the
        whole step (parameter update included) has landed, so
        device_step covers the tail the loss fetch alone would miss."""
        if self.sample_period <= 0:
            return False
        fence = step_index % self.sample_period == 0
        if fence:
            self._fenced += 1
        return fence

    # ---- export ----
    @property
    def steps(self) -> int:
        return self._steps

    def totals(self) -> dict:
        return dict(self._totals)

    def fractions(self) -> dict:
        """Shares of the MEASURED wall (the four parts' sum — loop
        bookkeeping outside them is not attributed). All zero before
        the first step."""
        wall = sum(self._totals.values())
        if wall <= 0.0:
            return {
                "data_wait_frac": 0.0,
                "host_overhead_frac": 0.0,
                "device_frac": 0.0,
                "checkpoint_stall_frac": 0.0,
            }
        return {
            "data_wait_frac": round(self._totals["data_wait"] / wall, 4),
            "host_overhead_frac": round(
                self._totals["host_dispatch"] / wall, 4
            ),
            "device_frac": round(self._totals["device_step"] / wall, 4),
            "checkpoint_stall_frac": round(
                self._totals["checkpoint_stall"] / wall, 4
            ),
        }

    def emit_pass(self, pass_id: int, global_step: int) -> None:
        """One `timeline` event on the JSONL stream per pass (no-op
        without a stream) — the record `mc_preempt_recovery` and the
        fault tests read back."""
        self._reg.event(
            "timeline",
            pass_id=pass_id,
            global_step=global_step,
            steps=self._steps,
            fenced_steps=self._fenced,
            sample_period=self.sample_period,
            **{f"{p}_s": round(self._totals[p], 6) for p in PARTS},
            **self.fractions(),
        )
