"""Python side of the C inference ABI.

The C library (native/src/capi.cc) embeds CPython — the same trick the
reference uses to run Python config parsing inside the C++ trainer
(utils/PythonUtil.h) — and calls these functions with raw buffer
addresses. All numpy/ctypes marshaling lives here so the C side stays a
thin ABI: create (load merged model), forward (fill caller buffers),
destroy.

Reference surface being reproduced: paddle/capi/gradient_machine.h:36-75
(paddle_gradient_machine_create_for_inference_with_parameters + forward)
with capi/matrix.h-style dense row-major float buffers.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

if os.environ.get("PADDLE_TPU_FORCE_CPU"):
    # serving hosts without an accelerator (and the CI that exercises the
    # C ABI) force the CPU backend before jax initializes
    import jax

    jax.config.update("jax_platforms", "cpu")

import itertools

_HANDLES: dict = {}
_NEXT = itertools.count(1)  # atomic under the GIL


def create(merged_path: str, output_layer: str = "") -> int:
    """Load a merged model file; returns an integer handle."""
    from paddle_tpu.trainer.trainer import Inferencer

    inf = Inferencer.from_merged(
        merged_path, outputs=[output_layer] if output_layer else None
    )
    h = next(_NEXT)
    _HANDLES[h] = inf
    return h


def output_dim(h: int) -> int:
    inf = _HANDLES[h]
    name = inf.output_names[0]
    spec = inf.net.specs[name]
    return int(spec.size)


def forward(
    h: int,
    names: list,
    addrs: list,
    shapes: list,
    is_ids: list,
    out_addr: int,
    out_capacity: int,
) -> list:
    """Run inference. Inputs arrive as (name, buffer address, shape,
    is_ids) quadruples; the first output layer's value is written into
    out_addr (float32, row-major) if it fits. Returns the output shape
    as a list of ints."""
    from paddle_tpu.core.arg import Arg

    inf = _HANDLES[h]
    feed = {}
    for name, addr, shape, ids in zip(names, addrs, shapes, is_ids):
        n = int(np.prod(shape))
        if ids:
            buf = (ctypes.c_int32 * n).from_address(addr)
            arr = np.frombuffer(buf, np.int32).reshape(shape).copy()
            feed[name] = Arg(ids=arr)
        else:
            buf = (ctypes.c_float * n).from_address(addr)
            arr = np.frombuffer(buf, np.float32).reshape(shape).copy()
            feed[name] = Arg(value=arr)
    outs = inf.infer(feed)
    out = np.ascontiguousarray(
        outs[inf.output_names[0]], np.float32
    )
    if out.size > out_capacity:
        raise ValueError(
            f"output needs {out.size} floats, caller buffer has "
            f"{out_capacity}"
        )
    dst = (ctypes.c_float * out.size).from_address(out_addr)
    ctypes.memmove(dst, out.ctypes.data, out.nbytes)
    return list(out.shape)


def destroy(h: int) -> None:
    _HANDLES.pop(h, None)
