"""Python side of the C inference ABI.

The C library (native/src/capi.cc) embeds CPython — the same trick the
reference uses to run Python config parsing inside the C++ trainer
(utils/PythonUtil.h) — and calls these functions with raw buffer
addresses. All numpy/ctypes marshaling lives here so the C side stays a
thin ABI: create (load merged model), forward (fill caller buffers),
destroy.

Reference surface being reproduced: paddle/capi/gradient_machine.h:36-75
(paddle_gradient_machine_create_for_inference_with_parameters + forward)
with capi/matrix.h-style dense row-major float buffers.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

if os.environ.get("PADDLE_TPU_FORCE_CPU"):
    # serving hosts without an accelerator (and the CI that exercises the
    # C ABI) force the CPU backend before jax initializes
    import jax

    jax.config.update("jax_platforms", "cpu")

import itertools

_HANDLES: dict = {}
_NEXT = itertools.count(1)  # atomic under the GIL


def create(merged_path: str, output_layer: str = "") -> int:
    """Load a merged model file; returns an integer handle."""
    from paddle_tpu.trainer.trainer import Inferencer

    inf = Inferencer.from_merged(
        merged_path, outputs=[output_layer] if output_layer else None
    )
    h = next(_NEXT)
    _HANDLES[h] = inf
    return h


def output_dim(h: int) -> int:
    inf = _HANDLES[h]
    name = inf.output_names[0]
    spec = inf.net.specs[name]
    return int(spec.size)


def forward(
    h: int,
    names: list,
    addrs: list,
    shapes: list,
    is_ids: list,
    out_addr: int,
    out_capacity: int,
) -> list:
    """Run inference. Inputs arrive as (name, buffer address, shape,
    is_ids) quadruples; the first output layer's value is written into
    out_addr (float32, row-major) if it fits. Returns the output shape
    as a list of ints."""
    from paddle_tpu.core.arg import Arg

    inf = _HANDLES[h]
    feed = {}
    for name, addr, shape, ids in zip(names, addrs, shapes, is_ids):
        n = int(np.prod(shape))
        if ids:
            feed[name] = Arg(ids=_read_i32(addr, n).reshape(shape))
        else:
            feed[name] = Arg(value=_read_f32(addr, n).reshape(shape))
    return _write_output(inf, feed, out_addr, out_capacity)


def _write_output(inf, feed: dict, out_addr: int,
                  out_capacity: int) -> list:
    """Run inference and copy the first output layer's value into the
    caller's float buffer; returns the output shape. Rank is capped at
    8 — the C side writes at most 8 dims into out_shape, so a larger
    rank must fail loudly rather than return dims the caller can't
    see."""
    outs = inf.infer(feed)
    out = np.ascontiguousarray(outs[inf.output_names[0]], np.float32)
    if out.ndim > 8:
        raise ValueError(f"output rank {out.ndim} exceeds the C ABI's 8")
    if out.size > out_capacity:
        raise ValueError(
            f"output needs {out.size} floats, caller buffer has "
            f"{out_capacity}"
        )
    dst = (ctypes.c_float * out.size).from_address(out_addr)
    ctypes.memmove(dst, out.ctypes.data, out.nbytes)
    return list(out.shape)


def _read_i32(addr: int, n: int) -> np.ndarray:
    buf = (ctypes.c_int32 * n).from_address(addr)
    return np.frombuffer(buf, np.int32).copy()


def _read_f32(addr: int, n: int) -> np.ndarray:
    buf = (ctypes.c_float * n).from_address(addr)
    return np.frombuffer(buf, np.float32).copy()


def _slot_to_arg(s: dict):
    """One pt_capi_slot (dict of addresses/sizes) -> Arg. Kinds mirror
    the reference input surface: dense/id matrices (capi/matrix.h,
    vector.h), sequence start positions incl. one nested level
    (capi/arguments.h:137), sparse CSR (capi/matrix.h:52,102-114)."""
    from paddle_tpu.core.arg import Arg, pad_ragged, sub_seq

    kind = s["kind"]
    shape = [int(d) for d in s["shape"]]
    if kind == 0:  # dense float
        n = int(np.prod(shape)) if shape else 0
        return Arg(value=_read_f32(s["buf"], n).reshape(shape))
    if kind == 1:  # dense ids
        n = int(np.prod(shape)) if shape else 0
        return Arg(ids=_read_i32(s["buf"], n).reshape(shape))
    if kind in (2, 3):  # ragged sequence (ids / dense rows)
        if not s["seq_pos"] or s["n_seq"] < 2:
            raise ValueError("sequence slot needs start positions")
        pos = _read_i32(s["seq_pos"], s["n_seq"])
        total = int(pos[-1])
        if kind == 2:
            flat = _read_i32(s["buf"], total)
        else:
            w = int(s["width"])
            if w <= 0:
                raise ValueError("PT_SLOT_SEQ_DENSE needs width > 0")
            flat = _read_f32(s["buf"], total * w).reshape(total, w)
        if s["subseq_pos"] and s["n_subseq"] >= 2:
            # nested level: subseq_pos refines the same timestep axis,
            # so it must be a superset of seq_pos's boundaries — a
            # malformed refinement would silently mask real timesteps
            sub = _read_i32(s["subseq_pos"], s["n_subseq"])
            if not np.isin(pos, sub).all():
                raise ValueError(
                    "subseq start positions must include every "
                    f"sequence boundary: seq_pos={pos.tolist()}, "
                    f"subseq_pos={sub.tolist()}"
                )
            if not (np.diff(sub) > 0).all():
                raise ValueError(
                    "subseq start positions must be strictly increasing"
                )
            sub_lens = []
            for i in range(len(pos) - 1):
                cuts = sub[(sub >= pos[i]) & (sub <= pos[i + 1])]
                sub_lens.append(np.diff(cuts).astype(np.int32))
            smax = max(len(x) for x in sub_lens)
            padded_sub = np.zeros((len(sub_lens), smax), np.int32)
            for i, x in enumerate(sub_lens):
                padded_sub[i, : len(x)] = x
            # flatten each sequence's timesteps then pad (sub_seq packs
            # [B, T] with per-subsequence lengths)
            padded, _ = pad_ragged(flat, pos)
            return sub_seq(padded, padded_sub, is_ids=(kind == 2))
        padded, lens = pad_ragged(flat, pos)
        if kind == 2:
            return Arg(ids=padded, seq_lens=lens)
        return Arg(value=padded, seq_lens=lens)
    if kind in (4, 5):  # sparse CSR [height, width] -> dense
        h, w, nnz = int(s["height"]), int(s["width"]), int(s["nnz"])
        if w <= 0 or h <= 0:
            raise ValueError("sparse slot needs height/width > 0")
        rows = _read_i32(s["rows"], h + 1)
        cols = _read_i32(s["cols"], nnz)
        # validate like the sequence slots do: a negative column index
        # would wrap via numpy indexing and silently scatter into the
        # wrong feature; malformed row offsets would drop/alias values
        if ((cols < 0) | (cols >= w)).any():
            raise ValueError(
                f"sparse col indices must be in [0, {w}); got "
                f"min={cols.min() if nnz else 0}, "
                f"max={cols.max() if nnz else 0}"
            )
        if (np.diff(rows) < 0).any() or rows[0] != 0 or rows[-1] != nnz:
            raise ValueError(
                "sparse row offsets must be non-decreasing with "
                f"rows[0]=0 and rows[{h}]=nnz={nnz}; got "
                f"rows[0]={int(rows[0])}, rows[-1]={int(rows[-1])}"
            )
        vals = (
            _read_f32(s["vals"], nnz)
            if kind == 5
            else np.ones(nnz, np.float32)
        )
        dense = np.zeros((h, w), np.float32)
        for i in range(h):
            sl = slice(rows[i], rows[i + 1])
            dense[i, cols[sl]] = vals[sl]
        return Arg(value=dense)
    raise ValueError(f"unknown slot kind {kind}")


def forward_slots(h: int, slots: list, out_addr: int,
                  out_capacity: int) -> list:
    """Full-surface forward: dense, ids, ragged-sequence (with optional
    nested level) and sparse CSR input slots. Returns the first output
    layer's shape; the value is written to out_addr (float32)."""
    inf = _HANDLES[h]
    feed = {s["name"]: _slot_to_arg(s) for s in slots}
    return _write_output(inf, feed, out_addr, out_capacity)


def destroy(h: int) -> None:
    _HANDLES.pop(h, None)
