"""Config-building DSL — the user-facing layer functions.

Reference: python/paddle/trainer_config_helpers/layers.py (6212 LoC of
`*_layer` functions emitting LayerConfig protos) and
python/paddle/v2/layer.py. Same programming model: each function appends a
LayerConf to an ambient graph under construction and returns a handle
usable as an input to later calls.

    with model() as m:
        img = data("image", dim=(28, 28, 1))
        lbl = data("label", dim=(1,), is_ids=True)
        h = fc(img, size=128, act="tanh")
        out = fc(h, size=10)
        classification_cost(out, lbl)
    net = Network(m.conf)
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from paddle_tpu.core.config import (
    InputConf,
    LayerConf,
    ModelConf,
    ParameterConf,
    SubModelConf,
)


@dataclass
class GraphBuilder:
    conf: ModelConf = field(default_factory=ModelConf)
    _counts: dict = field(default_factory=dict)
    memories: list = field(default_factory=list)  # recurrent-group steps

    def uniq(self, prefix: str) -> str:
        n = self._counts.get(prefix, 0)
        self._counts[prefix] = n + 1
        return f"__{prefix}_{n}__"

    def add(self, lc: LayerConf) -> "LayerRef":
        self.conf.layers.append(lc)
        return LayerRef(lc.name, self)


@dataclass(frozen=True)
class LayerRef:
    name: str
    builder: GraphBuilder

    @property
    def size(self) -> int:
        """Output width (the reference LayerOutput.size)."""
        return self.builder.conf.layer(self.name).size

    def __add__(self, other: "LayerRef") -> "LayerRef":
        return addto(self, other)


_stack: list = []

# layer types whose output width equals input `idx`'s width — stamped
# onto LayerConf.size at DSL time (see _add)
# layer types whose LayerConf.size is NOT the flat output width at
# DSL time (it holds num_filters; spatial dims resolve at build)
_SIZE_AT_BUILD_ONLY = {
    "exconv", "exconvt", "conv", "cudnn_conv", "conv_operator",
    "pool", "spp", "maxout", "blockexpand", "fused_conv1x1_bn",
    "fused_bottleneck_tail",
}

_SIZE_PRESERVING = {
    "addto": 0,
    "slope_intercept": 0,
    "eltmul": 0,
    "clip": 0,
    "print": 0,
    "interpolation": 1,
    "scaling": 1,
    "power": 1,
}


def current() -> GraphBuilder:
    if not _stack:
        raise RuntimeError("no model() context active")
    return _stack[-1]


def _cost_name() -> str:
    """Default cost-layer name: plain "cost" for the first cost in the
    graph (what configs and evaluators reference), unique thereafter —
    multi-cost models (e.g. the VAE's reconstruct + KL terms) must not
    silently collide."""
    g = current()
    if all(lc.name != "cost" for lc in g.conf.layers):
        return "cost"
    return g.uniq("cost")


@contextlib.contextmanager
def model():
    g = GraphBuilder()
    _stack.append(g)
    try:
        yield g
    finally:
        _stack.pop()


def _in(x) -> InputConf:
    if isinstance(x, InputConf):
        return x
    # anything with a .name is a layer handle (LayerRef or the v1
    # compat mixed-layer builder); bare strings are layer names
    return InputConf(name=getattr(x, "name", x))


def _add(type_, inputs, name=None, size=0, act="", bias=True, param=None,
         bias_param=None, drop_rate=0.0, **attrs):
    g = current()
    name = name or g.uniq(type_)
    ins = []
    for i, x in enumerate(inputs):
        ic = _in(x)
        if param is not None and i == 0 and ic.parameter is None:
            ic.parameter = param
        ins.append(ic)
    if not size and type_ in _SIZE_PRESERVING and ins:
        # stamp the width at DSL time (the reference's LayerOutput.size
        # is always populated; layer arithmetic reads it immediately)
        idx = min(_SIZE_PRESERVING[type_], len(ins) - 1)
        try:
            size = g.conf.layer(ins[idx].name).size
        except KeyError:
            pass  # extra-output refs ('x@state') resolve at build time
    lc = LayerConf(
        name=name, type=type_, size=size, inputs=ins, active_type=act,
        bias=bias, bias_parameter=bias_param, drop_rate=drop_rate, attrs=attrs,
    )
    return g.add(lc)


# ---- inputs ----

def data(name, dim, is_seq=False, is_ids=False, has_subseq=False):
    dim = tuple(dim) if isinstance(dim, (tuple, list)) else (dim,)
    g = current()
    lc = LayerConf(
        name=name, type="data", size=int(np.prod(dim)),
        attrs={"dim": dim, "is_seq": is_seq, "is_ids": is_ids,
               "has_subseq": has_subseq},
    )
    g.conf.input_layer_names.append(name)
    return g.add(lc)


# ---- dense / basic ----

def fc(*inputs, size, name=None, act="", bias=True, param=None,
       bias_param=None, drop_rate=0.0):
    return _add("fc", inputs, name=name, size=size, act=act, bias=bias,
                param=param, bias_param=bias_param, drop_rate=drop_rate)


def embedding(ids, size, vocab_size, name=None, param=None, sharded=False):
    """sharded=True marks the table for row-sharding across the mesh — the
    pserver-sharded large-embedding analogue (SURVEY.md 'MP sparse')."""
    return _add("embedding", [ids], name=name, size=size, bias=False,
                param=param, vocab_size=vocab_size, sharded=sharded)


def addto(*inputs, name=None, act="", bias=False):
    return _add("addto", inputs, name=name, act=act, bias=bias)


def concat(*inputs, name=None, act="", bias=False):
    # bias defaults OFF (reference concat_layer bias_attr=False); the
    # v1 façade enables it for ConcatenateLayer2-style biased concats
    return _add("concat", inputs, name=name, act=act, bias=bias)


def cos_sim(a, b, scale=1.0, size=1, name=None):
    """size=k > 1: b packs k vectors of a's width; output [B, k]
    similarities (layers.py cos_sim size param)."""
    return _add("cos", [a, b], name=name, size=size, scale=scale)


def scaling(weight, x, name=None):
    """Per-row scalar weight times vector x (ScalingLayer)."""
    return _add("scaling", [weight, x], name=name)


def dropout(x, rate, name=None):
    return _add("addto", [x], name=name, bias=False, drop_rate=rate)


def mixed(size, inputs, name=None, act="", bias=True):
    """inputs: list of (layer, proj, extra_attrs) or InputConf. An
    extra-attrs key "param" becomes the edge's ParameterConf (v1
    projections carry param_attr, e.g. dotmul_projection)."""
    ins = []
    for item in inputs:
        if isinstance(item, tuple):
            layer, proj, *rest = item
            attrs = {"proj": proj}
            if rest:
                attrs.update(rest[0])
            param = attrs.pop("param", None)
            ins.append(
                InputConf(name=layer.name, attrs=attrs, parameter=param)
            )
        else:
            ins.append(_in(item))
    if not size:
        # infer at DSL time from size-preserving projections so layer
        # arithmetic right after this call sees the real width
        # (reference layers.py mixed_layer size=None inference);
        # extra-output refs ('x@state') defer to MixedLayer.build
        g = current()
        for ic in ins:
            # an edge may carry its own declared width (a projection's
            # size=, or conv_operator's parse-time output size) — that
            # wins over source-layer inference
            inferred = ic.attrs.get("proj_size")
            if not inferred:
                try:
                    src_lc = g.conf.layer(ic.name)
                except KeyError:
                    continue
                if src_lc.type in _SIZE_AT_BUILD_ONLY:
                    # conv/pool-family LayerConf.size holds
                    # num_filters, not the flat width — only their
                    # build() knows the real size; leave 0 for
                    # MixedLayer.build to resolve
                    continue
                inferred = mixed_proj_size(
                    ic.attrs.get("proj", "full_matrix"), src_lc.size,
                    ic.attrs
                )
            if inferred:
                size = inferred
                break
    # a projection's declared size must agree with the layer width —
    # the reference config parser rejects the mismatch at parse time,
    # and silently coercing would build different dimensions than the
    # config author wrote
    for ic in ins:
        ps = ic.attrs.get("proj_size")
        if ps and size and ps != size:
            raise ValueError(
                f"mixed layer {name or '?'}: projection on "
                f"{ic.name!r} declares size {ps} but the layer is "
                f"{size} wide"
            )
    return _add("mixed", ins, name=name, size=size, act=act, bias=bias)


def mixed_proj_size(proj, in_size, attrs):
    """Output width a size-preserving mixed-layer projection implies,
    or None when the projection doesn't determine it (full_matrix et
    al.). The single source of truth for DSL-time inference above and
    MixedLayer.build."""
    if proj in ("identity", "dotmul"):
        return in_size
    if proj == "slice":
        return sum(e - b for b, e in attrs["slices"])
    if proj == "context":
        return in_size * attrs["context_length"]
    if proj in ("full_matrix", "trans_full_matrix", "table"):
        # a projection may declare its own output width
        # (full_matrix_projection(size=...) / table_projection(size=...)
        # under a sizeless mixed)
        return attrs.get("proj_size") or None
    return None


# ---- image ----

def conv(x, num_filters, filter_size, stride=1, padding=0, groups=1,
         dilation=1, name=None, act="relu", bias=True, param=None,
         num_channels=None):
    kw = {"num_channels": num_channels} if num_channels else {}
    return _add("exconv", [x], name=name, size=num_filters, act=act, bias=bias,
                param=param, num_filters=num_filters, filter_size=filter_size,
                stride=stride, padding=padding, groups=groups,
                dilation=dilation, **kw)


def fused_conv1x1_bn(x, num_filters, act="relu", name=None,
                     use_global_stats=False,
                     moving_average_fraction=0.9, epsilon=1e-5):
    """1x1 conv + batch norm with epilogue stats (layers/fused.py —
    the ResNet bottleneck MFU lever). BN kwargs mirror batch_norm."""
    return _add("fused_conv1x1_bn", [x], name=name, size=num_filters,
                act=act, bias=False, use_global_stats=use_global_stats,
                moving_average_fraction=moving_average_fraction,
                epsilon=epsilon)


def fused_bottleneck_tail(x, num_filters, residual=None, act="relu",
                          name=None, use_global_stats=False,
                          moving_average_fraction=0.9, epsilon=1e-5):
    """BN+ReLU -> 1x1 conv -> BN [+ residual] -> act as one fused layer
    (layers/fused.py). BN kwargs mirror batch_norm."""
    ins = [x] if residual is None else [x, residual]
    return _add("fused_bottleneck_tail", ins, name=name,
                size=num_filters, act=act, bias=False,
                use_global_stats=use_global_stats,
                moving_average_fraction=moving_average_fraction,
                epsilon=epsilon)


def conv_trans(x, num_filters, filter_size, stride=1, padding=0, name=None,
               act="relu", bias=True, param=None, bias_param=None,
               num_channels=None):
    kw = {"num_channels": num_channels} if num_channels else {}
    return _add("exconvt", [x], name=name, size=num_filters, act=act,
                bias=bias, param=param, bias_param=bias_param,
                num_filters=num_filters, filter_size=filter_size,
                stride=stride, padding=padding, **kw)


def pool(x, pool_size, stride=None, padding=0, pool_type="max", name=None):
    return _add("pool", [x], name=name, pool_type=pool_type,
                pool_size=pool_size, stride=stride or pool_size,
                padding=padding)


def batch_norm(x, name=None, act="", use_global_stats=False,
               moving_average_fraction=0.9, epsilon=1e-5):
    return _add("batch_norm", [x], name=name, act=act,
                use_global_stats=use_global_stats,
                moving_average_fraction=moving_average_fraction,
                epsilon=epsilon)


def lrn(x, size=5, scale=1e-4, power=0.75, name=None):
    return _add("norm", [x], name=name, size=size, scale=scale, pow=power)


def maxout(x, groups, name=None):
    return _add("maxout", [x], name=name, groups=groups)


def spp(x, pyramid_height=3, pool_type="max", name=None):
    return _add("spp", [x], name=name, pyramid_height=pyramid_height,
                pool_type=pool_type)


def block_expand(x, block, stride=None, padding=0, name=None):
    return _add("blockexpand", [x], name=name, block=block,
                stride=stride or block, padding=padding)


# ---- recurrence ----

def recurrent(x, size, name=None, act="tanh", reversed=False, bias=True):
    return _add("recurrent", [x], name=name, size=size, act=act,
                bias=bias, reversed=reversed)


def lstmemory(x, size, name=None, act="tanh", gate_act="sigmoid",
              state_act="tanh", reversed=False, bias=True, param=None):
    return _add("lstmemory", [x], name=name, size=size, act=act, bias=bias,
                param=param, active_gate_type=gate_act,
                active_state_type=state_act, reversed=reversed)


def mdlstm(x, size, name=None, act="tanh", gate_act="sigmoid",
           state_act="tanh", directions=(True, True), bias=True,
           param=None):
    """2-D multi-dimensional LSTM over a [H, W, 5*size] grid
    (gserver/layers/MDLstmLayer.cpp)."""
    return _add("mdlstm", [x], name=name, size=size, act=act, bias=bias,
                param=param, active_gate_type=gate_act,
                active_state_type=state_act,
                directions=tuple(directions))


def grumemory(x, size, name=None, act="tanh", gate_act="sigmoid",
              reversed=False, bias=True, param=None):
    return _add("grumemory", [x], name=name, size=size, act=act, bias=bias,
                param=param, active_gate_type=gate_act, reversed=reversed)


def simple_lstm(x, size, name=None, act="tanh", reversed=False):
    """fc(4h) + lstmemory — the networks.py simple_lstm
    (trainer_config_helpers/networks.py:548)."""
    proj = fc(x, size=size * 4, name=(name or "lstm") + "_proj", bias=True)
    return lstmemory(proj, size=size, name=name, act=act, reversed=reversed)


def simple_gru(x, size, name=None, act="tanh", gate_act="sigmoid",
               reversed=False):
    """(networks.py:975 simple_gru)."""
    proj = fc(x, size=size * 3, name=(name or "gru") + "_proj", bias=True)
    return grumemory(proj, size=size, name=name, act=act,
                     gate_act=gate_act, reversed=reversed)


def bidirectional_lstm(x, size, name=None, return_concat=True):
    """(networks.py:1207 bidirectional_lstm)."""
    fwd = simple_lstm(x, size, name=(name or "bilstm") + "_fwd")
    bwd = simple_lstm(x, size, name=(name or "bilstm") + "_bwd", reversed=True)
    return concat(fwd, bwd) if return_concat else (fwd, bwd)


# ---- step-level rnn units/groups (networks.py:633-1122) ----
# The 2017-era building blocks seq2seq configs compose inside
# recurrent_group: one-timestep cells over memory() links, and their
# prebuilt recurrent_group wrappers. Cell math lives in layers/steps.py
# (lstm_step/gru_step); here is only the wiring.

def lstmemory_unit(x, size=None, name=None, out_memory=None, act="tanh",
                   gate_act="sigmoid", state_act="tanh", param=None,
                   bias=True, bias_param=None):
    """One LSTM timestep inside a recurrent_group step
    (networks.py:633 lstmemory_unit). `x` must already carry the
    input-to-hidden projection (width 4*size — the reference's
    convention of hoisting W_x*x out of the unit). Unlike the
    reference, the hidden-to-hidden projection lives INSIDE lstm_step
    (its `w0`, layout-compatible with lstmemory so weights transfer) —
    no `%s_input_recurrent` mixed layer is needed. A `{name}_state`
    layer exposes c_t so the state memory links to it."""
    if size is None:
        assert x.size % 4 == 0, f"lstmemory_unit input {x.size} % 4 != 0"
        size = x.size // 4
    name = name or current().uniq("lstmemory_unit")
    out_mem = out_memory if out_memory is not None else memory(
        name, size=size
    )
    state_mem = memory(f"{name}_state", size=size)
    lstm_out = _add("lstm_step", [x, out_mem, state_mem], name=name,
                    size=size, act=act, bias=bias, param=param,
                    bias_param=bias_param,
                    active_gate_type=gate_act,
                    active_state_type=state_act)
    get_output(lstm_out, "state", name=f"{name}_state")
    return lstm_out


def lstmemory_group(x, size=None, name=None, out_memory=None,
                    reversed=False, act="tanh", gate_act="sigmoid",
                    state_act="tanh", param=None, bias=True,
                    bias_param=None):
    """recurrent_group-built LSTM over a sequence already projected to
    4*size (networks.py:744 lstmemory_group) — same math as lstmemory,
    with every step's hidden/cell state addressable by step-net layer
    name (the attention-model use case)."""
    if size is None:
        assert x.size % 4 == 0, f"lstmemory_group input {x.size} % 4 != 0"
        size = x.size // 4
    name = name or current().uniq("lstm_group")

    def step(ipt):
        return lstmemory_unit(
            ipt, size=size, name=name, out_memory=out_memory, act=act,
            gate_act=gate_act, state_act=state_act, param=param,
            bias=bias, bias_param=bias_param,
        )

    return recurrent_group(step, [x], name=f"{name}_recurrent_group",
                           reversed=reversed)


def gru_unit(x, size=None, name=None, memory_boot=None, act="tanh",
             gate_act="sigmoid", param=None, bias=True,
             bias_param=None, naive=False):
    """One GRU timestep inside a recurrent_group step (networks.py:840
    gru_unit). `x` must already be the 3*size gate pre-projection."""
    if size is None:
        assert x.size % 3 == 0, f"gru_unit input {x.size} % 3 != 0"
        size = x.size // 3
    name = name or current().uniq("gru_unit")
    out_mem = memory(name, size=size, boot_layer=memory_boot)
    return _add("gru_step_naive" if naive else "gru_step", [x, out_mem],
                name=name, size=size, act=act, bias=bias, param=param,
                bias_param=bias_param, active_gate_type=gate_act)


def gru_group(x, size=None, name=None, memory_boot=None, reversed=False,
              act="tanh", gate_act="sigmoid", param=None, bias=True,
              bias_param=None, naive=False):
    """recurrent_group-built GRU over a 3*size-projected sequence
    (networks.py:902 gru_group) — grumemory math with per-step hidden
    states addressable inside the group."""
    if size is None:
        assert x.size % 3 == 0, f"gru_group input {x.size} % 3 != 0"
        size = x.size // 3
    name = name or current().uniq("gru_group")

    def step(ipt):
        return gru_unit(ipt, size=size, name=name,
                        memory_boot=memory_boot, act=act,
                        gate_act=gate_act, param=param, bias=bias,
                        bias_param=bias_param, naive=naive)

    return recurrent_group(step, [x], name=f"{name}_recurrent_group",
                           reversed=reversed)


def simple_gru2(x, size, name=None, act="tanh", gate_act="sigmoid",
                reversed=False):
    """fc(3h) + grumemory (networks.py:1061 simple_gru2 — the faster
    formulation of simple_gru; here both lower to the same scanned
    cell, the distinction is per-step state addressability only)."""
    name = name or current().uniq("gru2")
    proj = fc(x, size=size * 3, name=f"{name}_transform", bias=True)
    return grumemory(proj, size=size, name=name, act=act,
                     gate_act=gate_act, reversed=reversed)


def bidirectional_gru(x, size, name=None, return_seq=False, act="tanh",
                      gate_act="sigmoid"):
    """(networks.py:1122 bidirectional_gru). return_seq=False concats
    the forward last / backward first frames; True concats the full
    output sequences."""
    name = name or current().uniq("bigru")
    fwd = simple_gru2(x, size, name=f"{name}_fw", act=act,
                      gate_act=gate_act)
    bwd = simple_gru2(x, size, name=f"{name}_bw", act=act,
                      gate_act=gate_act, reversed=True)
    if return_seq:
        return concat(fwd, bwd, name=name)
    return concat(last_seq(fwd), first_seq(bwd), name=name)


def img_conv_bn_pool(x, filter_size, num_filters, pool_size, name=None,
                     pool_type="max", act="relu", groups=1,
                     conv_stride=1, conv_padding=0, num_channel=None,
                     conv_param=None, pool_stride=1, pool_padding=0):
    """conv -> batch_norm(act) -> pool (networks.py:232
    img_conv_bn_pool)."""
    name = name or current().uniq("conv_bn_pool")
    c = conv(x, num_filters, filter_size, stride=conv_stride,
             padding=conv_padding, groups=groups, act="",
             param=conv_param, num_channels=num_channel,
             name=f"{name}_conv")
    bn = batch_norm(c, act=act, name=f"{name}_bn")
    return pool(bn, pool_size, pool_stride, padding=pool_padding,
                pool_type=pool_type, name=f"{name}_pool")


# ---- sequence structure ----

def seq_pool(x, pool_type="sum", level="seq", name=None, stride=0,
             output_max_index=False):
    """stride>0 pools each stride-window to one frame (output stays a
    sequence); output_max_index with max pooling emits the argmax
    timestep per feature instead of the value (both from
    SequencePoolLayer.cpp / MaxLayer.cpp)."""
    return _add("seqpool", [x], name=name, pool_type=pool_type,
                level=level, stride=stride,
                output_max_index=output_max_index)


def last_seq(x, name=None, stride=0, level="seq"):
    """level="subseq": one frame per subsequence of a nested input
    (AggregateLevel.TO_SEQUENCE); stride>0: one frame per
    stride-window (both from SequenceLastInstanceLayer.cpp)."""
    return _add("seqlastins", [x], name=name, stride=stride,
                level=level)


def first_seq(x, name=None, stride=0, level="seq"):
    return _add("seqlastins", [x], name=name, select_first=True,
                stride=stride, level=level)


def expand(x, ref, name=None, level="non-seq"):
    """level="seq" (ExpandLevel.FROM_SEQUENCE): x is a sequence with
    one frame per SUB-sequence of the nested ref; each frame repeats
    over its subsequence's timesteps."""
    return _add("expand", [x, ref], name=name, expand_level=level)


def seq_concat(a, b, name=None):
    return _add("seqconcat", [a, b], name=name)


def sub_seq(x, offset, size, name=None):
    """Dynamic per-example sub-span of a sequence (layers.py
    sub_seq_layer; SubSequenceLayer.cpp). offset/size: [B] id layers."""
    return _add("subseq", [x, offset, size], name=name, bias=False)


def seq_reverse(x, name=None):
    return _add("seqreverse", [x], name=name)


# ---- recurrent groups (trainer_config_helpers/layers.py memory:3160,
# recurrent_group:3610; executor in layers/recurrent_group.py) ----


class StaticInput:
    """Read-only per-sequence input to a recurrent group — the reference's
    StaticInput: a non-sliced value visible whole at every step (e.g. the
    encoder sequence for attention)."""

    def __init__(self, ref):
        self.ref = ref


class MemoryRef(LayerRef):
    """LayerRef for a memory link that also carries the memory record,
    so the reference's deferred-binding idiom works: `m = memory(
    name=None, size=...); ... ; m.set_input(layer)` (layers.py memory
    set_input — used by e.g. the reference test_rnn_group config)."""

    def __init__(self, name, builder, record):
        super().__init__(name, builder)
        object.__setattr__(self, "_record", record)

    def set_input(self, layer):
        self._record["layer"] = layer.name
        return self


def memory(name, size, boot_layer=None, boot_value=0.0):
    """Inside a recurrent_group step: the value the step-layer `name` had
    at t-1 (boot at t=0). Mirrors trainer_config_helpers memory().
    `name=None` defers the producing-layer binding to a later
    `.set_input(layer)` call on the returned ref."""
    g = current()
    link = f"@mem_{name}" if name is not None else g.uniq("@mem_anon")
    g.add(
        LayerConf(
            name=link, type="data", size=size,
            attrs={"dim": (size,), "is_seq": False, "is_ids": False},
        )
    )
    record = {
        "layer": name,
        "link": link,
        "boot_layer": boot_layer.name if boot_layer is not None else None,
        "boot_value": boot_value,
        "size": size,
    }
    g.memories.append(record)
    return MemoryRef(link, g, record)


def group_layer_conf(name, sub, *, parent_inputs, in_links, static_links,
                     out_links, reversed=False):
    """The scan-executor LayerConf for a recurrent group — the ONE
    place the contract lives (consumed by layers/recurrent_group.py);
    both recurrent_group below and the raw
    RecurrentLayerGroupBegin/End API build through it."""
    boot_layers = [
        m["boot_layer"] for m in sub.memories
        if m["boot_layer"] is not None
    ]
    return LayerConf(
        name=name,
        type="recurrent_group",
        size=0,
        inputs=[InputConf(n) for n in parent_inputs]
        + [InputConf(n) for n in boot_layers],
        attrs={
            "step_conf": sub.conf,
            "in_links": list(in_links),
            "static_links": list(static_links),
            "memories": sub.memories,
            "out_links": list(out_links),
            "reversed": reversed,
        },
    )


def recurrent_group(step, inputs, name=None, reversed=False):
    """Build a scanned step network. `inputs`: LayerRefs (sequence
    in-links, sliced per step) and/or StaticInput(ref). `step` receives
    one LayerRef per input (in order) and returns the output LayerRef
    (or tuple; first is the group's output)."""
    parent = current()
    name = name or parent.uniq("recurrent_group")
    seq_ins = [x for x in inputs if not isinstance(x, StaticInput)]
    stat_ins = [x.ref for x in inputs if isinstance(x, StaticInput)]
    # share the parent's name counters so auto-named step layers can never
    # collide with auto-named parent layers (one config namespace, as in
    # the reference where group layers live inside the global ModelConfig)
    with model() as sub:
        sub._counts = parent._counts
        step_args = []
        in_links, static_links = [], []

        def _parent_size(ref):
            try:
                return parent.conf.layer(ref.name).size
            except KeyError:
                return 0

        # stubs carry the parent layer's SIZE so size-dependent config
        # helpers (simple_attention's proj width) work on step args;
        # the group layer re-stamps dim/is_ids from the real inputs at
        # build time
        for i, r in enumerate(seq_ins):
            ln = f"@in_{i}"
            sz = _parent_size(r)
            sub.add(LayerConf(name=ln, type="data", size=sz,
                              attrs={"dim": (sz,), "is_seq": False,
                                     "is_ids": False}))
            in_links.append(ln)
        for i, r in enumerate(stat_ins):
            ln = f"@static_{i}"
            sz = _parent_size(r)
            sub.add(LayerConf(name=ln, type="data", size=sz,
                              attrs={"dim": (sz,), "is_seq": False,
                                     "is_ids": False}))
            static_links.append(ln)
        it_seq = iter(in_links)
        it_static = iter(static_links)
        for x in inputs:
            ln = next(it_static) if isinstance(x, StaticInput) else next(it_seq)
            step_args.append(LayerRef(ln, sub))
        out = step(*step_args)
    outs = list(out) if isinstance(out, (tuple, list)) else [out]
    lc = group_layer_conf(
        name, sub,
        parent_inputs=[r.name for r in seq_ins]
        + [r.name for r in stat_ins],
        in_links=in_links, static_links=static_links,
        out_links=[o.name for o in outs], reversed=reversed,
    )
    ref = parent.add(lc)
    if isinstance(out, (tuple, list)):
        # secondary out_links surface under their step-layer names
        return (ref,) + tuple(LayerRef(o.name, parent) for o in outs[1:])
    return ref


# ---- costs ----

def classification_cost(logits, label, name=None, coeff=1.0,
                        weight=None):
    ins = [logits, label] + ([weight] if weight is not None else [])
    return _add("classification_cost", ins, name=name or _cost_name(),
                bias=False, coeff=coeff)


def cross_entropy(prob, label, name=None, coeff=1.0, weight=None):
    ins = [prob, label] + ([weight] if weight is not None else [])
    return _add("multi-class-cross-entropy", ins,
                name=name or _cost_name(), bias=False, coeff=coeff)


def square_error(x, y, name=None, coeff=1.0, weight=None):
    ins = [x, y] + ([weight] if weight is not None else [])
    return _add("square_error", ins, name=name or _cost_name(),
                bias=False, coeff=coeff)


def rank_cost(a, b, label, name=None, coeff=1.0):
    return _add("rank-cost", [a, b, label], name=name or _cost_name(), bias=False,
                coeff=coeff)


def multibox_loss(priorbox_ref, gt_box, gt_label, loc_pred, conf_pred,
                  num_classes, name=None, overlap_threshold=0.5,
                  neg_pos_ratio=3.0, neg_overlap=0.5, background_id=0):
    """(trainer_config_helpers/layers.py multibox_loss_layer; gserver
    MultiBoxLossLayer.cpp). loc_pred/conf_pred may be lists of per-scale
    feature outputs — they are concatenated like the reference's
    multi-input wiring."""
    if isinstance(loc_pred, (tuple, list)):
        loc_pred = concat(*loc_pred)
    if isinstance(conf_pred, (tuple, list)):
        conf_pred = concat(*conf_pred)
    return _add("multibox_loss",
                [priorbox_ref, gt_box, gt_label, loc_pred, conf_pred],
                name=name, bias=False,
                num_classes=num_classes,
                overlap_threshold=overlap_threshold,
                neg_pos_ratio=neg_pos_ratio, neg_overlap=neg_overlap,
                background_id=background_id)


def moe(x, num_experts, hidden=None, name=None, capacity_factor=1.25,
        expert_act="relu", aux_loss_coeff=0.01):
    """Sparsely-activated mixture-of-experts FFN (layers/moe.py). Wires
    the layer's load-balancing aux output into a sum_cost so the
    trainer applies it alongside the task loss."""
    ref = _add("moe", [x], name=name, bias=False, num_experts=num_experts,
               hidden=hidden or 0, capacity_factor=capacity_factor,
               expert_act=expert_act)
    if aux_loss_coeff:
        sum_cost(LayerRef(f"{ref.name}@aux", current()),
                 name=f"{ref.name}@aux_cost", coeff=aux_loss_coeff)
    return ref


def dot_mul(a, b, name=None, act=""):
    """Elementwise product of two same-size layers (DotMulOperator)."""
    return _add("dot_mul", [a, b], name=name, bias=False, act=act)


def slope_intercept(x, slope=1.0, intercept=0.0, name=None):
    return _add("slope_intercept", [x], name=name, bias=False,
                slope=slope, intercept=intercept)


def interpolation(weight, a, b, name=None):
    return _add("interpolation", [weight, a, b], name=name, bias=False)


def soft_binary_cross_entropy(prob, label, name=None, coeff=1.0):
    """Elementwise binary CE with soft labels (layers.py
    cross_entropy_with_selfnorm family; CostLayer.cpp
    SoftBinaryClassCrossEntropy)."""
    return _add("soft_binary_class_cross_entropy", [prob, label],
                name=name or _cost_name(), bias=False, coeff=coeff)


def sum_cost(x, name=None, coeff=1.0):
    """(trainer_config_helpers sum_cost): cost = sum of the input."""
    return _add("sum_cost", [x], name=name or _cost_name(), bias=False,
                coeff=coeff)


def multi_binary_label_cross_entropy(prob, label, name=None, coeff=1.0):
    """Multi-label binary CE (CostLayer.cpp
    MultiBinaryLabelCrossEntropy); label is a dense 0/1 matrix."""
    return _add("multi_binary_label_cross_entropy", [prob, label],
                name=name or _cost_name(), bias=False, coeff=coeff)


def eltmul(a, b, scale=1.0, name=None):
    """Elementwise product (the reference mixed-layer DotMulOperator,
    config_parser.py DotMulOperator)."""
    return _add("eltmul", [a, b], name=name, bias=False, scale=scale)


def crf(emission, label, num_tags, name=None, param=None, coeff=1.0):
    """(layers.py crf_layer)."""
    return _add("crf", [emission, label], name=name or _cost_name(), size=num_tags,
                bias=False, param=param, coeff=coeff)


def crf_decoding(emission, num_tags, label=None, name=None, param=None):
    ins = [emission] if label is None else [emission, label]
    return _add("crf_decoding", ins, name=name, size=num_tags, bias=False,
                param=param)


# ---- long-tail layers (layers/extras.py) ----

def selective_fc(x, select=None, *, size, name=None, act="", bias=True,
                 param=None):
    """(layers.py selective_fc_layer). `select` is a dense 0/1 mask layer
    [B, size]; omitted -> plain fc behavior."""
    ins = [x] if select is None else [x, select]
    return _add("selective_fc", ins, name=name, size=size, act=act,
                bias=bias, param=param)


def conv_shift(a, b, name=None):
    """Circular convolution (layers.py conv_shift_layer, NTM)."""
    return _add("conv_shift", [a, b], name=name, bias=False)


def bilinear_interp(x, out_size_x, out_size_y, name=None):
    return _add("bilinear_interp", [x], name=name, bias=False,
                out_size_x=out_size_x, out_size_y=out_size_y)


def linear_comb(weights, vectors, size, name=None):
    """(layers.py linear_comb_layer / convex_comb_layer)."""
    return _add("convex_comb", [weights, vectors], name=name, size=size,
                bias=False)


def eos_id(x, eos_id, name=None):
    return _add("eos_id", [x], name=name, bias=False, eos_id=eos_id)


def power(weight, x, name=None):
    return _add("power", [weight, x], name=name, bias=False)


def clip(x, min=-1.0, max=1.0, name=None):
    return _add("clip", [x], name=name, bias=False, min=min, max=max)


def row_conv(x, context_length, name=None, param=None):
    """Lookahead convolution (layers.py row_conv_layer, DS2)."""
    return _add("row_conv", [x], name=name, bias=False, param=param,
                context_length=context_length)


def featmap_expand(x, num_filters, name=None):
    return _add("featmap_expand", [x], name=name, bias=False,
                num_filters=num_filters)


def context_projection(x, context_length, context_start=None):
    """A mixed()-input edge concatenating neighboring timesteps
    (ContextProjection.h). Usage:
    mixed(size=D*L, inputs=[context_projection(x, L, start)])."""
    return (x, "context", {
        "context_length": context_length,
        "context_start": (
            context_start if context_start is not None
            else -(context_length // 2)
        ),
    })


# ---- detection (SSD) ----

def priorbox(feature, image, min_size, max_size=(), aspect_ratio=(),
             variance=(0.1, 0.1, 0.2, 0.2), flip=True, clip=True,
             name=None):
    """(layers.py priorbox_layer; gserver PriorBox.cpp)."""
    return _add("priorbox", [feature, image], name=name, bias=False,
                min_size=tuple(min_size), max_size=tuple(max_size),
                aspect_ratio=tuple(aspect_ratio), variance=tuple(variance),
                flip=flip, clip=clip)


def detection_output(priorbox_ref, loc_pred, conf_pred, num_classes,
                     name=None, nms_threshold=0.45, nms_top_k=400,
                     keep_top_k=200, confidence_threshold=0.01,
                     background_id=0):
    """(layers.py detection_output_layer; DetectionOutputLayer.cpp)."""
    if isinstance(loc_pred, (tuple, list)):
        loc_pred = concat(*loc_pred)
    if isinstance(conf_pred, (tuple, list)):
        conf_pred = concat(*conf_pred)
    return _add("detection_output", [priorbox_ref, loc_pred, conf_pred],
                name=name, bias=False, num_classes=num_classes,
                nms_threshold=nms_threshold, nms_top_k=nms_top_k,
                keep_top_k=keep_top_k,
                confidence_threshold=confidence_threshold,
                background_id=background_id)


# ---- prebuilt networks (trainer_config_helpers/networks.py) ----

def simple_img_conv_pool(x, num_filters, filter_size, pool_size, pool_stride,
                         act="relu", name=None, padding=0):
    """(networks.py:145 simple_img_conv_pool)."""
    c = conv(x, num_filters, filter_size, padding=padding, act=act,
             name=(name or "convpool") + "_conv")
    return pool(c, pool_size, pool_stride, name=(name or "convpool") + "_pool")


def img_conv_group(x, conv_num_filter, conv_filter_size,
                   pool_size, pool_stride, conv_act="relu",
                   conv_with_batchnorm=False, pool_type="max"):
    """A VGG block (networks.py:333 img_conv_group)."""
    h = x
    for i, nf in enumerate(conv_num_filter):
        h = conv(h, nf, conv_filter_size, padding=(conv_filter_size - 1) // 2,
                 act="" if conv_with_batchnorm else conv_act)
        if conv_with_batchnorm:
            h = batch_norm(h, act=conv_act)
    return pool(h, pool_size, pool_stride, pool_type=pool_type)


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     name=None, weight_act="tanh", transform_param=None,
                     softmax_param=None, size=None):
    """Bahdanau additive attention (networks.py:1298 simple_attention):
    e_j = v·f(W s + U h_j), a = seq_softmax(e), c = sum_j a_j h_j.
    `encoded_proj` carries U h_j precomputed once over the encoder;
    call inside a recurrent_group step with `decoder_state` a memory
    (stubs inherit the parent layer's size there). Inside a
    BeamSearchDecoder step, pass `static_sizes=` to the decoder (or
    `size=` here) — its standalone stubs have no parent to inherit
    from."""
    name = name or current().uniq("simple_attention")
    proj_size = size or current().conf.layer(encoded_proj.name).size
    assert proj_size, (
        "simple_attention: encoded_proj has no size here — inside a "
        "BeamSearchDecoder step pass static_sizes= to the decoder, or "
        "size= to this call"
    )
    proj_s = fc(decoder_state, size=proj_size, bias=False,
                param=transform_param, name=f"{name}_dec_proj")
    expanded = expand(proj_s, encoded_proj, name=f"{name}_expand")
    mix = addto(encoded_proj, expanded, act=weight_act,
                name=f"{name}_mix")
    scores = fc(mix, size=1, bias=False, act="sequence_softmax",
                param=softmax_param, name=f"{name}_score")
    weighted = scaling(scores, encoded_sequence, name=f"{name}_weighted")
    return seq_pool(weighted, pool_type="sum", name=f"{name}_context")


def prelu(x, name=None, partial_sum=0, param=None):
    return _add("prelu", [x], name=name, bias=False, param=param,
                partial_sum=partial_sum)


def gated_unit(x, size, act="", name=None, bias=True):
    return _add("gated_unit", [x], name=name, size=size, act=act,
                bias=bias)


def repeat(x, num_repeats, name=None):
    return _add("repeat", [x], name=name, bias=False,
                num_repeats=num_repeats)


def kmax_seq_score(scores, beam_size=1, name=None):
    return _add("kmax_seq_score", [scores], name=name, bias=False,
                beam_size=beam_size)


def sub_nested_seq(x, selected_indices, name=None):
    """(layers.py:6098 sub_nested_seq_layer)."""
    return _add("sub_nested_seq", [x, selected_indices], name=name,
                bias=False)


def get_output(layer, arg_name, name=None):
    """Reference get_output_layer: reference a layer's named extra
    output (e.g. lstm_step's cell state). Extra outputs are addressable
    directly as '<layer>@<arg>' input names; with `name` given, an
    identity layer is materialized under that name so by-name lookups
    (outputs, evaluators, boot links) resolve."""
    ref = LayerRef(f"{layer.name}@{arg_name}", current())
    if name:
        return _add("addto", [ref], name=name, bias=False)
    return ref
