"""Recurrent group: a user-defined step network scanned over time.

Reference: the RecurrentGradientMachine
(gserver/gradientmachines/RecurrentGradientMachine.{h,cpp}, 1455 LoC) plus
its config plumbing (RecurrentLayerGroup.cpp, AgentLayer.cpp,
proto SubModelConfig ModelConfig.proto:579) and the DSL front-end
(trainer_config_helpers/layers.py memory:3160, recurrent_group:3610).

The reference builds one frame network per timestep and walks them
sequentially, wiring memory agents frame(t-1)->frame(t). TPU-first
redesign: the step net is built ONCE as a sub-Network of pure functions
and driven by `lax.scan`; memories are scan carries with masked
carry-through on padding; in-links are time slices; static links are
closed over (read-only per-sequence inputs, including full encoder
sequences for attention). XLA compiles the whole loop as one fused
while-op — no per-frame graph rebuilding.

Group layer conf:
  inputs: [in_links..., static_links..., boot_layers...]
  attrs:
    step_conf    — nested ModelConf (JSON dict) of the step net
    in_links     — step data-layer name per sliced sequence input
    static_links — step data-layer name per static input
    memories     — [{"layer": producer-in-step, "link": step data name,
                    "boot_layer": parent input name | None,
                    "boot_value": float, "size": int}]
    out_links    — step layer names to emit as sequences
    reversed     — scan right-to-left
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.config import ModelConf, _model_from_dict
from paddle_tpu.core.registry import LAYERS
from paddle_tpu.layers.base import Ctx, Layer, Spec
from paddle_tpu.ops import sequence_ops as sops


@LAYERS.register("recurrent_group", "recurrent_layer_group")
class RecurrentGroupLayer(Layer):
    def build(self, in_specs):
        from paddle_tpu.network import Network  # cycle-free late import

        a = self.conf.attrs
        step_conf = a["step_conf"]
        if isinstance(step_conf, dict):
            step_conf = _model_from_dict(step_conf)
        assert isinstance(step_conf, ModelConf)
        self.in_links = list(a.get("in_links", []))
        self.static_links = list(a.get("static_links", []))
        self.memories = list(a.get("memories", []))
        self.out_links = list(a.get("out_links", []))
        self.reversed = a.get("reversed", False)

        n_in = len(self.in_links)
        n_static = len(self.static_links)
        self._in_specs = in_specs
        boot_specs = in_specs[n_in + n_static:]
        # Nested (two-level) sequences: when the in-links are
        # sub-sequences (Argument.h:84-93 subSequenceStartPositions),
        # the OUTER walk is over subsequences and each step sees one
        # subsequence as a plain sequence — the
        # RecurrentGradientMachine's hierarchical-RNN semantics
        # (RecurrentGradientMachine.cpp sequence-level > 0).
        self.nested = bool(in_specs) and in_specs[0].has_subseq

        # fill step-net data layer dims from parent specs
        for i, link in enumerate(self.in_links):
            lc = step_conf.layer(link)
            lc.attrs["dim"] = tuple(in_specs[i].dim)
            lc.attrs["is_seq"] = self.nested
            lc.attrs["is_ids"] = in_specs[i].is_ids
        for i, link in enumerate(self.static_links):
            s = in_specs[n_in + i]
            lc = step_conf.layer(link)
            lc.attrs["dim"] = tuple(s.dim)
            lc.attrs["is_seq"] = s.is_seq
            lc.attrs["is_ids"] = s.is_ids
        for m in self.memories:
            lc = step_conf.layer(m["link"])
            lc.attrs["dim"] = (m["size"],)
            lc.attrs["is_seq"] = False

        self.step_net = Network(step_conf)
        self._boot_specs = boot_specs
        # Expose the step net's params as this layer's: names merge into
        # the parent param table, giving sharing-by-name as in the
        # reference. Params of AUTO-named step layers (dsl `__fc_0__`
        # style) are prefixed with the group name — per-builder uniq
        # counters restart inside the step context, so without the prefix
        # an unnamed parent layer of the same shape would silently share
        # weights with an unrelated step layer.
        renames = {
            old: f"_{self.name}.{old}"
            for old in self.step_net.param_confs
            if old.startswith("___")
        }
        for old, new in renames.items():
            pc = self.step_net.param_confs.pop(old)
            pc.name = new
            self.step_net.param_confs[new] = pc
        for slot_map in self.step_net.layer_params.values():
            for slot, g in list(slot_map.items()):
                if g in renames:
                    slot_map[slot] = renames[g]
        pcs = dict(self.step_net.param_confs)
        out_spec = self.step_net.specs[self.out_links[0]]
        self._out_specs = [self.step_net.specs[o] for o in self.out_links]
        # nested mode: a sequence-valued step output stays a nested
        # sequence; a scalar-per-subsequence output (e.g. last_seq of an
        # inner rnn) becomes a plain sequence over subsequences
        return (
            Spec(
                dim=out_spec.dim,
                is_seq=True,
                is_ids=out_spec.is_ids,
                has_subseq=self.nested and out_spec.is_seq,
            ),
            pcs,
        )

    def extra_output_specs(self):
        """Secondary out_links, registered by Network under their step-net
        layer names so parent layers can consume them."""
        return {
            o: Spec(
                dim=s.dim,
                is_seq=True,
                is_ids=s.is_ids,
                has_subseq=self.nested and s.is_seq,
            )
            for o, s in zip(self.out_links[1:], self._out_specs[1:])
        }

    def _boot(self, m, inputs, bsz, dtype):
        n_in = len(self.in_links)
        n_static = len(self.static_links)
        if m.get("boot_layer"):
            # boot layer is one of the trailing parent inputs
            names = [ic.name for ic in self.conf.inputs[n_in + n_static:]]
            idx = names.index(m["boot_layer"])
            return inputs[n_in + n_static + idx].value
        return jnp.full((bsz, m["size"]), m.get("boot_value", 0.0), dtype)

    def forward(self, params, inputs, ctx):
        if self.nested:
            return self._forward_nested(params, inputs, ctx)
        n_in = len(self.in_links)
        n_static = len(self.static_links)
        seq_arg = inputs[0]
        assert seq_arg.is_seq, "recurrent_group first in_link must be a sequence"
        bsz, t = seq_arg.batch, seq_arg.max_len
        dtype = jnp.float32
        seq_lens = seq_arg.seq_lens

        # sliced sequence inputs, time-major
        xs_vals = []
        for i in range(n_in):
            a = inputs[i]
            v = a.ids if a.ids is not None else a.value
            if self.reversed:
                v = sops.reverse_seq(v, seq_lens)
            xs_vals.append(v.swapaxes(0, 1))  # [T,B,...]
        mask_tb = (
            jnp.arange(t, dtype=jnp.int32)[None, :] < seq_lens[:, None]
        ).astype(dtype).swapaxes(0, 1)  # [T,B]

        static_feed = {}
        for i, link in enumerate(self.static_links):
            static_feed[link] = inputs[n_in + i]

        init_carry = {
            m["layer"]: self._boot(m, inputs, bsz, dtype)
            for m in self.memories
        }

        def body(carry, inp):
            m_t = inp[-1]
            feed = dict(static_feed)
            for i, link in enumerate(self.in_links):
                x_t = inp[i]
                if self._in_specs[i].is_ids:
                    feed[link] = Arg(ids=x_t)
                else:
                    feed[link] = Arg(value=x_t)
            for m in self.memories:
                feed[m["link"]] = Arg(value=carry[m["layer"]])
            outs, _ = self.step_net.forward(
                params, feed, train=ctx.train, rng=ctx.rng
            )
            new_carry = {}
            for m in self.memories:
                new_v = outs[m["layer"]].value
                prev = carry[m["layer"]]
                mm = m_t[:, None]
                # keep the carry dtype stable across steps: the float32
                # mask (or a step op that upcasts) must not promote a
                # bfloat16 carry under AMP — scan requires equal types
                new_carry[m["layer"]] = (
                    mm * new_v + (1.0 - mm) * prev
                ).astype(prev.dtype)
            ys = []
            for o in self.out_links:
                out_a = outs[o]
                y = out_a.ids if out_a.ids is not None else out_a.value
                if y.dtype in (jnp.float32, jnp.bfloat16, jnp.float16):
                    y = y * m_t.reshape((bsz,) + (1,) * (y.ndim - 1)).astype(
                        y.dtype
                    )
                ys.append(y)
            return new_carry, tuple(ys)

        xs = tuple(xs_vals) + (mask_tb,)
        _, ys = jax.lax.scan(body, init_carry, xs)
        outs = []
        for i, y in enumerate(ys):
            y = y.swapaxes(0, 1)  # [B,T,...]
            if self.reversed:
                y = sops.reverse_seq(y, seq_lens)
            spec = self._out_specs[i]
            if spec.is_ids:
                outs.append(Arg(ids=y, seq_lens=seq_lens))
            else:
                outs.append(Arg(value=y, seq_lens=seq_lens))
        self._extra_outs = {
            o: outs[i] for i, o in enumerate(self.out_links[1:], start=1)
        }
        return outs[0]

    # ---- nested (two-level) sequences --------------------------------

    def _forward_nested(self, params, inputs, ctx):
        """Outer scan over SUBSEQUENCES (RecurrentGradientMachine.cpp's
        hierarchical mode, Argument.h:84-93): each outer step feeds the
        step net ONE subsequence as a plain sequence; memories carry
        across subsequences (masked through empty/padded ones).

        Layout: a nested Arg is flat-packed [B, T, ...] with
        subseq_lens [B, S]. The in-links are unpacked once into dense
        [B, S, L, ...] (L = longest subsequence bound, default T), the
        outer scan runs over S, and sequence-valued outputs are packed
        back into the flat nested layout."""
        n_in = len(self.in_links)
        n_static = len(self.static_links)
        seq_arg = inputs[0]
        sub_lens = seq_arg.subseq_lens  # [B, S]
        bsz, t = seq_arg.batch, seq_arg.max_len
        s_max = sub_lens.shape[1]
        dtype = jnp.float32
        lcap = self.conf.attrs.get("max_subseq_len") or t
        l = min(lcap, t)

        # flat offsets of each subsequence start (exclusive prefix sum)
        # — from the ORIGINAL lengths, which define the flat layout
        csum = jnp.cumsum(sub_lens, axis=1)
        offsets = jnp.concatenate(
            [jnp.zeros((bsz, 1), sub_lens.dtype), csum[:, :-1]], axis=1
        )  # [B, S]
        # a max_subseq_len below the data's longest subsequence
        # TRUNCATES each subsequence to l steps; all step feeds, masks
        # and output metadata use the clamped lengths
        sub_lens = jnp.minimum(sub_lens, l)
        pos = jnp.arange(l, dtype=sub_lens.dtype)  # [L]
        idx = offsets[:, :, None] + pos[None, None, :]  # [B, S, L]
        valid = pos[None, None, :] < sub_lens[:, :, None]
        idx = jnp.clip(idx, 0, t - 1)

        def unpack(flat):  # [B, T, ...] -> [B, S, L, ...]
            return jax.vmap(lambda xb, ib: xb[ib])(flat, idx)

        order = (
            jnp.arange(s_max - 1, -1, -1)
            if self.reversed
            else jnp.arange(s_max)
        )

        xs_vals = []
        for i in range(n_in):
            a = inputs[i]
            v = a.ids if a.ids is not None else a.value
            nested = unpack(v)[:, order]  # [B, S, L, ...]
            xs_vals.append(nested.swapaxes(0, 1))  # [S, B, L, ...]
        sub_lens_s = sub_lens[:, order].swapaxes(0, 1)  # [S, B]

        static_feed = {}
        for i, link in enumerate(self.static_links):
            static_feed[link] = inputs[n_in + i]

        init_carry = {
            m["layer"]: self._boot(m, inputs, bsz, dtype)
            for m in self.memories
        }
        out_is_seq = [s.is_seq for s in self._out_specs]

        def body(carry, inp):
            lens_s = inp[-1]  # [B] this subsequence's lengths
            m_s = (lens_s > 0).astype(dtype)[:, None]
            feed = dict(static_feed)
            for i, link in enumerate(self.in_links):
                x_s = inp[i]  # [B, L, ...]
                if self._in_specs[i].is_ids:
                    feed[link] = Arg(ids=x_s, seq_lens=lens_s)
                else:
                    feed[link] = Arg(value=x_s, seq_lens=lens_s)
            for m in self.memories:
                feed[m["link"]] = Arg(value=carry[m["layer"]])
            outs, _ = self.step_net.forward(
                params, feed, train=ctx.train, rng=ctx.rng
            )
            new_carry = {}
            for m in self.memories:
                src = outs[m["layer"]]
                new_v = src.value
                if new_v.ndim == carry[m["layer"]].ndim + 1:
                    # the memory source produced a SEQUENCE this outer
                    # step (per-timestep layer inside the subsequence
                    # walk): carry its last VALID frame — the
                    # sequence-level memory of the reference's
                    # subsequence-group pattern (test_rnn_group)
                    last = jnp.maximum(lens_s - 1, 0)
                    new_v = jax.vmap(lambda xb, j: xb[j])(new_v, last)
                prev = carry[m["layer"]]
                new_carry[m["layer"]] = (
                    m_s * new_v + (1.0 - m_s) * prev
                ).astype(prev.dtype)
            ys = []
            for o in self.out_links:
                out_a = outs[o]
                y = out_a.ids if out_a.ids is not None else out_a.value
                if y.dtype in (jnp.float32, jnp.bfloat16, jnp.float16):
                    y = y * m_s.reshape(
                        (bsz,) + (1,) * (y.ndim - 1)
                    ).astype(y.dtype)
                ys.append(y)
            return new_carry, tuple(ys)

        xs = tuple(xs_vals) + (sub_lens_s,)
        _, ys = jax.lax.scan(body, init_carry, xs)

        n_subseq = jnp.sum((sub_lens > 0).astype(jnp.int32), axis=1)
        inv_order = order  # reversing twice restores the order
        outs = []
        for i, y in enumerate(ys):
            y = y.swapaxes(0, 1)[:, inv_order]  # [B, S, ...] outer order
            spec = self._out_specs[i]
            if out_is_seq[i]:
                # pack inner sequences back into the flat nested layout
                d = y.shape[3:]
                y2 = (y * valid.reshape(valid.shape + (1,) * len(d))
                      .astype(y.dtype)).reshape((bsz, s_max * l) + d)
                flat_idx = idx.reshape(bsz, s_max * l)
                flat = jax.vmap(
                    lambda acc_i, yv: jnp.zeros((t,) + d, y.dtype)
                    .at[acc_i]
                    .add(yv)
                )(flat_idx, y2)
                arg = Arg(
                    value=None if spec.is_ids else flat,
                    ids=flat if spec.is_ids else None,
                    seq_lens=seq_arg.seq_lens,
                    subseq_lens=sub_lens,
                )
            else:
                arg = Arg(
                    value=None if spec.is_ids else y,
                    ids=y if spec.is_ids else None,
                    seq_lens=n_subseq,
                )
            outs.append(arg)
        self._extra_outs = {
            o: outs[i] for i, o in enumerate(self.out_links[1:], start=1)
        }
        return outs[0]
