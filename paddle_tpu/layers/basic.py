"""Core dense layers: data, fc, embedding, mixed-style combinators.

Reference: paddle/gserver/layers/{DataLayer,FullyConnectedLayer,
TableProjection,AddtoLayer,ConcatenateLayer,CosSimLayer,
InterpolationLayer,SlopeInterceptLayer,ScalingLayer,DotMulLayer,
TensorLayer,OuterProdLayer,SelectiveFullyConnectedLayer}.cpp — rebuilt as
pure jnp functions; matmuls hit the MXU via jnp.dot/einsum.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.registry import LAYERS
from paddle_tpu.layers.base import Ctx, Layer, Spec


@LAYERS.register("data")
class DataLayer(Layer):
    """Input placeholder (gserver/layers/DataLayer.cpp). attrs:
    is_seq, has_subseq, is_ids, dim (feature shape tuple) or size."""

    def build(self, in_specs):
        a = self.conf.attrs
        dim = tuple(a.get("dim", (self.conf.size,)))
        return (
            Spec(
                dim=dim,
                is_seq=a.get("is_seq", False),
                has_subseq=a.get("has_subseq", False),
                is_ids=a.get("is_ids", False),
            ),
            {},
        )

    def forward(self, params, inputs, ctx):
        raise RuntimeError("data layers are fed, not computed")


@LAYERS.register("fc")
class FCLayer(Layer):
    """Fully connected: y = act(sum_i x_i @ W_i + b)
    (gserver/layers/FullyConnectedLayer.cpp). Multiple inputs sum into one
    output, as in the reference."""

    def build(self, in_specs):
        out = self.conf.size
        pcs = {}
        seq = any(s.is_seq for s in in_specs)
        sub = any(s.has_subseq for s in in_specs)
        for i, s in enumerate(in_specs):
            pcs[f"w{i}"] = self.weight_conf(i, (s.size, out))
        b = self.bias_conf((out,))
        if b is not None:
            pcs["b"] = b
        return Spec(dim=(out,), is_seq=seq, has_subseq=sub), pcs

    def forward(self, params, inputs, ctx):
        y = None
        seq_lens = None
        subseq_lens = None
        any_seq = any(a.is_seq for a in inputs)
        for i, arg in enumerate(inputs):
            x = arg.value
            if arg.is_seq:
                seq_lens = arg.seq_lens
                subseq_lens = arg.subseq_lens
            x = x.reshape(x.shape[: 2 if arg.is_seq else 1] + (-1,))
            t = jnp.dot(x, params[f"w{i}"])
            if any_seq and not arg.is_seq:
                # mixed seq + non-seq inputs: broadcast the per-example
                # term over the time axis (a sequence-level memory
                # feeding a per-timestep fc — the reference
                # test_rnn_group subsequence-group pattern)
                t = t[:, None, :]
            y = t if y is None else y + t
        if "b" in params:
            y = y + params["b"]
        y = self.apply_activation_and_dropout(y, ctx, seq_lens)
        return Arg(value=y, seq_lens=seq_lens, subseq_lens=subseq_lens)


@LAYERS.register("embedding")
class EmbeddingLayer(Layer):
    """Id -> row lookup (the reference's table_projection /
    TableProjection.cpp over a sparse-update parameter,
    math/SparseRowMatrix.h). Input must carry ids. The table parameter is
    marked sparse_update so the optimizer can apply row-sparse updates and
    the parallel runtime can shard it over the mesh."""

    def build(self, in_specs):
        (s,) = in_specs
        assert s.is_ids, f"embedding layer {self.name} needs an ids input"
        vocab = self.conf.attrs["vocab_size"]
        pc = self.weight_conf(0, (vocab, self.conf.size))
        pc.sparse_update = True
        if self.conf.attrs.get("sharded", False):
            pc.sparse_remote_update = True  # row-shard over the mesh
        return (
            Spec(
                dim=(self.conf.size,),
                is_seq=s.is_seq,
                has_subseq=s.has_subseq,  # nested slots stay nested
            ),
            {"w0": pc},
        )

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        y = jnp.take(params["w0"], arg.ids, axis=0)
        if arg.is_seq:
            from paddle_tpu.ops.sequence_ops import _mask

            y = y * _mask(arg.seq_lens, y.shape[1], y.dtype)[..., None]
        return Arg(
            value=y, seq_lens=arg.seq_lens, subseq_lens=arg.subseq_lens
        )


@LAYERS.register("addto")
class AddtoLayer(Layer):
    """Elementwise sum of same-shaped inputs + bias + activation
    (gserver/layers/AddtoLayer.cpp)."""

    def build(self, in_specs):
        s0 = in_specs[0]
        pcs = {}
        b = self.bias_conf((s0.size,))
        if b is not None:
            pcs["b"] = b
        return s0, pcs

    def forward(self, params, inputs, ctx):
        y = inputs[0].value
        for a in inputs[1:]:
            y = y + a.value
        if "b" in params:
            y = y + params["b"]
        y = self.apply_activation_and_dropout(y, ctx, inputs[0].seq_lens)
        return inputs[0].with_value(y)


@LAYERS.register("concat", "concat2")
class ConcatLayer(Layer):
    """Feature-axis concat (gserver/layers/ConcatenateLayer.cpp). When all
    inputs are same-H,W image specs, concatenates channels and keeps the
    spatial shape (inception-style branch merge); otherwise flattens."""

    def build(self, in_specs):
        seq = any(s.is_seq for s in in_specs)
        self._sub = any(s.has_subseq for s in in_specs)
        self._image = (
            all(len(s.dim) == 3 for s in in_specs)
            and len({s.dim[:2] for s in in_specs}) == 1
        )
        pcs = {}
        if self._image:
            h, w = in_specs[0].dim[:2]
            c = sum(s.dim[2] for s in in_specs)
            self._in_dims = [s.dim for s in in_specs]
            b = self.bias_conf((h * w * c,))
            if b is not None:
                pcs["b"] = b
            return Spec(dim=(h, w, c), is_seq=seq,
                        has_subseq=self._sub), pcs
        tot = sum(s.size for s in in_specs)
        b = self.bias_conf((tot,))
        if b is not None:
            pcs["b"] = b
        return Spec(dim=(tot,), is_seq=seq, has_subseq=self._sub), pcs

    def forward(self, params, inputs, ctx):
        flat = []
        seq_lens = None
        subseq_lens = None
        for i, a in enumerate(inputs):
            x = a.value
            lead = 2 if a.is_seq else 1
            if a.is_seq:
                seq_lens = a.seq_lens
                subseq_lens = a.subseq_lens
            if self._image:
                x = x.reshape(x.shape[:lead] + self._in_dims[i])
            else:
                x = x.reshape(x.shape[:lead] + (-1,))
            flat.append(x)
        y = jnp.concatenate(flat, axis=-1)
        if "b" in params:
            b = params["b"]
            y = y + (b.reshape(y.shape[-3:]) if self._image else b)
        y = self.apply_activation_and_dropout(y, ctx, seq_lens)
        return Arg(value=y, seq_lens=seq_lens, subseq_lens=subseq_lens)


@LAYERS.register("cos")
class CosSimLayer(Layer):
    """Cosine similarity of two inputs, scaled (gserver/layers/CosSimLayer.cpp,
    function/CosSimOp.cpp). attrs: scale (default 1). With size=k > 1,
    input b packs k vectors of a's width and the output is the k
    similarities per example (the reference's multi-vector form,
    cos_sim(size=k))."""

    def build(self, in_specs):
        seq = any(s.is_seq for s in in_specs)
        k = self.conf.size or 1
        if k > 1:
            assert in_specs[1].size == k * in_specs[0].size, (
                f"cos {self.name}: size={k} needs b of width "
                f"{k}*{in_specs[0].size}, got {in_specs[1].size}"
            )
        return Spec(dim=(k,), is_seq=seq), {}

    def forward(self, params, inputs, ctx):
        a, b = inputs[0].value, inputs[1].value
        scale = self.conf.attrs.get("scale", 1.0)
        k = self.conf.size or 1
        eps = 1e-8
        if k > 1:
            b = b.reshape(b.shape[:-1] + (k, a.shape[-1]))
            a = a[..., None, :]
            num = jnp.sum(a * b, axis=-1)
            den = jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1)
            return Arg(value=scale * num / jnp.maximum(den, eps),
                       seq_lens=inputs[0].seq_lens)
        num = jnp.sum(a * b, axis=-1, keepdims=True)
        den = jnp.linalg.norm(a, axis=-1, keepdims=True) * jnp.linalg.norm(
            b, axis=-1, keepdims=True
        )
        y = scale * num / jnp.maximum(den, eps)
        return Arg(value=y, seq_lens=inputs[0].seq_lens)


@LAYERS.register("interpolation")
class InterpolationLayer(Layer):
    """y = w*x1 + (1-w)*x2 with per-example scalar w
    (gserver/layers/InterpolationLayer.cpp). inputs: [w(1-dim), x1, x2]."""

    def build(self, in_specs):
        return in_specs[1], {}

    def forward(self, params, inputs, ctx):
        w = inputs[0].value
        x1, x2 = inputs[1].value, inputs[2].value
        y = w * x1 + (1.0 - w) * x2
        return inputs[1].with_value(y)

@LAYERS.register("scaling")
class ScalingLayer(Layer):
    """y = scalar_input * vector_input (gserver/layers/ScalingLayer.cpp).
    inputs: [weight(dim 1), x]."""

    def build(self, in_specs):
        return in_specs[1], {}

    def forward(self, params, inputs, ctx):
        return inputs[1].with_value(inputs[0].value * inputs[1].value)


@LAYERS.register("dot_mul")
class DotMulLayer(Layer):
    """Elementwise product of two inputs (DotMulOperator in MixedLayer)."""

    def build(self, in_specs):
        return in_specs[0], {}

    def forward(self, params, inputs, ctx):
        y = inputs[0].value * inputs[1].value
        y = self.apply_activation_and_dropout(y, ctx, inputs[0].seq_lens)
        return inputs[0].with_value(y)


@LAYERS.register("slope_intercept")
class SlopeInterceptLayer(Layer):
    """y = slope*x + intercept (gserver/layers/SlopeInterceptLayer.cpp)."""

    def build(self, in_specs):
        return in_specs[0], {}

    def forward(self, params, inputs, ctx):
        a = self.conf.attrs
        y = a.get("slope", 1.0) * inputs[0].value + a.get("intercept", 0.0)
        return inputs[0].with_value(y)


@LAYERS.register("mixed")
class MixedLayer(Layer):
    """Sum of projections (gserver/layers/MixedLayer.cpp). Each input edge
    has attrs["proj"] in {identity, full_matrix, table, dotmul, scaling,
    trans_full_matrix}; results are summed, then bias+activation — the
    reference's projection/operator composition model."""

    def build(self, in_specs):
        from paddle_tpu.dsl import mixed_proj_size

        out = self.conf.size
        if not out:
            # size omitted: infer from size-preserving projections
            # (reference layers.py mixed_layer size=None inference)
            for s, ic in zip(in_specs, self.conf.inputs):
                inferred = mixed_proj_size(
                    ic.attrs.get("proj", "full_matrix"), s.size, ic.attrs
                )
                if inferred:
                    out = inferred
                    break
            assert out, (
                f"mixed layer {self.name}: size must be given (no "
                f"size-preserving projection to infer it from)"
            )
            self.conf.size = out
        pcs = {}
        seq = any(s.is_seq for s in in_specs)
        for i, (s, ic) in enumerate(zip(in_specs, self.conf.inputs)):
            proj = ic.attrs.get("proj", "full_matrix")
            if proj == "full_matrix":
                pcs[f"w{i}"] = self.weight_conf(i, (s.size, out))
            elif proj == "trans_full_matrix":
                pcs[f"w{i}"] = self.weight_conf(i, (out, s.size))
            elif proj == "table":
                vocab = ic.attrs["vocab_size"]
                pc = self.weight_conf(i, (vocab, out))
                pc.sparse_update = True
                pcs[f"w{i}"] = pc
            elif proj == "dotmul":
                pcs[f"w{i}"] = self.weight_conf(i, (out,))
            elif proj == "scaling":
                pcs[f"w{i}"] = self.weight_conf(i, (1,))
            elif proj == "identity":
                assert s.size == out, f"identity proj size mismatch on {self.name}"
            elif proj == "slice":
                # SliceProjection.cpp: concat of [start, end) slices
                tot = sum(e - b for b, e in ic.attrs["slices"])
                assert tot == out, (
                    f"slice proj on {self.name}: slices sum to {tot}, "
                    f"layer is {out} wide"
                )
                for b_, e_ in ic.attrs["slices"]:
                    assert e_ <= s.size, (
                        f"slice ({b_}, {e_}) beyond input width {s.size}"
                    )
            elif proj == "context":
                # ContextProjection.h:18-43: concat context_length
                # neighboring timesteps starting at offset context_start
                L = ic.attrs["context_length"]
                assert s.size * L == out, (
                    f"context proj on {self.name}: {s.size}*{L} != {out}"
                )
                assert s.is_seq, "context projection needs a sequence input"
            else:
                raise KeyError(f"unknown projection {proj!r}")
        # conv projections share the bias PER FILTER
        # (config_parser.py:2984: shared_biases=True, bias_size =
        # sum of the projections' filter counts)
        conv_bias = [
            ic.attrs.get("conv_bias") for ic in self.conf.inputs
        ]
        self._shared_bias = bool(conv_bias and conv_bias[0])
        bias_width = (
            sum(cb or 0 for cb in conv_bias)
            if self._shared_bias
            else out
        )
        b = self.bias_conf((bias_width,))
        if b is not None:
            pcs["b"] = b
        sub = any(s.has_subseq for s in in_specs)
        if self._shared_bias and len(in_specs[0].dim) == 3:
            # a mixed over conv projections keeps the conv's spatial
            # shape (reference ConvProjection output) so a downstream
            # concat merges CHANNELS, matching a concat of conv layers
            return Spec(dim=in_specs[0].dim, is_seq=seq,
                        has_subseq=sub), pcs
        return Spec(dim=(out,), is_seq=seq, has_subseq=sub), pcs

    def forward(self, params, inputs, ctx):
        y = None
        seq_lens = None
        subseq_lens = None
        for i, (a, ic) in enumerate(zip(inputs, self.conf.inputs)):
            proj = ic.attrs.get("proj", "full_matrix")
            if a.is_seq:
                seq_lens = a.seq_lens
                subseq_lens = a.subseq_lens
            if proj == "identity":
                t = a.value
            elif proj == "full_matrix":
                t = jnp.dot(a.value, params[f"w{i}"])
            elif proj == "trans_full_matrix":
                t = jnp.dot(a.value, params[f"w{i}"].T)
            elif proj == "table":
                t = jnp.take(params[f"w{i}"], a.ids, axis=0)
            elif proj == "dotmul":
                t = a.value * params[f"w{i}"]
            elif proj == "scaling":
                t = a.value * params[f"w{i}"][0]
            elif proj == "slice":
                lead = 2 if a.is_seq else 1
                xs = a.value.reshape(a.value.shape[:lead] + (-1,))
                t = jnp.concatenate(
                    [xs[..., b_:e_] for b_, e_ in ic.attrs["slices"]],
                    axis=-1,
                )
            elif proj == "context":
                from paddle_tpu.ops.sequence_ops import seq_shift

                L = ic.attrs["context_length"]
                start = ic.attrs.get("context_start", -(L // 2))
                x = a.value  # [B, T, D]
                t = jnp.concatenate(
                    [
                        seq_shift(x, a.seq_lens, start + o)
                        for o in range(L)
                    ],
                    axis=-1,
                )
            y = t if y is None else y + t
        if "b" in params:
            b = params["b"]
            if (
                getattr(self, "_shared_bias", False)
                and y.shape[-1] != b.shape[0]
            ):
                # per-filter bias over an NHWC-flattened conv
                # output: channels are the fastest axis, so
                # tile over spatial
                b = jnp.tile(b, y.shape[-1] // b.shape[0])
            y = y + b
        y = self.apply_activation_and_dropout(y, ctx, seq_lens)
        return Arg(value=y, seq_lens=seq_lens, subseq_lens=subseq_lens)


@LAYERS.register("tensor")
class TensorLayer(Layer):
    """Bilinear tensor product y_k = x1 @ W_k @ x2
    (gserver/layers/TensorLayer.cpp)."""

    def build(self, in_specs):
        s1, s2 = in_specs
        out = self.conf.size
        pcs = {"w0": self.weight_conf(0, (out, s1.size, s2.size))}
        b = self.bias_conf((out,))
        if b is not None:
            pcs["b"] = b
        return Spec(dim=(out,), is_seq=s1.is_seq), pcs

    def forward(self, params, inputs, ctx):
        x1, x2 = inputs[0].value, inputs[1].value
        y = jnp.einsum("...i,kij,...j->...k", x1, params["w0"], x2)
        if "b" in params:
            y = y + params["b"]
        y = self.apply_activation_and_dropout(y, ctx, inputs[0].seq_lens)
        return inputs[0].with_value(y)


@LAYERS.register("outer_prod", "out_prod")
class OuterProdLayer(Layer):
    """Outer product of two vectors flattened (OuterProdLayer.cpp)."""

    def build(self, in_specs):
        s1, s2 = in_specs
        return Spec(dim=(s1.size * s2.size,), is_seq=s1.is_seq), {}

    def forward(self, params, inputs, ctx):
        x1, x2 = inputs[0].value, inputs[1].value
        y = jnp.einsum("...i,...j->...ij", x1, x2)
        y = y.reshape(y.shape[:-2] + (-1,))
        return inputs[0].with_value(y)


@LAYERS.register("sum_to_one_norm")
class SumToOneNormLayer(Layer):
    """Row-normalize to sum 1 (SumToOneNormLayer.cpp)."""

    def build(self, in_specs):
        return in_specs[0], {}

    def forward(self, params, inputs, ctx):
        x = inputs[0].value
        s = jnp.sum(x, axis=-1, keepdims=True)
        return inputs[0].with_value(x / jnp.where(s == 0, 1.0, s))


@LAYERS.register("trans")
class TransLayer(Layer):
    """Matrix transpose of the per-example [H,W] view (TransLayer.cpp).
    attrs: height, width."""

    def build(self, in_specs):
        (s,) = in_specs
        a = self.conf.attrs
        h, w = a.get("height"), a.get("width")
        if not (h and w):
            if len(s.dim) >= 2:
                # per-example [H, W(, C=1)] view from the input spec
                h, w = s.dim[0], s.dim[1] * (
                    s.dim[2] if len(s.dim) == 3 else 1
                )
            else:
                hw = int(round(s.size ** 0.5))
                assert hw * hw == s.size, (
                    f"trans {self.name}: flat width {s.size} is not "
                    "square; pass height/width"
                )
                h = w = hw
        self._hw = (h, w)
        return Spec(dim=(w * h,), is_seq=s.is_seq), {}

    def forward(self, params, inputs, ctx):
        h, w = self._hw
        x = inputs[0].value
        lead = x.shape[:-1]
        y = x.reshape(lead + (h, w)).swapaxes(-1, -2).reshape(lead + (h * w,))
        return inputs[0].with_value(y)


@LAYERS.register("resize")
class ResizeLayer(Layer):
    """Reshape feature dim (ResizeLayer.cpp)."""

    def build(self, in_specs):
        return Spec(dim=(self.conf.size,), is_seq=in_specs[0].is_seq), {}

    def forward(self, params, inputs, ctx):
        x = inputs[0].value
        lead = 2 if inputs[0].is_seq else 1
        return inputs[0].with_value(x.reshape(x.shape[:lead] + (self.conf.size,)))
