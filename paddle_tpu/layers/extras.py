"""Long-tail layers: selective FC, NTM conv-shift, bilinear interp,
convex combination, EOS check, power, clip, row (lookahead) conv,
feature-map expand.

Reference: gserver/layers/{SelectiveFullyConnectedLayer,ConvShiftLayer,
BilinearInterpLayer,ConvexCombinationLayer,EosIdCheckLayer,PowerLayer,
ClipLayer,RowConvLayer,FeatureMapExpandLayer}.cpp.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.registry import LAYERS
from paddle_tpu.layers.base import Layer, Spec


@LAYERS.register("selective_fc")
class SelectiveFCLayer(Layer):
    """FC that only scores a selected subset of output columns
    (SelectiveFullyConnectedLayer.h:20: with no selection it acts exactly
    like fc). inputs: [x] or [x, sel] where sel.value is a dense 0/1 mask
    [B, out] (the reference's sparse col-index rows, densified — TPU-first
    static shape). Non-selected outputs are zeroed after activation."""

    def build(self, in_specs):
        out = self.conf.size
        pcs = {"w0": self.weight_conf(0, (in_specs[0].size, out))}
        b = self.bias_conf((out,))
        if b is not None:
            pcs["b"] = b
        return Spec(dim=(out,), is_seq=in_specs[0].is_seq), pcs

    def forward(self, params, inputs, ctx):
        x = inputs[0]
        y = jnp.dot(x.value, params["w0"])
        if "b" in params:
            y = y + params["b"]
        sel = inputs[1].value if len(inputs) > 1 else None
        if sel is not None and self.conf.active_type in (
            "softmax",
            "sequence_softmax",
        ):
            # restrict the softmax denominator to the selected columns
            # (the reference computes softmax over selected cols only)
            y = jnp.where(sel > 0, y, -1e9)
        y = self.apply_activation_and_dropout(y, ctx, x.seq_lens)
        if sel is not None:
            y = y * sel
        return Arg(value=y, seq_lens=x.seq_lens)


@LAYERS.register("conv_shift")
class ConvShiftLayer(Layer):
    """Circular convolution (NTM addressing, ConvShiftLayer.cpp:22-41):
    inputs [a (B,M), b (B,N)] with N odd;
    c[i] = sum_{j=-(N-1)/2}^{(N-1)/2} a[(i+j) mod M] * b[j]."""

    def build(self, in_specs):
        sa, sb = in_specs
        assert sb.size % 2 == 1, "conv_shift filter width must be odd"
        self._n = sb.size
        return Spec(dim=(sa.size,), is_seq=sa.is_seq), {}

    def forward(self, params, inputs, ctx):
        a, b = inputs[0].value, inputs[1].value
        half = (self._n - 1) // 2
        c = 0.0
        for j in range(-half, half + 1):
            c = c + jnp.roll(a, -j, axis=-1) * b[..., j + half : j + half + 1]
        return Arg(value=c, seq_lens=inputs[0].seq_lens)


@LAYERS.register("bilinear_interp")
class BilinearInterpLayer(Layer):
    """Bilinear resize of an (H, W, C) feature map
    (BilinearInterpLayer.cpp). attrs: out_size_x (W), out_size_y (H)."""

    def build(self, in_specs):
        (s,) = in_specs
        assert len(s.dim) == 3, "bilinear_interp needs an (H,W,C) input"
        self._c = s.dim[2]
        self._oh = self.conf.attrs["out_size_y"]
        self._ow = self.conf.attrs["out_size_x"]
        return Spec(dim=(self._oh, self._ow, self._c)), {}

    def forward(self, params, inputs, ctx):
        # align-corners interpolation exactly as BilinearInterpLayer.cpp:
        # ratio = (inSize-1)/(outSize-1), corners preserved (jax.image's
        # "bilinear" is half-pixel-centers and would differ numerically)
        x = inputs[0].value  # [B, H, W, C]
        H, W = x.shape[1], x.shape[2]
        oh, ow = self._oh, self._ow
        ry = (H - 1) / (oh - 1) if oh > 1 else 0.0
        rx = (W - 1) / (ow - 1) if ow > 1 else 0.0
        ys = jnp.arange(oh) * ry
        xs = jnp.arange(ow) * rx
        y0 = jnp.floor(ys).astype(jnp.int32)
        x0 = jnp.floor(xs).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, H - 1)
        x1 = jnp.minimum(x0 + 1, W - 1)
        wy = (ys - y0)[None, :, None, None]
        wx = (xs - x0)[None, None, :, None]
        r0 = x[:, y0]  # [B, oh, W, C]
        r1 = x[:, y1]
        top = r0[:, :, x0] * (1 - wx) + r0[:, :, x1] * wx
        bot = r1[:, :, x0] * (1 - wx) + r1[:, :, x1] * wx
        return Arg(value=top * (1 - wy) + bot * wy)


@LAYERS.register("convex_comb", "linear_comb")
class ConvexCombLayer(Layer):
    """Weighted combination of M sub-vectors
    (ConvexCombinationLayer.cpp): inputs [w (B,M), x (B,M*D)];
    out[b] = sum_m w[b,m] * x[b,m,:]."""

    def build(self, in_specs):
        sw, sx = in_specs
        d = self.conf.size
        assert sx.size == sw.size * d, (
            f"convex_comb: {sx.size} != {sw.size} * {d}"
        )
        self._m = sw.size
        return Spec(dim=(d,), is_seq=sx.is_seq), {}

    def forward(self, params, inputs, ctx):
        w, x = inputs[0].value, inputs[1].value
        xm = x.reshape(x.shape[:-1] + (self._m, -1))
        return Arg(
            value=jnp.einsum("...m,...md->...d", w, xm),
            seq_lens=inputs[1].seq_lens,
        )


@LAYERS.register("eos_id")
class EosIdCheckLayer(Layer):
    """1.0 where the input id equals attrs["eos_id"]
    (EosIdCheckLayer.cpp) — the beam-search stop signal."""

    def build(self, in_specs):
        (s,) = in_specs
        return Spec(dim=(1,), is_seq=s.is_seq), {}

    def forward(self, params, inputs, ctx):
        ids = inputs[0].ids
        eos = self.conf.attrs["eos_id"]
        v = (ids == eos).astype(jnp.float32)[..., None]
        return Arg(value=v, seq_lens=inputs[0].seq_lens)


@LAYERS.register("power")
class PowerLayer(Layer):
    """y = x^w with a per-example scalar exponent (PowerLayer.cpp:25):
    inputs [w (B,1), x (B,D)]."""

    def build(self, in_specs):
        return Spec(dim=(in_specs[1].size,), is_seq=in_specs[1].is_seq), {}

    def forward(self, params, inputs, ctx):
        w, x = inputs[0].value, inputs[1].value
        return Arg(
            value=jnp.power(x, w), seq_lens=inputs[1].seq_lens
        )


@LAYERS.register("clip")
class ClipLayer(Layer):
    """Clamp to [attrs min, attrs max] (ClipLayer.cpp)."""

    def build(self, in_specs):
        (s,) = in_specs
        return s, {}

    def forward(self, params, inputs, ctx):
        a = self.conf.attrs
        x = inputs[0]
        return x.with_value(
            jnp.clip(x.value, a.get("min", -1.0), a.get("max", 1.0))
        )


@LAYERS.register("row_conv")
class RowConvLayer(Layer):
    """Lookahead (row) convolution over future timesteps
    (RowConvLayer.h:24-43, DeepSpeech2): y[t] = sum_{j=0}^{L-1}
    W[j] * x[t+j], weight [context_length, D], zero beyond sequence end."""

    def build(self, in_specs):
        (s,) = in_specs
        L = self.conf.attrs["context_length"]
        self._L = L
        pcs = {"w0": self.weight_conf(0, (L, s.size))}
        return Spec(dim=(s.size,), is_seq=True), pcs

    def forward(self, params, inputs, ctx):
        from paddle_tpu.ops.sequence_ops import seq_shift

        x = inputs[0].value  # [B, T, D]
        w = params["w0"]
        y = 0.0
        for j in range(self._L):
            # per-sequence shift: lookahead past a sequence's own end
            # contributes zero, even when the batch is padded longer
            y = y + seq_shift(x, inputs[0].seq_lens, j) * w[j]
        return Arg(value=y, seq_lens=inputs[0].seq_lens)


@LAYERS.register("featmap_expand")
class FeatureMapExpandLayer(Layer):
    """Tile a [B, D] vector across attrs["num_filters"] feature maps ->
    [B, num_filters * D] (FeatureMapExpandLayer.cpp — broadcasting
    attention weights over conv channels)."""

    def build(self, in_specs):
        (s,) = in_specs
        n = self.conf.attrs["num_filters"]
        self._n = n
        return Spec(dim=(n * s.size,), is_seq=s.is_seq), {}

    def forward(self, params, inputs, ctx):
        x = inputs[0].value
        y = jnp.repeat(x[..., None, :], self._n, axis=-2)
        return Arg(
            value=y.reshape(x.shape[:-1] + (-1,)),
            seq_lens=inputs[0].seq_lens,
        )
