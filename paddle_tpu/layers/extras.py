"""Long-tail layers: selective FC, NTM conv-shift, bilinear interp,
convex combination, EOS check, power, clip, row (lookahead) conv,
feature-map expand.

Reference: gserver/layers/{SelectiveFullyConnectedLayer,ConvShiftLayer,
BilinearInterpLayer,ConvexCombinationLayer,EosIdCheckLayer,PowerLayer,
ClipLayer,RowConvLayer,FeatureMapExpandLayer}.cpp.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.registry import LAYERS
from paddle_tpu.layers.base import Layer, Spec


@LAYERS.register("selective_fc")
class SelectiveFCLayer(Layer):
    """FC that only scores a selected subset of output columns
    (SelectiveFullyConnectedLayer.h:20: with no selection it acts exactly
    like fc). inputs: [x] or [x, sel] where sel.value is a dense 0/1 mask
    [B, out] (the reference's sparse col-index rows, densified — TPU-first
    static shape). Non-selected outputs are zeroed after activation."""

    def build(self, in_specs):
        out = self.conf.size
        pcs = {"w0": self.weight_conf(0, (in_specs[0].size, out))}
        b = self.bias_conf((out,))
        if b is not None:
            pcs["b"] = b
        return Spec(dim=(out,), is_seq=in_specs[0].is_seq), pcs

    def forward(self, params, inputs, ctx):
        x = inputs[0]
        y = jnp.dot(x.value, params["w0"])
        if "b" in params:
            y = y + params["b"]
        sel = inputs[1].value if len(inputs) > 1 else None
        if sel is not None and self.conf.active_type in (
            "softmax",
            "sequence_softmax",
        ):
            # restrict the softmax denominator to the selected columns
            # (the reference computes softmax over selected cols only)
            y = jnp.where(sel > 0, y, -1e9)
        y = self.apply_activation_and_dropout(y, ctx, x.seq_lens)
        if sel is not None:
            y = y * sel
        return Arg(value=y, seq_lens=x.seq_lens)


@LAYERS.register("conv_shift")
class ConvShiftLayer(Layer):
    """Circular convolution (NTM addressing, ConvShiftLayer.cpp:22-41):
    inputs [a (B,M), b (B,N)] with N odd;
    c[i] = sum_{j=-(N-1)/2}^{(N-1)/2} a[(i+j) mod M] * b[j]."""

    def build(self, in_specs):
        sa, sb = in_specs
        assert sb.size % 2 == 1, "conv_shift filter width must be odd"
        self._n = sb.size
        return Spec(dim=(sa.size,), is_seq=sa.is_seq), {}

    def forward(self, params, inputs, ctx):
        a, b = inputs[0].value, inputs[1].value
        half = (self._n - 1) // 2
        c = 0.0
        for j in range(-half, half + 1):
            c = c + jnp.roll(a, -j, axis=-1) * b[..., j + half : j + half + 1]
        return Arg(value=c, seq_lens=inputs[0].seq_lens)


@LAYERS.register("bilinear_interp")
class BilinearInterpLayer(Layer):
    """Bilinear resize of an (H, W, C) feature map
    (BilinearInterpLayer.cpp). attrs: out_size_x (W), out_size_y (H)."""

    def build(self, in_specs):
        (s,) = in_specs
        assert len(s.dim) == 3, "bilinear_interp needs an (H,W,C) input"
        self._c = s.dim[2]
        self._oh = self.conf.attrs["out_size_y"]
        self._ow = self.conf.attrs["out_size_x"]
        return Spec(dim=(self._oh, self._ow, self._c)), {}

    def forward(self, params, inputs, ctx):
        # align-corners interpolation exactly as BilinearInterpLayer.cpp:
        # ratio = (inSize-1)/(outSize-1), corners preserved (jax.image's
        # "bilinear" is half-pixel-centers and would differ numerically)
        x = inputs[0].value  # [B, H, W, C]
        H, W = x.shape[1], x.shape[2]
        oh, ow = self._oh, self._ow
        ry = (H - 1) / (oh - 1) if oh > 1 else 0.0
        rx = (W - 1) / (ow - 1) if ow > 1 else 0.0
        ys = jnp.arange(oh) * ry
        xs = jnp.arange(ow) * rx
        y0 = jnp.floor(ys).astype(jnp.int32)
        x0 = jnp.floor(xs).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, H - 1)
        x1 = jnp.minimum(x0 + 1, W - 1)
        wy = (ys - y0)[None, :, None, None]
        wx = (xs - x0)[None, None, :, None]
        r0 = x[:, y0]  # [B, oh, W, C]
        r1 = x[:, y1]
        top = r0[:, :, x0] * (1 - wx) + r0[:, :, x1] * wx
        bot = r1[:, :, x0] * (1 - wx) + r1[:, :, x1] * wx
        return Arg(value=top * (1 - wy) + bot * wy)


@LAYERS.register("convex_comb", "linear_comb")
class ConvexCombLayer(Layer):
    """Weighted combination of M sub-vectors
    (ConvexCombinationLayer.cpp): inputs [w (B,M), x (B,M*D)];
    out[b] = sum_m w[b,m] * x[b,m,:]."""

    def build(self, in_specs):
        sw, sx = in_specs
        d = self.conf.size
        assert sx.size == sw.size * d, (
            f"convex_comb: {sx.size} != {sw.size} * {d}"
        )
        self._m = sw.size
        return Spec(dim=(d,), is_seq=sx.is_seq), {}

    def forward(self, params, inputs, ctx):
        w, x = inputs[0].value, inputs[1].value
        xm = x.reshape(x.shape[:-1] + (self._m, -1))
        return Arg(
            value=jnp.einsum("...m,...md->...d", w, xm),
            seq_lens=inputs[1].seq_lens,
        )


@LAYERS.register("eos_id")
class EosIdCheckLayer(Layer):
    """1.0 where the input id equals attrs["eos_id"]
    (EosIdCheckLayer.cpp) — the beam-search stop signal."""

    def build(self, in_specs):
        (s,) = in_specs
        return Spec(dim=(1,), is_seq=s.is_seq), {}

    def forward(self, params, inputs, ctx):
        ids = inputs[0].ids
        eos = self.conf.attrs["eos_id"]
        v = (ids == eos).astype(jnp.float32)[..., None]
        return Arg(value=v, seq_lens=inputs[0].seq_lens)


@LAYERS.register("power")
class PowerLayer(Layer):
    """y = x^w with a per-example scalar exponent (PowerLayer.cpp:25):
    inputs [w (B,1), x (B,D)]."""

    def build(self, in_specs):
        return Spec(dim=(in_specs[1].size,), is_seq=in_specs[1].is_seq), {}

    def forward(self, params, inputs, ctx):
        w, x = inputs[0].value, inputs[1].value
        return Arg(
            value=jnp.power(x, w), seq_lens=inputs[1].seq_lens
        )


@LAYERS.register("clip")
class ClipLayer(Layer):
    """Clamp to [attrs min, attrs max] (ClipLayer.cpp)."""

    def build(self, in_specs):
        (s,) = in_specs
        return s, {}

    def forward(self, params, inputs, ctx):
        a = self.conf.attrs
        x = inputs[0]
        return x.with_value(
            jnp.clip(x.value, a.get("min", -1.0), a.get("max", 1.0))
        )


@LAYERS.register("row_conv")
class RowConvLayer(Layer):
    """Lookahead (row) convolution over future timesteps
    (RowConvLayer.h:24-43, DeepSpeech2): y[t] = sum_{j=0}^{L-1}
    W[j] * x[t+j], weight [context_length, D], zero beyond sequence end."""

    def build(self, in_specs):
        (s,) = in_specs
        L = self.conf.attrs["context_length"]
        self._L = L
        pcs = {"w0": self.weight_conf(0, (L, s.size))}
        return Spec(dim=(s.size,), is_seq=True), pcs

    def forward(self, params, inputs, ctx):
        from paddle_tpu.ops.sequence_ops import seq_shift

        x = inputs[0].value  # [B, T, D]
        w = params["w0"]
        y = 0.0
        for j in range(self._L):
            # per-sequence shift: lookahead past a sequence's own end
            # contributes zero, even when the batch is padded longer
            y = y + seq_shift(x, inputs[0].seq_lens, j) * w[j]
        return Arg(value=y, seq_lens=inputs[0].seq_lens)


@LAYERS.register("featmap_expand")
class FeatureMapExpandLayer(Layer):
    """Tile a [B, D] vector across attrs["num_filters"] feature maps ->
    [B, num_filters * D] (FeatureMapExpandLayer.cpp — broadcasting
    attention weights over conv channels)."""

    def build(self, in_specs):
        (s,) = in_specs
        n = self.conf.attrs["num_filters"]
        self._n = n
        return Spec(dim=(n * s.size,), is_seq=s.is_seq), {}

    def forward(self, params, inputs, ctx):
        x = inputs[0].value
        y = jnp.repeat(x[..., None, :], self._n, axis=-2)
        return Arg(
            value=y.reshape(x.shape[:-1] + (-1,)),
            seq_lens=inputs[0].seq_lens,
        )


@LAYERS.register("prelu")
class PReluLayer(Layer):
    """PReLU with learnable negative-side slopes (layers.py
    prelu_layer). attrs partial_sum groups slopes: 0 = one slope per
    element, size = one shared slope, else each slope covers
    partial_sum consecutive elements (v1 semantics; channel-shared conv
    PReLU = partial_sum of the spatial size)."""

    def build(self, in_specs):
        (s,) = in_specs
        n = self.conf.attrs.get("partial_sum", 0) or 1
        assert s.size % n == 0, (
            f"prelu partial_sum {n} must divide input size {s.size}"
        )
        self._group = n
        pcs = {"w0": self.weight_conf(0, (s.size // n,))}
        # reference default slope 0.25 — unless the user configured init
        if (
            pcs["w0"].initial_std is None
            and pcs["w0"].initial_strategy == "normal"
            and pcs["w0"].initial_mean == 0.0
        ):
            pcs["w0"].initial_strategy = "constant"
            pcs["w0"].initial_value = 0.25
        self._spec = s
        return s, pcs

    def forward(self, params, inputs, ctx):
        (x,) = inputs
        v = x.value
        a = jnp.repeat(params["w0"], self._group).reshape(self._spec.dim)
        y = jnp.where(v >= 0, v, v * a)
        return x.with_value(y)


@LAYERS.register("gated_unit")
class GatedUnitLayer(Layer):
    """GLU: act(x W1) * sigmoid(x W2) (layers.py gated_unit_layer)."""

    def build(self, in_specs):
        (s,) = in_specs
        out = self.conf.size
        pcs = {
            "w0": self.weight_conf(0, (s.size, out)),
            "wg": self.weight_conf(0, (s.size, out)),
        }
        pcs["wg"].name = pcs["w0"].name + "_gate"
        b = self.bias_conf((out,))
        if b is not None:
            pcs["b"] = b
        return Spec(dim=(out,), is_seq=s.is_seq), pcs

    def forward(self, params, inputs, ctx):
        (x,) = inputs
        h = jnp.dot(x.value, params["w0"])
        if "b" in params:
            h = h + params["b"]
        h = self.apply_activation_and_dropout(h, ctx, x.seq_lens)
        gate = jax.nn.sigmoid(jnp.dot(x.value, params["wg"]))
        return Arg(value=h * gate, seq_lens=x.seq_lens)


@LAYERS.register("repeat")
class RepeatLayer(Layer):
    """Tile the feature vector attrs["num_repeats"] times
    (layers.py repeat_layer / FeatureMapExpand sibling)."""

    def build(self, in_specs):
        (s,) = in_specs
        n = self.conf.attrs["num_repeats"]
        self._n = n
        return Spec(dim=(s.size * n,), is_seq=s.is_seq), {}

    def forward(self, params, inputs, ctx):
        (x,) = inputs
        return Arg(
            value=jnp.tile(x.value, (1,) * (x.value.ndim - 1) + (self._n,)),
            seq_lens=x.seq_lens,
        )


@LAYERS.register("kmax_seq_score")
class KmaxSeqScoreLayer(Layer):
    """Indices of the top-k scores within each sequence
    (KmaxSeqScoreLayer.cpp; layers.py kmax_sequence_score_layer).
    Input: [B, T, 1] scores (seq); output ids [B, k] (positions),
    padded positions excluded."""

    def build(self, in_specs):
        (s,) = in_specs
        self._k = self.conf.attrs.get("beam_size", 1)
        return Spec(dim=(self._k,), is_ids=True), {}

    def forward(self, params, inputs, ctx):
        (x,) = inputs
        v = x.value[..., 0] if x.value.ndim == 3 else x.value  # [B, T]
        neg = jnp.finfo(v.dtype).min
        masked = jnp.where(
            jnp.arange(v.shape[1])[None, :] < x.seq_lens[:, None], v, neg
        )
        top_s, idx = jax.lax.top_k(masked, self._k)
        # sequences shorter than k: pad with the reference's -1 sentinel
        # rather than garbage padded-position ids
        idx = jnp.where(top_s > neg, idx, -1)
        return Arg(ids=idx.astype(jnp.int32))


@LAYERS.register("cos_vm")
class CosSimVecMatLayer(Layer):
    """Cosine similarity between a vector and each row of a matrix
    (CosSimVecMatLayer.cpp, NTM content addressing):
    inputs [v (B,D), m (B, W*D)]; out[b,i] = scale * cos(v[b], m[b,i,:]).
    size = W."""

    def build(self, in_specs):
        sv, sm = in_specs
        w = self.conf.size
        assert sm.size == w * sv.size, (
            f"cos_vm: {sm.size} != {w} * {sv.size}"
        )
        self._w = w
        return Spec(dim=(w,)), {}

    def forward(self, params, inputs, ctx):
        v, m = inputs[0].value, inputs[1].value
        mm = m.reshape(m.shape[0], self._w, -1)  # [B, W, D]
        scale = self.conf.attrs.get("scale", 1.0)
        dot = jnp.einsum("bd,bwd->bw", v, mm)
        # safe norms: linalg.norm has a NaN vjp at exactly 0, and NTM
        # memory rows START at zero — sqrt(sum + eps) keeps grads finite
        nv = jnp.sqrt(jnp.sum(jnp.square(v), -1, keepdims=True) + 1e-12)
        nm = jnp.sqrt(jnp.sum(jnp.square(mm), -1) + 1e-12)
        return Arg(value=scale * dot / (nv * nm))


@LAYERS.register("data_norm")
class DataNormLayer(Layer):
    """Normalize inputs with PRECOMPUTED statistics held as a static
    parameter (DataNormLayer.cpp): attrs data_norm_strategy in
    {"z-score", "min-max", "decimal-scaling"}; the stats parameter is
    [3, D] rows (mean|min|decimal-scale, std|max-min|_) supplied by the
    user (is_static, like the reference loads them from file)."""

    def build(self, in_specs):
        (s,) = in_specs
        pc = self.weight_conf(0, (3, s.size))
        pc.is_static = True
        pc.initial_strategy = "zero"
        return s, {"w0": pc}

    def forward(self, params, inputs, ctx):
        (x,) = inputs
        stats = params["w0"]
        strat = self.conf.attrs.get("data_norm_strategy", "z-score")
        v = x.value

        def denom(row):
            # unloaded stats (all zeros) must mean IDENTITY, not a 1e8
            # blow-up from a zero divisor
            return jnp.where(row == 0, 1.0, row)

        if strat in ("z-score", "min-max"):
            # shared affine form; rows differ: (mean, std) vs (min,
            # max-min)
            y = (v - stats[0]) / denom(stats[1])
        elif strat == "decimal-scaling":
            y = v / denom(stats[0])
        else:
            raise KeyError(f"unknown data_norm_strategy {strat!r}")
        return x.with_value(y)


@LAYERS.register("print")
class PrintLayer(Layer):
    """Identity that prints its input during execution
    (PrintLayer.cpp) — jax.debug.print, so it works inside jit."""

    def build(self, in_specs):
        return in_specs[0], {}

    def forward(self, params, inputs, ctx):
        (x,) = inputs
        v = x.value if x.value is not None else x.ids
        # name passed as an ARG: a '{' in a layer name must not be
        # treated as a format field
        jax.debug.print("{}: {}", self.name, v)
        return x


@LAYERS.register("get_output")
class GetOutputLayer(Layer):
    """Forward a named extra output of the single input layer
    (gserver/layers/GetOutputLayer.cpp:39; config_parser.py:3135).

    The edge's ``input_layer_argument`` selects which argument: the
    builder resolves extra outputs under ``<producer>@<arg>`` spec
    names (the same canonical form dsl.get_output emits), so this layer
    normalizes its input edge to that key and is otherwise the
    identity. Mirrors the reference's init checks: exactly one input
    with a non-empty argument name."""

    def __init__(self, conf, model):
        super().__init__(conf, model)
        if len(conf.inputs) != 1:
            raise ValueError(
                f"get_output layer {conf.name!r} needs exactly 1 input, "
                f"got {len(conf.inputs)}"
            )
        edge = conf.inputs[0]
        if "@" not in edge.name:
            arg = edge.attrs.get("input_layer_argument")
            if not arg:
                raise ValueError(
                    f"get_output layer {conf.name!r} input edge must set "
                    f"attrs['input_layer_argument'] (the named output of "
                    f"{edge.name!r} to forward)"
                )
            edge.name = f"{edge.name}@{arg}"

    def build(self, in_specs):
        (s,) = in_specs
        return s, {}

    def forward(self, params, inputs, ctx):
        (x,) = inputs
        return x


@LAYERS.register("eltmul")
class ElementwiseMulLayer(Layer):
    """Elementwise product a ⊙ b (× scale) — the reference's
    DotMulOperator mixed-layer term (config_parser.py DotMulOperator,
    gserver/layers/DotMulOperator.cpp) hoisted to a standalone layer:
    an operator term is just another summand of the mixed layer, so an
    identity-projected input is exactly equivalent."""

    def build(self, in_specs):
        a, b = in_specs
        assert a.size == b.size, (
            f"eltmul {self.name}: operand sizes differ ({a.size} vs {b.size})"
        )
        return Spec(dim=a.dim, is_seq=a.is_seq or b.is_seq), {}

    def forward(self, params, inputs, ctx):
        a, b = inputs
        scale = self.conf.attrs.get("scale", 1.0)
        return a.with_value(scale * a.value * b.value)
