"""Sequence-structure layers.

Reference: gserver/layers/{SequencePoolLayer,SequenceLastInstanceLayer,
ExpandLayer,SequenceConcatLayer,SequenceReshapeLayer,SeqSliceLayer,
SequenceReverseLayer,SubSequenceLayer,FirstSeqLayer,...}.cpp. All are mask
semantics over dense [B,T,...] (see ops/sequence_ops.py).
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.registry import LAYERS
from paddle_tpu.layers.base import Layer, Spec
from paddle_tpu.ops import sequence_ops as sops


@LAYERS.register("seqpool", "sequence_pool", "average", "max")
class SequencePoolLayer(Layer):
    """Pool a sequence to one vector per example, or each sub-sequence to
    one timestep. attrs: pool_type in {sum, average, max, sqrt_average},
    level ("seq"->[B,D], "subseq"->[B,S,D])."""

    _OPS = {
        "sum": sops.seq_sum,
        "average": sops.seq_avg,
        "avg": sops.seq_avg,
        "sqrt_average": sops.seq_sqrt_avg,
        "max": sops.seq_max,
    }

    def build(self, in_specs):
        (s,) = in_specs
        level = self.conf.attrs.get("level", "seq")
        if level == "subseq":
            assert s.has_subseq
            return Spec(dim=s.dim, is_seq=True), {}
        return Spec(dim=s.dim), {}

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        # the reference's AverageLayer/MaxLayer are separate types with
        # the pool kind baked into the type name
        default = (
            self.conf.type
            if self.conf.type in ("average", "max")
            else "sum"
        )
        kind = self.conf.attrs.get("pool_type", default)
        level = self.conf.attrs.get("level", "seq")
        if level == "subseq":
            op_map = {
                "sum": "sum", "average": "avg", "avg": "avg", "max": "max",
                "sqrt_average": "sqrt_avg", "last": "last", "first": "first",
            }
            if kind not in op_map:
                raise KeyError(
                    f"seqpool {self.name}: pool_type {kind!r} not supported at "
                    f"subseq level (supported: {sorted(op_map)})"
                )
            y = sops.subseq_pool(arg.value, arg.subseq_lens, op_map[kind])
            lens = jnp.sum((arg.subseq_lens > 0).astype(jnp.int32), axis=1)
            return Arg(value=y, seq_lens=lens)
        y = self._OPS[kind](arg.value, arg.seq_lens)
        return Arg(value=y)


@LAYERS.register("seqlastins", "last_seq")
class SequenceLastInstanceLayer(Layer):
    """Last (or first) real timestep (SequenceLastInstanceLayer.cpp).
    attrs: select_first."""

    def build(self, in_specs):
        (s,) = in_specs
        return Spec(dim=s.dim), {}

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        if self.conf.attrs.get("select_first", False):
            y = sops.seq_first(arg.value, arg.seq_lens)
        else:
            y = sops.seq_last(arg.value, arg.seq_lens)
        return Arg(value=y)


@LAYERS.register("expand")
class ExpandLayer(Layer):
    """Broadcast a [B,D] vector along the time axis of a reference sequence
    (ExpandLayer.cpp). inputs: [x, seq_ref]."""

    def build(self, in_specs):
        x, ref = in_specs
        return Spec(dim=x.dim, is_seq=True), {}

    def forward(self, params, inputs, ctx):
        x, ref = inputs
        t = ref.max_len
        y = sops.expand_to_seq(x.value, ref.seq_lens, t)
        return Arg(value=y, seq_lens=ref.seq_lens)


@LAYERS.register("seqconcat")
class SequenceConcatLayer(Layer):
    """Concat two sequences along time, per example (SequenceConcatLayer.cpp)."""

    def build(self, in_specs):
        a, b = in_specs
        return Spec(dim=a.dim, is_seq=True), {}

    def forward(self, params, inputs, ctx):
        a, b = inputs
        y, lens = sops.seq_concat(a.value, a.seq_lens, b.value, b.seq_lens)
        return Arg(value=y, seq_lens=lens)


@LAYERS.register("seqreshape")
class SequenceReshapeLayer(Layer):
    """Reshape [B,T,D] -> [B,T*D/newD,newD] keeping token count
    (SequenceReshapeLayer.cpp). Requires lengths divisible in the same
    proportion; padding stays padding."""

    def build(self, in_specs):
        (s,) = in_specs
        return Spec(dim=(self.conf.size,), is_seq=True), {}

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        b, t, d = arg.value.shape
        nd = self.conf.size
        nt = t * d // nd
        y = arg.value.reshape(b, nt, nd)
        lens = arg.seq_lens * d // nd
        return Arg(value=y, seq_lens=lens)


@LAYERS.register("seqreverse", "sequence_reverse")
class SequenceReverseLayer(Layer):
    def build(self, in_specs):
        return in_specs[0], {}

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        return arg.with_value(sops.reverse_seq(arg.value, arg.seq_lens))


@LAYERS.register("slice", "seq_slice")
class SeqSliceLayer(Layer):
    """Static time-window slice (SeqSliceLayer.cpp static case).
    attrs: begin, size."""

    def build(self, in_specs):
        (s,) = in_specs
        return Spec(dim=s.dim, is_seq=True), {}

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        a = self.conf.attrs
        y, lens = sops.seq_slice_window(arg.value, arg.seq_lens, a["begin"], a["size"])
        return Arg(value=y, seq_lens=lens)


@LAYERS.register("padding", "pad")
class PadLayer(Layer):
    """Zero-pad spatial dims of an image input (gserver/layers/PadLayer.cpp,
    function/PadOp.cpp). attrs: pad_c/pad_h/pad_w as (before, after)."""

    def build(self, in_specs):
        (s,) = in_specs
        h, w, c = s.dim
        a = self.conf.attrs
        pc = tuple(a.get("pad_c", (0, 0)))
        ph = tuple(a.get("pad_h", (0, 0)))
        pw = tuple(a.get("pad_w", (0, 0)))
        self._shape = (h, w, c)
        self._pads = (ph, pw, pc)
        return Spec(dim=(h + sum(ph), w + sum(pw), c + sum(pc)), is_seq=s.is_seq), {}

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        x = arg.value.reshape((arg.value.shape[0],) + self._shape)
        ph, pw, pc = self._pads
        y = jnp.pad(x, ((0, 0), ph, pw, pc))
        return arg.with_value(y)


@LAYERS.register("crop")
class CropLayer(Layer):
    """Crop spatial dims (gserver/layers/CropLayer.cpp, function/CropOp.cpp).
    attrs: crop_h/crop_w (begin, size) or target taken from 2nd input."""

    def build(self, in_specs):
        s = in_specs[0]
        h, w, c = s.dim
        a = self.conf.attrs
        if len(in_specs) > 1:
            th, tw, _ = in_specs[1].dim
            bh = a.get("offset_h", (h - th) // 2)
            bw = a.get("offset_w", (w - tw) // 2)
            self._crop = (bh, th, bw, tw)
        else:
            bh, th = a["crop_h"]
            bw, tw = a["crop_w"]
            self._crop = (bh, th, bw, tw)
        self._shape = (h, w, c)
        return Spec(dim=(self._crop[1], self._crop[3], c), is_seq=s.is_seq), {}

    def forward(self, params, inputs, ctx):
        arg = inputs[0]
        bh, th, bw, tw = self._crop
        x = arg.value.reshape((arg.value.shape[0],) + self._shape)
        return arg.with_value(x[:, bh : bh + th, bw : bw + tw, :])


@LAYERS.register("rotate")
class RotateLayer(Layer):
    """Rotate the [H,W] view 90° CCW (gserver/layers/RotateLayer.cpp).
    attrs: height, width."""

    def build(self, in_specs):
        return in_specs[0], {}

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        a = self.conf.attrs
        h, w = a["height"], a["width"]
        x = arg.value
        lead = x.shape[:-1]
        y = x.reshape(lead + (h, w))
        y = jnp.flip(y.swapaxes(-1, -2), axis=-2)
        return arg.with_value(y.reshape(lead + (h * w,)))


@LAYERS.register("subseq", "sub_seq")
class SubSequenceLayer(Layer):
    """Take a per-example sub-span of each sequence given dynamic offset
    and size inputs (SubSequenceLayer.cpp: inputs [seq, offset, size]).
    offset/size are [B] id args (one integer per sequence). TPU-first:
    a clamped gather over the time axis plus a new seq_lens — static
    shapes, so the output keeps the input's max length with padding
    beyond each new length."""

    def build(self, in_specs):
        s = in_specs[0]
        assert s.is_seq, "subseq needs a sequence input"
        return Spec(dim=s.dim, is_seq=True, dtype=s.dtype), {}

    def forward(self, params, inputs, ctx):
        x, off, size = inputs
        v = x.value
        T = v.shape[1]
        o = off.ids.reshape(-1)  # [B]
        n = size.ids.reshape(-1)  # [B]
        # clamp the span inside the real sequence; an offset at or past
        # the end yields an EMPTY sequence, not a fabricated tail slice
        in_range = o < x.seq_lens
        o = jnp.clip(o, 0, jnp.maximum(x.seq_lens - 1, 0))
        n = jnp.where(in_range, jnp.clip(n, 0, x.seq_lens - o), 0)
        idx = o[:, None] + jnp.arange(T)[None, :]  # [B, T]
        idx = jnp.clip(idx, 0, T - 1)
        y = jnp.take_along_axis(
            v, idx.reshape(idx.shape + (1,) * (v.ndim - 2)), axis=1
        )
        mask = (jnp.arange(T)[None, :] < n[:, None]).astype(v.dtype)
        y = y * mask.reshape(mask.shape + (1,) * (v.ndim - 2))
        return Arg(value=y, seq_lens=n.astype(jnp.int32))


@LAYERS.register("sub_nested_seq")
class SubNestedSequenceLayer(Layer):
    """Select sub-sequences of a nested sequence by per-example indices
    (SubNestedSequenceLayer.cpp; layers.py:6098 sub_nested_seq_layer —
    beam training). inputs: [nested (flat [B,T,D] + subseq_lens [B,S]),
    selected (ids [B,K])]. Output: nested sequence of the K selected
    sub-sequences, in selection order, compacted to the front."""

    def build(self, in_specs):
        s, sel = in_specs
        assert s.has_subseq, "sub_nested_seq needs a nested input"
        return Spec(dim=s.dim, is_seq=True, has_subseq=True), {}

    def forward(self, params, inputs, ctx):
        x, sel = inputs
        v = x.value  # [B, T, D]
        T = v.shape[1]
        sl = x.subseq_lens  # [B, S]
        ends = jnp.cumsum(sl, axis=1)
        starts = ends - sl
        k_idx = sel.ids  # [B, K]
        K = k_idx.shape[1]
        # invalid selections select NOTHING: -1 sentinels (e.g. from
        # kmax_seq_score on short sequences) and slots beyond the
        # selection's own seq_lens must not wrap to the last sub-seq
        valid_sel = k_idx >= 0
        if sel.seq_lens is not None:
            valid_sel = valid_sel & (
                jnp.arange(K)[None, :] < sel.seq_lens[:, None]
            )
        safe_idx = jnp.clip(k_idx, 0, sl.shape[1] - 1)
        sel_lens = jnp.take_along_axis(sl, safe_idx, axis=1) * valid_sel
        in_starts = jnp.take_along_axis(starts, safe_idx, axis=1)
        out_ends = jnp.cumsum(sel_lens, axis=1)  # [B, K]
        out_starts = out_ends - sel_lens
        pos = jnp.arange(T, dtype=jnp.int32)[None, :]  # [1, T]
        # which selected segment does output position p fall into
        seg = jnp.sum(
            (pos[:, :, None] >= out_ends[:, None, :]), axis=-1
        )  # [B, T]
        seg_c = jnp.minimum(seg, k_idx.shape[1] - 1)
        offset = pos - jnp.take_along_axis(out_starts, seg_c, axis=1)
        src = jnp.take_along_axis(in_starts, seg_c, axis=1) + offset
        valid = pos < out_ends[:, -1:]
        src = jnp.clip(src, 0, T - 1)
        y = jnp.take_along_axis(
            v, src.reshape(src.shape + (1,) * (v.ndim - 2)), axis=1
        )
        y = y * valid.reshape(valid.shape + (1,) * (v.ndim - 2)).astype(
            y.dtype
        )
        return Arg(
            value=y,
            seq_lens=jnp.sum(sel_lens, axis=1).astype(jnp.int32),
            subseq_lens=sel_lens.astype(jnp.int32),
        )
