"""Sequence-structure layers.

Reference: gserver/layers/{SequencePoolLayer,SequenceLastInstanceLayer,
ExpandLayer,SequenceConcatLayer,SequenceReshapeLayer,SeqSliceLayer,
SequenceReverseLayer,SubSequenceLayer,FirstSeqLayer,...}.cpp. All are mask
semantics over dense [B,T,...] (see ops/sequence_ops.py).
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.registry import LAYERS
from paddle_tpu.layers.base import Layer, Spec
from paddle_tpu.ops import sequence_ops as sops


@LAYERS.register("seqpool", "sequence_pool", "average", "max")
class SequencePoolLayer(Layer):
    """Pool a sequence to one vector per example, or each sub-sequence to
    one timestep. attrs: pool_type in {sum, average, max, sqrt_average},
    level ("seq"->[B,D], "subseq"->[B,S,D])."""

    _OPS = {
        "sum": sops.seq_sum,
        "average": sops.seq_avg,
        "avg": sops.seq_avg,
        "sqrt_average": sops.seq_sqrt_avg,
        "max": sops.seq_max,
    }

    def build(self, in_specs):
        (s,) = in_specs
        level = self.conf.attrs.get("level", "seq")
        assert not (
            self.conf.attrs.get("stride", 0)
            and self.conf.attrs.get("output_max_index")
        ), f"seqpool {self.name}: stride with output_max_index is ambiguous"
        if self.conf.attrs.get("stride", 0):
            return Spec(dim=s.dim, is_seq=True), {}
        if level == "subseq":
            # non-nested input: each whole sequence acts as its ONE
            # subsequence (upstream configs apply TO_SEQUENCE to plain
            # sequences; parse accepts it there)
            return Spec(dim=s.dim, is_seq=True), {}
        return Spec(dim=s.dim), {}

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        # the reference's AverageLayer/MaxLayer are separate types with
        # the pool kind baked into the type name
        default = (
            self.conf.type
            if self.conf.type in ("average", "max")
            else "sum"
        )
        kind = self.conf.attrs.get("pool_type", default)
        level = self.conf.attrs.get("level", "seq")
        stride = self.conf.attrs.get("stride", 0) or 0
        if self.conf.attrs.get("output_max_index"):
            # max-pool-with-index (MaxLayer.cpp output_max_index): the
            # argmax TIMESTEP per feature, as values
            x = arg.value
            t = x.shape[1]
            mask = jnp.arange(t)[None, :, None] < arg.seq_lens[:, None, None]
            idx = jnp.argmax(
                jnp.where(mask, x, -jnp.inf), axis=1
            ).astype(x.dtype)
            return Arg(value=idx)
        if stride > 0:
            # one pooled frame per stride-window (strided sequence
            # pooling, SequencePoolLayer.cpp stride_): output a
            # sequence of ceil(len/stride) frames
            x = arg.value
            b, t = x.shape[0], x.shape[1]
            n_w = -(-t // stride)
            pad_t = n_w * stride - t
            xw = jnp.pad(x, ((0, 0), (0, pad_t)) + ((0, 0),) * (x.ndim - 2))
            xw = xw.reshape(b, n_w, stride, *x.shape[2:])
            pos = (jnp.arange(n_w * stride).reshape(n_w, stride))[None]
            m = (pos < arg.seq_lens[:, None, None]).astype(x.dtype)
            m = m.reshape(b, n_w, stride, *([1] * (x.ndim - 2)))
            if kind in ("sum", "average", "avg", "sqrt_average"):
                s = jnp.sum(xw * m, axis=2)
                if kind in ("average", "avg"):
                    s = s / jnp.maximum(m.sum(axis=2), 1.0)
                elif kind == "sqrt_average":
                    s = s / jnp.sqrt(jnp.maximum(m.sum(axis=2), 1.0))
                y = s
            else:  # max
                neg = jnp.where(m > 0, xw, -jnp.inf)
                y = jnp.max(neg, axis=2)
                y = jnp.where(jnp.isfinite(y), y, 0.0)
            out_lens = -(-arg.seq_lens // stride)
            return Arg(value=y, seq_lens=out_lens.astype(jnp.int32))
        if level == "subseq":
            op_map = {
                "sum": "sum", "average": "avg", "avg": "avg", "max": "max",
                "sqrt_average": "sqrt_avg", "last": "last", "first": "first",
            }
            if kind not in op_map:
                raise KeyError(
                    f"seqpool {self.name}: pool_type {kind!r} not supported at "
                    f"subseq level (supported: {sorted(op_map)})"
                )
            if arg.subseq_lens is None:
                # plain sequence under TO_SEQUENCE: the whole sequence
                # is its one subsequence -> [B, 1, D]
                y = self._OPS[kind](arg.value, arg.seq_lens)[:, None]
                ones = jnp.ones((y.shape[0],), jnp.int32)
                return Arg(value=y, seq_lens=ones)
            y = sops.subseq_pool(arg.value, arg.subseq_lens, op_map[kind])
            lens = jnp.sum((arg.subseq_lens > 0).astype(jnp.int32), axis=1)
            return Arg(value=y, seq_lens=lens)
        y = self._OPS[kind](arg.value, arg.seq_lens)
        return Arg(value=y)


@LAYERS.register("seqlastins", "last_seq")
class SequenceLastInstanceLayer(Layer):
    """Last (or first) real timestep (SequenceLastInstanceLayer.cpp).
    attrs: select_first; stride (>0: one frame per stride-window, the
    reference's strided selection — output stays a sequence); level
    ("seq" whole-sequence default; "subseq": one frame per
    SUB-sequence of a nested input, output a plain sequence —
    AggregateLevel.TO_SEQUENCE)."""

    def build(self, in_specs):
        (s,) = in_specs
        stride = self.conf.attrs.get("stride", 0) or 0
        level = self.conf.attrs.get("level", "seq")
        is_seq = stride > 0 or (level == "subseq" and s.has_subseq)
        return Spec(dim=s.dim, is_seq=is_seq), {}

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        first = self.conf.attrs.get("select_first", False)
        stride = self.conf.attrs.get("stride", 0) or 0
        level = self.conf.attrs.get("level", "seq")
        pick = sops.seq_first if first else sops.seq_last
        if level == "subseq" and arg.subseq_lens is not None:
            # one frame per subsequence: [B,T,...] + subseq_lens [B,S]
            # -> [B,S,...] plain sequence over subsequences
            sub = arg.subseq_lens
            csum = jnp.cumsum(sub, axis=1)
            starts = csum - sub  # [B, S]
            idx = jnp.where(
                sub > 0,
                starts if first else csum - 1,
                0,
            )
            y = jnp.take_along_axis(
                arg.value,
                idx[..., None].astype(jnp.int32).clip(0),
                axis=1,
            )
            n_sub = (sub > 0).sum(axis=1).astype(jnp.int32)
            return Arg(value=y, seq_lens=n_sub)
        if stride > 0:
            # one frame per stride-window: window w of example b is
            # valid when w*stride < len; its frame is the last (first)
            # valid timestep inside [w*stride, min(len, (w+1)*stride))
            t = arg.value.shape[1]
            n_w = -(-t // stride)  # ceil
            lens = arg.seq_lens
            w = jnp.arange(n_w)[None, :]  # [1, W]
            start = w * stride
            end = jnp.minimum(start + stride, lens[:, None])
            idx = (start if first else end - 1).clip(0, t - 1)
            y = jnp.take_along_axis(
                arg.value, idx[..., None].astype(jnp.int32), axis=1
            )
            out_lens = -(-lens // stride)
            return Arg(value=y, seq_lens=out_lens.astype(jnp.int32))
        return Arg(value=pick(arg.value, arg.seq_lens))


@LAYERS.register("expand")
class ExpandLayer(Layer):
    """Broadcast along the time axis of a reference sequence
    (ExpandLayer.cpp). inputs: [x, seq_ref]. Default (FROM_NO_SEQUENCE)
    x is [B,D] repeated per timestep; expand_level="seq"
    (FROM_SEQUENCE) x is a sequence with one frame per SUB-sequence of
    the NESTED ref, each frame repeated over its subsequence."""

    def build(self, in_specs):
        x, ref = in_specs
        if (self.conf.attrs.get("expand_level") == "seq"
                and ref.has_subseq):
            return Spec(dim=x.dim, is_seq=True, has_subseq=True), {}
        # FROM_SEQUENCE over a PLAIN (non-nested) ref coincides with
        # the default whole-sequence broadcast (one x entry per
        # sequence either way)
        return Spec(dim=x.dim, is_seq=True), {}

    def forward(self, params, inputs, ctx):
        x, ref = inputs
        t = ref.max_len
        if (self.conf.attrs.get("expand_level") == "seq"
                and ref.subseq_lens is not None):
            # x [B,S,D], ref subseq_lens [B,S]: timestep t belongs to
            # subsequence j(t) = #(subseq starts <= t) - 1
            sub = ref.subseq_lens
            csum = jnp.cumsum(sub, axis=1)  # [B, S]
            pos = jnp.arange(t)[None, :, None]  # [1, T, 1]
            j = jnp.sum(pos >= csum[:, None, :], axis=-1)  # [B, T]
            j = j.clip(0, x.value.shape[1] - 1)
            y = jnp.take_along_axis(x.value, j[..., None], axis=1)
            return Arg(value=y, seq_lens=ref.seq_lens,
                       subseq_lens=ref.subseq_lens)
        y = sops.expand_to_seq(x.value, ref.seq_lens, t)
        return Arg(value=y, seq_lens=ref.seq_lens)


@LAYERS.register("seqconcat")
class SequenceConcatLayer(Layer):
    """Concat two sequences along time, per example (SequenceConcatLayer.cpp)."""

    def build(self, in_specs):
        a, b = in_specs
        return Spec(dim=a.dim, is_seq=True), {}

    def forward(self, params, inputs, ctx):
        a, b = inputs
        y, lens = sops.seq_concat(a.value, a.seq_lens, b.value, b.seq_lens)
        return Arg(value=y, seq_lens=lens)


@LAYERS.register("seqreshape")
class SequenceReshapeLayer(Layer):
    """Reshape [B,T,D] -> [B,T*D/newD,newD] keeping token count
    (SequenceReshapeLayer.cpp). Requires lengths divisible in the same
    proportion; padding stays padding."""

    def build(self, in_specs):
        (s,) = in_specs
        return Spec(dim=(self.conf.size,), is_seq=True), {}

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        b, t, d = arg.value.shape
        nd = self.conf.size
        nt = t * d // nd
        y = arg.value.reshape(b, nt, nd)
        lens = arg.seq_lens * d // nd
        return Arg(value=y, seq_lens=lens)


@LAYERS.register("seqreverse", "sequence_reverse")
class SequenceReverseLayer(Layer):
    def build(self, in_specs):
        return in_specs[0], {}

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        return arg.with_value(sops.reverse_seq(arg.value, arg.seq_lens))


@LAYERS.register("slice", "seq_slice")
class SeqSliceLayer(Layer):
    """Static time-window slice (SeqSliceLayer.cpp static case).
    attrs: begin, size."""

    def build(self, in_specs):
        (s,) = in_specs
        return Spec(dim=s.dim, is_seq=True), {}

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        a = self.conf.attrs
        y, lens = sops.seq_slice_window(arg.value, arg.seq_lens, a["begin"], a["size"])
        return Arg(value=y, seq_lens=lens)


@LAYERS.register("padding", "pad")
class PadLayer(Layer):
    """Zero-pad spatial dims of an image input (gserver/layers/PadLayer.cpp,
    function/PadOp.cpp). attrs: pad_c/pad_h/pad_w as (before, after)."""

    def build(self, in_specs):
        (s,) = in_specs
        h, w, c = s.dim
        a = self.conf.attrs
        pc = tuple(a.get("pad_c", (0, 0)))
        ph = tuple(a.get("pad_h", (0, 0)))
        pw = tuple(a.get("pad_w", (0, 0)))
        self._shape = (h, w, c)
        self._pads = (ph, pw, pc)
        return Spec(dim=(h + sum(ph), w + sum(pw), c + sum(pc)), is_seq=s.is_seq), {}

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        x = arg.value.reshape((arg.value.shape[0],) + self._shape)
        ph, pw, pc = self._pads
        y = jnp.pad(x, ((0, 0), ph, pw, pc))
        return arg.with_value(y)


@LAYERS.register("crop")
class CropLayer(Layer):
    """Crop spatial dims (gserver/layers/CropLayer.cpp, function/CropOp.cpp).
    attrs: crop_h/crop_w (begin, size) or target taken from 2nd input."""

    def build(self, in_specs):
        s = in_specs[0]
        h, w, c = s.dim
        a = self.conf.attrs
        if len(in_specs) > 1:
            th, tw, _ = in_specs[1].dim
            bh = a.get("offset_h", (h - th) // 2)
            bw = a.get("offset_w", (w - tw) // 2)
            self._crop = (bh, th, bw, tw)
        else:
            bh, th = a["crop_h"]
            bw, tw = a["crop_w"]
            self._crop = (bh, th, bw, tw)
        self._shape = (h, w, c)
        return Spec(dim=(self._crop[1], self._crop[3], c), is_seq=s.is_seq), {}

    def forward(self, params, inputs, ctx):
        arg = inputs[0]
        bh, th, bw, tw = self._crop
        x = arg.value.reshape((arg.value.shape[0],) + self._shape)
        return arg.with_value(x[:, bh : bh + th, bw : bw + tw, :])


@LAYERS.register("rotate")
class RotateLayer(Layer):
    """Rotate each [H,W] channel plane 90° CLOCKWISE
    (gserver/layers/RotateLayer.cpp: y(j,i,:) = x(M-i-1,j,:) with
    Matrix::rotate clockWise=true; channels = size/(h*w)).
    attrs: height, width."""

    def build(self, in_specs):
        s = in_specs[0]
        a = self.conf.attrs
        h, w = a["height"], a["width"]
        size = 1
        for d in s.dim:
            size *= int(d)
        if size % (h * w):
            raise ValueError(
                f"rotate: input size {size} not divisible by "
                f"height*width {h}x{w}"
            )
        return in_specs[0], {}

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        a = self.conf.attrs
        h, w = a["height"], a["width"]
        x = arg.value
        lead = x.shape[:-1]
        size = x.shape[-1]
        c = size // (h * w)
        y = x.reshape(lead + (c, h, w))
        # clockwise: y[a,b] = x[h-1-b, a]  (flip rows, then transpose)
        y = jnp.flip(y, axis=-2).swapaxes(-1, -2)
        return arg.with_value(y.reshape(lead + (size,)))


@LAYERS.register("gen_output")
class GenOutputLayer(Layer):
    """Placeholder for the id sequences a generating beam-search group
    emits (the v1 '__beam_search_predict__' layer,
    trainer_config_helpers/layers.py:3757; executed by
    RecurrentGradientMachine::generateSequence,
    RecurrentGradientMachine.h:307). Generation runs through
    api.SequenceGenerator / paddle_tpu.beam_search — this layer only
    anchors the graph so outputs()/Topology see the generator."""

    def build(self, in_specs):
        return Spec(dim=(1,), is_seq=True, is_ids=True), {}

    def forward(self, params, inputs, ctx):
        raise RuntimeError(
            f"{self.name}: generated sequences come from "
            "api.SequenceGenerator (beam search), not Network.forward"
        )


@LAYERS.register("subseq", "sub_seq")
class SubSequenceLayer(Layer):
    """Take a per-example sub-span of each sequence given dynamic offset
    and size inputs (SubSequenceLayer.cpp: inputs [seq, offset, size]).
    offset/size are [B] id args (one integer per sequence). TPU-first:
    a clamped gather over the time axis plus a new seq_lens — static
    shapes, so the output keeps the input's max length with padding
    beyond each new length."""

    def build(self, in_specs):
        s = in_specs[0]
        assert s.is_seq, "subseq needs a sequence input"
        return Spec(dim=s.dim, is_seq=True, dtype=s.dtype), {}

    def forward(self, params, inputs, ctx):
        x, off, size = inputs
        v = x.value
        T = v.shape[1]
        o = off.ids.reshape(-1)  # [B]
        n = size.ids.reshape(-1)  # [B]
        # clamp the span inside the real sequence; an offset at or past
        # the end yields an EMPTY sequence, not a fabricated tail slice
        in_range = o < x.seq_lens
        o = jnp.clip(o, 0, jnp.maximum(x.seq_lens - 1, 0))
        n = jnp.where(in_range, jnp.clip(n, 0, x.seq_lens - o), 0)
        idx = o[:, None] + jnp.arange(T)[None, :]  # [B, T]
        idx = jnp.clip(idx, 0, T - 1)
        y = jnp.take_along_axis(
            v, idx.reshape(idx.shape + (1,) * (v.ndim - 2)), axis=1
        )
        mask = (jnp.arange(T)[None, :] < n[:, None]).astype(v.dtype)
        y = y * mask.reshape(mask.shape + (1,) * (v.ndim - 2))
        return Arg(value=y, seq_lens=n.astype(jnp.int32))


@LAYERS.register("sub_nested_seq")
class SubNestedSequenceLayer(Layer):
    """Select sub-sequences of a nested sequence by per-example indices
    (SubNestedSequenceLayer.cpp; layers.py:6098 sub_nested_seq_layer —
    beam training). inputs: [nested (flat [B,T,D] + subseq_lens [B,S]),
    selected (ids [B,K])]. Output: nested sequence of the K selected
    sub-sequences, in selection order, compacted to the front."""

    def build(self, in_specs):
        s, sel = in_specs
        assert s.has_subseq, "sub_nested_seq needs a nested input"
        return Spec(dim=s.dim, is_seq=True, has_subseq=True), {}

    def forward(self, params, inputs, ctx):
        x, sel = inputs
        v = x.value  # [B, T, D]
        T = v.shape[1]
        sl = x.subseq_lens  # [B, S]
        ends = jnp.cumsum(sl, axis=1)
        starts = ends - sl
        k_idx = sel.ids  # [B, K]
        K = k_idx.shape[1]
        # invalid selections select NOTHING: -1 sentinels (e.g. from
        # kmax_seq_score on short sequences) and slots beyond the
        # selection's own seq_lens must not wrap to the last sub-seq
        valid_sel = k_idx >= 0
        if sel.seq_lens is not None:
            valid_sel = valid_sel & (
                jnp.arange(K)[None, :] < sel.seq_lens[:, None]
            )
        safe_idx = jnp.clip(k_idx, 0, sl.shape[1] - 1)
        sel_lens = jnp.take_along_axis(sl, safe_idx, axis=1) * valid_sel
        in_starts = jnp.take_along_axis(starts, safe_idx, axis=1)
        out_ends = jnp.cumsum(sel_lens, axis=1)  # [B, K]
        out_starts = out_ends - sel_lens
        pos = jnp.arange(T, dtype=jnp.int32)[None, :]  # [1, T]
        # which selected segment does output position p fall into
        seg = jnp.sum(
            (pos[:, :, None] >= out_ends[:, None, :]), axis=-1
        )  # [B, T]
        seg_c = jnp.minimum(seg, k_idx.shape[1] - 1)
        offset = pos - jnp.take_along_axis(out_starts, seg_c, axis=1)
        src = jnp.take_along_axis(in_starts, seg_c, axis=1) + offset
        valid = pos < out_ends[:, -1:]
        src = jnp.clip(src, 0, T - 1)
        y = jnp.take_along_axis(
            v, src.reshape(src.shape + (1,) * (v.ndim - 2)), axis=1
        )
        y = y * valid.reshape(valid.shape + (1,) * (v.ndim - 2)).astype(
            y.dtype
        )
        return Arg(
            value=y,
            seq_lens=jnp.sum(sel_lens, axis=1).astype(jnp.int32),
            subseq_lens=sel_lens.astype(jnp.int32),
        )
