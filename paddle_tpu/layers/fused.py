"""Fused bottleneck layers over the Mosaic BN->ReLU->1x1-GEMM kernel.

The graph-level face of ops/pallas_fused.py (the ResNet-50 MFU lever,
PERF.md; CUDA analogue: the reference's hand-fused kernels in
cuda/src/hl_cuda_cnn.cu). Two layer types replace the XLA-separate
chains of the bottleneck block (models/image.py _bottleneck):

- `fused_conv1x1_bn`   = conv(1x1, no bias) + batch_norm(act):
  the GEMM runs with a stats epilogue, so the BN statistics cost no
  extra passes over the conv output; the normalize+act stays XLA
  elementwise (its output is consumed by the next conv anyway).
- `fused_bottleneck_tail` = batch_norm(act=relu) + conv(1x1, no bias)
  + batch_norm + residual add + act: the first BN's normalize/ReLU is
  folded into the GEMM's input side (the normalized activation is
  never materialized), the second BN's stats come from the epilogue,
  and the final normalize+add+act is one XLA elementwise pass.

Both match the plain graph numerically (tests/test_layers_extras.py
TestFusedBottleneck) and run in interpret mode on CPU.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.config import ParameterConf
from paddle_tpu.core.registry import LAYERS
from paddle_tpu.layers.base import Layer, Spec


def _bn_affine(gamma, beta, mean, var, eps):
    """BN normalize folded to per-channel (scale, shift), f32."""
    f32 = jnp.float32
    inv = lax.rsqrt(var.astype(f32) + eps)
    scale = gamma.astype(f32) * inv
    shift = beta.astype(f32) - mean.astype(f32) * scale
    return scale, shift


def _moments_from_epilogue(s1, s2, n):
    mean = s1 / n
    var = jnp.maximum(s2 / n - jnp.square(mean), 0.0)
    return mean, var


def _bn_param_confs(layer, c, prefix):
    gamma = ParameterConf(
        name=f"_{layer.name}.{prefix}g", dims=(c,),
        initial_strategy="constant", initial_value=1.0,
    )
    beta = ParameterConf(
        name=f"_{layer.name}.{prefix}b", dims=(c,),
        initial_strategy="constant", initial_value=0.0,
    )
    return gamma, beta


@LAYERS.register("fused_conv1x1_bn")
class FusedConv1x1BN(Layer):
    """1x1 conv (stride 1, no bias) + BatchNorm(act) with the BN stats
    accumulated in the GEMM's epilogue. attrs: num_filters, epsilon,
    moving_average_fraction, use_global_stats."""

    def build(self, in_specs):
        (s,) = in_specs
        assert not s.is_seq, (
            f"{self.name}: fused BN layers compute unmasked batch "
            "statistics — sequence inputs would let padding corrupt "
            "them (use conv+batch_norm)"
        )
        h, w, c = s.dim
        nf = self.conf.attrs.get("num_filters", self.conf.size)
        pcs = {"w0": self.weight_conf(0, (c, nf))}
        if pcs["w0"].initial_std is None:
            pcs["w0"].initial_std = (2.0 / c) ** 0.5
        pcs["g"], pcs["b"] = _bn_param_confs(self, nf, "bn")
        self._channels = nf
        self._in_shape = (h, w, c)
        return Spec(dim=(h, w, nf), is_seq=s.is_seq), pcs

    def init_state(self):
        c = self._channels
        return {"mean": jnp.zeros((c,)), "var": jnp.ones((c,))}

    def forward(self, params, inputs, ctx):
        from paddle_tpu.ops.pallas_fused import bn_act_conv1x1

        (arg,) = inputs
        a = self.conf.attrs
        eps = a.get("epsilon", 1e-5)
        frac = a.get("moving_average_fraction", 0.9)
        use_global = a.get("use_global_stats", False) or not ctx.train
        x = arg.value
        b, h, w, c = x.shape
        n = b * h * w
        cin = self._in_shape[2]
        ones = jnp.ones((cin,), jnp.float32)
        zeros = jnp.zeros((cin,), jnp.float32)
        y2d, s1, s2 = bn_act_conv1x1(
            x.reshape(n, cin), ones, zeros, params["w0"], act=""
        )
        st = ctx.state[self.name]
        if use_global:
            mean, var = st["mean"], st["var"]
            ctx.updated_state[self.name] = st
        else:
            mean, var = _moments_from_epilogue(s1, s2, n)
            ctx.updated_state[self.name] = {
                "mean": st["mean"] * frac + mean * (1 - frac),
                "var": st["var"] * frac + var * (1 - frac),
            }
        scale, shift = _bn_affine(
            params["g"], params["b"], mean, var, eps
        )
        y = y2d.reshape(b, h, w, -1)
        y = y * scale.astype(y.dtype) + shift.astype(y.dtype)
        y = self.apply_activation_and_dropout(y, ctx, arg.seq_lens)
        return Arg(value=y, seq_lens=arg.seq_lens)


@LAYERS.register("fused_bottleneck_tail")
class FusedBottleneckTail(Layer):
    """BN(in)+ReLU -> 1x1 conv -> BN(out) [+ residual] -> act, with the
    in-BN normalize/ReLU fused into the GEMM input side and the out-BN
    stats from the epilogue. Inputs: [conv_raw, residual?]. attrs:
    num_filters, epsilon, moving_average_fraction, use_global_stats."""

    def build(self, in_specs):
        s = in_specs[0]
        assert not s.is_seq, (
            f"{self.name}: fused BN layers compute unmasked batch "
            "statistics — sequence inputs would let padding corrupt "
            "them (use conv+batch_norm)"
        )
        h, w, c = s.dim
        nf = self.conf.attrs.get("num_filters", self.conf.size)
        if len(in_specs) > 1:
            rs = in_specs[1]
            assert rs.dim == (h, w, nf), (
                f"{self.name}: residual dim {rs.dim} != output "
                f"{(h, w, nf)}"
            )
        pcs = {"w0": self.weight_conf(0, (c, nf))}
        if pcs["w0"].initial_std is None:
            pcs["w0"].initial_std = (2.0 / c) ** 0.5
        pcs["gi"], pcs["bi"] = _bn_param_confs(self, c, "bni")
        pcs["go"], pcs["bo"] = _bn_param_confs(self, nf, "bno")
        self._cin, self._cout = c, nf
        return Spec(dim=(h, w, nf), is_seq=s.is_seq), pcs

    def init_state(self):
        return {
            "in_mean": jnp.zeros((self._cin,)),
            "in_var": jnp.ones((self._cin,)),
            "out_mean": jnp.zeros((self._cout,)),
            "out_var": jnp.ones((self._cout,)),
        }

    def forward(self, params, inputs, ctx):
        from paddle_tpu.ops.pallas_fused import bn_act_conv1x1

        arg = inputs[0]
        res = inputs[1].value if len(inputs) > 1 else None
        a = self.conf.attrs
        eps = a.get("epsilon", 1e-5)
        frac = a.get("moving_average_fraction", 0.9)
        use_global = a.get("use_global_stats", False) or not ctx.train
        x = arg.value
        b, h, w, c = x.shape
        n = b * h * w
        st = ctx.state[self.name]
        f32 = jnp.float32

        # in-BN statistics over the raw conv output (one bf16 pass —
        # same formulation as layers/norm.py BatchNormLayer)
        if use_global:
            in_mean, in_var = st["in_mean"], st["in_var"]
        else:
            red = (0, 1, 2)
            in_mean = jnp.mean(x, axis=red, dtype=f32)
            if x.dtype == f32:
                in_var = jnp.mean(
                    jnp.square(x - in_mean), axis=red, dtype=f32
                )
            else:
                msq = jnp.mean(jnp.square(x), axis=red, dtype=f32)
                in_var = jnp.maximum(msq - jnp.square(in_mean), 0.0)
        scale_i, shift_i = _bn_affine(
            params["gi"], params["bi"], in_mean, in_var, eps
        )

        y2d, s1, s2 = bn_act_conv1x1(
            x.reshape(n, c), scale_i, shift_i, params["w0"], act="relu"
        )
        if use_global:
            out_mean, out_var = st["out_mean"], st["out_var"]
            ctx.updated_state[self.name] = st
        else:
            out_mean, out_var = _moments_from_epilogue(s1, s2, n)
            ctx.updated_state[self.name] = {
                "in_mean": st["in_mean"] * frac + in_mean * (1 - frac),
                "in_var": st["in_var"] * frac + in_var * (1 - frac),
                "out_mean": st["out_mean"] * frac + out_mean * (1 - frac),
                "out_var": st["out_var"] * frac + out_var * (1 - frac),
            }
        scale_o, shift_o = _bn_affine(
            params["go"], params["bo"], out_mean, out_var, eps
        )
        y = y2d.reshape(b, h, w, -1)
        y = y * scale_o.astype(y.dtype) + shift_o.astype(y.dtype)
        if res is not None:
            y = y + res
        y = self.apply_activation_and_dropout(y, ctx, arg.seq_lens)
        return Arg(value=y, seq_lens=arg.seq_lens)
