"""Normalization layers.

Reference: gserver/layers/{BatchNormalizationLayer,CudnnBatchNormLayer,
BatchNormBaseLayer,CrossMapNormalLayer,NormLayer}.cpp (3 batch-norm impls;
LRN via function/CrossMapNormalOp.cpp). One XLA impl each. Running
mean/var live in network *state*, not params — they are not differentiated
(the reference models them as static parameters updated in forward).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.config import ParameterConf
from paddle_tpu.core.registry import LAYERS
from paddle_tpu.layers.base import Layer, Spec


@LAYERS.register("batch_norm", "cudnn_batch_norm")
class BatchNormLayer(Layer):
    """Batch normalization over the channel (last) axis. attrs:
    moving_average_fraction (default .9, reference
    BatchNormBaseLayer movingAvgFraction_), epsilon (1e-5),
    use_global_stats (force inference stats)."""

    def build(self, in_specs):
        (s,) = in_specs
        c = s.dim[-1] if len(s.dim) > 1 else s.size
        self._channels = c
        pcs = {
            "w0": self.weight_conf(0, (c,)),
            "b": self.bias_conf((c,)) or ParameterConf(name=f"_{self.name}.wbias", dims=(c,)),
        }
        # scale init = 1 (reference initializes gamma to 1)
        if pcs["w0"].initial_std is None:
            pcs["w0"].initial_strategy = "constant"
            pcs["w0"].initial_value = 1.0
        self._spec = s
        return s, pcs

    def init_state(self):
        c = self._channels
        return {
            "mean": jnp.zeros((c,)),
            "var": jnp.ones((c,)),
        }

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        a = self.conf.attrs
        eps = a.get("epsilon", 1e-5)
        frac = a.get("moving_average_fraction", 0.9)
        use_global = a.get("use_global_stats", False) or not ctx.train
        x = arg.value
        st = ctx.state[self.name]
        red = tuple(range(x.ndim - 1))
        f32 = jnp.float32
        # Stats in ONE pass over x (E[x], E[x^2] — XLA fuses both
        # reduces into a single read of the bf16 activation; the f32
        # converts fuse INTO the reduces, so no full-size f32 tensor is
        # ever materialized). The normalize is then a per-channel affine
        # y = x*scale + offset applied in x's own dtype — under bf16 AMP
        # this keeps the whole BN layer at one bf16 read + one bf16
        # write, which is what makes ResNet HBM traffic sane.
        if use_global:
            mean, var = st["mean"], st["var"]
            ctx.updated_state[self.name] = st
        elif arg.is_seq:
            # mask padded timesteps out of the statistics: padding must
            # never affect results (framework invariant; see core/arg.py)
            m = arg.mask(f32).reshape(x.shape[:2] + (1,) * (x.ndim - 2))
            n = jnp.maximum(jnp.sum(m), 1.0) * (
                x.size / (x.shape[0] * x.shape[1] * x.shape[-1])
            )
            # square in x's own dtype, ACCUMULATE in f32: squaring an
            # f32 upcast would make autodiff save the full-size f32
            # tensor for the backward (822MB per stem BN at bs=256);
            # squaring the bf16 value saves only x, which the conv
            # backward already keeps
            mean = jnp.sum(x * m.astype(x.dtype), axis=red,
                           dtype=f32) / n
            if x.dtype == f32:
                # full precision input: centered second moment — no
                # E[x^2]-E[x]^2 cancellation, and the saved residual is
                # x itself (no extra memory vs the one-pass form)
                d = (x - mean.astype(x.dtype)) * m.astype(x.dtype)
                var = jnp.sum(jnp.square(d), axis=red, dtype=f32) / n
            else:
                msq = jnp.sum(jnp.square(x) * m.astype(x.dtype),
                              axis=red, dtype=f32) / n
                var = jnp.maximum(msq - jnp.square(mean), 0.0)
            ctx.updated_state[self.name] = {
                "mean": st["mean"] * frac + mean * (1 - frac),
                "var": st["var"] * frac + var * (1 - frac),
            }
        else:
            # see the masked branch: E[x^2]-E[x]^2 (one bf16 pass) only
            # under AMP; full-precision inputs get the centered form
            mean = jnp.mean(x, axis=red, dtype=f32)
            if x.dtype == f32:
                var = jnp.mean(jnp.square(x - mean), axis=red, dtype=f32)
            else:
                msq = jnp.mean(jnp.square(x), axis=red, dtype=f32)
                var = jnp.maximum(msq - jnp.square(mean), 0.0)
            ctx.updated_state[self.name] = {
                "mean": st["mean"] * frac + mean * (1 - frac),
                "var": st["var"] * frac + var * (1 - frac),
            }
        inv = lax.rsqrt(var.astype(f32) + eps)
        scale = params["w0"].astype(f32) * inv
        offset = params["b"].astype(f32) - mean.astype(f32) * scale
        y = x * scale.astype(x.dtype) + offset.astype(x.dtype)
        y = self.apply_activation_and_dropout(y, ctx, arg.seq_lens)
        return Arg(value=y, seq_lens=arg.seq_lens)


@LAYERS.register("norm", "cmrnorm-projection")
class CrossMapNormLayer(Layer):
    """Local response normalization across channels
    (function/CrossMapNormalOp.cpp): y = x / (1 + alpha/N * sum x^2)^beta
    over a window of `size` channels. attrs: size, scale (alpha), pow (beta)."""

    def build(self, in_specs):
        (s,) = in_specs
        self._spec = s
        return s, {}

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        a = self.conf.attrs
        n = a.get("size", 5)
        alpha = a.get("scale", 1e-4)
        beta = a.get("pow", 0.75)
        x = arg.value
        sq = jnp.square(x)
        half = n // 2
        pad_cfg = [(0, 0)] * (x.ndim - 1) + [(half, n - 1 - half)]
        padded = jnp.pad(sq, pad_cfg)
        window = sum(
            padded[..., i : i + x.shape[-1]] for i in range(n)
        )
        denom = jnp.power(1.0 + alpha * window, beta)
        return arg.with_value(x / denom)


@LAYERS.register("row_l2_norm")
class RowL2NormLayer(Layer):
    """Row-wise L2 normalize (gserver/layers/RowL2NormLayer.cpp)."""

    def build(self, in_specs):
        return in_specs[0], {}

    def forward(self, params, inputs, ctx):
        x = inputs[0].value
        n = jnp.linalg.norm(x, axis=-1, keepdims=True)
        return inputs[0].with_value(x / jnp.maximum(n, 1e-12))
