"""Sampling-based output layers: NCE, hierarchical sigmoid, sampling_id.

Reference: gserver/layers/{NCELayer,HierarchicalSigmoidLayer,
SamplingIdLayer,MultinomialSampler}.cpp. Sampling uses JAX's counter-based
PRNG (no alias-table MultinomialSampler needed —
jax.random.categorical is the device-side equivalent).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.registry import LAYERS
from paddle_tpu.layers.base import Layer, Spec
from paddle_tpu.layers.cost import CostLayerBase


@LAYERS.register("nce")
class NCELayer(CostLayerBase):
    """Noise-contrastive estimation (NCELayer.cpp). inputs:
    [feature(s)..., label(ids)]. attrs: num_classes, num_neg_samples
    (default 10), neg_distribution (optional list of class probs).
    Params per feature input: W_i [num_classes, dim_i]; bias [num_classes].

    Training uses sampled logistic losses; at test time
    (ctx.train=False) it returns the same sampled objective with a fixed
    key so costs are deterministic."""

    def build(self, in_specs):
        nc = self.conf.attrs["num_classes"]
        pcs = {}
        self._feat_specs = in_specs[:-1]
        for i, s in enumerate(in_specs[:-1]):
            pcs[f"w{i}"] = self.weight_conf(i, (nc, s.size))
        b = self.bias_conf((nc,))
        if b is not None:
            pcs["b"] = b
        return Spec(dim=(1,), is_seq=False), pcs

    def forward(self, params, inputs, ctx):
        a = self.conf.attrs
        nc = a["num_classes"]
        k = a.get("num_neg_samples", 10)
        label = inputs[-1]
        feats = inputs[:-1]
        bsz = label.ids.shape[0]

        neg_dist = a.get("neg_distribution")
        if neg_dist is not None:
            logq = jnp.log(jnp.asarray(neg_dist, jnp.float32) + 1e-20)
        else:
            logq = jnp.full((nc,), -np.log(nc), jnp.float32)

        key = ctx.split(self.name) if ctx.train else jax.random.key(0)
        neg = jax.random.categorical(key, logq, shape=(bsz, k))  # [B,k]

        def score(cls_idx):
            """cls_idx: [B, m] -> scores [B, m]."""
            s = 0.0
            for i, f in enumerate(feats):
                w = params[f"w{i}"]  # [nc, d]
                rows = jnp.take(w, cls_idx, axis=0)  # [B,m,d]
                x = f.value.reshape(bsz, -1)
                s = s + jnp.einsum("bd,bmd->bm", x, rows)
            if "b" in params:
                s = s + jnp.take(params["b"], cls_idx)
            return s

        pos_s = score(label.ids[:, None])[:, 0]  # [B]
        neg_s = score(neg)  # [B,k]
        logk = jnp.log(float(k))
        pos_logit = pos_s - (logk + jnp.take(logq, label.ids))
        neg_logit = neg_s - (logk + jnp.take(logq, neg))
        loss = jax.nn.softplus(-pos_logit) + jnp.sum(
            jax.nn.softplus(neg_logit), axis=1
        )
        return self._reduce(loss, feats[0])


@LAYERS.register("hsigmoid")
class HierarchicalSigmoidLayer(CostLayerBase):
    """Hierarchical sigmoid over a complete binary tree
    (HierarchicalSigmoidLayer.cpp): class c's path is the bit pattern of
    (c + num_classes); internal node j has weight row j-1. Params per
    feature input: W_i [num_classes-1, dim_i]; bias [num_classes-1]."""

    def build(self, in_specs):
        nc = self.conf.attrs["num_classes"]
        self._depth = int(np.ceil(np.log2(nc))) + 1
        pcs = {}
        for i, s in enumerate(in_specs[:-1]):
            pcs[f"w{i}"] = self.weight_conf(i, (nc - 1, s.size))
        b = self.bias_conf((nc - 1,))
        if b is not None:
            pcs["b"] = b
        return Spec(dim=(1,), is_seq=False), pcs

    def forward(self, params, inputs, ctx):
        nc = self.conf.attrs["num_classes"]
        label = inputs[-1]
        feats = inputs[:-1]
        bsz = label.ids.shape[0]

        code = label.ids + nc  # [B]
        loss = jnp.zeros((bsz,), jnp.float32)
        for _ in range(self._depth):
            parent = code // 2
            bit = (code % 2).astype(jnp.float32)  # 1 = right child
            node = parent - 1  # weight row
            active = parent >= 1
            safe_node = jnp.clip(node, 0, nc - 2)
            s = jnp.zeros((bsz,), jnp.float32)
            for i, f in enumerate(feats):
                w_rows = jnp.take(params[f"w{i}"], safe_node, axis=0)
                s = s + jnp.einsum("bd,bd->b", f.value.reshape(bsz, -1), w_rows)
            if "b" in params:
                s = s + jnp.take(params["b"], safe_node)
            # binary logistic: target bit
            step_loss = jax.nn.softplus(jnp.where(bit > 0, -s, s))
            loss = loss + jnp.where(active, step_loss, 0.0)
            code = parent
        return self._reduce(loss, feats[0])


@LAYERS.register("sampling_id")
class SamplingIdLayer(Layer):
    """Sample an id from a probability row (SamplingIdLayer.cpp)."""

    def build(self, in_specs):
        return Spec(dim=(1,), is_seq=in_specs[0].is_seq, is_ids=True), {}

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        key = ctx.split(self.name)
        logits = jnp.log(jnp.maximum(arg.value, 1e-20))
        ids = jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
        return Arg(ids=ids, seq_lens=arg.seq_lens)


@LAYERS.register("max_id", "maxid")
class MaxIdLayer(Layer):
    """Argmax id (MaxIdLayer.cpp)."""

    def build(self, in_specs):
        return Spec(dim=(1,), is_seq=in_specs[0].is_seq, is_ids=True), {}

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        ids = jnp.argmax(arg.value, axis=-1).astype(jnp.int32)
        return Arg(ids=ids, seq_lens=arg.seq_lens)


@LAYERS.register("multiplex")
class MultiplexLayer(Layer):
    """Row-wise select among N inputs by index input
    (MultiplexLayer.cpp). inputs: [selector(ids), x1..xN]."""

    def build(self, in_specs):
        return in_specs[1], {}

    def forward(self, params, inputs, ctx):
        sel = inputs[0].ids
        stacked = jnp.stack([a.value for a in inputs[1:]], axis=0)  # [N,B,...]
        idx = sel.reshape((1, -1) + (1,) * (stacked.ndim - 2))
        y = jnp.take_along_axis(stacked, idx, axis=0)[0]
        return inputs[1].with_value(y)
