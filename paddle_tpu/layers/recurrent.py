"""Recurrent layers: simple RNN, LSTM, GRU over packed [B,T,*] batches.

Reference: gserver/layers/{RecurrentLayer,LstmLayer,GatedRecurrentLayer}.cpp
with fused CUDA cells (cuda/src/hl_cuda_lstm.cu, hl_gpu_gru.cuh) and
SequenceToBatch reordering (SequenceToBatch.h) so unequal-length sequences
advance together without padding.

TPU-first redesign: `lax.scan` over the time axis of a dense [B,T,*] batch.
Variable lengths are handled by masked state carry — at a padded timestep
the hidden/cell state is carried through unchanged and the output is zeroed,
which reproduces SequenceToBatch semantics exactly (padding can never leak
into real steps). The big input projection x@W (size -> 4h/3h) is done by
the *preceding* layer, as in the reference where lstmemory expects a
4*size input; the per-step matmul here is only h @ W_rec, which XLA fuses
into one MXU call per step inside the scan.

Gate order (matching the reference's buffer layout): LSTM = [i, f, g, o],
GRU = [u, r, c]. LSTM bias holds 4h gate biases + 3h peephole weights
(Wci, Wcf, Wco), total 7h, as in LstmLayer.cpp.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.registry import LAYERS
from paddle_tpu.layers.base import Layer, Spec
from paddle_tpu.ops import activations
from paddle_tpu.ops import sequence_ops as sops


def _use_fused(bsz=None, t_max=None, h=None, mult=4) -> bool:
    """Fused Pallas cell policy: explicit flag only.

    Round-3 interleaved A/B measurement (bench.py
    bench_lstm_fused_vs_scan: both arms compiled+warmed, alternating
    timing windows, min per arm — immune to the tunnel-preemption bias
    that produced round 2's contradictory numbers) shows XLA's
    lax.scan lowering BEATS the fused Pallas kernels on v5e at every
    tested shape, training AND inference:
      train  scan/fused: bs128 h256 0.85x, bs128 h512 1.04x (noise),
             bs128 h1280 0.81x, bs256 h256 0.59x, bs256 h512 0.64x
      fwd    bs128 h256 0.92x, bs128 h512 0.87x, bs256 h512 0.52x
    So auto NEVER engages the kernels; they remain available for
    explicit opt-in (flags.set_flag('use_pallas_rnn', True)) and are
    correctness-tested in test_pallas_kernels.py. The capability match
    for cuda/src/hl_cuda_lstm.cu is the kernels' existence; the perf
    match on TPU is the scan+XLA path.

    The shape parameters are intentionally retained (unused) so call
    sites keep passing them — if a future XLA/Mosaic shift flips the
    A/B (the bench row watches it), the shape-dependent policy slots
    back in without touching callers.

    Round-6 verdict (ROADMAP 5a, PERF.md "fused-RNN family retired"):
    the family is formally RETIRED as a production path. The GRU
    backward was never landed (it recomputes through the scan
    reference, so fused-GRU training pays kernel forward + scan
    backward), and the completed LSTM pair loses to the scan at every
    measured shape — engaging the flag now warns DeprecationWarning
    once per process. The kernels stay in-tree, correctness-tested, as
    the hl_cuda_lstm.cu capability match and the A/B tripwire arm."""
    from paddle_tpu.core.flags import get_flag

    v = get_flag("use_pallas_rnn")
    if v is not None:
        if bool(v) and not _WARNED_FUSED_OPTIN:
            import warnings

            _WARNED_FUSED_OPTIN.append(True)
            warnings.warn(
                "use_pallas_rnn=True engages the RETIRED fused Pallas "
                "RNN path: measured slower than XLA lax.scan at every "
                "tested shape (PERF.md), and GRU has no fused backward "
                "(training recomputes through the scan). Kept for "
                "kernel A/B testing only.",
                DeprecationWarning,
                stacklevel=3,
            )
        return bool(v)
    return False


# once-per-process latch: the bench A/B flips the flag per timing
# window and must not spam a warning per engaged forward
_WARNED_FUSED_OPTIN: list = []


def _interpret_mode() -> bool:
    return jax.devices()[0].platform == "cpu"


def _scan_rnn(step, x_btd, seq_lens, init_carry, reverse=False):
    """Run `step(carry, x_t, m_t) -> (carry, y_t)` over time with masked
    carry. x_btd: [B,T,D]. Returns y: [B,T,H]."""
    if reverse:
        x_btd = sops.reverse_seq(x_btd, seq_lens)
    t = x_btd.shape[1]
    mask_bt = (
        jnp.arange(t, dtype=jnp.int32)[None, :] < seq_lens[:, None]
    ).astype(x_btd.dtype)
    xs = (x_btd.swapaxes(0, 1), mask_bt.swapaxes(0, 1))  # time-major

    def body(carry, inp):
        x_t, m_t = inp
        new_carry, y_t = step(carry, x_t)
        m = m_t[:, None]
        new_carry = jax.tree_util.tree_map(
            lambda n, o: m * n + (1.0 - m) * o, new_carry, carry
        )
        return new_carry, y_t * m

    _, ys = lax.scan(body, init_carry, xs)
    y = ys.swapaxes(0, 1)
    if reverse:
        y = sops.reverse_seq(y, seq_lens)
    return y


@LAYERS.register("recurrent")
class RecurrentLayer(Layer):
    """h_t = act(x_t + h_{t-1} @ W) (gserver/layers/RecurrentLayer.cpp).
    attrs: reversed."""

    def build(self, in_specs):
        (s,) = in_specs
        h = self.conf.size
        if not h:
            # raw configs omit size; the reference defaults it to the
            # input width (config_parser RecurrentLayer set_layer_size)
            h = self.conf.size = s.size
        assert s.size == h, "recurrent layer input must equal size"
        pcs = {"w0": self.weight_conf(0, (h, h))}
        b = self.bias_conf((h,))
        if b is not None:
            pcs["b"] = b
        return Spec(dim=(h,), is_seq=True), pcs

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        act = self.activation() if self.conf.active_type else jnp.tanh
        w = params["w0"]
        b = params.get("b", 0.0)

        def step(h_prev, x_t):
            h = act(x_t + jnp.dot(h_prev, w) + b)
            return h, h

        bsz = arg.value.shape[0]
        h0 = jnp.zeros((bsz, self.conf.size), arg.value.dtype)
        y = _scan_rnn(
            step, arg.value, arg.seq_lens, h0, self.conf.attrs.get("reversed", False)
        )
        return Arg(value=y, seq_lens=arg.seq_lens)


@LAYERS.register("lstmemory", "lstm")
class LstmLayer(Layer):
    """LSTM with peepholes (gserver/layers/LstmLayer.cpp,
    cuda/src/hl_cuda_lstm.cu). Input: [B,T,4h] pre-projected. Params:
    W_rec [h,4h], bias [7h] = gate biases [4h] + peepholes Wci/Wcf/Wco [3h].
    attrs: reversed, active_gate_type (sigmoid), active_state_type (tanh).
    conf.active_type is the candidate/output activation (default tanh)."""

    def build(self, in_specs):
        (s,) = in_specs
        h = self.conf.size
        assert s.size == 4 * h, f"lstmemory input must be 4*size, got {s.size}"
        pcs = {"w0": self.weight_conf(0, (h, 4 * h))}
        b = self.bias_conf((7 * h,))
        if b is not None:
            pcs["b"] = b
        return Spec(dim=(h,), is_seq=True), pcs

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        h = self.conf.size
        act = activations.get(self.conf.active_type or "tanh")
        gate_act = activations.get(self.conf.attrs.get("active_gate_type", "sigmoid"))
        state_act = activations.get(self.conf.attrs.get("active_state_type", "tanh"))
        w = params["w0"]
        if "b" in params:
            gb = params["b"][: 4 * h]
            wci = params["b"][4 * h : 5 * h]
            wcf = params["b"][5 * h : 6 * h]
            wco = params["b"][6 * h : 7 * h]
        else:
            gb = jnp.zeros((4 * h,), arg.value.dtype)
            wci = wcf = wco = jnp.zeros((h,), arg.value.dtype)

        default_acts = (
            (self.conf.active_type or "tanh") == "tanh"
            and self.conf.attrs.get("active_gate_type", "sigmoid") == "sigmoid"
            and self.conf.attrs.get("active_state_type", "tanh") == "tanh"
        )
        if default_acts and _use_fused(
            arg.value.shape[0], arg.value.shape[1], h, mult=4
        ):
            from paddle_tpu.ops import pallas_rnn

            x = arg.value
            rev = self.conf.attrs.get("reversed", False)
            if rev:
                x = sops.reverse_seq(x, arg.seq_lens)
            y = pallas_rnn.lstm_fused(
                x, w, gb, wci, wcf, wco, arg.seq_lens, _interpret_mode()
            )
            if rev:
                y = sops.reverse_seq(y, arg.seq_lens)
            return Arg(value=y, seq_lens=arg.seq_lens)

        def step(carry, x_t):
            h_prev, c_prev = carry
            g = x_t + jnp.dot(h_prev, w) + gb
            gi, gf, gg, go = jnp.split(g, 4, axis=-1)
            i = gate_act(gi + wci * c_prev)
            f = gate_act(gf + wcf * c_prev)
            cand = act(gg)
            c = f * c_prev + i * cand
            o = gate_act(go + wco * c)
            out = o * state_act(c)
            return (out, c), out

        bsz = arg.value.shape[0]
        zeros = jnp.zeros((bsz, h), arg.value.dtype)
        y = _scan_rnn(
            step,
            arg.value,
            arg.seq_lens,
            (zeros, zeros),
            self.conf.attrs.get("reversed", False),
        )
        return Arg(value=y, seq_lens=arg.seq_lens)


@LAYERS.register("gated_recurrent", "grumemory", "gru")
class GruLayer(Layer):
    """GRU (gserver/layers/GatedRecurrentLayer.cpp, hl_gpu_gru.cuh).
    Input: [B,T,3h] pre-projected as [update, reset, candidate].
    h_t = u ⊙ h_{t-1} + (1-u) ⊙ c_t. attrs: reversed."""

    def build(self, in_specs):
        (s,) = in_specs
        h = self.conf.size
        assert s.size == 3 * h, f"grumemory input must be 3*size, got {s.size}"
        pcs = {"w0": self.weight_conf(0, (h, 2 * h)), "w_c": self.weight_conf(0, (h, h))}
        pcs["w_c"].name = f"_{self.name}.wc"
        b = self.bias_conf((3 * h,))
        if b is not None:
            pcs["b"] = b
        return Spec(dim=(h,), is_seq=True), pcs

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        h = self.conf.size
        act = activations.get(self.conf.active_type or "tanh")
        gate_act = activations.get(self.conf.attrs.get("active_gate_type", "sigmoid"))
        w_g = params["w0"]  # [h, 2h] for update+reset
        w_c = params["w_c"]  # [h, h] candidate
        b = params.get("b", jnp.zeros((3 * h,), arg.value.dtype))

        default_acts = (
            (self.conf.active_type or "tanh") == "tanh"
            and self.conf.attrs.get("active_gate_type", "sigmoid") == "sigmoid"
        )
        if default_acts and _use_fused(
            arg.value.shape[0], arg.value.shape[1], h, mult=3
        ):
            from paddle_tpu.ops import pallas_rnn

            x = arg.value
            rev = self.conf.attrs.get("reversed", False)
            if rev:
                x = sops.reverse_seq(x, arg.seq_lens)
            y = pallas_rnn.gru_fused(
                x, w_g, w_c, b, arg.seq_lens, _interpret_mode()
            )
            if rev:
                y = sops.reverse_seq(y, arg.seq_lens)
            return Arg(value=y, seq_lens=arg.seq_lens)

        def step(h_prev, x_t):
            xu, xr, xc = jnp.split(x_t + b, 3, axis=-1)
            gur = jnp.dot(h_prev, w_g)
            u = gate_act(xu + gur[..., :h])
            r = gate_act(xr + gur[..., h:])
            c = act(xc + jnp.dot(r * h_prev, w_c))
            out = u * h_prev + (1.0 - u) * c
            return out, out

        bsz = arg.value.shape[0]
        h0 = jnp.zeros((bsz, h), arg.value.dtype)
        y = _scan_rnn(
            step, arg.value, arg.seq_lens, h0, self.conf.attrs.get("reversed", False)
        )
        return Arg(value=y, seq_lens=arg.seq_lens)


@LAYERS.register("mdlstm", "mdlstmemory")
class MDLstmLayer(Layer):
    """2-D multi-dimensional LSTM (gserver/layers/MDLstmLayer.cpp):
    each grid cell takes the hidden/cell states of its row- and
    column-predecessors, with ONE shared recurrent weight applied to
    every neighbor's output (MDLstmLayer.cpp:547-561) and a forget gate
    PER dimension (forwardGate2OutputSequence, MDLstmLayer.cpp:475).

    Input: [B, H, W, 5h] pre-projected grid (gate layout
    [i | f_row | f_col | g | o], the (3+D)*size projection of the
    reference with D=2). Output [B, H, W, h]. Missing neighbors at the
    grid edges contribute nothing — realized exactly by zero boundary
    states. attrs: directions = (bool, bool) per dim, True = ascending
    scan (CoordIterator directions_); active_gate_type/
    active_state_type as in lstmemory. Params: w0 [h, 5h] shared
    recurrent weight; bias [5h gates + h wci + 2h wcf + h wco = 9h].

    TPU-first: lax.scan over rows with an inner lax.scan over columns
    (the reference's CoordIterator walk, compiled); grids are dense
    [H, W] — the nested-sequence packaging of the reference collapses
    to the image layout here."""

    def build(self, in_specs):
        (s,) = in_specs
        h = self.conf.size
        gh, gw, gc = s.dim
        assert gc == 5 * h, (
            f"mdlstm input must be (3+2)*size wide, got {gc} != {5 * h}"
        )
        self._grid = (gh, gw)
        pcs = {"w0": self.weight_conf(0, (h, 5 * h))}
        b = self.bias_conf((9 * h,))
        if b is not None:
            pcs["b"] = b
        return Spec(dim=(gh, gw, h), is_seq=s.is_seq), pcs

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        h = self.conf.size
        gh, gw = self._grid
        act = activations.get(self.conf.active_type or "tanh")
        gate_act = activations.get(
            self.conf.attrs.get("active_gate_type", "sigmoid")
        )
        state_act = activations.get(
            self.conf.attrs.get("active_state_type", "tanh")
        )
        dirs = self.conf.attrs.get("directions", (True, True))
        w = params["w0"]
        if "b" in params:
            gb = params["b"][: 5 * h]
            wci = params["b"][5 * h : 6 * h]
            wcf_r = params["b"][6 * h : 7 * h]
            wcf_c = params["b"][7 * h : 8 * h]
            wco = params["b"][8 * h : 9 * h]
        else:
            z = jnp.zeros((h,), arg.value.dtype)
            gb = jnp.zeros((5 * h,), arg.value.dtype)
            wci = wcf_r = wcf_c = wco = z

        x = arg.value.reshape(
            (arg.value.shape[0],) + (gh, gw, 5 * h)
        )
        # descending directions scan by flipping in, flipping back out
        if not dirs[0]:
            x = x[:, ::-1]
        if not dirs[1]:
            x = x[:, :, ::-1]
        bsz = x.shape[0]

        def cell(x_ij, h_top, c_top, h_left, c_left):
            pre = (
                x_ij
                + jnp.dot(h_top + h_left, w)
                + gb
            )
            ig = gate_act(pre[:, :h] + (c_top + c_left) * wci)
            f_r = gate_act(pre[:, h : 2 * h] + c_top * wcf_r)
            f_c = gate_act(pre[:, 2 * h : 3 * h] + c_left * wcf_c)
            g = act(pre[:, 3 * h : 4 * h])
            c = f_r * c_top + f_c * c_left + ig * g
            o = gate_act(pre[:, 4 * h :] + c * wco)
            return o * state_act(c), c

        zrow = jnp.zeros((bsz, gw, h), x.dtype)

        def row_step(carry, x_row):
            h_top_row, c_top_row = carry  # [B, W, h]
            zcol = jnp.zeros((bsz, h), x.dtype)

            def col_step(cc, inp):
                h_left, c_left = cc
                x_ij, h_t, c_t = inp
                out, c = cell(x_ij, h_t, c_t, h_left, c_left)
                return (out, c), (out, c)

            _, (h_row, c_row) = jax.lax.scan(
                col_step,
                (zcol, zcol),
                (
                    x_row.swapaxes(0, 1),
                    h_top_row.swapaxes(0, 1),
                    c_top_row.swapaxes(0, 1),
                ),
            )
            h_row = h_row.swapaxes(0, 1)
            c_row = c_row.swapaxes(0, 1)
            return (h_row, c_row), h_row

        _, ys = jax.lax.scan(
            row_step, (zrow, zrow), x.swapaxes(0, 1)
        )
        y = ys.swapaxes(0, 1)  # [B, H, W, h]
        if not dirs[0]:
            y = y[:, ::-1]
        if not dirs[1]:
            y = y[:, :, ::-1]
        return Arg(value=y, seq_lens=arg.seq_lens)
