"""Cost (loss) layers.

Reference: gserver/layers/CostLayer.cpp — MultiClassCrossEntropy,
SoftBinaryClassCrossEntropy, SumOfSquaresCostLayer, SmoothL1Cost,
RankingCost, LambdaCost, MultiBinaryLabelCrossEntropy, HuberTwoClass —
plus the classification_cost composite (softmax + CE) from
trainer_config_helpers/layers.py. Each outputs per-example cost [B] (or
masked per-token for sequences); the trainer reduces to the batch cost the
same way Argument::sum does (TrainerInternal.cpp:135).

For sequence inputs, padding tokens contribute exactly zero cost and the
per-example cost is the sum over real timesteps — matching the reference's
padding-free accounting.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.registry import LAYERS
from paddle_tpu.layers.base import Layer, Spec

_EPS = 1e-10


class CostLayerBase(Layer):
    is_cost = True

    def build(self, in_specs):
        self._in_specs = in_specs
        return Spec(dim=(1,), is_seq=False), {}

    def _reduce(self, per_token, arg: Arg):
        """per_token: [B] (non-seq) or [B,T] (seq) -> per-example [B]."""
        w = self.conf.attrs.get("coeff", 1.0)
        if arg.is_seq and per_token.ndim == 2:
            per_token = per_token * arg.mask(per_token.dtype)
            per_token = jnp.sum(per_token, axis=1)
        return Arg(value=w * per_token)

    def _weighted(self, cost_arg: Arg, rest) -> Arg:
        """Optional per-example weight input (the v1 weight= kwarg on
        classification_cost/mse_cost; CostLayer.cpp weightLayer_):
        multiplies each example's cost."""
        if not rest:
            return cost_arg
        w = rest[0].value.reshape(cost_arg.value.shape[0])
        return Arg(value=cost_arg.value * w)

    @staticmethod
    def _aligned_ids(pred: Arg, label: Arg):
        """(ids, label_mask): label ids padded/trimmed to the
        prediction's time axis, plus the LABEL's own validity mask on
        that axis (None when no reconciliation applies). The reference
        carries exact flat lengths; here independent padding can
        differ — e.g. a per-subsequence prediction sequence (S_max
        from the nested slot) vs a label sequence padded to its own
        bucket (sequence_nest_layer_group.conf). Multiplying the cost
        by the label mask keeps a REAL length mismatch conservative:
        positions with no real label contribute zero cost instead of
        phantom class-0 terms."""
        ids = label.ids
        lmask = None
        if pred.seq_lens is not None and ids is not None and ids.ndim == 2:
            tp = pred.value.shape[1]
            tl = ids.shape[1]
            if tl > tp:
                ids = ids[:, :tp]
            elif tl < tp:
                ids = jnp.pad(ids, ((0, 0), (0, tp - tl)))
            if label.seq_lens is not None:
                lmask = (
                    jnp.arange(tp)[None, :] < label.seq_lens[:, None]
                ).astype(pred.value.dtype)
        return ids, lmask


@LAYERS.register("multi-class-cross-entropy", "cross_entropy")
class MultiClassCrossEntropy(CostLayerBase):
    """-log p[label]; input is a probability distribution (after softmax
    layer). inputs: [prob, label(ids)]."""

    def forward(self, params, inputs, ctx):
        prob, label, *rest = inputs
        ids, lmask = self._aligned_ids(prob, label)
        p = jnp.take_along_axis(
            prob.value, ids[..., None], axis=-1
        )[..., 0]
        per = -jnp.log(jnp.maximum(p, _EPS))
        if lmask is not None:
            per = per * lmask
        return self._weighted(self._reduce(per, prob), rest)


@LAYERS.register("classification_cost", "softmax_with_cross_entropy")
class SoftmaxCrossEntropy(CostLayerBase):
    """Fused softmax+CE on logits — numerically the composite the v1 DSL
    builds (trainer_config_helpers/layers.py classification_cost), fused
    for TPU (one logsumexp, no materialized probs)."""

    def forward(self, params, inputs, ctx):
        logits, label, *rest = inputs
        ids, lmask = self._aligned_ids(logits, label)
        lse = jax.scipy.special.logsumexp(logits.value, axis=-1)
        picked = jnp.take_along_axis(
            logits.value, ids[..., None], axis=-1
        )[..., 0]
        per = lse - picked
        if lmask is not None:
            per = per * lmask
        return self._weighted(self._reduce(per, logits), rest)


@LAYERS.register("square_error", "sum_of_squares", "mse")
class SumOfSquaresCost(CostLayerBase):
    """0.5*||x - y||^2 per example (CostLayer.cpp SumOfSquaresCostLayer)."""

    def forward(self, params, inputs, ctx):
        x, y, *rest = inputs
        d = x.value - y.value
        return self._weighted(
            self._reduce(0.5 * jnp.sum(jnp.square(d), axis=-1), x), rest
        )


@LAYERS.register("smooth_l1")
class SmoothL1Cost(CostLayerBase):
    """Smooth-L1 (CostLayer.cpp SmoothL1CostLayer)."""

    def forward(self, params, inputs, ctx):
        x, y = inputs
        d = jnp.abs(x.value - y.value)
        per = jnp.where(d < 1.0, 0.5 * d * d, d - 0.5)
        return self._reduce(jnp.sum(per, axis=-1), x)


@LAYERS.register("sum_cost")
class SumCost(CostLayerBase):
    """cost = sum over the input vector (trainer_config_helpers
    sum_cost / SumCostLayer) — the raw-aggregation building block the
    VAE demo uses for its KL term (v1_api_demo/vae/vae_conf.py:103)."""

    def forward(self, params, inputs, ctx):
        (x,) = inputs
        return self._reduce(jnp.sum(x.value, axis=-1), x)


@LAYERS.register("soft_binary_class_cross_entropy")
class SoftBinaryCE(CostLayerBase):
    """Elementwise binary CE with soft labels (CostLayer.cpp)."""

    def forward(self, params, inputs, ctx):
        x, y = inputs
        p = jnp.clip(x.value, _EPS, 1.0 - _EPS)
        per = -(y.value * jnp.log(p) + (1 - y.value) * jnp.log(1 - p))
        return self._reduce(jnp.sum(per, axis=-1), x)


@LAYERS.register("multi_binary_label_cross_entropy")
class MultiBinaryLabelCE(CostLayerBase):
    """Multi-label binary CE; label is a dense 0/1 matrix (the reference
    accepts sparse binary labels — here densified by the feeder)."""

    forward = SoftBinaryCE.forward


@LAYERS.register("rank-cost")
class RankingCost(CostLayerBase):
    """Pairwise rank cost (CostLayer.cpp RankingCost): inputs
    [score_a, score_b, label] with label in [0,1];
    cost = log(1 + exp(o)) - t*o where o = a - b."""

    def forward(self, params, inputs, ctx):
        a, b, t = inputs
        o = (a.value - b.value)[..., 0]
        label = t.value[..., 0] if t.value is not None else t.ids.astype(o.dtype)
        per = jnp.logaddexp(0.0, o) - label * o
        return self._reduce(per, a)


@LAYERS.register("huber_classification", "huber-two-class", "huber")
class HuberTwoClassCost(CostLayerBase):
    """Huber loss for 2-class classification with {-1,1} margin
    (CostLayer.cpp HuberTwoClassification): input 1-D score, label 0/1."""

    def forward(self, params, inputs, ctx):
        x, t = inputs
        y = 2.0 * t.ids.astype(x.value.dtype) - 1.0  # {0,1} -> {-1,1}
        a = y * x.value[..., 0]
        per = jnp.where(a < -1.0, -4.0 * a, jnp.where(a < 1.0, jnp.square(1.0 - a), 0.0))
        return self._reduce(per, x)


@LAYERS.register("lambda_cost")
class LambdaCost(CostLayerBase):
    """LambdaRank NDCG cost over a sequence of (score, relevance)
    (CostLayer.cpp LambdaCost). inputs: [score(seq [B,T,1]), rel(seq)].
    attrs: NDCG_num (default 5), max_sort_size unused (full sort)."""

    def forward(self, params, inputs, ctx):
        score, rel = inputs
        s = score.value[..., 0]  # [B,T]
        r = rel.value[..., 0]
        mask = score.mask(s.dtype)
        ninf = jnp.asarray(-1e30, s.dtype)
        k = self.conf.attrs.get("NDCG_num", 5)
        t = s.shape[1]

        # ideal DCG from top-k relevances
        r_masked = jnp.where(mask > 0, r, ninf)
        r_sorted = -jnp.sort(-r_masked, axis=1)[:, :k]
        disc = 1.0 / jnp.log2(jnp.arange(2, k + 2, dtype=s.dtype))
        idcg = jnp.sum((jnp.exp2(jnp.maximum(r_sorted, 0)) - 1) * disc, axis=1)
        idcg = jnp.maximum(idcg, _EPS)

        # pairwise lambda cost: sum over pairs i<j with r_i != r_j
        si, sj = s[:, :, None], s[:, None, :]
        ri, rj = r[:, :, None], r[:, None, :]
        mij = mask[:, :, None] * mask[:, None, :]
        hi = (ri > rj).astype(s.dtype)
        o = si - sj
        pair_cost = jnp.logaddexp(0.0, -o) / jnp.log(2.0)
        per = jnp.sum(hi * pair_cost * mij, axis=(1, 2)) / idcg
        return Arg(value=self.conf.attrs.get("coeff", 1.0) * per)


@LAYERS.register("softmax")
class SoftmaxLayer(Layer):
    """Standalone softmax output layer (the v1 DSL's `softmax` activation on
    an fc is more common, but a bare softmax layer type also exists)."""

    def build(self, in_specs):
        return in_specs[0], {}

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        return arg.with_value(jax.nn.softmax(arg.value, axis=-1))


@LAYERS.register("multi_class_cross_entropy_with_selfnorm")
class MultiClassCrossEntropyWithSelfNorm(CostLayerBase):
    """CE over probabilities plus softmax_selfnorm_alpha * log(Z)^2
    (CostLayer.cpp MultiClassCrossEntropyWithSelfNorm): pushes the
    partition function toward 1 so inference can skip normalization."""

    def forward(self, params, inputs, ctx):
        prob, label = inputs
        ids, lmask = self._aligned_ids(prob, label)
        z = jnp.sum(prob.value, axis=-1)
        p = jnp.take_along_axis(
            prob.value / jnp.maximum(z, _EPS)[..., None],
            ids[..., None],
            axis=-1,
        )[..., 0]
        alpha = self.conf.attrs.get("softmax_selfnorm_alpha", 0.1)
        per = -jnp.log(jnp.maximum(p, _EPS)) + alpha * jnp.square(
            jnp.log(jnp.maximum(z, _EPS))
        )
        if lmask is not None:
            per = per * lmask
        return self._reduce(per, prob)
