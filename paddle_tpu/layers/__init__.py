"""Layer library. Importing this package registers all layer types."""

from paddle_tpu.layers import (  # noqa: F401
    attention,
    base,
    basic,
    conv,
    cost,
    detection,
    extras,
    fused,
    fused_text,
    moe,
    norm,
    pool,
    recurrent,
    recurrent_group,
    sampling,
    sequence,
    steps,
    structured,
)
