"""Layer base class and spec plumbing.

Reference: paddle/gserver/layers/Layer.h:56 (class Layer) — there, a layer
owns mutable output state and hand-written forward/backward methods
dispatched per device. Here a layer is a *pure-function module*: `build`
declares output spec + parameter specs from input specs; `forward` maps
(params, inputs) -> Arg. Backward is jax.grad over the whole network —
an intentional, idiomatic divergence with identical observable behavior.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.core.config import LayerConf, ModelConf, ParameterConf
from paddle_tpu.core.registry import LAYERS
from paddle_tpu.ops import activations


@dataclass(frozen=True)
class Spec:
    """Static description of a layer output (per-example feature shape,
    sequence-ness, dtype). The analogue of LayerConfig.size plus the image
    shape attrs the reference threads through config_parser."""

    dim: tuple = ()  # per-timestep feature shape, e.g. (784,) or (28,28,32)
    is_seq: bool = False
    has_subseq: bool = False
    is_ids: bool = False
    dtype: object = jnp.float32

    @property
    def size(self) -> int:
        n = 1
        for d in self.dim:
            n *= d
        return n


@dataclass
class Ctx:
    """Per-call context: train/test phase + RNG (for dropout/sampling)."""

    train: bool = False
    rng: Optional[jax.Array] = None
    # non-parameter persistent state (e.g. batch-norm running stats):
    # layers read ctx.state[layer_name] and write ctx.updated_state[layer_name]
    state: dict = field(default_factory=dict)
    updated_state: dict = field(default_factory=dict)
    extra: dict = field(default_factory=dict)

    def split(self, name: str) -> jax.Array:
        assert self.rng is not None, "layer needs rng but Ctx.rng is None"
        import zlib

        return jax.random.fold_in(self.rng, zlib.crc32(name.encode()))


class Layer:
    """Base layer. Subclasses set `type_names` via @LAYERS.register and
    implement build() and forward()."""

    def __init__(self, conf: LayerConf, model: ModelConf):
        self.conf = conf
        self.name = conf.name

    # ---- static graph construction ----
    def build(self, in_specs: list) -> tuple:
        """Return (out_spec, param_confs) where param_confs maps *local*
        param slot -> ParameterConf (with dims filled in)."""
        raise NotImplementedError

    def forward(self, params: dict, inputs: list, ctx: Ctx):
        raise NotImplementedError

    # ---- helpers ----
    def activation(self):
        return activations.get(self.conf.active_type)

    def apply_activation_and_dropout(self, y, ctx: Ctx, seq_lens=None):
        if self.conf.active_type == "sequence_softmax":
            from paddle_tpu.ops import sequence_ops

            assert seq_lens is not None, "sequence_softmax needs sequence input"
            sq = y.shape[-1] == 1
            y2 = y[..., 0] if sq else y
            y2 = sequence_ops.masked_softmax(y2, seq_lens)
            y = y2[..., None] if sq else y2
        else:
            y = self.activation()(y)
        rate = self.conf.drop_rate
        if rate > 0.0 and ctx.train:
            keep = 1.0 - rate
            m = jax.random.bernoulli(ctx.split(self.name + "/drop"), keep, y.shape)
            y = jnp.where(m, y / keep, 0.0)
        return y

    def weight_conf(self, idx: int, dims: tuple) -> ParameterConf:
        """Materialize a ParameterConf for input edge `idx` with dims.
        Returns a copy — never mutates the user's InputConf.parameter, so a
        layer may call this twice for one edge and parameter sharing stays
        by-name, not by-aliased-object."""
        import dataclasses

        ic = self.conf.inputs[idx]
        pc = (
            dataclasses.replace(ic.parameter)
            if ic.parameter is not None
            else ParameterConf()
        )
        if not pc.name:
            pc.name = f"_{self.name}.w{idx}"
        pc.dims = tuple(dims)
        return pc

    def bias_conf(self, dims: tuple) -> Optional[ParameterConf]:
        import dataclasses

        if not self.conf.bias:
            return None
        pc = (
            dataclasses.replace(self.conf.bias_parameter)
            if self.conf.bias_parameter is not None
            else ParameterConf()
        )
        if not pc.name:
            pc.name = f"_{self.name}.wbias"
        pc.dims = tuple(dims)
        return pc


def init_parameter(key: jax.Array, pc: ParameterConf, dtype=jnp.float32):
    """Initialize one parameter per its config.

    Matches the reference's defaults (paddle/parameter/Parameter.cpp
    randomize(): normal with std 1/sqrt(fan_in) for weights, zeros for
    biases/1-D unless initial_std is set)."""
    dims = tuple(pc.dims)
    if pc.initializer is not None:
        # user callback name -> ndarray (v2 ParameterAttribute
        # initializer; reference parameters.py __initialize_with__)
        return jnp.asarray(pc.initializer(pc.name), dtype).reshape(dims)
    if pc.initial_strategy == "zero":
        return jnp.zeros(dims, dtype)
    if pc.initial_strategy == "constant":
        return jnp.full(dims, pc.initial_value, dtype)
    std = pc.initial_std
    if std is None:
        if len(dims) == 1:
            return jnp.full(dims, pc.initial_mean, dtype)
        fan_in = dims[0] if len(dims) == 2 else int(jnp.prod(jnp.asarray(dims[:-1])))
        std = 1.0 / (fan_in ** 0.5)
    if pc.initial_strategy == "uniform":
        u = jax.random.uniform(key, dims, dtype, -1.0, 1.0)
        return pc.initial_mean + std * u
    return pc.initial_mean + std * jax.random.normal(key, dims, dtype)


def create_layer(conf: LayerConf, model: ModelConf) -> Layer:
    return LAYERS.get(conf.type)(conf, model)
