"""Structured-prediction layers: CRF, CRF decoding, CTC.

Reference: gserver/layers/{CRFLayer,CRFDecodingLayer,CTCLayer,
WarpCTCLayer}.cpp. The CRF transition parameter is a trainable weight of
shape [num_tags+2, num_tags] exactly like LinearChainCRF.cpp; CTC has no
parameters.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.registry import LAYERS
from paddle_tpu.layers.base import Layer, Spec
from paddle_tpu.layers.cost import CostLayerBase
from paddle_tpu.ops import crf as crf_ops
from paddle_tpu.ops import ctc as ctc_ops


@LAYERS.register("crf")
class CRFLayer(CostLayerBase):
    """Linear-chain CRF negative log-likelihood (CRFLayer.cpp).
    inputs: [emission(seq [B,T,N]), label(seq ids)]. size = num_tags."""

    def build(self, in_specs):
        n = self.conf.size or in_specs[0].size
        self._num_tags = n
        pcs = {"w0": self.weight_conf(0, (n + 2, n))}
        return Spec(dim=(1,), is_seq=False), pcs

    def forward(self, params, inputs, ctx):
        emit, label = inputs
        ll = crf_ops.crf_log_likelihood(
            emit.value, label.ids, emit.seq_lens, params["w0"]
        )
        return Arg(value=self.conf.attrs.get("coeff", 1.0) * (-ll))


@LAYERS.register("crf_decoding")
class CRFDecodingLayer(Layer):
    """Viterbi decode (CRFDecodingLayer.cpp). inputs: [emission] (+ optional
    label -> emits 0/1 error per token instead, like the reference)."""

    def build(self, in_specs):
        n = self.conf.size or in_specs[0].size
        pcs = {"w0": self.weight_conf(0, (n + 2, n))}
        out_dim = (1,)
        return Spec(dim=out_dim, is_seq=True, is_ids=True), pcs

    def forward(self, params, inputs, ctx):
        emit = inputs[0]
        paths, _ = crf_ops.crf_decode(emit.value, emit.seq_lens, params["w0"])
        if len(inputs) > 1:
            label = inputs[1]
            err = (paths != label.ids).astype(jnp.float32)[..., None]
            return Arg(value=err, seq_lens=emit.seq_lens)
        return Arg(ids=paths, seq_lens=emit.seq_lens)


@LAYERS.register("ctc", "warp_ctc")
class CTCLayer(CostLayerBase):
    """CTC loss (CTCLayer.cpp / WarpCTCLayer.cpp). inputs:
    [logits or probs (seq [B,T,C]), label (seq ids)]. attrs:
    blank (default 0), norm_by_times, apply_softmax (default True:
    input is pre-softmax logits, as warpctc expects)."""

    def build(self, in_specs):
        return Spec(dim=(1,), is_seq=False), {}

    def forward(self, params, inputs, ctx):
        logits, label = inputs
        a = self.conf.attrs
        lp = (
            jax.nn.log_softmax(logits.value, axis=-1)
            if a.get("apply_softmax", True)
            else jnp.log(jnp.maximum(logits.value, 1e-20))
        )
        nll = ctc_ops.ctc_loss(
            lp,
            logits.seq_lens,
            label.ids,
            label.seq_lens,
            blank=a.get("blank", 0),
        )
        if a.get("norm_by_times", False):
            nll = nll / jnp.maximum(logits.seq_lens, 1).astype(nll.dtype)
        return Arg(value=self.conf.attrs.get("coeff", 1.0) * nll)
