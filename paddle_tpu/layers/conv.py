"""Convolution layers.

Reference: gserver/layers/{ExpandConvLayer,CudnnConvBaseLayer,ConvTransLayer}
with im2col+GEMM / cuDNN kernels (function/GemmConvOp.cpp,
cuda/src/hl_cuda_cudnn.cc). TPU-first: a single `lax.conv_general_dilated`
in NHWC layout — XLA tiles it straight onto the MXU; no im2col, no backend
dispatch, grouped/depthwise via feature_group_count
(function/DepthwiseConvOp.cpp parity).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.registry import LAYERS
from paddle_tpu.layers.base import Layer, Spec


def _pair(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v)


def conv_out_size(in_size, filt, stride, pad):
    return (in_size + 2 * pad - filt) // stride + 1


def _image_shape(name, s, attrs):
    """(H, W, C) of the input. Flat inputs (v1 configs declare
    data_layer(size=H*W*C), and fc outputs feeding the GAN deconv
    stack are flat) infer a square image from num_channels — the
    reference config_parser's img_pixels = sqrt(size/channels) rule."""
    if isinstance(s.dim, tuple) and len(s.dim) == 3:
        return s.dim
    size = s.dim if isinstance(s.dim, int) else 1
    if not isinstance(s.dim, int):
        for d in s.dim:
            size *= d
    c = attrs.get("num_channels")
    if not c:
        raise ValueError(
            f"conv '{name}': flat input of size {size} "
            "needs num_channels to infer the image shape"
        )
    hw = int(round((size / c) ** 0.5))
    if hw * hw * c != size:
        raise ValueError(
            f"conv '{name}': input size {size} is not "
            f"a square image with {c} channels"
        )
    return (hw, hw, c)


@LAYERS.register("exconv", "cudnn_conv", "conv")
class ConvLayer(Layer):
    """2-D convolution. attrs: num_filters (or conf.size used as out dim),
    filter_size, stride=1, padding=0, groups=1, dilation=1.
    Input spec dim must be (H, W, C)."""

    def build(self, in_specs):
        (s,) = in_specs
        a = self.conf.attrs
        h, w, c = _image_shape(self.conf.name, s, a)
        fh, fw = _pair(a.get("filter_size", 3))
        sh, sw = _pair(a.get("stride", 1))
        ph, pw = _pair(a.get("padding", 0))
        dh, dw = _pair(a.get("dilation", 1))
        groups = a.get("groups", 1)
        nf = a.get("num_filters", self.conf.size)
        oh = conv_out_size(h, dh * (fh - 1) + 1, sh, ph)
        ow = conv_out_size(w, dw * (fw - 1) + 1, sw, pw)
        pcs = {"w0": self.weight_conf(0, (fh, fw, c // groups, nf))}
        if pcs["w0"].initial_std is None:
            # match reference conv init: std = sqrt(2 / (fan_in))
            pcs["w0"].initial_std = (2.0 / (fh * fw * c / groups)) ** 0.5
        b = self.bias_conf((nf,))
        if b is not None:
            pcs["b"] = b
        self._shape = (h, w, c)
        return Spec(dim=(oh, ow, nf), is_seq=s.is_seq), pcs

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        a = self.conf.attrs
        sh, sw = _pair(a.get("stride", 1))
        ph, pw = _pair(a.get("padding", 0))
        dh, dw = _pair(a.get("dilation", 1))
        groups = a.get("groups", 1)
        x = arg.value
        x = x.reshape((x.shape[0],) + self._shape)
        y = lax.conv_general_dilated(
            x,
            params["w0"],
            window_strides=(sh, sw),
            padding=((ph, ph), (pw, pw)),
            rhs_dilation=(dh, dw),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=groups,
            # float32 accumulation for float32 inputs; bf16 (AMP) inputs
            # keep bf16 outputs so activations stay half-width in HBM
            preferred_element_type=(
                None if x.dtype == jnp.bfloat16 else jnp.float32
            ),
        )
        if "b" in params:
            y = y + params["b"]
        y = self.apply_activation_and_dropout(y, ctx, arg.seq_lens)
        return Arg(value=y, seq_lens=arg.seq_lens)


@LAYERS.register("exconvt", "conv_trans", "cudnn_convt")
class ConvTransLayer(Layer):
    """Transposed conv (gserver/layers/ConvTransLayer.cpp et al.)."""

    def build(self, in_specs):
        (s,) = in_specs
        a = self.conf.attrs
        h, w, c = _image_shape(self.conf.name, s, a)
        fh, fw = _pair(a.get("filter_size", 3))
        sh, sw = _pair(a.get("stride", 1))
        ph, pw = _pair(a.get("padding", 0))
        nf = a.get("num_filters", self.conf.size)
        oh = sh * (h - 1) + fh - 2 * ph
        ow = sw * (w - 1) + fw - 2 * pw
        pcs = {"w0": self.weight_conf(0, (fh, fw, nf, c))}
        b = self.bias_conf((nf,))
        if b is not None:
            pcs["b"] = b
        self._shape = (h, w, c)
        return Spec(dim=(oh, ow, nf), is_seq=s.is_seq), pcs

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        a = self.conf.attrs
        fh, fw = _pair(a.get("filter_size", 3))
        sh, sw = _pair(a.get("stride", 1))
        ph, pw = _pair(a.get("padding", 0))
        x = arg.value.reshape((arg.value.shape[0],) + self._shape)
        # transposed conv as the gradient of conv: input dilation by stride,
        # spatially-flipped kernel, padding k-1-p. Output (h-1)*s + k - 2p.
        w = params["w0"]  # (fh, fw, nf, c)
        w = jnp.flip(w, axis=(0, 1)).transpose(0, 1, 3, 2)  # -> (fh, fw, c, nf)
        y = lax.conv_general_dilated(
            x,
            w,
            window_strides=(1, 1),
            padding=((fh - 1 - ph, fh - 1 - ph), (fw - 1 - pw, fw - 1 - pw)),
            lhs_dilation=(sh, sw),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            # float32 accumulation for float32 inputs; bf16 (AMP) inputs
            # keep bf16 outputs so activations stay half-width in HBM
            preferred_element_type=(
                None if x.dtype == jnp.bfloat16 else jnp.float32
            ),
        )
        if "b" in params:
            y = y + params["b"]
        y = self.apply_activation_and_dropout(y, ctx, arg.seq_lens)
        return Arg(value=y, seq_lens=arg.seq_lens)


@LAYERS.register("conv_operator")
class ConvOperatorLayer(Layer):
    """Dynamic-filter 2-D conv (trainer_config_helpers conv_operator;
    gserver ConvOperator.cpp as a mixed-layer term): inputs
    [img, filter] where the FILTER VALUES are a graph output
    [B, fh*fw*C*NF] — each example is convolved with its own filter;
    the operator has no learned parameters of its own. attrs:
    num_filters, num_channels, filter_size, stride, padding, trans
    (conv_transpose)."""

    def build(self, in_specs):
        s = in_specs[0]
        a = self.conf.attrs
        h, w, c = _image_shape(self.conf.name, s, a)
        fh, fw = _pair(a.get("filter_size", 3))
        sh, sw = _pair(a.get("stride", 1))
        ph, pw = _pair(a.get("padding", 0))
        nf = a["num_filters"]
        exp = fh * fw * c * nf
        assert in_specs[1].size == exp, (
            f"conv_operator {self.name}: filter input is "
            f"{in_specs[1].size} wide, need fh*fw*C*NF = {exp}"
        )
        if a.get("trans"):
            oh = (h - 1) * sh - 2 * ph + fh
            ow = (w - 1) * sw - 2 * pw + fw
        else:
            oh = conv_out_size(h, fh, sh, ph)
            ow = conv_out_size(w, fw, sw, pw)
        self._shape = (h, w, c)
        return Spec(dim=(oh, ow, nf)), {}

    def forward(self, params, inputs, ctx):
        import jax
        from jax import lax

        img, filt = inputs
        a = self.conf.attrs
        fh, fw = _pair(a.get("filter_size", 3))
        sh, sw = _pair(a.get("stride", 1))
        ph, pw = _pair(a.get("padding", 0))
        nf = a["num_filters"]
        h, w, c = self._shape
        x = img.value.reshape(-1, h, w, c)
        f = filt.value.reshape(-1, fh, fw, c, nf)
        dn = ("NHWC", "HWIO", "NHWC")
        pad = [(ph, ph), (pw, pw)]

        def one(xb, fb):
            if a.get("trans"):
                return lax.conv_transpose(
                    xb[None], fb, (sh, sw), pad, dimension_numbers=dn
                )[0]
            return lax.conv_general_dilated(
                xb[None], fb, (sh, sw), pad, dimension_numbers=dn
            )[0]

        return Arg(value=jax.vmap(one)(x, f))
