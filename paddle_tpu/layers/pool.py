"""Spatial pooling layers.

Reference: gserver/layers/{PoolLayer,CudnnPoolLayer,SpatialPyramidPoolLayer,
MaxOutLayer}.cpp. TPU-first: `lax.reduce_window`, which XLA lowers to
vectorized windows — one impl for what the reference has three of
(CPU / CUDA hand kernel / cuDNN).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.registry import LAYERS
from paddle_tpu.layers.base import Layer, Spec
from paddle_tpu.layers.conv import _pair, conv_out_size


def _pool2d(x, kind, window, stride, pad):
    kh, kw = window
    sh, sw = stride
    ph, pw = pad
    dims = (1, kh, kw, 1)
    strides = (1, sh, sw, 1)
    padding = ((0, 0), (ph, ph), (pw, pw), (0, 0))
    if kind in ("max", "max-projection", "cudnn-max-pool"):
        init = -jnp.inf
        y = lax.reduce_window(x, init, lax.max, dims, strides, padding)
        return y
    # average pooling, excluding padding from the divisor (cuDNN
    # avg-pool-exclude-padding semantics, the reference's AvgPooling)
    summed = lax.reduce_window(x, 0.0, lax.add, dims, strides, padding)
    ones = jnp.ones_like(x[..., :1])
    counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, padding)
    return summed / counts


@LAYERS.register("pool", "cudnn_pool")
class PoolLayer(Layer):
    """attrs: pool_type in {max, avg}, pool_size, stride, padding.
    Input spec dim (H, W, C)."""

    def build(self, in_specs):
        (s,) = in_specs
        h, w, c = s.dim
        a = self.conf.attrs
        kh, kw = _pair(a.get("pool_size", 2))
        sh, sw = _pair(a.get("stride", a.get("pool_size", 2)))
        ph, pw = _pair(a.get("padding", 0))
        oh = conv_out_size(h, kh, sh, ph)
        ow = conv_out_size(w, kw, sw, pw)
        self._shape = (h, w, c)
        return Spec(dim=(oh, ow, c), is_seq=s.is_seq), {}

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        a = self.conf.attrs
        kind = a.get("pool_type", "max")
        window = _pair(a.get("pool_size", 2))
        stride = _pair(a.get("stride", a.get("pool_size", 2)))
        pad = _pair(a.get("padding", 0))
        x = arg.value.reshape((arg.value.shape[0],) + self._shape)
        y = _pool2d(x, kind, window, stride, pad)
        return Arg(value=y, seq_lens=arg.seq_lens)


@LAYERS.register("maxout")
class MaxOutLayer(Layer):
    """Max over `groups` channels (gserver/layers/MaxOutLayer.cpp)."""

    def build(self, in_specs):
        (s,) = in_specs
        h, w, c = s.dim
        g = self.conf.attrs["groups"]
        self._shape = (h, w, c)
        return Spec(dim=(h, w, c // g), is_seq=s.is_seq), {}

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        g = self.conf.attrs["groups"]
        x = arg.value.reshape((arg.value.shape[0],) + self._shape)
        b, h, w, c = x.shape
        y = x.reshape(b, h, w, c // g, g).max(axis=-1)
        return Arg(value=y, seq_lens=arg.seq_lens)


@LAYERS.register("spp")
class SpatialPyramidPoolLayer(Layer):
    """SPP (gserver/layers/SpatialPyramidPoolLayer.cpp): pyramid of
    pool levels concat'd to a fixed-length vector. attrs: pyramid_height,
    pool_type."""

    def build(self, in_specs):
        (s,) = in_specs
        h, w, c = s.dim
        ph = self.conf.attrs.get("pyramid_height", 3)
        total = sum((2**l) * (2**l) for l in range(ph)) * c
        self._shape = (h, w, c)
        return Spec(dim=(total,), is_seq=s.is_seq), {}

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        ph = self.conf.attrs.get("pyramid_height", 3)
        kind = self.conf.attrs.get("pool_type", "max")
        x = arg.value.reshape((arg.value.shape[0],) + self._shape)
        b, h, w, c = x.shape
        outs = []
        for l in range(ph):
            bins = 2**l
            kh, kw = -(-h // bins), -(-w // bins)  # ceil
            sh, sw = kh, kw
            pad_h, pad_w = (kh * bins - h + 1) // 2, (kw * bins - w + 1) // 2
            y = _pool2d(x, kind, (kh, kw), (sh, sw), (pad_h, pad_w))
            outs.append(y.reshape(b, -1))
        return Arg(value=jnp.concatenate(outs, axis=-1), seq_lens=arg.seq_lens)


@LAYERS.register("blockexpand", "block_expand")
class BlockExpandLayer(Layer):
    """Image -> sequence of patches (gserver/layers/BlockExpandLayer.cpp,
    function/BlockExpandOp.cpp): each output timestep is one [bh*bw*C]
    block, scanned row-major."""

    def build(self, in_specs):
        (s,) = in_specs
        h, w, c = s.dim
        a = self.conf.attrs
        bh, bw = _pair(a["block"])
        sh, sw = _pair(a.get("stride", a["block"]))
        ph, pw = _pair(a.get("padding", 0))
        oh = conv_out_size(h, bh, sh, ph)
        ow = conv_out_size(w, bw, sw, pw)
        self._shape = (h, w, c)
        self._steps = oh * ow
        return Spec(dim=(bh * bw * c,), is_seq=True), {}

    def forward(self, params, inputs, ctx):
        (arg,) = inputs
        a = self.conf.attrs
        bh, bw = _pair(a["block"])
        sh, sw = _pair(a.get("stride", a["block"]))
        ph, pw = _pair(a.get("padding", 0))
        x = arg.value.reshape((arg.value.shape[0],) + self._shape)
        patches = lax.conv_general_dilated_patches(
            x,
            filter_shape=(bh, bw),
            window_strides=(sh, sw),
            padding=((ph, ph), (pw, pw)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )  # [B, OH, OW, bh*bw*C]
        b = patches.shape[0]
        seq = patches.reshape(b, self._steps, -1)
        lens = jnp.full((b,), self._steps, jnp.int32)
        return Arg(value=seq, seq_lens=lens)
