"""Fused attention-decoder recurrence for the NMT north star.

The generic recurrent_group executor lowers the 2017 Bahdanau decoder
step (models/text.py _attention_decoder_state_step) to ~10 small XLA
ops per scan iteration; at bs=256/T=32 the train step is bound by that
serial chain, not FLOPs (PERF.md roofline: ~0.55 ms/iteration measured
vs <0.1 ms roofline). This layer computes IDENTICAL math with the
loop-invariant work hoisted out of the scan and the prev-dependent
GEMMs merged, shortening the per-iteration critical path:

- the cell's input projection emb_t @ W0 + b runs once for all steps
  as one [B*T, E] @ [E, H] GEMM (teacher forcing makes the whole
  target embedding sequence available up front);
- the context projection moves across the attention sum by linearity:
  ctx @ W2 = sum_j a_j (enc_j @ W2), so enc @ W2 is precomputed once
  and the per-step [B,H]@[H,H] GEMM disappears;
- the two prev-dependent projections (attention query `_att_dec_proj`
  and cell recurrence `_dec_state.w1`) run as ONE [B,H] @ [H,2H] GEMM
  per step.

Parameter NAMES and SHAPES are exactly the unfused graph's
(`_dec_state.w0/w1/w2/wbias`, `_att_dec_proj.w0`, `_att_score.w0`),
so checkpoints interoperate and the beam-search generation decoder
(which runs the unfused step net, models/text.py
seq2seq_attention_decoder) shares the trained weights untouched.

Reference: demo/seqToseq/seqToseq_net.py gru_decoder_with_attention +
trainer_config_helpers/networks.py:1298 simple_attention (the additive
attention this reproduces).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.config import ParameterConf
from paddle_tpu.core.registry import LAYERS
from paddle_tpu.layers.base import Layer, Spec


@LAYERS.register("fused_att_decoder")
class FusedAttDecoderLayer(Layer):
    """inputs: [trg_emb (B,T,E) seq, enc (B,S,H) seq, boot (B,H)];
    output: decoder states (B,T,H) seq (project to vocab outside the
    scan, as seq2seq_attention does)."""

    def build(self, in_specs):
        se, sc, sb = in_specs
        h = self.conf.size or sc.size
        assert sc.size == h and sb.size == h, (
            f"fused_att_decoder: enc/boot width must equal size={h}, "
            f"got {sc.size}/{sb.size}"
        )
        e = se.size
        prefix = self.conf.attrs.get("param_prefix", "dec_state")
        att = self.conf.attrs.get("att_prefix", "att")

        def pc(name, dims):
            return ParameterConf(name=name, dims=tuple(dims))

        pcs = {
            "w_emb": pc(f"_{prefix}.w0", (e, h)),
            "w_prev": pc(f"_{prefix}.w1", (h, h)),
            "w_ctx": pc(f"_{prefix}.w2", (h, h)),
            "w_att": pc(f"_{att}_dec_proj.w0", (h, h)),
            "v": pc(f"_{att}_score.w0", (h, 1)),
        }
        if self.conf.bias:
            pcs["b"] = pc(f"_{prefix}.wbias", (h,))
        self._h = h
        return Spec(dim=(h,), is_seq=True), pcs

    def forward(self, params, inputs, ctx):
        emb, enc, boot = inputs
        h = self._h
        x = emb.value  # [B,T,E]
        encv = enc.value  # [B,S,H]
        b = params.get("b", jnp.zeros((h,), x.dtype))
        # hoisted: input projection for ALL steps, one big GEMM
        xp = jnp.einsum("bte,eh->bth", x, params["w_emb"]) + b
        # hoisted: context projection moved across the attention sum
        encW2 = jnp.einsum("bsh,hk->bsk", encv, params["w_ctx"])
        # one merged prev-projection per step: [cell | attention query]
        wp = jnp.concatenate([params["w_prev"], params["w_att"]], axis=1)
        v = params["v"][:, 0]  # [H]
        s_len = encv.shape[1]
        smask = (
            jnp.arange(s_len)[None, :] < enc.seq_lens[:, None]
            if enc.seq_lens is not None
            else jnp.ones((encv.shape[0], s_len), bool)
        )

        def step(prev, x_t):
            ph = jnp.dot(prev, wp)  # [B,2H]
            q = ph[:, h:]
            e = jnp.einsum(
                "bsh,h->bs", jnp.tanh(encv + q[:, None, :]), v
            )
            e = jnp.where(smask, e, jnp.asarray(-1e30, e.dtype))
            a = jax.nn.softmax(e, axis=-1)
            ctx_w2 = jnp.einsum("bs,bsh->bh", a, encW2)
            s = jnp.tanh(x_t + ph[:, :h] + ctx_w2)
            return s, s

        xs = xp.swapaxes(0, 1)  # [T,B,H]
        _, ys = lax.scan(step, boot.value, xs)
        return Arg(value=ys.swapaxes(0, 1), seq_lens=emb.seq_lens)
