"""MoE layer: sparsely-activated expert FFN with load-balancing loss.

Beyond-reference capability (expert parallelism). The layer emits its
aux load-balancing loss as an extra output `<name>@aux` that the DSL
wires into a sum_cost, so the trainer's multi-cost reduction (the same
mechanism the VAE demo uses) applies it; expert weights carry an
"expert" leading dim that parallel/sharding can place on the mesh model
axis for EP.
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.registry import LAYERS
from paddle_tpu.layers.base import Layer, Spec
from paddle_tpu.ops import moe as moe_ops
from paddle_tpu.ops import activations


@LAYERS.register("moe")
class MoELayer(Layer):
    """attrs: num_experts, hidden (expert FFN width), capacity_factor,
    expert_act. size = output dim (== input dim). Params: router w0
    [D, E]; experts w_in [E, D, H], w_out [E, H, D]."""

    def build(self, in_specs):
        (s,) = in_specs
        d = s.size
        a = self.conf.attrs
        E = a["num_experts"]
        H = a.get("hidden") or 4 * d
        pcs = {
            "w0": self.weight_conf(0, (d, E)),
            "w_in": self.weight_conf(0, (E, d, H)),
            "w_out": self.weight_conf(0, (E, H, d)),
        }
        # distinct auto-names for the three slots
        pcs["w_in"].name = pcs["w0"].name + "_in"
        pcs["w_out"].name = pcs["w0"].name + "_out"
        pcs["w_in"].expert_sharded = True
        pcs["w_out"].expert_sharded = True
        # per-expert fan-in: each token multiplies ONE [D,H] slice, so
        # std is 1/sqrt(D) (init_parameter's prod(dims[:-1]) would give
        # 1/sqrt(E*D) — E-times too small). User-set std wins.
        if pcs["w_in"].initial_std is None:
            pcs["w_in"].initial_std = 1.0 / (d ** 0.5)
        if pcs["w_out"].initial_std is None:
            pcs["w_out"].initial_std = 1.0 / (H ** 0.5)
        self._spec = s
        return s, pcs

    def extra_output_specs(self):
        return {f"{self.name}@aux": Spec(dim=(1,))}

    def forward(self, params, inputs, ctx):
        (x,) = inputs
        a = self.conf.attrs
        act = activations.get(a.get("expert_act", "relu"))
        v = x.value
        lead = v.shape[:-1]
        flat = v.reshape(-1, v.shape[-1])
        # padded tokens are excluded from routing itself (capacity and
        # balance statistics), not just output-masked
        token_mask = (
            x.mask(v.dtype).reshape(-1) if x.is_seq else None
        )
        y, aux = moe_ops.moe_ffn(
            flat,
            params["w0"],
            params["w_in"],
            params["w_out"],
            capacity_factor=a.get("capacity_factor", 1.25),
            activation=act,
            token_mask=token_mask,
        )
        y = y.reshape(lead + (-1,))
        self._extra_outs = {
            f"{self.name}@aux": Arg(value=jnp.broadcast_to(aux, (1, 1)))
        }
        return Arg(value=y, seq_lens=x.seq_lens)
