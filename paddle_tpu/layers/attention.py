"""Multi-head attention layer with pluggable sequence parallelism.

Long-context capability layer (beyond the reference's 2017 additive
attention built from mixed/expand layers in
trainer_config_helpers/networks.py:1298 simple_attention — which is also
reproduced, via models/text.py). attrs:
  num_heads     — head count (must divide size)
  causal        — bool, autoregressive mask
  attn_impl     — "dense" (materializes [B,H,T,T] scores — the 2017
                  reference path) | "flash" (no score matrix in HBM:
                  Pallas TPU kernel, portable blocked lowering
                  elsewhere — the measured long-context path, PERF.md
                  round 8). Applies to seq_parallel "none" (whole
                  attention) and "ulysses" (the local per-head-group
                  attention); "ring" is flash-class by construction.
  seq_parallel  — "none" (single-chip) | "ring" | "ulysses";
                  ring/ulysses shard the time dim over the mesh `seq`
                  axis (parallel/ring.py) and need the global mesh set via
                  paddle_tpu.core.mesh.set_mesh.
Inputs: one sequence Arg (self-attention) or (query, keyvalue).
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.registry import LAYERS
from paddle_tpu.layers.base import Ctx, Layer, Spec


@LAYERS.register("multi_head_attention", "attention")
class MultiHeadAttentionLayer(Layer):
    def build(self, in_specs):
        d = self.conf.size
        h = self.conf.attrs.get("num_heads", 1)
        assert d % h == 0, f"size {d} not divisible by num_heads {h}"
        sq = in_specs[0]
        skv = in_specs[-1]
        assert sq.is_seq and skv.is_seq, "attention needs sequence inputs"
        # distinct names per projection — weight_conf(idx) keys on the
        # input edge, which would alias all four for self-attention
        pcs = {}
        for slot, idx, dims in (
            ("wq", 0, (sq.size, d)),
            ("wk", len(in_specs) - 1, (skv.size, d)),
            ("wv", len(in_specs) - 1, (skv.size, d)),
            ("wo", 0, (d, d)),
        ):
            pc = self.weight_conf(idx, dims)
            pc.name = f"_{self.name}.{slot}"
            pcs[slot] = pc
        b = self.bias_conf((d,))
        if b is not None:
            pcs["b"] = b
        return Spec(dim=(d,), is_seq=True), pcs

    def forward(self, params, inputs, ctx: Ctx):
        qa = inputs[0]
        kva = inputs[-1]
        h = self.conf.attrs.get("num_heads", 1)
        causal = bool(self.conf.attrs.get("causal", False))
        mode = self.conf.attrs.get("seq_parallel", "none")
        d = self.conf.size
        hd = d // h

        def split_heads(x):
            return x.reshape(x.shape[0], x.shape[1], h, hd)

        q = split_heads(jnp.dot(qa.value, params["wq"]))
        k = split_heads(jnp.dot(kva.value, params["wk"]))
        v = split_heads(jnp.dot(kva.value, params["wv"]))

        from paddle_tpu.parallel import ring

        def _get_mesh():
            from paddle_tpu.core.mesh import get_mesh

            return get_mesh()

        impl = self.conf.attrs.get("attn_impl", "dense")
        if mode == "none":
            # attn_impl "flash" never materializes the [B,H,T,T]
            # scores (Pallas TPU kernel; portable blocked lowering on
            # other backends) — the long-context lever; "dense" stays
            # the default (the 2017-semantics reference path)
            if impl == "flash":
                out = ring.flash_dense_attention(
                    q, k, v, causal=causal, kv_len=kva.seq_lens,
                    q_len=qa.seq_lens if qa is not kva else None,
                )
            else:
                out = ring.dense_attention(
                    q, k, v, causal=causal, kv_len=kva.seq_lens
                )
        elif mode == "ring":
            # ring attention IS flash-class already (online softmax,
            # no [T,T] scores) — attn_impl does not apply
            out = ring.ring_attention(
                q, k, v, _get_mesh(), causal=causal,
                kv_lens=kva.seq_lens,
            )
        else:
            out = ring.ulysses_attention(
                q, k, v, _get_mesh(), causal=causal,
                kv_lens=kva.seq_lens, attn_impl=impl,
            )
        out = out.reshape(out.shape[0], out.shape[1], d)
        y = jnp.dot(out, params["wo"])
        if "b" in params:
            y = y + params["b"]
        y = self.apply_activation_and_dropout(y, ctx, qa.seq_lens)
        # zero padded query positions so downstream seq reductions stay exact
        if qa.seq_lens is not None:
            t = y.shape[1]
            pos = jnp.arange(t)[None, :]
            y = jnp.where((pos < qa.seq_lens[:, None])[..., None], y, 0.0)
        return Arg(value=y, seq_lens=qa.seq_lens)
