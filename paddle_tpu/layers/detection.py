"""SSD detection layers: priorbox, multibox_loss, detection_output.

Reference: gserver/layers/{PriorBox,MultiBoxLossLayer,DetectionOutputLayer}
.cpp. Ground truth arrives as fixed-shape Args — boxes [B, G, 4] plus
labels [B, G] ids with seq_lens giving the per-image ground-truth count —
instead of the reference's variable-length label sequences; everything
stays jittable (see ops/detection.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.registry import LAYERS
from paddle_tpu.layers.base import Layer, Spec
from paddle_tpu.layers.cost import CostLayerBase
from paddle_tpu.ops import detection as D


@LAYERS.register("priorbox")
class PriorBoxLayer(Layer):
    """inputs: [feature_map(conv, HWC dim), image(data, HWC dim)];
    attrs: min_size, max_size, aspect_ratio, variance, flip, clip.
    Output: [B, P*8] prior (box4, var4) rows — constant per shape, folded
    by XLA (PriorBox.cpp:79)."""

    def build(self, in_specs):
        feat, img = in_specs
        assert len(feat.dim) == 3, "priorbox needs an (H,W,C) feature map"
        assert len(img.dim) == 3, "priorbox needs an (H,W,C) image input"
        a = self.conf.attrs
        self._priors = D.prior_boxes(
            layer_hw=feat.dim[:2],
            image_hw=img.dim[:2],
            min_sizes=list(a.get("min_size", [])),
            max_sizes=list(a.get("max_size", [])),
            aspect_ratios=list(a.get("aspect_ratio", [])),
            variances=list(a.get("variance", (0.1, 0.1, 0.2, 0.2))),
            flip=a.get("flip", True),
            clip=a.get("clip", True),
        )
        self.num_priors = self._priors.shape[0]
        return Spec(dim=(self.num_priors * 8,)), {}

    def forward(self, params, inputs, ctx):
        b = inputs[0].batch
        flat = jnp.asarray(self._priors.reshape(-1))
        return Arg(value=jnp.broadcast_to(flat, (b, flat.shape[0])))


def _split_priors(prior_arg: Arg):
    pr = prior_arg.value[0].reshape(-1, 8)  # identical across batch
    return pr[:, :4], pr[:, 4:]


@LAYERS.register("multibox_loss")
class MultiBoxLossLayer(CostLayerBase):
    """inputs: [priorbox, label_boxes([B,G,4] seq), label_ids([B,G] ids
    seq), loc_pred([B,P*4]), conf_pred([B,P*C])]; attrs: num_classes,
    overlap_threshold, neg_pos_ratio, neg_overlap, background_id.

    Per-batch cost matches MultiBoxLossLayer.cpp:207,259:
    (smoothL1_sum + conf_ce_sum) / num_matches, computed fully on device.
    """

    def forward(self, params, inputs, ctx):
        prior, gt_box, gt_label, loc, conf = inputs
        a = self.conf.attrs
        if a.get("packed_label"):
            # the v1 packed ground-truth record: per box
            # [label, x1, y1, x2, y2, difficult]; split on device
            packed = gt_box.value.reshape(
                gt_box.value.shape[0], -1, 6
            )
            gt_label = Arg(
                ids=packed[..., 0].astype(jnp.int32),
                seq_lens=gt_box.seq_lens,
            )
            gt_box = Arg(value=packed[..., 1:5],
                         seq_lens=gt_box.seq_lens)
        C = a["num_classes"]
        priors, variances = _split_priors(prior)
        P = priors.shape[0]
        loc_pred = loc.value.reshape(-1, P, 4)
        conf_pred = conf.value.reshape(-1, P, C)
        boxes = gt_box.value  # [B, G, 4]
        labels = gt_label.ids  # [B, G]
        G = boxes.shape[1]
        mask = (
            jnp.arange(G)[None, :] < gt_box.seq_lens[:, None]
        ).astype(jnp.float32)

        def per_image(lp, cp, bx, lb, mk):
            return D.multibox_loss(
                lp,
                cp,
                priors,
                variances,
                bx,
                lb,
                mk,
                overlap_threshold=a.get("overlap_threshold", 0.5),
                neg_pos_ratio=a.get("neg_pos_ratio", 3.0),
                neg_overlap=a.get("neg_overlap", 0.5),
                background_id=a.get("background_id", 0),
            )

        loc_l, conf_l, n_pos = jax.vmap(per_image)(
            loc_pred, conf_pred, boxes, labels, mask
        )
        denom = jnp.maximum(jnp.sum(n_pos).astype(jnp.float32), 1.0)
        # loss_fn takes the batch MEAN of per-example costs; scale by B so
        # the total equals (loc_sum + conf_sum) / num_matches exactly like
        # locLoss_/confLoss_ in MultiBoxLossLayer.cpp:207,259
        per_img = (loc_l + conf_l) * (loc_l.shape[0] / denom)
        w = self.conf.attrs.get("coeff", 1.0)
        return Arg(value=w * per_img)


@LAYERS.register("detection_output")
class DetectionOutputLayer(Layer):
    """inputs: [priorbox, loc_pred, conf_pred]; attrs: num_classes,
    nms_threshold, nms_top_k, keep_top_k, confidence_threshold,
    background_id. Output [B, keep_top_k*6]; rows (label, score, box4),
    score==0 marks padding (DetectionOutputLayer.cpp)."""

    def build(self, in_specs):
        a = self.conf.attrs
        self._keep = a.get("keep_top_k", 200)
        return Spec(dim=(self._keep * 6,)), {}

    def forward(self, params, inputs, ctx):
        prior, loc, conf = inputs
        a = self.conf.attrs
        C = a["num_classes"]
        priors, variances = _split_priors(prior)
        P = priors.shape[0]
        loc_pred = loc.value.reshape(-1, P, 4)
        conf_pred = conf.value.reshape(-1, P, C)

        def per_image(lp, cp):
            return D.detection_output(
                lp,
                cp,
                priors,
                variances,
                num_classes=C,
                background_id=a.get("background_id", 0),
                nms_threshold=a.get("nms_threshold", 0.45),
                nms_top_k=a.get("nms_top_k", 400),
                keep_top_k=self._keep,
                confidence_threshold=a.get("confidence_threshold", 0.01),
            )

        dets = jax.vmap(per_image)(loc_pred, conf_pred)  # [B,K,6]
        return Arg(value=dets.reshape(dets.shape[0], -1))
