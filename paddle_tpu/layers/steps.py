"""Single-step recurrent cells for custom recurrence inside groups.

Reference: gserver/layers/GruStepLayer.cpp:22-36 and
LstmStepLayer.cpp:45 — the cell math of GatedRecurrentLayer/LstmLayer
exposed as one-timestep layers so a recurrent_group step net can build
custom recurrences (the seqToseq demo's decoder pattern). Parameter
layouts match the sequence layers (recurrent.py), so weights transfer.

Divergence: LstmStepLayer exposed its cell state via get_output_layer;
here lstm_step emits it as the extra output `<name>@state`.
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.registry import LAYERS
from paddle_tpu.layers.base import Layer, Spec
from paddle_tpu.ops import activations


@LAYERS.register("gru_step", "gru_step_naive")
class GruStepLayer(Layer):
    """inputs: [xg (B, 3h: update|reset|candidate pre-projection),
    prev_out (B, h)]; output h_t (GruStepLayer.cpp:22-36)."""

    def build(self, in_specs):
        sx, sp = in_specs
        h = self.conf.size or sp.size
        assert sx.size == 3 * h, (
            f"gru_step input must be 3*size, got {sx.size} vs h={h}"
        )
        pcs = {
            "w0": self.weight_conf(0, (h, 2 * h)),
            "w_c": self.weight_conf(0, (h, h)),
        }
        pcs["w_c"].name = f"_{self.name}.wc"
        b = self.bias_conf((3 * h,))
        if b is not None:
            pcs["b"] = b
        self._h = h
        return Spec(dim=(h,)), pcs

    def forward(self, params, inputs, ctx):
        xg, prev = inputs
        h = self._h
        act = activations.get(self.conf.active_type or "tanh")
        gate_act = activations.get(
            self.conf.attrs.get("active_gate_type", "sigmoid")
        )
        x = xg.value
        p = prev.value
        b = params.get("b", jnp.zeros((3 * h,), x.dtype))
        gur = jnp.dot(p, params["w0"])  # [B, 2h]
        u = gate_act(x[:, :h] + gur[:, :h] + b[:h])
        r = gate_act(x[:, h : 2 * h] + gur[:, h:] + b[h : 2 * h])
        c = act(x[:, 2 * h :] + jnp.dot(r * p, params["w_c"]) + b[2 * h :])
        out = u * p + (1.0 - u) * c
        return Arg(value=out)


@LAYERS.register("lstm_step")
class LstmStepLayer(Layer):
    """inputs: [x4 (B, 4h gate pre-projection), prev_h (B, h),
    prev_c (B, h)]; output h_t, extra `<name>@state` = c_t
    (LstmStepLayer.cpp; cell math of LstmLayer/hl_cuda_lstm)."""

    def build(self, in_specs):
        sx = in_specs[0]
        h = self.conf.size or in_specs[1].size
        assert sx.size == 4 * h, (
            f"lstm_step input must be 4*size, got {sx.size} vs h={h}"
        )
        pcs = {"w0": self.weight_conf(0, (h, 4 * h))}
        b = self.bias_conf((7 * h,))  # 4h gate biases + 3h peepholes
        if b is not None:
            pcs["b"] = b
        self._h = h
        return Spec(dim=(h,)), pcs

    def extra_output_specs(self):
        return {f"{self.name}@state": Spec(dim=(self._h,))}

    def forward(self, params, inputs, ctx):
        x4, prev_h, prev_c = inputs
        h = self._h
        act = activations.get(self.conf.active_type or "tanh")
        gate_act = activations.get(
            self.conf.attrs.get("active_gate_type", "sigmoid")
        )
        state_act = activations.get(
            self.conf.attrs.get("active_state_type", "tanh")
        )
        b = params.get("b", jnp.zeros((7 * h,), x4.value.dtype))
        gb, wci, wcf, wco = (
            b[: 4 * h],
            b[4 * h : 5 * h],
            b[5 * h : 6 * h],
            b[6 * h :],
        )
        g = x4.value + jnp.dot(prev_h.value, params["w0"]) + gb
        gi, gf, gg, go = jnp.split(g, 4, axis=-1)
        c_prev = prev_c.value
        i = gate_act(gi + wci * c_prev)
        f = gate_act(gf + wcf * c_prev)
        c = f * c_prev + i * act(gg)
        o = gate_act(go + wco * c)
        out = o * state_act(c)
        self._extra_outs = {f"{self.name}@state": Arg(value=c)}
        return Arg(value=out)
