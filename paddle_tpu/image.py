"""Image preprocessing helpers.

Reference: python/paddle/v2/image.py — load/resize_short/to_chw/
center_crop/random_crop/left_right_flip/simple_transform/
load_and_transform, all returning numpy HWC uint8 (until to_chw).
PIL-based here (the reference uses cv2)."""

from __future__ import annotations

import os

import numpy as np

__all__ = [
    "batch_images_from_tar",
    "load_image",
    "load_image_bytes",
    "resize_short",
    "to_chw",
    "center_crop",
    "random_crop",
    "left_right_flip",
    "simple_transform",
    "load_and_transform",
]


def batch_images_from_tar(
    data_file: str,
    dataset_name: str,
    img2label: dict,
    num_per_batch: int = 1024,
) -> str:
    """Read images from a tar file and group them into pickled batch
    files (reference python/paddle/v2/image.py batch_images_from_tar):
    each batch file holds {"label": [...], "data": [raw bytes, ...]}
    for up to `num_per_batch` members whose tar name appears in
    `img2label`. Batches land in `<data_file>_batch/<dataset_name>/`;
    returns the path of a meta file listing one batch-file path per
    line. An existing batch dir is reused (the reference's resume
    behavior)."""
    import pickle
    import tarfile

    batch_dir = data_file + "_batch"
    out_path = os.path.join(batch_dir, dataset_name)
    meta_file = os.path.join(batch_dir, dataset_name + ".txt")
    if os.path.exists(out_path):
        return meta_file
    os.makedirs(out_path)

    labels, data = [], []
    file_id = 0

    def flush():
        nonlocal file_id, labels, data
        with open(
            os.path.join(out_path, f"batch_{file_id}"), "wb"
        ) as f:
            pickle.dump({"label": labels, "data": data}, f,
                        protocol=2)
        file_id += 1
        labels, data = [], []

    with tarfile.open(data_file) as tf:
        for mem in tf.getmembers():
            if mem.name not in img2label:
                continue
            data.append(tf.extractfile(mem).read())
            labels.append(img2label[mem.name])
            if len(data) == num_per_batch:
                flush()
    if data:
        flush()

    with open(meta_file, "w") as meta:
        for name in sorted(os.listdir(out_path)):
            meta.write(
                os.path.abspath(os.path.join(out_path, name)) + "\n"
            )
    return meta_file


def load_image_bytes(data: bytes, is_color: bool = True) -> np.ndarray:
    import io

    from PIL import Image

    im = Image.open(io.BytesIO(data))
    im = im.convert("RGB" if is_color else "L")
    return np.asarray(im)


def load_image(path: str, is_color: bool = True) -> np.ndarray:
    from PIL import Image

    im = Image.open(path)
    im = im.convert("RGB" if is_color else "L")
    return np.asarray(im)


def resize_short(im: np.ndarray, size: int) -> np.ndarray:
    """Scale so the SHORT side equals `size` (image.py:143)."""
    from PIL import Image

    h, w = im.shape[:2]
    if h > w:
        new_w, new_h = size, int(round(h * size / w))
    else:
        new_w, new_h = int(round(w * size / h)), size
    pil = Image.fromarray(im)
    return np.asarray(pil.resize((new_w, new_h), Image.BILINEAR))


def to_chw(im: np.ndarray, order=(2, 0, 1)) -> np.ndarray:
    assert len(im.shape) == len(order)
    return im.transpose(order)


def center_crop(im: np.ndarray, size: int, is_color: bool = True):
    h, w = im.shape[:2]
    h0 = (h - size) // 2
    w0 = (w - size) // 2
    return im[h0 : h0 + size, w0 : w0 + size]


def random_crop(im: np.ndarray, size: int, is_color: bool = True,
                rng=None):
    rng = rng or np.random.default_rng()
    h, w = im.shape[:2]
    h0 = int(rng.integers(0, h - size + 1))
    w0 = int(rng.integers(0, w - size + 1))
    return im[h0 : h0 + size, w0 : w0 + size]


def left_right_flip(im: np.ndarray) -> np.ndarray:
    return im[:, ::-1]


def simple_transform(im: np.ndarray, resize_size: int, crop_size: int,
                     is_train: bool, is_color: bool = True, mean=None,
                     rng=None) -> np.ndarray:
    """resize-short -> crop (random+flip when training, center else) ->
    CHW float32 -> optional mean subtract (image.py:265)."""
    rng = rng or np.random.default_rng()
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, rng=rng)
        if rng.integers(0, 2) == 0:
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    if len(im.shape) == 3:
        im = to_chw(im)
    im = im.astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        if mean.ndim == 1:
            if im.ndim == 2:  # grayscale: collapse per-channel mean
                mean = mean.mean()
            else:
                mean = mean[:, None, None]
        im -= mean
    return im


def load_and_transform(filename: str, resize_size: int, crop_size: int,
                       is_train: bool, is_color: bool = True, mean=None):
    return simple_transform(
        load_image(filename, is_color), resize_size, crop_size, is_train,
        is_color, mean,
    )
