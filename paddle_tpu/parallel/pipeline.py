"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh
axis.

Beyond-reference capability (SURVEY.md §2 parallelism table: absent in
2017). TPU-first design: the classic SPMD pipeline — every device holds
ONE stage's parameters (stacked stage-major and sharded over the
"pipe" axis), microbatches stream through a `lax.scan` of pipeline
ticks, and activations hop stage-to-stage with `lax.ppermute` over ICI.
Because ppermute/scan are differentiable, `jax.grad` through
`pipeline_apply` IS pipelined backprop (activations rematerialized per
tick by XLA; add jax.checkpoint on stage_fn for long pipelines) — no
hand-built 1F1B schedule.

All stages must share one activation signature (same shape in/out), the
standard homogeneous-stage formulation (e.g. a stack of identical
transformer/FC blocks split across devices).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core.mesh import shard_map as _shard_map


def _pipeline_local(stage_fn, axis_name, params, xs, n_stages):
    """Runs under shard_map: `params` is THIS device's stage slice (no
    stage axis), `xs` [M, ...] the full microbatch stream (replicated).
    Returns [M, ...] outputs, valid on the LAST stage (zeros elsewhere,
    all-gathered by the caller). `n_stages` is the static axis size
    (lax.axis_size is missing on this runtime's jax 0.4.37, and the
    tick count must be static anyway)."""
    idx = lax.axis_index(axis_name)
    S = n_stages
    M = xs.shape[0]
    T = M + S - 1  # total ticks to drain the pipe

    perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        acts, outputs = carry
        # stage 0 ingests microbatch t; other stages process what the
        # previous tick handed them
        inp = jnp.where(idx == 0, xs[jnp.clip(t, 0, M - 1)], acts)
        y = stage_fn(params, inp)
        # hand to the next stage over ICI
        passed = lax.ppermute(y, axis_name, perm)
        # last stage emits microbatch t-(S-1) at this tick
        out_t = t - (S - 1)
        emit = (idx == S - 1) & (out_t >= 0)
        outputs = jnp.where(
            emit,
            outputs.at[jnp.clip(out_t, 0, M - 1)].set(y),
            outputs,
        )
        return (passed, outputs), None

    acts0 = jnp.zeros_like(stage_fn(params, xs[0]))
    outs0 = jnp.zeros((M,) + acts0.shape, acts0.dtype)
    (acts, outputs), _ = lax.scan(
        tick, (acts0, outs0), jnp.arange(T)
    )
    # only the last stage ever writes outputs (zeros elsewhere), so a
    # psum over the pipe axis replicates its values to every member
    return lax.psum(outputs, axis_name)


def pipeline_apply(
    mesh: Mesh,
    axis_name: str,
    stage_fn: Callable,
    stacked_params,
    xs: jax.Array,
    batch_axis: str | None = None,
):
    """Run the pipeline.

    stacked_params: pytree whose leaves have a leading stage axis of
    size mesh.shape[axis_name], sharded over `axis_name` (see
    `shard_stacked_params`). xs: [M, micro_batch, ...] microbatches.
    Returns [M, micro_batch, ...] outputs. Differentiable end-to-end.

    batch_axis: name of a mesh data axis to shard the micro_batch dim
    over — pp×dp in one program (each data shard streams its slice of
    every microbatch through the same pipe; stage params are replicated
    across `batch_axis`, so their gradient allreduce over data is
    inserted by shard_map's transpose automatically).
    """
    xspec = P(None, batch_axis) if batch_axis else P()
    in_specs = (
        jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params),
        xspec,
    )

    def local(params, xs):
        # shard_map hands us the [1, ...]-sliced stage params
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        return _pipeline_local(stage_fn, axis_name, params, xs,
                               mesh.shape[axis_name])

    return _shard_map(
        local,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=xspec,
        check_vma=False,
    )(stacked_params, xs)


def shard_stacked_params(mesh: Mesh, axis_name: str, stacked_params):
    """Place each stage's slice on its pipe device."""
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(
            a, NamedSharding(mesh, P(axis_name))
        ),
        stacked_params,
    )


def microbatch(x: jax.Array, n_micro: int) -> jax.Array:
    """[B, ...] -> [M, B/M, ...]."""
    assert x.shape[0] % n_micro == 0, (
        f"batch {x.shape[0]} not divisible into {n_micro} microbatches"
    )
    return x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])


def unmicrobatch(x: jax.Array) -> jax.Array:
    return x.reshape((-1,) + x.shape[2:])
