"""Parallelism over the device mesh: data (dp), tensor/model (sharding),
sequence/context (ring), sharded embeddings (sparse), and the elastic
100M–1B-row hot-cache embedding tier (sparse_shard)."""

from paddle_tpu.parallel.dp import (  # noqa: F401
    TrainStep,
    batch_sharding,
    param_sharding,
    replicated,
    shard_batch,
)
from paddle_tpu.parallel.ring import (  # noqa: F401
    dense_attention,
    ring_attention,
    ulysses_attention,
)
from paddle_tpu.parallel.sharding import (  # noqa: F401
    Sharder,
    auto_param_spec,
    constrain,
)
from paddle_tpu.parallel.sparse import (  # noqa: F401
    SparseUpdater,
    apply_rows,
    sparse_apply,
    embedding_lookup,
    touched_rows,
)
from paddle_tpu.parallel.sparse_shard import (  # noqa: F401
    ShardedEmbeddingTable,
    ShardedTableConfig,
    adagrad_row_update,
    sgd_row_update,
)
