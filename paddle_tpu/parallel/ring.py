"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

New first-class capability (absent in the 2017 reference — SURVEY.md §2
"Pipeline / TP / SP" row): long sequences are sharded over the mesh `seq`
axis. Two strategies:

- ``ring_attention``: K/V blocks rotate around the ring via
  ``lax.ppermute`` while each device keeps its Q shard; softmax is
  accumulated online (flash-attention style running max/denominator), so
  the full [T, T] score matrix never materializes and comm rides ICI
  neighbor links.
- ``ulysses_attention``: ``lax.all_to_all`` reshards seq -> heads, runs
  dense local attention per head group, and reshards back.

Both are numerically identical to dense masked attention (tested on a
virtual CPU mesh in tests/test_parallel_tp_sp.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core.mesh import SEQ_AXIS

NEG_INF = -1e30


def dense_attention(q, k, v, *, causal=False, kv_len=None, scale=None):
    """Reference masked attention. q,k,v: [B, T, H, D]; kv_len: [B] valid
    K/V length (padding masked out)."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(D).astype(q.dtype)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    mask = jnp.zeros((B, 1, Tq, Tk), q.dtype)
    if kv_len is not None:
        pad = jnp.arange(Tk)[None, :] >= kv_len[:, None]  # [B, Tk]
        mask = jnp.where(pad[:, None, None, :], NEG_INF, mask)
    if causal:
        qpos = jnp.arange(Tq)[:, None]
        kpos = jnp.arange(Tk)[None, :]
        mask = mask + jnp.where(kpos > qpos, NEG_INF, 0.0)
    p = jax.nn.softmax(s + mask, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def flash_dense_attention(q, k, v, *, causal=False, kv_len=None,
                          scale=None):
    """Single-chip flash attention (jax.experimental.pallas TPU
    kernel): same contract as dense_attention — q,k,v [B, T, H, D],
    kv_len [B] — but never materializes the [B, H, T, T] score matrix
    in HBM (the bandwidth bound of the dense path at long T). Padding
    is masked via segment ids (pad tokens get segment 0, valid get 1,
    and cross-segment attention is masked by the kernel); padded QUERY
    rows still emit garbage, which the attention layer zeroes after
    the output projection exactly as in the dense path."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        SegmentIds,
        flash_attention as _flash,
    )

    B, T, H, D = q.shape
    scale = (
        float(scale)
        if scale is not None
        else 1.0 / float(jnp.sqrt(jnp.float32(D)))
    )
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    seg = None
    if kv_len is not None:
        ids = (
            jnp.arange(T)[None, :] < kv_len[:, None]
        ).astype(jnp.int32)
        seg = SegmentIds(q=ids, kv=ids)
    o = _flash(qt, kt, vt, segment_ids=seg, causal=causal,
               sm_scale=scale)
    return o.transpose(0, 2, 1, 3)


def _ring_body(axis_name, n_shards, causal, scale, q, k0, v0, q_off, kv_lens):
    """Online-softmax accumulation over ring steps. Shapes per shard:
    q: [B, Tq, H, D]; k0/v0: [B, Tk, H, D] (local shard); q_off scalar
    global offset of this shard's queries; kv_lens: [B] global valid len."""
    B, Tq, H, D = q.shape
    Tk = k0.shape[1]
    my = lax.axis_index(axis_name)

    acc = jnp.zeros((B, Tq, H, D), jnp.float32)
    m = jnp.full((B, H, Tq), NEG_INF, jnp.float32)
    den = jnp.zeros((B, H, Tq), jnp.float32)

    qpos = q_off + jnp.arange(Tq)

    def step(i, carry):
        acc, m, den, k, v = carry
        src = (my - i) % n_shards  # whose K/V block we hold at step i
        k_off = src * Tk
        kpos = k_off + jnp.arange(Tk)
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
        ) * scale
        neg = jnp.zeros((B, 1, Tq, Tk), jnp.float32)
        if kv_lens is not None:
            pad = kpos[None, :] >= kv_lens[:, None]
            neg = jnp.where(pad[:, None, None, :], NEG_INF, neg)
        if causal:
            neg = neg + jnp.where(
                kpos[None, :] > qpos[:, None], NEG_INF, 0.0
            )[None, None]
        s = s + neg
        blk_max = jnp.max(s, axis=-1)  # [B,H,Tq]
        m_new = jnp.maximum(m, blk_max)
        # guard: all-masked block keeps m at NEG_INF; exp underflows to 0
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        den_new = den * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
        acc_new = acc * corr.transpose(0, 2, 1)[..., None] + pv

        def rotate(kv):
            return jax.tree_util.tree_map(
                lambda x: lax.ppermute(
                    x,
                    axis_name,
                    [(j, (j + 1) % n_shards) for j in range(n_shards)],
                ),
                kv,
            )

        # the last step's rotation would be discarded — skip the exchange
        k, v = lax.cond(
            i < n_shards - 1, rotate, lambda kv: kv, (k, v)
        )
        return acc_new, m_new, den_new, k, v

    acc, m, den, _, _ = lax.fori_loop(
        0, n_shards, step, (acc, m, den, k0, v0)
    )
    den = jnp.where(den == 0.0, 1.0, den)  # fully-masked query rows
    out = acc / den.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _batch_axis(mesh: Mesh):
    from paddle_tpu.core.mesh import DATA_AXIS

    return DATA_AXIS if DATA_AXIS in mesh.axis_names else None


def ring_attention(
    q, k, v, mesh: Mesh, *, axis: str = SEQ_AXIS, causal=False, kv_lens=None
):
    """q,k,v: [B, T, H, D] with T sharded over `axis` (and B over `data`
    when that axis exists). kv_lens: [B] valid lengths (global). Returns
    [B, T, H, D] sharded the same way."""
    n = mesh.shape[axis]
    D = q.shape[-1]
    scale = 1.0 / (D**0.5)
    Tq_local = q.shape[1] // n
    b = _batch_axis(mesh)
    spec = P(b, axis, None, None)

    def local(q, k, v, kv_lens):
        idx = lax.axis_index(axis)
        return _ring_body(
            axis, n, causal, scale, q, k, v, idx * Tq_local, kv_lens
        )

    if kv_lens is None:
        return jax.shard_map(
            lambda a, c, d: local(a, c, d, None),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )(q, k, v)
    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec, P(b)),
        out_specs=spec,
        check_vma=False,
    )(q, k, v, kv_lens)


def ulysses_attention(
    q, k, v, mesh: Mesh, *, axis: str = SEQ_AXIS, causal=False, kv_lens=None
):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style): reshard
    [B, T/s, H, D] -> [B, T, H/s, D], dense attention locally, reshard
    back. Heads must divide the axis size."""
    n = mesh.shape[axis]
    H = q.shape[2]
    assert H % n == 0, f"heads {H} not divisible by seq shards {n}"

    def local(q, k, v, kv_lens):
        # local shapes: q [B, T/s, H, D] -> all_to_all over heads
        qh, kh, vh = (
            lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)
            for x in (q, k, v)
        )  # [B, T, H/s, D]
        out = dense_attention(qh, kh, vh, causal=causal, kv_len=kv_lens)
        return lax.all_to_all(
            out, axis, split_axis=1, concat_axis=2, tiled=True
        )

    b = _batch_axis(mesh)
    spec = P(b, axis, None, None)
    if kv_lens is None:
        return jax.shard_map(
            lambda x, y, z: local(x, y, z, None),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )(q, k, v)
    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec, P(b)),
        out_specs=spec,
        check_vma=False,
    )(q, k, v, kv_lens)
