"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

New first-class capability (absent in the 2017 reference — SURVEY.md §2
"Pipeline / TP / SP" row): long sequences are sharded over the mesh `seq`
axis. Two strategies:

- ``ring_attention``: K/V blocks rotate around the ring via
  ``lax.ppermute`` while each device keeps its Q shard; softmax is
  accumulated online (flash-attention style running max/denominator), so
  the full [T, T] score matrix never materializes and comm rides ICI
  neighbor links.
- ``ulysses_attention``: ``lax.all_to_all`` reshards seq -> heads, runs
  local attention per head group (dense or flash — at T >= 32k the
  local dense [T, T] scores would not fit, so the long-context rows use
  ``attn_impl="flash"``), and reshards back.

Single-chip, two lowerings of the same masked-attention contract:

- ``dense_attention``: the reference path — materializes [B, H, T, T]
  scores (O(T^2) HBM bytes; the measured bound of the longctx bench
  rows).
- ``flash_dense_attention``: flash attention. On TPU the Pallas kernel
  (jax.experimental.pallas.ops.tpu.flash_attention); on every other
  backend a portable blocked online-softmax lowering
  (``flash_blocked_attention``) with a recompute backward via
  custom_vjp — the same O(T) score-byte algorithm, so parity tests,
  CPU-mesh smokes and HLO byte attribution run without a TPU.

All are numerically identical to dense masked attention (tested on a
virtual CPU mesh in tests/test_parallel_tp_sp.py and
tests/test_flash_attention.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core.mesh import SEQ_AXIS
from paddle_tpu.core.mesh import shard_map as _shard_map

NEG_INF = -1e30

# flash_blocked_attention unrolls the K/V-block loop up to this many
# blocks (exact static HLO: every block's ops visible to byte
# attribution, no while-loop); longer sequences scan. Either way the
# custom_vjp backward recomputes scores per block, so peak score bytes
# stay O(T * block_k), never O(T^2).
_UNROLL_MAX_BLOCKS = 16


def dense_attention(q, k, v, *, causal=False, kv_len=None, scale=None):
    """Reference masked attention. q,k,v: [B, T, H, D]; kv_len: [B] valid
    K/V length (padding masked out)."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(D).astype(q.dtype)
    with jax.named_scope("dense_attention"):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        mask = jnp.zeros((B, 1, Tq, Tk), q.dtype)
        if kv_len is not None:
            pad = jnp.arange(Tk)[None, :] >= kv_len[:, None]  # [B, Tk]
            mask = jnp.where(pad[:, None, None, :], NEG_INF, mask)
        if causal:
            qpos = jnp.arange(Tq)[:, None]
            kpos = jnp.arange(Tk)[None, :]
            mask = mask + jnp.where(kpos > qpos, NEG_INF, 0.0)
        p = jax.nn.softmax(s + mask, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _pad_time(x, pad, value=0.0):
    return jnp.pad(
        x, ((0, 0), (0, pad)) + ((0, 0),) * (x.ndim - 2),
        constant_values=value,
    ) if pad else x


def _blocked_kv(k, v, kbias, block_k):
    """Pad Tk to a block multiple and return (k, v, kbias, n_blocks).
    Padding positions carry kbias = NEG_INF so their exp underflows to
    0 in every row."""
    Tk = k.shape[1]
    nb = -(-Tk // block_k)
    pad = nb * block_k - Tk
    return (
        _pad_time(k, pad), _pad_time(v, pad),
        _pad_time(kbias, pad, value=NEG_INF), nb,
    )


def _blocked_fwd(q, k, v, kbias, causal, scale, block_k):
    """Online-softmax forward over K/V blocks. Returns (out f32, lse)
    where lse[b,h,i] = m + log(sum exp(s - m)) is the log-sum-exp the
    backward needs to recompute p without renormalizing. Fully-masked
    query rows get out = 0 and lse = +1e30 (so recomputed p == 0)."""
    B, Tq, H, D = q.shape
    qf = q.astype(jnp.float32)
    qpos = jnp.arange(Tq)

    def one_block(carry, kb, vb, bb, off):
        acc, m, den = carry
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", qf, kb.astype(jnp.float32)
        ) * scale + bb[:, None, None, :]
        if causal:
            kpos = off + jnp.arange(kb.shape[1])
            s = s + jnp.where(
                kpos[None, :] > qpos[:, None], NEG_INF, 0.0
            )[None, None]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        corr = jnp.exp(m - m_new)  # NEG_INF - NEG_INF == 0 (finite)
        # explicit zero for masked positions: in a FULLY-masked row
        # m_new == s == NEG_INF and exp(s - m_new) would be exp(0)=1,
        # silently attending uniformly; with the where, such rows keep
        # den == 0 and the epilogue emits exactly 0 (and lse=+1e30, so
        # the backward's recomputed p is 0 too)
        p = jnp.where(s > 0.5 * NEG_INF,
                      jnp.exp(s - m_new[..., None]), 0.0)
        den_new = den * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p, vb.astype(jnp.float32)
        )
        return acc_new, m_new, den_new

    k, v, kbias, nb = _blocked_kv(k, v, kbias, block_k)
    acc = jnp.zeros((B, Tq, H, D), jnp.float32)
    m = jnp.full((B, H, Tq), NEG_INF, jnp.float32)
    den = jnp.zeros((B, H, Tq), jnp.float32)
    if nb <= _UNROLL_MAX_BLOCKS:
        carry = (acc, m, den)
        for i in range(nb):
            sl = slice(i * block_k, (i + 1) * block_k)
            carry = one_block(
                carry, k[:, sl], v[:, sl], kbias[:, sl], i * block_k
            )
        acc, m, den = carry
    else:
        ks = k.reshape(B, nb, block_k, H, D).transpose(1, 0, 2, 3, 4)
        vs = v.reshape(B, nb, block_k, H, D).transpose(1, 0, 2, 3, 4)
        bs = kbias.reshape(B, nb, block_k).transpose(1, 0, 2)
        offs = jnp.arange(nb) * block_k

        def body(carry, xs):
            kb, vb, bb, off = xs
            return one_block(carry, kb, vb, bb, off), None

        (acc, m, den), _ = lax.scan(
            body, (acc, m, den), (ks, vs, bs, offs)
        )
    alive = den > 0.0
    out = acc / jnp.where(alive, den, 1.0).transpose(0, 2, 1)[..., None]
    lse = jnp.where(alive, m + jnp.log(jnp.where(alive, den, 1.0)),
                    jnp.float32(1e30))
    return out, lse


@partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_blocked(q, k, v, kbias, causal, scale, block_k):
    out, _ = _blocked_fwd(q, k, v, kbias, causal, scale, block_k)
    return out.astype(q.dtype)


def _flash_blocked_fwd(q, k, v, kbias, causal, scale, block_k):
    out, lse = _blocked_fwd(q, k, v, kbias, causal, scale, block_k)
    return out.astype(q.dtype), (q, k, v, kbias, out, lse)


def _flash_blocked_bwd(causal, scale, block_k, res, do):
    """Flash backward: recompute each block's p = exp(s - lse) and
    accumulate dq / per-block dk, dv. Only [B, H, Tq, block_k] score
    tiles ever exist — the backward moves O(T) score bytes too."""
    q, k, v, kbias, out, lse = res
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    qpos = jnp.arange(Tq)
    # delta[b,h,i] = sum_d dO * O — the softmax-jacobian row term
    delta = jnp.einsum("bqhd,bqhd->bhq", dof, out)

    def one_block(dq, kb, vb, bb, off):
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", qf, kb.astype(jnp.float32)
        ) * scale + bb[:, None, None, :]
        if causal:
            kpos = off + jnp.arange(kb.shape[1])
            s = s + jnp.where(
                kpos[None, :] > qpos[:, None], NEG_INF, 0.0
            )[None, None]
        p = jnp.exp(s - lse[..., None])
        dvb = jnp.einsum("bhqk,bqhd->bkhd", p, dof)
        dp = jnp.einsum("bqhd,bkhd->bhqk", dof, vb.astype(jnp.float32))
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("bhqk,bkhd->bqhd", ds,
                             kb.astype(jnp.float32))
        dkb = jnp.einsum("bhqk,bqhd->bkhd", ds, qf)
        return dq, dkb, dvb

    k, v, kbias, nb = _blocked_kv(k, v, kbias, block_k)
    dq = jnp.zeros((B, Tq, H, D), jnp.float32)
    if nb <= _UNROLL_MAX_BLOCKS:
        dks, dvs = [], []
        for i in range(nb):
            sl = slice(i * block_k, (i + 1) * block_k)
            dq, dkb, dvb = one_block(
                dq, k[:, sl], v[:, sl], kbias[:, sl], i * block_k
            )
            dks.append(dkb)
            dvs.append(dvb)
        dk = jnp.concatenate(dks, axis=1)
        dv = jnp.concatenate(dvs, axis=1)
    else:
        ks = k.reshape(B, nb, block_k, H, D).transpose(1, 0, 2, 3, 4)
        vs = v.reshape(B, nb, block_k, H, D).transpose(1, 0, 2, 3, 4)
        bs = kbias.reshape(B, nb, block_k).transpose(1, 0, 2)
        offs = jnp.arange(nb) * block_k

        def body(dq, xs):
            kb, vb, bb, off = xs
            dq, dkb, dvb = one_block(dq, kb, vb, bb, off)
            return dq, (dkb, dvb)

        dq, (dks, dvs) = lax.scan(body, dq, (ks, vs, bs, offs))
        dk = dks.transpose(1, 0, 2, 3, 4).reshape(B, nb * block_k, H, D)
        dv = dvs.transpose(1, 0, 2, 3, 4).reshape(B, nb * block_k, H, D)
    Tk_orig = res[1].shape[1]
    return (
        dq.astype(q.dtype),
        dk[:, :Tk_orig].astype(res[1].dtype),
        dv[:, :Tk_orig].astype(res[2].dtype),
        jnp.zeros_like(res[3]),
    )


_flash_blocked.defvjp(_flash_blocked_fwd, _flash_blocked_bwd)


def flash_blocked_attention(q, k, v, *, causal=False, kv_len=None,
                            scale=None, block_k=512):
    """Portable flash attention: online-softmax over K/V blocks with a
    recompute backward (custom_vjp) — the [B, H, Tq, Tk] score matrix
    never exists; peak score bytes are O(Tq * block_k). Same contract
    as dense_attention. Runs on every backend (the CPU-mesh smokes and
    HLO byte attribution use it); on TPU the Pallas kernel
    (flash_dense_attention) is the faster lowering of the same
    algorithm."""
    D = q.shape[-1]
    Tk = k.shape[1]
    scale = float(scale) if scale is not None else 1.0 / float(D) ** 0.5
    kpos = jnp.arange(Tk)[None, :]
    if kv_len is not None:
        kbias = jnp.where(kpos >= kv_len[:, None],
                          jnp.float32(NEG_INF), 0.0)
    else:
        kbias = jnp.zeros((q.shape[0], Tk), jnp.float32)
    return _flash_blocked(q, k, v, kbias, bool(causal), scale,
                          int(block_k))


def _pallas_flash(q, k, v, *, causal, kv_len, q_len, scale):
    """The TPU Pallas kernel behind flash_dense_attention, with the
    wrapper responsibilities: [B,T,H,D] -> [B,H,T,D] layout, padding T
    up to the kernel's block multiple (segment ids mask the pad — pad
    tokens get segment 0, valid get 1, and cross-segment attention is
    masked by the kernel), and slicing the pad back off. Padded QUERY
    rows still emit garbage, which the attention layer zeroes after
    the output projection exactly as in the dense path."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        SegmentIds,
        flash_attention as _flash,
    )

    B, Tq, H, D = q.shape
    Tk = k.shape[1]

    # kernel block sizes must divide each (padded) sequence length:
    # default blocks are min(512, T), so pad to a multiple of 512 past
    # 512 and to the 128-lane minimum below it (pallas_guide tiling) —
    # q and k/v pad independently (cross-attention: Tq != Tk)
    def _padded(t):
        mult = 512 if t > 512 else 128
        return -(-t // mult) * mult

    Tqp, Tkp = _padded(Tq), _padded(Tk)
    q = _pad_time(q, Tqp - Tq)
    k = _pad_time(k, Tkp - Tk)
    v = _pad_time(v, Tkp - Tk)
    seg = None
    if (kv_len is not None or q_len is not None
            or Tqp != Tq or Tkp != Tk):
        q_valid = q_len[:, None] if q_len is not None else (
            kv_len[:, None] if kv_len is not None else Tq
        )
        kv_valid = kv_len[:, None] if kv_len is not None else Tk
        seg = SegmentIds(
            q=(jnp.arange(Tqp)[None, :] < q_valid)
            * jnp.ones((B, 1), jnp.int32),
            kv=(jnp.arange(Tkp)[None, :] < kv_valid)
            * jnp.ones((B, 1), jnp.int32),
        )
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    o = _flash(qt, kt, vt, segment_ids=seg, causal=causal,
               sm_scale=scale)
    return o.transpose(0, 2, 1, 3)[:, :Tq]


def flash_dense_attention(q, k, v, *, causal=False, kv_len=None,
                          q_len=None, scale=None, impl=None):
    """Single-chip flash attention: same contract as dense_attention —
    q,k,v [B, T, H, D], kv_len [B] — but never materializes the
    [B, H, T, T] score matrix in HBM (the bandwidth bound of the dense
    path at long T; see PERF.md round 8). `impl` selects the lowering:
    "pallas" (TPU kernel), "blocked" (portable online-softmax scan),
    None = pallas on TPU, blocked elsewhere. `q_len` masks query-side
    padding independently of `kv_len` (cross-attention); self-attention
    callers pass only kv_len and get the old behavior."""
    D = q.shape[-1]
    scale = (
        float(scale) if scale is not None else 1.0 / float(D) ** 0.5
    )
    if impl is None:
        impl = "pallas" if jax.default_backend() == "tpu" else "blocked"
    with jax.named_scope("flash_attention"):
        if impl == "pallas":
            return _pallas_flash(q, k, v, causal=causal, kv_len=kv_len,
                                 q_len=q_len, scale=scale)
        return flash_blocked_attention(
            q, k, v, causal=causal, kv_len=kv_len, scale=scale
        )


# analytic HBM-byte model for the attention CORE (scores + softmax +
# P@V on one layer's forward), the accounting the longctx bench rows
# carry so "flash removes bytes" is a stated, checkable expectation:
# dense round-trips the [B,H,Tq,Tk] scores ~4 times (QK^T write,
# softmax read+write, P read for P@V); flash never writes them, so
# only the q/k/v/o streams remain.
def attention_hbm_bytes(B, Tq, Tk, H, D, impl, dtype_bytes=2,
                        passes=3):
    """`passes`=3 approximates fwd+bwd (the same convention as the
    rows' analytic FLOP accounting)."""
    io = B * H * D * (2 * Tq + 2 * Tk) * dtype_bytes  # q,o + k,v
    score = 4 * B * H * Tq * Tk * dtype_bytes if impl == "dense" else 0
    return passes * (io + score)


# largest local score tile a ring step may materialize: the per-step
# K/V shard is sub-blocked to [B, H, Tq_local, RING_BLOCK_K] when it
# is larger (and divisible), so a T=128k ring shard streams score
# tiles instead of allocating the full [Tq/s, Tk/s] local square —
# flash semantics inside every ring step, not just across them.
RING_BLOCK_K = 2048


def _ring_body(axis_name, n_shards, causal, scale, q, k0, v0, q_off, kv_lens):
    """Online-softmax accumulation over ring steps. Shapes per shard:
    q: [B, Tq, H, D]; k0/v0: [B, Tk, H, D] (local shard); q_off scalar
    global offset of this shard's queries; kv_lens: [B] global valid len."""
    B, Tq, H, D = q.shape
    Tk = k0.shape[1]
    my = lax.axis_index(axis_name)

    acc = jnp.zeros((B, Tq, H, D), jnp.float32)
    m = jnp.full((B, H, Tq), NEG_INF, jnp.float32)
    den = jnp.zeros((B, H, Tq), jnp.float32)

    qpos = q_off + jnp.arange(Tq)
    blk = (
        RING_BLOCK_K
        if Tk > RING_BLOCK_K and Tk % RING_BLOCK_K == 0 else Tk
    )
    nsub = Tk // blk

    def step(i, carry):
        acc, m, den, k, v = carry
        src = (my - i) % n_shards  # whose K/V block we hold at step i
        k_off = src * Tk

        def sub(j, c):
            acc, m, den = c
            kb = lax.dynamic_slice_in_dim(k, j * blk, blk, axis=1)
            vb = lax.dynamic_slice_in_dim(v, j * blk, blk, axis=1)
            kpos = k_off + j * blk + jnp.arange(blk)
            s = jnp.einsum(
                "bqhd,bkhd->bhqk", q.astype(jnp.float32),
                kb.astype(jnp.float32),
            ) * scale
            neg = jnp.zeros((B, 1, Tq, blk), jnp.float32)
            if kv_lens is not None:
                pad = kpos[None, :] >= kv_lens[:, None]
                neg = jnp.where(pad[:, None, None, :], NEG_INF, neg)
            if causal:
                neg = neg + jnp.where(
                    kpos[None, :] > qpos[:, None], NEG_INF, 0.0
                )[None, None]
            s = s + neg
            blk_max = jnp.max(s, axis=-1)  # [B,H,Tq]
            m_new = jnp.maximum(m, blk_max)
            corr = jnp.exp(m - m_new)  # NEG_INF - NEG_INF == 0
            # explicit zero for masked positions (an all-masked tile
            # would otherwise contribute exp(0) == 1 per position;
            # the stale contribution self-heals once a live tile
            # raises m, but fully-masked ROWS would keep it)
            p = jnp.where(s > 0.5 * NEG_INF,
                          jnp.exp(s - m_new[..., None]), 0.0)
            den_new = den * corr + jnp.sum(p, axis=-1)
            pv = jnp.einsum(
                "bhqk,bkhd->bqhd", p, vb.astype(jnp.float32)
            )
            acc_new = acc * corr.transpose(0, 2, 1)[..., None] + pv
            return acc_new, m_new, den_new

        acc, m, den = lax.fori_loop(0, nsub, sub, (acc, m, den))

        def rotate(kv):
            return jax.tree_util.tree_map(
                lambda x: lax.ppermute(
                    x,
                    axis_name,
                    [(j, (j + 1) % n_shards) for j in range(n_shards)],
                ),
                kv,
            )

        # the last step's rotation would be discarded — skip the exchange
        k, v = lax.cond(
            i < n_shards - 1, rotate, lambda kv: kv, (k, v)
        )
        return acc, m, den, k, v

    acc, m, den, _, _ = lax.fori_loop(
        0, n_shards, step, (acc, m, den, k0, v0)
    )
    den = jnp.where(den == 0.0, 1.0, den)  # fully-masked query rows
    out = acc / den.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _batch_axis(mesh: Mesh):
    from paddle_tpu.core.mesh import DATA_AXIS

    return DATA_AXIS if DATA_AXIS in mesh.axis_names else None


def ring_attention(
    q, k, v, mesh: Mesh, *, axis: str = SEQ_AXIS, causal=False, kv_lens=None
):
    """q,k,v: [B, T, H, D] with T sharded over `axis` (and B over `data`
    when that axis exists). kv_lens: [B] valid lengths (global). Returns
    [B, T, H, D] sharded the same way."""
    n = mesh.shape[axis]
    D = q.shape[-1]
    scale = 1.0 / (D**0.5)
    Tq_local = q.shape[1] // n
    b = _batch_axis(mesh)
    spec = P(b, axis, None, None)

    def local(q, k, v, kv_lens):
        idx = lax.axis_index(axis)
        return _ring_body(
            axis, n, causal, scale, q, k, v, idx * Tq_local, kv_lens
        )

    if kv_lens is None:
        return _shard_map(
            lambda a, c, d: local(a, c, d, None),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )(q, k, v)
    return _shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec, P(b)),
        out_specs=spec,
        check_vma=False,
    )(q, k, v, kv_lens)


def ulysses_attention(
    q, k, v, mesh: Mesh, *, axis: str = SEQ_AXIS, causal=False,
    kv_lens=None, attn_impl="dense",
):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style): reshard
    [B, T/s, H, D] -> [B, T, H/s, D], local attention per head group,
    reshard back. Heads must divide the axis size. `attn_impl` picks
    the local lowering: "dense" materializes the full local [T, T]
    scores (fine at short T); "flash" uses flash_dense_attention — at
    T >= 32k the dense local scores would be O(T^2) bytes, so the
    long-context multichip rows run flash locally."""
    n = mesh.shape[axis]
    H = q.shape[2]
    assert H % n == 0, f"heads {H} not divisible by seq shards {n}"

    def local(q, k, v, kv_lens):
        # local shapes: q [B, T/s, H, D] -> all_to_all over heads
        qh, kh, vh = (
            lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)
            for x in (q, k, v)
        )  # [B, T, H/s, D]
        if attn_impl == "flash":
            out = flash_dense_attention(
                qh, kh, vh, causal=causal, kv_len=kv_lens
            )
        else:
            out = dense_attention(
                qh, kh, vh, causal=causal, kv_len=kv_lens
            )
        return lax.all_to_all(
            out, axis, split_axis=1, concat_axis=2, tiled=True
        )

    b = _batch_axis(mesh)
    spec = P(b, axis, None, None)
    if kv_lens is None:
        return _shard_map(
            lambda x, y, z: local(x, y, z, None),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )(q, k, v)
    return _shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec, P(b)),
        out_specs=spec,
        check_vma=False,
    )(q, k, v, kv_lens)
