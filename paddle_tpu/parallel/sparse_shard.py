"""Elastic sharded embedding tier for 100M–1B-row CTR tables
(ISSUE 20 tentpole).

`parallel/sparse.py` proves O(touched) sparse updates and
V-independence for tables that FIT: every row is materialized in
device memory. The reference's CTR workloads
(math/SparseRowMatrix.h:29 SparseRowCpuMatrix,
doc/design/cluster_train/large_model_dist_train.md) are an order of
magnitude past that — a 1B x 64 f32 table is 256 GB, and the pserver
tier existed precisely so no single host ever held it. This module is
that tier rebuilt TPU-first, with elasticity as the design
constraint:

- **Explicit placement.** Every logical row id has exactly one owner
  shard — `range` (id // rows_per_shard: the pserver block layout,
  ParameterService.proto GET_PARAMETER_SPARSE) or `hash` (splitmix64
  mix, the skew-resistant layout for power-law CTR vocabularies).
  Ownership is arithmetic, not a directory: any process can compute
  where any row lives, which is what makes per-shard recovery
  manifests possible (a respawned rank knows exactly which shard
  files are its rows).

- **Hot-cache residency, not materialization.** Each shard owns a
  fixed-capacity device buffer of `capacity` rows (plus parallel
  per-shard optimizer-slot buffers). A host-side LRU map binds
  resident row ids to slots; rows the traffic stops touching are
  EVICTED — written back to the shard's host spill store — and rows
  touched again are rebuilt from spill (or from the deterministic
  init for never-touched rows), never silently zero. The device
  programs see only slot indices in [0, capacity): their shapes,
  layouts, and compiled code depend on (capacity, dim, batch) and
  NEVER on `rows_total` — V-independence by construction, at any V.

- **All-gather-free by construction, policed by audit.** Lookup is
  the `sparse.embedding_lookup` shard_map (local gather + one psum);
  update and residency fill are local masked scatters with NO
  collective at all. The committed `mc_sparse_shard_step` capture is
  audited by `analysis/spmd_audit.py` under a policy that FORBIDS
  all-gather — a future "optimization" that gathers the hot cache
  onto every chip fails CI, it does not ship.

Checkpointing: `export_shards()` returns one payload dict per shard
(resident rows in LRU order + spill store + optimizer slots), the
unit `trainer/async_checkpoint.py`'s sharded-table generations
(`sharded-table-v1`) commit with per-shard sha256 manifests. See
`trainer/online.py` for the commit-acknowledged training ledger that
turns those generations into the zero-batches-lost elastic contract.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass
from math import ceil

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.core.mesh import MODEL_AXIS, get_mesh
from paddle_tpu.core.mesh import shard_map as _shard_map
from paddle_tpu.parallel.sparse import embedding_lookup

_MASK64 = np.uint64(0xFFFFFFFFFFFFFFFF)


def _mix64(x):
    """splitmix64 finalizer, vectorized over uint64 numpy arrays —
    the hash behind `hash` placement and the deterministic row init.
    Stdlib-deterministic: the same id hashes the same on every
    process, every run, every platform."""
    x = np.asarray(x, np.uint64)
    with np.errstate(over="ignore"):
        x = (x + np.uint64(0x9E3779B97F4A7C15)) & _MASK64
        x = ((x ^ (x >> np.uint64(30)))
             * np.uint64(0xBF58476D1CE4E5B9)) & _MASK64
        x = ((x ^ (x >> np.uint64(27)))
             * np.uint64(0x94D049BB133111EB)) & _MASK64
        return x ^ (x >> np.uint64(31))


# Memoized by hyperparameters: two calls with the same lr return the
# SAME function object, so two tables configured alike share every
# compiled program (the V-independence cache test leans on this).
_UPDATE_FNS: dict = {}


def sgd_row_update(lr: float = 0.1):
    """Plain row SGD `update_fn` (no optimizer slots)."""
    key = ("sgd", float(lr))
    if key not in _UPDATE_FNS:
        def update(rows, grads):
            return rows - lr * grads

        _UPDATE_FNS[key] = update
    return _UPDATE_FNS[key]


def adagrad_row_update(lr: float = 0.1, eps: float = 1e-6):
    """Adagrad with one per-row accumulator slot buffer — the
    catchUpWith-style sparse optimizer state the shard checkpoints
    must carry (evict-then-touch would silently reset a row's
    effective learning rate if the accumulator were dropped)."""
    key = ("adagrad", float(lr), float(eps))
    if key not in _UPDATE_FNS:
        def update(rows, grads, acc):
            acc = acc + grads * grads
            return rows - lr * grads / jnp.sqrt(acc + eps), acc

        _UPDATE_FNS[key] = update
    return _UPDATE_FNS[key]


@dataclass(frozen=True)
class ShardedTableConfig:
    """Static shape/placement contract for one sharded table.

    rows_total: LOGICAL vocabulary (100M–1B). Costs nothing: only
        host-side owner arithmetic ever sees it.
    dim: row width D.
    capacity: HOT rows per shard (device-resident). Total device
        footprint = num_shards * capacity * dim * 4 bytes.
    num_slots: static unique-touched-rows capacity per update step
        (the `sparse_apply` k). Must be <= capacity so one batch can
        always be made fully resident.
    placement: "range" | "hash".
    init_scale: deterministic per-(row, col) init amplitude; 0.0 =
        zero init. Never-touched rows ARE this init — there is no
        materialized cold table to read them from.
    seed: folded into the init hash stream.
    """

    rows_total: int
    dim: int
    capacity: int
    num_slots: int
    placement: str = "range"
    init_scale: float = 0.0
    seed: int = 0

    def __post_init__(self):
        if self.placement not in ("range", "hash"):
            raise ValueError(f"placement {self.placement!r}")
        if self.num_slots > self.capacity:
            raise ValueError(
                f"num_slots {self.num_slots} > capacity "
                f"{self.capacity}: a single batch could not be made "
                f"resident"
            )


# ---- compiled-program cache -----------------------------------------
#
# Keyed on (mesh, axis, hot-cache shape, batch shape, update_fn) —
# NEVER on rows_total. Two tables differing only in logical vocab hit
# the SAME entries: the V-independence invariant is testable as cache
# identity, not just as a wall-clock smoke.
_PROGRAMS: dict = {}


def program_cache_size() -> int:
    return len(_PROGRAMS)


def _lookup_program(mesh, axis, S, D, N, dtype):
    key = ("lookup", mesh, axis, S, D, N, str(dtype))
    if key not in _PROGRAMS:
        def fn(cache, slots):
            return embedding_lookup(cache, slots, mesh, axis=axis)

        _PROGRAMS[key] = jax.jit(fn)
    return _PROGRAMS[key]


def _pull_program(mesh, axis, S, D, M, n_state, dtype):
    """Gather M rows (by global slot) from cache AND every optimizer
    slot buffer — the eviction write-back read. -1 slots return 0 and
    are ignored by the host."""
    key = ("pull", mesh, axis, S, D, M, n_state, str(dtype))
    if key not in _PROGRAMS:
        def fn(cache, state, slots):
            rows = embedding_lookup(cache, slots, mesh, axis=axis)
            srows = tuple(
                embedding_lookup(st, slots, mesh, axis=axis)
                for st in state
            )
            return rows, srows

        _PROGRAMS[key] = jax.jit(fn)
    return _PROGRAMS[key]


def _push_program(mesh, axis, S, D, M, n_state, dtype):
    """Write M rows (by global slot) into cache + slot buffers — the
    residency fill. Pure local masked scatter: each shard writes only
    its own slot range, NO collective touches the table."""
    key = ("push", mesh, axis, S, D, M, n_state, str(dtype))
    if key not in _PROGRAMS:
        n = mesh.shape[axis]
        C = S // n

        def local(cache, state, slots, rows, srows):
            shard = lax.axis_index(axis)
            loc = slots - shard * C
            ok = (loc >= 0) & (loc < C)
            safe = jnp.clip(loc, 0, C - 1)
            m = ok[:, None].astype(cache.dtype)
            new_cache = cache.at[safe].add((rows - cache[safe]) * m)
            new_state = tuple(
                st.at[safe].add((sr - st[safe]) * m)
                for st, sr in zip(state, srows)
            )
            return new_cache, new_state

        sharded = P(axis, None)
        fn = _shard_map(
            local, mesh=mesh,
            in_specs=(sharded, (sharded,) * n_state, P(), P(),
                      (P(),) * n_state),
            out_specs=(sharded, (sharded,) * n_state),
        )
        _PROGRAMS[key] = jax.jit(fn, donate_argnums=(0, 1))
    return _PROGRAMS[key]


def _update_program(mesh, axis, S, D, N, k, n_state, dtype,
                    update_fn):
    """The sparse train step: segment-sum per-occurrence grads into k
    unique slots, gather those rows + optimizer slots, apply
    update_fn, scatter back as masked deltas. Each shard touches only
    its own slot range — like push, NO collective."""
    key = ("update", mesh, axis, S, D, N, k, n_state, str(dtype),
           update_fn)
    if key not in _PROGRAMS:
        n = mesh.shape[axis]
        C = S // n

        def local(cache, state, uslots, inv, grads):
            gsum = jnp.zeros((k, D), grads.dtype).at[inv].add(grads)
            shard = lax.axis_index(axis)
            loc = uslots - shard * C
            ok = (loc >= 0) & (loc < C)
            safe = jnp.clip(loc, 0, C - 1)
            prows = cache[safe]
            srows = tuple(st[safe] for st in state)
            out = update_fn(prows, gsum, *srows)
            if n_state:
                new_rows, *new_srows = out
            else:
                new_rows, new_srows = out, []
            m = ok[:, None].astype(cache.dtype)
            new_cache = cache.at[safe].add((new_rows - prows) * m)
            new_state = tuple(
                st.at[safe].add((ns - sr) * m)
                for st, sr, ns in zip(state, srows, new_srows)
            )
            return new_cache, new_state

        sharded = P(axis, None)
        fn = _shard_map(
            local, mesh=mesh,
            in_specs=(sharded, (sharded,) * n_state, P(), P(), P()),
            out_specs=(sharded, (sharded,) * n_state),
        )
        _PROGRAMS[key] = jax.jit(fn, donate_argnums=(0, 1))
    return _PROGRAMS[key]


def step_program(mesh, axis, S, D, N, k, n_state, dtype, update_fn):
    """Lookup + sparse update as ONE traced program — the
    `mc_sparse_shard_step` capture target (tools/profile_multichip).
    Shapes are (hot-cache, batch) only: lowering this at rows_total =
    2**30 produces byte-identical HLO to rows_total = 2**20, which is
    the audit-visible form of the V-independence claim."""
    n = mesh.shape[axis]
    C = S // n

    def local(cache, state, slots, uslots, inv, grads):
        shard = lax.axis_index(axis)
        # lookup: local gather + psum (the only collective)
        loc_l = slots - shard * C
        ok_l = (loc_l >= 0) & (loc_l < C)
        rows = jnp.take(cache, jnp.clip(loc_l, 0, C - 1), axis=0)
        out = lax.psum(jnp.where(ok_l[:, None], rows, 0), axis)
        # update: local masked delta scatter, no collective
        gsum = jnp.zeros((k, D), grads.dtype).at[inv].add(grads)
        loc = uslots - shard * C
        ok = (loc >= 0) & (loc < C)
        safe = jnp.clip(loc, 0, C - 1)
        prows = cache[safe]
        srows = tuple(st[safe] for st in state)
        upd = update_fn(prows, gsum, *srows)
        if n_state:
            new_rows, *new_srows = upd
        else:
            new_rows, new_srows = upd, []
        m = ok[:, None].astype(cache.dtype)
        new_cache = cache.at[safe].add((new_rows - prows) * m)
        new_state = tuple(
            st.at[safe].add((ns - sr) * m)
            for st, sr, ns in zip(state, srows, new_srows)
        )
        return out, new_cache, new_state

    sharded = P(axis, None)
    fn = _shard_map(
        local, mesh=mesh,
        in_specs=(sharded, (sharded,) * n_state, P(), P(), P(), P()),
        out_specs=(P(), sharded, (sharded,) * n_state),
    )
    return jax.jit(fn, donate_argnums=(0, 1))


class ShardedEmbeddingTable:
    """A logically huge embedding table as explicit per-shard hot
    caches over the mesh `axis`. See the module docstring for the
    design; the API is host-driven:

        cfg = ShardedTableConfig(rows_total=1 << 30, dim=16,
                                 capacity=4096, num_slots=256)
        table = ShardedEmbeddingTable(cfg, mesh, update_fn=sgd_row_update(0.1))
        emb = table.lookup(ids)          # [..., D] — ids anywhere in [0, 1<<30)
        table.update(ids, grads)         # per-occurrence grads [N, D]
        payloads = table.export_shards() # one dict per shard, for
                                         # async_checkpoint table generations
        table.restore_shards(payloads)   # elastic resume

    Thread contract: single-writer (the training loop). Checkpoint
    snapshots copy on export, so the async writer never races device
    donation.
    """

    def __init__(self, config: ShardedTableConfig, mesh=None,
                 axis: str = MODEL_AXIS, update_fn=None,
                 num_state: int = 0):
        self.config = config
        self.mesh = mesh if mesh is not None else get_mesh()
        if axis not in self.mesh.shape:
            raise ValueError(f"mesh has no axis {axis!r}")
        self.axis = axis
        self.num_shards = int(self.mesh.shape[axis])
        self.update_fn = (update_fn if update_fn is not None
                          else sgd_row_update(0.1))
        self.num_state = int(num_state)
        self.rows_per_shard = ceil(config.rows_total / self.num_shards)
        C, D = config.capacity, config.dim
        self._S = self.num_shards * C  # total hot slots
        self._sharding = NamedSharding(self.mesh, P(axis, None))
        zeros = jnp.zeros((self._S, D), jnp.float32)
        self._cache = jax.device_put(zeros, self._sharding)
        self._state = tuple(
            jax.device_put(jnp.zeros((self._S, D), jnp.float32),
                           self._sharding)
            for _ in range(self.num_state)
        )
        # host residency maps, per shard: id -> local slot, LRU order
        # (oldest first); free slots; spill store id -> (row, *slots)
        self._slot_of = [OrderedDict() for _ in range(self.num_shards)]
        self._free = [list(range(C - 1, -1, -1))
                      for _ in range(self.num_shards)]
        self._spill = [dict() for _ in range(self.num_shards)]
        self.stats = {"hits": 0, "misses": 0, "evictions": 0,
                      "steps": 0}

    # ---- placement ----
    def owners(self, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64)
        if self.config.placement == "range":
            return ids // self.rows_per_shard
        return (_mix64(ids.astype(np.uint64))
                % np.uint64(self.num_shards)).astype(np.int64)

    # ---- deterministic init ----
    def _init_rows(self, ids) -> np.ndarray:
        D = self.config.dim
        ids = np.asarray(ids, np.int64)
        if not self.config.init_scale:
            return np.zeros((len(ids), D), np.float32)
        base = (ids.astype(np.uint64)[:, None] * np.uint64(D)
                + np.arange(D, dtype=np.uint64)[None, :]
                + np.uint64(self.config.seed) * np.uint64(0x9E37))
        u = (_mix64(base) >> np.uint64(11)).astype(np.float64) * 2.0**-53
        return ((u * 2.0 - 1.0)
                * self.config.init_scale).astype(np.float32)

    # ---- residency ----
    def _global_slot(self, shard: int, local: int) -> int:
        return shard * self.config.capacity + local

    def ensure_resident(self, uids: np.ndarray) -> None:
        """Make every id in `uids` (unique, any order) resident,
        faulting misses in from spill/init and LRU-evicting to make
        room. Evicted rows are written back (row + optimizer slots)
        to the owner shard's spill store — an evicted row touched
        again is REBUILT, never reset."""
        uids = np.asarray(uids, np.int64)
        if len(uids) and (int(uids.min()) < 0
                          or int(uids.max()) >= self.config.rows_total):
            raise ValueError(
                f"ids must lie in [0, {self.config.rows_total}); got "
                f"range [{int(uids.min())}, {int(uids.max())}]"
            )
        shards = self.owners(uids)
        misses = []  # (shard, id)
        for i, s in zip(uids.tolist(), shards.tolist()):
            d = self._slot_of[s]
            if i in d:
                d.move_to_end(i)
                self.stats["hits"] += 1
            else:
                misses.append((s, i))
                self.stats["misses"] += 1
        if not misses:
            return
        if len(misses) > self.config.num_slots:
            raise ValueError(
                f"{len(misses)} misses in one batch > num_slots "
                f"{self.config.num_slots}"
            )
        evict = []   # (shard, id, local slot)
        assign = []  # (shard, id, local slot)
        for s, i in misses:
            if self._free[s]:
                slot = self._free[s].pop()
            else:
                old_id, slot = self._slot_of[s].popitem(last=False)
                evict.append((s, old_id, slot))
                self.stats["evictions"] += 1
            assign.append((s, i, slot))
            self._slot_of[s][i] = slot  # newest; never a victim below
        if evict:
            self._write_back(evict)
        # values for the faulted-in rows: spill wins, else init
        vals = np.empty((len(assign), self.config.dim), np.float32)
        svals = [np.zeros_like(vals) for _ in range(self.num_state)]
        init_ix, init_ids = [], []
        for j, (s, i, _slot) in enumerate(assign):
            spilled = self._spill[s].pop(i, None)
            if spilled is not None:
                vals[j] = spilled[0]
                for t in range(self.num_state):
                    svals[t][j] = spilled[1 + t]
            else:
                init_ix.append(j)
                init_ids.append(i)
        if init_ix:
            vals[init_ix] = self._init_rows(init_ids)
        gslots = np.array(
            [self._global_slot(s, slot) for s, _i, slot in assign],
            np.int32,
        )
        self._push(gslots, vals, svals)

    def _write_back(self, evict) -> None:
        gslots = np.full((self.config.num_slots,), -1, np.int32)
        for j, (s, _i, slot) in enumerate(evict):
            gslots[j] = self._global_slot(s, slot)
        pull = _pull_program(
            self.mesh, self.axis, self._S, self.config.dim,
            len(gslots), self.num_state, "float32",
        )
        rows, srows = pull(self._cache, self._state, gslots)
        rows = np.asarray(rows)
        srows = [np.asarray(sr) for sr in srows]
        for j, (s, i, _slot) in enumerate(evict):
            self._spill[s][i] = (
                rows[j].copy(),
                *(sr[j].copy() for sr in srows),
            )

    def _push(self, gslots, vals, svals) -> None:
        M = self.config.num_slots
        pad = M - len(gslots)
        if pad:
            gslots = np.concatenate(
                [gslots, np.full((pad,), -1, np.int32)]
            )
            vals = np.concatenate(
                [vals, np.zeros((pad, self.config.dim), np.float32)]
            )
            svals = [
                np.concatenate(
                    [sv, np.zeros((pad, self.config.dim), np.float32)]
                )
                for sv in svals
            ]
        push = _push_program(
            self.mesh, self.axis, self._S, self.config.dim, M,
            self.num_state, "float32",
        )
        self._cache, self._state = push(
            self._cache, self._state, gslots, vals, tuple(svals)
        )

    # ---- the data path ----
    def _slots_for(self, flat_ids: np.ndarray) -> np.ndarray:
        shards = self.owners(flat_ids)
        out = np.empty(len(flat_ids), np.int32)
        for j, (i, s) in enumerate(
            zip(flat_ids.tolist(), shards.tolist())
        ):
            out[j] = self._global_slot(s, self._slot_of[s][i])
        return out

    def lookup(self, ids):
        """ids: int array, any shape, values in [0, rows_total).
        Returns [*ids.shape, D] (replicated)."""
        ids = np.asarray(ids, np.int64)
        flat = ids.reshape(-1)
        self.ensure_resident(np.unique(flat))
        slots = self._slots_for(flat)
        look = _lookup_program(
            self.mesh, self.axis, self._S, self.config.dim,
            len(slots), "float32",
        )
        out = look(self._cache, slots)
        return out.reshape(ids.shape + (self.config.dim,))

    def update(self, ids, grads):
        """One sparse optimizer step: per-occurrence grads [N, D] are
        segment-summed per touched row and applied via `update_fn`.
        More than `num_slots` unique rows in one batch raises (the
        capacity contract is explicit here, unlike sparse_apply's
        skip-silently prefetch semantics — a sharded trainer must
        never silently drop gradient)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(
            len(ids), self.config.dim
        )
        uids, inv = np.unique(ids, return_inverse=True)
        k = self.config.num_slots
        if len(uids) > k:
            raise ValueError(
                f"{len(uids)} unique ids in one step > num_slots {k}"
            )
        self.ensure_resident(uids)
        uslots = np.full((k,), -1, np.int32)
        uslots[: len(uids)] = self._slots_for(uids)
        upd = _update_program(
            self.mesh, self.axis, self._S, self.config.dim,
            len(ids), k, self.num_state, "float32", self.update_fn,
        )
        self._cache, self._state = upd(
            self._cache, self._state, uslots,
            inv.astype(np.int32), grads,
        )
        self.stats["steps"] += 1

    # ---- introspection ----
    @property
    def rows_materialized(self) -> int:
        """Distinct rows this table has ever touched (resident +
        spilled) — the numerator of the bench row's
        `rows_touched_frac`."""
        return sum(len(d) for d in self._slot_of) + sum(
            len(sp) for sp in self._spill
        )

    def resident_ids(self, shard: int) -> list:
        return list(self._slot_of[shard])

    # ---- checkpointing ----
    def export_shards(self) -> list:
        """One payload dict per shard, each self-contained: resident
        ids in LRU order (oldest first) with their slots + rows +
        optimizer slots, and the spill store. Bytes are COPIED — the
        async writer serializes while training donates these very
        buffers (the snapshot_shards lesson)."""
        C, D = self.config.capacity, self.config.dim
        cache = np.array(self._cache, copy=True)
        state = [np.array(st, copy=True) for st in self._state]
        out = []
        for s in range(self.num_shards):
            d = self._slot_of[s]
            rids = np.fromiter(d.keys(), np.int64, len(d))
            slots = np.fromiter(d.values(), np.int32, len(d))
            g = s * C + slots
            payload = {
                "ids": rids,
                "slots": slots,
                "rows": cache[g] if len(d) else
                np.zeros((0, D), np.float32),
            }
            for t, st in enumerate(state):
                payload[f"state{t}"] = (
                    st[g] if len(d) else np.zeros((0, D), np.float32)
                )
            sp = self._spill[s]
            sids = np.fromiter(sp.keys(), np.int64, len(sp))
            payload["spill_ids"] = sids
            payload["spill_rows"] = (
                np.stack([sp[i][0] for i in sids.tolist()])
                if len(sp) else np.zeros((0, D), np.float32)
            )
            for t in range(self.num_state):
                payload[f"spill_state{t}"] = (
                    np.stack([sp[i][1 + t] for i in sids.tolist()])
                    if len(sp) else np.zeros((0, D), np.float32)
                )
            out.append(payload)
        return out

    def table_meta(self) -> dict:
        """Config echo for the generation manifest — restore verifies
        shape agreement instead of quietly mis-assembling."""
        return {
            "rows_total": self.config.rows_total,
            "dim": self.config.dim,
            "capacity": self.config.capacity,
            "num_shards": self.num_shards,
            "num_state": self.num_state,
            "placement": self.config.placement,
        }

    def restore_shards(self, payloads) -> None:
        """Rebuild residency + device buffers from `export_shards`
        payloads (the elastic resume path). LRU order, slot
        assignment, optimizer slots, and the spill store all come
        back exactly, so a resumed trainer evicts the same rows the
        dead one would have."""
        if len(payloads) != self.num_shards:
            raise ValueError(
                f"{len(payloads)} shard payloads for "
                f"{self.num_shards} shards"
            )
        C, D = self.config.capacity, self.config.dim
        cache = np.zeros((self._S, D), np.float32)
        state = [np.zeros((self._S, D), np.float32)
                 for _ in range(self.num_state)]
        self._slot_of = [OrderedDict() for _ in range(self.num_shards)]
        self._free = [list(range(C - 1, -1, -1))
                      for _ in range(self.num_shards)]
        self._spill = [dict() for _ in range(self.num_shards)]
        for s, p in enumerate(payloads):
            rids = np.asarray(p["ids"], np.int64)
            slots = np.asarray(p["slots"], np.int32)
            rows = np.asarray(p["rows"], np.float32)
            used = set()
            for j, (i, slot) in enumerate(
                zip(rids.tolist(), slots.tolist())
            ):
                self._slot_of[s][i] = slot
                used.add(slot)
                cache[s * C + slot] = rows[j]
                for t in range(self.num_state):
                    state[t][s * C + slot] = np.asarray(
                        p[f"state{t}"], np.float32
                    )[j]
            self._free[s] = [sl for sl in range(C - 1, -1, -1)
                             if sl not in used]
            sids = np.asarray(p["spill_ids"], np.int64)
            srows = np.asarray(p["spill_rows"], np.float32)
            sstate = [
                np.asarray(p[f"spill_state{t}"], np.float32)
                for t in range(self.num_state)
            ]
            for j, i in enumerate(sids.tolist()):
                self._spill[s][i] = (
                    srows[j].copy(),
                    *(ss[j].copy() for ss in sstate),
                )
        self._cache = jax.device_put(
            jnp.asarray(cache), self._sharding
        )
        self._state = tuple(
            jax.device_put(jnp.asarray(st), self._sharding)
            for st in state
        )
