"""Sharding rules: parameters and activations onto the device mesh.

The reference's model parallelism was (a) per-layer `device` placement in
ParallelNeuralNetwork (gserver/gradientmachines/ParallelNeuralNetwork.h:34,
61,63) and (b) pserver-sharded embedding tables pulled row-wise
(math/SparseRowMatrix.h:204, doc/design/cluster_train/
large_model_dist_train.md). TPU-first both become GSPMD sharding
annotations: parameters get a PartitionSpec over the mesh `model` axis and
XLA inserts the collectives; per-layer placement hints become
`with_sharding_constraint` on layer outputs.
"""

from __future__ import annotations

import re
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core.mesh import DATA_AXIS, MODEL_AXIS, SEQ_AXIS


def _axis_size(mesh: Mesh, axis: str) -> int:
    try:
        return mesh.shape[axis]
    except KeyError:
        return 1


def auto_param_spec(pc, mesh: Mesh) -> P:
    """Default tensor-parallel placement for one parameter.

    - row-sharded embedding tables (sparse_remote_update — the pserver
      sharded-table analogue): rows over `model` (or `data` if no model
      axis, matching ZeRO-style placement);
    - 2-D weights [in, out]: output dim over `model` when divisible
      (Megatron-style column parallel; XLA's sharding propagation derives
      the matching row-parallel layouts for consumers);
    - 1-D biases: over `model` when divisible and a model axis exists.
    """
    m = _axis_size(mesh, MODEL_AXIS)
    dims = tuple(pc.dims)
    if getattr(pc, "sparse_remote_update", False) and len(dims) == 2:
        if m > 1 and dims[0] % m == 0:
            return P(MODEL_AXIS, None)
        d = _axis_size(mesh, DATA_AXIS)
        if d > 1 and dims[0] % d == 0:
            return P(DATA_AXIS, None)
        return P()
    if m <= 1:
        return P()
    if getattr(pc, "expert_sharded", False) and dims and dims[0] % m == 0:
        # MoE expert weights [E, ...]: experts over the model axis (EP);
        # GSPMD turns the dispatch einsum into an all-to-all
        return P(*([MODEL_AXIS] + [None] * (len(dims) - 1)))
    if len(dims) == 2 and dims[1] % m == 0 and dims[1] >= m:
        return P(None, MODEL_AXIS)
    if len(dims) == 4 and dims[-1] % m == 0:  # conv kernels HWIO
        return P(None, None, None, MODEL_AXIS)
    return P()


class Sharder:
    """Maps parameter names to NamedShardings.

    `rules` is a list of (regex, PartitionSpec) tried in order; unmatched
    parameters fall back to `auto_param_spec`. The regex tier is the
    explicit-placement escape hatch (the analogue of the reference's
    per-layer `device` attribute)."""

    def __init__(self, mesh: Mesh, rules: Optional[list] = None):
        self.mesh = mesh
        self.rules = [(re.compile(pat), spec) for pat, spec in (rules or [])]

    def spec(self, name: str, pc) -> P:
        for pat, spec in self.rules:
            if pat.search(name):
                return spec
        return auto_param_spec(pc, self.mesh)

    def sharding(self, name: str, pc) -> NamedSharding:
        return NamedSharding(self.mesh, self.spec(name, pc))

    def param_shardings(self, param_confs: dict) -> dict:
        return {n: self.sharding(n, pc) for n, pc in param_confs.items()}


def activation_spec(mesh: Mesh, seq_sharded: bool = False) -> P:
    """Canonical activation layout: batch over `data`, optionally the
    time dim over `seq` (sequence parallelism)."""
    if seq_sharded and _axis_size(mesh, SEQ_AXIS) > 1:
        return P(DATA_AXIS, SEQ_AXIS)
    return P(DATA_AXIS)


def constrain(x, mesh: Mesh, spec: P):
    """with_sharding_constraint that tolerates rank < len(spec)."""
    def one(a):
        s = P(*tuple(spec)[: a.ndim])
        return jax.lax.with_sharding_constraint(a, NamedSharding(mesh, s))

    return jax.tree_util.tree_map(one, x)
