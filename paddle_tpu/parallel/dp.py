"""Data-parallel (and sharded-state) training over a device mesh.

Replaces three reference subsystems with one compiled program:
- MultiGradientMachine's per-device TrainerThreads + ring gradient merge
  (gserver/gradientmachines/MultiGradientMachine.cpp:389,502-598),
- the C++ sync parameter server (pserver/ParameterServer2.h:254,482,660:
  barriers, gradient add, server-side op_SGD),
- the Go pserver's dense shards (go/pserver/service.go:221,240).

TPU-first: the batch is sharded over the mesh "data" axis; params are
either replicated or sharded (ZeRO-style, the optimizer-state analogue of
pserver block shards). XLA inserts the psum/all-gather over ICI. The
optimizer runs sharded on-device — there is no parameter-server process.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.analysis.recompile_guard import RecompileGuard
from paddle_tpu.core.mesh import DATA_AXIS


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Arg leaves are [B, ...]: shard batch dim over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def param_sharding(mesh: Mesh, pc=None) -> NamedSharding:
    """Parameter placement: delegated to the tensor-parallel auto rules
    (parallel/sharding.py) — replicated on a pure-data mesh, model-sharded
    weights / row-sharded embedding tables when a `model` axis exists."""
    from paddle_tpu.parallel.sharding import auto_param_spec

    if pc is None:
        return NamedSharding(mesh, P())
    return NamedSharding(mesh, auto_param_spec(pc, mesh))


def shard_batch(feed: dict, mesh: Mesh) -> dict:
    """Device-put a host feed with batch-dim sharding."""
    sh = batch_sharding(mesh)

    def put(x):
        return jax.device_put(x, sh) if x is not None else None

    return jax.tree_util.tree_map(put, feed)


class TrainStep:
    """One jit-compiled train step: forward + grad + optimizer update.

    With a mesh, the feed is sharded over DATA_AXIS and params/opt-state
    are placed per `param_sharding`; XLA emits the gradient allreduce over
    ICI (the compiled replacement for ADD_GRADIENT + barriers,
    ParameterService.proto:24-41).

    With `watchdog=True` the step additionally computes an on-device
    all-finite reduction over the loss and every gradient leaf, SKIPS
    the whole update when any value is non-finite (params, opt-state
    and layer state keep their previous values — a bad batch can never
    poison the model), takes an `lr_scale` operand (the watchdog's
    backoff/re-warm multiplier; a traced scalar, so changing it never
    recompiles), and returns a 2-float `health` vector
    `[loss, all_finite]` IN PLACE of the scalar loss — the finiteness
    verdict rides the loss fetch the trainer already pays for, so the
    happy path adds zero device->host transfers."""

    def __init__(
        self,
        net,
        opt,
        mesh: Optional[Mesh] = None,
        donate=True,
        keep_outputs=None,
        sharding_rules=None,
        watchdog=False,
    ):
        self.net = net
        self.opt = opt
        self.mesh = mesh
        self.sharding_rules = sharding_rules
        self.watchdog = watchdog
        # Only declared outputs survive the step: returning every layer's
        # activations would pin all intermediates in HBM and block XLA
        # fusion/rematerialization.
        keep = set(keep_outputs or []) | set(net.output_names) | set(
            net.cost_names
        )
        # jit-cache-miss tracker (ISSUE 13): note() runs at TRACE
        # time only (it is a plain Python call in the traced body),
        # so the cached dispatch path pays nothing. The trainer arms
        # it after warmup; an armed retrace is a steady-state
        # recompile — the silent seconds-long stall the dispatch
        # -floor work exists to kill.
        guard = self.recompile_guard = RecompileGuard("train_step")

        def step(params, opt_state, state, feed, step_i, rng,
                 lr_scale=None):
            guard.note(params, feed)
            (loss, (outs, new_state)), grads = jax.value_and_grad(
                net.loss_fn, has_aux=True
            )(params, feed, state=state, train=True, rng=rng)
            new_params, new_opt_state = opt.update(
                grads, params, opt_state, step_i, lr_scale=lr_scale
            )
            outs = {k: v for k, v in outs.items() if k in keep}
            if not watchdog:
                return new_params, new_opt_state, new_state, loss, outs
            # all-finite reduction, fused into the update program: a
            # handful of per-leaf reductions + ANDs, no extra pass over
            # activations and no host sync
            finite = jnp.isfinite(loss)
            for g in jax.tree_util.tree_leaves(grads):
                finite = finite & jnp.all(jnp.isfinite(g))

            def _keep(new, old):
                return jnp.where(finite, new, old)

            new_params = jax.tree_util.tree_map(
                _keep, new_params, params
            )
            new_opt_state = jax.tree_util.tree_map(
                _keep, new_opt_state, opt_state
            )
            new_state = jax.tree_util.tree_map(_keep, new_state, state)
            health = jnp.stack([
                loss.astype(jnp.float32),
                finite.astype(jnp.float32),
            ])
            return new_params, new_opt_state, new_state, health, outs

        if mesh is not None:
            from paddle_tpu.parallel.sharding import Sharder

            rep = replicated(mesh)
            data = batch_sharding(mesh)
            sharder = Sharder(mesh, rules=sharding_rules)
            param_sh = sharder.param_shardings(net.param_confs)

            def param_tree_sharding(params):
                return {k: param_sh.get(k, rep) for k in params}

            self._param_sh = param_sh
            self._rep = rep
            self._data = data
            # in_shardings: params, opt_state (match params), state (rep),
            # feed (data), step (rep), rng (rep)
            self._step = jax.jit(
                step,
                donate_argnums=(0, 1, 2) if donate else (),
            )
        else:
            self._step = jax.jit(
                step, donate_argnums=(0, 1, 2) if donate else ()
            )

        # multi-step pipelining (ROADMAP 5d): N consecutive steps as a
        # lax.scan over the SAME step body inside ONE jitted dispatch,
        # so short-step models amortize the per-program submission
        # floor (~2-10 ms through the tunnel) N-fold. Per-step RNG is
        # fold_in(step_key, global_step) — bit-identical to what the
        # sequential loop derives, so N-step and 1-step training walk
        # the same trajectory. Returns stacked per-step losses (or
        # [n, 2] health vectors in watchdog mode) and stacked outs.
        def multi_step(params, opt_state, state, feeds, step_i,
                       step_key, lr_scale=None):
            guard.note(params, feeds)

            def body(carry, feed):
                params, opt_state, state, i = carry
                rng = jax.random.fold_in(step_key, i)
                params, opt_state, state, loss, outs = step(
                    params, opt_state, state, feed, i, rng,
                    lr_scale=lr_scale,
                )
                return (params, opt_state, state, i + 1), (loss, outs)

            carry = (params, opt_state, state, jnp.int32(step_i))
            (params, opt_state, state, _), (losses, outs) = jax.lax.scan(
                body, carry, feeds
            )
            return params, opt_state, state, losses, outs

        self._multi = jax.jit(
            multi_step, donate_argnums=(0, 1, 2) if donate else ()
        )

    def place(self, params, opt_state, state):
        """Place params/opt-state/state on the mesh per their shardings."""
        if self.mesh is None:
            return params, opt_state, state
        p = {
            k: jax.device_put(v, self._param_sh.get(k, self._rep))
            for k, v in params.items()
        }
        o = {
            k: jax.tree_util.tree_map(
                lambda x: jax.device_put(x, self._param_sh.get(k, self._rep)),
                v,
            )
            for k, v in opt_state.items()
        }
        s = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, self._rep), state
        )
        return p, o, s

    def multi(self, params, opt_state, state, feeds, step_i, step_key,
              lr_scale=None):
        """Run n = leading-dim(feeds) consecutive steps in ONE
        dispatch. `feeds` is the per-step feed pytree stacked on a new
        leading axis (jnp.stack over the batch feeds); `step_key` is
        the TRAINER's step key (per-step rngs are derived inside, so
        the trajectory matches n sequential __call__s exactly).
        Returns (params, opt_state, state, losses, outs) with losses
        [n] (or [n, 2] health vectors in watchdog mode) and outs
        leaves stacked [n, ...]. jax.jit retraces per distinct n —
        use one or two stable chunk sizes."""
        if self.mesh is not None:
            sh = NamedSharding(self.mesh, P(None, DATA_AXIS))
            feeds = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, sh) if x is not None else None,
                feeds,
            )
        if self.watchdog:
            return self._multi(
                params, opt_state, state, feeds, step_i, step_key,
                1.0 if lr_scale is None else float(lr_scale),
            )
        return self._multi(params, opt_state, state, feeds, step_i,
                           step_key)

    def __call__(self, params, opt_state, state, feed, step_i, rng,
                 lr_scale=None):
        if self.mesh is not None:
            feed = shard_batch(feed, self.mesh)
        if self.watchdog:
            # always pass the scale so the traced signature is stable;
            # a changed float re-dispatches, never recompiles
            return self._step(
                params, opt_state, state, feed, step_i, rng,
                1.0 if lr_scale is None else float(lr_scale),
            )
        return self._step(params, opt_state, state, feed, step_i, rng)

    def aot(self, params, opt_state, state, feed, step_i, rng):
        """AOT-compile the step for exactly these args; returns
        (run, hlo_text) where run() executes the compiled step. The
        multi-chip gate asserts the expected collectives (all-reduce
        for dp grads, all-to-all for sp/MoE dispatch,
        collective-permute for ring/pp) are really in hlo_text, so a
        sharding-dropping regression fails loudly instead of silently
        running replicated. AOT compilation does NOT populate the jit
        dispatch cache — run() reuses the compiled executable so the
        step is compiled once."""
        if self.mesh is not None:
            feed = shard_batch(feed, self.mesh)
        args = (params, opt_state, state, feed, step_i, rng)
        if self.watchdog:
            args += (1.0,)
        compiled = self._step.lower(*args).compile()

        def run():
            return compiled(*args)

        return run, compiled.as_text()


def assert_collectives(hlo: str, where: str, *, require=(),
                       forbid=()) -> dict:
    """Parser-backed collective gate (ISSUE 15): parse the compiled
    module's collective INSTRUCTIONS (analysis/hlo_text) and assert
    each `require`d kind appears at least once and each `forbid`den
    kind not at all. Returns {kind: count} so callers can reason
    about the mix.

    This replaces the old substring gate (`"all-reduce" in hlo`): a
    substring matches comments, metadata op_names, and region names —
    e.g. a fused computation NAMED after an inlined-away all-reduce —
    so it can vacuously pass after the real collective is gone. The
    parser only counts instruction lines (async -start/-done pairs
    collapse to one), which is the same object the spmd-audit byte
    budgets are built from."""
    from paddle_tpu.analysis import hlo_text as _hlo

    counts: dict = {}
    for c in _hlo.parse_collectives(hlo.splitlines()):
        counts[c["kind"]] = counts.get(c["kind"], 0) + 1
    for kind in require:
        if not counts.get(kind):
            raise AssertionError(
                f"{where}: expected a {kind!r} instruction in the "
                f"compiled HLO but none parsed (found: {counts}) — "
                f"a sharding was dropped"
            )
    for kind in forbid:
        if counts.get(kind):
            raise AssertionError(
                f"{where}: {counts[kind]} forbidden {kind!r} "
                f"instruction(s) in the compiled HLO — the program "
                f"is repartitioning instead of staying sharded"
            )
    return counts
