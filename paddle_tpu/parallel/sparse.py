"""Sharded embedding tables + row-sparse updates (large-model training).

Capability parity with the reference's sparse distributed training: huge
embedding tables sharded across pservers, trainers prefetching only the
rows a batch touches and pushing row-sparse gradients
(math/SparseRowMatrix.h:29,204,235; trainer/RemoteParameterUpdater.h:265;
ParameterService.proto:40 GET_PARAMETER_SPARSE;
doc/design/cluster_train/large_model_dist_train.md).

TPU-first: the table lives row-sharded over the mesh (`model` axis) in
HBM. Lookup is a shard_map: each shard gathers the rows it owns and a
psum combines partial rows — one ICI allreduce instead of a pserver RPC.
The backward of this program is automatically the row-sparse
scatter-add, and `touched_rows`/`apply_rows` reproduce the
"optimize only touched rows" update rule (ThreadParameterUpdater.h:71
catchUpWith semantics) for the host-side updater parity tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core.mesh import MODEL_AXIS


def embedding_lookup(table, ids, mesh: Mesh, *, axis: str = MODEL_AXIS):
    """Gather rows from a row-sharded table.

    table: [V, D] sharded P(axis, None); ids: int32 [...] replicated.
    Returns [..., D] replicated (shard it over data/batch downstream via
    sharding constraints; XLA folds the transpose)."""
    n = mesh.shape[axis]
    V = table.shape[0]
    assert V % n == 0, f"vocab {V} not divisible by {n} shards"
    rows_local = V // n

    def local(tbl, ids):
        shard = lax.axis_index(axis)
        local_ids = ids - shard * rows_local
        ok = (local_ids >= 0) & (local_ids < rows_local)
        safe = jnp.clip(local_ids, 0, rows_local - 1)
        rows = jnp.take(tbl, safe, axis=0)
        rows = jnp.where(ok[..., None], rows, 0)
        return lax.psum(rows, axis)

    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis, None), P()),
        out_specs=P(),
        check_vma=False,
    )(table, ids)


def touched_rows(ids, vocab_size: int):
    """Boolean [V] marker of rows referenced by this batch — the analogue
    of the prefetch row-id set (SparsePrefetchRowCpuMatrix)."""
    return (
        jnp.zeros((vocab_size,), jnp.bool_)
        .at[ids.reshape(-1)]
        .set(True)
    )


def apply_rows(update_fn, param, grad, touched):
    """DENSE reference implementation: apply `update_fn(param_rows,
    grad_rows) -> new_rows` to touched rows, leaving the rest
    bit-identical — the sparse_update optimizer contract
    (ParameterOptimizer needSpecialTraversal / catchUpWith). O(V) — the
    parity oracle for `sparse_apply`, which is the production path."""
    new = update_fn(param, grad)
    return jnp.where(touched[:, None], new, param)


def sparse_apply(update_fn, param, ids, grads, state=(), num_slots=None):
    """Gather-touched -> update -> scatter: step cost independent of V.

    The reference's large-model update rule (math/SparseRowMatrix.h:204
    SparsePrefetchRowCpuMatrix + trainer/RemoteParameterUpdater.h:265
    SparseRemoteParameterUpdater; design
    doc/design/cluster_train/large_model_dist_train.md): only the rows a
    batch touches are pulled, optimized, and written back.

    param: [V, D]. ids: int [N] (token occurrences, duplicates fine).
    grads: [N, D] per-occurrence gradients (the row-sparse cotangent of
    the lookups). state: tuple of [V, ...] optimizer-state tensors
    sliced/written alongside param (momentum, adagrad accumulators...).
    update_fn(param_rows, grad_rows, *state_rows) ->
    (new_rows, *new_state_rows) — or just new_rows when state is empty.
    num_slots: static unique-row capacity (default N).

    Returns (new_param, new_state) (new_state a tuple like `state`).
    All compute is O(num_slots * D): ids are unique'd (sorted, static
    size), per-occurrence grads segment-summed into their slot, rows
    gathered once, updated, and scattered back as deltas."""
    ids = ids.reshape(-1).astype(jnp.int32)
    n = ids.shape[0]
    k = num_slots or n
    uids, inv = jnp.unique(
        ids, size=k, fill_value=-1, return_inverse=True
    )
    valid = uids >= 0
    safe = jnp.where(valid, uids, 0)
    # Capacity guard: with num_slots below the batch's true unique
    # count, jnp.unique truncates and `inv` aliases the dropped ids
    # onto surviving slots — their gradients would land on WRONG rows.
    # An occurrence only contributes where its slot really holds its
    # id; overflowed ids are skipped this step (matching the
    # prefetch-capacity semantics of SparsePrefetchRowCpuMatrix rather
    # than corrupting neighbors).
    inv_flat = inv.reshape(-1)
    hit = (uids[inv_flat] == ids).astype(grads.dtype)
    gflat = grads.reshape((n,) + grads.shape[1:])
    gflat = gflat * hit.reshape((n,) + (1,) * (gflat.ndim - 1))
    gsum = (
        jnp.zeros((k,) + grads.shape[1:], grads.dtype)
        .at[inv_flat]
        .add(gflat)
    )
    prows = param[safe]
    srows = tuple(s[safe] for s in state)
    out = update_fn(prows, gsum, *srows)
    if state:
        new_rows, *new_srows = out
    else:
        new_rows, new_srows = out, []
    # scatter as masked DELTAS: invalid slots all alias row 0, and
    # adding zero there is order-independent (a .set with duplicate
    # indices would not be)
    vmask = valid[:, None].astype(param.dtype)
    new_param = param.at[safe].add((new_rows - prows) * vmask)
    new_state = tuple(
        s.at[safe].add(
            (ns - sr) * valid.reshape((k,) + (1,) * (ns.ndim - 1)).astype(
                s.dtype
            )
        )
        for s, sr, ns in zip(state, srows, new_srows)
    )
    return new_param, new_state
