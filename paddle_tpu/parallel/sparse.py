"""Sharded embedding tables + row-sparse updates (large-model training).

Capability parity with the reference's sparse distributed training: huge
embedding tables sharded across pservers, trainers prefetching only the
rows a batch touches and pushing row-sparse gradients
(math/SparseRowMatrix.h:29,204,235; trainer/RemoteParameterUpdater.h:265;
ParameterService.proto:40 GET_PARAMETER_SPARSE;
doc/design/cluster_train/large_model_dist_train.md).

TPU-first: the table lives row-sharded over the mesh (`model` axis) in
HBM. Lookup is a shard_map: each shard gathers the rows it owns and a
psum combines partial rows — one ICI allreduce instead of a pserver RPC.
The backward of this program is automatically the row-sparse
scatter-add, and `touched_rows`/`apply_rows` reproduce the
"optimize only touched rows" update rule (ThreadParameterUpdater.h:71
catchUpWith semantics) for the host-side updater parity tests.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core.mesh import MODEL_AXIS
from paddle_tpu.core.mesh import shard_map as _shard_map

# Per-process cache-busting constant for layout-pinned programs,
# embedded by adding it to the table's SCRATCH row (index V — a
# don't-care landing zone) on TRACED outputs: an O(D) touch whose
# distinct constant survives into the lowered module the
# persistent-cache key hashes. Rationale: the persistent compilation
# cache does not honor custom input/output LAYOUT contracts when an
# executable is reloaded by a later process — the reloaded program
# expects/produces default layouts and crashes pinned callers
# ('Layout passed to jit does not match the layout on the respective
# arg'). Keying each process to its own entries keeps the broken
# reload path unreachable while in-process jit reuse (and all
# non-layout programs' caching) stays intact. Scoping the fix at the
# cache layer instead is not possible mid-process: the cache object
# latches at first use, and flipping jax_enable_compilation_cache /
# the cache dir afterwards has no effect (measured). The magnitude is
# a small integer, exactly representable in every table dtype incl.
# fp16/int (a subnormal-sized salt would underflow to the SAME 0.0 in
# fp16 and silently disable the keying).
import os as _os

_PROC_SALT = float((_os.getpid() & 0x3FF) + 1)


def _salt_scratch(table):
    """Add the per-process salt to the scratch row only."""
    s = jnp.asarray(_PROC_SALT, table.dtype)
    return table.at[-1].add(s)


def embedding_lookup(table, ids, mesh: Mesh, *, axis: str = MODEL_AXIS):
    """Gather rows from a row-sharded table.

    table: [V, D] sharded P(axis, None); ids: int32 [...] replicated.
    Returns [..., D] replicated (shard it over data/batch downstream via
    sharding constraints; XLA folds the transpose)."""
    n = mesh.shape[axis]
    V = table.shape[0]
    assert V % n == 0, f"vocab {V} not divisible by {n} shards"
    rows_local = V // n

    def local(tbl, ids):
        shard = lax.axis_index(axis)
        local_ids = ids - shard * rows_local
        ok = (local_ids >= 0) & (local_ids < rows_local)
        safe = jnp.clip(local_ids, 0, rows_local - 1)
        rows = jnp.take(tbl, safe, axis=0)
        rows = jnp.where(ok[..., None], rows, 0)
        return lax.psum(rows, axis)

    return _shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis, None), P()),
        out_specs=P(),
        check_vma=False,
    )(table, ids)


def touched_rows(ids, vocab_size: int):
    """Boolean [V] marker of rows referenced by this batch — the analogue
    of the prefetch row-id set (SparsePrefetchRowCpuMatrix)."""
    return (
        jnp.zeros((vocab_size,), jnp.bool_)
        .at[ids.reshape(-1)]
        .set(True)
    )


def apply_rows(update_fn, param, grad, touched):
    """DENSE reference implementation: apply `update_fn(param_rows,
    grad_rows) -> new_rows` to touched rows, leaving the rest
    bit-identical — the sparse_update optimizer contract
    (ParameterOptimizer needSpecialTraversal / catchUpWith). O(V) — the
    parity oracle for `sparse_apply` and `SparseUpdater`."""
    new = update_fn(param, grad)
    return jnp.where(touched[:, None], new, param)


def _unique_segment_grads(flat_ids, grads, k):
    """Unique the touched ids into k sorted slots and segment-sum the
    per-occurrence grads into them. Returns (uids [k] with -1 fills at
    the END, gsum [k, ...]).

    Capacity guard: with k below the batch's true unique count,
    jnp.unique truncates and the inverse aliases dropped ids onto
    surviving slots — their gradients would land on WRONG rows. An
    occurrence only contributes where its slot really holds its id;
    overflowed ids are skipped this step (matching the prefetch-capacity
    semantics of SparsePrefetchRowCpuMatrix rather than corrupting
    neighbors). Shared by sparse_apply and SparseUpdater so the oracle
    and the kernel cannot diverge."""
    n = flat_ids.shape[0]
    uids, inv = jnp.unique(
        flat_ids, size=k, fill_value=-1, return_inverse=True
    )
    inv = inv.reshape(-1)
    hit = (uids[inv] == flat_ids).astype(grads.dtype)
    g = grads.reshape((n,) + grads.shape[1:])
    g = g * hit.reshape((n,) + (1,) * (g.ndim - 1))
    gsum = (
        jnp.zeros((k,) + grads.shape[1:], grads.dtype).at[inv].add(g)
    )
    return uids, gsum


def sparse_apply(update_fn, param, ids, grads, state=(), num_slots=None):
    """Gather-touched -> update -> scatter, as ONE functional XLA
    program — use this form INSIDE a larger jit (a training step whose
    other ops dominate), and as the numpy-checkable oracle for
    `SparseUpdater`. For a STANDALONE large-table update step (the
    pserver-analogue big-embedding path), use `SparseUpdater`: on its
    own, this formulation pays O(V) full-table relayout copies that XLA
    inserts between the gather and the scatter (measured and documented
    in PERF.md), which the SparseUpdater kernel eliminates.

    The reference's large-model update rule (math/SparseRowMatrix.h:204
    SparsePrefetchRowCpuMatrix + trainer/RemoteParameterUpdater.h:265
    SparseRemoteParameterUpdater; design
    doc/design/cluster_train/large_model_dist_train.md): only the rows a
    batch touches are pulled, optimized, and written back.

    param: [V, D]. ids: int [N] (token occurrences, duplicates fine).
    grads: [N, D] per-occurrence gradients (the row-sparse cotangent of
    the lookups). state: tuple of [V, ...] optimizer-state tensors
    sliced/written alongside param (momentum, adagrad accumulators...).
    update_fn(param_rows, grad_rows, *state_rows) ->
    (new_rows, *new_state_rows) — or just new_rows when state is empty.
    num_slots: static unique-row capacity (default N).

    Returns (new_param, new_state) (new_state a tuple like `state`).
    All compute is O(num_slots * D): ids are unique'd (sorted, static
    size), per-occurrence grads segment-summed into their slot, rows
    gathered once, updated, and scattered back as deltas."""
    ids = ids.reshape(-1).astype(jnp.int32)
    k = num_slots or ids.shape[0]
    uids, gsum = _unique_segment_grads(ids, grads, k)
    valid = uids >= 0
    safe = jnp.where(valid, uids, 0)
    prows = param[safe]
    srows = tuple(s[safe] for s in state)
    out = update_fn(prows, gsum, *srows)
    if state:
        new_rows, *new_srows = out
    else:
        new_rows, new_srows = out, []
    # scatter as masked DELTAS: invalid slots all alias row 0, and
    # adding zero there is order-independent (a .set with duplicate
    # indices would not be)
    vmask = valid[:, None].astype(param.dtype)
    new_param = param.at[safe].add((new_rows - prows) * vmask)
    new_state = tuple(
        s.at[safe].add(
            (ns - sr) * valid.reshape((k,) + (1,) * (ns.ndim - 1)).astype(
                s.dtype
            )
        )
        for s, sr, ns in zip(state, srows, new_srows)
    )
    return new_param, new_state


class SparseUpdater:
    """Truly V-independent sparse step: ONE Pallas kernel updates the
    touched rows of the table (and optimizer state) IN PLACE.

    Why a kernel: in plain XLA the table is both gathered (wants
    row-major) and scattered (the compiler picks dim0-minor tiling for
    [V, small-D] tables), so every formulation materializes full-table
    relayout copies — measured in round 2 as `ctr_sparse_step_v_independence`
    = 2.17 (a 4x larger table doubled step time) with the copies
    visible in the HLO. The Mosaic kernel owns the layout end to end:
    tables are born in the kernel's row-major layout (`place`), the
    grid walks the k unique touched rows via scalar-prefetched indices,
    and input_output_aliases make the update genuinely in place.
    Measured: 2.8 ms at 1M rows vs 3.5 ms at 4M rows x 64 (the
    dispatch floor) vs 6.4/13.8 ms for the XLA scatter formulation.

    This is the TPU realization of the reference's in-place sparse-row
    update (math/SparseRowMatrix.h:204 SparsePrefetchRowCpuMatrix;
    trainer/RemoteParameterUpdater.h:265;
    doc/design/cluster_train/large_model_dist_train.md): like the
    pserver-hosted table, the placed table lives outside the regular
    training program and only its touched rows move.

    Layout contract: tables are [V, 1, D] arrays placed by
    `place()` (the singleton axis satisfies Mosaic's (8,128) block
    tiling rule for single-row blocks). `unplace()` returns a plain
    [V, D] numpy view for checkpointing.

    Overflow: when the batch touches FEWER than num_slots unique rows,
    the unused fill slots map to a dedicated SCRATCH row appended by
    `place()` (index V), so they write only scratch — never a real
    row. (Masking the write instead would race: the pipeline
    prefetches each slot's block before earlier slots' write-backs, so
    an "unchanged" write of a real row could clobber a real update.)
    When the batch touches MORE than num_slots unique rows,
    jnp.unique truncation keeps the num_slots SMALLEST ids; the
    dropped ids' gradients are zeroed by the hit-mask in
    _unique_segment_grads before the kernel ever runs — skipped this
    step, never corrupting neighbors (sparse_apply's contract).

    Usage:
        upd = SparseUpdater(momentum_update)
        param = upd.place(table_2d)          # once per table
        mom = upd.place(np.zeros_like(table_2d))
        param, (mom,) = upd(param, ids, grads, (mom,))  # per step;
        # the PREVIOUS buffers are donated (invalidated)
    """

    def __init__(self, update_fn, num_slots=None, interpret=None):
        self.update_fn = update_fn
        self.num_slots = num_slots
        if interpret is None:
            interpret = jax.devices()[0].platform != "tpu"
        self._interpret = interpret
        self._steps: dict = {}
        # what the runtime ACTUALLY produced per (shape, dtype) — TPU
        # tilings are dtype-dependent, so one recorded format must not
        # be forced onto tables of another dtype
        self._achieved_fmt: dict = {}
        self._relayouts: dict = {}

    # ---- table placement ----
    def _format(self, shape=None, dtype=None):
        """The table format every layout-pinned program agrees on.
        Until a table of this (shape, dtype) is placed this is the
        REQUESTED row-major layout; afterwards it is whatever the
        runtime ACTUALLY produced for that request (`place` records
        it) — runtimes differ in which layouts/tilings they honor
        (one axon runtime honored Layout((0,1,2)) exactly, a later
        one substituted a (1,128)-tiled variant and IGNORED custom
        device_put layouts entirely), and hard-coding the ideal form
        makes every pinned jit reject the real arrays. Called with no
        key (external users sharing ONE table kind) it returns the
        single recorded format when unambiguous."""
        if shape is not None:
            key = (tuple(shape), str(dtype))
            if key in self._achieved_fmt:
                return self._achieved_fmt[key]
        elif len(self._achieved_fmt) == 1:
            return next(iter(self._achieved_fmt.values()))
        from jax.experimental.layout import Format, Layout
        from jax.sharding import SingleDeviceSharding

        return Format(
            Layout((0, 1, 2)), SingleDeviceSharding(jax.devices()[0])
        )

    def place(self, table):
        """[V, D] -> [V, 1, D] device array in the kernel's row-major
        layout (no per-step relayout copies).

        The relayout runs through a per-process-salted jitted identity
        rather than a layouted device_put: the persistent compilation
        cache does not preserve custom layout contracts when a
        transfer/executable is RELOADED in a later process (the array
        arrives default-layout and every pinned consumer rejects it
        with 'Layout passed to jit does not match...'). The salt keys
        each process to a fresh compile of the layout-bearing
        programs; see _jit_pinned."""
        t = np.asarray(table)
        v, d = t.shape
        # +1 scratch row: the landing zone for fill/overflow slots
        t = np.concatenate([t, np.zeros((1, d), t.dtype)], axis=0)
        if self._interpret:
            return jnp.asarray(t.reshape(v + 1, 1, d))
        arr = jax.device_put(t.reshape(v + 1, 1, d))
        key = (arr.shape, str(arr.dtype))
        if key not in self._relayouts:
            self._relayouts[key] = jax.jit(
                _salt_scratch,
                out_shardings=self._format(arr.shape, arr.dtype),
            )
        arr = self._relayouts[key](arr)
        if key not in self._achieved_fmt:
            # record what the runtime really produced; all pinned jits
            # (_jit_pinned and external in_shardings users) key off it
            self._achieved_fmt[key] = arr.format
        else:
            assert arr.format == self._achieved_fmt[key], (
                f"runtime produced {arr.format} for {key}, previously "
                f"{self._achieved_fmt[key]} — layout contract drifted"
            )
        return arr

    @staticmethod
    def unplace(table):
        t = np.asarray(table)
        return t.reshape(t.shape[0], t.shape[2])[:-1]  # drop scratch

    # ---- the kernel ----
    def _make_call(self, V, D, k, n_state, dtype):
        """The pallas_call updating k touched rows in place (shared by
        the single-step and amortized multi-step builders)."""
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        update_fn = self.update_fn

        def kernel(ids_ref, gsum_ref, *refs):
            table_refs = refs[: 1 + n_state]
            out_refs = refs[1 + n_state :]
            p = table_refs[0][...]
            srows = tuple(r[...] for r in table_refs[1:])
            out = update_fn(p, gsum_ref[...], *srows)
            if n_state:
                new_p, *new_s = out
            else:
                new_p, new_s = out, []
            # every slot's row is distinct (unique ids; fills share only
            # the scratch row, whose content is don't-care), so writes
            # are unconditional — no masking, no pipeline write races
            out_refs[0][...] = new_p
            for o, ns in zip(out_refs[1:], new_s):
                o[...] = ns

        def row_map(i, ids):
            # V here is the scratch row index (tables are [V+1, 1, D])
            return (jnp.minimum(ids[i], V), 0, 0)

        blk = pl.BlockSpec((1, 1, D), row_map)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(k,),
            in_specs=[pl.BlockSpec((1, 1, D), lambda i, ids: (i, 0, 0))]
            + [blk] * (1 + n_state),
            out_specs=[blk] * (1 + n_state),
        )
        shape = jax.ShapeDtypeStruct((V + 1, 1, D), dtype)
        # operand index space includes the scalar-prefetch arg: ids=0,
        # gsum=1, tables start at 2; alias table_j -> output_j
        aliases = {2 + j: j for j in range(1 + n_state)}
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[shape] * (1 + n_state),
            input_output_aliases=aliases,
            interpret=self._interpret,
        )

    def _one_step(self, call, V, k):
        def step_once(param, state, ids, grads):
            flat = ids.reshape(-1).astype(jnp.int32)
            uids, gsum = _unique_segment_grads(
                flat, grads.reshape((flat.shape[0], -1)), k
            )
            oob = jnp.where(uids >= 0, uids, V).astype(jnp.int32)
            outs = call(oob, gsum.reshape(k, 1, -1), param, *state)
            return outs[0], tuple(outs[1:])

        return step_once

    def _jit_pinned(self, fn, n_state, V=None, D=None, dtype=None):
        """Donating jit with the table layouts pinned on BOTH sides:
        without out_shardings the compiler would emit outputs in the
        default (dim0-minor) layout and every subsequent step would pay
        two full-table relayout copies on entry.

        The program carries a PER-PROCESS constant: the persistent XLA
        compilation cache does not honor the pinned input layouts when
        an executable is reloaded in a later process ('Layout passed
        to jit does not match the layout on the respective arg'), so
        each process keys its own entry and the broken cross-process
        reload path can never trigger. In-process jit reuse is
        unaffected."""
        def salted(param, state, ids, grads):
            out_p, out_s = fn(param, state, ids, grads)
            # O(D) touch of the don't-care scratch row only
            return _salt_scratch(out_p), out_s

        if self._interpret:
            return jax.jit(salted, donate_argnums=(0, 1))
        fmt = (
            self._format((V + 1, 1, D), dtype)
            if V is not None
            else self._format()
        )
        return jax.jit(
            salted,
            donate_argnums=(0, 1),
            in_shardings=(fmt, (fmt,) * n_state, None, None),
            out_shardings=(fmt, (fmt,) * n_state),
        )

    def _build(self, V, D, k, n_state, dtype):
        call = self._make_call(V, D, k, n_state, dtype)
        return self._jit_pinned(self._one_step(call, V, k), n_state,
                                V=V, D=D, dtype=dtype)

    def _build_multi(self, V, D, k, n_state, dtype, n_steps):
        """n_steps updates inside ONE jitted program (lax.fori_loop over
        the kernel). Amortizes the per-dispatch floor so benchmarks
        measure the row-update work itself, and serves k-step update
        bursts (the catchUpWith batching) with one dispatch."""
        call = self._make_call(V, D, k, n_state, dtype)
        step = self._one_step(call, V, k)

        def steps(param, state, ids_seq, grads_seq):
            def body(i, carry):
                p, s = carry
                ids = jax.lax.dynamic_index_in_dim(
                    ids_seq, i, keepdims=False
                )
                g = jax.lax.dynamic_index_in_dim(
                    grads_seq, i, keepdims=False
                )
                return step(p, s, ids, g)

            return jax.lax.fori_loop(
                0, n_steps, body, (param, tuple(state))
            )

        return self._jit_pinned(steps, n_state, V=V, D=D, dtype=dtype)

    def __call__(self, param, ids, grads, state=()):
        V = param.shape[0] - 1  # last row is scratch
        D = param.shape[2]
        k = self.num_slots or int(np.prod(ids.shape))
        key = (V, D, k, len(state), str(param.dtype))
        if key not in self._steps:
            self._steps[key] = self._build(
                V, D, k, len(state), param.dtype
            )
        return self._steps[key](param, tuple(state), ids, grads)

    def run_steps(self, param, ids_seq, grads_seq, state=()):
        """Apply n_steps sequential updates in one dispatch.
        ids_seq: [n_steps, N]; grads_seq: [n_steps, N, D]."""
        V = param.shape[0] - 1
        D = param.shape[2]
        n_steps = ids_seq.shape[0]
        k = self.num_slots or int(np.prod(ids_seq.shape[1:]))
        key = ("multi", V, D, k, len(state), str(param.dtype), n_steps)
        if key not in self._steps:
            self._steps[key] = self._build_multi(
                V, D, k, len(state), param.dtype, n_steps
            )
        return self._steps[key](param, tuple(state), ids_seq, grads_seq)

