"""Sharded embedding tables + row-sparse updates (large-model training).

Capability parity with the reference's sparse distributed training: huge
embedding tables sharded across pservers, trainers prefetching only the
rows a batch touches and pushing row-sparse gradients
(math/SparseRowMatrix.h:29,204,235; trainer/RemoteParameterUpdater.h:265;
ParameterService.proto:40 GET_PARAMETER_SPARSE;
doc/design/cluster_train/large_model_dist_train.md).

TPU-first: the table lives row-sharded over the mesh (`model` axis) in
HBM. Lookup is a shard_map: each shard gathers the rows it owns and a
psum combines partial rows — one ICI allreduce instead of a pserver RPC.
The backward of this program is automatically the row-sparse
scatter-add, and `touched_rows`/`apply_rows` reproduce the
"optimize only touched rows" update rule (ThreadParameterUpdater.h:71
catchUpWith semantics) for the host-side updater parity tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core.mesh import MODEL_AXIS


def embedding_lookup(table, ids, mesh: Mesh, *, axis: str = MODEL_AXIS):
    """Gather rows from a row-sharded table.

    table: [V, D] sharded P(axis, None); ids: int32 [...] replicated.
    Returns [..., D] replicated (shard it over data/batch downstream via
    sharding constraints; XLA folds the transpose)."""
    n = mesh.shape[axis]
    V = table.shape[0]
    assert V % n == 0, f"vocab {V} not divisible by {n} shards"
    rows_local = V // n

    def local(tbl, ids):
        shard = lax.axis_index(axis)
        local_ids = ids - shard * rows_local
        ok = (local_ids >= 0) & (local_ids < rows_local)
        safe = jnp.clip(local_ids, 0, rows_local - 1)
        rows = jnp.take(tbl, safe, axis=0)
        rows = jnp.where(ok[..., None], rows, 0)
        return lax.psum(rows, axis)

    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis, None), P()),
        out_specs=P(),
        check_vma=False,
    )(table, ids)


def touched_rows(ids, vocab_size: int):
    """Boolean [V] marker of rows referenced by this batch — the analogue
    of the prefetch row-id set (SparsePrefetchRowCpuMatrix)."""
    return (
        jnp.zeros((vocab_size,), jnp.bool_)
        .at[ids.reshape(-1)]
        .set(True)
    )

def apply_rows(update_fn, param, grad, touched):
    """Apply `update_fn(param_rows, grad_rows) -> new_rows` only to touched
    rows, leaving the rest bit-identical — the sparse_update optimizer
    contract (ParameterOptimizer needSpecialTraversal / catchUpWith)."""
    new = update_fn(param, grad)
    return jnp.where(touched[:, None], new, param)
