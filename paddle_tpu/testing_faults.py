"""Fault-injection harness for the elastic-training test suite.

Reproducing the reference's fault-tolerance story (a trainer SIGKILLed
mid-task re-leases through the Go master, go/master/service.go:313; a
master restart recovers from its snapshot, service.go:166-207) requires
*injecting* those faults deterministically. This module is the one
place the tests get their violence from:

- `kill_process`: SIGKILL a worker/master subprocess (no cleanup, no
  atexit — the honest crash).
- `FlakyProxy`: a TCP proxy in front of the master that can refuse,
  reset (RST via SO_LINGER 0), delay, or cut connections on command —
  drives the master-client retry/backoff tests without racing a real
  master restart.
- `truncate_file` / `corrupt_file`: tear or bit-flip a checkpoint
  shard to exercise manifest rejection and fallback.
- `start_preemptible_trainer`: a REAL SGD trainer subprocess with
  checkpointing + auto-resume, the target for SIGTERM-preemption and
  NaN-injection experiments (shared by tests/test_elastic_faults.py
  and the `mc_preempt_recovery` bench row).

Test-support code, but shipped in the package (like the reference's
paddle/cuda stubs) so downstream users can fault-test their own
deployments.
"""

from __future__ import annotations

import os
import signal
import socket
import struct
import subprocess
import sys
import threading


class TransientFault:
    """Wrap a callable so its first `fail` calls raise `exc`, then it
    passes through — the deterministic 'NFS hiccup' injector for the
    async-checkpoint retry/backoff contract (ISSUE 20 satellite).

        cp = AsyncCheckpointer(d)
        cp._write_shard = TransientFault(cp._write_shard, fail=2)

    The checkpointer's bounded-backoff retry must absorb `fail` <=
    retries transient OSErrors without ever latching `last_error`;
    `fail` > retries must still surface."""

    def __init__(self, fn, fail: int = 1, exc: Exception = None):
        self.fn = fn
        self.remaining = int(fail)
        self.exc = exc if exc is not None else OSError(
            "injected transient write failure"
        )
        self.calls = 0
        self.failures = 0

    def __call__(self, *args, **kwargs):
        self.calls += 1
        if self.remaining > 0:
            self.remaining -= 1
            self.failures += 1
            raise self.exc
        return self.fn(*args, **kwargs)


def write_torn_table_generation(save_dir: str, generation: int,
                                payloads, fail_after_shard: int,
                                meta=None, tear: str = "missing"):
    """Deterministically reproduce a sharded-table checkpoint writer
    SIGKILLed between table shard `fail_after_shard` and the next one
    (ISSUE 20 satellite): the generation manifest (written first, as
    the real writer does) names ALL len(payloads) shards, but only
    shards 0..fail_after_shard exist on disk.

    tear="missing": the next shard simply never lands (killed before
    its write began). tear="short": shard `fail_after_shard` itself
    is additionally truncated to half its bytes AFTER its .ok.json
    committed (killed mid-flush on a filesystem that reordered the
    rename) — the checksum path, not just the existence path.

    Reused by the elastic kill/resume tests and the
    quarantine-and-rebuild tests so torn-recovery is exercised
    against one canonical injury, not ad-hoc file surgery."""
    from paddle_tpu.trainer import async_checkpoint as ac

    d = ac.begin_table_generation(save_dir, generation,
                                  num_shards=len(payloads), meta=meta)
    last = None
    for s in range(min(fail_after_shard + 1, len(payloads))):
        last = ac.write_table_shard(save_dir, generation, s,
                                    payloads[s])
    if tear == "short" and last is not None:
        truncate_file(last, keep_fraction=0.5)
    return d


def kill_process(proc) -> None:
    """SIGKILL a subprocess.Popen and reap it. The process gets no
    chance to flush, ack, or release leases — exactly the crash the
    elastic master must absorb."""
    if proc.poll() is None:
        proc.send_signal(signal.SIGKILL)
    proc.wait()


def truncate_file(path: str, keep_fraction: float = 0.5) -> int:
    """Truncate `path` to `keep_fraction` of its size (a torn write /
    partial flush at crash). Returns the new size."""
    size = os.path.getsize(path)
    keep = int(size * keep_fraction)
    with open(path, "r+b") as f:
        f.truncate(keep)
    return keep


def corrupt_file(path: str, offset: int = None, nbytes: int = 8) -> None:
    """Flip bits in-place (silent media corruption — same size, wrong
    payload). Defaults to the middle of the file."""
    size = os.path.getsize(path)
    if offset is None:
        offset = size // 2
    with open(path, "r+b") as f:
        f.seek(offset)
        chunk = f.read(nbytes)
        f.seek(offset)
        f.write(bytes(b ^ 0xFF for b in chunk))


# ---- preemptible trainer worker -------------------------------------
#
# A tiny but REAL training job (fc classifier, deterministic data,
# async checkpoints each pass) that auto-resumes from SAVE_DIR and
# appends one JSON line per trained batch to OUT_FILE:
#     {"pass": p, "bi": i, "step": g, "loss": c}
#     {"resume": start_pass, "skip": k}     on auto-resume
#     {"preempted": pass, "bi": n}          before exiting 75
#     {"done": true}                        on completion
# NAN_AT (a global step index) poisons that batch's features with NaN
# — the watchdog must skip/rollback it, never the operator.
PREEMPTIBLE_TRAINER_SRC = """
import json, os, sys, time
sys.path.insert(0, os.environ["REPO"])
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

# METRICS_FILE: attach the obs JSONL event stream — watchdog rungs,
# preemption flushes, and per-pass timelines land there with
# global_step stamps (read back via read_metrics_records)
_mf = os.environ.get("METRICS_FILE")
if _mf:
    from paddle_tpu.obs import metrics as _om
    _om.enable_event_stream(_mf, flush_interval_s=0.2)
# PADDLE_FLIGHT_DIR: arm the anomaly flight recorder (watchdog rungs
# dump span/timeline bundles there — the 5c investigation hook)
from paddle_tpu.obs import flight_recorder as _fr
_fr.enable_from_env()

from paddle_tpu import dsl
from paddle_tpu.core.config import OptimizationConf
from paddle_tpu.data import reader as R
from paddle_tpu.data.feeder import DataFeeder, dense_vector, integer_value
from paddle_tpu.trainer import EndIteration, SGD
from paddle_tpu.trainer import watchdog as wdg

save_dir = os.environ["SAVE_DIR"]
out = open(os.environ["OUT_FILE"], "a")
num_passes = int(os.environ.get("NUM_PASSES", "3"))
batches = int(os.environ.get("BATCHES", "16"))
nan_at = int(os.environ.get("NAN_AT", "-1"))
skip_budget = int(os.environ.get("SKIP_BUDGET", "5"))
good_batches = int(os.environ.get("GOOD_BATCHES", "4"))
# widen the preemption window: pretend each step costs this long (the
# CPU-smoke model trains a batch in ~ms; real steps take 100ms+)
batch_sleep = float(os.environ.get("BATCH_SLEEP", "0"))

with dsl.model() as g:
    x = dsl.data("x", (6,))
    y = dsl.data("y", (1,), is_ids=True)
    h = dsl.fc(x, size=8, act="tanh")
    o = dsl.fc(h, size=3, name="output")
    dsl.classification_cost(o, y)

rng = np.random.default_rng(5)
W = rng.standard_normal((6, 3))
xs = rng.standard_normal((batches * 4, 6)).astype(np.float32)
ys = np.argmax(xs @ W, axis=1).astype(np.int64)
data = [(xs[i], int(ys[i])) for i in range(len(xs))]

def reader():
    yield from data

feeder = DataFeeder({"x": 0, "y": 1},
                    {"x": dense_vector(6), "y": integer_value(3)})
wd_conf = wdg.WatchdogConfig(skip_budget=skip_budget,
                             good_batches=good_batches)
trainer = SGD(g.conf, OptimizationConf(
    learning_method="adam", learning_rate=0.05), seed=11,
    watchdog=wd_conf)

if nan_at >= 0:
    # poison ONE batch's features, keyed on a MONOTONIC feed counter
    # (not global_step, which rewinds on rollback): the fault is
    # transient, like a bad record that streams past once
    import dataclasses
    base_feeder = feeder
    fed = [0]
    def feeder(raw):
        f = base_feeder(raw)
        if fed[0] == nan_at:
            f["x"] = dataclasses.replace(
                f["x"], value=np.full_like(f["x"].value, np.nan))
        fed[0] += 1
        return f

start = 0
try:
    start = trainer.resume(save_dir)
    out.write(json.dumps({"resume": start,
                          "skip": trainer._resume_skip_batches})
              + "\\n")
    out.flush()
except (FileNotFoundError, ValueError):
    pass

def handler(e):
    if isinstance(e, EndIteration):
        out.write(json.dumps({"pass": e.pass_id, "bi": e.batch_id,
                              "step": trainer.global_step - 1,
                              "loss": e.cost}) + "\\n")
        out.flush()
        if batch_sleep:
            time.sleep(batch_sleep)

try:
    trainer.train(reader=R.batched(reader, 4), feeder=feeder,
                  num_passes=num_passes, start_pass=start,
                  event_handler=handler, save_dir=save_dir,
                  checkpoint_mode="async")
except wdg.Preempted as p:
    out.write(json.dumps({"preempted": p.pass_id,
                          "bi": p.batches_done}) + "\\n")
    out.flush()
    sys.exit(wdg.EXIT_PREEMPTED)
if trainer.last_watchdog_report is not None:
    out.write(json.dumps(
        {"report": trainer.last_watchdog_report.to_dict()}) + "\\n")
out.write(json.dumps({"done": True}) + "\\n")
out.flush()
"""


def _read_jsonl(path: str) -> list:
    """One JSON dict per line; missing file = empty list. The single
    parser behind both worker-record and metrics-stream readers."""
    import json

    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def read_worker_records(out_file: str) -> list:
    """Parse the preemptible worker's OUT_FILE (one JSON dict per
    line; schema documented on PREEMPTIBLE_TRAINER_SRC). Shared by
    the elastic-fault tests and the mc_preempt_recovery bench row so
    a record-format change breaks in one place, loudly."""
    return _read_jsonl(out_file)


def read_metrics_records(path: str, kind: str = None,
                         event: str = None) -> list:
    """Metrics-stream variant of `read_worker_records`: parse the obs
    JSONL event stream a worker wrote when METRICS_FILE was set
    (records carry `kind` — "watchdog" / "timeline" / "preempt_flush"
    — plus their payload; watchdog records name their ladder rung in
    `event` and stamp `global_step`). Optional filters narrow by
    `kind` and, for watchdog records, by `event`. Also reads the
    rotated `<path>.1` generation first, so a stream that rotated
    mid-run still replays in order."""
    recs = _read_jsonl(path + ".1") + _read_jsonl(path)
    if kind is not None:
        recs = [r for r in recs if r.get("kind") == kind]
    if event is not None:
        recs = [r for r in recs if r.get("event") == event]
    return recs


def start_preemptible_trainer(repo: str, save_dir: str, out_file: str,
                              **env_overrides) -> subprocess.Popen:
    """Launch the preemptible SGD worker above. `env_overrides` set
    the worker knobs (NUM_PASSES, BATCHES, NAN_AT, SKIP_BUDGET,
    GOOD_BATCHES, METRICS_FILE — the obs event-stream path) as
    strings."""
    env = dict(
        os.environ, REPO=repo, SAVE_DIR=save_dir, OUT_FILE=out_file,
        **{k: str(v) for k, v in env_overrides.items()},
    )
    return subprocess.Popen(
        [sys.executable, "-c", PREEMPTIBLE_TRAINER_SRC], env=env,
        cwd=repo, stderr=subprocess.PIPE, text=True,
    )


# ---- elastic sharded-CTR trainer worker (ISSUE 20) ------------------
#
# A REAL online-CTR trainer over a ShardedEmbeddingTable: deterministic
# traffic (trainer/online.make_batch), async sharded-table generations
# after every batch, and the commit-acknowledged ledger — a batch is
# logged `{"trained": b}` ONLY after its generation's per-shard sha256
# manifest verifies on disk. SIGKILL it mid-epoch with writes in
# flight, respawn the same command line, and the union of ledger
# lines across incarnations must be every batch EXACTLY once: zero
# lost (no gaps — committed-but-unlogged batches are reconciled from
# the recovered manifest), zero retrained (no duplicates —
# unacknowledged work re-runs without ever double-logging).
# OUT_FILE records:
#     {"start": true, "t": wall}                     each incarnation
#     {"resume": gen, "next_batch": nb,
#      "quarantined": [{"generation","reason"},...]} on recovery
#     {"trained": b, "gen": g, "loss": l, "t": wall} on COMMIT ack
#     {"trained": b, "reconciled": true}             ledger repair
#     {"done": true, "rows_materialized": m,
#      "rows_total": R, "evictions": e, "t": wall}   on completion
SHARDED_CTR_TRAINER_SRC = """
import json, os, sys, time
sys.path.insert(0, os.environ["REPO"])
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_n = int(os.environ.get("SHARDS", "4"))
_fl = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _fl:
    os.environ["XLA_FLAGS"] = (
        _fl + " --xla_force_host_platform_device_count=%d" % _n).strip()
import numpy as np
import jax

from paddle_tpu.core.mesh import MODEL_AXIS, make_mesh
from paddle_tpu.parallel.sparse_shard import (
    ShardedEmbeddingTable, ShardedTableConfig, adagrad_row_update,
    sgd_row_update,
)
from paddle_tpu.trainer import online

save_dir = os.environ["SAVE_DIR"]
out = open(os.environ["OUT_FILE"], "a")
rows_total = int(os.environ.get("ROWS_TOTAL", str(1 << 30)))
dim = int(os.environ.get("DIM", "8"))
capacity = int(os.environ.get("CAPACITY", "64"))
num_slots = int(os.environ.get("NUM_SLOTS", "48"))
batches = int(os.environ.get("BATCHES", "24"))
bsz = int(os.environ.get("BATCH", "8"))
feats = int(os.environ.get("FEATS", "4"))
hot = int(os.environ.get("HOT", "96"))
seed = int(os.environ.get("SEED", "7"))
lr = float(os.environ.get("LR", "0.5"))
placement = os.environ.get("PLACEMENT", "range")
use_adagrad = os.environ.get("ADAGRAD", "0") == "1"
batch_sleep = float(os.environ.get("BATCH_SLEEP", "0"))

def rec(**kw):
    out.write(json.dumps(kw) + "\\n")
    out.flush()

rec(start=True, t=time.time())

mesh = make_mesh({MODEL_AXIS: _n})
cfg = ShardedTableConfig(
    rows_total=rows_total, dim=dim, capacity=capacity,
    num_slots=num_slots, placement=placement, init_scale=0.0,
    seed=seed)
table = ShardedEmbeddingTable(
    cfg, mesh,
    update_fn=adagrad_row_update(lr) if use_adagrad
    else sgd_row_update(lr),
    num_state=1 if use_adagrad else 0)
trainer = online.OnlineCTRTrainer(table, save_dir)
hot_ids = online.hot_id_set(seed, hot, rows_total)
losses = {}

# ---- elastic resume: quarantine-and-rebuild + ledger reconcile ----
gen, meta, quarantined = trainer.resume()
next_b = int(meta.get("next_batch", 0)) if gen >= 0 else 0
if gen >= 0 or quarantined:
    rec(resume=gen, next_batch=next_b,
        quarantined=[{"generation": q["generation"],
                      "reason": q["reason"]} for q in quarantined])
if gen >= 0:
    acked = {r["trained"] for r in
             (json.loads(ln) for ln in open(os.environ["OUT_FILE"]))
             if "trained" in r}
    for b in range(next_b):
        if b not in acked:
            # committed generation, missing ledger line (killed
            # between commit and append): acknowledge from the
            # durable manifest, never by re-running the batch
            rec(trained=b, reconciled=True)

def ack(pairs):
    for g, m in pairs:
        rec(trained=g, gen=g, loss=losses.get(g, m.get("loss")),
            t=time.time())

for b in range(next_b, batches):
    ids, labels = online.make_batch(seed, b, bsz, feats, hot_ids)
    losses[b] = trainer.train_step(ids, labels)
    # generation b = state after batch b; async, in flight while the
    # next batch trains (the kill window the elastic test aims at)
    trainer.save_generation(b, b + 1,
                            extra_meta={"loss": losses[b]})
    ack(trainer.poll_acks())
    if batch_sleep:
        time.sleep(batch_sleep)

ack(trainer.drain())
trainer.close()
rec(done=True, rows_materialized=table.rows_materialized,
    rows_total=rows_total, evictions=table.stats["evictions"],
    t=time.time())
"""


def start_sharded_ctr_trainer(repo: str, save_dir: str,
                              out_file: str,
                              **env_overrides) -> subprocess.Popen:
    """Launch the elastic sharded-CTR worker above. Knobs via
    env_overrides: ROWS_TOTAL, DIM, CAPACITY, NUM_SLOTS, SHARDS,
    BATCHES, BATCH, FEATS, HOT, SEED, LR, PLACEMENT, ADAGRAD,
    BATCH_SLEEP — all stringified. Respawn = call again with the same
    arguments; the worker recovers itself from SAVE_DIR."""
    env = dict(
        os.environ, REPO=repo, SAVE_DIR=save_dir, OUT_FILE=out_file,
        **{k: str(v) for k, v in env_overrides.items()},
    )
    return subprocess.Popen(
        [sys.executable, "-c", SHARDED_CTR_TRAINER_SRC], env=env,
        cwd=repo, stderr=subprocess.PIPE, text=True,
    )


def replica_program_fn(layers: int = 16, d: int = 256):
    """The canonical serving program for fleet/coldstart harnesses: a
    `layers`-deep tanh MLP over a [B, 8] f32 feed. Both the cache
    *store* side (tests / bench compile it once through
    `inference.store_verified`) and the replica's compile-from-scratch
    boot mode build it from here, so the verified-cache row compares
    the same program, not two different ones."""
    import jax.numpy as jnp

    def fn(x):
        h = x
        for i in range(layers):
            w = jnp.full((h.shape[-1], d), 0.01, jnp.float32)
            h = jnp.tanh(h @ w + i * 1e-3)
        return jnp.sum(h, axis=-1)

    return fn


SERVING_REPLICA_SRC = """
import json, os, sys, threading, time
t0 = time.monotonic()
sys.path.insert(0, os.environ["REPO"])
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

from paddle_tpu.serving.server import InferenceServer, ServeConfig
from paddle_tpu.serving.tcp import ServingTCPServer
from paddle_tpu.obs import flight_recorder as _fr

# every replica keeps a flight ring (ring-only unless
# PADDLE_FLIGHT_DIR points somewhere): the fleet router's incident
# bundles stitch replica rings over the flightz frame, so a replica
# without a ring is a blind spot in every cross-process incident
_fr.enable_flight_recorder(
    dump_dir=os.environ.get("PADDLE_FLIGHT_DIR") or None)

mode = os.environ.get("REPLICA_MODE", "toy")  # toy|cache|compile|ctr
model_name = os.environ.get("MODEL_NAME", "m")
tag = os.environ.get("MODEL_TAG", "v1")
delay = float(os.environ.get("TOY_DELAY_S", "0.005"))
max_queue = int(os.environ.get("MAX_QUEUE", "64"))
max_batch = int(os.environ.get("MAX_BATCH", "4"))
deadline = float(os.environ.get("DEADLINE_S", "30"))


class Toy:
    can_host = False
    engine = None
    named_hooks = {}
    def __init__(self, tag, delay_s):
        self.tag = tag
        self.delay_s = delay_s
    def run_batch(self, ids, lens, hooks, host):
        time.sleep(self.delay_s)
        return [{"tokens": [int(lens[i])], "score": 0.0,
                 "tag": self.tag} for i in range(ids.shape[0])]


class Cached:
    # AOT executables are shape-specialized, so cache/compile replicas
    # run with max_batch=1 + a single length bucket: every dispatch is
    # exactly the [1, 8] feed the program was compiled for
    can_host = False
    engine = None
    named_hooks = {}
    def __init__(self, prog, tag):
        self.prog = prog
        self.tag = tag
    def run_batch(self, ids, lens, hooks, host):
        y = np.asarray(self.prog(ids.astype(np.float32)))
        return [{"tokens": [int(lens[i])],
                 "score": float(np.ravel(y)[i]), "tag": self.tag}
                for i in range(ids.shape[0])]


class CTRScorer:
    # online-learning serving side (ISSUE 20): score CTR requests
    # from the newest COMMITTED sharded-table generation in
    # MODEL_DIR. A rollout()'s swap_model frame re-runs _boot_model,
    # which re-reads the directory — the hot-swap IS "load the
    # trainer's latest checkpoint", exactly the loop ROADMAP item 4
    # names. Request ids are feature ids; score = sigmoid(sum of
    # their learned weights).
    can_host = False
    engine = None
    named_hooks = {}
    def __init__(self, weights, tag, gen):
        self.w = weights
        self.tag = tag
        self.gen = gen
    def run_batch(self, ids, lens, hooks, host):
        import math
        outs = []
        for i in range(ids.shape[0]):
            feats = ids[i, : max(int(lens[i]), 0)]
            z = sum(self.w.get(int(f), 0.0) for f in feats)
            p = 1.0 / (1.0 + math.exp(-z))
            outs.append({"tokens": [int(lens[i])], "score": p,
                         "tag": self.tag, "gen": self.gen})
        return outs


def _boot_model(new_tag):
    if mode == "toy":
        return Toy(new_tag, delay)
    if mode == "ctr":
        from paddle_tpu.trainer import async_checkpoint as ac
        from paddle_tpu.trainer import online
        gen, payloads, _meta = ac.load_table_generation(
            os.environ["MODEL_DIR"], -1)
        return CTRScorer(online.weights_from_payloads(payloads),
                         new_tag, gen)
    from paddle_tpu import inference, testing_faults
    if mode == "cache":
        policy = json.loads(os.environ.get("CACHE_POLICY", "null"))
        prog = inference.load_verified(
            os.environ["CACHE_DIR"], os.environ["CACHE_KEY"],
            policy=policy)
        return Cached(prog, new_tag)
    fn = testing_faults.replica_program_fn(
        int(os.environ.get("FN_LAYERS", "16")),
        int(os.environ.get("FN_DIM", "256")))
    compiled = jax.jit(fn).lower(
        np.zeros((1, 8), np.float32)).compile()
    return Cached(compiled, new_tag)


try:
    model = _boot_model(tag)
except BaseException as e:
    # the verified-cache gate biting IS a supported outcome: refuse
    # loudly, exit nonzero, serve nothing
    print("BOOT_REFUSED " + type(e).__name__ + ": " + str(e),
          flush=True)
    sys.exit(3)
print("BOOT %s %.6f" % (mode, time.monotonic() - t0), flush=True)

srv = InferenceServer(ServeConfig(
    max_queue=max_queue,
    max_batch=max_batch if mode in ("toy", "ctr") else 1,
    default_deadline_s=deadline,
    buckets=(8, 16, 32, 64) if mode in ("toy", "ctr") else (8,),
))
srv.add_model(model_name, model)


def load_model(name, new_tag):
    return _boot_model(new_tag or "swapped")


tcp = ServingTCPServer(srv, port=int(os.environ.get("PORT", "0")),
                       model_loader=load_model)
print("LISTENING %d" % tcp.port, flush=True)

done = threading.Event()
import signal
signal.signal(signal.SIGTERM, lambda *a: done.set())
done.wait()
tcp.stop_accepting()
srv.shutdown(drain=True)
tcp.stop(drain=True)
print("DRAINED", flush=True)
"""


def start_serving_replica(repo: str, **env_overrides):
    """Launch one serving replica (SERVING_REPLICA_SRC) and wait for
    its boot handshake. Returns `(proc, port)`; `port` is None when
    the boot was refused (verified-cache gate) or the process died
    before listening. The boot line ("BOOT <mode> <seconds>" or
    "BOOT_REFUSED <err>") is stashed on `proc.boot_line`.

    Knobs via env_overrides: REPLICA_MODE (toy|cache|compile|ctr),
    MODEL_NAME, MODEL_TAG, TOY_DELAY_S, MAX_QUEUE, MAX_BATCH,
    DEADLINE_S, CACHE_DIR, CACHE_KEY, CACHE_POLICY (JSON), FN_LAYERS,
    FN_DIM, PORT, MODEL_DIR (ctr: the sharded-table generation dir
    the scorer loads from — and reloads on every swap_model)."""
    env = dict(
        os.environ, REPO=repo, JAX_PLATFORMS="cpu",
        **{k: str(v) for k, v in env_overrides.items()},
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", SERVING_REPLICA_SRC], env=env,
        cwd=repo, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True,
    )
    boot = None
    port = None
    while True:
        line = proc.stdout.readline()
        if not line:
            break
        line = line.strip()
        if line.startswith("BOOT_REFUSED"):
            boot = line
            break
        if line.startswith("BOOT "):
            boot = line
            continue
        if line.startswith("LISTENING"):
            port = int(line.split()[1])
            break
    proc.boot_line = boot
    return proc, port


def replica_boot_seconds(proc) -> float:
    """Parse the boot duration off a replica's handshake line."""
    line = getattr(proc, "boot_line", None) or ""
    parts = line.split()
    if len(parts) == 3 and parts[0] == "BOOT":
        return float(parts[2])
    raise ValueError(f"no boot line on replica: {line!r}")


class FlakyProxy:
    """TCP proxy with programmable connection faults.

    Sits between a master client and the real master:

        proxy = FlakyProxy(("127.0.0.1", master_port))
        client = MasterClient(f"127.0.0.1:{proxy.port}")
        proxy.reset_next(3)   # next 3 connections get RST mid-call
        proxy.refuse_all()    # then: connect() succeeds, dies instantly
        proxy.heal()          # back to transparent forwarding

    Faults are applied per accepted connection, so a client with
    reconnect-and-retry semantics sees exactly N failures and then a
    clean master — the deterministic version of "the master is
    restarting"."""

    def __init__(self, target: tuple, listen_host: str = "127.0.0.1"):
        self._target = target
        self._lock = threading.Lock()
        self._reset_budget = 0  # connections to RST after the request
        self._refuse = False  # close every connection immediately
        self._delay_s = 0.0  # added latency before forwarding starts
        self._cut_after = 0  # RST after N response bytes (0 = off)
        self._black_hole = False  # accept + read, never answer
        self._conns: list = []
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((listen_host, 0))
        self._listener.listen(16)
        self.port = self._listener.getsockname()[1]
        self._stopped = False
        self._thread = threading.Thread(
            target=self._accept_loop, name="flaky-proxy", daemon=True
        )
        self._thread.start()

    # ---- fault programming ----
    def reset_next(self, n: int = 1) -> None:
        """RST the next `n` connections right after they send data."""
        with self._lock:
            self._reset_budget = n

    def refuse_all(self) -> None:
        """Kill every new connection immediately after accept — the
        observable shape of a master that is down/restarting."""
        with self._lock:
            self._refuse = True

    def delay(self, seconds: float) -> None:
        with self._lock:
            self._delay_s = seconds

    def cut_after(self, n_bytes: int) -> None:
        """RST each new connection after `n_bytes` of RESPONSE bytes
        have been relayed — the client receives a torn half-response
        (a mid-reply network cut, not a clean close)."""
        with self._lock:
            self._cut_after = n_bytes

    def black_hole(self) -> None:
        """Accept every connection and read its requests, but never
        forward or answer — the nastiest master failure mode: alive at
        the TCP layer, dead at the protocol layer. A client whose recv
        is unbounded hangs here FOREVER regardless of its retry
        deadline (the master_client settimeout(None) bug this fault
        exists to pin)."""
        with self._lock:
            self._black_hole = True

    def heal(self) -> None:
        with self._lock:
            self._refuse = False
            self._reset_budget = 0
            self._delay_s = 0.0
            self._cut_after = 0
            self._black_hole = False

    def cut_existing(self) -> None:
        """RST every currently-open proxied connection (network
        partition for in-flight calls)."""
        with self._lock:
            conns, self._conns = self._conns, []
        for s in conns:
            _rst_close(s)

    # ---- plumbing ----
    def _accept_loop(self):
        while not self._stopped:
            try:
                client, _ = self._listener.accept()
            except OSError:
                return
            with self._lock:
                refuse = self._refuse
                reset = self._reset_budget > 0
                if reset:
                    self._reset_budget -= 1
                delay_s = self._delay_s
                cut_after = self._cut_after
                black_hole = self._black_hole
            if refuse:
                _rst_close(client)
                continue
            if black_hole:
                with self._lock:
                    self._conns.append(client)
                threading.Thread(
                    target=_swallow, args=(client,), daemon=True
                ).start()
                continue
            threading.Thread(
                target=self._serve,
                args=(client, reset, delay_s, cut_after),
                daemon=True,
            ).start()

    def _serve(self, client: socket.socket, reset: bool, delay_s: float,
               cut_after: int = 0):
        try:
            upstream = socket.create_connection(self._target, timeout=5)
        except OSError:
            _rst_close(client)
            return
        with self._lock:
            self._conns += [client, upstream]
        if reset:
            # let exactly one request through to the wire, then RST the
            # client before the response lands: the retried call is the
            # at-least-once duplicate the protocol must absorb
            try:
                data = client.recv(65536)
                if data:
                    upstream.sendall(data)
                    if delay_s:
                        threading.Event().wait(delay_s)
            except OSError:
                pass
            _rst_close(client)
            _rst_close(upstream)
            return
        if delay_s:
            threading.Event().wait(delay_s)
        t = threading.Thread(
            target=_pump, args=(client, upstream), daemon=True
        )
        t.start()
        _pump(upstream, client, limit=cut_after or None)
        if cut_after:
            # torn mid-response: RST both halves, no clean FIN
            _rst_close(client)
            _rst_close(upstream)

    def close(self):
        self._stopped = True
        try:
            self._listener.close()
        finally:
            self.cut_existing()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _rst_close(s: socket.socket) -> None:
    """Close sending RST instead of FIN (SO_LINGER 0) — the peer's
    blocked recv fails with ECONNRESET instead of a clean EOF."""
    try:
        s.setsockopt(
            socket.SOL_SOCKET, socket.SO_LINGER, struct.pack("ii", 1, 0)
        )
    except OSError:
        pass
    try:
        s.close()
    except OSError:
        pass


def _swallow(s: socket.socket) -> None:
    """black_hole service: read and discard until the peer gives up."""
    try:
        while s.recv(65536):
            pass
    except OSError:
        pass
    finally:
        try:
            s.close()
        except OSError:
            pass


def _pump(src: socket.socket, dst: socket.socket,
          limit: int = None) -> None:
    """Relay src -> dst; with `limit`, stop (returning to the caller,
    which RSTs) once `limit` bytes have been forwarded."""
    sent = 0
    try:
        while True:
            data = src.recv(65536)
            if not data:
                break
            if limit is not None and sent + len(data) >= limit:
                dst.sendall(data[: max(limit - sent, 0)])
                return  # caller tears the connection down with RST
            dst.sendall(data)
            sent += len(data)
    except OSError:
        pass
    finally:
        if limit is None:
            try:
                dst.shutdown(socket.SHUT_WR)
            except OSError:
                pass
