"""The `paddle` CLI dispatcher.

Reference: paddle/scripts/submit_local.sh.in:3-13 — subcommands
train / pserver / merge_model / dump_config / make_diagram / version —
plus trainer/TrainerMain.cpp and trainer/MergeModel.cpp. TPU-native
differences: there is no pserver process (data parallelism is one pjit
program; `master` serves the elastic-input role instead), `bench`
wraps the repo benchmark harness, and `serve` runs the
continuous-batching inference server (paddle_tpu/serving).

A config file is a Python source that defines:
    get_config() -> (ModelConf, OptimizationConf)
and optionally:
    train_reader() / test_reader()   (batched sample readers)
    feeder(batch) -> feed dict of Args

Usage:  python -m paddle_tpu <cmd> [args]   (installed alias: paddle)
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import sys


def _load_config(path: str):
    spec = importlib.util.spec_from_file_location("_paddle_config", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    if not hasattr(mod, "get_config"):
        raise SystemExit(
            f"{path} must define get_config() -> (ModelConf, "
            f"OptimizationConf)"
        )
    return mod


def cmd_version(args):
    from paddle_tpu import __version__

    print(f"paddle_tpu {__version__}")
    import jax

    print(f"jax {jax.__version__}, devices: {jax.devices()}")
    return 0


def cmd_dump_config(args):
    if _is_v1_config(args.config):
        from paddle_tpu.compat.config_parser import parse_config

        tc = parse_config(args.config, args.config_args)
        model_conf, opt_conf = tc.model, tc.opt
    else:
        mod = _load_config(args.config)
        model_conf, opt_conf = mod.get_config()
    doc = {
        "model": json.loads(model_conf.to_json()),
        "optimization": vars(opt_conf),
    }
    out = json.dumps(doc, indent=2, default=str)
    if args.output:
        with open(args.output, "w") as f:
            f.write(out)
    else:
        print(out)
    return 0


def cmd_train(args):
    from paddle_tpu.launch import distributed_init_from_env
    from paddle_tpu.obs import flight_recorder as _flight
    from paddle_tpu.trainer import SGD
    from paddle_tpu.trainer import events
    from paddle_tpu.trainer import watchdog as wdg

    # PADDLE_FLIGHT_DIR=<dir> arms the anomaly flight recorder
    # (watchdog rungs dump span/timeline/event bundles there)
    _flight.enable_from_env()

    # under `paddle launch` every worker carries the rendezvous env —
    # join it before any device use (cluster_train trainer_id wiring)
    distributed_init_from_env()

    # --job=test needs only the config's TEST data source; everything
    # else drives the train source. The config is parsed exactly once.
    which = "test" if args.job == "test" else "train"
    if _is_v1_config(args.config):
        # UNMODIFIED reference v1 config: the `paddle train --config X
        # --config_args Y` path (trainer/TrainerMain.cpp:32 +
        # config_parser.py:3724) — model + optimizer + data provider
        # all come from the config file itself
        model_conf, opt_conf, reader, feeder, evaluators = _v1_setup(
            args.config, args.config_args, which
        )
    else:
        mod = _load_config(args.config)
        model_conf, opt_conf = mod.get_config()
        if which == "test":
            if not hasattr(mod, "test_reader"):
                raise SystemExit(
                    f"{args.config} must define test_reader() for "
                    "--job=test"
                )
            reader = mod.test_reader()
        else:
            reader = mod.train_reader()
        feeder = getattr(mod, "feeder", None)
        if feeder is None:
            raise SystemExit(f"{args.config} must define feeder(batch)")
        evaluators = getattr(mod, "evaluators", None) or []
    trainer = SGD(model_conf, opt_conf, evaluators=evaluators)

    if args.job == "test":
        # evaluation-only pass (trainer/Tester.h; `paddle train
        # --job=test`), optionally on a saved checkpoint
        # (--save_dir/--pass_id = --init_model_path semantics)
        if args.save_dir:
            trainer.resume(args.save_dir, args.pass_id)
        res = trainer.test(reader, feeder)
        print(
            f"test cost {res['cost']:.6f} "
            + " ".join(
                f"{k}={v}" for k, v in res["evaluators"].items()
            )
        )
        return 0

    if args.job == "time":
        # --job=time (trainer/TrainerBenchmark.cpp, the harness behind
        # the reference's published numbers, benchmark/paddle/image/
        # run.sh:10): warm up, then report ms/batch over the next
        # batches
        import time as _time

        want = args.time_batches + 5
        batches = []
        while len(batches) < want:
            got_any = False
            for b in reader():
                got_any = True
                batches.append(b)
                if len(batches) == want:
                    break
            if not got_any:  # empty source: error out, don't spin
                raise SystemExit("data source produced no batches")
        feeds = [feeder(b) for b in batches]
        for f in feeds[:5]:  # warmup/compile
            trainer.train_batch(f)
        t0 = _time.perf_counter()
        for f in feeds[5:]:
            trainer.train_batch(f)
        n = len(feeds) - 5
        ms = (_time.perf_counter() - t0) / max(n, 1) * 1e3
        print(f"time: {ms:.3f} ms/batch over {n} batches")
        return 0

    def handler(ev):
        if isinstance(ev, events.EndIteration) and (
            ev.batch_id % args.log_period == 0
        ):
            print(
                f"pass {ev.pass_id} batch {ev.batch_id} "
                f"cost {ev.cost:.6f}"
            )

    # auto-resume: a respawned (preempted or crashed) worker picks up
    # from the newest complete checkpoint in save_dir — including a
    # MID-PASS preemption flush, which resumes at the exact batch
    # (--from_scratch opts out)
    start_pass = 0
    if args.save_dir and not args.from_scratch:
        try:
            start_pass = trainer.resume(args.save_dir)
            print(
                f"resuming from {args.save_dir}: start pass "
                f"{start_pass}, skip {trainer._resume_skip_batches} "
                f"batches", flush=True,
            )
        except (FileNotFoundError, ValueError):
            pass  # no (complete) checkpoint yet: fresh start
    try:
        trainer.train(
            reader=reader,
            feeder=feeder,
            num_passes=args.num_passes,
            event_handler=handler,
            save_dir=args.save_dir or None,
            start_pass=start_pass,
        )
    except wdg.Preempted as p:
        # the contract launch.py keys on: checkpoint flushed, exit
        # EXIT_PREEMPTED (75), respawn resumes losslessly
        print(f"PREEMPTED pass {p.pass_id} batch {p.batches_done}",
              flush=True)
        return wdg.EXIT_PREEMPTED
    except wdg.WatchdogAbort as a:
        print("WATCHDOG_ABORT " + json.dumps(a.report.to_dict()),
              flush=True)
        return 1
    return 0


def _is_v1_config(path: str) -> bool:
    """A config is v2-native iff its module BINDS the name `get_config`
    at top level (def / assignment / import); everything else is an
    unmodified v1 file for compat parse_config. Decided from the AST —
    a substring match would misroute a v1 config that merely mentions
    get_config in a comment or defines get_configuration."""
    import ast

    with open(path) as f:
        try:
            tree = ast.parse(f.read(), path)
        except SyntaxError:
            return True  # py2-era source: certainly a v1 config

    # bindings anywhere at module scope count, including under try/if
    # (guarded imports); class/function BODIES don't bind module names,
    # so those subtrees are not descended into
    def binds(node) -> bool:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return node.name == "get_config"
        if isinstance(node, ast.ClassDef):
            return False
        def target_binds(t) -> bool:
            if isinstance(t, ast.Name):
                return t.id == "get_config"
            if isinstance(t, (ast.Tuple, ast.List)):
                return any(target_binds(e) for e in t.elts)
            if isinstance(t, ast.Starred):
                return target_binds(t.value)
            return False

        if isinstance(node, ast.Assign) and any(
            target_binds(t) for t in node.targets
        ):
            return True
        if isinstance(
            node, (ast.AnnAssign, ast.AugAssign)
        ) and target_binds(node.target):
            return True
        if isinstance(node, (ast.With, ast.AsyncWith)) and any(
            item.optional_vars is not None
            and target_binds(item.optional_vars)
            for item in node.items
        ):
            return True
        if isinstance(node, (ast.For, ast.AsyncFor)) and target_binds(
            node.target
        ):
            return True
        if isinstance(node, ast.NamedExpr) and target_binds(node.target):
            return True
        if isinstance(node, (ast.Import, ast.ImportFrom)) and any(
            (alias.asname or alias.name) == "get_config"
            for alias in node.names
        ):
            return True
        return any(binds(c) for c in ast.iter_child_nodes(node))

    return not any(binds(node) for node in tree.body)


def _v1_setup(config_path, config_args, which="train"):
    """Build (model, opt, batched_reader, feeder) from an unmodified v1
    config: parse it ONCE, load the data-provider module for the
    requested source (train or test), annotate data-layer slot types
    from that provider's declaration, and wire the feeder by data-layer
    order (tuple samples) or name (dict samples)."""
    from paddle_tpu.compat.config_parser import (
        apply_data_types,
        parse_config,
    )
    from paddle_tpu.data.feeder import DataFeeder
    from paddle_tpu.data.reader import batched

    tc = parse_config(config_path, config_args)
    ds = tc.data_sources
    if ds is None or not getattr(ds, f"{which}_list"):
        raise SystemExit(
            f"{config_path} declares no {which} data source "
            "(define_py_data_sources2)"
        )
    reader_creator, types = (
        ds.train_reader() if which == "train" else ds.test_reader()
    )
    apply_data_types(tc.model, types)
    data_names = [
        lc.name for lc in tc.model.layers if lc.type == "data"
    ]
    # the config's inputs() declaration fixes provider-slot order
    order = [
        n for n in (tc.model.input_layer_names or data_names)
        if n in data_names
    ] or data_names
    if isinstance(types, dict):
        feeding = {n: n for n in types}
        type_map = dict(types)
    else:
        feeding = {n: i for i, n in enumerate(order)}
        type_map = dict(zip(order, types))
    feeder = DataFeeder(feeding, type_map)
    reader = batched(
        reader_creator, tc.opt.batch_size, drop_last=False
    )
    return tc.model, tc.opt, reader, feeder, tc.evaluators


def cmd_merge_model(args):
    from paddle_tpu.trainer import checkpoint as ckpt

    mod = _load_config(args.config)
    model_conf, _ = mod.get_config()
    params, _, state, _ = ckpt.load_pass(args.model_dir, args.pass_id)
    ckpt.merge_model(args.output, model_conf, params, state)
    print(f"merged {args.model_dir} (pass {args.pass_id}) -> {args.output}")
    return 0


def cmd_infer(args):
    import numpy as np

    from paddle_tpu.trainer.trainer import Inferencer

    inf = Inferencer.from_merged(args.model)
    print(f"outputs: {inf.output_names}")
    if args.example:
        # feed zero batches of the declared shapes as a smoke test
        from paddle_tpu.core.arg import Arg

        feed = {}
        T = 4  # smoke-test time steps for sequence inputs
        for lc in inf.net.conf.layers:
            if lc.type != "data":
                continue
            a = lc.attrs
            is_seq = a.get("is_seq", False)
            lead = (args.batch, T) if is_seq else (args.batch,)
            lens = (
                np.full(args.batch, T, np.int32) if is_seq else None
            )
            if a.get("is_ids"):
                feed[lc.name] = Arg(
                    ids=np.zeros(lead, np.int32), seq_lens=lens
                )
            else:
                feed[lc.name] = Arg(
                    value=np.zeros(lead + tuple(a["dim"]), np.float32),
                    seq_lens=lens,
                )
        outs = inf.infer(feed)
        for n, v in outs.items():
            print(f"{n}: shape {v.shape}")
    return 0


def cmd_master(args):
    from paddle_tpu.native.master import Master
    from paddle_tpu.native.recordio import count_chunks

    m = Master(lease_seconds=args.timeout, failure_max=args.failure_max)
    total = 0
    for path in args.chunks:
        n = count_chunks(path)
        m.add_chunk_tasks(path, n)
        total += n
    print(
        f"elastic master over {len(args.chunks)} files / {total} chunk "
        f"tasks; Ctrl-C to stop"
    )
    import time

    try:
        while True:
            time.sleep(30)
            if args.snapshot:
                m.snapshot(args.snapshot)
    except KeyboardInterrupt:
        if args.snapshot:
            m.snapshot(args.snapshot)
    return 0


def cmd_serve(args):
    """Run the continuous-batching inference server (serving/). The
    config file defines `get_server() -> serving.InferenceServer` with
    its models already registered; this command owns the TCP front end
    and the drain-on-shutdown lifecycle (SIGTERM/SIGINT -> stop
    admission, finish or cleanly reject in-flight work, exit 0)."""
    import json as _json
    import signal
    import time as _time

    from paddle_tpu.obs import flight_recorder as _flight
    from paddle_tpu.serving.tcp import ServingTCPServer

    # PADDLE_FLIGHT_DIR=<dir> arms the anomaly flight recorder
    # (breaker opens / shed spikes / SLO breaches dump bundles there)
    _flight.enable_from_env()

    spec = importlib.util.spec_from_file_location("_serve_config",
                                                  args.config)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    if not hasattr(mod, "get_server"):
        raise SystemExit(
            f"{args.config} must define get_server() -> InferenceServer"
        )
    server = mod.get_server()
    # optional `load_model(name, tag) -> model` in the config enables
    # the {"admin": "swap_model"} frame (zero-downtime rollout)
    tcp = ServingTCPServer(server, port=args.port,
                           model_loader=getattr(mod, "load_model",
                                                None))
    print(f"LISTENING {tcp.port}", flush=True)

    stopping = []
    signal.signal(signal.SIGTERM, lambda *_: stopping.append(1))
    signal.signal(signal.SIGINT, lambda *_: stopping.append(1))
    try:
        while not stopping:
            _time.sleep(0.1)
    finally:
        # stop NEW connections first, drain with established clients
        # still attached (their in-flight responses must land), then
        # close what remains
        tcp.stop_accepting()
        server.shutdown(drain=True, timeout=args.drain_timeout)
        tcp.stop(drain=True)
        print("DRAINED " + _json.dumps(server.stats()), flush=True)
    return 0


def cmd_metrics(args):
    """One-shot telemetry dump (ISSUE 10). Without arguments, prints
    the CURRENT process's registry snapshot (text or --json) — mostly
    useful from code or a REPL. With --stream FILE, summarizes a JSONL
    event stream another process wrote (enable_event_stream /
    METRICS_FILE): event counts by kind, watchdog rungs, the last
    per-pass timeline record. Deliberately jax-free: inspecting
    telemetry must not initialize a device runtime."""
    from paddle_tpu.obs import metrics as om

    if args.spans:
        if not args.stream:
            raise SystemExit("--spans needs --stream FILE")
        return _metrics_spans(args)
    if args.stream:
        from paddle_tpu.testing_faults import read_metrics_records

        recs = read_metrics_records(args.stream)
        kinds = {}
        for r in recs:
            kinds[r.get("kind", "?")] = kinds.get(r.get("kind", "?"), 0) + 1
        wd = {}
        for r in recs:
            if r.get("kind") == "watchdog":
                wd[r["event"]] = wd.get(r["event"], 0) + 1
        timelines = [r for r in recs if r.get("kind") == "timeline"]
        summary = {
            "stream": args.stream,
            "events": len(recs),
            "by_kind": kinds,
            "watchdog_events": wd,
            "last_timeline": timelines[-1] if timelines else None,
        }
        if args.json:
            print(json.dumps(summary, indent=2))
        else:
            print(f"event stream {args.stream}: {len(recs)} events")
            for k, n in sorted(kinds.items()):
                print(f"  {k:20s} {n}")
            if wd:
                print("watchdog ladder:")
                for k, n in sorted(wd.items()):
                    print(f"  {k:20s} {n}")
            if timelines:
                t = timelines[-1]
                print(
                    "last timeline: pass %s step %s  "
                    "data_wait=%.1f%% host=%.1f%% device=%.1f%% "
                    "ckpt=%.1f%%" % (
                        t.get("pass_id"), t.get("global_step"),
                        100 * t.get("data_wait_frac", 0),
                        100 * t.get("host_overhead_frac", 0),
                        100 * t.get("device_frac", 0),
                        100 * t.get("checkpoint_stall_frac", 0),
                    )
                )
        return 0
    reg = om.get_registry()
    if args.json:
        print(json.dumps(reg.snapshot(), indent=2))
    else:
        print(reg.render_text())
    return 0


def _metrics_spans(args):
    """`metrics --stream FILE --spans` (ISSUE 11): per-span-name
    count/p50/p99 table plus the top-N slowest traces, computed from
    the span events on a JSONL stream. Jax-free like the rest of the
    metrics paths — span analytics must run on any box the stream was
    copied to."""
    from paddle_tpu.testing_faults import read_metrics_records

    spans = read_metrics_records(args.stream, kind="span")
    if not spans:
        print(f"event stream {args.stream}: no span events")
        return 0

    def pctl(sorted_vals, q):
        return sorted_vals[int(q * (len(sorted_vals) - 1))]

    by_name = {}
    for s in spans:
        by_name.setdefault(s.get("name", "?"), []).append(
            float(s.get("dur_s", 0.0))
        )
    rows = []
    for name, durs in by_name.items():
        durs.sort()
        rows.append({
            "name": name,
            "count": len(durs),
            "p50_ms": round(pctl(durs, 0.50) * 1e3, 3),
            "p99_ms": round(pctl(durs, 0.99) * 1e3, 3),
            "max_ms": round(durs[-1] * 1e3, 3),
        })
    rows.sort(key=lambda r: r["p99_ms"] * r["count"], reverse=True)

    # slowest traces: each trace scored by its root span (no parent
    # within the trace), falling back to its longest span. Root
    # semantics mirror tools/trace_view.py::_root_of — that file must
    # stay standalone-stdlib (copyable to any box without this
    # package), so the few lines are duplicated, not imported; change
    # both together.
    by_trace = {}
    for s in spans:
        by_trace.setdefault(s.get("trace_id", "?"), []).append(s)
    traces = []
    for tid, group in by_trace.items():
        ids = {g.get("span_id") for g in group}
        roots = [g for g in group
                 if g.get("parent_id", "") not in ids]
        root = max(roots or group,
                   key=lambda g: float(g.get("dur_s", 0.0)))
        traces.append({
            "trace_id": tid,
            "root": root.get("name"),
            "dur_ms": round(float(root.get("dur_s", 0.0)) * 1e3, 3),
            "spans": len(group),
            "status": root.get("status", "ok"),
        })
    traces.sort(key=lambda t: t["dur_ms"], reverse=True)
    traces = traces[: args.top]

    if args.json:
        print(json.dumps(
            {"stream": args.stream, "span_count": len(spans),
             "by_name": rows, "slowest_traces": traces}, indent=2,
        ))
        return 0
    print(f"event stream {args.stream}: {len(spans)} spans")
    print(f"{'span':28s} {'count':>7s} {'p50_ms':>10s} "
          f"{'p99_ms':>10s} {'max_ms':>10s}")
    for r in rows:
        print(f"{r['name']:28s} {r['count']:7d} {r['p50_ms']:10.3f} "
              f"{r['p99_ms']:10.3f} {r['max_ms']:10.3f}")
    print(f"top {len(traces)} slowest traces:")
    for t in traces:
        print(f"  {t['trace_id'][:16]:16s} {t['root'] or '?':24s} "
              f"{t['dur_ms']:10.3f} ms  {t['spans']:4d} spans  "
              f"{t['status']}")
    return 0


def cmd_fleetz(args):
    """Live fleet snapshot (ISSUE 17): scrape every replica's metricz
    twice, `--interval` apart, merge the snapshots into one fleet
    view (counters summed, histograms merged bucket-wise), and print
    a per-replica health table + fleet quantiles + active threshold
    breaches. Deliberately jax-free, like `metrics`: the operator box
    watching a fleet must not need a device runtime."""
    import time as _t

    from paddle_tpu.obs import aggregate as agg
    from paddle_tpu.serving.tcp import ServeClient

    replicas = {}
    for i, spec in enumerate(args.addr):
        if "=" in spec:
            name, _, a = spec.partition("=")
        else:
            name, a = f"r{i}", spec
        replicas[name] = a

    def scrape():
        snaps, stats, errors = {}, {}, {}
        for name, a in replicas.items():
            try:
                c = ServeClient(a, retries=0,
                                admin_timeout=args.timeout)
                resp = c.metricz()
                c.close()
                snaps[name] = resp.get("metricz", {})
                stats[name] = resp.get("stats", {})
            except Exception as e:
                errors[name] = f"{type(e).__name__}: {e}"
        return snaps, stats, errors

    t0 = _t.time()
    first, _, _ = scrape()
    _t.sleep(args.interval)
    second, stats, errors = scrape()
    dt = _t.time() - t0

    both = {n: s for n, s in second.items() if n in first}
    prev = agg.merge_snapshots({n: first[n] for n in both})
    cur = agg.merge_snapshots(second)
    delta = agg.snapshot_delta(prev, cur)
    rates = agg.counter_rates(delta, dt)

    family_sum = agg.family_total

    def merged_latency(histograms):
        """All serving.admitted_latency_s series (one per model)
        folded into one distribution."""
        return agg.family_histogram(histograms,
                                    "serving.admitted_latency_s")

    table = []
    for name in sorted(replicas):
        if name in errors:
            table.append({"replica": name, "up": False,
                          "error": errors[name]})
            continue
        st = stats.get(name, {}) or {}
        dsnap = agg.snapshot_delta(
            agg.merge_snapshots({name: first.get(name, {})}),
            agg.merge_snapshots({name: second.get(name, {})}),
        )
        admitted = family_sum(dsnap["counters"], "serving.admitted")
        shed = family_sum(dsnap["counters"], "serving.shed")
        total = admitted + shed
        lat = merged_latency(dsnap["histograms"])
        p99 = agg.quantile(lat, 0.99) if lat else None
        table.append({
            "replica": name,
            "up": True,
            "queue_depth": st.get("queue_depth"),
            "admitted": admitted,
            "shed": shed,
            "shed_frac": round(shed / total, 4) if total else 0.0,
            "p99_ms": round(p99 * 1e3, 3) if p99 is not None else None,
        })

    fleet_lat = merged_latency(delta["histograms"])
    fleet = {
        "replicas_up": sum(1 for r in table if r.get("up")),
        "replicas_down": sum(1 for r in table if not r.get("up")),
        "admitted_rate_rps": round(
            family_sum(rates, "serving.admitted"), 3),
        "shed_rate_rps": round(family_sum(rates, "serving.shed"), 3),
        "p50_ms": None,
        "p99_ms": None,
    }
    for q, key in ((0.50, "p50_ms"), (0.99, "p99_ms")):
        v = agg.quantile(fleet_lat, q) if fleet_lat else None
        fleet[key] = round(v * 1e3, 3) if v is not None else None

    alerts = []
    for r in table:
        if not r.get("up"):
            alerts.append({"alert": "replica_down",
                           "replica": r["replica"]})
            continue
        if args.slo_ms > 0 and r.get("p99_ms") is not None \
                and r["p99_ms"] > args.slo_ms:
            alerts.append({"alert": "p99_slo", "replica": r["replica"],
                           "p99_ms": r["p99_ms"],
                           "slo_ms": args.slo_ms})
        if r.get("shed_frac", 0.0) > args.shed_threshold:
            alerts.append({"alert": "shedding", "replica": r["replica"],
                           "shed_frac": r["shed_frac"]})

    if args.json:
        print(json.dumps({"interval_s": round(dt, 3),
                          "replicas": table, "fleet": fleet,
                          "alerts": alerts}, indent=2))
        return 1 if alerts else 0
    print(f"fleet of {len(replicas)} replicas "
          f"({fleet['replicas_up']} up), {dt:.1f}s window")
    print(f"{'replica':12s} {'state':6s} {'queue':>6s} {'adm':>8s} "
          f"{'shed':>8s} {'shed%':>7s} {'p99_ms':>9s}")
    for r in table:
        if not r.get("up"):
            print(f"{r['replica']:12s} {'DOWN':6s} {r['error']}")
            continue
        p99 = f"{r['p99_ms']:9.3f}" if r["p99_ms"] is not None \
            else f"{'-':>9s}"
        print(f"{r['replica']:12s} {'up':6s} "
              f"{str(r['queue_depth'] if r['queue_depth'] is not None else '-'):>6s} "
              f"{r['admitted']:8.0f} {r['shed']:8.0f} "
              f"{100 * r['shed_frac']:6.1f}% {p99}")
    print(f"fleet: {fleet['admitted_rate_rps']} rps admitted, "
          f"{fleet['shed_rate_rps']} rps shed, "
          f"p50={fleet['p50_ms']} ms p99={fleet['p99_ms']} ms "
          f"(merged buckets)")
    if alerts:
        print("active alerts:")
        for a in alerts:
            print("  " + json.dumps(a))
    else:
        print("no active alerts")
    return 1 if alerts else 0


def cmd_make_diagram(args):
    """Emit a graphviz .dot of the layer graph (the reference's
    `paddle make_diagram`, scripts/submit_local.sh.in:3-13)."""
    from paddle_tpu.plot import make_diagram

    if _is_v1_config(args.config):
        # an unmodified v1 config file (settings()/outputs() style)
        from paddle_tpu.compat.config_parser import parse_config

        model_conf = parse_config(args.config, args.config_args).model
    else:
        mod = _load_config(args.config)
        model_conf, _ = mod.get_config()
    dot = make_diagram(model_conf, title=args.config)
    if args.output:
        with open(args.output, "w") as f:
            f.write(dot)
    else:
        print(dot, end="")
    return 0


def cmd_bench(args):
    import runpy

    sys.argv = ["bench.py"]
    runpy.run_path(args.script, run_name="__main__")
    return 0


def _cmd_launch(args):
    from paddle_tpu import launch as _launch

    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        raise SystemExit("launch: give the worker command after --")
    args.command = cmd
    return _launch.main(args)


def main(argv=None):
    p = argparse.ArgumentParser(prog="paddle", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("train", help="train a config")
    sp.add_argument("--config", required=True)
    sp.add_argument("--config_args", default="",
                    help="v1 config interpolation, e.g. batch_size=64")
    sp.add_argument("--job", choices=["train", "time", "test"],
                    default="train",
                    help="time = ms/batch harness (TrainerBenchmark"
                         ".cpp); test = evaluation pass (Tester.h)")
    sp.add_argument("--time_batches", type=int, default=10)
    sp.add_argument("--pass_id", type=int, default=-1,
                    help="with --job=test --save_dir: checkpoint pass")
    sp.add_argument("--num_passes", type=int, default=1)
    sp.add_argument("--save_dir", default="")
    sp.add_argument("--log_period", type=int, default=10)
    sp.add_argument("--from_scratch", action="store_true",
                    help="ignore existing checkpoints in --save_dir "
                         "instead of auto-resuming")
    sp.set_defaults(fn=cmd_train)

    sp = sub.add_parser("dump_config", help="print config as JSON")
    sp.add_argument("--config", required=True)
    sp.add_argument("--config_args", default="")
    sp.add_argument("--output", default="")
    sp.set_defaults(fn=cmd_dump_config)

    sp = sub.add_parser("merge_model", help="pack config+weights")
    sp.add_argument("--config", required=True)
    sp.add_argument("--model_dir", required=True)
    sp.add_argument("--pass_id", type=int, default=-1)
    sp.add_argument("--output", required=True)
    sp.set_defaults(fn=cmd_merge_model)

    sp = sub.add_parser("infer", help="load a merged model")
    sp.add_argument("--model", required=True)
    sp.add_argument("--example", action="store_true",
                    help="run a zero-batch smoke forward")
    sp.add_argument("--batch", type=int, default=1)
    sp.set_defaults(fn=cmd_infer)

    sp = sub.add_parser("master", help="run the elastic input master")
    sp.add_argument("chunks", nargs="+")
    sp.add_argument("--timeout", type=float, default=60.0)
    sp.add_argument("--failure_max", type=int, default=3)
    sp.add_argument("--snapshot", default="")
    sp.set_defaults(fn=cmd_master)

    sp = sub.add_parser(
        "serve",
        help="run the continuous-batching inference server "
             "(bounded queue, load shedding, deadlines, drain)",
    )
    sp.add_argument("--config", required=True,
                    help="python file defining get_server()")
    sp.add_argument("--port", type=int, default=0,
                    help="TCP port (0 = ephemeral, printed as "
                         "LISTENING <port>)")
    sp.add_argument("--drain_timeout", type=float, default=30.0)
    sp.set_defaults(fn=cmd_serve)

    sp = sub.add_parser(
        "metrics",
        help="one-shot telemetry snapshot (process registry, or "
             "--stream FILE to summarize a JSONL event stream)",
    )
    sp.add_argument("--json", action="store_true",
                    help="JSON instead of text")
    sp.add_argument("--stream", default="",
                    help="summarize this JSONL event-stream file "
                         "instead of the in-process registry")
    sp.add_argument("--spans", action="store_true",
                    help="with --stream: per-span-name count/p50/p99 "
                         "table and the top-N slowest traces")
    sp.add_argument("--top", type=int, default=10,
                    help="with --spans: slowest traces to list")
    sp.set_defaults(fn=cmd_metrics)

    sp = sub.add_parser(
        "fleetz",
        help="live fleet snapshot: scrape replicas' metricz, merge "
             "into one fleet view (per-replica health table, fleet "
             "p50/p99 from merged buckets, active alerts)",
    )
    sp.add_argument("--addr", action="append", required=True,
                    help="replica address, repeatable: host:port or "
                         "name=host:port")
    sp.add_argument("--interval", type=float, default=1.0,
                    help="seconds between the two scrapes the "
                         "delta/rate view is computed over")
    sp.add_argument("--timeout", type=float, default=2.0,
                    help="per-replica scrape timeout")
    sp.add_argument("--slo-ms", type=float, default=0.0,
                    dest="slo_ms",
                    help="admitted-p99 SLO in ms (0 = no p99 alert)")
    sp.add_argument("--shed-threshold", type=float, default=0.5,
                    dest="shed_threshold",
                    help="per-replica shed-fraction alert threshold")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_fleetz)

    sp = sub.add_parser("make_diagram", help="emit graphviz dot of a config")
    sp.add_argument("--config", required=True)
    sp.add_argument("--config_args", default="")
    sp.add_argument("--output", default="")
    sp.set_defaults(fn=cmd_make_diagram)

    sp = sub.add_parser("bench", help="run the benchmark harness")
    sp.add_argument("--script", default="bench.py")
    sp.set_defaults(fn=cmd_bench)

    sp = sub.add_parser(
        "launch",
        help="start a multi-host job (the cluster_train/paddle.py "
             "ssh launcher, TPU-shaped: one jax.distributed process "
             "per host)",
    )
    sp.add_argument("--hosts", required=True,
                    help="comma-separated host list; first runs the "
                         "coordinator. localhost spawns locally")
    sp.add_argument("--nproc-per-host", type=int, default=1)
    sp.add_argument("--port", type=int, default=7164,
                    help="coordinator port on the first host")
    sp.add_argument("--ssh-opts", default="",
                    help="extra ssh options, e.g. '-i key.pem'")
    sp.add_argument("--max-respawns", type=int, default=3,
                    dest="max_respawns",
                    help="per-rank restarts after a preemption exit "
                         "(code 75) before it counts as a failure")
    sp.add_argument("command", nargs=argparse.REMAINDER,
                    help="the per-process command (after --), e.g. "
                         "python -m paddle_tpu train --config cfg.py")
    sp.set_defaults(fn=_cmd_launch)

    sp = sub.add_parser("version", help="print versions")
    sp.set_defaults(fn=cmd_version)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
