"""Beam-search sequence generation.

Reference: RecurrentGradientMachine::generateSequence + beamSearch
(gserver/gradientmachines/RecurrentGradientMachine.h:307,309, .cpp) and
the SWIG SequenceGenerator (api/SequenceGenerator.cpp). There, generation
walks frame nets step-by-step on a dynamically shrinking batch of live
beams. TPU-first: fixed [B, K] beam layout scanned to max_length with
finished-beam masking — one compiled program, no dynamic batch.

The step net is authored with the same DSL as recurrent_group: a data
layer for the previous word id, static links (encoder outputs etc.),
memories for decoder state. Its output layer must produce a probability
distribution [*, V] (softmax output).

User-callback beam hooks (RecurrentGradientMachine.h:92-152
registerBeamSearchControlCallbacks): `BeamHooks` carries plain-Python
callbacks executed HOST-SIDE each step via `jax.pure_callback` —
`adjust` rewrites candidate log-probs before expansion (the
BeamSearchCandidatesAdjustCallback), `drop` truncates/renormalizes
selected beams (NormOrDropNodeCallback/DropCallback), `stop` ends the
whole generation early (stopBeamSearch). A purely-JAX `logprob_fn` is
still available for hooks that don't need host code. Generation runs in
a `lax.while_loop` that exits as soon as every beam has emitted EOS (or
a stop hook fires) — no fixed worst-case step count.

Multi-token dispatch (ISSUE 18): the committed `nmt_beam4_decode_b32`
capture proved decode is dispatch-chain-bound, not byte-bound (~11.8 ms
byte floor vs 91.4 ms measured — a 7.7x gap from the 32-deep sequential
chain). `tokens_per_dispatch=K` makes one while-loop iteration advance
K steps via `lax.scan` over the same step body, cutting the chain from
`max_len` to `ceil(max_len/K)`. Every substep is guarded by a carried
done flag (`lax.cond`), so early-exit-on-all-finished, stop hooks, and
ragged tails stay BIT-IDENTICAL to the K=1 reference — hooks included
(guarded substeps skip their pure_callbacks entirely). The measured
chain depth of the last run is exposed as `last_chain_depth` — bench
rows report it measured-from-the-carried-counter, never assumed.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.config import LayerConf, ModelConf
from paddle_tpu.network import Network

NEG_INF = -1e30


@dataclass
class BeamHooks:
    """Host-side beam-search control callbacks
    (RecurrentGradientMachine.h:92-152). All are optional plain-Python
    functions receiving numpy arrays:

    - adjust(logp [B,K,V] f32, t int) -> [B,K,V] f32 — rewrite the
      step's candidate log-probs before expansion (forbid words, add
      user priors): BeamSearchCandidatesAdjustCallback.
    - drop(words [B,K] i32, scores [B,K] f32, t int) ->
      (scores [B,K] f32, drop_mask [B,K] bool) — renormalize selected
      beams and/or mark beams to truncate (they finish at this step
      with score NEG_INF): NormOrDropNodeCallback + DropCallback.
    - stop(finished [B,K] bool, scores [B,K] f32, t int) -> bool —
      end the whole generation now: stopBeamSearch.
    """

    adjust: Optional[Callable] = None
    drop: Optional[Callable] = None
    stop: Optional[Callable] = None


class BeamSearchDecoder:
    """Built from DSL pieces:

        def step(word, enc):
            emb = dsl.embedding(word, size=E, vocab_size=V, param=...)
            prev = dsl.memory("s", size=H, boot_layer=enc_last)
            s = dsl.fc(emb, prev, size=H, act="tanh", name="s")
            return dsl.fc(s, size=V, act="softmax", name="prob")

        dec = BeamSearchDecoder(step, n_static=1, bos_id=0, eos_id=1,
                                beam_size=4, max_length=20)
        seqs, lens, scores = dec.generate(params, statics=[enc_arg],
                                          boots={"s": enc_last_value})
    """

    def __init__(
        self,
        step: Callable,
        n_static: int,
        bos_id: int,
        eos_id: int,
        beam_size: int,
        max_length: int,
        logprob_fn: Optional[Callable] = None,
        static_sizes: Optional[list] = None,
        hooks: Optional[BeamHooks] = None,
        tokens_per_dispatch: int = 1,
    ):
        """`static_sizes` (optional, one int per static input) stamps
        the static stubs' sizes so size-dependent config helpers (e.g.
        dsl.simple_attention) work inside `step` at generation time the
        same way they do inside a training recurrent_group (whose stubs
        inherit sizes from the parent graph)."""
        from paddle_tpu import dsl

        assert static_sizes is None or len(static_sizes) == n_static, (
            f"static_sizes needs one entry per static input "
            f"({len(static_sizes)} given, n_static={n_static})"
        )
        assert tokens_per_dispatch >= 1, (
            f"tokens_per_dispatch must be >= 1, got {tokens_per_dispatch}"
        )
        self.bos_id, self.eos_id = bos_id, eos_id
        self.k = beam_size
        self.max_length = max_length
        self.logprob_fn = logprob_fn
        self.hooks = hooks or BeamHooks()
        self.tokens_per_dispatch = int(tokens_per_dispatch)
        # measured diagnostics of the LAST generate()/host run: how many
        # sequential dispatch-chain links the decode actually executed
        # (while-loop iterations here; jitted chunk programs on the host
        # rung) and how many token steps they covered
        self.last_chain_depth: Optional[int] = None
        self.last_steps: Optional[int] = None

        with dsl.model() as sub:
            word = sub.add(
                LayerConf(name="@word", type="data", size=1,
                          attrs={"dim": (1,), "is_seq": False,
                                 "is_ids": True})
            )
            statics = []
            for i in range(n_static):
                sz = (static_sizes or [0] * n_static)[i]
                statics.append(
                    sub.add(LayerConf(name=f"@static_{i}", type="data",
                                      size=sz,
                                      attrs={"dim": (sz,),
                                             "is_seq": False,
                                             "is_ids": False}))
                )
            out = step(word, *statics)
        self.step_conf: ModelConf = sub.conf
        self.memories = sub.memories
        self.out_name = out.name
        self.static_links = [f"@static_{i}" for i in range(n_static)]
        self._net: Optional[Network] = None

    def _build(self, statics: list):
        for i, a in enumerate(statics):
            lc = self.step_conf.layer(self.static_links[i])
            v = a.value if a.value is not None else a.ids
            dim = tuple(v.shape[2:] if a.is_seq else v.shape[1:]) or (1,)
            lc.attrs["dim"] = dim
            lc.attrs["is_seq"] = a.is_seq
            lc.attrs["is_ids"] = a.ids is not None
        self._net = Network(self.step_conf)
        return self._net

    def param_confs(self, statics: list):
        """Parameter table of the step net (names shared with training)."""
        return self._build(statics).param_confs

    def prepare(self, statics: list, boots: dict = None,
                batch_size: int = None):
        """Build (static_feed, init_carry_mem, b) — the K-tiled feed
        dict and boot memories both decode paths start from. Shared by
        the jitted while-loop program (generate) and the host-stepped
        per-token path (serving/host_decode.py), so the two rungs of
        the serving degradation ladder see identical inputs."""
        if self._net is None:
            self._build(statics)
        k = self.k
        boots = boots or {}
        if batch_size is not None:
            b = batch_size
        elif statics:
            a0 = statics[0]
            b = (a0.value if a0.value is not None else a0.ids).shape[0]
        elif boots:
            b = next(iter(boots.values())).shape[0]
        else:
            raise ValueError("generate() needs statics, boots, or batch_size")

        def tile(x):
            # [B, ...] -> [B*K, ...]
            return jnp.repeat(x, k, axis=0)

        static_feed = {}
        for i, a in enumerate(statics):
            static_feed[self.static_links[i]] = Arg(
                value=None if a.value is None else tile(a.value),
                ids=None if a.ids is None else tile(a.ids),
                seq_lens=None if a.seq_lens is None else tile(a.seq_lens),
            )

        init_carry_mem = {}
        for m in self.memories:
            if m["layer"] in boots:
                init_carry_mem[m["layer"]] = tile(boots[m["layer"]])
            elif m.get("boot_layer"):
                raise ValueError(
                    f"memory {m['layer']!r} declares boot_layer="
                    f"{m['boot_layer']!r}, but generate() cannot compute "
                    f"parent layers — pass boots={{{m['layer']!r}: value}} "
                    f"with that layer's [B, {m['size']}] output"
                )
            else:
                init_carry_mem[m["layer"]] = jnp.full(
                    (b * k, m["size"]), m.get("boot_value", 0.0), jnp.float32
                )
        return static_feed, init_carry_mem, b

    def generate(self, params: dict, statics: list, boots: dict = None,
                 batch_size: int = None):
        """statics: list[Arg] (batch-major, B rows). boots: memory layer
        name -> [B, size] boot value (overrides zeros/boot_value).
        Returns (seqs [B, K, max_length] int32, lens [B, K], scores [B, K]),
        beams sorted best-first."""
        static_feed, init_carry_mem, b = self.prepare(
            statics, boots, batch_size
        )
        run = self._decode_program()
        t0 = time.perf_counter()
        seqs, lens, scores, t_end, chunks = run(
            params, static_feed, init_carry_mem, b
        )
        t1 = time.perf_counter()
        # the chain depth is MEASURED: `chunks` is a counter carried
        # through the while-loop state, incremented once per executed
        # iteration (= one sequential dispatch-chain link on a tunneled
        # runtime), fetched after the run — never derived from config.
        # The int() fetches BLOCK on the whole jitted while-loop, so
        # they are the device-time window; only the submit window
        # before them is host dispatch work (`last_timeline` is what
        # bench rows must read — timing around generate() itself
        # attributes the entire device run to dispatch and reports a
        # nonsense host_overhead_frac of ~1.0)
        self.last_steps = int(t_end)
        self.last_chain_depth = int(chunks)
        t2 = time.perf_counter()
        self.last_timeline = {"dispatch_s": t1 - t0,
                              "device_s": t2 - t1}
        return seqs, lens, scores

    def _decode_program(self):
        """The whole decode (step net + while-loop + backtrace) as ONE
        jitted program, cached on the decoder (keyed by the hook/logprob
        closures; jax.jit handles shape-keyed retraces). Without this,
        every generate() call re-traced the loop and paid seconds of
        host tracing + compile-cache lookups per batch — measured 122
        ms/decode-step at B=32 K=4 V=30k vs ~3 ms jitted."""
        # key on everything _decode_core closes over at trace time —
        # hooks/logprob AND the scalar decode config (k/max_length/
        # eos/bos): mutating decoder attributes after the first
        # generate() must not silently reuse a stale compiled program
        hk = (self.hooks.adjust, self.hooks.drop, self.hooks.stop,
              self.logprob_fn, self.k, self.max_length, self.eos_id,
              self.bos_id, self.tokens_per_dispatch)
        cache = getattr(self, "_decode_cache", None)
        if cache is None:
            cache = self._decode_cache = {}
        guard = getattr(self, "_recompile_guard", None)
        if guard is None:
            from paddle_tpu.analysis.recompile_guard import (
                RecompileGuard,
            )

            guard = self._recompile_guard = RecompileGuard(
                "beam_decode"
            )
        if hk not in cache and len(cache) >= 8:
            # bound the cache: fresh hook lambdas per call would
            # otherwise grow it without limit (hooks should be stable
            # objects; evict oldest insertion when they are not)
            cache.pop(next(iter(cache)))
        if hk not in cache:
            # one jitted program per hook configuration — alternating
            # hook setups keep their compiled traces. NB: jit a fresh
            # closure, NOT the bound method: bound methods of the same
            # instance compare equal, so jit wrappers around them share
            # one trace cache and the second hook config would silently
            # reuse the first config's compiled program.
            def core(params, static_feed, init_carry_mem, b):
                # trace-time only (ISSUE 13): the serving batcher
                # arms this after warmup — a steady-state retrace of
                # a cached decode program is the 122 ms/step cliff
                # this cache exists to prevent
                guard.note(static_feed, init_carry_mem, b=b)
                return self._decode_core(
                    params, static_feed, init_carry_mem, b
                )

            cache[hk] = jax.jit(core, static_argnums=(3,))
        return cache[hk]

    def _expand_step(self, params, static_feed, mems, words, scores,
                     finished, t, b, adjust_fn=None, drop_fn=None):
        """One beam-expansion step: step-net forward, candidate scoring,
        finished-beam eos-extension, top-k, parent-conditioned memory
        carry. Shared by the jitted while-loop program (hook
        pure_callbacks threaded in via adjust_fn/drop_fn) and the host
        rung's chunked K-step program (hook-free) so the two dispatch
        granularities cannot drift semantically."""
        net, k = self._net, self.k
        feed = dict(static_feed)
        feed["@word"] = Arg(ids=words.reshape(b * k))
        for m in self.memories:
            feed[m["link"]] = Arg(value=mems[m["layer"]])
        outs, _ = net.forward(params, feed, train=False)
        prob = outs[self.out_name].value  # [B*K, V]
        v = prob.shape[-1]
        # score math is pinned to f32 regardless of AMP: under bf16
        # matmul precision the step net emits bf16 probs, and letting
        # weak-type promotion decide the carry dtype made the score
        # accumulator backend-dependent (while_loop silently promoted
        # the carry to bf16; lax.scan/cond refuse the same mismatch)
        logp = jnp.log(
            jnp.maximum(prob, 1e-20)
        ).reshape(b, k, v).astype(jnp.float32)
        if self.logprob_fn is not None:
            logp = self.logprob_fn(logp, t)
        if adjust_fn is not None:
            logp = adjust_fn(logp, t)
        # finished beams only extend with eos at no cost
        fin_row = jnp.full((v,), NEG_INF).at[self.eos_id].set(0.0)
        logp = jnp.where(finished[..., None], fin_row[None, None, :], logp)
        cand = scores[..., None] + logp  # [B,K,V]
        flat = cand.reshape(b, k * v)
        top_scores, top_idx = jax.lax.top_k(flat, k)  # [B,K]
        parent = top_idx // v  # [B,K]
        word = (top_idx % v).astype(jnp.int32)
        # reorder memories by parent beam
        new_mems = {}
        for m in self.memories:
            mm = outs[m["layer"]].value.reshape(b, k, -1)
            sel = jnp.take_along_axis(mm, parent[..., None], axis=1)
            prev = mems[m["layer"]].reshape(b, k, -1)
            prev_sel = jnp.take_along_axis(prev, parent[..., None], axis=1)
            was_fin = jnp.take_along_axis(finished, parent, axis=1)
            keep = was_fin[..., None]
            new_mems[m["layer"]] = jnp.where(
                keep, prev_sel, sel
            ).reshape(b * k, -1)
        was_fin = jnp.take_along_axis(finished, parent, axis=1)
        new_fin = was_fin | (word == self.eos_id)
        if drop_fn is not None:
            top_scores, new_fin = drop_fn(word, top_scores, new_fin, t)
        return new_mems, word, parent, top_scores, new_fin

    def _decode_core(self, params, static_feed, init_carry_mem, b):
        k = self.k
        hooks = self.hooks
        t_max = self.max_length
        k_tok = min(self.tokens_per_dispatch, t_max)

        adjust_fn = None
        if hooks.adjust is not None:
            # BeamSearchCandidatesAdjustCallback: host code rewrites
            # the candidate log-probs
            def adjust_fn(logp, t):
                bb, kk, vv = logp.shape
                return jax.pure_callback(
                    lambda lp, tt: np.asarray(
                        hooks.adjust(np.asarray(lp), int(tt)),
                        np.float32,
                    ),
                    jax.ShapeDtypeStruct((bb, kk, vv), jnp.float32),
                    logp, t,
                )

        drop_fn = None
        if hooks.drop is not None:
            # NormOrDropNodeCallback/DropCallback: host code
            # renormalizes selected beams and truncates dropped ones
            def drop_fn(word, top_scores, new_fin, t):
                def _drop(wd, sc, tt):
                    s2, dm = hooks.drop(
                        np.asarray(wd), np.asarray(sc), int(tt)
                    )
                    return (
                        np.asarray(s2, np.float32),
                        np.asarray(dm, bool),
                    )

                top_scores, drop_mask = jax.pure_callback(
                    _drop,
                    (
                        jax.ShapeDtypeStruct((b, k), jnp.float32),
                        jax.ShapeDtypeStruct((b, k), bool),
                    ),
                    word, top_scores, t,
                )
                top_scores = jnp.where(drop_mask, NEG_INF, top_scores)
                return top_scores, new_fin | drop_mask

        def step_once(mems, words, scores, finished, t):
            new_mems, word, parent, top_scores, new_fin = (
                self._expand_step(
                    params, static_feed, mems, words, scores, finished,
                    t, b, adjust_fn=adjust_fn, drop_fn=drop_fn,
                )
            )
            user_stop = jnp.asarray(False)
            if hooks.stop is not None:
                user_stop = jax.pure_callback(
                    lambda f, s, tt: bool(
                        hooks.stop(np.asarray(f), np.asarray(s), int(tt))
                    ),
                    jax.ShapeDtypeStruct((), bool),
                    new_fin, top_scores, t,
                )
            return new_mems, word, parent, top_scores, new_fin, user_stop

        # while-loop with preallocated trace buffers: exits as soon as
        # every beam has finished (or a stop hook fires) instead of
        # always paying max_length steps. Unwritten steps hold
        # (word=eos, parent=identity), which backtraces benignly.
        words0 = jnp.full((b, k), self.bos_id, jnp.int32)
        scores0 = jnp.full(
            (b, k), NEG_INF, jnp.float32
        ).at[:, 0].set(0.0)
        fin0 = jnp.zeros((b, k), bool)
        idk = jnp.broadcast_to(
            jnp.arange(k, dtype=jnp.int32)[None, :], (b, k)
        )
        ws0 = jnp.full((t_max, b, k), self.eos_id, jnp.int32)
        ps0 = jnp.broadcast_to(idk[None], (t_max, b, k))
        state0 = (
            init_carry_mem, words0, scores0, fin0, jnp.int32(0),
            jnp.asarray(False), ws0, ps0, jnp.int32(0),
        )

        def cond(state):
            _, _, _, finished, t, stop, _, _, _ = state
            return (t < t_max) & ~stop & ~jnp.all(finished)

        def run_one(inner):
            mems, words, scores, finished, t, _, ws, ps = inner
            new_mems, word, parent, scores, new_fin, user_stop = (
                step_once(mems, words, scores, finished, t)
            )
            ws = ws.at[t].set(word)
            ps = ps.at[t].set(parent)
            return (
                new_mems, word, scores, new_fin, t + 1, user_stop, ws, ps,
            )

        def body(state):
            # one while-loop iteration = ONE sequential dispatch-chain
            # link; `chunks` counts them so the reported chain depth is
            # measured, not derived from config
            inner, chunks = state[:8], state[8]
            if k_tok == 1:
                inner = run_one(inner)
            else:
                # advance up to k_tok steps inside this iteration. Each
                # substep re-checks the exit condition and no-ops once
                # it holds (lax.cond skips the step net AND any hook
                # pure_callbacks), so early-finish/stop mid-chunk and
                # ragged t_max tails stay bit-identical to K=1.
                def substep(carry, _):
                    _, _, _, finished, t, stop, _, _ = carry
                    done = (
                        stop | (t >= t_max) | jnp.all(finished)
                    )
                    carry = jax.lax.cond(
                        done, lambda c: c, run_one, carry
                    )
                    return carry, None

                inner, _ = jax.lax.scan(
                    substep, inner, None, length=k_tok
                )
            return (*inner, chunks + 1)

        _, _, scores, finished, t_end, _, ws, ps, chunks = (
            jax.lax.while_loop(cond, body, state0)
        )

        # backtrace beam parents to recover sequences
        def back(nxt_parent, step_out):
            w_t, p_t = step_out
            w = jnp.take_along_axis(w_t, nxt_parent, axis=1)
            p = jnp.take_along_axis(p_t, nxt_parent, axis=1)
            return p, w

        _, seq_rev = jax.lax.scan(back, idk, (ws, ps), reverse=True)
        seqs = seq_rev.transpose(1, 2, 0)  # [B,K,T]
        # length = position of first eos + 1 (or max_length)
        is_eos = seqs == self.eos_id
        any_eos = jnp.any(is_eos, axis=-1)
        first_eos = jnp.argmax(is_eos, axis=-1)
        lens = jnp.where(any_eos, first_eos + 1, t_max).astype(jnp.int32)
        return seqs, lens, scores, t_end, chunks

    def _chunk_step_program(self, b: int, n_steps: int):
        """K beam-expansion steps + bookkeeping as ONE jitted program —
        the serving host rung's per-chunk dispatch unit (ISSUE 18).
        Hook-free by construction: host callbacks force the per-token
        path. Each substep is guarded by an all-finished check so an
        early finish mid-chunk no-ops the tail (word=eos,
        parent=identity — the trace-buffer convention the backtrace
        already treats as benign). The carried memories are DONATED:
        they alias the returned memories buffer-for-buffer, which the
        committed capture's audit policy checks via input_output_alias.

        Returns a jitted fn (params, static_feed, mems, words, scores,
        finished, t0) -> (words_stack [n,B,K], parents_stack [n,B,K],
        last_words, scores, finished, new_mems)."""
        cache = getattr(self, "_chunk_cache", None)
        if cache is None:
            cache = self._chunk_cache = {}
        key = (b, self.k, n_steps, self.logprob_fn, self.eos_id,
               self.max_length)
        if key not in cache and len(cache) >= 8:
            cache.pop(next(iter(cache)))
        if key not in cache:
            k, eos = self.k, self.eos_id
            idk = jnp.broadcast_to(
                jnp.arange(k, dtype=jnp.int32)[None, :], (b, k)
            )

            def chunk(params, static_feed, mems, words, scores,
                      finished, t0):
                def substep(carry, j):
                    mems, words, scores, finished = carry
                    t = t0 + j

                    def run(c):
                        mems, words, scores, finished = c
                        new_mems, word, parent, s2, fin2 = (
                            self._expand_step(
                                params, static_feed, mems, words,
                                scores, finished, t, b,
                            )
                        )
                        return (
                            (new_mems, word, s2, fin2), (word, parent)
                        )

                    def skip(c):
                        word = jnp.full((b, k), eos, jnp.int32)
                        return c, (word, idk)

                    return jax.lax.cond(
                        jnp.all(finished), skip, run, carry
                    )

                (mems2, words2, scores2, fin2), (ws, ps) = jax.lax.scan(
                    substep, (mems, words, scores, finished),
                    jnp.arange(n_steps),
                )
                return ws, ps, words2, scores2, fin2, mems2

            cache[key] = jax.jit(chunk, donate_argnums=(2,))
        return cache[key]
