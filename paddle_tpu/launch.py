"""Multi-host job launcher — `python -m paddle_tpu launch`.

Reference: paddle/scripts/cluster_train/paddle.py:24-157 — the fabric/
ssh launcher that started pservers + trainers on every node of a
cluster with the right ports/trainer_id environment. The TPU-native
equivalent is much smaller because there are no pserver processes:
one process per host joins a `jax.distributed` rendezvous (the
coordinator is process 0) and the SAME jit-compiled program runs SPMD
across all hosts' chips — the launcher only has to start the processes
with the right coordinator/world/rank environment.

    python -m paddle_tpu launch --hosts a,b,c -- \
        python -m paddle_tpu train --config cfg.py

Local smoke form (and the unit-tested path): --hosts localhost with
--nproc-per-host N starts N local processes. Remote hosts are reached
via plain `ssh` (the reference assumed the binaries/data are already
installed on every node — same contract, cluster_train/paddle.py
job_prepare docstring).

Environment protocol (read by `distributed_init_from_env`):
    PADDLE_COORDINATOR  host:port of process 0's coordinator
    PADDLE_NUM_PROCESSES / PADDLE_PROCESS_ID  world size / rank
"""

from __future__ import annotations

import os
import shlex
import subprocess
import sys
import threading

__all__ = ["launch", "distributed_init_from_env", "main"]


def distributed_init_from_env(env=os.environ) -> bool:
    """Join the rendezvous the launcher described in the environment.
    Returns True if distributed mode was initialized."""
    coord = env.get("PADDLE_COORDINATOR")
    if not coord:
        return False
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.core.mesh import distributed_init

    n = int(env.get("PADDLE_NUM_PROCESSES", "1"))
    pid = int(env.get("PADDLE_PROCESS_ID", "0"))
    _flags.set_flag("coordinator_address", coord)
    _flags.set_flag("num_processes", n)
    _flags.set_flag("process_id", pid)
    distributed_init(
        coordinator_address=coord, num_processes=n, process_id=pid
    )
    return True


def _is_local(host: str) -> bool:
    return host in ("localhost", "127.0.0.1", "::1")


def _stream(proc, tag):
    """Prefix a worker's stdout lines (the launcher's merged log —
    cluster_train/paddle.py tailed per-node logs instead)."""

    def pump():
        for line in proc.stdout:
            sys.stdout.write(f"[{tag}] {line}")
            sys.stdout.flush()

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    return t


def launch(
    hosts,
    command,
    nproc_per_host: int = 1,
    coordinator_port: int = 7164,
    ssh_opts=(),
    extra_env=None,
    max_respawns: int = 3,
) -> int:
    """Start `command` on every host with the rendezvous environment;
    wait for all; kill the survivors if any process fails. Returns the
    first non-zero exit code (0 = all succeeded).

    A rank that exits with `EXIT_PREEMPTED` (75 — the trainer's
    SIGTERM contract, trainer/watchdog.py) is NOT a failure: it
    flushed a checkpoint and asked to be restarted, so the launcher
    respawns it in place (up to `max_respawns` times per rank) and the
    respawned trainer auto-resumes from the flushed checkpoint."""
    from paddle_tpu.trainer.watchdog import EXIT_PREEMPTED

    if isinstance(hosts, str):
        hosts = [h.strip() for h in hosts.split(",") if h.strip()]
    world = len(hosts) * nproc_per_host
    coord_host = hosts[0] if not _is_local(hosts[0]) else "127.0.0.1"
    coord = f"{coord_host}:{coordinator_port}"

    def _spawn(host, rank):
        env_kv = {
            "PADDLE_COORDINATOR": coord,
            "PADDLE_NUM_PROCESSES": str(world),
            "PADDLE_PROCESS_ID": str(rank),
            **(extra_env or {}),
        }
        if _is_local(host):
            p = subprocess.Popen(
                command,
                env={**os.environ, **env_kv},
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        else:
            # the reference's fabric run() ≙ plain ssh; quoting via
            # shlex so the command survives the remote shell
            remote = "cd {wd} && {env} {cmd}".format(
                wd=shlex.quote(os.getcwd()),
                env=" ".join(
                    f"{k}={shlex.quote(v)}" for k, v in env_kv.items()
                ),
                cmd=" ".join(shlex.quote(c) for c in command),
            )
            p = subprocess.Popen(
                ["ssh", *ssh_opts, host, remote],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        _stream(p, f"rank{rank}@{host}")
        return p

    slots = []  # rank -> (host,)
    procs = []
    rank = 0
    for host in hosts:
        for _ in range(nproc_per_host):
            slots.append(host)
            procs.append(_spawn(host, rank))
            rank += 1
    respawns = [0] * len(procs)

    rc = 0
    try:
        # Poll ALL ranks, not procs[0] first: a crash on a later rank
        # must be observed even while earlier ranks block forever in a
        # collective waiting for it.
        import time as _time

        live = list(range(len(procs)))
        while live:
            for r in list(live):
                code = procs[r].poll()
                if code is None:
                    continue
                if (code == EXIT_PREEMPTED
                        and respawns[r] < max_respawns):
                    # preemption, not failure: restart the rank; its
                    # trainer resumes from the flushed checkpoint
                    respawns[r] += 1
                    sys.stdout.write(
                        f"[launch] rank{r} preempted (exit "
                        f"{EXIT_PREEMPTED}); respawn "
                        f"{respawns[r]}/{max_respawns}\n"
                    )
                    sys.stdout.flush()
                    procs[r] = _spawn(slots[r], r)
                    continue
                live.remove(r)
                if code and not rc:
                    rc = code
                    # fail fast: a dead member blocks the collective
                    # for everyone else — bring the job down
                    for q in procs:
                        if q.poll() is None:
                            q.kill()
            if live:
                _time.sleep(0.05)
    finally:
        for q in procs:
            if q.poll() is None:
                q.kill()
                q.wait()
    return rc


def main(args) -> int:
    return launch(
        args.hosts,
        args.command,
        nproc_per_host=args.nproc_per_host,
        coordinator_port=args.port,
        ssh_opts=shlex.split(args.ssh_opts) if args.ssh_opts else (),
        max_respawns=getattr(args, "max_respawns", 3),
    )
