"""Test utilities: numeric gradient checking and random batch builders.

The reference gates every layer behind numeric-vs-analytic gradient checks
(gserver/tests/LayerGradUtil.h:299 testLayerGrad, perturbation loop
:204-279) and random input builders (paddle/testing/TestUtil.h). Same
contract here: build a one-layer net from a LayerConf, compare jax.grad
against central finite differences for every parameter and every
differentiable input.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.config import InputConf, LayerConf, ModelConf
from paddle_tpu.network import Network


def make_seq_lens(rng: np.random.Generator, batch: int, max_len: int):
    lens = rng.integers(1, max_len + 1, size=batch)
    lens[rng.integers(0, batch)] = max_len  # at least one full-length row
    return jnp.asarray(lens, jnp.int32)


def random_arg(
    rng: np.random.Generator,
    spec_dim,
    batch=4,
    is_seq=False,
    max_len=5,
    is_ids=False,
    vocab=10,
):
    dim = tuple(spec_dim) if isinstance(spec_dim, (tuple, list)) else (spec_dim,)
    lead = (batch, max_len) if is_seq else (batch,)
    lens = make_seq_lens(rng, batch, max_len) if is_seq else None
    if is_ids:
        ids = jnp.asarray(rng.integers(0, vocab, size=lead), jnp.int32)
        return Arg(ids=ids, seq_lens=lens)
    v = jnp.asarray(rng.standard_normal(lead + dim), jnp.float32)
    return Arg(value=v, seq_lens=lens)


def build_single_layer_net(layer_conf: LayerConf, data_confs: list) -> Network:
    """data_confs: list of LayerConf of type 'data' matching
    layer_conf.inputs order."""
    model = ModelConf(layers=data_confs + [layer_conf])
    return Network(model)


def check_layer_grad(
    layer_conf: LayerConf,
    data_confs: list,
    feed: dict,
    *,
    seed: int = 0,
    eps: float = 1e-3,
    rtol: float = 5e-2,
    atol: float = 1e-3,
    loss_weights: bool = True,
    check_inputs: bool = True,
    train: bool = False,
):
    """Numeric-vs-analytic gradient check, the testLayerGrad contract.

    Builds net = data layers + the layer under test, defines
    loss = sum(output * random_fixed_weight) (masked for sequences, as the
    reference weights each output element), and compares jax.grad to
    central differences for every parameter (and optionally every dense
    input)."""
    net = build_single_layer_net(layer_conf, data_confs)
    key = jax.random.key(seed)
    params = net.init_params(key)
    state = net.init_state()
    out_name = layer_conf.name
    rng = np.random.default_rng(seed + 1)

    # fixed random output weighting -> scalar loss
    def compute_loss(params, feed):
        outs, _ = net.forward(
            params, feed, state=state, train=train, rng=jax.random.key(123)
        )
        out = outs[out_name]
        w = jnp.asarray(
            np.random.default_rng(seed + 2).standard_normal(out.value.shape),
            jnp.float32,
        )
        v = out.value * w
        if out.is_seq:
            m = out.mask(v.dtype)
            v = v * m.reshape(m.shape + (1,) * (v.ndim - 2))
        return jnp.sum(v)

    # jit once: numeric differencing calls this O(params*64*2) times,
    # and the eager op-by-op walk dominated the suite's wall clock
    compute_loss = jax.jit(compute_loss)

    # analytic
    g_params = jax.grad(compute_loss)(params, feed)

    # numeric per parameter
    def numeric_grad(getter, setter, shape, nelem_cap=64):
        flat_idx = np.arange(int(np.prod(shape)))
        if len(flat_idx) > nelem_cap:
            flat_idx = np.random.default_rng(seed + 3).choice(
                flat_idx, nelem_cap, replace=False
            )
        grads = {}
        for fi in flat_idx:
            idx = np.unravel_index(fi, shape)
            base = getter()
            pert = np.asarray(base).copy()
            pert[idx] += eps
            lp = float(compute_loss(*setter(jnp.asarray(pert))))
            pert[idx] -= 2 * eps
            lm = float(compute_loss(*setter(jnp.asarray(pert))))
            grads[idx] = (lp - lm) / (2 * eps)
        return grads

    failures = []
    for pname, pval in params.items():
        def getter(pname=pname):
            return params[pname]

        def setter(v, pname=pname):
            p2 = dict(params)
            p2[pname] = v
            return (p2, feed)

        num = numeric_grad(getter, setter, pval.shape)
        ana = np.asarray(g_params[pname])
        for idx, gn in num.items():
            ga = float(ana[idx])
            if not np.isclose(gn, ga, rtol=rtol, atol=atol):
                failures.append(f"param {pname}{list(idx)}: numeric={gn:.6f} analytic={ga:.6f}")

    if check_inputs:
        g_feed = jax.grad(lambda f: compute_loss(params, f), allow_int=True)(feed)
        for dname, arg in feed.items():
            if arg.value is None:
                continue

            def getter(dname=dname):
                return feed[dname].value

            def setter(v, dname=dname):
                f2 = dict(feed)
                f2[dname] = feed[dname].with_value(v)
                return (params, f2)

            num = numeric_grad(getter, setter, arg.value.shape)
            ana = np.asarray(g_feed[dname].value)
            for idx, gn in num.items():
                ga = float(ana[idx])
                if not np.isclose(gn, ga, rtol=rtol, atol=atol):
                    failures.append(
                        f"input {dname}{list(idx)}: numeric={gn:.6f} analytic={ga:.6f}"
                    )

    assert not failures, (
        f"gradient check failed for layer {layer_conf.type}:\n" + "\n".join(failures[:20])
    )


def data_conf(name, dim, is_seq=False, is_ids=False, has_subseq=False):
    dim = tuple(dim) if isinstance(dim, (tuple, list)) else (dim,)
    return LayerConf(
        name=name,
        type="data",
        size=int(np.prod(dim)),
        attrs={"dim": dim, "is_seq": is_seq, "is_ids": is_ids, "has_subseq": has_subseq},
    )


def input_conf(name, **attrs):
    return InputConf(name=name, attrs=attrs)
