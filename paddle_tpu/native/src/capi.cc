// C inference ABI over the paddle_tpu runtime.
//
// Reference surface: paddle/capi/gradient_machine.h:36-75
// (paddle_gradient_machine_create_for_inference_with_parameters,
// paddle_gradient_machine_forward) and capi/matrix.h dense buffers.
// Like the reference trainer embedding Python for config parsing
// (paddle/utils/PythonUtil.h), this library embeds CPython and defers
// marshaling to paddle_tpu/capi_bridge.py; the exported surface is a
// pure C ABI a serving process can dlopen with no Python headers.
//
// Build: make -C paddle_tpu/native capi   (links libpython).

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>

#include "../include/pt_capi.h"

namespace {

std::mutex g_mu;
std::string g_error;
bool g_we_initialized = false;
PyThreadState* g_main_tstate = nullptr;

std::mutex g_err_mu;  // guards g_error against cross-thread get/set

void set_error(const char* what) {
  std::string msg = what ? what : "unknown error";
  if (PyErr_Occurred()) {
    PyObject *type, *value, *tb;
    PyErr_Fetch(&type, &value, &tb);
    if (value) {
      PyObject* s = PyObject_Str(value);
      if (s) {
        const char* c = PyUnicode_AsUTF8(s);
        if (c) {
          msg += ": ";
          msg += c;
        }
        Py_DECREF(s);
      }
      // PyObject_Str or PyUnicode_AsUTF8 may have set a NEW exception;
      // never leave it pending for the next bridge call
      PyErr_Clear();
    }
    Py_XDECREF(type);
    Py_XDECREF(value);
    Py_XDECREF(tb);
  }
  std::lock_guard<std::mutex> lock(g_err_mu);
  g_error = std::move(msg);
}

PyObject* bridge() {
  static PyObject* mod = nullptr;
  if (!mod) {
    mod = PyImport_ImportModule("paddle_tpu.capi_bridge");
  }
  return mod;
}

struct Gil {
  PyGILState_STATE st;
  Gil() : st(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(st); }
};

}  // namespace

extern "C" {

// Initialize the runtime. `repo_path` (may be null) is prepended to
// sys.path so `import paddle_tpu` resolves. Returns 0 on success.
int pt_capi_init(const char* repo_path) {
  std::lock_guard<std::mutex> lock(g_mu);
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    g_we_initialized = true;
  }
  int rc = 0;
  {
    Gil gil;
    if (repo_path && *repo_path) {
      PyObject* sys_path = PySys_GetObject("path");  // borrowed
      PyObject* p = PyUnicode_FromString(repo_path);
      if (!sys_path || !p || PyList_Insert(sys_path, 0, p) != 0) {
        Py_XDECREF(p);
        set_error("cannot extend sys.path");
        rc = -1;
      } else {
        Py_DECREF(p);
      }
    }
    if (rc == 0 && !bridge()) {
      set_error("cannot import paddle_tpu.capi_bridge");
      rc = -1;
    }
  }
  // Py_InitializeEx leaves the calling thread holding the GIL; release
  // it so pt_capi_* calls from OTHER threads (the normal serving
  // pattern) can PyGILState_Ensure without deadlocking on this thread.
  if (g_we_initialized && g_main_tstate == nullptr && PyGILState_Check()) {
    g_main_tstate = PyEval_SaveThread();
  }
  return rc;
}

// Load a merged model (trainer/MergeModel.cpp analogue). Returns a
// handle > 0, or 0 on error.
int64_t pt_capi_create(const char* merged_path, const char* output_layer) {
  Gil gil;
  PyObject* m = bridge();
  if (!m) {
    set_error("runtime not initialized");
    return 0;
  }
  PyObject* r = PyObject_CallMethod(
      m, "create", "ss", merged_path, output_layer ? output_layer : "");
  if (!r) {
    set_error("create failed");
    return 0;
  }
  int64_t h = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return h;
}

// Total per-example output width of the first output layer.
int64_t pt_capi_output_dim(int64_t handle) {
  Gil gil;
  PyObject* r =
      PyObject_CallMethod(bridge(), "output_dim", "L", (long long)handle);
  if (!r) {
    set_error("output_dim failed");
    return -1;
  }
  int64_t d = PyLong_AsLongLong(r);
  Py_DECREF(r);
  return d;
}

// Forward one batch. n_inputs parallel arrays describe the feed:
// names[i]; bufs[i] (float32 row-major, or int32 when is_ids[i]);
// shapes[i] points at ndims[i] int64 dims. The first output layer's
// value is written to out_buf (capacity out_cap floats); its shape is
// written to out_shape (capacity 8), returning the output rank, or -1.
int pt_capi_forward(int64_t handle, const char** names, const void** bufs,
                    const int64_t** shapes, const int* ndims,
                    const int* is_ids, int n_inputs, float* out_buf,
                    int64_t out_cap, int64_t* out_shape) {
  Gil gil;
  PyObject *py_names = PyList_New(n_inputs),
           *py_addrs = PyList_New(n_inputs),
           *py_shapes = PyList_New(n_inputs),
           *py_ids = PyList_New(n_inputs);
  bool alloc_ok = py_names && py_addrs && py_shapes && py_ids;
  for (int i = 0; alloc_ok && i < n_inputs; ++i) {
    PyObject* nm = PyUnicode_FromString(names[i]);
    PyObject* addr = PyLong_FromVoidPtr((void*)bufs[i]);
    PyObject* shp = PyList_New(ndims[i]);
    PyObject* ids = PyBool_FromLong(is_ids[i]);
    if (!nm || !addr || !shp || !ids) {
      Py_XDECREF(nm);
      Py_XDECREF(addr);
      Py_XDECREF(shp);
      Py_XDECREF(ids);
      alloc_ok = false;
      break;
    }
    for (int d = 0; alloc_ok && d < ndims[i]; ++d) {
      PyObject* dim = PyLong_FromLongLong(shapes[i][d]);
      if (!dim) {
        alloc_ok = false;
        break;
      }
      PyList_SetItem(shp, d, dim);
    }
    PyList_SetItem(py_names, i, nm);
    PyList_SetItem(py_addrs, i, addr);
    PyList_SetItem(py_shapes, i, shp);
    PyList_SetItem(py_ids, i, ids);
  }
  if (!alloc_ok) {
    Py_XDECREF(py_names);
    Py_XDECREF(py_addrs);
    Py_XDECREF(py_shapes);
    Py_XDECREF(py_ids);
    set_error("forward: allocation failed");
    return -1;
  }
  PyObject* r = PyObject_CallMethod(
      bridge(), "forward", "LOOOOLL", (long long)handle, py_names, py_addrs,
      py_shapes, py_ids, (long long)(intptr_t)out_buf, (long long)out_cap);
  Py_DECREF(py_names);
  Py_DECREF(py_addrs);
  Py_DECREF(py_shapes);
  Py_DECREF(py_ids);
  if (!r) {
    set_error("forward failed");
    return -1;
  }
  int rank = (int)PyList_Size(r);
  for (int d = 0; d < rank && d < 8; ++d)
    out_shape[d] = PyLong_AsLongLong(PyList_GetItem(r, d));
  Py_DECREF(r);
  return rank;
}

// Full-surface forward: sequence (ragged ids / dense rows + start
// positions, optional nested level) and sparse CSR slots — the
// reference C API's paddle_arguments_set_sequence_start_pos
// (capi/arguments.h:137) and paddle_matrix_create_sparse /
// paddle_matrix_sparse_copy_from (capi/matrix.h:52,102) surface.
// Marshaling stays in capi_bridge.forward_slots; here each slot is
// packed into a dict of addresses/sizes.
int pt_capi_forward_slots(int64_t handle, const pt_capi_slot* slots,
                          int n_slots, float* out_buf, int64_t out_cap,
                          int64_t* out_shape) {
  Gil gil;
  PyObject* py_slots = PyList_New(n_slots);
  if (!py_slots) {
    set_error("forward_slots: allocation failed");
    return -1;
  }
  bool ok = true;
  for (int i = 0; ok && i < n_slots; ++i) {
    const pt_capi_slot& s = slots[i];
    PyObject* shp = PyList_New(s.ndims > 0 ? s.ndims : 0);
    for (int d = 0; shp && d < s.ndims; ++d) {
      PyObject* dim = PyLong_FromLongLong(s.shape[d]);
      if (!dim) {
        Py_CLEAR(shp);
        break;
      }
      PyList_SetItem(shp, d, dim);
    }
    PyObject* dict =
        shp ? Py_BuildValue(
                  "{s:s, s:i, s:L, s:O, s:L, s:i, s:L, s:i, s:L, s:L, "
                  "s:L, s:L, s:L, s:L}",
                  "name", s.name ? s.name : "", "kind", s.kind, "buf",
                  (long long)(intptr_t)s.buf, "shape", shp, "seq_pos",
                  (long long)(intptr_t)s.seq_pos, "n_seq", s.n_seq,
                  "subseq_pos", (long long)(intptr_t)s.subseq_pos,
                  "n_subseq", s.n_subseq, "width", (long long)s.width,
                  "rows", (long long)(intptr_t)s.rows, "cols",
                  (long long)(intptr_t)s.cols, "vals",
                  (long long)(intptr_t)s.vals, "height",
                  (long long)s.height, "nnz", (long long)s.nnz)
            : nullptr;
    // "O" borrows shp (increfs on use), so this frame's reference is
    // released unconditionally — leak-free on failure without the
    // double-decref a "N" + manual-clear pairing risks when the dict
    // builder fails AFTER consuming the shape pair
    Py_XDECREF(shp);
    if (!dict) {
      ok = false;
      break;
    }
    PyList_SetItem(py_slots, i, dict);
  }
  if (!ok) {
    Py_DECREF(py_slots);
    set_error("forward_slots: allocation failed");
    return -1;
  }
  PyObject* r = PyObject_CallMethod(
      bridge(), "forward_slots", "LOLL", (long long)handle, py_slots,
      (long long)(intptr_t)out_buf, (long long)out_cap);
  Py_DECREF(py_slots);
  if (!r) {
    set_error("forward_slots failed");
    return -1;
  }
  int rank = (int)PyList_Size(r);
  for (int d = 0; d < rank && d < 8; ++d)
    out_shape[d] = PyLong_AsLongLong(PyList_GetItem(r, d));
  Py_DECREF(r);
  return rank;
}

void pt_capi_destroy(int64_t handle) {
  Gil gil;
  PyObject* r =
      PyObject_CallMethod(bridge(), "destroy", "L", (long long)handle);
  Py_XDECREF(r);
}

// Copies the last error into a thread-local buffer so the returned
// pointer stays valid on this thread even if another thread sets a new
// error concurrently.
const char* pt_capi_error() {
  static thread_local std::string local;
  std::lock_guard<std::mutex> lock(g_err_mu);
  local = g_error;
  return local.c_str();
}

}  // extern "C"
