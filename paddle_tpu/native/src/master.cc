// Elastic task-queue master (fault-tolerant input dispatch).
//
// Capability parity with the reference's Go master
// (go/master/service.go): todo/pending/done task queues over dataset
// chunks, lease timeouts that requeue lost tasks, a per-task failure cap
// that discards poison tasks, pass rotation (done -> todo), and
// CRC-protected snapshot/restore so a restarted master resumes where it
// left off (service.go:89,166,207,313-356,448). etcd is replaced by a
// snapshot file the coordinator host owns — rebuilt in C++ as a
// lock-protected in-process service callable from any trainer process.

#include <chrono>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "common.h"

namespace {

double now_s() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

struct Task {
  int64_t id = 0;
  std::string payload;
  int failures = 0;
};

struct Master {
  double lease_seconds = 60.0;
  int failure_max = 3;
  int64_t next_id = 1;
  int64_t next_lease = 1;  // lease ids are fresh per lease: a worker
                           // holding an expired lease cannot ack a task
                           // that was re-leased to someone else (the Go
                           // master's epoch check, service.go:410)
  std::deque<Task> todo;
  std::unordered_map<int64_t, std::pair<Task, double>> pending;  // lease -> (task, deadline)
  std::vector<Task> done;
  std::vector<Task> discarded;
  // save-model election (go/master/service.go:467-495): the granted
  // trainer holds the save slot until block_dur elapses; re-requests by
  // the same trainer are re-granted. Transient — not snapshotted.
  std::string saving_trainer;
  double saving_deadline = 0.0;
  std::mutex mu;

  void requeue_expired_locked() {
    double t = now_s();
    for (auto it = pending.begin(); it != pending.end();) {
      if (it->second.second <= t) {
        Task task = std::move(it->second.first);
        task.failures++;
        it = pending.erase(it);
        if (task.failures >= failure_max) {
          discarded.push_back(std::move(task));
        } else {
          todo.push_back(std::move(task));
        }
      } else {
        ++it;
      }
    }
  }
};

constexpr uint32_t kSnapMagic = 0x50544d53;  // "PTMS"

void put_task(std::string* buf, const Task& t) {
  pt::put<int64_t>(buf, t.id);
  pt::put<int32_t>(buf, t.failures);
  pt::put<uint32_t>(buf, static_cast<uint32_t>(t.payload.size()));
  buf->append(t.payload);
}

bool get_task(const char** p, const char* end, Task* t) {
  uint32_t plen;
  int32_t fails;
  if (!pt::get(p, end, &t->id)) return false;
  if (!pt::get(p, end, &fails)) return false;
  if (!pt::get(p, end, &plen)) return false;
  if (end - *p < static_cast<ptrdiff_t>(plen)) return false;
  t->failures = fails;
  t->payload.assign(*p, plen);
  *p += plen;
  return true;
}

}  // namespace

extern "C" {

Master* pt_master_create(double lease_seconds, int failure_max) {
  auto* m = new Master();
  if (lease_seconds >= 0) m->lease_seconds = lease_seconds;
  if (failure_max > 0) m->failure_max = failure_max;
  return m;
}

void pt_master_destroy(Master* m) { delete m; }

int64_t pt_master_add_task(Master* m, const char* payload, int64_t len) {
  std::lock_guard<std::mutex> l(m->mu);
  Task t;
  t.id = m->next_id++;
  t.payload.assign(payload, static_cast<size_t>(len));
  m->todo.push_back(std::move(t));
  return m->next_id - 1;
}

// Lease the next task. Returns payload length (>= 0; empty payloads are
// valid), -3 if no task is currently available, -1 if buf too small (the
// task is NOT leased; *task_id receives the required size so the caller
// can retry with a larger buffer instead of wedging the queue head).
// On success task_id receives the lease id to report done/failed against.
int64_t pt_master_get_task(Master* m, char* buf, int64_t cap,
                           int64_t* task_id) {
  std::lock_guard<std::mutex> l(m->mu);
  m->requeue_expired_locked();
  if (m->todo.empty()) return -3;
  Task& t = m->todo.front();
  if (static_cast<int64_t>(t.payload.size()) > cap) {
    *task_id = static_cast<int64_t>(t.payload.size());
    return -1;
  }
  int64_t n = static_cast<int64_t>(t.payload.size());
  std::memcpy(buf, t.payload.data(), t.payload.size());
  int64_t lease = m->next_lease++;
  *task_id = lease;
  m->pending[lease] = {std::move(t), now_s() + m->lease_seconds};
  m->todo.pop_front();
  return n;
}

int pt_master_task_done(Master* m, int64_t task_id) {
  std::lock_guard<std::mutex> l(m->mu);
  auto it = m->pending.find(task_id);
  if (it == m->pending.end()) return -1;  // lease lost (timed out)
  m->done.push_back(std::move(it->second.first));
  m->pending.erase(it);
  return 0;
}

int pt_master_task_failed(Master* m, int64_t task_id) {
  std::lock_guard<std::mutex> l(m->mu);
  auto it = m->pending.find(task_id);
  if (it == m->pending.end()) return -1;
  Task t = std::move(it->second.first);
  m->pending.erase(it);
  t.failures++;
  if (t.failures >= m->failure_max) {
    m->discarded.push_back(std::move(t));
  } else {
    m->todo.push_back(std::move(t));
  }
  return 0;
}

// All tasks finished this pass? (todo and pending empty)
int pt_master_pass_finished(Master* m) {
  std::lock_guard<std::mutex> l(m->mu);
  m->requeue_expired_locked();
  return m->todo.empty() && m->pending.empty() ? 1 : 0;
}

// Rotate done -> todo for the next pass (service.go's pass semantics).
int64_t pt_master_start_pass(Master* m) {
  std::lock_guard<std::mutex> l(m->mu);
  for (auto& t : m->done) {
    t.failures = 0;
    m->todo.push_back(std::move(t));
  }
  m->done.clear();
  return static_cast<int64_t>(m->todo.size());
}

int64_t pt_master_count(Master* m, int which) {
  std::lock_guard<std::mutex> l(m->mu);
  m->requeue_expired_locked();
  switch (which) {
    case 0: return static_cast<int64_t>(m->todo.size());
    case 1: return static_cast<int64_t>(m->pending.size());
    case 2: return static_cast<int64_t>(m->done.size());
    case 3: return static_cast<int64_t>(m->discarded.size());
    default: return -1;
  }
}

// Save-model election (go/master/service.go:467-495 RequestSaveModel):
// returns 1 if `trainer_id` should save (it becomes the saving trainer
// for `block_seconds`), 0 if another trainer holds the slot, -1 on empty
// trainer id.
int pt_master_request_save(Master* m, const char* trainer_id,
                           double block_seconds) {
  if (!trainer_id || !*trainer_id) return -1;
  std::lock_guard<std::mutex> l(m->mu);
  double t = now_s();
  bool need = m->saving_trainer.empty() || m->saving_deadline <= t ||
              m->saving_trainer == trainer_id;
  if (need) {
    m->saving_trainer = trainer_id;
    m->saving_deadline = t + block_seconds;
  }
  return need ? 1 : 0;
}

void pt_master_set_lease(Master* m, double lease_seconds) {
  std::lock_guard<std::mutex> l(m->mu);
  m->lease_seconds = lease_seconds;
}

// ---- snapshot / restore ----
// Pending tasks snapshot into todo (a restarted master re-issues them —
// same semantics as the Go master recovering from etcd).

int pt_master_snapshot(Master* m, const char* path) {
  std::lock_guard<std::mutex> l(m->mu);
  std::string buf;
  pt::put<uint32_t>(&buf, kSnapMagic);
  pt::put<uint32_t>(&buf, 1u);
  pt::put<int64_t>(&buf, m->next_id);
  pt::put<double>(&buf, m->lease_seconds);
  pt::put<int32_t>(&buf, m->failure_max);
  auto dump = [&buf](const auto& seq) {
    pt::put<uint32_t>(&buf, static_cast<uint32_t>(seq.size()));
    for (const auto& t : seq) put_task(&buf, t);
  };
  // todo + pending together: a pending lease does not survive restart
  pt::put<uint32_t>(&buf,
                    static_cast<uint32_t>(m->todo.size() + m->pending.size()));
  for (const auto& t : m->todo) put_task(&buf, t);
  for (const auto& kv : m->pending) put_task(&buf, kv.second.first);
  dump(m->done);
  dump(m->discarded);
  pt::put<uint32_t>(&buf, pt::crc32(buf.data(), buf.size()));
  std::string tmp = std::string(path) + ".tmp";
  FILE* f = fopen(tmp.c_str(), "wb");
  if (!f) return -1;
  bool ok = fwrite(buf.data(), 1, buf.size(), f) == buf.size();
  ok = (fclose(f) == 0) && ok;
  if (!ok) return -1;
  return rename(tmp.c_str(), path) == 0 ? 0 : -1;
}

Master* pt_master_restore(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  std::string buf;
  char tmp[1 << 16];
  size_t got;
  while ((got = fread(tmp, 1, sizeof(tmp), f)) > 0) buf.append(tmp, got);
  fclose(f);
  if (buf.size() < 8) return nullptr;
  uint32_t crc_stored;
  std::memcpy(&crc_stored, buf.data() + buf.size() - 4, 4);
  if (pt::crc32(buf.data(), buf.size() - 4) != crc_stored) return nullptr;
  const char* p = buf.data();
  const char* end = buf.data() + buf.size() - 4;
  uint32_t magic, version;
  if (!pt::get(&p, end, &magic) || magic != kSnapMagic) return nullptr;
  if (!pt::get(&p, end, &version) || version != 1) return nullptr;
  auto* m = new Master();
  int32_t fmax;
  if (!pt::get(&p, end, &m->next_id) ||
      !pt::get(&p, end, &m->lease_seconds) || !pt::get(&p, end, &fmax)) {
    delete m;
    return nullptr;
  }
  m->failure_max = fmax;
  auto load = [&p, end](auto* out) -> bool {
    uint32_t n;
    if (!pt::get(&p, end, &n)) return false;
    for (uint32_t i = 0; i < n; i++) {
      Task t;
      if (!get_task(&p, end, &t)) return false;
      out->push_back(std::move(t));
    }
    return true;
  };
  if (!load(&m->todo) || !load(&m->done) || !load(&m->discarded)) {
    delete m;
    return nullptr;
  }
  return m;
}

}  // extern "C"
