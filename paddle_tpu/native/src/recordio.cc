// Chunked record file format + asynchronous double-buffered reader.
//
// Capability parity with two reference subsystems, rebuilt TPU-native:
// - the RecordIO chunk files the Go master dispatches as tasks
//   (go/master/service.go:89,280 partitions datasets by chunk), and
// - the async double-buffered data pipeline of
//   gserver/dataproviders/DataProvider.h:249 (DoubleBuffer prefetch
//   thread hiding host IO behind device compute).
//
// Format: file = sequence of chunks.
//   chunk header: magic u32 "PTRC" | num_records u32 | payload_len u32 |
//                 crc32(payload) u32
//   payload: per record varint-free u32 length + bytes.
// Readers can seek chunk-by-chunk (header carries payload_len), enabling
// sharded reads (every k-th chunk) and task-queue dispatch by
// (path, chunk_index) without a central index file.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "common.h"

namespace {

constexpr uint32_t kChunkMagic = 0x50545243;  // "PTRC"

struct Writer {
  FILE* f = nullptr;
  std::string payload;
  uint32_t num_records = 0;
  int64_t max_chunk_bytes = 1 << 20;

  int flush_chunk() {
    if (num_records == 0) return 0;
    std::string hdr;
    pt::put<uint32_t>(&hdr, kChunkMagic);
    pt::put<uint32_t>(&hdr, num_records);
    pt::put<uint32_t>(&hdr, static_cast<uint32_t>(payload.size()));
    pt::put<uint32_t>(&hdr, pt::crc32(payload.data(), payload.size()));
    if (fwrite(hdr.data(), 1, hdr.size(), f) != hdr.size()) return -1;
    if (fwrite(payload.data(), 1, payload.size(), f) != payload.size())
      return -1;
    payload.clear();
    num_records = 0;
    return 0;
  }
};

// pt_recordio_next/peek_len sentinels (length >= 0 means a record, so an
// empty record is representable and does not terminate iteration)
constexpr int64_t kTooSmall = -1;
constexpr int64_t kReadError = -2;
constexpr int64_t kEof = -3;

struct Reader {
  // (path, chunk stride/offset) sharding
  std::vector<std::string> paths;
  int start_chunk = 0, step_chunk = 1;
  // bounded prefetch queue of decoded records
  std::deque<std::string> queue;
  size_t max_queued = 4096;
  std::mutex mu;
  std::condition_variable cv_can_push, cv_can_pop;
  std::thread worker;
  std::atomic<bool> done{false}, stop{false};
  std::string error;

  void run() {
    int64_t global_chunk = 0;
    for (const auto& path : paths) {
      FILE* f = fopen(path.c_str(), "rb");
      if (!f) {
        std::lock_guard<std::mutex> l(mu);
        error = "open failed: " + path;
        break;
      }
      // file size, to catch fseek-past-EOF on skipped chunks
      fseek(f, 0, SEEK_END);
      long fsize = ftell(f);
      fseek(f, 0, SEEK_SET);
      while (!stop.load()) {
        char hdr[16];
        size_t got = fread(hdr, 1, 16, f);
        if (got == 0) break;  // clean EOF
        if (got != 16) {
          std::lock_guard<std::mutex> l(mu);
          error = "truncated chunk header: " + path;
          break;
        }
        uint32_t magic, nrec, plen, crc;
        std::memcpy(&magic, hdr, 4);
        std::memcpy(&nrec, hdr + 4, 4);
        std::memcpy(&plen, hdr + 8, 4);
        std::memcpy(&crc, hdr + 12, 4);
        if (magic != kChunkMagic) {
          std::lock_guard<std::mutex> l(mu);
          error = "bad chunk magic: " + path;
          break;
        }
        bool mine = (global_chunk - start_chunk) % step_chunk == 0 &&
                    global_chunk >= start_chunk;
        global_chunk++;
        if (!mine) {  // skip payload without decoding
          // fseek past EOF "succeeds" on regular files — validate the
          // target so a truncated tail is an error for every shard, not
          // just the one that owns the chunk
          if (fseek(f, plen, SEEK_CUR) != 0 || ftell(f) > fsize) {
            std::lock_guard<std::mutex> l(mu);
            error = "truncated chunk payload (skipped): " + path;
            break;
          }
          continue;
        }
        std::string payload(plen, '\0');
        if (fread(payload.data(), 1, plen, f) != plen) {
          std::lock_guard<std::mutex> l(mu);
          error = "truncated chunk payload: " + path;
          break;
        }
        if (pt::crc32(payload.data(), payload.size()) != crc) {
          std::lock_guard<std::mutex> l(mu);
          error = "chunk crc mismatch: " + path;
          break;
        }
        const char* p = payload.data();
        const char* end = p + payload.size();
        for (uint32_t i = 0; i < nrec && !stop.load(); i++) {
          uint32_t rlen;
          if (!pt::get(&p, end, &rlen) ||
              end - p < static_cast<ptrdiff_t>(rlen)) {
            std::lock_guard<std::mutex> l(mu);
            error = "corrupt record in: " + path;
            break;
          }
          std::unique_lock<std::mutex> l(mu);
          cv_can_push.wait(
              l, [&] { return queue.size() < max_queued || stop.load(); });
          if (stop.load()) break;
          queue.emplace_back(p, rlen);
          p += rlen;
          cv_can_pop.notify_one();
        }
        {
          std::lock_guard<std::mutex> l(mu);
          if (!error.empty()) break;
        }
      }
      fclose(f);
      {
        std::lock_guard<std::mutex> l(mu);
        if (!error.empty()) break;
      }
      if (stop.load()) break;
    }
    {
      // set under mu: a consumer that just evaluated its wait predicate
      // (queue empty, done false) must not be able to block after this
      // store without seeing the notify (lost-wakeup)
      std::lock_guard<std::mutex> l(mu);
      done.store(true);
    }
    cv_can_pop.notify_all();
  }
};

}  // namespace

extern "C" {

// ---------------- writer ----------------
Writer* pt_recordio_writer_open(const char* path, int64_t max_chunk_bytes) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  auto* w = new Writer();
  w->f = f;
  if (max_chunk_bytes > 0) w->max_chunk_bytes = max_chunk_bytes;
  return w;
}

int pt_recordio_write(Writer* w, const char* data, int64_t len) {
  pt::put<uint32_t>(&w->payload, static_cast<uint32_t>(len));
  w->payload.append(data, static_cast<size_t>(len));
  w->num_records++;
  if (static_cast<int64_t>(w->payload.size()) >= w->max_chunk_bytes)
    return w->flush_chunk();
  return 0;
}

int pt_recordio_writer_close(Writer* w) {
  int rc = w->flush_chunk();
  fclose(w->f);
  delete w;
  return rc;
}

// ---------------- reader ----------------
Reader* pt_recordio_reader_open(const char** paths, int n_paths,
                                int start_chunk, int step_chunk,
                                int max_queued) {
  auto* r = new Reader();
  for (int i = 0; i < n_paths; i++) r->paths.emplace_back(paths[i]);
  r->start_chunk = start_chunk;
  r->step_chunk = step_chunk > 0 ? step_chunk : 1;
  if (max_queued > 0) r->max_queued = max_queued;
  r->worker = std::thread([r] { r->run(); });
  return r;
}

// Returns record length (>= 0, empty records are valid); -3 = end of
// data; -1 = caller buffer too small (call again with >=
// pt_recordio_peek_len bytes); -2 = read error.
int64_t pt_recordio_next(Reader* r, char* buf, int64_t cap) {
  std::unique_lock<std::mutex> l(r->mu);
  r->cv_can_pop.wait(l, [&] { return !r->queue.empty() || r->done.load(); });
  if (r->queue.empty()) return r->error.empty() ? kEof : kReadError;
  const std::string& rec = r->queue.front();
  if (static_cast<int64_t>(rec.size()) > cap) return kTooSmall;
  int64_t n = static_cast<int64_t>(rec.size());
  std::memcpy(buf, rec.data(), rec.size());
  r->queue.pop_front();
  r->cv_can_push.notify_one();
  return n;
}

int64_t pt_recordio_peek_len(Reader* r) {
  std::unique_lock<std::mutex> l(r->mu);
  r->cv_can_pop.wait(l, [&] { return !r->queue.empty() || r->done.load(); });
  if (r->queue.empty()) return r->error.empty() ? kEof : kReadError;
  return static_cast<int64_t>(r->queue.front().size());
}

const char* pt_recordio_error(Reader* r) {
  std::lock_guard<std::mutex> l(r->mu);
  return r->error.empty() ? nullptr : r->error.c_str();
}

void pt_recordio_reader_close(Reader* r) {
  {
    // set under mu so the worker can't block on a full queue between
    // evaluating its wait predicate and this store (lost-wakeup)
    std::lock_guard<std::mutex> l(r->mu);
    r->stop.store(true);
  }
  r->cv_can_push.notify_all();
  r->cv_can_pop.notify_all();
  if (r->worker.joinable()) r->worker.join();
  delete r;
}

// Count chunks in a file by walking headers (for task partitioning).
int64_t pt_recordio_count_chunks(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  fseek(f, 0, SEEK_END);
  long fsize = ftell(f);
  fseek(f, 0, SEEK_SET);
  int64_t count = 0;
  for (;;) {
    char hdr[16];
    size_t got = fread(hdr, 1, 16, f);
    if (got == 0) break;
    if (got != 16) { count = -2; break; }
    uint32_t magic, plen;
    std::memcpy(&magic, hdr, 4);
    std::memcpy(&plen, hdr + 8, 4);
    if (magic != kChunkMagic) { count = -2; break; }
    // fseek past EOF "succeeds" on regular files — a truncated final
    // chunk must be a partition-time error, not N worker lease failures
    if (fseek(f, plen, SEEK_CUR) != 0 || ftell(f) > fsize) {
      count = -2;
      break;
    }
    count++;
  }
  fclose(f);
  return count;
}

}  // extern "C"
