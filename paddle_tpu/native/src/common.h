// Shared helpers for the native runtime: CRC32 (self-contained, no zlib
// dependency) and little-endian buffer IO.
//
// TPU-native counterpart of the reference's C++ runtime utilities
// (paddle/utils/, go/pserver checkpoint CRC — go/pserver/service.go:76).
#pragma once

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace pt {

inline uint32_t crc32(const void* data, size_t n, uint32_t crc = 0) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  const uint8_t* p = static_cast<const uint8_t*>(data);
  crc = ~crc;
  for (size_t i = 0; i < n; i++) crc = table[(crc ^ p[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

// Little-endian append/read of PODs into a byte buffer.
template <typename T>
inline void put(std::string* buf, T v) {
  buf->append(reinterpret_cast<const char*>(&v), sizeof(T));
}

template <typename T>
inline bool get(const char** p, const char* end, T* v) {
  if (end - *p < static_cast<ptrdiff_t>(sizeof(T))) return false;
  std::memcpy(v, *p, sizeof(T));
  *p += sizeof(T);
  return true;
}

}  // namespace pt
