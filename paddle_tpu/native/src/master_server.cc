// Networked elastic master: a TCP server over the task-queue master in
// master.cc, making the fault-tolerance capability available ACROSS
// processes and hosts — the counterpart of the reference's Go master RPC
// service (go/master/service.go:89-495; trainers connect via
// go/master/client.go / c/client.go). etcd is replaced by the snapshot
// file the serving host owns (periodic + on shutdown).
//
// Wire protocol (little-endian, length-prefixed):
//   request:  [u32 body_len][u8 op][body ...]
//   response: [u32 body_len][i64 status][body ...]
// Ops: 1 ADD_TASK(payload) -> status=task id
//      2 GET_TASK() -> status=payload len or -3 none; body=[i64 lease][payload]
//      3 TASK_DONE([i64 lease]) -> 0 / -1 lease lost
//      4 TASK_FAILED([i64 lease]) -> 0 / -1
//      5 PASS_FINISHED() -> 1 / 0
//      6 START_PASS() -> todo count
//      7 COUNT([i32 which]) -> count
//      8 SET_LEASE([f64 seconds]) -> 0
//      9 SNAPSHOT() -> 0 / -1 (uses the server's snapshot path)
//     10 REQUEST_SAVE([f64 block_s][trainer_id bytes]) -> 1 grant / 0 deny
//     11 PING() -> 0
//     12 SHUTDOWN() -> 0, then the server stops accepting and exits
//
// Threading: accept loop + thread per connection (a handful of trainer
// processes; the reference's Go side likewise serves net/rpc with a
// goroutine per conn). All master state is behind Master's own mutex.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

struct Master;  // opaque; we only use the extern "C" master API
extern "C" {
int64_t pt_master_add_task(Master*, const char*, int64_t);
int64_t pt_master_get_task(Master*, char*, int64_t, int64_t*);
int pt_master_task_done(Master*, int64_t);
int pt_master_task_failed(Master*, int64_t);
int pt_master_pass_finished(Master*);
int64_t pt_master_start_pass(Master*);
int64_t pt_master_count(Master*, int);
void pt_master_set_lease(Master*, double);
int pt_master_snapshot(Master*, const char*);
int pt_master_request_save(Master*, const char*, double);
}

namespace {

struct Server {
  Master* m = nullptr;
  int listen_fd = -1;
  int port = 0;
  std::string snapshot_path;
  double snapshot_every = 0.0;
  std::atomic<bool> stop{false};
  std::thread accept_thread;
  std::thread snapshot_thread;
  // live connection sockets: stop() shuts them down so their threads'
  // blocking recv returns, then waits for active_conns to drain before
  // the Server is freed (no use-after-free on s->m / snapshot_path)
  std::mutex conns_mu;
  std::set<int> conn_fds;
  std::atomic<int> active_conns{0};
  bool listen_closed = false;  // guarded by conns_mu
};

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t got = recv(fd, p, n, 0);
    if (got <= 0) return false;
    p += got;
    n -= static_cast<size_t>(got);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t put = send(fd, p, n, MSG_NOSIGNAL);
    if (put <= 0) return false;
    p += put;
    n -= static_cast<size_t>(put);
  }
  return true;
}

bool respond(int fd, int64_t status, const std::string& body) {
  uint32_t len = static_cast<uint32_t>(8 + body.size());
  std::string out;
  out.reserve(4 + len);
  out.append(reinterpret_cast<const char*>(&len), 4);
  out.append(reinterpret_cast<const char*>(&status), 8);
  out.append(body);
  return write_full(fd, out.data(), out.size());
}

template <typename T>
bool pop(const char** p, const char* end, T* v) {
  if (end - *p < static_cast<ptrdiff_t>(sizeof(T))) return false;
  std::memcpy(v, *p, sizeof(T));
  *p += sizeof(T);
  return true;
}

void handle_conn(Server* s, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  std::vector<char> task_buf(1 << 20);
  for (;;) {
    uint32_t len;
    if (!read_full(fd, &len, 4)) break;
    if (len < 1 || len > (64u << 20)) break;  // corrupt/hostile frame
    std::string req(len, '\0');
    if (!read_full(fd, req.data(), len)) break;
    uint8_t op = static_cast<uint8_t>(req[0]);
    const char* p = req.data() + 1;
    const char* end = req.data() + req.size();
    bool ok = true;
    switch (op) {
      case 1:  // ADD_TASK
        ok = respond(fd, pt_master_add_task(s->m, p, end - p), "");
        break;
      case 2: {  // GET_TASK
        int64_t lease = 0;
        int64_t n;
        for (;;) {
          n = pt_master_get_task(s->m, task_buf.data(),
                                 static_cast<int64_t>(task_buf.size()),
                                 &lease);
          if (n == -1) {  // buffer too small; lease holds required size
            task_buf.resize(static_cast<size_t>(lease));
            continue;
          }
          break;
        }
        if (n < 0) {
          ok = respond(fd, n, "");
        } else {
          std::string body(reinterpret_cast<const char*>(&lease), 8);
          body.append(task_buf.data(), static_cast<size_t>(n));
          ok = respond(fd, n, body);
        }
        break;
      }
      case 3:
      case 4: {
        int64_t lease;
        if (!pop(&p, end, &lease)) {
          ok = respond(fd, -2, "");
          break;
        }
        int r = op == 3 ? pt_master_task_done(s->m, lease)
                        : pt_master_task_failed(s->m, lease);
        ok = respond(fd, r, "");
        break;
      }
      case 5:
        ok = respond(fd, pt_master_pass_finished(s->m), "");
        break;
      case 6:
        ok = respond(fd, pt_master_start_pass(s->m), "");
        break;
      case 7: {
        int32_t which;
        if (!pop(&p, end, &which)) {
          ok = respond(fd, -2, "");
          break;
        }
        ok = respond(fd, pt_master_count(s->m, which), "");
        break;
      }
      case 8: {
        double secs;
        if (!pop(&p, end, &secs)) {
          ok = respond(fd, -2, "");
          break;
        }
        pt_master_set_lease(s->m, secs);
        ok = respond(fd, 0, "");
        break;
      }
      case 9:
        ok = respond(fd,
                     s->snapshot_path.empty()
                         ? -2
                         : pt_master_snapshot(s->m, s->snapshot_path.c_str()),
                     "");
        break;
      case 10: {  // REQUEST_SAVE
        double block_s;
        if (!pop(&p, end, &block_s)) {
          ok = respond(fd, -2, "");
          break;
        }
        std::string trainer(p, end - p);
        ok = respond(fd, pt_master_request_save(s->m, trainer.c_str(), block_s),
                     "");
        break;
      }
      case 11:
        ok = respond(fd, 0, "");
        break;
      case 12:
        respond(fd, 0, "");
        s->stop.store(true);
        // unblock the accept loop; conn_main closes this socket.
        // listen_fd shutdown is guarded so it cannot race stop()'s
        // close() onto a recycled descriptor
        {
          std::lock_guard<std::mutex> g(s->conns_mu);
          if (!s->listen_closed) shutdown(s->listen_fd, SHUT_RDWR);
        }
        return;
      default:
        ok = respond(fd, -100, "");
    }
    if (!ok) break;
  }
}

// registers the connection, runs handle_conn, deregisters — the unit
// the detached per-connection threads execute. The socket is closed
// here under the registry lock so stop() can never shutdown() a
// recycled descriptor.
void conn_main(Server* s, int fd) {
  {
    std::lock_guard<std::mutex> g(s->conns_mu);
    s->conn_fds.insert(fd);
    // stop() may have swept conn_fds between our accept and this
    // registration — shut the socket down ourselves so recv returns
    if (s->stop.load()) shutdown(fd, SHUT_RDWR);
  }
  handle_conn(s, fd);
  {
    std::lock_guard<std::mutex> g(s->conns_mu);
    s->conn_fds.erase(fd);
    close(fd);
  }
  s->active_conns.fetch_sub(1);
}

}  // namespace

extern "C" {

// Start serving `m` on `port` (0 = ephemeral). Returns a Server handle,
// or nullptr on bind failure. `snapshot_path` (nullable) enables the
// SNAPSHOT op and, with snapshot_every_s > 0, periodic snapshots.
// The caller keeps ownership of `m` and must not destroy it until after
// pt_master_server_stop.
Server* pt_master_server_start(Master* m, int port, const char* snapshot_path,
                               double snapshot_every_s) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 64) != 0) {
    close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);

  auto* s = new Server();
  s->m = m;
  s->listen_fd = fd;
  s->port = ntohs(addr.sin_port);
  if (snapshot_path) s->snapshot_path = snapshot_path;
  s->snapshot_every = snapshot_every_s;

  s->accept_thread = std::thread([s] {
    while (!s->stop.load()) {
      int cfd = accept(s->listen_fd, nullptr, nullptr);
      if (cfd < 0) {
        if (s->stop.load()) break;
        continue;
      }
      // detached but registered: stop() shuts the sockets down and
      // waits for the count to drain before freeing the Server
      s->active_conns.fetch_add(1);
      std::thread(conn_main, s, cfd).detach();
    }
  });
  if (!s->snapshot_path.empty() && snapshot_every_s > 0) {
    s->snapshot_thread = std::thread([s] {
      while (!s->stop.load()) {
        // sleep in 50 ms slices so stop is honored promptly
        for (double t = 0; t < s->snapshot_every && !s->stop.load();
             t += 0.05)
          std::this_thread::sleep_for(std::chrono::milliseconds(50));
        if (s->stop.load()) break;
        pt_master_snapshot(s->m, s->snapshot_path.c_str());
      }
    });
  }
  return s;
}

int pt_master_server_port(Server* s) { return s ? s->port : -1; }

int pt_master_server_stopped(Server* s) {
  return s && s->stop.load() ? 1 : 0;
}

// Stop accepting, join service threads, force open connections closed
// and wait for their threads to drain, snapshot one last time if
// configured. If a connection thread is wedged past the drain timeout
// the Server is intentionally leaked instead of freed under it.
void pt_master_server_stop(Server* s) {
  if (!s) return;
  s->stop.store(true);
  {
    std::lock_guard<std::mutex> g(s->conns_mu);
    shutdown(s->listen_fd, SHUT_RDWR);
    close(s->listen_fd);
    s->listen_closed = true;
  }
  if (s->accept_thread.joinable()) s->accept_thread.join();
  if (s->snapshot_thread.joinable()) s->snapshot_thread.join();
  {
    // unblock every connection thread's recv
    std::lock_guard<std::mutex> g(s->conns_mu);
    for (int fd : s->conn_fds) shutdown(fd, SHUT_RDWR);
  }
  for (int waited_ms = 0;
       s->active_conns.load() > 0 && waited_ms < 5000; waited_ms += 10)
    std::this_thread::sleep_for(std::chrono::milliseconds(10));
  if (!s->snapshot_path.empty())
    pt_master_snapshot(s->m, s->snapshot_path.c_str());
  if (s->active_conns.load() > 0) return;  // leak rather than UAF
  delete s;
}

}  // extern "C"
