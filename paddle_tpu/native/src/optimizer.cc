// Standalone C-ABI optimizer library.
//
// Capability parity with the reference's paddle/optimizer/ (its C-linkage
// optimizer built for the Go pserver via cgo — optimizer/optimizer.h,
// optimizer/parameter_optimizer.cc, optimizer/serialization.h): dense
// SGD/momentum/adagrad/adadelta/rmsprop/adam with learning-rate policies
// (const / t_inv / poly) and binary state (de)serialization with CRC.
// Rebuilt from the update equations, not the reference code; the hot
// TPU path applies optimizers on-device (paddle_tpu/optimizers/), this
// library serves the host-side runtime: checkpoint-portable optimizer
// state and host-resident (e.g. CPU-offloaded embedding) updates.

#include <cmath>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include "common.h"

namespace {

enum Method { SGD, MOMENTUM, ADAGRAD, ADADELTA, RMSPROP, ADAM };
enum LrPolicy { LR_CONST, LR_T_INV, LR_POLY };

struct Optimizer {
  Method method = SGD;
  LrPolicy lr_policy = LR_CONST;
  double lr = 0.01, momentum = 0.0, eps = 1e-6, rho = 0.95;
  double beta1 = 0.9, beta2 = 0.999, decay = 0.0;
  // lr policy params: t_inv: lr/(1+a*t); poly: lr*(1+a*t)^(-b)
  double lr_a = 0.0, lr_b = 0.0;
  int64_t n = 0;
  std::vector<float> buf1, buf2;  // method-dependent state slots

  double lr_at(int64_t step) const {
    switch (lr_policy) {
      case LR_T_INV: return lr / (1.0 + lr_a * step);
      case LR_POLY: return lr * std::pow(1.0 + lr_a * step, -lr_b);
      default: return lr;
    }
  }
};

}  // namespace

extern "C" {

Optimizer* pt_optimizer_create(const char* method, int64_t n, double lr,
                               double momentum, double eps, double rho,
                               double beta1, double beta2, double decay,
                               const char* lr_policy, double lr_a,
                               double lr_b) {
  auto* o = new Optimizer();
  std::string m = method ? method : "sgd";
  if (m == "sgd") o->method = SGD;
  else if (m == "momentum") o->method = MOMENTUM;
  else if (m == "adagrad") o->method = ADAGRAD;
  else if (m == "adadelta") o->method = ADADELTA;
  else if (m == "rmsprop") o->method = RMSPROP;
  else if (m == "adam") o->method = ADAM;
  else { delete o; return nullptr; }
  std::string p = lr_policy ? lr_policy : "const";
  if (p == "const") o->lr_policy = LR_CONST;
  else if (p == "t_inv") o->lr_policy = LR_T_INV;
  else if (p == "poly") o->lr_policy = LR_POLY;
  else { delete o; return nullptr; }
  o->n = n;
  o->lr = lr; o->momentum = momentum; o->eps = eps; o->rho = rho;
  o->beta1 = beta1; o->beta2 = beta2; o->decay = decay;
  o->lr_a = lr_a; o->lr_b = lr_b;
  switch (o->method) {
    case SGD: break;
    case MOMENTUM: case ADAGRAD: o->buf1.assign(n, 0.f); break;
    case ADADELTA: case RMSPROP: case ADAM:
      o->buf1.assign(n, 0.f); o->buf2.assign(n, 0.f); break;
  }
  return o;
}

void pt_optimizer_destroy(Optimizer* o) { delete o; }

// In-place parameter update; step is the 0-based update count.
void pt_optimizer_update(Optimizer* o, float* param, const float* grad,
                         int64_t n, int64_t step) {
  if (n != o->n) return;
  const double lr = o->lr_at(step);
  switch (o->method) {
    case SGD:
      for (int64_t i = 0; i < n; i++) {
        double g = grad[i] + o->decay * param[i];
        param[i] = static_cast<float>(param[i] - lr * g);
      }
      break;
    case MOMENTUM:
      for (int64_t i = 0; i < n; i++) {
        double g = grad[i] + o->decay * param[i];
        double v = o->momentum * o->buf1[i] - lr * g;
        o->buf1[i] = static_cast<float>(v);
        param[i] = static_cast<float>(param[i] + v);
      }
      break;
    case ADAGRAD:
      for (int64_t i = 0; i < n; i++) {
        double g = grad[i] + o->decay * param[i];
        double a = o->buf1[i] + g * g;
        o->buf1[i] = static_cast<float>(a);
        param[i] = static_cast<float>(param[i] - lr * g / (std::sqrt(a) + o->eps));
      }
      break;
    case ADADELTA:
      for (int64_t i = 0; i < n; i++) {
        double g = grad[i] + o->decay * param[i];
        double acc = o->rho * o->buf1[i] + (1 - o->rho) * g * g;
        double dx = -std::sqrt((o->buf2[i] + o->eps) / (acc + o->eps)) * g;
        o->buf2[i] = static_cast<float>(o->rho * o->buf2[i] + (1 - o->rho) * dx * dx);
        o->buf1[i] = static_cast<float>(acc);
        param[i] = static_cast<float>(param[i] + lr * dx);
      }
      break;
    case RMSPROP:
      // centered variant (tracks E[g] too), matching the reference's
      // rmspropApply (math/TrainingAlgorithmOp.h)
      for (int64_t i = 0; i < n; i++) {
        double g = grad[i] + o->decay * param[i];
        double g2 = o->rho * o->buf1[i] + (1 - o->rho) * g * g;
        double g1 = o->rho * o->buf2[i] + (1 - o->rho) * g;
        o->buf1[i] = static_cast<float>(g2);
        o->buf2[i] = static_cast<float>(g1);
        param[i] = static_cast<float>(
            param[i] - lr * g / std::sqrt(g2 - g1 * g1 + o->eps));
      }
      break;
    case ADAM: {
      double t = static_cast<double>(step) + 1.0;
      double bc1 = 1.0 - std::pow(o->beta1, t);
      double bc2 = 1.0 - std::pow(o->beta2, t);
      for (int64_t i = 0; i < n; i++) {
        double g = grad[i] + o->decay * param[i];
        double m = o->beta1 * o->buf1[i] + (1 - o->beta1) * g;
        double v = o->beta2 * o->buf2[i] + (1 - o->beta2) * g * g;
        o->buf1[i] = static_cast<float>(m);
        o->buf2[i] = static_cast<float>(v);
        double mh = m / bc1, vh = v / bc2;
        param[i] = static_cast<float>(param[i] - lr * mh / (std::sqrt(vh) + o->eps));
      }
      break;
    }
  }
}

// ---- state serialization (CRC-protected, versioned) ----
// layout: magic u32 | version u32 | method u32 | n i64 | buf1 | buf2 | crc u32

static const uint32_t kMagic = 0x50544f50;  // "PTOP"

int64_t pt_optimizer_state_size(Optimizer* o) {
  return static_cast<int64_t>(4 + 4 + 4 + 8 +
                              (o->buf1.size() + o->buf2.size()) * 4 + 4);
}

int64_t pt_optimizer_get_state(Optimizer* o, char* out, int64_t cap) {
  std::string buf;
  pt::put<uint32_t>(&buf, kMagic);
  pt::put<uint32_t>(&buf, 1u);
  pt::put<uint32_t>(&buf, static_cast<uint32_t>(o->method));
  pt::put<int64_t>(&buf, o->n);
  buf.append(reinterpret_cast<const char*>(o->buf1.data()), o->buf1.size() * 4);
  buf.append(reinterpret_cast<const char*>(o->buf2.data()), o->buf2.size() * 4);
  pt::put<uint32_t>(&buf, pt::crc32(buf.data(), buf.size()));
  if (static_cast<int64_t>(buf.size()) > cap) return -1;
  std::memcpy(out, buf.data(), buf.size());
  return static_cast<int64_t>(buf.size());
}

int pt_optimizer_set_state(Optimizer* o, const char* data, int64_t len) {
  if (len < 24) return -1;
  uint32_t crc_stored;
  std::memcpy(&crc_stored, data + len - 4, 4);
  if (pt::crc32(data, len - 4) != crc_stored) return -2;
  const char* p = data;
  const char* end = data + len - 4;
  uint32_t magic, version, method;
  int64_t n;
  if (!pt::get(&p, end, &magic) || magic != kMagic) return -3;
  if (!pt::get(&p, end, &version) || version != 1) return -4;
  if (!pt::get(&p, end, &method) || method != static_cast<uint32_t>(o->method))
    return -5;
  if (!pt::get(&p, end, &n) || n != o->n) return -6;
  size_t want = (o->buf1.size() + o->buf2.size()) * 4;
  if (static_cast<size_t>(end - p) != want) return -7;
  std::memcpy(o->buf1.data(), p, o->buf1.size() * 4);
  std::memcpy(o->buf2.data(), p + o->buf1.size() * 4, o->buf2.size() * 4);
  return 0;
}

}  // extern "C"
