/* C inference ABI for paddle_tpu — public header.
 *
 * Capability match for the reference C API (paddle/capi/capi.h):
 *   - dense float and integer-id inputs (capi/matrix.h, vector.h)
 *   - ragged sequence inputs via start positions
 *     (capi/arguments.h paddle_arguments_set_sequence_start_pos),
 *     including one nested level (sub-sequences)
 *   - sparse-binary / sparse-float CSR inputs
 *     (capi/matrix.h paddle_matrix_create_sparse +
 *     paddle_matrix_sparse_copy_from)
 *
 * The library embeds CPython; link nothing but -ldl and dlopen
 * libpaddle_tpu_capi.so, or link against it directly. All functions are
 * thread-safe: any thread may call pt_capi_forward* concurrently after
 * pt_capi_init (calls serialize on the embedded interpreter).
 */
#ifndef PT_CAPI_H
#define PT_CAPI_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* Slot kinds for pt_capi_forward_slots. */
enum {
  PT_SLOT_DENSE = 0,      /* float32 row-major, `shape` dims           */
  PT_SLOT_IDS = 1,        /* int32, `shape` dims                       */
  PT_SLOT_SEQ_IDS = 2,    /* ragged int32 ids + seq start positions    */
  PT_SLOT_SEQ_DENSE = 3,  /* ragged float32 rows + seq start positions */
  PT_SLOT_SPARSE_BINARY = 4, /* CSR, implicit 1.0 values               */
  PT_SLOT_SPARSE_FLOAT = 5   /* CSR with explicit float values         */
};

typedef struct {
  const char* name; /* data layer name */
  int kind;         /* PT_SLOT_* */

  /* PT_SLOT_DENSE / PT_SLOT_IDS: buf + shape/ndims.
   * PT_SLOT_SEQ_IDS: buf = int32[seq_pos[n_seq-1]] flat token ids.
   * PT_SLOT_SEQ_DENSE: buf = float32[seq_pos[n_seq-1] * width]. */
  const void* buf;
  const int64_t* shape;
  int ndims;

  /* Sequence slots: start positions, length n_seq (= #sequences + 1),
   * first 0, last = total timesteps — exactly the reference's
   * sequenceStartPositions. Optional `subseq_pos` adds the nested
   * level (arguments.h nestedLevel=1): positions into the same flat
   * timestep axis, refining seq_pos. */
  const int32_t* seq_pos;
  int n_seq;
  const int32_t* subseq_pos;
  int n_subseq;
  int64_t width; /* per-timestep feature width (PT_SLOT_SEQ_DENSE) */

  /* Sparse slots: CSR over [height, width]; rows has height+1 entries,
   * cols has nnz entries, vals is NULL for PT_SLOT_SPARSE_BINARY. */
  const int32_t* rows;
  const int32_t* cols;
  const float* vals;
  int64_t height;
  int64_t nnz;
} pt_capi_slot;

/* Initialize the runtime; `repo_path` (nullable) is prepended to
 * sys.path so `import paddle_tpu` resolves. Returns 0 on success. */
int pt_capi_init(const char* repo_path);

/* Load a merged model; returns handle > 0, or 0 on error. */
int64_t pt_capi_create(const char* merged_path, const char* output_layer);

/* Per-example output width of the first output layer, or -1. */
int64_t pt_capi_output_dim(int64_t handle);

/* Dense-only forward (original ABI, kept stable). */
int pt_capi_forward(int64_t handle, const char** names, const void** bufs,
                    const int64_t** shapes, const int* ndims,
                    const int* is_ids, int n_inputs, float* out_buf,
                    int64_t out_cap, int64_t* out_shape);

/* Full-surface forward: sequence + sparse slots. Writes the first
 * output layer's value into out_buf (float32, row-major, capacity
 * out_cap floats) and its dims into out_shape (capacity 8); returns
 * the output rank, or -1 (see pt_capi_error). */
int pt_capi_forward_slots(int64_t handle, const pt_capi_slot* slots,
                          int n_slots, float* out_buf, int64_t out_cap,
                          int64_t* out_shape);

void pt_capi_destroy(int64_t handle);

/* Last error on this thread's view of the runtime (thread-safe). */
const char* pt_capi_error(void);

#ifdef __cplusplus
}
#endif

#endif /* PT_CAPI_H */
