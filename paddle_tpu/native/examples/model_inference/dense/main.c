/* Dense inference over the C ABI.
 *
 * Counterpart of reference capi/examples/model_inference/dense/main.c:
 * feed one dense float batch, print the output row-major.
 *
 * usage: main LIBPATH REPOPATH MERGED_MODEL OUTPUT_LAYER
 */
#include "../common/common.h"

int main(int argc, char** argv) {
  CHECK(argc == 5);
  pt_api pt = pt_load(argv[1]);
  if (pt.init(argv[2]) != 0) {
    fprintf(stderr, "init: %s\n", pt.error());
    return 3;
  }
  int64_t h = pt.create(argv[3], argv[4]);
  if (!h) {
    fprintf(stderr, "create: %s\n", pt.error());
    return 4;
  }

  float in[16];
  for (int i = 0; i < 16; ++i) in[i] = (float)i / 16.0f;
  int64_t shape[] = {2, 8};

  pt_capi_slot s = pt_slot("x", PT_SLOT_DENSE);
  s.buf = in;
  s.shape = shape;
  s.ndims = 2;

  float out[64];
  int64_t oshape[8];
  int rank = pt.forward_slots(h, &s, 1, out, 64, oshape);
  if (rank < 0) {
    fprintf(stderr, "forward: %s\n", pt.error());
    return 5;
  }
  pt_print_output(out, oshape, rank);
  pt.destroy(h);
  return 0;
}
