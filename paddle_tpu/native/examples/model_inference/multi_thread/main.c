/* Multi-threaded serving over the C ABI: one model handle shared by
 * several threads, each running its own forwards concurrently — the
 * reference's multi_thread example
 * (capi/examples/model_inference/multi_thread/main.c). The embedded
 * interpreter serializes marshaling; each call's buffers are
 * thread-local so no external locking is needed.
 *
 * Every thread feeds a batch derived from its thread id and checks it
 * gets the same result each iteration (catches cross-thread mixups).
 *
 * usage: main LIBPATH REPOPATH MERGED_MODEL OUTPUT_LAYER
 */
#include <pthread.h>
#include <signal.h>
#include <string.h>

#include "../common/common.h"

#define NUM_THREAD 4
#define NUM_ITER 5

static pt_api pt;
static int64_t g_h;
/* written from worker threads, read after join — keep the flag atomic
 * so the template users copy for threaded serving is race-free */
static volatile sig_atomic_t g_failed = 0;

static void* thread_main(void* arg) {
  long tid = (long)arg;
  float in[16];
  for (int i = 0; i < 16; ++i) in[i] = (float)((i + tid) % 16) / 16.0f;
  int64_t shape[] = {2, 8};

  pt_capi_slot s = pt_slot("x", PT_SLOT_DENSE);
  s.buf = in;
  s.shape = shape;
  s.ndims = 2;

  float first[64], out[64];
  int64_t oshape[8];
  for (int iter = 0; iter < NUM_ITER; ++iter) {
    int rank = pt.forward_slots(g_h, &s, 1, out, 64, oshape);
    if (rank < 0) {
      fprintf(stderr, "thread %ld: forward: %s\n", tid, pt.error());
      g_failed = 1;
      return 0;
    }
    int64_t n = 1;
    for (int d = 0; d < rank; ++d) n *= oshape[d];
    if (iter == 0) {
      memcpy(first, out, n * sizeof(float));
    } else if (memcmp(first, out, n * sizeof(float)) != 0) {
      fprintf(stderr, "thread %ld: result changed across iterations\n",
              tid);
      g_failed = 1;
      return 0;
    }
  }
  return 0;
}

int main(int argc, char** argv) {
  CHECK(argc == 5);
  pt = pt_load(argv[1]);
  if (pt.init(argv[2]) != 0) {
    fprintf(stderr, "init: %s\n", pt.error());
    return 3;
  }
  g_h = pt.create(argv[3], argv[4]);
  if (!g_h) {
    fprintf(stderr, "create: %s\n", pt.error());
    return 4;
  }
  pthread_t threads[NUM_THREAD];
  for (long i = 0; i < NUM_THREAD; ++i)
    pthread_create(&threads[i], 0, thread_main, (void*)i);
  for (int i = 0; i < NUM_THREAD; ++i) pthread_join(threads[i], 0);
  pt.destroy(g_h);
  if (g_failed) return 5;
  printf("OK\n");
  return 0;
}
