/* Sparse-binary inference over the C ABI: a CSR multi-hot row, the
 * reference's sparse example surface
 * (capi/examples/model_inference/sparse_binary/main.c,
 * capi/matrix.h paddle_matrix_create_sparse +
 * paddle_matrix_sparse_copy_from with NULL values).
 *
 * usage: main LIBPATH REPOPATH MERGED_MODEL OUTPUT_LAYER WIDTH
 */
#include "../common/common.h"

int main(int argc, char** argv) {
  CHECK(argc == 6);
  pt_api pt = pt_load(argv[1]);
  if (pt.init(argv[2]) != 0) {
    fprintf(stderr, "init: %s\n", pt.error());
    return 3;
  }
  int64_t h = pt.create(argv[3], argv[4]);
  if (!h) {
    fprintf(stderr, "create: %s\n", pt.error());
    return 4;
  }

  /* batch of 2 rows; row 0 has features {1, 3}, row 1 has {0, 5, 6} */
  int32_t rows[] = {0, 2, 5};
  int32_t cols[] = {1, 3, 0, 5, 6};

  pt_capi_slot s = pt_slot("x", PT_SLOT_SPARSE_BINARY);
  s.rows = rows;
  s.cols = cols;
  s.height = 2;
  s.width = atoll(argv[5]);
  s.nnz = 5;

  float out[64];
  int64_t oshape[8];
  int rank = pt.forward_slots(h, &s, 1, out, 64, oshape);
  if (rank < 0) {
    fprintf(stderr, "forward: %s\n", pt.error());
    return 5;
  }
  pt_print_output(out, oshape, rank);
  pt.destroy(h);
  return 0;
}
