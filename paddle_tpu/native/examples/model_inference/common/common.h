/* Shared helpers for the model_inference examples.
 *
 * Counterpart of the reference's examples/model_inference/common/
 * common.h (the CHECK macro around paddle_error). Here the library is
 * dlopen-ed so the examples build with nothing but -ldl -lpthread; a
 * serving process may equally link libpaddle_tpu_capi.so directly.
 */
#ifndef PT_EXAMPLES_COMMON_H
#define PT_EXAMPLES_COMMON_H

#include <dlfcn.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

#include "../../../include/pt_capi.h"

typedef struct {
  void* lib;
  int (*init)(const char*);
  int64_t (*create)(const char*, const char*);
  int64_t (*output_dim)(int64_t);
  int (*forward)(int64_t, const char**, const void**, const int64_t**,
                 const int*, const int*, int, float*, int64_t, int64_t*);
  int (*forward_slots)(int64_t, const pt_capi_slot*, int, float*, int64_t,
                       int64_t*);
  void (*destroy)(int64_t);
  const char* (*error)(void);
} pt_api;

#define CHECK(stmt)                                                    \
  do {                                                                 \
    if (!(stmt)) {                                                     \
      fprintf(stderr, "%s:%d: check failed: %s\n", __FILE__, __LINE__, \
              #stmt);                                                  \
      exit(1);                                                         \
    }                                                                  \
  } while (0)

static pt_api pt_load(const char* libpath) {
  pt_api a;
  a.lib = dlopen(libpath, RTLD_NOW | RTLD_GLOBAL);
  if (!a.lib) {
    fprintf(stderr, "dlopen %s: %s\n", libpath, dlerror());
    exit(2);
  }
  a.init = (int (*)(const char*))dlsym(a.lib, "pt_capi_init");
  a.create = (int64_t(*)(const char*, const char*))dlsym(a.lib,
                                                         "pt_capi_create");
  a.output_dim = (int64_t(*)(int64_t))dlsym(a.lib, "pt_capi_output_dim");
  a.forward = (int (*)(int64_t, const char**, const void**,
                       const int64_t**, const int*, const int*, int,
                       float*, int64_t, int64_t*))
      dlsym(a.lib, "pt_capi_forward");
  a.forward_slots =
      (int (*)(int64_t, const pt_capi_slot*, int, float*, int64_t,
               int64_t*))dlsym(a.lib, "pt_capi_forward_slots");
  a.destroy = (void (*)(int64_t))dlsym(a.lib, "pt_capi_destroy");
  a.error = (const char* (*)(void))dlsym(a.lib, "pt_capi_error");
  CHECK(a.init && a.create && a.forward && a.forward_slots && a.destroy &&
        a.error);
  return a;
}

static void pt_print_output(const float* buf, const int64_t* shape,
                            int rank) {
  int64_t n = 1;
  for (int d = 0; d < rank; ++d) n *= shape[d];
  for (int64_t i = 0; i < n; ++i) printf("%.6f\n", buf[i]);
}

/* zero-initialized slot (every example fills only what it needs) */
static pt_capi_slot pt_slot(const char* name, int kind) {
  pt_capi_slot s;
  s.name = name;
  s.kind = kind;
  s.buf = 0;
  s.shape = 0;
  s.ndims = 0;
  s.seq_pos = 0;
  s.n_seq = 0;
  s.subseq_pos = 0;
  s.n_subseq = 0;
  s.width = 0;
  s.rows = 0;
  s.cols = 0;
  s.vals = 0;
  s.height = 0;
  s.nnz = 0;
  return s;
}

#endif /* PT_EXAMPLES_COMMON_H */
