/* Sequence inference over the C ABI: ragged integer-id input described
 * by start positions, exactly the reference's sequence example surface
 * (capi/examples/model_inference/sequence/main.c,
 * capi/arguments.h paddle_arguments_set_sequence_start_pos).
 *
 * Two sentences of different lengths in one batch: ids are flat, the
 * start-position vector {0, 5, 9} says tokens [0,5) are sentence 0 and
 * [5,9) are sentence 1.
 *
 * usage: main LIBPATH REPOPATH MERGED_MODEL OUTPUT_LAYER
 */
#include "../common/common.h"

int main(int argc, char** argv) {
  CHECK(argc == 5);
  pt_api pt = pt_load(argv[1]);
  if (pt.init(argv[2]) != 0) {
    fprintf(stderr, "init: %s\n", pt.error());
    return 3;
  }
  int64_t h = pt.create(argv[3], argv[4]);
  if (!h) {
    fprintf(stderr, "create: %s\n", pt.error());
    return 4;
  }

  int32_t word_ids[] = {13, 8, 2, 14, 9, 7, 3, 14, 5};
  int32_t seq_pos[] = {0, 5, 9};

  pt_capi_slot s = pt_slot("words", PT_SLOT_SEQ_IDS);
  s.buf = word_ids;
  s.seq_pos = seq_pos;
  s.n_seq = 3;

  float out[64];
  int64_t oshape[8];
  int rank = pt.forward_slots(h, &s, 1, out, 64, oshape);
  if (rank < 0) {
    fprintf(stderr, "forward: %s\n", pt.error());
    return 5;
  }
  pt_print_output(out, oshape, rank);
  pt.destroy(h);
  return 0;
}
