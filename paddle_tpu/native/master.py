"""ctypes wrapper for the elastic task-queue master.

Fault-tolerant input dispatch (go/master/service.go capability): chunk
tasks leased to workers, timeout requeue, failure cap, pass rotation,
snapshot/restore. Typical use: the coordinator host owns a Master over
(file, chunk) tasks; trainer processes lease tasks, read those chunks via
RecordReader(start_chunk=..., step_chunk=...), and report done/failed.
"""

from __future__ import annotations

import ctypes
import json
from typing import Optional

from paddle_tpu.native import load

_CAP = 1 << 20


class Master:
    def __init__(
        self,
        lease_seconds: float = 60.0,
        failure_max: int = 3,
        _handle=None,
    ):
        self._lib = load()
        self._h = (
            _handle
            if _handle is not None
            else self._lib.pt_master_create(lease_seconds, failure_max)
        )

    # ---- task lifecycle ----
    def add_task(self, payload: bytes) -> int:
        if isinstance(payload, str):
            payload = payload.encode()
        return self._lib.pt_master_add_task(self._h, payload, len(payload))

    def add_chunk_tasks(self, path: str, num_chunks: int) -> None:
        """One task per chunk of a record file (the Go master's dataset
        partitioning, service.go:280)."""
        for i in range(num_chunks):
            self.add_task(json.dumps({"path": path, "chunk": i}).encode())

    def get_task(self) -> Optional[tuple]:
        """Lease a task: (task_id, payload), or None when nothing is
        leasable right now (empty payloads are valid tasks)."""
        cap = _CAP
        while True:
            buf = ctypes.create_string_buffer(cap)
            tid = ctypes.c_int64(0)
            n = self._lib.pt_master_get_task(
                self._h, buf, cap, ctypes.byref(tid)
            )
            if n == -3:
                return None
            if n == -1:  # buffer too small; tid holds the required size
                cap = tid.value
                continue
            if n < 0:
                raise RuntimeError(f"get_task failed (code {n})")
            return tid.value, buf.raw[:n]

    def task_done(self, task_id: int) -> bool:
        """False if the lease had already expired (task was requeued)."""
        return self._lib.pt_master_task_done(self._h, task_id) == 0

    def task_failed(self, task_id: int) -> bool:
        return self._lib.pt_master_task_failed(self._h, task_id) == 0

    # ---- pass control ----
    def pass_finished(self) -> bool:
        return self._lib.pt_master_pass_finished(self._h) == 1

    def start_pass(self) -> int:
        """Rotate done tasks back into todo; returns todo count."""
        return self._lib.pt_master_start_pass(self._h)

    # ---- introspection ----
    @property
    def counts(self) -> dict:
        c = self._lib.pt_master_count
        return {
            "todo": c(self._h, 0),
            "pending": c(self._h, 1),
            "done": c(self._h, 2),
            "discarded": c(self._h, 3),
        }

    def set_lease(self, seconds: float) -> None:
        self._lib.pt_master_set_lease(self._h, seconds)

    def request_save_model(
        self, trainer_id: str, block_seconds: float = 60.0
    ) -> bool:
        """Save-model election (go/master/service.go:467-495): True iff
        this trainer should save; the grant blocks other trainers for
        `block_seconds`."""
        r = self._lib.pt_master_request_save(
            self._h, trainer_id.encode(), block_seconds
        )
        if r < 0:
            raise ValueError("trainer_id must be non-empty")
        return r == 1

    # ---- serving (networked master; see data/master_client.py) ----
    def serve(
        self,
        port: int = 0,
        snapshot_path: Optional[str] = None,
        snapshot_every: float = 0.0,
    ) -> "MasterServer":
        """Expose this master over TCP (master_server.cc) so trainer
        processes on other hosts can lease tasks — the Go master's RPC
        service (go/master/service.go:89). Returns the running server."""
        h = self._lib.pt_master_server_start(
            self._h,
            port,
            snapshot_path.encode() if snapshot_path else None,
            snapshot_every,
        )
        if not h:
            raise OSError(f"cannot serve master on port {port}")
        return MasterServer(self._lib, h, self)

    # ---- durability ----
    def snapshot(self, path: str) -> None:
        if self._lib.pt_master_snapshot(self._h, path.encode()) != 0:
            raise IOError(f"snapshot to {path} failed")

    @classmethod
    def restore(cls, path: str) -> "Master":
        h = load().pt_master_restore(path.encode())
        if not h:
            raise IOError(f"cannot restore master from {path}")
        return cls(_handle=h)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.pt_master_destroy(h)
            self._h = None


class MasterServer:
    """Handle for a running networked master (pt_master_server_*)."""

    def __init__(self, lib, handle, master: "Master"):
        self._lib = lib
        self._h = handle
        self.master = master  # keep the Master alive while serving

    @property
    def port(self) -> int:
        return self._lib.pt_master_server_port(self._h)

    @property
    def stopped(self) -> bool:
        """True once a client sent SHUTDOWN."""
        return self._lib.pt_master_server_stopped(self._h) == 1

    def stop(self) -> None:
        if self._h:
            self._lib.pt_master_server_stop(self._h)
            self._h = None
