"""Native runtime loader: builds (if needed) and binds the C++ shared
library via ctypes.

The reference's native runtime pieces this library reproduces:
- paddle/optimizer/ — standalone C-ABI optimizer lib (used there by the
  Go pserver through cgo; here by host-side updaters and checkpoints),
- RecordIO chunk IO + DoubleBuffer async prefetch
  (go/master/service.go:280, gserver/dataproviders/DataProvider.h:249),
- the elastic master task queue (go/master/service.go).

No pybind11 in this image — plain ctypes over an `extern "C"` ABI.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "lib", "libpaddle_tpu_native.so")
_lock = threading.Lock()
_lib = None


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    src = os.path.join(_DIR, "src")
    return any(
        os.path.getmtime(os.path.join(src, f)) > lib_mtime
        for f in os.listdir(src)
    )


def build() -> str:
    """Compile the shared library (idempotent, mtime-cached). A file
    lock serializes concurrent builds across processes; the Makefile
    additionally renames the .so into place atomically."""
    if _needs_build():
        import fcntl

        os.makedirs(os.path.join(_DIR, "lib"), exist_ok=True)
        lock_path = os.path.join(_DIR, "lib", ".build.lock")
        with open(lock_path, "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            try:
                if _needs_build():  # re-check under the lock
                    subprocess.run(
                        ["make", "-s", "-C", _DIR],
                        check=True,
                        capture_output=True,
                        text=True,
                    )
            finally:
                fcntl.flock(lock, fcntl.LOCK_UN)
    return _LIB_PATH


def load() -> ctypes.CDLL:
    """Build if stale and dlopen; memoized."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        lib = ctypes.CDLL(build())

        c = ctypes
        i64, f64, i32 = c.c_int64, c.c_double, c.c_int
        p = c.c_void_p
        cp = c.c_char_p

        # optimizer
        lib.pt_optimizer_create.restype = p
        lib.pt_optimizer_create.argtypes = [
            cp, i64, f64, f64, f64, f64, f64, f64, f64, cp, f64, f64,
        ]
        lib.pt_optimizer_destroy.argtypes = [p]
        lib.pt_optimizer_update.argtypes = [
            p, c.POINTER(c.c_float), c.POINTER(c.c_float), i64, i64,
        ]
        lib.pt_optimizer_state_size.restype = i64
        lib.pt_optimizer_state_size.argtypes = [p]
        lib.pt_optimizer_get_state.restype = i64
        lib.pt_optimizer_get_state.argtypes = [p, cp, i64]
        lib.pt_optimizer_set_state.restype = i32
        lib.pt_optimizer_set_state.argtypes = [p, cp, i64]

        # recordio
        lib.pt_recordio_writer_open.restype = p
        lib.pt_recordio_writer_open.argtypes = [cp, i64]
        lib.pt_recordio_write.restype = i32
        lib.pt_recordio_write.argtypes = [p, cp, i64]
        lib.pt_recordio_writer_close.restype = i32
        lib.pt_recordio_writer_close.argtypes = [p]
        lib.pt_recordio_reader_open.restype = p
        lib.pt_recordio_reader_open.argtypes = [
            c.POINTER(cp), i32, i32, i32, i32,
        ]
        lib.pt_recordio_next.restype = i64
        lib.pt_recordio_next.argtypes = [p, c.c_char_p, i64]
        lib.pt_recordio_peek_len.restype = i64
        lib.pt_recordio_peek_len.argtypes = [p]
        lib.pt_recordio_error.restype = cp
        lib.pt_recordio_error.argtypes = [p]
        lib.pt_recordio_reader_close.argtypes = [p]
        lib.pt_recordio_count_chunks.restype = i64
        lib.pt_recordio_count_chunks.argtypes = [cp]

        # master
        lib.pt_master_create.restype = p
        lib.pt_master_create.argtypes = [f64, i32]
        lib.pt_master_destroy.argtypes = [p]
        lib.pt_master_add_task.restype = i64
        lib.pt_master_add_task.argtypes = [p, cp, i64]
        lib.pt_master_get_task.restype = i64
        lib.pt_master_get_task.argtypes = [p, c.c_char_p, i64, c.POINTER(i64)]
        lib.pt_master_task_done.restype = i32
        lib.pt_master_task_done.argtypes = [p, i64]
        lib.pt_master_task_failed.restype = i32
        lib.pt_master_task_failed.argtypes = [p, i64]
        lib.pt_master_pass_finished.restype = i32
        lib.pt_master_pass_finished.argtypes = [p]
        lib.pt_master_start_pass.restype = i64
        lib.pt_master_start_pass.argtypes = [p]
        lib.pt_master_count.restype = i64
        lib.pt_master_count.argtypes = [p, i32]
        lib.pt_master_set_lease.argtypes = [p, f64]
        lib.pt_master_snapshot.restype = i32
        lib.pt_master_snapshot.argtypes = [p, cp]
        lib.pt_master_restore.restype = p
        lib.pt_master_restore.argtypes = [cp]
        lib.pt_master_request_save.restype = i32
        lib.pt_master_request_save.argtypes = [p, cp, f64]

        # master server (networked elastic master)
        lib.pt_master_server_start.restype = p
        lib.pt_master_server_start.argtypes = [p, i32, cp, f64]
        lib.pt_master_server_port.restype = i32
        lib.pt_master_server_port.argtypes = [p]
        lib.pt_master_server_stopped.restype = i32
        lib.pt_master_server_stopped.argtypes = [p]
        lib.pt_master_server_stop.argtypes = [p]

        _lib = lib
        return _lib
