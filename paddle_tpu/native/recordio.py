"""ctypes wrappers for the chunked record format + async prefetch reader.

The dataset container for the elastic input pipeline: files are written
in CRC-protected chunks, readers stream records through a C++ prefetch
thread (the DoubleBuffer analogue, DataProvider.h:249), and chunk
boundaries are the task unit the master dispatches
(go/master/service.go:280).
"""

from __future__ import annotations

import ctypes

from paddle_tpu.native import load


class RecordWriter:
    def __init__(self, path: str, max_chunk_bytes: int = 1 << 20):
        self._lib = load()
        self._h = self._lib.pt_recordio_writer_open(
            path.encode(), max_chunk_bytes
        )
        if not self._h:
            raise IOError(f"cannot open {path} for writing")

    def write(self, record: bytes) -> None:
        if self._lib.pt_recordio_write(self._h, record, len(record)) != 0:
            raise IOError("record write failed")

    def close(self) -> None:
        if self._h:
            rc = self._lib.pt_recordio_writer_close(self._h)
            self._h = None
            if rc != 0:
                raise IOError("writer close/flush failed")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RecordReader:
    """Iterates records across files; `start_chunk`/`step_chunk` give
    sharded reads (worker i of k passes start_chunk=i, step_chunk=k)."""

    def __init__(
        self,
        paths,
        start_chunk: int = 0,
        step_chunk: int = 1,
        max_queued: int = 4096,
    ):
        self._lib = load()
        if isinstance(paths, str):
            paths = [paths]
        arr = (ctypes.c_char_p * len(paths))(
            *[p.encode() for p in paths]
        )
        self._h = self._lib.pt_recordio_reader_open(
            arr, len(paths), start_chunk, step_chunk, max_queued
        )
        if not self._h:
            raise IOError(f"cannot open reader for {paths}")

    def __iter__(self):
        return self

    def __next__(self) -> bytes:
        n = self._lib.pt_recordio_peek_len(self._h)
        if n == -3:  # end of data (0 is a valid empty record)
            raise StopIteration
        if n == -2:
            err = self._lib.pt_recordio_error(self._h)
            raise IOError(err.decode() if err else "read error")
        buf = ctypes.create_string_buffer(max(n, 1))
        got = self._lib.pt_recordio_next(self._h, buf, max(n, 1))
        if got != n:
            raise IOError("short read from prefetch queue")
        return buf.raw[:got]

    def close(self) -> None:
        if self._h:
            self._lib.pt_recordio_reader_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def count_chunks(path: str) -> int:
    n = load().pt_recordio_count_chunks(path.encode())
    if n < 0:
        raise IOError(f"cannot count chunks in {path} (code {n})")
    return n
