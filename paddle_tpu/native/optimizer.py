"""ctypes wrapper over the native C++ optimizer library.

Host-side optimizer with portable serialized state — the paddle/optimizer
capability (SURVEY.md §2 row 9). The TPU training path applies optimizers
on-device (paddle_tpu/optimizers/); this one serves host-resident
parameters (e.g. CPU-offloaded embedding shards) and state round-trips.
"""

from __future__ import annotations

import ctypes

import numpy as np

from paddle_tpu.native import load


class NativeOptimizer:
    def __init__(
        self,
        method: str,
        n: int,
        learning_rate: float = 0.01,
        momentum: float = 0.0,
        epsilon: float = 1e-6,
        rho: float = 0.95,
        beta1: float = 0.9,
        beta2: float = 0.999,
        decay: float = 0.0,
        lr_policy: str = "const",
        lr_decay_a: float = 0.0,
        lr_decay_b: float = 0.0,
    ):
        self._lib = load()
        self._n = int(n)
        self._h = self._lib.pt_optimizer_create(
            method.encode(), self._n, learning_rate, momentum, epsilon,
            rho, beta1, beta2, decay, lr_policy.encode(),
            lr_decay_a, lr_decay_b,
        )
        if not self._h:
            raise ValueError(f"unknown method/policy: {method}/{lr_policy}")

    def update(self, param: np.ndarray, grad: np.ndarray, step: int) -> None:
        """In-place update of `param` (float32, C-contiguous)."""
        assert param.dtype == np.float32 and param.flags["C_CONTIGUOUS"]
        assert param.size == self._n and grad.size == self._n
        grad = np.ascontiguousarray(grad, np.float32)
        fp = ctypes.POINTER(ctypes.c_float)
        self._lib.pt_optimizer_update(
            self._h,
            param.ctypes.data_as(fp),
            grad.ctypes.data_as(fp),
            self._n,
            step,
        )

    def get_state(self) -> bytes:
        size = self._lib.pt_optimizer_state_size(self._h)
        buf = ctypes.create_string_buffer(size)
        got = self._lib.pt_optimizer_get_state(self._h, buf, size)
        if got < 0:
            raise RuntimeError("optimizer state serialization failed")
        return buf.raw[:got]

    def set_state(self, state: bytes) -> None:
        rc = self._lib.pt_optimizer_set_state(self._h, state, len(state))
        if rc != 0:
            raise ValueError(f"bad optimizer state (code {rc})")

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.pt_optimizer_destroy(h)
            self._h = None
