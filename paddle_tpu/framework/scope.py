"""Variable and hierarchical Scope.

Reference: framework/variable.h:24 (type-erased holder), framework/scope.h:36
(name->Variable map with parent lookup chain, scope.h:52-59 NewScope/parent).
Here a Variable holds either an array (jax or numpy) or any Python object
(e.g. the step-scope list a RecurrentOp stores in its parent scope,
operators/recurrent_op.h:49-52).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class Variable:
    __slots__ = ("name", "value")

    def __init__(self, name: str, value: Any = None):
        self.name = name
        self.value = value

    def is_initialized(self) -> bool:
        return self.value is not None


class Scope:
    """Hierarchical variable store. Lookup walks to the parent
    (scope.h:52-59); creation is always local."""

    def __init__(self, parent: Optional["Scope"] = None):
        self.parent = parent
        self._vars: Dict[str, Variable] = {}
        self._kids: List["Scope"] = []

    def new_scope(self) -> "Scope":
        kid = type(self)(parent=self)  # subclass-preserving (core.Scope)
        self._kids.append(kid)
        return kid

    def var(self, name: str) -> Variable:
        """Find-or-create in THIS scope (scope.h Var())."""
        v = self._vars.get(name)
        if v is None:
            v = self._vars[name] = Variable(name)
        return v

    def find_var(self, name: str) -> Optional[Variable]:
        v = self._vars.get(name)
        if v is not None:
            return v
        return self.parent.find_var(name) if self.parent else None

    def get(self, name: str) -> Any:
        v = self.find_var(name)
        if v is None:
            raise KeyError(f"variable {name!r} not found in scope")
        return v.value

    def set(self, name: str, value: Any) -> None:
        self.var(name).value = value

    def local_names(self) -> List[str]:
        return list(self._vars)

    def __contains__(self, name: str) -> bool:
        return self.find_var(name) is not None
