"""Autodiff by op-level transposition.

Reference: framework/backward.cc:65-109 — walk the forward net in reverse,
emit each op's registered grad op; when a forward variable feeds several
ops its gradient has several producers, so each producer is renamed to
X@GRAD@RENAME@<uid> and an accumulation op is inserted
(backward.cc:117-140); outputs whose base variables are in the no-grad
set become @EMPTY@ (grad_op_builder semantics); forward outputs that are
never consumed get fill_zeros_like seeds; RecurrentOp recurses into its
stepnet (backward.cc:193).

The caller seeds the gradient of the root outputs (the pybind/test
convention: ones for the loss). jax.grad over `net_to_fn` gives the same
derivatives by tracing — the transposition path exists for capability
parity and for runtimes that want an explicit backward graph.
"""

from __future__ import annotations

import itertools
from typing import List, Set

from paddle_tpu.framework.op import (
    EMPTY_VAR,
    GRAD_SUFFIX,
    NetOp,
    OperatorBase,
    create_op,
    grad_op_for,
)

_uid = itertools.count()


def _collect_grad_ops(op: OperatorBase, out: List[OperatorBase]) -> None:
    from paddle_tpu.framework.recurrent import RecurrentOp

    if isinstance(op, RecurrentOp):
        out.append(op.build_grad_op())
    elif isinstance(op, NetOp):
        for child in reversed(op.ops):
            _collect_grad_ops(child, out)
    else:
        out.extend(grad_op_for(op))


def backward(
    forward_op: OperatorBase,
    no_grad: Set[str] = frozenset(),
    seeded: Set[str] = frozenset(),
) -> NetOp:
    """Build the backward NetOp of a forward op/net.

    `seeded`: forward vars whose gradients the caller feeds into the
    scope before running the backward net (the loss: ones). Every other
    gradient consumed before being produced gets a fill_zeros_like seed
    — the reference's treatment of unused forward outputs.
    """
    no_grad_g = {n + GRAD_SUFFIX for n in no_grad}
    grad_ops: List[OperatorBase] = []
    _collect_grad_ops(forward_op, grad_ops)

    # no-grad outputs -> @EMPTY@; drop fully-empty ops (backward.cc NOP)
    kept: List[OperatorBase] = []
    for gop in grad_ops:
        empty = True
        for slot, names in gop.outputs.items():
            names[:] = [
                EMPTY_VAR if n in no_grad_g else n for n in names
            ]
            empty = empty and all(n == EMPTY_VAR for n in names)
        if not empty:
            kept.append(gop)
    grad_ops = kept

    # fan-out accumulation: rename duplicate producers, insert sum
    producers: dict = {}
    for i, gop in enumerate(grad_ops):
        for names in gop.outputs.values():
            for n in names:
                if n != EMPTY_VAR and n.endswith(GRAD_SUFFIX):
                    producers.setdefault(n, []).append(i)
    net = NetOp()
    root_seeded = {n + GRAD_SUFFIX for n in seeded}
    inserted_after: dict = {}
    for name, idxs in producers.items():
        ext_seed = name in root_seeded  # caller-fed grad also a summand
        if len(idxs) > 1 or (ext_seed and idxs):
            renamed = []
            for i in idxs:
                new = f"{name}@RENAME@{next(_uid)}"
                for names in grad_ops[i].outputs.values():
                    names[:] = [new if n == name else n for n in names]
                renamed.append(new)
            summands = ([name] if ext_seed else []) + renamed
            inserted_after.setdefault(idxs[-1], []).append(
                create_op("sum", {"X": summands}, {"Out": name})
            )

    ordered: List[OperatorBase] = []
    for i, gop in enumerate(grad_ops):
        ordered.append(gop)
        ordered.extend(inserted_after.get(i, []))

    # unseeded @GRAD inputs (unused forward outputs) -> fill_zeros_like
    produced: Set[str] = set()
    final: List[OperatorBase] = []
    for gop in ordered:
        for names in gop.inputs.values():
            for n in names:
                if (
                    n.endswith(GRAD_SUFFIX)
                    and "@RENAME@" not in n
                    and n not in produced
                    and n not in root_seeded
                    and n != EMPTY_VAR
                ):
                    src = n[: -len(GRAD_SUFFIX)]
                    final.append(
                        create_op(
                            "fill_zeros_like", {"Src": src}, {"Dst": n}
                        )
                    )
                    produced.add(n)
        final.append(gop)
        produced.update(
            n for ns in gop.outputs.values() for n in ns if n != EMPTY_VAR
        )

    for gop in final:
        net.append_op(gop)
    net.complete_add_op()
    return net
