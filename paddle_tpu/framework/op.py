"""OperatorBase, op registry, grad-op builders, NetOp, and the jit bridge.

Reference: framework/operator.h:63 (OperatorBase: type + named input/output
var lists + attrs, Run(scope, ctx)), framework/op_registry.h (registration
+ CreateOp), framework/grad_op_builder.cc (forward op -> grad op with
I/O wired by @GRAD-suffix convention), operators/net_op.h (composite op
running children in order, CompleteAddOp output inference).

TPU-first divergence: a kernel is a pure function of jax arrays; `run`
executes it eagerly (numpy/jax interop), while `net_to_fn` closes a whole
net over a feed list and returns a jittable pure function — XLA then fuses
across op boundaries, which is the role the reference's per-op CUDA
kernels + planned executor could never fill.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

from paddle_tpu.framework.scope import Scope

GRAD_SUFFIX = "@GRAD"  # framework: kGradVarSuffix
EMPTY_VAR = "@EMPTY@"  # framework: kEmptyVarName

VarMap = Dict[str, List[str]]

_OPS: Dict[str, type] = {}
_GRAD_BUILDERS: Dict[str, Callable] = {}


def register_op(name: str):
    def deco(cls):
        cls.type = name
        _OPS[name] = cls
        return cls

    return deco


def register_grad(name: str):
    """Register fn(fwd_op) -> list[OperatorBase] building the grad op(s)."""

    def deco(fn):
        _GRAD_BUILDERS[name] = fn
        return fn

    return deco


def _as_varmap(m) -> VarMap:
    out: VarMap = {}
    for k, v in (m or {}).items():
        out[k] = [v] if isinstance(v, str) else list(v)
    return out


class OperatorBase:
    """type + named input/output variable lists + attrs
    (framework/operator.h:63,90)."""

    type: str = "base"
    # OpProto-style slot signature (framework/op_registry.h OpProto):
    # declared per registered op via set_signature; introspected by the
    # v2 Operator facade and the generic op-test harness.
    INPUT_SLOTS: tuple = ()
    OUTPUT_SLOTS: tuple = ()
    ATTR_NAMES: tuple = ()

    def __init__(self, inputs=None, outputs=None, attrs=None):
        self.inputs: VarMap = _as_varmap(inputs)
        self.outputs: VarMap = _as_varmap(outputs)
        self.attrs: Dict[str, Any] = dict(attrs or {})

    # -- slot helpers (operator.h Input/Inputs/Output) --
    def input(self, slot: str) -> str:
        names = self.inputs[slot]
        assert len(names) == 1, f"{self.type}.{slot} is a list slot"
        return names[0]

    def output(self, slot: str) -> str:
        names = self.outputs[slot]
        assert len(names) == 1, f"{self.type}.{slot} is a list slot"
        return names[0]

    def input_vars(self) -> List[str]:
        return [n for ns in self.inputs.values() for n in ns]

    def output_vars(self) -> List[str]:
        return [n for ns in self.outputs.values() for n in ns]

    # -- execution --
    def kernel(self, ins: Dict[str, Any], attrs: Dict[str, Any]):
        """Pure function: slot->array(s) in, slot->array(s) out."""
        raise NotImplementedError(self.type)

    def run(self, scope: Scope) -> None:
        ins = {}
        for slot, names in self.inputs.items():
            vals = [
                None if n == EMPTY_VAR else scope.get(n) for n in names
            ]
            ins[slot] = vals[0] if len(vals) == 1 else vals
        outs = self.kernel(ins, self.attrs)
        for slot, names in self.outputs.items():
            vals = outs[slot]
            if len(names) == 1:
                vals = [vals]
            for n, v in zip(names, vals):
                if n != EMPTY_VAR:
                    scope.set(n, v)

    def __repr__(self):
        return (
            f"Op({self.type}, inputs={self.inputs}, "
            f"outputs={self.outputs})"
        )


def create_op(type_name: str, inputs=None, outputs=None, attrs=None):
    """OpRegistry::CreateOp (framework/op_registry.h)."""
    if type_name not in _OPS:
        known = ", ".join(sorted(_OPS))
        raise KeyError(f"unknown op type {type_name!r}; registered: {known}")
    return _OPS[type_name](inputs=inputs, outputs=outputs, attrs=attrs)


def set_signature(type_name: str, input_slots, output_slots,
                  attr_names=()):
    """Attach the OpProto slot signature to a registered op."""
    cls = _OPS[type_name]
    cls.INPUT_SLOTS = tuple(input_slots)
    cls.OUTPUT_SLOTS = tuple(output_slots)
    cls.ATTR_NAMES = tuple(attr_names)


def op_types() -> List[str]:
    """All registered op type names (OpRegistry enumeration)."""
    return sorted(_OPS)


def op_signature(type_name: str):
    """(input_slots, output_slots, attr_names) of a registered op —
    the role of the reference's OpProto / get_all_op_protos()."""
    if type_name not in _OPS:
        raise KeyError(f"unknown op type {type_name!r}")
    cls = _OPS[type_name]
    return cls.INPUT_SLOTS, cls.OUTPUT_SLOTS, cls.ATTR_NAMES


def grad_op_for(op: OperatorBase) -> List[OperatorBase]:
    """Build the grad op(s) of a forward op
    (framework/grad_op_builder.cc)."""
    if op.type not in _GRAD_BUILDERS:
        raise KeyError(f"op {op.type!r} has no registered grad builder")
    ops = _GRAD_BUILDERS[op.type](op)
    return ops if isinstance(ops, list) else [ops]


class NetOp(OperatorBase):
    """Composite op: children run in insertion order
    (operators/net_op.h)."""

    type = "net"

    def __init__(self, inputs=None, outputs=None, attrs=None):
        super().__init__(inputs, outputs, attrs)
        self.ops: List[OperatorBase] = []

    def append_op(self, op: OperatorBase) -> OperatorBase:
        self.ops.append(op)
        return op

    def add_op(self, type_name, inputs=None, outputs=None, attrs=None):
        return self.append_op(create_op(type_name, inputs, outputs, attrs))

    def complete_add_op(self) -> None:
        """Infer net-level inputs (consumed before produced) and outputs
        (produced by any child) — net_op.h CompleteAddOp."""
        produced, needed = set(), []
        outs = []
        for op in self.ops:
            for n in op.input_vars():
                if n not in produced and n != EMPTY_VAR:
                    needed.append(n)
            for n in op.output_vars():
                if n != EMPTY_VAR:
                    produced.add(n)
                    outs.append(n)
        seen = set()
        self.inputs = {
            "X": [n for n in needed if not (n in seen or seen.add(n))]
        }
        seen = set()
        self.outputs = {
            "Out": [n for n in outs if not (n in seen or seen.add(n))]
        }

    def run(self, scope: Scope) -> None:
        for op in self.ops:
            op.run(scope)


def net_to_fn(
    net: OperatorBase,
    feed_names: Sequence[str],
    fetch_names: Sequence[str],
    const_scope: Optional[Scope] = None,
) -> Callable:
    """Close a net over (feeds -> fetches) as a pure function.

    jax.jit(net_to_fn(net, ...)) compiles the whole op graph into one XLA
    program. `const_scope` supplies non-differentiated constants visible
    via parent lookup.
    """

    def fn(*feed_values):
        scope = Scope(parent=const_scope)
        for name, val in zip(feed_names, feed_values):
            scope.set(name, val)
        net.run(scope)
        return tuple(scope.get(n) for n in fetch_names)

    return fn
