"""RecurrentOp — a step net run over time with per-step scopes.

Reference: operators/recurrent_op.h:44-121 (RecurrentAlgorithm: step-scope
list stored in the parent scope, SegmentInputs/ConcatOutputs over [T,...]
sequence vars, memories linked pre_var(t) <- var(t-1) with boot_var init —
rnn/recurrent_op_utils.h MemoryAttr/Link) and RecurrentGradientAlgorithm
(reverse-time walk of the backward stepnet with LinkBootMemoryGradients).

Two execution modes:
- `run(scope)`: eager, literal per-step scopes — the reference semantics,
  inspectable step state.
- `scan_fn(...)`: the TPU path — the stepnet closed into a pure function
  and driven by `jax.lax.scan`, so the whole recurrence compiles to one
  XLA while loop; jax.grad over it differentiates the recurrence without
  the explicit grad op.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List

import jax
import jax.numpy as jnp

from paddle_tpu.framework.op import (
    GRAD_SUFFIX,
    NetOp,
    OperatorBase,
    net_to_fn,
)
from paddle_tpu.framework.scope import Scope


@dataclass
class MemoryAttr:
    """rnn::MemoryAttr (rnn/recurrent_op_utils.h): step state `var`,
    read in-step as `pre_var`, initialized from parent-scope
    `boot_var`."""

    var: str
    pre_var: str
    boot_var: str


class RecurrentOp(OperatorBase):
    type = "recurrent"

    def __init__(
        self,
        stepnet: NetOp,
        inlinks: List[str],
        outlinks: List[str],
        memories: List[MemoryAttr],
        inputs=None,
        outputs=None,
        attrs=None,
    ):
        super().__init__(
            inputs or {"inlinks": inlinks},
            outputs or {"outlinks": outlinks},
            attrs,
        )
        self.stepnet = stepnet
        self.inlinks = list(inlinks)
        self.outlinks = list(outlinks)
        self.memories = list(memories)

    # -- eager reference semantics ------------------------------------
    def run(self, scope: Scope) -> None:
        T = None
        for name in self.inlinks:
            seq = scope.get(name)
            T = seq.shape[0] if T is None else T
            assert seq.shape[0] == T, "inlink sequence lengths differ"
        step_scopes = self._create_scopes(scope, T)
        for t in range(T):
            st = step_scopes[t]
            for name in self.inlinks:  # SegmentInputs
                st.set(name, scope.get(name)[t])
            for m in self.memories:  # InitMemories / link pre <- prev
                if t == 0:
                    st.set(m.pre_var, scope.get(m.boot_var))
                else:
                    st.set(m.pre_var, step_scopes[t - 1].get(m.var))
            self.stepnet.run(st)
        for name in self.outlinks:  # ConcatOutputs
            scope.set(
                name,
                jnp.stack([step_scopes[t].get(name) for t in range(T)]),
            )

    def _create_scopes(self, scope: Scope, T: int) -> List[Scope]:
        holder = scope.var(self._scopes_name())
        if holder.value is None:
            holder.value = []
        while len(holder.value) < T:  # reuse + expand (recurrent_op.h:53)
            holder.value.append(scope.new_scope())
        return holder.value

    def _scopes_name(self) -> str:
        return f"@step_scopes@{id(self)}"

    # -- TPU scan path -------------------------------------------------
    def scan_fn(self, extern_names: List[str]):
        """Pure fn(extern_vals, boot_vals, inlink_seqs) -> outlink_seqs,
        with the stepnet under `lax.scan`. `extern_names` are the
        parent-scope vars the stepnet reads (weights)."""
        feed = (
            list(extern_names)
            + [m.pre_var for m in self.memories]
            + self.inlinks
        )
        fetch = [m.var for m in self.memories] + self.outlinks
        step = net_to_fn(self.stepnet, feed, fetch)
        n_mem = len(self.memories)

        def fn(extern_vals, boot_vals, inlink_seqs):
            def body(carry, xs):
                outs = step(*extern_vals, *carry, *xs)
                return tuple(outs[:n_mem]), tuple(outs[n_mem:])

            _, ys = jax.lax.scan(body, tuple(boot_vals), tuple(inlink_seqs))
            return ys

        return fn

    def extern_names(self) -> List[str]:
        """Stepnet inputs resolved from the parent scope (weights): not
        inlinks, not memories' pre_vars, not produced in-step."""
        produced = set()
        local = set(self.inlinks) | {m.pre_var for m in self.memories}
        ext: List[str] = []
        for op in self.stepnet.ops:
            for n in op.input_vars():
                if (
                    n not in local
                    and n not in produced
                    and n not in ext
                ):
                    ext.append(n)
            produced.update(op.output_vars())
        return ext

    def build_grad_op(self) -> "RecurrentGradientOp":
        return RecurrentGradientOp(self)


class RecurrentGradientOp(OperatorBase):
    """Reverse-time backward pass (RecurrentGradientAlgorithm).

    Consumes outlink grads from the parent scope, walks steps T-1..0
    running the backward stepnet in each step scope, carries the memory
    gradient pre_var@GRAD(t+1) into var@GRAD(t) (LinkBootMemoryGradients),
    stacks inlink grads, sums extern (weight) grads across steps, and
    writes boot_var@GRAD.
    """

    type = "recurrent_grad"

    def __init__(self, fwd: RecurrentOp):
        extern = fwd.extern_names()
        super().__init__(
            {"outlinks_grad": [n + GRAD_SUFFIX for n in fwd.outlinks]},
            {
                "inlinks_grad": [n + GRAD_SUFFIX for n in fwd.inlinks],
                "extern_grad": [n + GRAD_SUFFIX for n in extern],
                "boot_grad": [
                    m.boot_var + GRAD_SUFFIX for m in fwd.memories
                ],
            },
        )
        self.fwd = fwd
        self._extern = extern
        from paddle_tpu.framework.backward import backward

        # per-step seeds: outlink grads (sliced from the parent) and
        # memory-var grads (the carry from step t+1)
        self.grad_stepnet = backward(
            fwd.stepnet,
            seeded=set(fwd.outlinks) | {m.var for m in fwd.memories},
        )

    def run(self, scope: Scope) -> None:
        # all writes go through the DECLARED output names so backward()'s
        # @RENAME@ fan-out rewriting and @EMPTY@/no-grad substitution on
        # this op's outputs take effect (grad_op_builder semantics)
        from paddle_tpu.framework.op import EMPTY_VAR

        fwd = self.fwd
        step_scopes: List[Scope] = scope.get(fwd._scopes_name())
        T = scope.get(self.inputs["outlinks_grad"][0]).shape[0]
        extern = self._extern
        extern_acc: Dict[str, Any] = {}
        mem_carry: Dict[str, Any] = {}

        for t in reversed(range(T)):
            st = step_scopes[t]
            for name, src in zip(fwd.outlinks, self.inputs["outlinks_grad"]):
                g = scope.get(src)[t]
                carried = mem_carry.pop(name, None)
                st.set(name + GRAD_SUFFIX, g if carried is None else g + carried)
            for m in self.fwd.memories:
                if m.var not in fwd.outlinks:
                    carried = mem_carry.pop(m.var, None)
                    st.set(
                        m.var + GRAD_SUFFIX,
                        jnp.zeros_like(st.get(m.var))
                        if carried is None
                        else carried,
                    )
            self.grad_stepnet.run(st)
            for m, boot_tgt in zip(fwd.memories, self.outputs["boot_grad"]):
                g = st.find_var(m.pre_var + GRAD_SUFFIX)
                if g is not None and g.value is not None:
                    mem_carry[m.var] = g.value
                    if t == 0 and boot_tgt != EMPTY_VAR:
                        scope.set(boot_tgt, g.value)
            for n in extern:
                g = st.find_var(n + GRAD_SUFFIX)
                if g is not None and g.value is not None:
                    prev = extern_acc.get(n)
                    extern_acc[n] = (
                        g.value if prev is None else prev + g.value
                    )

        for name, target in zip(fwd.inlinks, self.outputs["inlinks_grad"]):
            if target != EMPTY_VAR:
                scope.set(
                    target,
                    jnp.stack(
                        [
                            step_scopes[t].get(name + GRAD_SUFFIX)
                            for t in range(T)
                        ]
                    ),
                )
        for n, target in zip(extern, self.outputs["extern_grad"]):
            if target != EMPTY_VAR and n in extern_acc:
                scope.set(target, extern_acc[n])
