"""The new-style op set with registered grad-op builders.

Reference: paddle/operators/*.cc — add, mul, mean, sigmoid, softmax,
onehot cross_entropy, rowwise_add, sgd, fill_zeros_like, gaussian_random,
uniform_random (35 REGISTER_OP* registrations total), gather/scatter
kernels (operators/gather.h, operators/scatter.h). Kernels here are pure
jax.numpy; each forward op registers a grad builder wiring @GRAD-suffixed
variables exactly like framework/grad_op_builder.cc.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.framework.op import (
    GRAD_SUFFIX as G,
    OperatorBase,
    create_op,
    register_grad,
    register_op,
    set_signature,
)


def _g(name: str) -> str:
    return name + G


# ---------------------------------------------------------------- add
@register_op("add")
class AddOp(OperatorBase):
    def kernel(self, ins, attrs):
        return {"Out": ins["X"] + ins["Y"]}


@register_grad("add")
def _add_grad(op):
    x, y, out = op.input("X"), op.input("Y"), op.output("Out")
    return [
        create_op("identity", {"X": _g(out)}, {"Out": _g(x)}),
        create_op(
            "reduce_to_shape_of",
            {"X": _g(out), "Like": y},
            {"Out": _g(y)},
        ),
    ]


@register_op("identity")
class IdentityOp(OperatorBase):
    def kernel(self, ins, attrs):
        return {"Out": ins["X"]}


@register_grad("identity")
def _identity_grad(op):
    return [
        create_op(
            "identity",
            {"X": _g(op.output("Out"))},
            {"Out": _g(op.input("X"))},
        )
    ]


@register_op("reduce_to_shape_of")
class ReduceToShapeOfOp(OperatorBase):
    """Sum-reduce X over broadcast dims so it matches Like's shape
    (the unbroadcast needed by add/rowwise_add grads)."""

    def kernel(self, ins, attrs):
        x, like = ins["X"], ins["Like"]
        extra = x.ndim - like.ndim
        if extra:
            x = x.sum(axis=tuple(range(extra)))
        keep = tuple(
            i for i, (a, b) in enumerate(zip(x.shape, like.shape)) if a != b
        )
        if keep:
            x = x.sum(axis=keep, keepdims=True)
        return {"Out": x.reshape(like.shape)}


# ---------------------------------------------------------------- sum
@register_op("sum")
class SumOp(OperatorBase):
    """Accumulates a list of same-shape inputs; inserted by backward()
    for fan-out gradient accumulation (framework/backward.cc:117-140
    add op over @RENAME@ duplicates)."""

    def kernel(self, ins, attrs):
        xs = ins["X"]
        if not isinstance(xs, list):
            xs = [xs]
        out = xs[0]
        for x in xs[1:]:
            out = out + x
        return {"Out": out}


# ---------------------------------------------------------------- mul
@register_op("mul")
class MulOp(OperatorBase):
    """Matrix multiply (operators/mul_op.cc)."""

    def kernel(self, ins, attrs):
        return {"Out": ins["X"] @ ins["Y"]}


@register_grad("mul")
def _mul_grad(op):
    x, y, out = op.input("X"), op.input("Y"), op.output("Out")
    return [
        create_op("matmul_nt", {"X": _g(out), "Y": y}, {"Out": _g(x)}),
        create_op("matmul_tn", {"X": x, "Y": _g(out)}, {"Out": _g(y)}),
    ]


@register_op("matmul_nt")
class MatmulNTOp(OperatorBase):
    def kernel(self, ins, attrs):
        return {"Out": ins["X"] @ ins["Y"].T}


@register_op("matmul_tn")
class MatmulTNOp(OperatorBase):
    def kernel(self, ins, attrs):
        return {"Out": ins["X"].T @ ins["Y"]}


# ---------------------------------------------------------------- mean
@register_op("mean")
class MeanOp(OperatorBase):
    def kernel(self, ins, attrs):
        return {"Out": jnp.mean(ins["X"])}


@register_grad("mean")
def _mean_grad(op):
    x, out = op.input("X"), op.output("Out")
    return [
        create_op("mean_grad", {"X": x, "Out@G": _g(out)}, {"Out": _g(x)})
    ]


@register_op("mean_grad")
class MeanGradOp(OperatorBase):
    def kernel(self, ins, attrs):
        x = ins["X"]
        return {"Out": jnp.broadcast_to(ins["Out@G"] / x.size, x.shape)}


# ---------------------------------------------------------------- scale
@register_op("scale")
class ScaleOp(OperatorBase):
    def kernel(self, ins, attrs):
        return {"Out": ins["X"] * attrs.get("scale", 1.0)}


@register_grad("scale")
def _scale_grad(op):
    return [
        create_op(
            "scale",
            {"X": _g(op.output("Out"))},
            {"Out": _g(op.input("X"))},
            {"scale": op.attrs.get("scale", 1.0)},
        )
    ]


# ---------------------------------------------------------------- sigmoid
@register_op("sigmoid")
class SigmoidOp(OperatorBase):
    def kernel(self, ins, attrs):
        return {"Y": jax.nn.sigmoid(ins["X"])}


@register_grad("sigmoid")
def _sigmoid_grad(op):
    y = op.output("Y")
    return [
        create_op(
            "sigmoid_grad",
            {"Y": y, "Y@G": _g(y)},
            {"Out": _g(op.input("X"))},
        )
    ]


@register_op("sigmoid_grad")
class SigmoidGradOp(OperatorBase):
    def kernel(self, ins, attrs):
        y = ins["Y"]
        return {"Out": ins["Y@G"] * y * (1.0 - y)}


# ---------------------------------------------------------------- softmax
@register_op("softmax")
class SoftmaxOp(OperatorBase):
    def kernel(self, ins, attrs):
        return {"Y": jax.nn.softmax(ins["X"], axis=-1)}


@register_grad("softmax")
def _softmax_grad(op):
    y = op.output("Y")
    return [
        create_op(
            "softmax_grad",
            {"Y": y, "Y@G": _g(y)},
            {"Out": _g(op.input("X"))},
        )
    ]


@register_op("softmax_grad")
class SoftmaxGradOp(OperatorBase):
    def kernel(self, ins, attrs):
        y, dy = ins["Y"], ins["Y@G"]
        return {"Out": y * (dy - jnp.sum(dy * y, axis=-1, keepdims=True))}


# ------------------------------------------------------- cross entropy
@register_op("onehot_cross_entropy")
class OnehotCrossEntropyOp(OperatorBase):
    """Y_i = -log(X[i, label_i]) (operators/cross_entropy_op.cc)."""

    def kernel(self, ins, attrs):
        x, label = ins["X"], ins["label"]
        picked = jnp.take_along_axis(x, label[:, None], axis=1)[:, 0]
        return {"Y": -jnp.log(jnp.maximum(picked, 1e-20))}


@register_grad("onehot_cross_entropy")
def _xent_grad(op):
    x, label, y = op.input("X"), op.input("label"), op.output("Y")
    return [
        create_op(
            "onehot_cross_entropy_grad",
            {"X": x, "label": label, "Y@G": _g(y)},
            {"Out": _g(x)},
        )
    ]


@register_op("onehot_cross_entropy_grad")
class OnehotCrossEntropyGradOp(OperatorBase):
    def kernel(self, ins, attrs):
        x, label, dy = ins["X"], ins["label"], ins["Y@G"]
        onehot = jax.nn.one_hot(label, x.shape[1], dtype=x.dtype)
        return {"Out": -onehot * (dy[:, None] / jnp.maximum(x, 1e-20))}


# ------------------------------------------------------- rowwise add
@register_op("rowwise_add")
class RowwiseAddOp(OperatorBase):
    def kernel(self, ins, attrs):
        return {"Out": ins["X"] + ins["b"]}


@register_grad("rowwise_add")
def _rowwise_add_grad(op):
    x, b, out = op.input("X"), op.input("b"), op.output("Out")
    return [
        create_op("identity", {"X": _g(out)}, {"Out": _g(x)}),
        create_op(
            "reduce_to_shape_of", {"X": _g(out), "Like": b}, {"Out": _g(b)}
        ),
    ]


# ---------------------------------------------------------------- sgd
@register_op("sgd")
class SGDOp(OperatorBase):
    """param_out = param - lr * grad (operators/sgd_op.cc)."""

    def kernel(self, ins, attrs):
        lr = attrs.get("learning_rate", 0.01)
        return {"param_out": ins["param"] - lr * ins["grad"]}


# ------------------------------------------------------ fill zeros like
@register_op("fill_zeros_like")
class FillZerosLikeOp(OperatorBase):
    def kernel(self, ins, attrs):
        return {"Dst": jnp.zeros_like(ins["Src"])}


# ------------------------------------------------------- random ops
@register_op("gaussian_random")
class GaussianRandomOp(OperatorBase):
    def kernel(self, ins, attrs):
        key = jax.random.key(attrs.get("seed", 0))
        shape = tuple(attrs["dims"])
        return {
            "Out": attrs.get("mean", 0.0)
            + attrs.get("std", 1.0)
            * jax.random.normal(key, shape, dtype=jnp.float32)
        }


@register_op("uniform_random")
class UniformRandomOp(OperatorBase):
    def kernel(self, ins, attrs):
        key = jax.random.key(attrs.get("seed", 0))
        shape = tuple(attrs["dims"])
        return {
            "Out": jax.random.uniform(
                key,
                shape,
                minval=attrs.get("min", -1.0),
                maxval=attrs.get("max", 1.0),
                dtype=jnp.float32,
            )
        }


# ------------------------------------------------------- gather/scatter
@register_op("gather")
class GatherOp(OperatorBase):
    """Out = X[Index] rows (operators/gather.h)."""

    def kernel(self, ins, attrs):
        return {"Out": jnp.take(ins["X"], ins["Index"], axis=0)}


@register_grad("gather")
def _gather_grad(op):
    x, idx, out = op.input("X"), op.input("Index"), op.output("Out")
    return [
        create_op(
            "scatter_add_like",
            {"Like": x, "Index": idx, "Updates": _g(out)},
            {"Out": _g(x)},
        )
    ]


@register_op("scatter_add_like")
class ScatterAddLikeOp(OperatorBase):
    def kernel(self, ins, attrs):
        zeros = jnp.zeros_like(ins["Like"])
        return {"Out": zeros.at[ins["Index"]].add(ins["Updates"])}


@register_op("scatter")
class ScatterOp(OperatorBase):
    """Out = Ref with Updates added at Index rows
    (operators/scatter.h ScatterUpdate)."""

    def kernel(self, ins, attrs):
        return {"Out": ins["Ref"].at[ins["Index"]].add(ins["Updates"])}


@register_grad("scatter")
def _scatter_grad(op):
    ref, idx, upd = op.input("Ref"), op.input("Index"), op.input("Updates")
    out = op.output("Out")
    return [
        create_op("identity", {"X": _g(out)}, {"Out": _g(ref)}),
        create_op(
            "gather", {"X": _g(out), "Index": idx}, {"Out": _g(upd)}
        ),
    ]


# --------------------------------------------------- slot signatures
# OpProto declarations (framework/op_registry.h: each op's Maker names
# its input/output slots and attributes). The v2 Operator facade
# (paddle.v2.framework.op) and the generic op-test/gradient-check
# harness build ops by slot name from these.
for _name, _sig in {
    "add": (("X", "Y"), ("Out",)),
    "identity": (("X",), ("Out",)),
    "reduce_to_shape_of": (("X", "Like"), ("Out",)),
    "sum": (("X",), ("Out",)),
    "mul": (("X", "Y"), ("Out",)),
    "matmul_nt": (("X", "Y"), ("Out",)),
    "matmul_tn": (("X", "Y"), ("Out",)),
    "mean": (("X",), ("Out",)),
    "mean_grad": (("X", "Out@G"), ("Out",)),
    "scale": (("X",), ("Out",), ("scale",)),
    "sigmoid": (("X",), ("Y",)),
    "sigmoid_grad": (("Y", "Y@G"), ("Out",)),
    "softmax": (("X",), ("Y",)),
    "softmax_grad": (("Y", "Y@G"), ("Out",)),
    "onehot_cross_entropy": (("X", "label"), ("Y",)),
    "onehot_cross_entropy_grad": (("X", "label", "Y@G"), ("Out",)),
    "rowwise_add": (("X", "b"), ("Out",)),
    "sgd": (("param", "grad"), ("param_out",), ("learning_rate",)),
    "fill_zeros_like": (("Src",), ("Dst",)),
    "gaussian_random": ((), ("Out",), ("dims", "mean", "std", "seed")),
    "uniform_random": ((), ("Out",), ("dims", "min", "max", "seed")),
    "gather": (("X", "Index"), ("Out",)),
    "scatter_add_like": (("Like", "Index", "Updates"), ("Out",)),
    "scatter": (("Ref", "Index", "Updates"), ("Out",)),
}.items():
    set_signature(_name, *_sig)
