"""The op framework — imperative op graphs over Scopes.

Capability equivalent of the reference's embryonic "framework" rewrite
(SURVEY.md §2 rows 25-26): Variable/Scope (framework/variable.h:24,
framework/scope.h:36), OperatorBase + registry (framework/operator.h:63,
framework/op_registry.h), autodiff by op-level transposition
(framework/backward.cc:65-109), composite NetOp (operators/net_op.h) and
the dynamic RecurrentOp with per-step scopes (operators/recurrent_op.h:44).

TPU-first divergence: ops carry pure jax.numpy kernels, so the same graph
runs eagerly op-by-op (the reference's Run(scope, dev_ctx) mode) or is
traced once by `net_to_fn` and jit-compiled into a single fused XLA
program — the "operators on a compiler" endpoint the reference stack was
heading toward.
"""

from paddle_tpu.framework.scope import Scope, Variable  # noqa: F401
from paddle_tpu.framework.op import (  # noqa: F401
    GRAD_SUFFIX,
    NetOp,
    OperatorBase,
    create_op,
    grad_op_for,
    net_to_fn,
    register_grad,
    register_op,
)
from paddle_tpu.framework import ops  # noqa: F401
from paddle_tpu.framework.backward import backward  # noqa: F401
from paddle_tpu.framework.recurrent import (  # noqa: F401
    MemoryAttr,
    RecurrentGradientOp,
    RecurrentOp,
)
