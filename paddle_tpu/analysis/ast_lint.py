"""Framework AST lint — registered source passes over paddle_tpu/
(ISSUE 13 tentpole, part c).

Generalizes `check_bench_record.py`'s one-off `obs` mode into a pass
registry the `tools/framework_lint.py` driver runs over the whole
tree. Each pass encodes a rule the repo learned the hard way:

- **jax_import_fence** — the module-scope jax-import allowlist,
  inverted into explicit jax-free zones: obs/ (serving front ends and
  data workers must import telemetry without the device runtime),
  analysis/ (this very lint runs in CI with jax blocked), serving/,
  data/, native/ (TCP front end, feeders, master server — all clean
  today and load-bearing that way), plus the lazily-importing package
  entry points. A top-level `import jax` in a fenced module is a
  regression that only surfaces when a front end box without jaxlib
  falls over.
- **duplicate_dict_keys** — a duplicate key in a dict literal is
  legal Python that silently keeps the LAST value; in the flag
  registry (core/flags.py `_DEFAULTS`) or a bench row dict it is a
  silently-dropped setting. Any dict literal with a repeated constant
  key fails.
- **unfenced_timing** — a function that binds a jitted callable
  (`f = jax.jit(...)` / `...lower().compile()`), calls it between
  clock reads, and never fences (block_until_ready / float / asarray
  / device_get / tolist / item) measures DISPATCH, not execution —
  the async-dispatch timing bug the dispatch-floor campaign
  (ROADMAP 5d) kept re-finding in bench code. Trainer-style
  self-fencing APIs (run_step fetches the loss) are not flagged: the
  pass tracks only locally-bound jit objects.
- **raw_collective_outside_shard_map** — `lax.psum` / `ppermute` /
  `all_to_all` / `all_gather` are only meaningful over a named mesh
  axis, i.e. inside a function that flows into `core.mesh.shard_map`.
  A raw collective in ordinary jit code either crashes on an unbound
  axis name or — under an enclosing pmap/shard_map it was never
  written for — silently reduces over the WRONG axis. The pass roots
  at every function passed to a `*shard_map` call and closes over
  same-file name references and lexical nesting; anything else that
  calls a raw collective fails. A deliberate exception carries a
  `# lint: raw-collective-ok` pragma saying why.
- **unlocked_mutation** — in a class that owns a `self._lock`,
  mutating a container attribute (one assigned `{}`/`[]`/`deque()`/
  `set()` in `__init__`) outside a `with self._lock`/`self._work`
  block races the locked readers. Methods named `*_locked` are
  exempt by the repo's held-by-contract convention; a deliberate
  lock-free site carries a `# lint: unlocked-ok` pragma on the
  statement (or the line above) saying why.

All pure stdlib/ast — no imports of the scanned code.
"""

from __future__ import annotations

import ast
import os

__all__ = ["PASSES", "run_passes", "iter_py_files"]

# ---- jax_import_fence configuration -------------------------------
JAX_FREE_DIRS = (
    "paddle_tpu/obs",
    "paddle_tpu/analysis",
    "paddle_tpu/serving",
    "paddle_tpu/data",
    "paddle_tpu/native",
    "paddle_tpu/decoding",
)
JAX_FREE_FILES = (
    "paddle_tpu/__init__.py",
    "paddle_tpu/__main__.py",
    "paddle_tpu/launch.py",
    "paddle_tpu/testing_faults.py",
    "paddle_tpu/trainer/__init__.py",
    "paddle_tpu/trainer/watchdog.py",
    "paddle_tpu/trainer/events.py",
    "paddle_tpu/core/flags.py",
    "paddle_tpu/core/stat.py",
    "paddle_tpu/core/config.py",
    "paddle_tpu/core/registry.py",
)

_CLOCK_FNS = {"time", "perf_counter", "monotonic"}
_FENCE_FNS = {
    "block_until_ready", "asarray", "float", "result", "device_get",
    "tolist", "item", "ravel",
}
_MUTATORS = {
    "append", "appendleft", "add", "pop", "popleft", "popitem",
    "update", "clear", "extend", "remove", "discard", "setdefault",
    "insert",
}
_CONTAINER_CTORS = {"dict", "list", "set", "deque", "defaultdict",
                    "OrderedDict", "Counter"}
_PRAGMA = "lint: unlocked-ok"

# ---- raw_collective_outside_shard_map configuration ---------------
_RAW_COLLECTIVES = {"psum", "ppermute", "all_to_all", "all_gather",
                    "pmean", "psum_scatter"}
_COLLECTIVE_PRAGMA = "lint: raw-collective-ok"


def iter_py_files(repo_dir: str, subpaths=("paddle_tpu",)):
    for sub in subpaths:
        path = os.path.join(repo_dir, sub)
        if os.path.isfile(path):
            yield path
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def _parse(path: str):
    with open(path) as f:
        src = f.read()
    return ast.parse(src, path), src


def _module_scope(node):
    """Nodes reachable at import time (function bodies are lazy)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield child
        yield from _module_scope(child)


def _call_name(node):
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute):
            return f.attr
        if isinstance(f, ast.Name):
            return f.id
    return None


# ---- pass: jax_import_fence ---------------------------------------
def check_jax_import_fence(repo_dir: str) -> list:
    violations = []
    fenced = []
    for d in JAX_FREE_DIRS:
        full = os.path.join(repo_dir, d)
        if not os.path.isdir(full):
            violations.append(
                f"{d}: fenced jax-free package is missing — a "
                f"load-bearing subsystem was deleted"
            )
            continue
        fenced.extend(
            p for p in iter_py_files(repo_dir, (d,))
        )
    for f in JAX_FREE_FILES:
        full = os.path.join(repo_dir, f)
        if not os.path.exists(full):
            violations.append(
                f"{f}: fenced jax-free module is missing"
            )
            continue
        fenced.append(full)
    for path in fenced:
        rel = os.path.relpath(path, repo_dir)
        tree, _src = _parse(path)
        for node in _module_scope(tree):
            mods = []
            if isinstance(node, ast.Import):
                mods = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                mods = [node.module or ""]
            for m in mods:
                if m.split(".")[0] in ("jax", "jaxlib"):
                    violations.append(
                        f"{rel}:{node.lineno}: imports {m!r} at "
                        f"module scope inside a jax-free fence — "
                        f"use a function-local import; this module "
                        f"must stay importable without the device "
                        f"runtime"
                    )
    return violations


# ---- pass: duplicate_dict_keys ------------------------------------
def check_duplicate_dict_keys(repo_dir: str) -> list:
    violations = []
    for path in iter_py_files(repo_dir):
        rel = os.path.relpath(path, repo_dir)
        tree, _src = _parse(path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Dict):
                continue
            seen = set()
            for k in node.keys:
                if not isinstance(k, ast.Constant):
                    continue
                try:
                    key = k.value
                    if key in seen:
                        violations.append(
                            f"{rel}:{k.lineno}: duplicate key "
                            f"{key!r} in dict literal — Python "
                            f"silently keeps the LAST value; the "
                            f"first registration is dead (flag "
                            f"registry / bench-row field shadowing)"
                        )
                    seen.add(key)
                except TypeError:
                    continue
    return violations


# ---- pass: unfenced_timing ----------------------------------------
def _is_jit_binding(node):
    """`x = jax.jit(...)` / `x = jit(...)` / `x = <...>.compile()`"""
    if not (isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)):
        return None
    name = _call_name(node.value)
    if name in ("jit", "compile"):
        return [
            t.id for t in node.targets if isinstance(t, ast.Name)
        ]
    return None


def check_unfenced_timing(repo_dir: str) -> list:
    violations = []
    subpaths = ("paddle_tpu", "bench.py", "bench_multichip.py",
                "tools")
    for path in iter_py_files(repo_dir, subpaths):
        if os.sep + "traces" + os.sep in path:
            continue
        rel = os.path.relpath(path, repo_dir)
        tree, _src = _parse(path)
        for fn in [n for n in ast.walk(tree)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]:
            jitted = set()
            for n in ast.walk(fn):
                names = _is_jit_binding(n)
                if names:
                    jitted.update(names)
            if not jitted:
                continue
            has_clock = False
            has_fence = False
            calls_jitted = False
            for n in ast.walk(fn):
                nm = _call_name(n)
                if nm in _CLOCK_FNS:
                    has_clock = True
                if nm in _FENCE_FNS:
                    has_fence = True
                if (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Name)
                        and n.func.id in jitted):
                    calls_jitted = True
            if has_clock and calls_jitted and not has_fence:
                violations.append(
                    f"{rel}:{fn.lineno}: {fn.name}() times a jitted "
                    f"callable ({sorted(jitted)}) with no fence "
                    f"(block_until_ready/float/asarray/...) — the "
                    f"clock measures async DISPATCH, not execution"
                )
    return violations


# ---- pass: unlocked_mutation --------------------------------------
def _container_attrs(cls) -> set:
    """Attributes assigned a container literal/ctor in __init__ —
    the state the class's lock exists to guard."""
    out = set()
    for meth in cls.body:
        if not (isinstance(meth, ast.FunctionDef)
                and meth.name == "__init__"):
            continue
        for n in ast.walk(meth):
            if not isinstance(n, ast.Assign):
                continue
            is_container = isinstance(
                n.value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                          ast.ListComp, ast.SetComp)
            ) or _call_name(n.value) in _CONTAINER_CTORS
            if not is_container:
                continue
            for t in n.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                        and t.attr.startswith("_")):
                    out.add(t.attr)
    return out


class _LockedMutationVisitor(ast.NodeVisitor):
    def __init__(self, attrs):
        self.attrs = attrs
        self.depth = 0
        self.hits = []

    def _is_lock_item(self, item):
        e = item.context_expr
        return (
            isinstance(e, ast.Attribute)
            and e.attr in ("_lock", "_work")
            and isinstance(e.value, ast.Name)
            and e.value.id == "self"
        )

    def visit_With(self, node):
        locked = any(self._is_lock_item(i) for i in node.items)
        if locked:
            self.depth += 1
        self.generic_visit(node)
        if locked:
            self.depth -= 1

    def visit_Assign(self, node):
        if self.depth == 0:
            for t in node.targets:
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Attribute)
                        and isinstance(t.value.value, ast.Name)
                        and t.value.value.id == "self"
                        and t.value.attr in self.attrs):
                    self.hits.append(
                        (node.lineno, t.value.attr, "[...]=")
                    )
        self.generic_visit(node)

    def visit_Delete(self, node):
        if self.depth == 0:
            for t in node.targets:
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Attribute)
                        and isinstance(t.value.value, ast.Name)
                        and t.value.value.id == "self"
                        and t.value.attr in self.attrs):
                    self.hits.append(
                        (node.lineno, t.value.attr, "del")
                    )
        self.generic_visit(node)

    def visit_Call(self, node):
        f = node.func
        if (self.depth == 0
                and isinstance(f, ast.Attribute)
                and f.attr in _MUTATORS
                and isinstance(f.value, ast.Attribute)
                and f.value.attr in self.attrs
                and isinstance(f.value.value, ast.Name)
                and f.value.value.id == "self"):
            self.hits.append((node.lineno, f.value.attr, f.attr))
        self.generic_visit(node)


def check_unlocked_mutation(repo_dir: str) -> list:
    violations = []
    for path in iter_py_files(repo_dir):
        rel = os.path.relpath(path, repo_dir)
        tree, src = _parse(path)
        lines = src.splitlines()

        def suppressed(lineno):
            for ln in (lineno, lineno - 1):
                if 1 <= ln <= len(lines) and _PRAGMA in lines[ln - 1]:
                    return True
            return False

        for cls in [n for n in ast.walk(tree)
                    if isinstance(n, ast.ClassDef)]:
            has_lock = any(
                isinstance(n, ast.Assign) and any(
                    isinstance(t, ast.Attribute)
                    and t.attr == "_lock"
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                    for t in n.targets
                )
                for n in ast.walk(cls)
            )
            if not has_lock:
                continue
            attrs = _container_attrs(cls)
            if not attrs:
                continue
            for meth in cls.body:
                if not isinstance(meth, ast.FunctionDef):
                    continue
                if (meth.name == "__init__"
                        or meth.name.endswith("_locked")):
                    continue
                v = _LockedMutationVisitor(attrs)
                v.visit(meth)
                for ln, attr, kind in v.hits:
                    if suppressed(ln):
                        continue
                    violations.append(
                        f"{rel}:{ln}: {cls.name}.{meth.name}() "
                        f"mutates self.{attr} ({kind}) outside "
                        f"`with self._lock` — races the locked "
                        f"readers; hold the lock, use a *_locked "
                        f"helper, or justify with `# {_PRAGMA}`"
                    )
    return violations


# ---- pass: raw_collective_outside_shard_map -----------------------
_FN_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _index_functions(tree):
    """(fn_node -> enclosing fn_node | None) for every def/lambda."""
    parent = {}

    def walk(node, enclosing):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FN_NODES):
                parent[child] = enclosing
                walk(child, child)
            else:
                walk(child, enclosing)

    walk(tree, None)
    return parent


def _is_raw_collective(node):
    """`lax.psum(...)` / `jax.lax.psum(...)` / bare `psum(...)` after
    `from jax.lax import psum`. Bare names are only trusted when the
    attribute chain is absent — a method named .psum on some other
    object still counts (no framework object has one; erring loud)."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _RAW_COLLECTIVES:
        v = f.value
        if (isinstance(v, ast.Name) and v.id == "lax") or (
            isinstance(v, ast.Attribute) and v.attr == "lax"
        ):
            return f.attr
    if isinstance(f, ast.Name) and f.id in _RAW_COLLECTIVES:
        return f.id
    return None


def _shard_map_roots(tree):
    """Function nodes / names handed to a `*shard_map(...)` call:
    direct `shard_map(f, ...)` args, inline lambdas, and
    `partial(f, ...)` wrappers."""
    root_nodes, root_names = set(), set()

    def claim(arg):
        if isinstance(arg, ast.Lambda):
            root_nodes.add(arg)
        elif isinstance(arg, ast.Name):
            root_names.add(arg.id)
        elif (isinstance(arg, ast.Call)
              and _call_name(arg) == "partial" and arg.args):
            claim(arg.args[0])

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node) or ""
        if not name.endswith("shard_map"):
            continue
        for arg in node.args:
            claim(arg)
        for kw in node.keywords:
            if kw.arg == "f":
                claim(kw.value)
    return root_nodes, root_names


def check_raw_collective_outside_shard_map(repo_dir: str) -> list:
    violations = []
    for path in iter_py_files(repo_dir):
        rel = os.path.relpath(path, repo_dir)
        tree, src = _parse(path)
        lines = src.splitlines()

        def suppressed(lineno):
            for ln in (lineno, lineno - 1):
                if (1 <= ln <= len(lines)
                        and _COLLECTIVE_PRAGMA in lines[ln - 1]):
                    return True
            return False

        # any raw collective in the file at all? (cheap early-out)
        hits = [
            (n, _is_raw_collective(n)) for n in ast.walk(tree)
            if _is_raw_collective(n)
        ]
        if not hits:
            continue

        parent = _index_functions(tree)
        root_nodes, root_names = _shard_map_roots(tree)
        by_name = {}
        for fn in parent:
            if not isinstance(fn, ast.Lambda):
                by_name.setdefault(fn.name, []).append(fn)

        covered = set(root_nodes)
        for nm in root_names:
            covered.update(by_name.get(nm, []))
        # fixpoint over two edge kinds: (a) lexical nesting — a def
        # inside a covered function runs under the same shard_map
        # (lax.cond/fori_loop branch callbacks); (b) same-file name
        # REFERENCE from a covered body — `local` calling (or merely
        # passing along) `_ring_body` extends the covered region.
        changed = True
        while changed:
            changed = False
            for fn, enc in parent.items():
                if fn not in covered and enc in covered:
                    covered.add(fn)
                    changed = True
            for fn in list(covered):
                for n in ast.walk(fn):
                    if not isinstance(n, ast.Name):
                        continue
                    for target in by_name.get(n.id, ()):
                        if target not in covered:
                            covered.add(target)
                            changed = True

        def enclosing(node):
            """Innermost fn the call sits in (parents map has only
            fn->fn edges, so walk the tree for the chain)."""
            chain = []

            def down(cur, stack):
                for child in ast.iter_child_nodes(cur):
                    if child is node:
                        chain.extend(stack)
                        return True
                    nxt = stack + [child] if isinstance(
                        child, _FN_NODES
                    ) else stack
                    if down(child, nxt):
                        return True
                return False

            down(tree, [])
            return chain[-1] if chain else None

        for call, kind in hits:
            if suppressed(call.lineno):
                continue
            fn = enclosing(call)
            if fn is not None and fn in covered:
                continue
            where = (
                "module scope" if fn is None else
                (fn.name if not isinstance(fn, ast.Lambda)
                 else f"<lambda>:{fn.lineno}") + "()"
            )
            violations.append(
                f"{rel}:{call.lineno}: raw lax.{kind} in {where} "
                f"which never flows into shard_map — the axis name "
                f"is unbound (or bound to the WRONG mesh axis under "
                f"someone else's pmap); wrap the caller in "
                f"core.mesh.shard_map or justify with "
                f"`# {_COLLECTIVE_PRAGMA}`"
            )
    return violations


PASSES = {
    "jax_import_fence": check_jax_import_fence,
    "duplicate_dict_keys": check_duplicate_dict_keys,
    "unfenced_timing": check_unfenced_timing,
    "unlocked_mutation": check_unlocked_mutation,
    "raw_collective_outside_shard_map":
        check_raw_collective_outside_shard_map,
}


def run_passes(repo_dir: str, names=None) -> list:
    violations = []
    for name in (names or PASSES):
        violations.extend(
            f"[{name}] {v}" for v in PASSES[name](repo_dir)
        )
    return violations
