"""REQUIRED_ROWS — the single source of truth for the bench-record
row lists every lint pass enforces (ISSUE 13 satellite).

Before this module, `tools/check_bench_record.py`'s static AST pass
and its compare pass each hard-coded their own copy of the
north-star/permanent row lists, and the two had already started to
drift (the compare pass matched `mc_preempt_recovery`/`mc_longctx_`
by prefix while the static pass pinned exact names). Every consumer —
check_bench_record's static and compare modes AND the
tools/framework_lint.py driver — now reads THIS module; bench.py's
own `NORTH_STARS` literal stays independent on purpose (the static
pass cross-checks it against TIMELINE_ROWS here, which is exactly the
drift tripwire).

Pure stdlib, importable with jax blocked (the lint discipline).
"""

from __future__ import annotations

# permanent rows the multichip sweep must keep registering (ROADMAP 4 /
# ISSUE 9: elasticity is measured, not assumed; ISSUE 12: the T>=32k
# ring/Ulysses long-context rows are the measured proof the framework
# left the reference's 2017 sequence lengths — deleting one is a
# capability regression, not a cleanup)
REQUIRED_MC_ROWS = (
    "mc_checkpoint_overhead", "mc_preempt_recovery",
    "mc_longctx_ring_t32768", "mc_longctx_ulysses_t32768",
    "mc_longctx_ring_t131072",
)

# rows whose measured record must carry an interleaved A/B verdict
# (ISSUE 12): `fused_speedup` (the dense-vs-flash ratio on the
# longctx/NMT-T128 rows) or an explicit `ab_skipped` reason — the A/B
# cannot silently drop from the record
AB_ROWS = (
    "longctx_selfattn_train_tokens_per_s_t4096",
    "longctx_selfattn_train_tokens_per_s_t8192",
    "nmt_attention_train_tokens_per_s_t128",
)

# serving-fleet rows bench.py must keep registering (ISSUE 16): the
# replica-kill sweep and the verified-cache cold-start comparison.
# Their measured records carry robustness invariants the compare pass
# enforces field-by-field (FLEET_KILL_FIELDS / COLDSTART_FIELDS below)
# — in particular `admitted_lost` must be PRESENT and ZERO: a fleet
# that loses an admitted request during the SIGKILL phase is a
# correctness regression, not a slow row.
REQUIRED_SERVE_ROWS = ("serve_fleet_loadtest", "serve_coldstart")

# fields the serve_fleet_loadtest row's `kill` dict must carry —
# dropping the kill-phase goodput (the whole point of the row) or the
# loss counter fails the record check
FLEET_KILL_FIELDS = ("goodput_rps", "admitted_lost")

# fields the serve_coldstart row must carry: both boot times, so the
# speedup claim stays auditable against its raw measurements
COLDSTART_FIELDS = ("cache_boot_s", "compile_boot_s")

# fleet-aggregated observability fields the serve_fleet_loadtest row
# must carry (ISSUE 17): the fleet p99 merged bucket-wise from the
# replicas' own admitted-latency histograms, the router's independent
# end-to-end p99 of the same requests, and the alert/scrape-failure
# accounting. The two p99s are measured through DIFFERENT pipes
# (replica-side histogram scrape vs router-side wall clock), so their
# agreement — within the tolerances below — is the cross-check that
# the whole scrape→merge→quantile chain is wired to reality.
FLEET_AGG_FIELDS = (
    "fleet_p99_ms", "router_p99_ms", "fleet_alerts",
    "fleet_scrape_errors",
)

# agreement tolerance: the fleet p99 is a bucket-boundary upper bound
# (default buckets step ~2x) and the router p99 includes routing +
# socket time on a loaded CPU CI box, while a mid-sweep replica
# respawn drops pre-kill samples from the scraped side — so the
# ratio bound is generous, with a small absolute floor for the
# sub-millisecond toy-model regime
FLEET_P99_RATIO_TOL = 3.0
FLEET_P99_ABS_TOL_MS = 30.0

# decode dispatch-chain gate (ISSUE 18): the beam-decode north-star
# row must carry a MEASURED chain-depth A/B — the K-token arm's
# dispatch count (counted in the running program / host loop, never
# derived from config), the K=1 baseline's count, and the interleaved
# tokens/s ratio between them. The compare pass trips when the depth
# stops shrinking or the speedup falls under the floor — chain depth
# is the decode bottleneck the nmt_beam4_decode_b32 capture proved
# (7.7x gap over the byte floor), so losing the reduction is a
# regression of the row's whole point. An explicit
# `chain_ab_skipped` reason is the only accepted absence, mirroring
# AB_ROWS' ab_skipped discipline.
DECODE_CHAIN_ROW = "nmt_beam4_decode_tokens_per_s"
DECODE_CHAIN_FIELDS = (
    "dispatch_chain_depth", "dispatch_chain_depth_k1", "chain_speedup",
)
DECODE_CHAIN_SPEEDUP_FLOOR = 1.5

# Transformer-LM north star (ISSUE 19): the LM-train row must carry a
# measured-vs-analytic MFU (the `_nmt_train_flops_per_batch`
# discipline — FLOPs derived from the model config, never from a
# profiler), and the paged-decode row must carry the measured cache
# story: `cache_hit_frac` (prefix tokens read from KV pages vs
# recomputed by re-prefills), `prefix_recompute_bytes_saved` (those
# cached reads priced at the per-token K/V recompute cost — bytes the
# full-recompute baseline would have paid), and `cache_speedup` (the
# interleaved paged-vs-recompute A/B ratio, floored below: if reading
# the cache stops beating recomputing the prefix, the pool is
# overhead, not an optimization). `cache_ab_skipped` is the only
# accepted absence for the A/B fields, mirroring AB_ROWS.
LM_TRAIN_ROW = "lm_train_tokens_per_s"
LM_TRAIN_FIELDS = ("mfu",)
LM_DECODE_ROW = "lm_decode_paged_tokens_per_s"
LM_DECODE_FIELDS = (
    "cache_hit_frac", "prefix_recompute_bytes_saved", "cache_speedup",
)
LM_CACHE_SPEEDUP_FLOOR = 1.1

# Elastic pod-scale sparse CTR (ISSUE 20): the `ctr_bigvocab` row is
# the measured record of the sharded embedding tier's robustness
# story — a SIGKILLed worker mid-epoch with a sharded-table
# generation in flight, recovered from per-shard manifests, plus the
# online-learning hot swap. Its fields are enforced field-by-field:
# `rows_total` / `rows_touched_frac` pin the pod-scale claim (a
# 2**30-row table where only the hot set ever materializes),
# `kill_recover_s` prices the recovery, and the three ZERO fields are
# correctness invariants, not metrics — a lost batch, a retrained
# batch, or a request dropped during the rollout swap is a
# regression even when every throughput number improved.
CTR_BIGVOCAB_ROW = "ctr_bigvocab"
CTR_BIGVOCAB_FIELDS = (
    "rows_total", "rows_touched_frac", "kill_recover_s",
    "batches_lost", "batches_retrained",
    "swap_downtime_requests_lost",
)
# present AND exactly zero, every run
CTR_BIGVOCAB_ZERO_FIELDS = (
    "batches_lost", "batches_retrained",
    "swap_downtime_requests_lost",
)

# north-star rows that must carry the timeline triple (ISSUE 10).
# MUST equal bench.py's NORTH_STARS — check_bench_record's static
# mode enforces the sync.
TIMELINE_ROWS = (
    "resnet50_train_imgs_per_s",
    "nmt_attention_train_tokens_per_s",
    "nmt_attention_train_tokens_per_s_bs512",
    "nmt_attention_train_tokens_per_s_t128",
    "nmt_beam4_decode_tokens_per_s",
    "lm_train_tokens_per_s",
    "lm_decode_paged_tokens_per_s",
    "serve_loadtest",
    "ctr_sparse_step_v_independence",
    "ctr_widedeep_sparse_v_independence",
)

# row-name prefixes that ALSO must carry the timeline triple when they
# appear in a measured record (the parameterized mc_* rows emit
# per-mesh-shape suffixes like `mc_longctx_ring_t32768_sp4`)
TIMELINE_ROW_PREFIXES = ("mc_preempt_recovery", "mc_longctx_")

TIMELINE_FIELDS = (
    "data_wait_frac", "host_overhead_frac", "device_frac",
)


def needs_timeline(metric: str) -> bool:
    """One predicate for both lint passes: must this measured row
    carry the per-step time-attribution triple?"""
    return metric in TIMELINE_ROWS or metric.startswith(
        TIMELINE_ROW_PREFIXES
    )
