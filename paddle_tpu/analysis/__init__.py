"""paddle_tpu.analysis — static analysis of the framework and its
compiled programs (ISSUE 13).

Submodules (all pure stdlib, importable with jax blocked — the same
discipline as paddle_tpu.obs, enforced by the jax_import_fence pass):

- `hlo_text`        compiled-HLO text parser + op classifier (shared
                    with tools/trace_attribution.py)
- `hlo_audit`       compiled-program auditor: donation/aliasing,
                    host-transfer budgets, byte budgets, forbidden-op
                    patterns, driven by tools/traces/audit_budgets.json
- `recompile_guard` jit-cache-miss tracker armed after warmup by the
                    trainer and serving batcher
- `ast_lint`        source-level pass registry (jax-import fence,
                    duplicate dict keys, unfenced timing, unlocked
                    mutation)
- `lock_order`      named-lock instrumentation + inversion detection
                    (the faults shard runs with PADDLE_LOCK_CHECK=1)
- `rows`            REQUIRED_ROWS — the single source of truth for
                    the bench-record row lists the lints enforce

Driver: `python tools/framework_lint.py --all`.
"""

from __future__ import annotations

_SUBMODULES = (
    "ast_lint", "hlo_audit", "hlo_text", "lock_order",
    "recompile_guard", "rows",
)

__all__ = list(_SUBMODULES)


def __getattr__(name):  # PEP 562: lazy submodule access
    if name in _SUBMODULES:
        import importlib

        return importlib.import_module(f"{__name__}.{name}")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
