"""Compiled-program auditor (ISSUE 13 tentpole, part a).

Static checks over captured HLO modules (`tools/traces/*.hlo.txt.gz`
— REAL compiled programs dumped by tools/profile_longctx.py /
bench.write_decode_hlo), turning the repo's hardest-won perf
invariants into machine-checked tripwires:

- **donation/aliasing** — a train-update program that donates its
  parameter/optimizer buffers must show them in the module's
  `input_output_alias` map. A missing alias means XLA kept the input
  buffers live across the step: HBM footprint silently doubles and
  nobody notices until the first OOM at scale.
- **host transfers** — infeed/outfeed/send/recv/host-offload
  custom-calls per step against an explicit budget (default 0: the
  watchdog's "zero extra D2H per batch" pin, generalized to any
  audited program).
- **byte budgets** — total program bytes, the largest single
  materialized tensor, and per-category bytes (the attention category
  is how the flash-vs-dense byte removal was proven) against the
  committed baseline + headroom. A byte *regression* fails the lint —
  the static counterpart of the measured `fused_speedup` A/B.
- **forbidden-op patterns** — no [T,T] score materialization in a
  program captured with `attn_impl="flash"` (any instruction whose
  output carries two adjacent seq_len dims), and no large f32 upcasts
  in programs captured under an AMP policy.

Every check is driven by a per-capture policy from
`tools/traces/audit_budgets.json`; `audit_capture` returns a
machine-readable report (committed as `<stem>.audit.json` next to the
capture) and `tools/framework_lint.py` fails CI when a check fails OR
when a committed report no longer matches the capture it describes.

Pure stdlib — runs with jax blocked, like every analysis/ module.
"""

from __future__ import annotations

import json
import os

from paddle_tpu.analysis import hlo_text as _hlo

AUDIT_SCHEMA = "paddle-tpu-hlo-audit/v1"

# opcodes / custom-call targets that move data across the host
# boundary. `copy` is NOT here: device-internal copies are layout
# traffic; host copies on TPU surface as infeed/outfeed or the
# MoveToHost/MoveToDevice offload annotations.
_HOST_TRANSFER_OPCODES = (
    "infeed", "outfeed", "send", "send-done", "recv", "recv-done",
)
_HOST_OFFLOAD_TOKENS = ("movetohost", "movetodevice")

# byte-budget fields checked against the policy's `*_max` keys
_BYTE_BUDGET_FIELDS = ("total_bytes", "largest_output_bytes")

# adjacent equal dims below this are ignored by the [T,T] check —
# square weight matrices (e.g. [512,512] projections) are not score
# materializations
_TT_MIN_DIM = 1024


def _instructions(path: str):
    text = _hlo.load_text(path)
    return text, list(_hlo.iter_instructions(text.splitlines()))


def check_donation(text: str, policy: dict, report: dict) -> dict:
    """`require_donation` policies: the module's input_output_alias
    map must cover at least `min_aliased_buffers` parameter indices
    (the capture's sibling report records how many buffers the
    program was compiled to donate)."""
    need = int(
        policy.get("min_aliased_buffers")
        or report.get("donated_arg_buffers")
        or 0
    )
    aliased = _hlo.parse_input_output_alias(text)
    ok = len(aliased) >= need
    return {
        "name": "donation",
        "ok": ok,
        "aliased_buffers": len(aliased),
        "min_aliased_buffers": need,
        "detail": (
            "" if ok else
            f"only {len(aliased)} input buffer(s) in "
            f"input_output_alias, expected >= {need} — donated "
            f"params are being copied, HBM footprint doubles"
        ),
    }


def check_host_transfers(instrs, policy: dict) -> dict:
    """Count host-boundary ops against the per-step budget."""
    budget = int(policy.get("host_transfer_budget", 0))
    found = []
    for name, _out, opcode, _ops, line in instrs:
        low = line.lower()
        if opcode in _HOST_TRANSFER_OPCODES:
            found.append(f"{opcode} {name}")
        elif opcode == "custom-call" and any(
            t in low for t in _HOST_OFFLOAD_TOKENS
        ):
            found.append(f"custom-call {name}")
    ok = len(found) <= budget
    return {
        "name": "host_transfers",
        "ok": ok,
        "host_transfer_ops": len(found),
        "budget": budget,
        "ops": found[:8],
        "detail": (
            "" if ok else
            f"{len(found)} host-transfer op(s) vs budget {budget}: "
            f"{found[:4]} — an extra D2H/H2D per step landed in the "
            f"compiled program"
        ),
    }


def check_byte_budgets(attrib: dict, policy: dict) -> list:
    """total_bytes / largest_output_bytes / per-category bytes vs the
    committed `*_max` budgets. Budgets carry the baseline + headroom;
    exceeding one is a byte REGRESSION against the measured record."""
    checks = []
    for field in _BYTE_BUDGET_FIELDS:
        cap = policy.get(field + "_max")
        if cap is None:
            continue
        got = attrib[field]
        ok = got <= cap
        checks.append({
            "name": f"byte_budget.{field}",
            "ok": ok,
            "measured": got,
            "budget": cap,
            "detail": (
                "" if ok else
                f"{field}={got / 1e6:.1f} MB exceeds the committed "
                f"budget {cap / 1e6:.1f} MB — bytes regressed vs the "
                f"baseline this capture was committed with"
            ),
        })
    for cat, cap in (policy.get("category_bytes_max") or {}).items():
        got = attrib["categories"].get(cat, {}).get("bytes", 0)
        ok = got <= cap
        checks.append({
            "name": f"byte_budget.category.{cat}",
            "ok": ok,
            "measured": got,
            "budget": cap,
            "detail": (
                "" if ok else
                f"category {cat!r} bytes {got / 1e6:.1f} MB exceed "
                f"the committed budget {cap / 1e6:.1f} MB"
            ),
        })
    return checks


def check_no_tt_materialization(instrs, policy: dict,
                                report: dict) -> dict:
    """Flash-path programs must not materialize a [T,T] score tensor:
    no instruction OUTPUT may carry two adjacent dims equal to the
    capture's seq_len (>= _TT_MIN_DIM so square weights don't trip
    it). This is the static pin behind PERF round 8's 2147->268 MB
    largest-tensor verdict."""
    t = int(policy.get("seq_len") or report.get("seq_len") or 0)
    offenders = []
    if t >= _TT_MIN_DIM:
        for name, out_shape, _opcode, _ops, _line in instrs:
            for _dt, dims in _hlo.shape_dims(out_shape):
                for a, b in zip(dims, dims[1:]):
                    if a == t and b == t:
                        offenders.append(f"{name} {out_shape}")
                        break
    ok = not offenders
    return {
        "name": "no_tt_materialization",
        "ok": ok,
        "seq_len": t,
        "offenders": offenders[:6],
        "detail": (
            "" if ok else
            f"{len(offenders)} instruction(s) materialize a "
            f"[{t},{t}] tensor on an attn_impl='flash' program: "
            f"{offenders[:3]} — the O(T^2) score matrix is back"
        ),
    }


def check_no_f32_upcast(instrs, policy: dict) -> dict:
    """AMP-policy programs must not grow large f32 tensors out of
    bf16 inputs at fusion boundaries (an upcast fusion silently
    doubles the bytes AMP exists to halve). Only outputs >=
    `f32_upcast_bytes_min` count — scalar/stat upcasts (loss, BN
    statistics) are the point of mixed precision."""
    floor = int(policy.get("f32_upcast_bytes_min", 1 << 20))
    offenders = []
    for name, out_shape, _opcode, operands, _line in instrs:
        dims = _hlo.shape_dims(out_shape)
        if not dims or any(dt != "f32" for dt, _ in dims):
            continue
        if _hlo.shape_bytes(out_shape) < floor:
            continue
        if "bf16[" in operands or "f16[" in operands:
            offenders.append(f"{name} {out_shape}")
    ok = not offenders
    return {
        "name": "no_f32_upcast",
        "ok": ok,
        "floor_bytes": floor,
        "offenders": offenders[:6],
        "detail": (
            "" if ok else
            f"{len(offenders)} fusion(s) upcast bf16 operands into "
            f">= {floor / 1e6:.1f} MB f32 outputs inside an AMP "
            f"program: {offenders[:3]}"
        ),
    }


def audit_capture(hlo_path: str, policy: dict,
                  report: dict = None) -> dict:
    """Run every policy-enabled check on one capture; returns the
    audit report dict (`ok` = all checks passed). `report` is the
    capture's sibling `<stem>.report.json` (auto-loaded when not
    passed) — it carries the shape/donation context the capture
    generator knew at compile time."""
    if report is None:
        stem = hlo_path
        for suf in (".hlo.txt.gz", ".hlo.txt"):
            if stem.endswith(suf):
                stem = stem[: -len(suf)]
                break
        sibling = stem + ".report.json"
        report = {}
        if os.path.exists(sibling):
            with open(sibling) as f:
                report = json.load(f)

    text, instrs = _instructions(hlo_path)
    lines = text.splitlines()
    attrib = _hlo.analyze_hlo(hlo_path, lines=lines)
    checks = []
    if policy.get("require_donation"):
        checks.append(check_donation(text, policy, report))
    if "host_transfer_budget" in policy:
        checks.append(check_host_transfers(instrs, policy))
    checks.extend(check_byte_budgets(attrib, policy))
    if policy.get("forbid_tt_materialization"):
        checks.append(
            check_no_tt_materialization(instrs, policy, report)
        )
    if policy.get("forbid_f32_upcast"):
        checks.append(check_no_f32_upcast(instrs, policy))
    out = {
        "schema": AUDIT_SCHEMA,
        "source": os.path.basename(hlo_path),
        "attn_impl": report.get("attn_impl"),
        "seq_len": report.get("seq_len"),
        "n_instructions": attrib["n_instructions"],
        "total_bytes": attrib["total_bytes"],
        "largest_output_bytes": attrib["largest_output_bytes"],
    }
    # SPMD policies (ISSUE 15): partitioning/replication/collective/
    # schedule checks ride the SAME report + freshness machinery —
    # one <stem>.audit.json per capture, never two writers. The extra
    # keys appear only on SPMD policies so the single-device reports
    # stay byte-identical.
    from paddle_tpu.analysis import spmd_audit as _spmd

    if _spmd.is_spmd_policy(policy):
        spmd_checks, summary = _spmd.spmd_checks(
            text, policy, lines=lines
        )
        checks.extend(spmd_checks)
        out["num_partitions"] = _hlo.num_partitions(text)
        out["collectives"] = summary
    out["ok"] = all(c["ok"] for c in checks)
    out["checks"] = checks
    return out


def load_budgets(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def audit_dir(traces_dir: str, budgets_path: str = None,
              only=None) -> dict:
    """Audit every capture named in the budgets file. Returns
    {stem: report}. A budget entry whose capture file is missing is
    itself a violation (reported as a failed pseudo-check): deleting
    an audited capture must not silently drop its tripwires.
    `only` is an optional predicate on the policy dict — the
    spmd-audit pass uses it to run exactly the SPMD-policy stems."""
    budgets_path = budgets_path or os.path.join(
        traces_dir, "audit_budgets.json"
    )
    budgets = load_budgets(budgets_path)
    out = {}
    for stem, policy in sorted(budgets.items()):
        if stem.startswith("_"):  # "_comment" etc.
            continue
        if only is not None and not only(policy):
            continue
        hlo_path = os.path.join(traces_dir, stem + ".hlo.txt.gz")
        if not os.path.exists(hlo_path):
            hlo_path = os.path.join(traces_dir, stem + ".hlo.txt")
        if not os.path.exists(hlo_path):
            out[stem] = {
                "schema": AUDIT_SCHEMA,
                "source": stem,
                "ok": False,
                "checks": [{
                    "name": "capture_exists",
                    "ok": False,
                    "detail": f"{stem}: capture named in "
                              f"{os.path.basename(budgets_path)} is "
                              f"missing from {traces_dir}",
                }],
            }
            continue
        out[stem] = audit_capture(hlo_path, policy)
    return out


def violations(reports: dict) -> list:
    """Flatten failed checks into lint-style violation strings."""
    out = []
    for stem, rep in sorted(reports.items()):
        for c in rep["checks"]:
            if not c["ok"]:
                out.append(f"{stem}: [{c['name']}] {c['detail']}")
    return out
