"""Lock-order checker (ISSUE 13 tentpole, part d).

The process now holds four families of locks that can meet on one
call path: the metrics registry (obs/metrics.py — taken inside
`registry.event()`, which EVERY subsystem calls), the serving
admission queue (serving/server.py — held while forming batches and
recording breaker verdicts), the async checkpointer's snapshot/error
locks (trainer/async_checkpoint.py), and the flight recorder's ring
lock (obs/flight_recorder.py — fed BY registry.event's tap). A
lock-order inversion between any two of them is a deadlock that only
fires under the faults shard's timing (SIGKILL mid-dispatch, breaker
storm during a dump) — exactly the kind of bug a test suite passes
over 99 times and wedges on the 100th.

Instrumentation: the known locks are created through `named_lock()`.
When checking is DISABLED (the default) that returns a plain
`threading.Lock` — zero overhead, nothing changes. When enabled
(`PADDLE_LOCK_CHECK=1` in the environment at process start, the way
tests/run_suite.sh runs the faults shard, or `enable()` before the
locks are constructed), it returns an instrumented wrapper that
records, per thread, which named locks are held at every acquire and
builds the global acquired-while-holding edge graph. A cycle in that
graph is a lock-order inversion: `violations()` names the locks and
the first stack that created each offending edge, and the faults
shard fails on any.

The wrapper supports the full Lock protocol including use as the
underlying lock of a `threading.Condition` (the admission queue's
`_work` condition wraps the queue lock).

Pure stdlib; importable with jax blocked.
"""

from __future__ import annotations

import os
import threading
import traceback

__all__ = [
    "named_lock", "enable", "disable", "enabled", "violations",
    "reset", "edges", "LockOrderMonitor", "InstrumentedLock",
]


class LockOrderMonitor:
    """Collects held-set edges from every instrumented lock."""

    def __init__(self):
        self._meta = threading.Lock()  # guards the edge graph only
        # (held_name, acquired_name) -> short stack of first sighting
        self._edges: dict = {}
        self._tls = threading.local()

    # -- per-thread held set ---------------------------------------
    def _held(self) -> list:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    def on_acquired(self, name: str) -> None:
        held = self._held()
        new_edges = [
            (h, name) for h in held
            if h != name and (h, name) not in self._edges
        ]
        if new_edges:
            stack = "".join(traceback.format_stack(limit=8)[:-2])
            with self._meta:
                for e in new_edges:
                    self._edges.setdefault(e, stack)
        held.append(name)

    def on_released(self, name: str) -> None:
        held = self._held()
        # remove the most recent acquisition of `name` (locks are
        # typically released LIFO but the protocol does not require
        # it — Condition.wait releases out of order)
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                break

    # -- reporting --------------------------------------------------
    def edges(self) -> dict:
        with self._meta:
            return dict(self._edges)

    def violations(self) -> list:
        """Every cycle in the edge graph, reported as one violation
        per cycle (deduped by cycle set)."""
        graph: dict = {}
        edge_map = self.edges()
        for (a, b) in edge_map:
            graph.setdefault(a, set()).add(b)

        seen_cycles = set()
        out = []

        def dfs(start, node, path):
            for nxt in graph.get(node, ()):
                if nxt == start:
                    cyc = frozenset(path)
                    if cyc not in seen_cycles:
                        seen_cycles.add(cyc)
                        order = path + [start]
                        stacks = {
                            f"{x}->{y}": edge_map.get((x, y), "")
                            for x, y in zip(order, order[1:])
                        }
                        out.append({
                            "cycle": order,
                            "detail": (
                                "lock-order inversion: "
                                + " -> ".join(order)
                                + " (each lock acquired while "
                                  "holding the previous)"
                            ),
                            "stacks": stacks,
                        })
                elif nxt not in path:
                    dfs(start, nxt, path + [nxt])

        for node in sorted(graph):
            dfs(node, node, [node])
        return out

    def reset(self) -> None:
        with self._meta:
            self._edges = {}


class InstrumentedLock:
    """threading.Lock wrapper reporting acquisitions to a monitor.
    Condition-compatible: acquire/release/locked plus the context
    protocol (Condition probes ownership via acquire(False))."""

    def __init__(self, name: str, monitor: LockOrderMonitor,
                 lock=None):
        self.name = name
        self._monitor = monitor
        self._lock = lock if lock is not None else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._lock.acquire(blocking, timeout)
        if got:
            # record AFTER a successful acquire (a failed
            # non-blocking probe — Condition._is_owned — held
            # nothing, so it must not create an edge)
            self._monitor.on_acquired(self.name)
        return got

    def release(self) -> None:
        self._monitor.on_released(self.name)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<InstrumentedLock {self.name!r} {self._lock!r}>"


_MONITOR = LockOrderMonitor()
_ENABLED = bool(os.environ.get("PADDLE_LOCK_CHECK"))


def enable() -> LockOrderMonitor:
    """Turn instrumentation on for locks created AFTER this call.
    (Module singletons build their locks at import time — to cover
    them, set PADDLE_LOCK_CHECK=1 in the environment instead, as the
    faults shard does.)"""
    global _ENABLED
    _ENABLED = True
    return _MONITOR


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def enabled() -> bool:
    return _ENABLED


def named_lock(name: str):
    """The known-lock constructor: a plain threading.Lock when
    checking is off (the production path — zero overhead), an
    instrumented one when on."""
    if not _ENABLED:
        return threading.Lock()
    return InstrumentedLock(name, _MONITOR)


def violations() -> list:
    return _MONITOR.violations()


def edges() -> dict:
    return _MONITOR.edges()


def reset() -> None:
    _MONITOR.reset()
