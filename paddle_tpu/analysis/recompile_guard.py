"""Recompile guard — a jit-cache-miss tracker (ISSUE 13 tentpole,
part b).

The dispatch-floor work (ROADMAP 5d) and the bucketed serving program
cache both rest on one assumption: in steady state, the hot loop's
jitted program NEVER retraces. A silent retrace (a Python-object key
churning, a float passed where a traced operand should be, a cache
falling out from under a weakref) costs seconds of compile per
occurrence and shows up in no test — only as an unexplained latency
cliff in production. The guard turns it into a hard failure:

    guard = RecompileGuard("train_step")

    @jax.jit
    def step(params, feed):
        guard.note(params, feed)   # runs at TRACE time only
        ...

    # ... warmup: every expected shape traced once ...
    guard.arm(strict=True)
    # any further trace => violation (strict: RecompileError raised
    # from inside the trace, failing the dispatch loudly)

`note()` is a plain Python call in the traced function's body, so it
executes exactly when jax (re)traces — zero cost on the cached
dispatch path. Each guard also counts traces while disarmed (the
warmup compile count, visible in `obs` metrics as
`recompile_guard.traces{label=...}`).

The trainer (SGD, via the `recompile_guard` flag) and the serving
batcher (`InferenceServer.arm_recompile_guard`) arm their guards
after warmup; `assert_steady_state()` is the bench-harness hook that
fails a measured row whose hot loop retraced.

Pure stdlib (the traced operands are only used via getattr-probed
shape/dtype), importable with jax blocked.
"""

from __future__ import annotations

import threading
import time
import weakref

__all__ = [
    "RecompileError", "RecompileGuard", "all_guards", "arm_all",
    "disarm_all", "all_violations", "assert_steady_state",
]


class RecompileError(RuntimeError):
    """A jitted hot loop retraced while its guard was armed."""


_GUARDS: "weakref.WeakSet[RecompileGuard]" = weakref.WeakSet()
_GUARDS_LOCK = threading.Lock()


def _signature(args, kwargs):
    """Shape/dtype signature of the traced operands — at trace time
    these are jax tracers, whose shape/dtype are ordinary attributes
    (no jax import needed)."""

    def leaf(x):
        s = getattr(x, "shape", None)
        d = getattr(x, "dtype", None)
        if s is None and d is None:
            return type(x).__name__
        return (tuple(s) if s is not None else None, str(d))

    def walk(x):
        if isinstance(x, dict):
            return tuple(
                (k, walk(v)) for k, v in sorted(x.items())
            )
        if isinstance(x, (list, tuple)):
            return tuple(walk(v) for v in x)
        return leaf(x)

    return walk(list(args) + sorted(kwargs.items()))


class RecompileGuard:
    """One guard per jitted program family (a TrainStep, a decode
    cache, a merged serving forward). Thread-safe."""

    def __init__(self, label: str):
        self.label = label
        self._lock = threading.Lock()
        self._armed = False
        self._strict = False
        self.traces = 0          # total traces ever
        self.warmup_traces = 0   # traces while disarmed
        self.violations: list = []
        with _GUARDS_LOCK:
            _GUARDS.add(self)

    # -- called from INSIDE the traced function ---------------------
    def note(self, *args, **kwargs) -> None:
        """Record one trace. Passing the traced operands gives the
        violation record a shape signature to name the retrace."""
        with self._lock:
            self.traces += 1
            armed, strict = self._armed, self._strict
            if not armed:
                self.warmup_traces += 1
        self._count_metric()
        if not armed:
            return
        try:
            sig = _signature(args, kwargs)
        except Exception:
            sig = "<unavailable>"
        rec = {
            "label": self.label,
            "ts": round(time.time(), 6),
            "signature": repr(sig),
            "trace_n": self.traces,
        }
        with self._lock:
            self.violations.append(rec)
        self._report_violation(rec)
        if strict:
            raise RecompileError(
                f"{self.label}: jitted hot loop retraced in steady "
                f"state (trace #{self.traces}, signature {sig!r}) — "
                f"a cached program was expected; something in the "
                f"call is churning the jit cache"
            )

    # -- lifecycle --------------------------------------------------
    def arm(self, strict: bool = False) -> "RecompileGuard":
        with self._lock:
            self._armed = True
            self._strict = strict
        return self

    def disarm(self) -> "RecompileGuard":
        with self._lock:
            self._armed = False
        return self

    @property
    def armed(self) -> bool:
        return self._armed

    def reset(self) -> None:
        with self._lock:
            self.violations = []

    # -- reporting (lazy obs imports: analysis stays stdlib-clean
    # and usable before the metrics registry exists) ----------------
    def _count_metric(self) -> None:
        try:
            from paddle_tpu.obs import metrics as _m

            _m.get_registry().counter("recompile_guard.traces").inc(
                label=self.label
            )
        except Exception:
            pass

    def _report_violation(self, rec: dict) -> None:
        try:
            from paddle_tpu.obs import metrics as _m

            reg = _m.get_registry()
            reg.counter("recompile_guard.violations").inc(
                label=self.label
            )
            reg.event("recompile", **rec)
        except Exception:
            pass
        try:
            from paddle_tpu.obs import flight_recorder as _f

            _f.maybe_dump("recompile", **rec)
        except Exception:
            pass


def all_guards() -> list:
    with _GUARDS_LOCK:
        return sorted(_GUARDS, key=lambda g: g.label)


def arm_all(strict: bool = False, label_prefix: str = "") -> list:
    armed = []
    for g in all_guards():
        if g.label.startswith(label_prefix):
            armed.append(g.arm(strict=strict))
    return armed


def disarm_all(label_prefix: str = "") -> None:
    for g in all_guards():
        if g.label.startswith(label_prefix):
            g.disarm()


def all_violations() -> list:
    out = []
    for g in all_guards():
        out.extend(g.violations)
    return out


def assert_steady_state(label_prefix: str = "") -> None:
    """Raise RecompileError if any (matching) guard recorded a
    violation — the bench-harness/CI hook."""
    bad = [
        v for v in all_violations()
        if v["label"].startswith(label_prefix)
    ]
    if bad:
        labels = sorted({v["label"] for v in bad})
        raise RecompileError(
            f"{len(bad)} steady-state retrace(s) recorded on "
            f"guard(s) {labels}: {bad[:3]}"
        )
