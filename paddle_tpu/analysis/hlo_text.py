"""Compiled-HLO text parsing + op classification (pure stdlib).

The single parser behind BOTH measurement tools and the static
auditor (ISSUE 13): `tools/trace_attribution.py` uses it to attribute
a captured program's bytes to categories, and
`paddle_tpu/analysis/hlo_audit.py` uses the same instruction stream
to enforce donation/aliasing, host-transfer budgets, byte budgets and
forbidden-op patterns. One parser means the audit argues about the
exact bytes the perf record argues about — the two can never drift.

Input is the `*.hlo.txt[.gz]` capture format written by
tools/profile_longctx.py / bench.write_decode_hlo: the
`compiled.as_text()` dump of a REAL compiled program
(`is_scheduled=true`, fusions closed), NOT pre-optimization stable
HLO. Bytes are charged at fusion boundaries — exactly the tensors
that cross HBM.

No jax anywhere in this module: the audits must run in CI shards and
serving front ends with the device runtime blocked (the obs-lint
discipline, extended to analysis/).
"""

from __future__ import annotations

import gzip
import json
import os
import re
from collections import defaultdict

CATEGORIES = (
    "conv", "gemm", "attention", "bn_elementwise", "layout",
    "collective", "infeed", "other",
)

_COLLECTIVE_TOKENS = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective", "send", "recv",
)
_LAYOUT_NAME_PREFIXES = (
    "copy", "transpose", "bitcast", "reshape", "convert_element_type",
    "slice-start", "slice-done", "dynamic_slice", "dynamic-update",
    "pad",
)
# attention bucketing (ISSUE 12): ops under the attention
# named_scopes (parallel/ring.py stamps dense_attention /
# flash_attention / ring/ulysses scopes into HLO metadata op_name,
# which trace events carry in long_name/tf_op) and Pallas/Mosaic
# custom-call attention kernels
_ATTENTION_TOKENS = (
    "dense_attention", "flash_attention", "ring_attention",
    "ulysses_attention", "flash_att",
)
_ATTENTION_CUSTOM_CALL_TOKENS = ("mosaic", "tpu_custom_call")


def classify(name: str, category: str, long_name: str) -> str:
    """Map one device op to a report category. `category` is XLA's own
    `hlo_category` arg (or the HLO opcode in hlo-module captures);
    `long_name` the HLO text incl. metadata (both may be '')."""
    n = name.lower()
    c = (category or "").lower()
    ln = (long_name or "").lower()
    if any(t in n or t in c for t in _COLLECTIVE_TOKENS):
        return "collective"
    if "infeed" in n or "outfeed" in n or "infeed" in c or "outfeed" in c:
        return "infeed"
    # attention BEFORE conv/gemm: the attention scopes' dots/fusions
    # must land here, and a Pallas flash kernel is a custom-call whose
    # only category hint is its target/metadata
    if any(t in n or t in ln for t in _ATTENTION_TOKENS):
        return "attention"
    if ("custom-call" in c or "custom_call" in c
            or n.startswith("custom")) and any(
        t in n or t in ln for t in _ATTENTION_CUSTOM_CALL_TOKENS
    ):
        return "attention"
    if "convolution" in c or "convolution(" in ln or n.startswith("conv_"):
        return "conv"
    if ("dot(" in ln or "dot " in ln or "gemm" in n or "gemm" in c
            or c == "dot" or n.startswith("dot")):
        return "gemm"
    # layout/data-movement BEFORE elementwise: convert_element_type is
    # a dtype/layout relayout even though XLA categorizes it
    # "non-fusion elementwise", and the async slice-start/done pairs
    # are HBM<->scratch staging copies
    if (c in ("copy", "copy-start", "copy-done", "data formatting",
              "dynamic-slice", "async-start", "async-done")
            or n.startswith(_LAYOUT_NAME_PREFIXES)):
        return "layout"
    if ("fusion" in c or "elementwise" in c or "reduce" in c
            or "scatter" in c or "select-and-scatter" in c
            or n.startswith(("fusion", "add", "multiply", "reduce",
                             "select_and_scatter", "broadcast"))):
        return "bn_elementwise"
    return "other"


_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
    "f16": 2, "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8,
    "u64": 8, "f64": 8, "c64": 8, "c128": 16, "f8e4m3fn": 1,
    "f8e5m2": 1,
}
_SHAPE_RE = re.compile(
    r"\b(" + "|".join(_DTYPE_BYTES) + r")\[([0-9,]*)\]"
)
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*"      # instruction name
    r"((?:\([^()]*\))|\S+)\s+"                   # output shape (or tuple;
    # tuple shapes nest no parens but DO carry /*index=N*/ comments
    # from 6 elements up — a [^=] shape matcher loses every big-carry
    # while loop and tuple-form all-to-all)
    r"([\w\-]+)\("                               # opcode
)
# instructions that move no HBM bytes of their own: reads are charged
# at the consuming op, parameters/constants at their users, tuple
# plumbing is free
_FREE_OPCODES = {
    "parameter", "constant", "get-tuple-element", "tuple", "bitcast",
    "after-all", "partition-id", "replica-id", "iota",
}


def shape_bytes(text: str) -> int:
    """Total bytes of every dtype[shape] occurrence in `text` (tuples
    sum their elements; scalars count their dtype size)."""
    total = 0
    for dt, dims in _SHAPE_RE.findall(text):
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * _DTYPE_BYTES[dt]
    return total


def shape_dims(text: str) -> list:
    """Every dtype[shape] occurrence in `text` as (dtype, [dims])."""
    out = []
    for dt, dims in _SHAPE_RE.findall(text):
        out.append(
            (dt, [int(d) for d in dims.split(",")] if dims else [])
        )
    return out


def operand_section(rest: str) -> str:
    """`rest` starts right after the opcode's '(' — return the operand
    text up to its matching ')' (attributes/metadata excluded)."""
    depth = 1
    for i, ch in enumerate(rest):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return rest[:i]
    return rest


def load_text(path: str) -> str:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        return f.read()


def iter_instructions(lines):
    """Yield (name, out_shape, opcode, operands, line) for every
    top-level instruction — instructions inside %fused_computation
    bodies are skipped (they live in registers/scratch; only fusion
    boundaries cross HBM). Other non-entry computations (while bodies,
    reduce appliers) are yielded once — callers needing loop-trip
    semantics must handle `while` opcodes themselves."""
    in_fused = False
    depth_at_fused = 0
    brace_depth = 0
    for line in lines:
        stripped = line.strip()
        opens = line.count("{") - line.count("}")
        if not in_fused and (
            stripped.startswith("%fused_computation")
            or stripped.startswith("fused_computation")
        ) and "{" in line:
            in_fused = True
            depth_at_fused = brace_depth
        brace_depth += opens
        if in_fused:
            if brace_depth <= depth_at_fused:
                in_fused = False
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, out_shape, opcode = m.groups()
        rest = line[m.end():]
        yield name, out_shape, opcode, operand_section(rest), line


def module_header(text: str) -> str:
    """The `HloModule ...` header line (alias map, entry layout)."""
    for line in text.splitlines():
        if line.startswith("HloModule"):
            return line
    return ""


_ALIAS_ENTRY_RE = re.compile(r"\{[\d,\s]*\}:\s*\((\d+)")


def parse_input_output_alias(text: str) -> list:
    """Parameter indices appearing in the module's
    `input_output_alias` map — the donated/aliased input buffers.
    Empty list = the program aliases nothing: every parameter is a
    live extra buffer for the whole step (the donation regression the
    auditor exists to catch). The map nests braces
    (`{ {0}: (0, {}, may-alias), ... }`), so the span is found by
    brace balancing, not regex."""
    header = module_header(text)
    start = header.find("input_output_alias={")
    if start < 0:
        return []
    i = start + len("input_output_alias=")
    depth = 0
    for j in range(i, len(header)):
        if header[j] == "{":
            depth += 1
        elif header[j] == "}":
            depth -= 1
            if depth == 0:
                body = header[i + 1:j]
                return sorted({
                    int(g) for g in _ALIAS_ENTRY_RE.findall(body)
                })
    return []


# categories with a positive token/opcode signal; the fallback buckets
# (bn_elementwise / layout / other) are WEAK — a weak op whose operand
# was produced by an attention op inherits "attention" (dataflow
# closure). XLA's backward-pass fission drops metadata from some
# fusions (e.g. the [T,T] softmax-backward convert fusions in the
# dense longctx capture carry no op_name at all), and without the
# closure those score-matrix bytes silently leak into bn_elementwise.
_STRONG_CATEGORIES = ("collective", "infeed", "attention", "conv",
                      "gemm")
_OPERAND_NAME_RE = re.compile(r"%([\w.\-]+)")


def analyze_hlo(path: str, top: int = 10, lines=None) -> dict:
    """Static byte attribution of one compiled HLO module (the
    `*.hlo.txt[.gz]` captures): each top-level instruction is charged
    its output + operand bytes — at fusion granularity, exactly the
    tensors that cross HBM — and bucketed with the same classify() as
    the trace path (plus the weak-op dataflow inheritance above).
    Instructions inside %fused_computation bodies are skipped (they
    live in registers/scratch); other non-entry computations (while
    bodies, reduce appliers) count once, with the while-instruction
    count reported so the caveat is visible. `lines` lets a caller
    that already loaded the capture (hlo_audit runs several checks
    over one module) skip the second read+decompress."""
    if lines is None:
        lines = load_text(path).splitlines()

    cat_bytes = defaultdict(int)
    cat_ops = defaultdict(int)
    by_name = {}
    prod_cat: dict = {}  # instruction -> category (dataflow closure)
    total = 0
    n_instr = 0
    n_while = 0
    largest_output = 0
    inherited = 0
    for name, out_shape, opcode, operands, line in iter_instructions(
        lines
    ):
        if opcode in _FREE_OPCODES:
            continue
        n_instr += 1
        if opcode == "while":
            n_while += 1
        out_bytes = shape_bytes(out_shape)
        largest_output = max(largest_output, out_bytes)
        nbytes = out_bytes + shape_bytes(operands)
        cat = classify(name, opcode, line)
        if cat not in _STRONG_CATEGORIES:
            for op_name in _OPERAND_NAME_RE.findall(operands):
                if prod_cat.get(op_name) == "attention":
                    cat = "attention"
                    inherited += 1
                    break
        prod_cat[name] = cat
        cat_bytes[cat] += nbytes
        cat_ops[cat] += 1
        total += nbytes
        rec = by_name.setdefault(
            name, {"name": name, "category": cat, "bytes": 0,
                   "count": 0},
        )
        rec["bytes"] += nbytes
        rec["count"] += 1

    if n_instr == 0:
        raise SystemExit(f"{path}: no HLO instructions found")

    categories = {}
    for cat in CATEGORIES:
        if cat_ops.get(cat, 0) == 0:
            continue
        categories[cat] = {
            "bytes": cat_bytes[cat],
            "share": round(cat_bytes[cat] / total, 4) if total else 0.0,
            "n_ops": cat_ops[cat],
        }
    top_hlos = sorted(by_name.values(), key=lambda r: -r["bytes"])[:top]
    for r in top_hlos:
        r["share_of_bytes"] = round(r["bytes"] / total, 4) if total \
            else 0.0

    report = {
        "source": os.path.basename(path),
        "capture_kind": "hlo_module",
        "total_bytes": total,
        "n_instructions": n_instr,
        # while bodies are charged ONCE; a loopy capture must fold its
        # trip count in by hand (the decode analysis multiplies by
        # max_len) — 0 means the byte table is exact
        "while_instructions": n_while,
        # the footprint pin: the biggest single tensor the program
        # materializes (dense longctx: the [B,H,T,T] scores; flash:
        # a [B,H,T,block_k] tile)
        "largest_output_bytes": largest_output,
        "attention_inherited_ops": inherited,
        "shares": {c: v["share"] for c, v in categories.items()},
        "categories": categories,
        "top_hlos": top_hlos,
    }
    stem = path
    for suf in (".hlo.txt.gz", ".hlo.txt"):
        if stem.endswith(suf):
            stem = stem[: -len(suf)]
            break
    sibling = stem + ".report.json"
    if os.path.exists(sibling):
        with open(sibling) as f:
            report["capture_report"] = json.load(f)
    return report


# ==== SPMD parsing (ISSUE 15) ======================================
# Partitioned-module structure the SPMD auditor
# (analysis/spmd_audit.py) and the runtime multi-chip gate
# (parallel/dp.py assert_collectives) argue about: `sharding={...}`
# annotations, the collective instructions with their replica groups /
# channel ids / permute pairs, and which computation each instruction
# lives in (collectives appear inside while bodies and conditional
# branch regions — the ring attention hop is a collective-permute
# inside a branch inside the ring while loop).

# canonical collective opcodes; async pairs normalize to the base kind
# and only the -start half is yielded (the -done moves no new bytes)
COLLECTIVE_KINDS = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast",
)

_NUM_PARTITIONS_RE = re.compile(r"\bnum_partitions=(\d+)\b")
_CHANNEL_ID_RE = re.compile(r"\bchannel_id=(\d+)\b")
_GROUP_LIST_RE = re.compile(r"\{([0-9,\s]*)\}")
_IOTA_GROUPS_RE = re.compile(
    r"\[([0-9,]+)\]<=\[([0-9,]+)\]"
)
# computation definition lines: `%name (params...) -> shape {` with an
# optional leading ENTRY; fused computations match too (collectives
# never fuse today, but the walker must not silently lose one if a
# future runtime puts them there)
_COMP_DEF_RE = re.compile(r"^\s*(?:ENTRY\s+)?%([\w.\-]+)\s*\(")


def num_partitions(text: str) -> int:
    """The module header's partition count — 1 (or absent) means the
    program was NOT SPMD-partitioned; an audited sharded capture with
    num_partitions=1 silently ran single-device."""
    m = _NUM_PARTITIONS_RE.search(module_header(text))
    return int(m.group(1)) if m else 1


def _balanced_braces(text: str, start: int) -> str:
    """`text[start]` is '{' — return the body between it and its
    matching '}' (exclusive)."""
    depth = 0
    for j in range(start, len(text)):
        if text[j] == "{":
            depth += 1
        elif text[j] == "}":
            depth -= 1
            if depth == 0:
                return text[start + 1:j]
    return text[start + 1:]


def _split_top_level(body: str) -> list:
    """Split `a, b, c` at depth-0 commas (sub-braces kept intact)."""
    parts, depth, cur = [], 0, []
    for ch in body:
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    tail = "".join(cur).strip()
    if tail:
        parts.append(tail)
    return parts


def _parse_sharding_body(body: str) -> dict:
    """`body` is the text INSIDE the annotation's outer braces."""
    body = body.strip()
    if body.startswith("{"):
        # tuple sharding: one `{...}` element per tuple leaf, in order
        return {
            "kind": "tuple",
            "elements": [
                _parse_sharding_body(_balanced_braces(e, e.find("{")))
                for e in _split_top_level(body)
            ],
        }
    if body == "replicated":
        return {"kind": "replicated"}
    if body == "manual":
        return {"kind": "manual"}
    if body.startswith("maximal"):
        m = re.search(r"device=(\d+)", body)
        return {
            "kind": "maximal",
            "device": int(m.group(1)) if m else 0,
        }
    if body.startswith("devices="):
        m = re.match(r"devices=\[([0-9,]+)\]", body)
        tile = [int(d) for d in m.group(1).split(",")] if m else []
        return {
            "kind": "devices",
            "tile": tile,
            "last_tile_dim_replicate":
                "last_tile_dim_replicate" in body,
        }
    return {"kind": "other", "raw": body}


def parse_sharding(line: str):
    """The `sharding={...}` annotation on one instruction line, as a
    dict — kind 'replicated' | 'maximal' | 'devices' (with the tile
    assignment dims) | 'tuple' (per-leaf elements) | 'manual' — or
    None when the line carries no annotation."""
    i = line.find("sharding=")
    if i < 0:
        return None
    j = line.find("{", i)
    if j < 0:
        return None
    return _parse_sharding_body(_balanced_braces(line, j))


def sharding_is_replicated(sh: dict) -> bool:
    """True when the annotation pins FULL bytes on every device: plain
    replicated, maximal (one device holds the whole tensor), or a
    devices= tiling whose non-replication dims are all 1."""
    if sh is None:
        return False
    kind = sh.get("kind")
    if kind in ("replicated", "maximal"):
        return True
    if kind == "devices":
        tile = sh.get("tile") or []
        if sh.get("last_tile_dim_replicate"):
            tile = tile[:-1]
        return all(d == 1 for d in tile)
    return False


def iter_computations(lines):
    """Yield (computation_name, line) for every line, tracking which
    computation definition the walker is inside (fused bodies
    included — unlike iter_instructions, nothing is skipped)."""
    comp = ""
    for line in lines:
        m = _COMP_DEF_RE.match(line)
        if m and "->" in line and line.rstrip().endswith("{"):
            comp = m.group(1)
        yield comp, line


def iter_shardings(lines):
    """Yield (name, out_shape, sharding, computation) for every
    instruction carrying a `sharding={...}` annotation, across ALL
    computations (entry params, outputs, copies)."""
    for comp, line in iter_computations(lines):
        if "sharding=" not in line:
            continue
        m = _INSTR_RE.match(line)
        if not m:
            # parameters have no '(': `%p = f32[8]{0} parameter(0), ...`
            # — they DO match _INSTR_RE (opcode `parameter(`); anything
            # else with a sharding but no instruction form is skipped
            continue
        name, out_shape, _opcode = m.groups()
        sh = parse_sharding(line)
        if sh is not None:
            yield name, out_shape, sh, comp


def _parse_replica_groups(line: str):
    """`replica_groups={{0,1},{2,3}}` -> [[0,1],[2,3]]; the iota form
    `replica_groups=[2,4]<=[8]` expands row-major when untransposed
    (the transposed form is kept raw — no capture uses it today)."""
    i = line.find("replica_groups=")
    if i < 0:
        return []
    rest = line[i + len("replica_groups="):]
    if rest.startswith("{"):
        body = _balanced_braces(rest, 0)
        return [
            [int(x) for x in g.split(",") if x.strip()]
            for g in _GROUP_LIST_RE.findall("{" + body + "}")
        ]
    m = _IOTA_GROUPS_RE.match(rest)
    if m:
        dims = [int(d) for d in m.group(1).split(",")]
        n = 1
        for d in [int(d) for d in m.group(2).split(",")]:
            n *= d
        if len(dims) == 2 and dims[0] * dims[1] == n \
                and not rest[m.end():m.end() + 1] == "T":
            return [
                list(range(r * dims[1], (r + 1) * dims[1]))
                for r in range(dims[0])
            ]
    return []


def _parse_pairs(line: str):
    """`source_target_pairs={{0,1},{1,2}}` -> [(0,1),(1,2)]."""
    i = line.find("source_target_pairs=")
    if i < 0:
        return []
    body = _balanced_braces(line, line.find("{", i))
    out = []
    for g in _GROUP_LIST_RE.findall("{" + body + "}"):
        xs = [int(x) for x in g.split(",") if x.strip()]
        if len(xs) == 2:
            out.append((xs[0], xs[1]))
    return out


def parse_collectives(lines) -> list:
    """Every collective instruction in the module, across ALL
    computations (while bodies, conditional branches, fusion bodies),
    as dicts:

      {name, kind, opcode, out_shape, bytes, channel_id,
       replica_groups, source_target_pairs, computation, operands}

    `kind` normalizes async pairs (`all-gather-start` -> all-gather);
    only the -start half is recorded. `bytes` is the instruction's
    output bytes — for a tuple-shaped all-to-all the sum over
    elements — i.e. what one program execution moves through the
    fabric per device. `channel_id` is None for unchanneled
    (replica-mode) collectives."""
    out = []
    for comp, line in iter_computations(lines):
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, out_shape, opcode = m.groups()
        base = opcode
        for suf in ("-start", "-done"):
            if base.endswith(suf):
                base = base[: -len(suf)]
        if base not in COLLECTIVE_KINDS:
            continue
        if opcode.endswith("-done"):
            continue
        cm = _CHANNEL_ID_RE.search(line)
        rest = line[m.end():]
        out.append({
            "name": name,
            "kind": base,
            "opcode": opcode,
            "out_shape": out_shape,
            "bytes": shape_bytes(out_shape),
            "channel_id": int(cm.group(1)) if cm else None,
            "replica_groups": _parse_replica_groups(line),
            "source_target_pairs": _parse_pairs(line),
            "computation": comp,
            "operands": operand_section(rest),
        })
    return out


def collective_summary(collectives) -> dict:
    """Aggregate byte/count view of `parse_collectives` output — the
    numbers the collective byte budget is enforced against."""
    by_kind: dict = {}
    total = 0
    largest = 0
    largest_name = ""
    for c in collectives:
        k = by_kind.setdefault(c["kind"], {"count": 0, "bytes": 0})
        k["count"] += 1
        k["bytes"] += c["bytes"]
        total += c["bytes"]
        if c["bytes"] > largest:
            largest, largest_name = c["bytes"], c["name"]
    return {
        "count": len(collectives),
        "total_bytes": total,
        "largest_bytes": largest,
        "largest": largest_name,
        "by_kind": by_kind,
    }
