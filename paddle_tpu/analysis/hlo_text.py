"""Compiled-HLO text parsing + op classification (pure stdlib).

The single parser behind BOTH measurement tools and the static
auditor (ISSUE 13): `tools/trace_attribution.py` uses it to attribute
a captured program's bytes to categories, and
`paddle_tpu/analysis/hlo_audit.py` uses the same instruction stream
to enforce donation/aliasing, host-transfer budgets, byte budgets and
forbidden-op patterns. One parser means the audit argues about the
exact bytes the perf record argues about — the two can never drift.

Input is the `*.hlo.txt[.gz]` capture format written by
tools/profile_longctx.py / bench.write_decode_hlo: the
`compiled.as_text()` dump of a REAL compiled program
(`is_scheduled=true`, fusions closed), NOT pre-optimization stable
HLO. Bytes are charged at fusion boundaries — exactly the tensors
that cross HBM.

No jax anywhere in this module: the audits must run in CI shards and
serving front ends with the device runtime blocked (the obs-lint
discipline, extended to analysis/).
"""

from __future__ import annotations

import gzip
import json
import os
import re
from collections import defaultdict

CATEGORIES = (
    "conv", "gemm", "attention", "bn_elementwise", "layout",
    "collective", "infeed", "other",
)

_COLLECTIVE_TOKENS = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective", "send", "recv",
)
_LAYOUT_NAME_PREFIXES = (
    "copy", "transpose", "bitcast", "reshape", "convert_element_type",
    "slice-start", "slice-done", "dynamic_slice", "dynamic-update",
    "pad",
)
# attention bucketing (ISSUE 12): ops under the attention
# named_scopes (parallel/ring.py stamps dense_attention /
# flash_attention / ring/ulysses scopes into HLO metadata op_name,
# which trace events carry in long_name/tf_op) and Pallas/Mosaic
# custom-call attention kernels
_ATTENTION_TOKENS = (
    "dense_attention", "flash_attention", "ring_attention",
    "ulysses_attention", "flash_att",
)
_ATTENTION_CUSTOM_CALL_TOKENS = ("mosaic", "tpu_custom_call")


def classify(name: str, category: str, long_name: str) -> str:
    """Map one device op to a report category. `category` is XLA's own
    `hlo_category` arg (or the HLO opcode in hlo-module captures);
    `long_name` the HLO text incl. metadata (both may be '')."""
    n = name.lower()
    c = (category or "").lower()
    ln = (long_name or "").lower()
    if any(t in n or t in c for t in _COLLECTIVE_TOKENS):
        return "collective"
    if "infeed" in n or "outfeed" in n or "infeed" in c or "outfeed" in c:
        return "infeed"
    # attention BEFORE conv/gemm: the attention scopes' dots/fusions
    # must land here, and a Pallas flash kernel is a custom-call whose
    # only category hint is its target/metadata
    if any(t in n or t in ln for t in _ATTENTION_TOKENS):
        return "attention"
    if ("custom-call" in c or "custom_call" in c
            or n.startswith("custom")) and any(
        t in n or t in ln for t in _ATTENTION_CUSTOM_CALL_TOKENS
    ):
        return "attention"
    if "convolution" in c or "convolution(" in ln or n.startswith("conv_"):
        return "conv"
    if ("dot(" in ln or "dot " in ln or "gemm" in n or "gemm" in c
            or c == "dot" or n.startswith("dot")):
        return "gemm"
    # layout/data-movement BEFORE elementwise: convert_element_type is
    # a dtype/layout relayout even though XLA categorizes it
    # "non-fusion elementwise", and the async slice-start/done pairs
    # are HBM<->scratch staging copies
    if (c in ("copy", "copy-start", "copy-done", "data formatting",
              "dynamic-slice", "async-start", "async-done")
            or n.startswith(_LAYOUT_NAME_PREFIXES)):
        return "layout"
    if ("fusion" in c or "elementwise" in c or "reduce" in c
            or "scatter" in c or "select-and-scatter" in c
            or n.startswith(("fusion", "add", "multiply", "reduce",
                             "select_and_scatter", "broadcast"))):
        return "bn_elementwise"
    return "other"


_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
    "f16": 2, "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8,
    "u64": 8, "f64": 8, "c64": 8, "c128": 16, "f8e4m3fn": 1,
    "f8e5m2": 1,
}
_SHAPE_RE = re.compile(
    r"\b(" + "|".join(_DTYPE_BYTES) + r")\[([0-9,]*)\]"
)
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*"      # instruction name
    r"((?:\([^=]*?\))|\S+)\s+"                   # output shape (or tuple)
    r"([\w\-]+)\("                               # opcode
)
# instructions that move no HBM bytes of their own: reads are charged
# at the consuming op, parameters/constants at their users, tuple
# plumbing is free
_FREE_OPCODES = {
    "parameter", "constant", "get-tuple-element", "tuple", "bitcast",
    "after-all", "partition-id", "replica-id", "iota",
}


def shape_bytes(text: str) -> int:
    """Total bytes of every dtype[shape] occurrence in `text` (tuples
    sum their elements; scalars count their dtype size)."""
    total = 0
    for dt, dims in _SHAPE_RE.findall(text):
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * _DTYPE_BYTES[dt]
    return total


def shape_dims(text: str) -> list:
    """Every dtype[shape] occurrence in `text` as (dtype, [dims])."""
    out = []
    for dt, dims in _SHAPE_RE.findall(text):
        out.append(
            (dt, [int(d) for d in dims.split(",")] if dims else [])
        )
    return out


def operand_section(rest: str) -> str:
    """`rest` starts right after the opcode's '(' — return the operand
    text up to its matching ')' (attributes/metadata excluded)."""
    depth = 1
    for i, ch in enumerate(rest):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return rest[:i]
    return rest


def load_text(path: str) -> str:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        return f.read()


def iter_instructions(lines):
    """Yield (name, out_shape, opcode, operands, line) for every
    top-level instruction — instructions inside %fused_computation
    bodies are skipped (they live in registers/scratch; only fusion
    boundaries cross HBM). Other non-entry computations (while bodies,
    reduce appliers) are yielded once — callers needing loop-trip
    semantics must handle `while` opcodes themselves."""
    in_fused = False
    depth_at_fused = 0
    brace_depth = 0
    for line in lines:
        stripped = line.strip()
        opens = line.count("{") - line.count("}")
        if not in_fused and (
            stripped.startswith("%fused_computation")
            or stripped.startswith("fused_computation")
        ) and "{" in line:
            in_fused = True
            depth_at_fused = brace_depth
        brace_depth += opens
        if in_fused:
            if brace_depth <= depth_at_fused:
                in_fused = False
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, out_shape, opcode = m.groups()
        rest = line[m.end():]
        yield name, out_shape, opcode, operand_section(rest), line


def module_header(text: str) -> str:
    """The `HloModule ...` header line (alias map, entry layout)."""
    for line in text.splitlines():
        if line.startswith("HloModule"):
            return line
    return ""


_ALIAS_ENTRY_RE = re.compile(r"\{[\d,\s]*\}:\s*\((\d+)")


def parse_input_output_alias(text: str) -> list:
    """Parameter indices appearing in the module's
    `input_output_alias` map — the donated/aliased input buffers.
    Empty list = the program aliases nothing: every parameter is a
    live extra buffer for the whole step (the donation regression the
    auditor exists to catch). The map nests braces
    (`{ {0}: (0, {}, may-alias), ... }`), so the span is found by
    brace balancing, not regex."""
    header = module_header(text)
    start = header.find("input_output_alias={")
    if start < 0:
        return []
    i = start + len("input_output_alias=")
    depth = 0
    for j in range(i, len(header)):
        if header[j] == "{":
            depth += 1
        elif header[j] == "}":
            depth -= 1
            if depth == 0:
                body = header[i + 1:j]
                return sorted({
                    int(g) for g in _ALIAS_ENTRY_RE.findall(body)
                })
    return []


# categories with a positive token/opcode signal; the fallback buckets
# (bn_elementwise / layout / other) are WEAK — a weak op whose operand
# was produced by an attention op inherits "attention" (dataflow
# closure). XLA's backward-pass fission drops metadata from some
# fusions (e.g. the [T,T] softmax-backward convert fusions in the
# dense longctx capture carry no op_name at all), and without the
# closure those score-matrix bytes silently leak into bn_elementwise.
_STRONG_CATEGORIES = ("collective", "infeed", "attention", "conv",
                      "gemm")
_OPERAND_NAME_RE = re.compile(r"%([\w.\-]+)")


def analyze_hlo(path: str, top: int = 10, lines=None) -> dict:
    """Static byte attribution of one compiled HLO module (the
    `*.hlo.txt[.gz]` captures): each top-level instruction is charged
    its output + operand bytes — at fusion granularity, exactly the
    tensors that cross HBM — and bucketed with the same classify() as
    the trace path (plus the weak-op dataflow inheritance above).
    Instructions inside %fused_computation bodies are skipped (they
    live in registers/scratch); other non-entry computations (while
    bodies, reduce appliers) count once, with the while-instruction
    count reported so the caveat is visible. `lines` lets a caller
    that already loaded the capture (hlo_audit runs several checks
    over one module) skip the second read+decompress."""
    if lines is None:
        lines = load_text(path).splitlines()

    cat_bytes = defaultdict(int)
    cat_ops = defaultdict(int)
    by_name = {}
    prod_cat: dict = {}  # instruction -> category (dataflow closure)
    total = 0
    n_instr = 0
    n_while = 0
    largest_output = 0
    inherited = 0
    for name, out_shape, opcode, operands, line in iter_instructions(
        lines
    ):
        if opcode in _FREE_OPCODES:
            continue
        n_instr += 1
        if opcode == "while":
            n_while += 1
        out_bytes = shape_bytes(out_shape)
        largest_output = max(largest_output, out_bytes)
        nbytes = out_bytes + shape_bytes(operands)
        cat = classify(name, opcode, line)
        if cat not in _STRONG_CATEGORIES:
            for op_name in _OPERAND_NAME_RE.findall(operands):
                if prod_cat.get(op_name) == "attention":
                    cat = "attention"
                    inherited += 1
                    break
        prod_cat[name] = cat
        cat_bytes[cat] += nbytes
        cat_ops[cat] += 1
        total += nbytes
        rec = by_name.setdefault(
            name, {"name": name, "category": cat, "bytes": 0,
                   "count": 0},
        )
        rec["bytes"] += nbytes
        rec["count"] += 1

    if n_instr == 0:
        raise SystemExit(f"{path}: no HLO instructions found")

    categories = {}
    for cat in CATEGORIES:
        if cat_ops.get(cat, 0) == 0:
            continue
        categories[cat] = {
            "bytes": cat_bytes[cat],
            "share": round(cat_bytes[cat] / total, 4) if total else 0.0,
            "n_ops": cat_ops[cat],
        }
    top_hlos = sorted(by_name.values(), key=lambda r: -r["bytes"])[:top]
    for r in top_hlos:
        r["share_of_bytes"] = round(r["bytes"] / total, 4) if total \
            else 0.0

    report = {
        "source": os.path.basename(path),
        "capture_kind": "hlo_module",
        "total_bytes": total,
        "n_instructions": n_instr,
        # while bodies are charged ONCE; a loopy capture must fold its
        # trip count in by hand (the decode analysis multiplies by
        # max_len) — 0 means the byte table is exact
        "while_instructions": n_while,
        # the footprint pin: the biggest single tensor the program
        # materializes (dense longctx: the [B,H,T,T] scores; flash:
        # a [B,H,T,block_k] tile)
        "largest_output_bytes": largest_output,
        "attention_inherited_ops": inherited,
        "shares": {c: v["share"] for c, v in categories.items()},
        "categories": categories,
        "top_hlos": top_hlos,
    }
    stem = path
    for suf in (".hlo.txt.gz", ".hlo.txt"):
        if stem.endswith(suf):
            stem = stem[: -len(suf)]
            break
    sibling = stem + ".report.json"
    if os.path.exists(sibling):
        with open(sibling) as f:
            report["capture_report"] = json.load(f)
    return report
