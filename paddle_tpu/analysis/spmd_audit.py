"""SPMD partitioning & collective-schedule auditor (ISSUE 15
tentpole) — the third leg of the static-analysis subsystem after the
source passes (ast_lint) and the single-program audits (hlo_audit).

A sharded program that silently stops being sharded still RUNS — XLA
happily repartitions, replicates the table, or swaps a reduce-scatter
for a full all-gather, and the only symptom is bytes. These checks
turn "partitioned" into a machine-checked property of the committed
`mc_*` captures:

- **replication budget** — on a capture whose policy names it
  sharded, no tensor above `replication_floor_bytes` may carry a
  replicated/maximal sharding annotation. The sparse table and the
  T>=32k attention operands must shrink per device; small replicated
  weights are fine below the floor.
- **collective byte budget** — total collective bytes and the largest
  single collective vs the committed baseline + headroom
  (`collective_total_bytes_max` / `largest_collective_bytes_max`),
  plus required/forbidden collective kinds: a repartition that swaps
  the ring permute for a full all-gather of the sequence fails even
  when the byte total happens to squeak under.
- **schedule safety** — the static deadlock tripwires:
  (1) a channel_id may name at most ONE collective (two collectives
  matched on one channel is the classic mismatched-rendezvous hang);
  (2) within a computation, channel order must agree with data flow —
  if collective B transitively consumes collective A's result, then
  channel_id(A) < channel_id(B). Data flow forces A to execute first
  on every rank; a lower channel on B means a rank whose runtime
  matches channels in order waits on B first — rank-divergent
  schedules, the classic SPMD deadlock. (Independent collectives may
  be legally reordered by the scheduler — real captures DO interleave
  them out of channel order, so the check is deliberately limited to
  data-dependent chains.)
  (3) every collective-permute's source-target pairs form a valid
  partial permutation (distinct sources, distinct targets), and under
  `require_single_ring` exactly one cycle covering every participant
  — the ring invariant of ring attention / pipeline hops. Two
  disjoint half-rings ship the same bytes and deadlock the online
  softmax's global reduction.

Driven per capture by the same `tools/traces/audit_budgets.json`
policies as hlo_audit — a policy carrying any SPMD_POLICY_KEYS gets
these checks appended to its `<stem>.audit.json` report, and the
`spmd-audit` framework_lint pass runs exactly those stems.

Pure stdlib, jax-free, like every analysis/ module.
"""

from __future__ import annotations

from paddle_tpu.analysis import hlo_text as _hlo

# a policy with any of these keys is an SPMD policy: its capture gets
# the partitioning/collective/schedule checks and is picked up by the
# `spmd-audit` framework_lint pass
SPMD_POLICY_KEYS = (
    "num_partitions",
    "replication_floor_bytes",
    "allow_replicated",
    "collective_total_bytes_max",
    "largest_collective_bytes_max",
    "require_collectives",
    "forbid_collectives",
    "require_single_ring",
)


def is_spmd_policy(policy: dict) -> bool:
    return any(k in policy for k in SPMD_POLICY_KEYS)


# ---- check family (pre): the module really is partitioned ----------
def check_partitioning(text: str, policy: dict) -> dict:
    """`num_partitions` in the module header must match the mesh the
    capture claims — a sharded capture recompiled single-device would
    pass every other check vacuously (no shardings, no collectives)."""
    need = int(policy.get("num_partitions", 0))
    got = _hlo.num_partitions(text)
    ok = got == need
    return {
        "name": "spmd.partitioning",
        "ok": ok,
        "num_partitions": got,
        "expected": need,
        "detail": (
            "" if ok else
            f"module header says num_partitions={got}, the policy "
            f"pins {need} — this capture did not compile onto the "
            f"mesh it claims, every other SPMD check is vacuous"
        ),
    }


# ---- check family (a): replication budget --------------------------
def _tuple_shape_parts(out_shape: str) -> list:
    """Per-leaf (dtype, dims) of a tuple shape, in leaf order."""
    return _hlo.shape_dims(out_shape)


def check_replication(lines, policy: dict) -> dict:
    """No tensor above the size floor may carry a replicated/maximal
    sharding annotation. Shapes in a partitioned module are LOCAL
    (per-device), so a replicated annotation means the full global
    bytes sit on every chip — exactly the repartition this exists to
    catch (the 100M-row table all-gathered back together)."""
    floor = int(policy.get("replication_floor_bytes", 1 << 20))
    allow = set(policy.get("allow_replicated", []))
    offenders = []
    for name, out_shape, sh, comp in _hlo.iter_shardings(lines):
        if name in allow:
            continue
        if sh.get("kind") == "tuple":
            parts = _tuple_shape_parts(out_shape)
            els = sh.get("elements", [])
            for i, el in enumerate(els):
                if not _hlo.sharding_is_replicated(el):
                    continue
                if i >= len(parts):
                    continue
                dt, dims = parts[i]
                n = 1
                for d in dims:
                    n *= d
                nbytes = n * _hlo._DTYPE_BYTES[dt]
                if nbytes >= floor:
                    offenders.append(
                        f"{name}[{i}] {dt}{dims} ({nbytes} B) in "
                        f"{comp}"
                    )
            continue
        if not _hlo.sharding_is_replicated(sh):
            continue
        nbytes = _hlo.shape_bytes(out_shape)
        if nbytes >= floor:
            offenders.append(
                f"{name} {out_shape} ({nbytes} B) in {comp}"
            )
    ok = not offenders
    return {
        "name": "spmd.replication",
        "ok": ok,
        "floor_bytes": floor,
        "offenders": offenders[:6],
        "detail": (
            "" if ok else
            f"{len(offenders)} tensor(s) >= {floor / 1e6:.1f} MB "
            f"carry a replicated/maximal sharding on a capture whose "
            f"policy names it sharded: {offenders[:3]} — the full "
            f"bytes sit on EVERY device; the partitioning silently "
            f"dropped"
        ),
    }


# ---- check family (b): collective byte budget ----------------------
def check_collective_bytes(summary: dict, policy: dict) -> list:
    """Total / largest collective bytes vs the committed baseline +
    headroom, plus the required/forbidden kind lists."""
    checks = []
    for field, key in (
        ("total_bytes", "collective_total_bytes_max"),
        ("largest_bytes", "largest_collective_bytes_max"),
    ):
        cap = policy.get(key)
        if cap is None:
            continue
        got = summary[field]
        ok = got <= cap
        checks.append({
            "name": f"spmd.collective_{field}",
            "ok": ok,
            "measured": got,
            "budget": cap,
            "detail": (
                "" if ok else
                f"collective {field}={got / 1e6:.2f} MB exceeds the "
                f"committed budget {cap / 1e6:.2f} MB — the program "
                f"moves more fabric bytes than the baseline it was "
                f"committed with (a repartition/over-gather crept in)"
            ),
        })
    by_kind = summary["by_kind"]
    for kind in policy.get("require_collectives", []):
        ok = by_kind.get(kind, {}).get("count", 0) > 0
        checks.append({
            "name": f"spmd.require.{kind}",
            "ok": ok,
            "detail": (
                "" if ok else
                f"no {kind} in the compiled module — the sharding "
                f"this capture exists to prove was dropped (the "
                f"program runs fine fully replicated; bytes are the "
                f"only witness)"
            ),
        })
    for kind in policy.get("forbid_collectives", []):
        got = by_kind.get(kind, {})
        ok = got.get("count", 0) == 0
        checks.append({
            "name": f"spmd.forbid.{kind}",
            "ok": ok,
            "count": got.get("count", 0),
            "bytes": got.get("bytes", 0),
            "detail": (
                "" if ok else
                f"{got.get('count')} {kind} op(s) moving "
                f"{got.get('bytes', 0) / 1e6:.2f} MB — this capture "
                f"must not {kind} (the over-gather repartition: e.g. "
                f"a reduce-scatter swapped for a full all-gather)"
            ),
        })
    return checks


# ---- check family (c): schedule safety -----------------------------
def _computation_ancestry(lines, collectives):
    """For every channel-bearing collective, the set of channel-bearing
    collectives whose results it transitively consumes (within its
    computation; HLO text is def-before-use, so one forward pass).
    Returns [(ancestor, descendant), ...] collective-record pairs."""
    chan = {
        c["name"]: c for c in collectives
        if c["channel_id"] is not None
    }
    pairs = []
    anc: dict = {}
    cur_comp = None
    for comp, line in _hlo.iter_computations(lines):
        if comp != cur_comp:
            cur_comp = comp
            anc = {}
        m = _hlo._INSTR_RE.match(line)
        if not m:
            continue
        name = m.group(1)
        operands = _hlo.operand_section(line[m.end():])
        up: set = set()
        for op in _hlo._OPERAND_NAME_RE.findall(operands):
            up |= anc.get(op, set())
            if op in chan:
                up.add(op)
        anc[name] = up
        if name in chan:
            for a in up:
                pairs.append((chan[a], chan[name]))
    return pairs


def check_channel_unique(collectives) -> dict:
    """One channel_id, one collective: two instructions matched on the
    same channel is a mismatched rendezvous — ranks can pair opposite
    ops and wait forever."""
    seen: dict = {}
    dups = []
    for c in collectives:
        ch = c["channel_id"]
        if ch is None:
            continue
        if ch in seen:
            dups.append(
                f"channel {ch}: {seen[ch]} and {c['name']}"
            )
        else:
            seen[ch] = c["name"]
    ok = not dups
    return {
        "name": "spmd.schedule.channel_unique",
        "ok": ok,
        "channels": len(seen),
        "detail": (
            "" if ok else
            f"duplicate channel_id(s): {dups[:3]} — two collectives "
            f"share a rendezvous channel; ranks can match opposite "
            f"ops and deadlock"
        ),
    }


def check_channel_order(lines, collectives) -> dict:
    """Channel order must agree with data flow: if collective B
    consumes collective A's result, channel_id(A) < channel_id(B).
    Data dependence fixes the execution order on every rank; a lower
    channel on the LATER op means a runtime that services channels in
    order rendezvouses on B first — the rank-divergent schedule that
    hangs a pod. Independent collectives are exempt on purpose: real
    schedulers interleave them out of channel order legally."""
    bad = []
    for a, b in _computation_ancestry(lines, collectives):
        if a["channel_id"] >= b["channel_id"]:
            bad.append(
                f"{b['name']} (ch {b['channel_id']}) data-depends on "
                f"{a['name']} (ch {a['channel_id']}) in "
                f"{b['computation']}"
            )
    ok = not bad
    return {
        "name": "spmd.schedule.channel_order",
        "ok": ok,
        "detail": (
            "" if ok else
            f"{len(bad)} collective pair(s) whose channel order "
            f"contradicts data flow: {bad[:3]} — the dependency "
            f"forces one execution order while the channel numbers "
            f"promise another; rank-divergent rendezvous = deadlock"
        ),
    }


def _cycles(pairs):
    """Decompose source->target pairs into cycles; returns
    (cycles, open_paths) where each cycle is a node list."""
    nxt = dict(pairs)
    nodes = set(nxt) | {t for _, t in pairs}
    starts = sorted(nxt)
    seen: set = set()
    cycles, open_paths = [], []
    for s in starts:
        if s in seen:
            continue
        path = [s]
        seen.add(s)
        cur = s
        while True:
            cur = nxt.get(cur)
            if cur is None:
                open_paths.append(path)
                break
            if cur == path[0]:
                cycles.append(path)
                break
            if cur in seen:
                open_paths.append(path)
                break
            seen.add(cur)
            path.append(cur)
    return cycles, open_paths, nodes


def check_permute_cycles(collectives, policy: dict) -> dict:
    """Every collective-permute must be a valid partial permutation
    (distinct sources, distinct targets — XLA rejects anything else
    at compile time, but a hand-edited or cross-version capture must
    not sneak past the audit), and with `require_single_ring` each
    permute's pairs must form exactly ONE cycle covering every
    participant: the ring invariant. A split ring ships identical
    bytes and still deadlocks the ring reduction."""
    single = bool(policy.get("require_single_ring"))
    bad = []
    n_permutes = 0
    for c in collectives:
        if c["kind"] != "collective-permute":
            continue
        n_permutes += 1
        pairs = c["source_target_pairs"]
        srcs = [s for s, _ in pairs]
        tgts = [t for _, t in pairs]
        if len(set(srcs)) != len(srcs) or len(set(tgts)) != len(tgts):
            bad.append(
                f"{c['name']}: duplicate source or target in "
                f"{pairs[:6]}"
            )
            continue
        if single:
            cycles, open_paths, nodes = _cycles(pairs)
            if open_paths:
                bad.append(
                    f"{c['name']}: {len(open_paths)} open chain(s) — "
                    f"some rank sends and never receives; the ring "
                    f"does not close"
                )
            elif len(cycles) != 1 or len(cycles[0]) != len(nodes):
                bad.append(
                    f"{c['name']}: {len(cycles)} disjoint cycle(s) "
                    f"over {len(nodes)} ranks — the ring is split"
                )
    ok = not bad
    return {
        "name": "spmd.schedule.permute_ring",
        "ok": ok,
        "permutes": n_permutes,
        "require_single_ring": single,
        "detail": (
            "" if ok else
            f"{len(bad)} collective-permute(s) break the "
            f"{'single-ring' if single else 'permutation'} "
            f"invariant: {bad[:3]}"
        ),
    }


# ---- driver --------------------------------------------------------
def spmd_checks(text: str, policy: dict, lines=None):
    """All SPMD checks for one capture. Returns (checks, summary)
    where `summary` is the collective byte table for the report."""
    if lines is None:
        lines = text.splitlines()
    collectives = _hlo.parse_collectives(lines)
    summary = _hlo.collective_summary(collectives)
    checks = []
    if "num_partitions" in policy:
        checks.append(check_partitioning(text, policy))
    if "replication_floor_bytes" in policy:
        checks.append(check_replication(lines, policy))
    checks.extend(check_collective_bytes(summary, policy))
    checks.append(check_channel_unique(collectives))
    checks.append(check_channel_order(lines, collectives))
    checks.append(check_permute_cycles(collectives, policy))
    return checks, summary
