"""Image-classification model zoo.

Reference configs: benchmark/paddle/image/{alexnet,googlenet,
smallnet_mnist_cifar}.py, v1_api_demo/mnist/light_mnist.py,
v1_api_demo/model_zoo/resnet/resnet.py,
trainer_config_helpers/networks.py:465 vgg_16_network. Rebuilt with the
paddle_tpu DSL in NHWC; all convs run on the MXU via XLA.
"""

from __future__ import annotations

from paddle_tpu import dsl
from paddle_tpu.core.config import ModelConf


def _head(g, feat, num_classes, label):
    out = dsl.fc(feat, size=num_classes, name="output")
    cost = dsl.classification_cost(out, label)
    g.conf.output_layer_names.append("output")
    return out


def lenet(image_shape=(28, 28, 1), num_classes=10) -> ModelConf:
    """LeNet-style mnist net (v1_api_demo/mnist/light_mnist.py)."""
    with dsl.model() as g:
        img = dsl.data("image", image_shape)
        lbl = dsl.data("label", (1,), is_ids=True)
        h = dsl.conv(img, 32, 5, padding=2, act="relu")
        h = dsl.pool(h, 2, 2)
        h = dsl.conv(h, 64, 5, padding=2, act="relu")
        h = dsl.pool(h, 2, 2)
        h = dsl.fc(h, size=128, act="tanh")
        _head(g, h, num_classes, lbl)
    return g.conf


def smallnet_mnist_cifar(image_shape=(32, 32, 3), num_classes=10) -> ModelConf:
    """cifar10-quick (benchmark/paddle/image/smallnet_mnist_cifar.py)."""
    with dsl.model() as g:
        img = dsl.data("image", image_shape)
        lbl = dsl.data("label", (1,), is_ids=True)
        h = dsl.conv(img, 32, 5, padding=2, act="relu")
        h = dsl.pool(h, 3, 2, padding=1)
        h = dsl.conv(h, 32, 5, padding=2, act="relu")
        h = dsl.pool(h, 3, 2, padding=1, pool_type="avg")
        h = dsl.conv(h, 64, 5, padding=2, act="relu")
        h = dsl.pool(h, 3, 2, padding=1, pool_type="avg")
        h = dsl.fc(h, size=64, act="relu")
        _head(g, h, num_classes, lbl)
    return g.conf


def alexnet(image_shape=(224, 224, 3), num_classes=1000) -> ModelConf:
    """(benchmark/paddle/image/alexnet.py)."""
    with dsl.model() as g:
        img = dsl.data("image", image_shape)
        lbl = dsl.data("label", (1,), is_ids=True)
        h = dsl.conv(img, 64, 11, stride=4, padding=2, act="relu")
        h = dsl.lrn(h, size=5)
        h = dsl.pool(h, 3, 2)
        h = dsl.conv(h, 192, 5, padding=2, act="relu")
        h = dsl.lrn(h, size=5)
        h = dsl.pool(h, 3, 2)
        h = dsl.conv(h, 384, 3, padding=1, act="relu")
        h = dsl.conv(h, 256, 3, padding=1, act="relu")
        h = dsl.conv(h, 256, 3, padding=1, act="relu")
        h = dsl.pool(h, 3, 2)
        h = dsl.fc(h, size=4096, act="relu", drop_rate=0.5)
        h = dsl.fc(h, size=4096, act="relu", drop_rate=0.5)
        _head(g, h, num_classes, lbl)
    return g.conf


def vgg16(image_shape=(224, 224, 3), num_classes=1000,
          with_batchnorm=False) -> ModelConf:
    """(trainer_config_helpers/networks.py:465 vgg_16_network)."""
    with dsl.model() as g:
        img = dsl.data("image", image_shape)
        lbl = dsl.data("label", (1,), is_ids=True)
        h = img
        for nfs in ([64, 64], [128, 128], [256, 256, 256],
                    [512, 512, 512], [512, 512, 512]):
            h = dsl.img_conv_group(
                h, nfs, 3, 2, 2, conv_with_batchnorm=with_batchnorm
            )
        h = dsl.fc(h, size=4096, act="relu", drop_rate=0.5)
        h = dsl.fc(h, size=4096, act="relu", drop_rate=0.5)
        _head(g, h, num_classes, lbl)
    return g.conf


def _inception(name, x, nf1, nf3r, nf3, nf5r, nf5, proj):
    """GoogleNet inception-v1 block (benchmark/paddle/image/googlenet.py)."""
    b1 = dsl.conv(x, nf1, 1, act="relu", name=f"{name}_1x1")
    b3 = dsl.conv(x, nf3r, 1, act="relu", name=f"{name}_3x3r")
    b3 = dsl.conv(b3, nf3, 3, padding=1, act="relu", name=f"{name}_3x3")
    b5 = dsl.conv(x, nf5r, 1, act="relu", name=f"{name}_5x5r")
    b5 = dsl.conv(b5, nf5, 5, padding=2, act="relu", name=f"{name}_5x5")
    bp = dsl.pool(x, 3, 1, padding=1, name=f"{name}_pool")
    bp = dsl.conv(bp, proj, 1, act="relu", name=f"{name}_proj")
    return dsl.concat(b1, b3, b5, bp, name=f"{name}_out")


def googlenet(image_shape=(224, 224, 3), num_classes=1000) -> ModelConf:
    with dsl.model() as g:
        img = dsl.data("image", image_shape)
        lbl = dsl.data("label", (1,), is_ids=True)
        h = dsl.conv(img, 64, 7, stride=2, padding=3, act="relu")
        h = dsl.pool(h, 3, 2, padding=1)
        h = dsl.conv(h, 64, 1, act="relu")
        h = dsl.conv(h, 192, 3, padding=1, act="relu")
        h = dsl.pool(h, 3, 2, padding=1)
        h = _inception("i3a", h, 64, 96, 128, 16, 32, 32)
        h = _inception("i3b", h, 128, 128, 192, 32, 96, 64)
        h = dsl.pool(h, 3, 2, padding=1)
        h = _inception("i4a", h, 192, 96, 208, 16, 48, 64)
        h = _inception("i4b", h, 160, 112, 224, 24, 64, 64)
        h = _inception("i4c", h, 128, 128, 256, 24, 64, 64)
        h = _inception("i4d", h, 112, 144, 288, 32, 64, 64)
        h = _inception("i4e", h, 256, 160, 320, 32, 128, 128)
        h = dsl.pool(h, 3, 2, padding=1)
        h = _inception("i5a", h, 256, 160, 320, 32, 128, 128)
        h = _inception("i5b", h, 384, 192, 384, 48, 128, 128)
        h = dsl.pool(h, max(image_shape[0] // 32, 1), 1, pool_type="avg")
        h = dsl.dropout(h, 0.4)
        _head(g, h, num_classes, lbl)
    return g.conf


def _bottleneck(name, x, ch, stride, project, fused=False):
    """ResNet bottleneck: 1x1 -> 3x3 -> 1x1(4ch) + shortcut
    (v1_api_demo/model_zoo/resnet/resnet.py bottleneck blocks).
    fused=True routes the stride-1 1x1 sites through the Mosaic
    fused BN/ReLU/GEMM layers (layers/fused.py, the MFU lever) —
    same math, fewer HBM passes."""
    if fused and stride == 1:
        h = dsl.fused_conv1x1_bn(x, ch, act="relu", name=f"{name}_a")
    else:
        h = dsl.conv(x, ch, 1, stride=stride, act="", bias=False,
                     name=f"{name}_a")
        h = dsl.batch_norm(h, act="relu", name=f"{name}_a_bn")
    h = dsl.conv(h, ch, 3, padding=1, act="", bias=False, name=f"{name}_b")
    if project:
        sc = dsl.conv(x, ch * 4, 1, stride=stride, act="", bias=False,
                      name=f"{name}_sc")
        sc = dsl.batch_norm(sc, act="", name=f"{name}_sc_bn")
    else:
        sc = x
    if fused:
        return dsl.fused_bottleneck_tail(
            h, ch * 4, residual=sc, act="relu", name=f"{name}_tail"
        )
    h = dsl.batch_norm(h, act="relu", name=f"{name}_b_bn")
    h = dsl.conv(h, ch * 4, 1, act="", bias=False, name=f"{name}_c")
    h = dsl.batch_norm(h, act="", name=f"{name}_c_bn")
    return dsl.addto(h, sc, act="relu", name=f"{name}_add")


def resnet(depth=50, image_shape=(224, 224, 3), num_classes=1000,
           fused=False) -> ModelConf:
    """ResNet-50/101/152 (v1_api_demo/model_zoo/resnet/resnet.py).
    fused=True uses the Mosaic fused bottleneck layers (new parameter
    names — not checkpoint-compatible with the plain graph)."""
    stages = {
        50: (3, 4, 6, 3),
        101: (3, 4, 23, 3),
        152: (3, 8, 36, 3),
    }[depth]
    with dsl.model() as g:
        img = dsl.data("image", image_shape)
        lbl = dsl.data("label", (1,), is_ids=True)
        h = dsl.conv(img, 64, 7, stride=2, padding=3, act="", bias=False,
                     name="conv1")
        h = dsl.batch_norm(h, act="relu", name="conv1_bn")
        h = dsl.pool(h, 3, 2, padding=1)
        for si, (n_blocks, ch) in enumerate(zip(stages, (64, 128, 256, 512))):
            for bi in range(n_blocks):
                stride = 2 if (si > 0 and bi == 0) else 1
                h = _bottleneck(
                    f"res{si + 2}{chr(ord('a') + bi)}", h, ch, stride,
                    project=(bi == 0), fused=fused,
                )
        final = max(image_shape[0] // 32, 1)  # global avg pool
        h = dsl.pool(h, final, 1, pool_type="avg")
        _head(g, h, num_classes, lbl)
    return g.conf
