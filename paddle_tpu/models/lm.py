"""Decoder-only Transformer LM (ISSUE 19 tentpole).

The LM north star ROADMAP item 1 asks for, built from the layer
inventory that already exists: `embedding` -> N causal
`multi_head_attention` blocks with a relu-fc residual (the exact
block `bench.longctx_conf` measures) -> `fc` head ->
`classification_cost`. `transformer_lm()` returns that ModelConf for
the TRAIN path (Network/Trainer/AMP/donation all apply unchanged).

Generation does NOT run the DSL graph per token — that is the
prefix-recompute decode the PR12 capture verdict condemned (7.7x over
the byte floor, all dispatch chain). Instead this module exposes the
LM's math as pure functions over the SAME flat param dict
`Network.init_params` produces (`_lm_emb.w0`, `_lm_att{i}.wq`, ...),
so `paddle_tpu/decoding/kv_cache.py` can compile the two generation
programs (bucketed prefill + fused per-token decode) against trained
parameters directly:

- `lm_forward(..., with_kv=True)` — full causal forward returning
  per-layer K/V for the prefill program to page out.
- `lm_decode_chunk` — n new tokens against a gathered cache context
  (n=1: the per-token decode step; n=propose_k: the speculative
  verify chunk; rows=B*K: the beam step). Slot s in the gathered
  context IS absolute position s, so the chunk scatters its own new
  K/V into the context before attending — intra-chunk causality for
  free.
- `beam_init_select` / `beam_step_select` — the beam expansion rule,
  shared verbatim by the paged and full-recompute paths so the
  pinned token-for-token equality test compares ONLY the logits
  source (cache vs recompute), never divergent beam semantics.
- `greedy_decode_recompute` / `beam_decode_recompute` — the
  full-recompute references those pins compare against (every step
  re-runs the whole prefix through `lm_forward`).

Analytic accounting mirrors the NMT row's `_nmt_train_flops_per_batch`
pattern: `lm_train_flops_per_batch` feeds the train row's MFU;
`lm_prefix_recompute_bytes_saved` turns the serving engine's MEASURED
cached-prefix-token counters into the bytes a recompute decode would
have streamed (the decode row's `prefix_recompute_bytes_saved` field).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from paddle_tpu.core.config import ModelConf
from paddle_tpu.parallel import ring


@dataclasses.dataclass(frozen=True)
class LMSpec:
    """Static LM architecture — everything the functional forward and
    the compiled generation programs need to agree with the DSL conf.
    attn_impl applies to the FULL-sequence paths (train / prefill /
    recompute reference); the per-token decode step always attends
    densely over the gathered page context (its score matrix is
    [B, 1, S] — there is no [T, T] to remove)."""

    vocab: int = 2048
    d_model: int = 128
    num_heads: int = 4
    num_layers: int = 2
    attn_impl: str = "dense"

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.num_heads == 0
        return self.d_model // self.num_heads


def transformer_lm(spec: LMSpec) -> ModelConf:
    """Trainer config from the existing DSL layer inventory. Teacher
    forcing: `ids` is the BOS-prefixed input, `label` the next-token
    target; the causal mask keeps position t blind to t+1 exactly like
    the generation programs."""
    from paddle_tpu import dsl

    d, h = spec.d_model, spec.num_heads
    with dsl.model() as g:
        ids = dsl.data("ids", dim=(), is_ids=True, is_seq=True)
        lbl = dsl.data("label", dim=(), is_ids=True, is_seq=True)
        x = dsl.embedding(ids, size=d, vocab_size=spec.vocab,
                          name="lm_emb")
        for i in range(spec.num_layers):
            att = dsl._add(
                "multi_head_attention", [x], size=d, num_heads=h,
                causal=True, attn_impl=spec.attn_impl,
                name=f"lm_att{i}",
            )
            x = dsl.addto(att, dsl.fc(att, size=d, act="relu",
                                      name=f"lm_ff{i}"),
                          name=f"lm_blk{i}")
        out = dsl.fc(x, size=spec.vocab, act="", name="lm_head")
        dsl.classification_cost(out, lbl, name="lm_cost")
        g.conf.output_layer_names.append("lm_head")
    return g.conf


def lm_init_params(spec: LMSpec, key) -> dict:
    """Flat param dict via the DSL graph's own initializer — the
    generation programs consume Network-trained params unchanged."""
    from paddle_tpu.network import Network

    return Network(transformer_lm(spec)).init_params(key)


# ---- functional forward (same params, same math) -------------------

def _heads(spec: LMSpec, x):
    return x.reshape(x.shape[0], x.shape[1], spec.num_heads,
                     spec.head_dim)


def _block_tail(spec: LMSpec, params, i: int, att):
    """Post-attention half of block i: wo projection + bias, then the
    addto(att, relu-fc(att)) residual — the longctx block shape."""
    d = spec.d_model
    att = att.reshape(att.shape[0], att.shape[1], d)
    att = jnp.dot(att, params[f"_lm_att{i}.wo"])
    att = att + params[f"_lm_att{i}.wbias"]
    ff = jnp.dot(att, params[f"_lm_ff{i}.w0"])
    ff = jax.nn.relu(ff + params[f"_lm_ff{i}.wbias"])
    return att + ff


def _head_logits(spec: LMSpec, params, x):
    return jnp.dot(x, params["_lm_head.w0"]) + params["_lm_head.wbias"]


def lm_forward(spec: LMSpec, params: dict, ids, lens=None,
               with_kv: bool = False):
    """Full causal forward: ids [B, T] int32 -> logits [B, T, vocab].
    Identical math to the DSL graph at every valid position (pinned by
    tests/test_lm_kv_cache.py). with_kv=True additionally returns the
    per-layer pre-attention K/V stacks [L, B, T, H, hd] — what the
    prefill program pages out."""
    x = jnp.take(params["_lm_emb.w0"], ids, axis=0)
    if lens is not None:
        pos = jnp.arange(ids.shape[1])[None, :]
        x = jnp.where((pos < lens[:, None])[..., None], x, 0.0)
    ks, vs = [], []
    for i in range(spec.num_layers):
        q = _heads(spec, jnp.dot(x, params[f"_lm_att{i}.wq"]))
        k = _heads(spec, jnp.dot(x, params[f"_lm_att{i}.wk"]))
        v = _heads(spec, jnp.dot(x, params[f"_lm_att{i}.wv"]))
        if with_kv:
            ks.append(k)
            vs.append(v)
        if spec.attn_impl == "flash":
            att = ring.flash_dense_attention(q, k, v, causal=True,
                                             kv_len=lens)
        else:
            att = ring.dense_attention(q, k, v, causal=True,
                                       kv_len=lens)
        x = _block_tail(spec, params, i, att)
    logits = _head_logits(spec, params, x)
    if with_kv:
        return logits, jnp.stack(ks), jnp.stack(vs)
    return logits


def chunk_attention(q, ctx_k, ctx_v, start):
    """Attention for a chunk of n NEW tokens at absolute positions
    start[b]..start[b]+n-1 over a gathered cache context whose slot s
    is absolute position s (the chunk's own K/V already scattered in).
    q [B, n, H, hd], ctx [B, S, H, hd], start [B] int32. Query j may
    see slots s <= start[b] + j; everything else (unwritten pages,
    stale speculative entries, padding slots) is masked to NEG_INF —
    the same mask/scale/softmax conventions as ring.dense_attention,
    so the paged path is token-identical to the full recompute."""
    hd = q.shape[-1]
    scale = 1.0 / jnp.sqrt(hd).astype(q.dtype)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, ctx_k) * scale
    qpos = start[:, None] + jnp.arange(q.shape[1])[None, :]  # [B, n]
    kpos = jnp.arange(ctx_k.shape[1])  # [S]
    bad = kpos[None, None, :] > qpos[:, :, None]  # [B, n, S]
    s = s + jnp.where(bad[:, None, :, :], ring.NEG_INF, 0.0)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, ctx_v)


def lm_decode_chunk(spec: LMSpec, params: dict, toks, start,
                    ctx_k, ctx_v):
    """Forward n new tokens against a gathered cache context — the
    shared core of the per-token decode step (n=1), the speculative
    verify chunk (n=propose_k), and the beam step (rows flattened to
    B*K). toks [B, n] int32, start [B] int32 (absolute position of
    toks[:, 0]), ctx [L, B, S, H, hd] gathered from the page pool
    BEFORE this chunk's writes. Returns (logits [B, n, vocab],
    new_k [L, B, n, H, hd], new_v) — the caller scatters new_k/new_v
    into the pool at the same absolute slots this function wrote them
    into the context."""
    b, n = toks.shape
    x = jnp.take(params["_lm_emb.w0"], toks, axis=0)
    idx = start[:, None] + jnp.arange(n)[None, :]  # [B, n] abs slots
    rows = jnp.arange(b)[:, None]
    new_ks, new_vs = [], []
    for i in range(spec.num_layers):
        q = _heads(spec, jnp.dot(x, params[f"_lm_att{i}.wq"]))
        kn = _heads(spec, jnp.dot(x, params[f"_lm_att{i}.wk"]))
        vn = _heads(spec, jnp.dot(x, params[f"_lm_att{i}.wv"]))
        new_ks.append(kn)
        new_vs.append(vn)
        ck = ctx_k[i].at[rows, idx].set(kn)
        cv = ctx_v[i].at[rows, idx].set(vn)
        att = chunk_attention(q, ck, cv, start)
        x = _block_tail(spec, params, i, att)
    logits = _head_logits(spec, params, x)
    return logits, jnp.stack(new_ks), jnp.stack(new_vs)


def lm_logp(logits):
    """f32 log-softmax — score math stays f32 regardless of AMP, the
    same pinned-accumulator rule as the beam decoder."""
    return jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)


# ---- beam expansion rule (shared by paged + recompute paths) -------

def beam_init_select(logp0, k: int):
    """First expansion from the prompt's next-token distribution:
    logp0 [B, vocab] -> (scores [B, k], tokens [B, k])."""
    scores, tokens = jax.lax.top_k(logp0, k)
    return scores, tokens.astype(jnp.int32)


def beam_step_select(scores, logp, finished, eos_id: int):
    """One beam expansion: scores [B, K] f32, logp [B, K, vocab] f32,
    finished [B, K] bool -> (scores, parent, token, finished), each
    [B, K]. A finished beam contributes exactly one candidate — eos at
    its frozen score — so it survives top-k without growing."""
    b, k, v = logp.shape
    live = scores[..., None] + logp
    fin = jnp.full_like(logp, ring.NEG_INF).at[..., eos_id].set(
        scores
    )
    cand = jnp.where(finished[..., None], fin, live)
    top, idx = jax.lax.top_k(cand.reshape(b, k * v), k)
    parent = (idx // v).astype(jnp.int32)
    token = (idx % v).astype(jnp.int32)
    was_fin = jnp.take_along_axis(finished, parent, axis=1)
    return top, parent, token, was_fin | (token == eos_id)


# ---- full-recompute references (what the pins compare against) -----

def _last_logp(spec, params, buf, lens):
    logits = lm_forward(spec, params, buf, lens=lens)
    last = jnp.take_along_axis(
        logits, (lens - 1)[:, None, None], axis=1
    )[:, 0, :]
    return lm_logp(last)


# jitting a fresh lambda per decode call would re-trace every call —
# the recompute arm of the bench A/B must be as warm as the paged arm,
# so the step program is cached per spec (bounded; specs are frozen
# dataclasses, hence hashable)
_RECOMPUTE_PROGS: dict = {}
_MAX_RECOMPUTE_PROGS = 8


def _recompute_step(spec):
    fn = _RECOMPUTE_PROGS.get(spec)
    if fn is None:
        if len(_RECOMPUTE_PROGS) >= _MAX_RECOMPUTE_PROGS:
            _RECOMPUTE_PROGS.pop(next(iter(_RECOMPUTE_PROGS)))
        fn = jax.jit(lambda p, bf, ln: _last_logp(spec, p, bf, ln))
        _RECOMPUTE_PROGS[spec] = fn
    return fn


def greedy_decode_recompute(spec: LMSpec, params: dict, ids, lens,
                            max_new: int, eos_id: int):
    """The decode the PR12 verdict condemned: every new token re-runs
    the FULL prefix through lm_forward. ids [B, T0] int32 (padded),
    lens [B] int32. Returns (tokens [B, max_new] int32, scores [B]
    f32) — the token-for-token reference for the paged path."""
    import numpy as np

    b, t0 = ids.shape
    buf = np.zeros((b, t0 + max_new), np.int32)
    buf[:, :t0] = np.asarray(ids)
    lens = np.asarray(lens).astype(np.int32).copy()
    step = _recompute_step(spec)
    out = np.zeros((b, max_new), np.int32)
    scores = np.zeros((b,), np.float32)
    finished = np.zeros((b,), bool)
    for t in range(max_new):
        logp = np.asarray(step(params, jnp.asarray(buf),
                               jnp.asarray(lens)))
        tok = logp.argmax(axis=-1).astype(np.int32)
        tok = np.where(finished, eos_id, tok)
        scores = np.where(
            finished, scores,
            scores + logp[np.arange(b), tok],
        ).astype(np.float32)
        out[:, t] = tok
        buf[np.arange(b), lens] = tok
        lens += 1
        finished |= tok == eos_id
    return out, scores


def beam_decode_recompute(spec: LMSpec, params: dict, ids, lens,
                          beam_k: int, max_new: int, eos_id: int):
    """Full-recompute beam search under the shared expansion rule.
    Returns (tokens [B, K, max_new] int32, scores [B, K] f32)."""
    import numpy as np

    b, t0 = ids.shape
    k = beam_k
    ids_np = np.asarray(ids)
    lens_np = np.asarray(lens).astype(np.int32)
    init = _recompute_step(spec)
    logp0 = np.asarray(init(params, jnp.asarray(ids_np),
                            jnp.asarray(lens_np)))
    sc, tok = beam_init_select(jnp.asarray(logp0), k)
    scores = np.asarray(sc)
    hist = np.zeros((b, k, max_new), np.int32)
    hist[:, :, 0] = np.asarray(tok)
    finished = hist[:, :, 0] == eos_id

    buf = np.zeros((b, k, t0 + max_new), np.int32)
    buf[:, :, :t0] = ids_np[:, None, :]
    rows = np.arange(b)[:, None], np.arange(k)[None, :]
    buf[rows[0], rows[1], lens_np[:, None]] = hist[:, :, 0]
    blens = np.broadcast_to(lens_np[:, None] + 1, (b, k)).copy()

    flat = _recompute_step(spec)
    for t in range(1, max_new):
        logp = np.asarray(flat(
            params, jnp.asarray(buf.reshape(b * k, -1)),
            jnp.asarray(blens.reshape(b * k)),
        )).reshape(b, k, -1)
        sc, parent, tok, fin = beam_step_select(
            jnp.asarray(scores), jnp.asarray(logp),
            jnp.asarray(finished), eos_id,
        )
        scores = np.asarray(sc)
        parent_np = np.asarray(parent)
        tok_np = np.asarray(tok)
        finished = np.asarray(fin)
        gi = np.arange(b)[:, None]
        hist = hist[gi, parent_np]
        buf = buf[gi, parent_np]
        blens = blens[gi, parent_np]
        hist[:, :, t] = tok_np
        buf[rows[0], rows[1], blens] = tok_np
        blens += 1
    return hist, scores


# ---- analytic accounting (the _nmt_train_flops pattern) ------------

def lm_train_flops_per_batch(spec: LMSpec, bs: int, t: int) -> int:
    """Model FLOPs per optimizer step (2/MAC, train ~ 3x fwd — the
    same conventions as _nmt_train_flops_per_batch / _longctx_flops):
    per layer QKVO projections + the [T,T] score/value matmuls (full
    square for both attn impls) + the d->d relu fc, plus the vocab
    head."""
    d, l = spec.d_model, spec.num_layers
    per_layer = (
        4 * 2 * bs * t * d * d          # wq/wk/wv/wo
        + 2 * 2 * bs * t * t * d        # QK^T and attn@V
        + 2 * bs * t * d * d            # residual fc
    )
    head = 2 * bs * t * d * spec.vocab
    return 3 * (l * per_layer + head)


def lm_param_bytes(spec: LMSpec, dtype_bytes: int = 4) -> int:
    d, l, v = spec.d_model, spec.num_layers, spec.vocab
    n = v * d                            # embedding
    n += l * (4 * d * d + d)             # attention (+ bias)
    n += l * (d * d + d)                 # residual fc
    n += d * v + v                       # head
    return n * dtype_bytes


def lm_prefix_token_recompute_bytes(spec: LMSpec,
                                    dtype_bytes: int = 4) -> int:
    """HBM bytes a full-recompute decode streams PER PREFIX TOKEN per
    step that the paged cache avoids: re-embedding plus the per-layer
    activation round trips (x in, q/k/v/att/ff out-and-in) of pushing
    one already-seen token back through every block. Weight streaming
    is excluded on purpose — both paths read the weights once per
    step, so it cancels in the saved-bytes accounting."""
    d, l = spec.d_model, spec.num_layers
    per_layer = 8 * d * dtype_bytes      # x,q,k,v,att,wo-out,ff,res
    return d * dtype_bytes + l * per_layer


def lm_prefix_recompute_bytes_saved(spec: LMSpec,
                                    cached_prefix_tokens: int,
                                    dtype_bytes: int = 4) -> int:
    """Turn the engine's MEASURED counter (sum over decode dispatches
    of the prefix tokens served from the page pool) into the bytes a
    recompute decode would have streamed for those same tokens."""
    return int(cached_prefix_tokens) * lm_prefix_token_recompute_bytes(
        spec, dtype_bytes
    )
