"""GAN demo (v1_api_demo/gan/gan_conf.py + gan_trainer.py).

The reference builds one config per training mode and freezes the other
half with is_static param attrs (gan_conf.py:51,94), alternating modes
from the trainer. Same design here: generator and discriminator share
parameters BY NAME across the two training configs; the config for each
phase marks the other network's parameters is_static so its optimizer
update is skipped (optimizers.Optimizer.update h.is_static). `GAN`
wraps the two jitted train steps and the sample path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu import dsl
from paddle_tpu.core.arg import Arg, id_arg, non_seq
from paddle_tpu.core.config import ModelConf, ParameterConf
from paddle_tpu.network import Network
from paddle_tpu.optimizers import create_optimizer


def _p(name, static):
    return ParameterConf(name=name, is_static=static)


def _fc(x, size, act, name, pname, static):
    # weight AND bias carry is_static — the reference freezes whole
    # layers via ParamAttr+bias_attr (gan_conf.py:51-53)
    return dsl.fc(x, size=size, act=act, name=name,
                  param=_p(pname, static),
                  bias_param=_p(pname + "_b", static))


def _generator(noise, sample_dim, hidden, static):
    h = _fc(noise, hidden, "relu", "gen_h1", "gen_w1", static)
    h = _fc(h, hidden, "relu", "gen_h2", "gen_w2", static)
    return _fc(h, sample_dim, "", "gen_out", "gen_w3", static)


def _discriminator(sample, hidden, static):
    h = _fc(sample, hidden, "relu", "dis_h1", "dis_w1", static)
    h = _fc(h, hidden, "relu", "dis_h2", "dis_w2", static)
    return _fc(h, 2, "", "dis_out", "dis_w3", static)


def gan_conf(mode: str, noise_dim=10, sample_dim=2, hidden=64) -> ModelConf:
    """mode in {generator_training, discriminator_training, generator}
    (gan_conf.py:16-24)."""
    assert mode in (
        "generator_training",
        "discriminator_training",
        "generator",
    )
    with dsl.model() as g:
        if mode == "discriminator_training":
            sample = dsl.data("sample", sample_dim)
            label = dsl.data("label", 1, is_ids=True)
            logits = _discriminator(sample, hidden, static=False)
            dsl.classification_cost(logits, label, name="cost")
        else:
            noise = dsl.data("noise", noise_dim)
            sample = _generator(
                noise, sample_dim, hidden, static=(mode == "generator")
            )
            g.conf.output_layer_names.append("gen_out")
            if mode == "generator_training":
                label = dsl.data("label", 1, is_ids=True)
                logits = _discriminator(sample, hidden, static=True)
                dsl.classification_cost(logits, label, name="cost")
    return g.conf


class GAN:
    """Alternating trainer (gan_trainer.py): d-step on real+fake
    samples, g-step through the frozen discriminator. One parameter
    dict is shared across phases — exactly the by-name sharing the
    reference gets from its parameter server."""

    def __init__(self, opt_conf, noise_dim=10, sample_dim=2, hidden=64,
                 seed=0):
        self.noise_dim = noise_dim
        self.g_net = Network(
            gan_conf("generator_training", noise_dim, sample_dim, hidden)
        )
        self.d_net = Network(
            gan_conf("discriminator_training", noise_dim, sample_dim,
                     hidden)
        )
        key = jax.random.key(seed)
        kg, kd = jax.random.split(key)
        # one shared dict: generator params from g_net init,
        # discriminator params from d_net init
        self.params = dict(self.g_net.init_params(kg))
        self.params.update(self.d_net.init_params(kd))
        self.g_opt = create_optimizer(opt_conf, self.g_net.param_confs)
        self.d_opt = create_optimizer(opt_conf, self.d_net.param_confs)
        self.g_opt_state = self.g_opt.init_state(self.params)
        self.d_opt_state = self.d_opt.init_state(self.params)

        def g_step(params, opt_state, noise, step_i):
            feed = {
                "noise": non_seq(noise),
                # generator wants fakes scored as REAL (label 1)
                "label": id_arg(
                    jnp.ones(noise.shape[0], jnp.int32)
                ),
            }
            (loss, _), grads = jax.value_and_grad(
                self.g_net.loss_fn, has_aux=True
            )(params, feed)
            params, opt_state = self.g_opt.update(
                grads, params, opt_state, step_i
            )
            return params, opt_state, loss

        def d_step(params, opt_state, sample, label, step_i):
            feed = {"sample": non_seq(sample), "label": id_arg(label)}
            (loss, _), grads = jax.value_and_grad(
                self.d_net.loss_fn, has_aux=True
            )(params, feed)
            params, opt_state = self.d_opt.update(
                grads, params, opt_state, step_i
            )
            return params, opt_state, loss

        def sample_fn(params, noise):
            outs, _ = self.g_net.forward(
                params, {"noise": non_seq(noise)}, outputs=["gen_out"]
            )
            return outs["gen_out"].value

        self._g_step = jax.jit(g_step)
        self._d_step = jax.jit(d_step)
        self._sample = jax.jit(sample_fn)

    def sample(self, noise):
        return self._sample(self.params, noise)

    def train_d(self, real, noise, step_i):
        fake = self.sample(noise)
        samples = jnp.concatenate([real, fake])
        labels = jnp.concatenate(
            [
                jnp.ones(real.shape[0], jnp.int32),
                jnp.zeros(fake.shape[0], jnp.int32),
            ]
        )
        self.params, self.d_opt_state, loss = self._d_step(
            self.params, self.d_opt_state, samples, labels, step_i
        )
        return float(loss)

    def train_g(self, noise, step_i):
        self.params, self.g_opt_state, loss = self._g_step(
            self.params, self.g_opt_state, noise, step_i
        )
        return float(loss)
