"""VAE demo (v1_api_demo/vae/vae_conf.py).

Encoder q(z|x) -> (mu, logvar); reparameterization z = mu +
exp(0.5*logvar) * eps with eps fed as a data input (the reference feeds
its noise the same way, vae_conf.py:27-32); decoder p(x|z) with sigmoid
output; loss = binary cross-entropy reconstruction
(vae_conf.py:94-96) + 0.5 * sum(exp(logvar) + mu^2 - 1 - logvar)
(vae_conf.py:99-103), both as cost layers summed by the trainer.
"""

from __future__ import annotations

from paddle_tpu import dsl
from paddle_tpu.core.config import ModelConf


def vae_conf(x_dim=784, hidden=256, latent=16) -> ModelConf:
    with dsl.model() as g:
        x = dsl.data("x", x_dim)
        eps = dsl.data("eps", latent)

        # encoder
        h = dsl.fc(x, size=hidden, act="relu", name="enc_h")
        mu = dsl.fc(h, size=latent, name="mu")
        logvar = dsl.fc(h, size=latent, name="logvar")

        # z = mu + exp(0.5 * logvar) * eps
        std = dsl.addto(
            dsl.slope_intercept(logvar, slope=0.5), act="exponential",
            name="std",
        )
        z = dsl.addto(dsl.dot_mul(std, eps), mu, name="z")

        # decoder
        dh = dsl.fc(z, size=hidden, act="relu", name="dec_h")
        prob = dsl.fc(dh, size=x_dim, act="sigmoid", name="prob")
        g.conf.output_layer_names.append("prob")

        # reconstruction: elementwise binary CE against the input
        dsl.soft_binary_cross_entropy(prob, x, name="recon_cost")

        # KL(q || N(0,1)) = 0.5 * sum(exp(logvar) + mu^2 - 1 - logvar)
        exp_logvar = dsl.addto(logvar, act="exponential")
        mu_sq = dsl.addto(mu, act="square")
        neg_logvar_m1 = dsl.slope_intercept(
            logvar, slope=-1.0, intercept=-1.0
        )
        inner = dsl.addto(exp_logvar, mu_sq, neg_logvar_m1)
        dsl.sum_cost(inner, name="kl_cost", coeff=0.5)
    return g.conf
