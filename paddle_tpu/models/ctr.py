"""CTR models: wide-sparse logistic regression and wide&deep.

Reference workload: the BASELINE config list's "CTR wide-sparse logistic
regression (high-dim sparse updater)" — the pserver-era sparse training
story (SURVEY §2 'MP sparse'): a huge per-feature weight table touched
sparsely per batch. TPU-first: features arrive as an id SEQUENCE
(variable number of active features per example); the weight table is an
embedding with sparse/sharded updates (parallel/sparse.py), pooled by
sum — exactly w.x for binary features.
"""

from __future__ import annotations

from paddle_tpu import dsl
from paddle_tpu.core.config import ModelConf, ParameterConf


def ctr_linear(feature_dim=100000, sharded=False) -> ModelConf:
    """Wide sparse LR: sigmoid(sum_i w[f_i] + b)."""
    with dsl.model() as g:
        feats = dsl.data("features", (1,), is_seq=True, is_ids=True)
        label = dsl.data("label", (1,), is_ids=True)
        w = dsl.embedding(
            feats, size=1, vocab_size=feature_dim, sharded=sharded,
            param=ParameterConf(name="wide_w", sparse_update=True),
        )
        s = dsl.seq_pool(w, pool_type="sum")
        logit = dsl.fc(s, size=2, name="output")
        dsl.classification_cost(logit, label, name="cost")
        g.conf.output_layer_names.append("output")
    return g.conf


def ctr_wide_deep(
    feature_dim=100000, emb_dim=16, hidden=(64, 32), sharded=False
) -> ModelConf:
    """Wide & deep: the wide sum above plus an embedding MLP tower."""
    with dsl.model() as g:
        feats = dsl.data("features", (1,), is_seq=True, is_ids=True)
        label = dsl.data("label", (1,), is_ids=True)
        wide = dsl.seq_pool(
            dsl.embedding(
                feats, size=1, vocab_size=feature_dim, sharded=sharded,
                param=ParameterConf(name="wide_w", sparse_update=True),
            ),
            pool_type="sum",
        )
        deep = dsl.seq_pool(
            dsl.embedding(
                feats, size=emb_dim, vocab_size=feature_dim,
                sharded=sharded,
                param=ParameterConf(name="deep_emb", sparse_update=True),
            ),
            pool_type="avg",
        )
        h = deep
        for i, n in enumerate(hidden):
            h = dsl.fc(h, size=n, act="relu", name=f"deep_h{i}")
        logit = dsl.fc(dsl.concat(wide, h), size=2, name="output")
        dsl.classification_cost(logit, label, name="cost")
        g.conf.output_layer_names.append("output")
    return g.conf
