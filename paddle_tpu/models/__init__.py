from paddle_tpu.models.image import (  # noqa: F401
    alexnet,
    googlenet,
    lenet,
    resnet,
    smallnet_mnist_cifar,
    vgg16,
)
from paddle_tpu.models.text import (  # noqa: F401
    bidi_lstm_tagger,
    stacked_lstm_classifier,
)
