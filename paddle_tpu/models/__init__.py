from paddle_tpu.models.image import (  # noqa: F401
    alexnet,
    googlenet,
    lenet,
    resnet,
    smallnet_mnist_cifar,
    vgg16,
)
from paddle_tpu.models.text import (  # noqa: F401
    hierarchical_lstm_classifier,
    bidi_lstm_tagger,
    linear_crf_tagger,
    rnn_crf_tagger,
    seq2seq_attention,
    seq2seq_attention_decoder,
    stacked_lstm_classifier,
)
from paddle_tpu.models.ctr import ctr_linear, ctr_wide_deep  # noqa: F401
from paddle_tpu.models.gan import GAN, gan_conf  # noqa: F401
from paddle_tpu.models.vae import vae_conf  # noqa: F401
