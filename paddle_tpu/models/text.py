"""Text/sequence model zoo.

Reference configs: benchmark/paddle/rnn/rnn.py (IMDB stacked LSTM
classifier), v1_api_demo/quick_start (text classification),
v1_api_demo/sequence_tagging (bidi-RNN tagger).
"""

from __future__ import annotations

from paddle_tpu import dsl
from paddle_tpu.core.config import ModelConf


def stacked_lstm_classifier(
    vocab_size=30000,
    emb_dim=128,
    hidden=256,
    num_layers=2,
    num_classes=2,
    max_len=None,
) -> ModelConf:
    """IMDB LSTM benchmark config (benchmark/paddle/rnn/rnn.py:9-21:
    embedding -> N×(fc+lstmemory) -> max-pool over time -> fc softmax)."""
    with dsl.model() as g:
        ids = dsl.data("words", (1,), is_seq=True, is_ids=True)
        lbl = dsl.data("label", (1,), is_ids=True)
        h = dsl.embedding(ids, size=emb_dim, vocab_size=vocab_size)
        for i in range(num_layers):
            h = dsl.simple_lstm(h, hidden, name=f"lstm{i}")
        pooled = dsl.seq_pool(h, pool_type="max")
        out = dsl.fc(pooled, size=num_classes, name="output")
        dsl.classification_cost(out, lbl)
        g.conf.output_layer_names.append("output")
    return g.conf


def bidi_lstm_tagger(
    vocab_size=30000,
    emb_dim=64,
    hidden=128,
    num_tags=9,
) -> ModelConf:
    """Sequence tagging with a bidirectional LSTM and per-token softmax
    (v1_api_demo/sequence_tagging/rnn_crf.py without the CRF head for now)."""
    with dsl.model() as g:
        ids = dsl.data("words", (1,), is_seq=True, is_ids=True)
        tags = dsl.data("tags", (1,), is_seq=True, is_ids=True)
        emb = dsl.embedding(ids, size=emb_dim, vocab_size=vocab_size)
        h = dsl.bidirectional_lstm(emb, hidden)
        out = dsl.fc(h, size=num_tags, name="output")
        dsl.classification_cost(out, tags)
        g.conf.output_layer_names.append("output")
    return g.conf
