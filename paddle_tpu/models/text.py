"""Text/sequence model zoo.

Reference configs: benchmark/paddle/rnn/rnn.py (IMDB stacked LSTM
classifier), v1_api_demo/quick_start (text classification),
v1_api_demo/sequence_tagging (bidi-RNN tagger).
"""

from __future__ import annotations

from paddle_tpu import dsl
from paddle_tpu.core.config import ModelConf


def stacked_lstm_classifier(
    vocab_size=30000,
    emb_dim=128,
    hidden=256,
    num_layers=2,
    num_classes=2,
    max_len=None,
) -> ModelConf:
    """IMDB LSTM benchmark config (benchmark/paddle/rnn/rnn.py:9-21:
    embedding -> N×(fc+lstmemory) -> max-pool over time -> fc softmax)."""
    with dsl.model() as g:
        ids = dsl.data("words", (1,), is_seq=True, is_ids=True)
        lbl = dsl.data("label", (1,), is_ids=True)
        h = dsl.embedding(ids, size=emb_dim, vocab_size=vocab_size)
        for i in range(num_layers):
            h = dsl.simple_lstm(h, hidden, name=f"lstm{i}")
        pooled = dsl.seq_pool(h, pool_type="max")
        out = dsl.fc(pooled, size=num_classes, name="output")
        dsl.classification_cost(out, lbl)
        g.conf.output_layer_names.append("output")
    return g.conf


def bidi_lstm_tagger(
    vocab_size=30000,
    emb_dim=64,
    hidden=128,
    num_tags=9,
) -> ModelConf:
    """Sequence tagging with a bidirectional LSTM and per-token softmax
    (v1_api_demo/sequence_tagging/rnn_crf.py without the CRF head for now)."""
    with dsl.model() as g:
        ids = dsl.data("words", (1,), is_seq=True, is_ids=True)
        tags = dsl.data("tags", (1,), is_seq=True, is_ids=True)
        emb = dsl.embedding(ids, size=emb_dim, vocab_size=vocab_size)
        h = dsl.bidirectional_lstm(emb, hidden)
        out = dsl.fc(h, size=num_tags, name="output")
        dsl.classification_cost(out, tags)
        g.conf.output_layer_names.append("output")
    return g.conf


def linear_crf_tagger(
    vocab_size=5000,
    num_tags=9,
    emb_dim=32,
    context_length=3,
) -> ModelConf:
    """Linear-chain CRF tagger (v1_api_demo/sequence_tagging/
    linear_crf.py): context-window features -> fc emissions -> crf cost,
    with crf_decoding sharing the "crfw" transition parameter for
    prediction (linear_crf.py:59-69)."""
    from paddle_tpu.core.config import ParameterConf

    with dsl.model() as g:
        ids = dsl.data("words", (1,), is_seq=True, is_ids=True)
        tags = dsl.data("tags", (1,), is_seq=True, is_ids=True)
        emb = dsl.embedding(ids, size=emb_dim, vocab_size=vocab_size)
        feat = dsl.mixed(
            emb_dim * context_length,
            [dsl.context_projection(emb, context_length)],
            name="ctx_feat", bias=False,
        )
        emission = dsl.fc(feat, size=num_tags, name="emission")
        dsl.crf(emission, tags, num_tags=num_tags, name="crf_cost",
                param=ParameterConf(name="crfw"))
        dsl.crf_decoding(emission, num_tags=num_tags, name="decoded",
                         param=ParameterConf(name="crfw"))
        g.conf.output_layer_names.append("decoded")
    return g.conf


def rnn_crf_tagger(
    vocab_size=5000,
    num_tags=9,
    emb_dim=32,
    hidden=64,
) -> ModelConf:
    """Bidirectional-RNN + CRF tagger (v1_api_demo/sequence_tagging/
    rnn_crf.py): the neural emission model under the same CRF head."""
    from paddle_tpu.core.config import ParameterConf

    with dsl.model() as g:
        ids = dsl.data("words", (1,), is_seq=True, is_ids=True)
        tags = dsl.data("tags", (1,), is_seq=True, is_ids=True)
        emb = dsl.embedding(ids, size=emb_dim, vocab_size=vocab_size)
        h = dsl.bidirectional_lstm(emb, hidden)
        emission = dsl.fc(h, size=num_tags, name="emission")
        dsl.crf(emission, tags, num_tags=num_tags, name="crf_cost",
                param=ParameterConf(name="crfw"))
        dsl.crf_decoding(emission, num_tags=num_tags, name="decoded",
                         param=ParameterConf(name="crfw"))
        g.conf.output_layer_names.append("decoded")
    return g.conf


def _attention_decoder_step(hidden, trg_vocab, emb_dim):
    """One decoder step: shared verbatim between the training
    recurrent_group and the generation BeamSearchDecoder so all parameter
    names line up (the reference reuses the SubModelConfig the same way:
    RecurrentGradientMachine builds both training frames and generation
    frames from one step net)."""
    from paddle_tpu import dsl
    from paddle_tpu.core.config import ParameterConf

    def step(trg_word, enc):
        emb = dsl.embedding(trg_word, size=emb_dim, vocab_size=trg_vocab,
                            param=ParameterConf(name="trg_emb"),
                            name="trg_emb_lookup")
        prev = dsl.memory("dec_state", size=hidden)
        # additive attention over the encoder sequence — the shared
        # helper generates the exact layer names the previous inline
        # block used, so checkpoints stay compatible
        ctx_vec = dsl.simple_attention(enc, enc, prev, name="att",
                                       size=hidden)
        s = dsl.fc(emb, prev, ctx_vec, size=hidden, act="tanh",
                   name="dec_state")
        return dsl.fc(s, size=trg_vocab, act="softmax", name="dec_prob")

    return step


def _attention_decoder_state_step(hidden, trg_vocab, emb_dim):
    """Training-time step: returns the decoder STATE only; the h->V
    softmax projection is applied OUTSIDE the scan as one batched GEMM
    (see seq2seq_attention). Same parameters, same math."""
    from paddle_tpu import dsl
    from paddle_tpu.core.config import ParameterConf

    def step(trg_word, enc):
        emb = dsl.embedding(trg_word, size=emb_dim, vocab_size=trg_vocab,
                            param=ParameterConf(name="trg_emb"),
                            name="trg_emb_lookup")
        prev = dsl.memory("dec_state", size=hidden)
        ctx_vec = dsl.simple_attention(enc, enc, prev, name="att",
                                       size=hidden)
        return dsl.fc(emb, prev, ctx_vec, size=hidden, act="tanh",
                      name="dec_state")

    return step


def seq2seq_attention(
    src_vocab=30000,
    trg_vocab=30000,
    emb_dim=128,
    hidden=256,
    fused_decoder=False,
) -> ModelConf:
    """Attention NMT trainer config (the quick_start seqToseq demo /
    SURVEY.md north-star NMT). Teacher forcing: decoder consumes
    `trg_in` (BOS-prefixed) and is scored against `trg_out` (EOS-suffixed).
    Encoder hidden size = `hidden` (bidi concat of hidden/2 each).

    fused_decoder=True runs the decoder recurrence as the fused layer
    (layers/fused_text.py: hoisted input/context projections, merged
    prev-GEMMs — identical math and parameter names). Built to test
    the r4 hypothesis that the step was bound on the scan's serial op
    chain; MEASURED LOSING 0.93x on a healthy chip (PERF.md round 5 —
    the hypothesis was wrong, XLA's scan lowering was not
    overhead-bound), so it ships opt-in and the bench NMT row keeps a
    permanent plain-vs-fused A/B tripwire. False (default) is the
    generic recurrent_group lowering of the step net."""
    from paddle_tpu import dsl
    from paddle_tpu.core.config import InputConf, ParameterConf

    # the projection is hoisted OUT of the decoder scan: the step emits
    # the decoder state, and one batched [B*T, h] @ [h, V] GEMM applies
    # dec_prob afterwards — identical math and parameter names (the
    # generation decoder still projects in-step), but the 30 MB
    # projection weight is read once per batch instead of once per
    # timestep, and the GEMM is T× larger for the MXU (measured: the
    # in-scan form ran the whole step at 16.5% analytic MFU)
    step = _attention_decoder_state_step(hidden, trg_vocab, emb_dim)
    with dsl.model() as g:
        src = dsl.data("src", (1,), is_seq=True, is_ids=True)
        trg_in = dsl.data("trg_in", (1,), is_seq=True, is_ids=True)
        trg_out = dsl.data("trg_out", (1,), is_seq=True, is_ids=True)
        src_emb = dsl.embedding(src, size=emb_dim, vocab_size=src_vocab,
                                param=ParameterConf(name="src_emb"),
                                name="src_emb_lookup")
        fwd = dsl.simple_gru(src_emb, hidden // 2, name="enc_fwd")
        bwd = dsl.simple_gru(src_emb, hidden // 2, name="enc_bwd",
                             reversed=True)
        enc = dsl.concat(fwd, bwd, name="enc")
        # backward GRU's output at t=0 has processed the whole source
        # (its scan runs right-to-left and is re-reversed to time order)
        enc_summary = dsl.first_seq(bwd, name="enc_summary")
        boot = dsl.fc(enc_summary, size=hidden, act="tanh", name="dec_boot")
        if fused_decoder:
            trg_emb = dsl.embedding(
                trg_in, size=emb_dim, vocab_size=trg_vocab,
                param=ParameterConf(name="trg_emb"),
                name="trg_emb_lookup",
            )
            states = dsl._add(
                "fused_att_decoder", [trg_emb, enc, boot],
                name="decoder", size=hidden, bias=True,
            )
        else:
            states = dsl.recurrent_group(
                step, [trg_in, dsl.StaticInput(enc)], name="decoder"
            )
        prob = dsl.fc(states, size=trg_vocab, act="softmax",
                      name="dec_prob")
        dsl.cross_entropy(prob, trg_out, name="cost")
        g.conf.output_layer_names.append("dec_prob")
    if not fused_decoder:
        # wire the decoder-state boot to the parent layer
        rg = g.conf.layer("decoder")
        for m in rg.attrs["memories"]:
            if m["layer"] == "dec_state":
                m["boot_layer"] = "dec_boot"
        rg.inputs.append(InputConf("dec_boot"))
    return g.conf


def seq2seq_attention_decoder(
    trg_vocab=30000,
    emb_dim=128,
    hidden=256,
    bos_id=0,
    eos_id=1,
    beam_size=4,
    max_length=50,
    tokens_per_dispatch=1,
):
    """Generation decoder sharing parameter names with
    seq2seq_attention (use the trained params dict directly).
    `tokens_per_dispatch=K` advances K steps per compiled dispatch
    (ISSUE 18) — bit-identical output, chain depth ceil(max_length/K)."""
    from paddle_tpu.beam_search import BeamSearchDecoder

    step = _attention_decoder_step(hidden, trg_vocab, emb_dim)
    return BeamSearchDecoder(step, n_static=1, bos_id=bos_id, eos_id=eos_id,
                             beam_size=beam_size, max_length=max_length,
                             tokens_per_dispatch=tokens_per_dispatch)


def hierarchical_lstm_classifier(
    vocab_size=1000,
    emb_dim=16,
    hidden=32,
    num_classes=2,
) -> ModelConf:
    """Two-level document classifier over NESTED sequences (words
    grouped into sentences): the outer recurrent group walks sentences,
    its step encodes one sentence (embedding + rnn, last state) and
    chains a document memory across sentences — the
    RecurrentGradientMachine hierarchical mode
    (gserver/gradientmachines/RecurrentGradientMachine.cpp nested
    sequences, parameter/Argument.h:84-93; config analogue of the
    reference's gserver/tests/sequence_nest_rnn.conf)."""
    with dsl.model() as g:
        words = dsl.data("words", (1,), is_seq=True, is_ids=True,
                         has_subseq=True)
        lbl = dsl.data("label", (1,), is_ids=True)

        def sentence_step(w_sub):
            doc_prev = dsl.memory("doc", size=hidden)
            emb = dsl.embedding(w_sub, size=emb_dim,
                                vocab_size=vocab_size, name="word_emb")
            enc = dsl.recurrent(
                dsl.fc(emb, size=hidden, bias=True, name="sent_proj"),
                size=hidden, act="tanh", name="sent_rnn",
            )
            last = dsl.last_seq(enc, name="sent_vec")
            return dsl.mixed(
                hidden,
                [(last, "identity"), (doc_prev, "full_matrix")],
                act="tanh", bias=False, name="doc",
            )

        sent_seq = dsl.recurrent_group(sentence_step, [words],
                                       name="doc_enc")
        pooled = dsl.last_seq(sent_seq, name="doc_vec")
        out = dsl.fc(pooled, size=num_classes, name="output")
        dsl.classification_cost(out, lbl)
        g.conf.output_layer_names.append("output")
    return g.conf
