"""paddle_tpu — a TPU-native deep learning framework.

A brand-new framework with the capabilities of 2017-era PaddlePaddle
(reference surveyed in SURVEY.md) rebuilt idiomatically on JAX/XLA/Pallas:

- a config-driven layer/network system (reference: paddle/gserver/layers,
  python/paddle/trainer/config_parser.py) where forward passes are pure
  functions and gradients come from ``jax.grad`` rather than hand-written
  backward methods;
- padding-free variable-length sequence semantics expressed as dense
  [B, T] arrays plus length metadata (reference: paddle/parameter/Argument.h:84-93)
  with ``lax.scan`` recurrence instead of per-timestep frame networks;
- data/model parallelism via ``jax.sharding.Mesh`` + ``shard_map`` and XLA
  collectives over ICI (reference: MultiGradientMachine ring + C++/Go
  parameter servers, paddle/pserver, go/pserver);
- an event-driven Python training API with reader combinators and
  checkpointing (reference: python/paddle/v2).
"""

__version__ = "0.1.0"

# Eager imports stay jax-free so `import paddle_tpu` works in serving
# front ends / data workers without the device runtime (obs lint);
# Arg/get_mesh/set_mesh resolve lazily below.
from paddle_tpu.core import config, registry  # noqa: F401


def init(**flags):
    """Process-level init, analogous to paddle.init / initMain
    (reference: paddle/trainer/TrainerMain.cpp:32, paddle/utils/Flags.cpp).
    Accepts keyword flags stored in the global flag registry."""
    from paddle_tpu.core import flags as _flags

    for k, v in flags.items():
        _flags.set_flag(k, v)


_LAZY = {
    "dsl": "paddle_tpu.dsl",
    "layers": "paddle_tpu.layers",
    "models": "paddle_tpu.models",
    "optimizers": "paddle_tpu.optimizers",
    "evaluators": "paddle_tpu.evaluators",
    "inference": "paddle_tpu.inference",
    "api": "paddle_tpu.api",
    "plot": "paddle_tpu.plot",
    "image": "paddle_tpu.image",
    "framework": "paddle_tpu.framework",
    "dataset": "paddle_tpu.data.dataset",
    "reader": "paddle_tpu.data.reader",
}


def __getattr__(name):
    """Lazy submodule access (keeps `import paddle_tpu` light):
    paddle_tpu.dsl, paddle_tpu.dataset.mnist, paddle_tpu.infer, ..."""
    if name == "Arg":
        from paddle_tpu.core.arg import Arg

        return Arg
    if name in ("get_mesh", "set_mesh"):
        from paddle_tpu.core import mesh

        return getattr(mesh, name)
    if name == "Network":
        from paddle_tpu.network import Network

        return Network
    if name == "SGD":
        from paddle_tpu.trainer import SGD

        return SGD
    if name == "infer":
        from paddle_tpu.inference import infer

        return infer
    if name in _LAZY:
        import importlib

        return importlib.import_module(_LAZY[name])
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")
