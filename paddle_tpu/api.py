"""Compatibility surface mirroring the SWIG `swig_paddle` module.

Reference: paddle/api/PaddleAPI.h:103,244,402 and paddle/py_paddle —
Matrix/Vector/IVector with numpy zero-copy (api/Paddle.i:142-165),
Arguments, GradientMachine (createFromConfigProto, forward/backward),
ParameterUpdater, SequenceGenerator (api/SequenceGenerator.cpp). Our
native runtime IS Python+jax, so these are thin views over Network /
optimizers / BeamSearchDecoder, kept for users porting v1-era scripts;
new code should use those modules directly.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import numpy as np

from paddle_tpu.core.arg import Arg
from paddle_tpu.core.config import ModelConf, OptimizationConf
from paddle_tpu.network import Network
from paddle_tpu.optimizers import create_optimizer

__all__ = [
    "Matrix",
    "IVector",
    "Arguments",
    "GradientMachine",
    "ParameterUpdater",
    "SequenceGenerator",
]


class Matrix:
    """Dense float matrix with numpy round-trip (api/Matrix.cpp;
    createDenseFromNumpy / toNumpyMat)."""

    def __init__(self, array):
        self._a = np.asarray(array, np.float32)
        assert self._a.ndim == 2, "Matrix is 2-D"

    @classmethod
    def createDenseFromNumpy(cls, a):
        return cls(a)

    def toNumpyMat(self) -> np.ndarray:
        return self._a

    def getHeight(self):
        return self._a.shape[0]

    def getWidth(self):
        return self._a.shape[1]


class IVector:
    """Integer id vector (api/Vector.cpp)."""

    def __init__(self, array):
        self._a = np.asarray(array, np.int32).reshape(-1)

    @classmethod
    def createVectorFromNumpy(cls, a):
        return cls(a)

    def toNumpyArray(self) -> np.ndarray:
        return self._a


class Arguments:
    """Slot-indexed value/id holder (api/Arguments.cpp; the Argument
    bridging used by py_paddle.dataprovider_converter)."""

    def __init__(self, n_slots: int = 0):
        self._args = [Arg() for _ in range(n_slots)]

    @classmethod
    def createArguments(cls, n):
        return cls(n)

    def getSlotNum(self):
        return len(self._args)

    def setSlotValue(self, i: int, m: Matrix):
        self._args[i] = dataclasses.replace(
            self._args[i], value=jax.numpy.asarray(m.toNumpyMat())
        )

    def setSlotIds(self, i: int, v: IVector):
        self._args[i] = dataclasses.replace(
            self._args[i], ids=jax.numpy.asarray(v.toNumpyArray())
        )

    def getSlotValue(self, i: int) -> Matrix:
        return Matrix(np.asarray(self._args[i].value))

    def getSlotIds(self, i: int) -> IVector:
        return IVector(np.asarray(self._args[i].ids))

    def slots(self):
        return self._args


class GradientMachine:
    """Stateful wrapper over Network — createFromConfigProto +
    forward/backward/forwardBackward (api/GradientMachine.cpp;
    GradientMachine.h:72). Holds mutable params the way the SWIG object
    owned its Parameters."""

    def __init__(self, conf: ModelConf, seed: int = 0):
        self.net = Network(conf)
        self.params = self.net.init_params(jax.random.key(seed))
        self.state = self.net.init_state()
        self._grads = None

    @classmethod
    def createFromConfigProto(cls, conf: ModelConf) -> "GradientMachine":
        return cls(conf)

    def getParameterNames(self):
        return sorted(self.params)

    def getParameter(self, name: str) -> np.ndarray:
        return np.asarray(self.params[name])

    def setParameter(self, name: str, value) -> None:
        self.params[name] = jax.numpy.asarray(value)

    def forward(self, feed: dict, outputs=None) -> dict:
        outs, self.state = self.net.forward(
            self.params, feed, state=self.state, train=False,
            outputs=outputs,
        )
        return outs

    def forwardBackward(self, feed: dict, rng=None):
        """Returns the scalar cost; gradients retrievable via
        getGradient (the UpdateCallback analogue)."""
        (loss, (outs, new_state)), grads = jax.value_and_grad(
            self.net.loss_fn, has_aux=True
        )(self.params, feed, state=self.state, rng=rng)
        self.state = new_state
        self._grads = grads
        return float(loss), outs

    def getGradient(self, name: str) -> np.ndarray:
        assert self._grads is not None, "call forwardBackward first"
        return np.asarray(self._grads[name])


class ParameterUpdater:
    """Local updater (api/ParameterUpdater.cpp createLocalUpdater):
    applies the configured optimizer to a GradientMachine's params."""

    def __init__(self, opt_conf: OptimizationConf, gm: GradientMachine):
        self.gm = gm
        self.opt = create_optimizer(opt_conf, gm.net.param_confs)
        self.opt_state = self.opt.init_state(gm.params)
        self.step = 0

    @classmethod
    def createLocalUpdater(cls, opt_conf, gm):
        return cls(opt_conf, gm)

    def update(self) -> None:
        assert self.gm._grads is not None, "no gradients pending"
        self.gm.params, self.opt_state = self.opt.update(
            self.gm._grads, self.gm.params, self.opt_state, self.step
        )
        self.gm._grads = None
        self.step += 1


class SequenceGenerator:
    """Beam-search generation front-end (api/SequenceGenerator.cpp):
    wraps BeamSearchDecoder, returning id sequences per input."""

    def __init__(self, decoder, params: dict, dict_list=None,
                 num_results=None):
        self.decoder = decoder
        self.params = params
        self.dict_list = dict_list
        # beams returned per sample (v1 num_results_per_sample;
        # None = all beam_size beams)
        self.num_results = num_results

    def setBeamSize(self, k: int):
        self.decoder.k = k

    def registerBeamSearchControlCallbacks(
        self, adjust=None, drop=None, stop=None
    ):
        """User beam-control hooks, executed host-side each step
        (RecurrentGradientMachine.h:143-152
        registerBeamSearchControlCallbacks; see
        beam_search.BeamHooks for the signatures)."""
        from paddle_tpu.beam_search import BeamHooks

        self.decoder.hooks = BeamHooks(
            adjust=adjust, drop=drop, stop=stop
        )

    def removeBeamSearchControlCallbacks(self):
        """(RecurrentGradientMachine.h:155) back to plain beam search."""
        from paddle_tpu.beam_search import BeamHooks

        self.decoder.hooks = BeamHooks()

    def generate(self, statics: Sequence[Arg], boots=None):
        seqs, lens, scores = self.decoder.generate(
            self.params, list(statics), boots=boots
        )
        seqs, lens = np.asarray(seqs), np.asarray(lens)
        out = []
        n_keep = self.num_results or seqs.shape[1]
        for b in range(seqs.shape[0]):
            beams = []
            for k in range(min(n_keep, seqs.shape[1])):
                ids = seqs[b, k, : lens[b, k]].tolist()
                if self.dict_list is not None:
                    beams.append(
                        " ".join(self.dict_list[i] for i in ids)
                    )
                else:
                    beams.append(ids)
            out.append(beams)
        return out


def create_config_generator(model_conf, params, group_name=None):
    """SequenceGenerator for a GENERATING v1 config — the
    `beam_search(...)` declaration parsed into a
    SubModelConf(is_generating=True) (trainer_config_helpers
    beam_search:3893; executed upstream by
    RecurrentGradientMachine::generateSequence,
    RecurrentGradientMachine.h:307). The user step runs per decode
    step; the GeneratedInput position receives the `embedding_name`
    lookup of the previously generated word."""
    from paddle_tpu import dsl
    from paddle_tpu.beam_search import BeamSearchDecoder
    from paddle_tpu.core.config import ParameterConf

    def _find(conf):
        for sm in conf.sub_models:
            if sm.is_generating and (
                group_name is None or sm.name == group_name
            ):
                return sm, conf
        # a beam_search nested inside an outer recurrent_group's step
        # (the nested-generation form, sample_trainer_nest_rnn_gen:
        # each outer subsequence step generates one sequence) — its
        # statics are per-outer-step values, so the flat decoder runs
        # with batch = number of outer steps
        for lc in conf.layers:
            if lc.type == "recurrent_group":
                sub = lc.attrs.get("step_conf")
                if sub is not None:
                    found = _find(sub)
                    if found:
                        return found
        return None

    found = _find(model_conf)
    if not found:
        raise ValueError("config declares no generating beam_search group")
    gen_sm, host_conf = found
    a = gen_sm.attrs
    static_names = list(a["static_layer_names"])
    by_name = {lc.name: lc for lc in host_conf.layers}
    static_sizes = [by_name[n].size for n in static_names]

    def adapted_step(word, *statics):
        emb = dsl.embedding(
            word,
            size=a["embedding_size"],
            vocab_size=a["gen_size"],
            param=ParameterConf(name=a["embedding_name"]),
        )
        args = list(statics)
        args.insert(a["gen_pos"], emb)
        return a["step"](*args)

    dec = BeamSearchDecoder(
        adapted_step,
        n_static=len(static_names),
        bos_id=a["bos_id"],
        eos_id=a["eos_id"],
        beam_size=a["beam_size"],
        max_length=a["max_length"],
        static_sizes=static_sizes,
    )
    return (
        SequenceGenerator(dec, params, num_results=a["num_results"]),
        static_names,
        a,
    )
