"""Optimizers, LR schedulers, regularizers, parameter averaging.

Reference equations: paddle/parameter/FirstOrderOptimizer.h:23-320 and the
fused kernels in paddle/math/TrainingAlgorithmOp.h:38-114 (sgdUpdate,
adagradApply, adadeltaApply, rmspropApply, decayedAdagradApply, adamApply,
adamaxApply); schedulers: paddle/parameter/LearningRateScheduler.cpp:50-172;
regularizers: paddle/parameter/Regularizer.h; averaging:
paddle/parameter/AverageOptimizer.h.

TPU-first: one functional `update(grads, params, state, step)` jit-compiled
and shardable with the params; no per-block pserver traversal — the
optimizer runs sharded on-device under pjit (replacing
ParameterServer2::blockTraverse, pserver/ParameterServer2.h:637).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.core.config import OptimizationConf, ParameterConf
from paddle_tpu.core.registry import LR_SCHEDULERS, OPTIMIZERS


# ---------------- learning-rate schedulers ----------------
# reference: parameter/LearningRateScheduler.cpp:50-172

def _sched_constant(conf: OptimizationConf, step):
    return 1.0


def _sched_poly(conf, step):
    # lr * (1 + a*t)^(-b)
    t = step.astype(jnp.float32) if hasattr(step, "astype") else float(step)
    return jnp.power(1.0 + conf.learning_rate_decay_a * t, -conf.learning_rate_decay_b)


def _sched_exp(conf, step):
    # lr * a^(t/b)
    t = step
    return jnp.power(conf.learning_rate_decay_a, t / conf.learning_rate_decay_b)


def _sched_discexp(conf, step):
    # lr * a^floor(t/b)
    t = step
    return jnp.power(
        conf.learning_rate_decay_a, jnp.floor(t / conf.learning_rate_decay_b)
    )


def _sched_linear(conf, step):
    # max(lr - a*t, b) / lr  (linear_decay in reference returns absolute)
    lr = conf.learning_rate
    return jnp.maximum(lr - conf.learning_rate_decay_a * step, conf.learning_rate_decay_b) / lr


def _sched_caffe_poly(conf, step):
    # lr * (1 - t/a)^b while t <= a, else 0 (CaffePolyLRS). Time axis is
    # BATCH STEPS like every scheduler here (lr_at docstring); the
    # reference counts samples — scale decay_a by batch size when
    # porting configs.
    t = step
    a, b = conf.learning_rate_decay_a, conf.learning_rate_decay_b
    return jnp.where(
        t <= a, jnp.power(jnp.maximum(1.0 - t / a, 0.0), b), 0.0
    )


def _parse_lr_args(conf):
    """"seg1:rate1,seg2:rate2,..." (ManualLRS segment table)."""
    segs, rates = [], []
    for part in conf.learning_rate_args.split(","):
        part = part.strip()
        if not part:
            continue
        s, r = part.split(":")
        segs.append(float(s))
        rates.append(float(r))
    assert segs, "manual LR schedule needs learning_rate_args"
    return segs, rates


def _manual_select(segs, rates, t):
    out = jnp.asarray(rates[-1], jnp.float32)
    for s, r in reversed(list(zip(segs, rates))):
        out = jnp.where(t <= s, r, out)
    return out


def _sched_manual(conf, step):
    # segment table over BATCH STEPS (ManualLRS counts samples — scale
    # segment boundaries by batch size when porting configs)
    segs, rates = _parse_lr_args(conf)
    return _manual_select(segs, rates, step)


def _sched_pass_manual(conf, step):
    # segments over pass number (PassManualLRS); pass index derives
    # from batches_per_pass when set, else `step` is taken as the pass
    segs, rates = _parse_lr_args(conf)
    bpp = getattr(conf, "batches_per_pass", 0)
    t = jnp.floor(step / bpp) if bpp else step
    return _manual_select(segs, rates, t)


for _n, _f in [
    ("constant", _sched_constant),
    ("poly", _sched_poly),
    ("caffe_poly", _sched_caffe_poly),
    ("exp", _sched_exp),
    ("discexp", _sched_discexp),
    ("linear", _sched_linear),
    ("manual", _sched_manual),
    ("pass_manual", _sched_pass_manual),
]:
    LR_SCHEDULERS.register(_n)(type("S_" + _n, (), {"fn": staticmethod(_f)}))


def lr_at(conf: OptimizationConf, step) -> jax.Array:
    """Effective learning rate at `step` (num samples processed in the
    reference's pass-scale scheduling; we use batch steps)."""
    sched = LR_SCHEDULERS.get(conf.learning_rate_schedule).fn
    step = jnp.asarray(step, jnp.float32)
    return conf.learning_rate * sched(conf, step)


# ---------------- per-parameter static hyperparams ----------------

@dataclass(frozen=True)
class ParamHyper:
    lr_mult: float = 1.0
    l1: float = 0.0
    l2: float = 0.0
    clip: float = 0.0  # per-parameter clip threshold
    is_static: bool = False
    momentum: Optional[float] = None
    # static pruning (ParameterUpdaterHook.cpp:39 StaticPruningHook):
    # fraction of weights masked to zero by initial |value|
    sparsity_ratio: Optional[float] = None


def hyper_from_conf(pc: ParameterConf, opt: OptimizationConf) -> ParamHyper:
    return ParamHyper(
        lr_mult=pc.learning_rate,
        l1=pc.decay_rate_l1 if pc.decay_rate_l1 is not None else opt.l1_rate,
        l2=pc.decay_rate if pc.decay_rate is not None else opt.l2_rate,
        clip=pc.gradient_clipping_threshold or opt.gradient_clipping_threshold,
        is_static=pc.is_static,
        momentum=pc.momentum,
        sparsity_ratio=getattr(pc, "sparsity_ratio", None),
    )


def prune_mask(value: jax.Array, sparsity_ratio: float) -> jax.Array:
    """0/1 mask keeping EXACTLY the (1 - ratio) largest |value| entries
    (StaticPruningHook::generateMask). Index-based, so ties (e.g. a
    constant- or zero-initialized parameter) still honor the ratio."""
    flat = jnp.abs(value).ravel()
    keep = max(int(round(flat.size * (1.0 - sparsity_ratio))), 1)
    order = jnp.argsort(-flat)
    mask = jnp.zeros_like(flat).at[order[:keep]].set(1.0)
    return mask.reshape(value.shape).astype(value.dtype)


# ---------------- optimizer base ----------------

class Optimizer:
    """Functional optimizer. State is a pytree parallel to params."""

    name = None

    def __init__(self, conf: OptimizationConf, hypers: dict):
        self.conf = conf
        self.hypers = hypers  # param name -> ParamHyper

    def init_state(self, params: dict) -> dict:
        st = {}
        for k, v in params.items():
            s = self._init_one(v)
            h = self.hypers.get(k, ParamHyper())
            if h.sparsity_ratio:
                # mask fixed from the INITIAL weights (the reference
                # generates it once at the first update)
                s["prune_mask"] = prune_mask(v, h.sparsity_ratio)
            st[k] = s
        return st

    def update(self, grads: dict, params: dict, state: dict, step,
               lr_scale=None) -> tuple:
        """Returns (new_params, new_state). `step` is the global batch
        counter (0-based). `lr_scale` (optional traced scalar) scales
        the scheduled LR for this step — the watchdog's spike-backoff
        rung; scaling here (not the gradients) keeps adaptive moments
        (Adam m/v, Adagrad accumulators) fed with the TRUE gradient."""
        lr = lr_at(self.conf, step)
        if lr_scale is not None:
            lr = lr * lr_scale
        new_p, new_s = {}, {}
        for k, p in params.items():
            h = self.hypers.get(k, ParamHyper())
            g = grads.get(k)
            if g is None or h.is_static:
                new_p[k], new_s[k] = p, state[k]
                continue
            mask = state[k].get("prune_mask") if isinstance(
                state[k], dict
            ) else None
            if mask is not None:  # StaticPruningHook::update grad mask
                g = g * mask
            if h.clip > 0.0:
                g = jnp.clip(g, -h.clip, h.clip)
            # L2 decay folded into gradient (reference applies decay in the
            # update kernels, TrainingAlgorithmOp.h sgdUpdate decayRate)
            if h.l2 > 0.0:
                g = g + h.l2 * p
            np_, ns_ = self._apply_one(p, g, state[k], lr * h.lr_mult, h, step)
            # L1: proximal shrinkage after the step (reference
            # applyL1 in Regularizer)
            if h.l1 > 0.0:
                shrink = lr * h.lr_mult * h.l1
                np_ = jnp.sign(np_) * jnp.maximum(jnp.abs(np_) - shrink, 0.0)
            if mask is not None:
                # keep pruned weights exactly zero (decay/momentum must
                # not revive them) and carry the mask in the new state
                np_ = np_ * mask
                ns_["prune_mask"] = mask
            new_p[k], new_s[k] = np_, ns_
        return new_p, new_s

    def _init_one(self, p):
        raise NotImplementedError

    def _apply_one(self, p, g, s, lr, h, step):
        raise NotImplementedError


@OPTIMIZERS.register("sgd", "momentum")
class SgdOptimizer(Optimizer):
    """SGD + (optionally Nesterov) momentum
    (TrainingAlgorithmOp.h sgdUpdate, FirstOrderOptimizer.h SgdOptimizer)."""

    def _init_one(self, p):
        return {"mom": jnp.zeros_like(p)}

    def _apply_one(self, p, g, s, lr, h, step):
        mu = h.momentum if h.momentum is not None else self.conf.momentum
        v = mu * s["mom"] - lr * g
        if self.conf.use_nesterov:
            p_new = p + mu * v - lr * g
        else:
            p_new = p + v
        return p_new, {"mom": v}


@OPTIMIZERS.register("adagrad")
class AdagradOptimizer(Optimizer):
    """accum += g^2; p -= lr * g / (sqrt(accum) + eps)
    (TrainingAlgorithmOp.h adagradApply)."""

    def _init_one(self, p):
        return {"accum": jnp.zeros_like(p)}

    def _apply_one(self, p, g, s, lr, h, step):
        accum = s["accum"] + jnp.square(g)
        p_new = p - lr * g / (jnp.sqrt(accum) + self.conf.ada_epsilon)
        return p_new, {"accum": accum}


@OPTIMIZERS.register("decayed_adagrad")
class DecayedAdagradOptimizer(Optimizer):
    """accum = rou*accum + (1-rou)*g^2 (TrainingAlgorithmOp.h
    decayedAdagradApply)."""

    def _init_one(self, p):
        return {"accum": jnp.zeros_like(p)}

    def _apply_one(self, p, g, s, lr, h, step):
        rou = self.conf.ada_rou
        accum = rou * s["accum"] + (1 - rou) * jnp.square(g)
        p_new = p - lr * g / (jnp.sqrt(accum) + self.conf.ada_epsilon)
        return p_new, {"accum": accum}


@OPTIMIZERS.register("adadelta")
class AdadeltaOptimizer(Optimizer):
    """(TrainingAlgorithmOp.h adadeltaApply)."""

    def _init_one(self, p):
        return {"accum": jnp.zeros_like(p), "accum_update": jnp.zeros_like(p)}

    def _apply_one(self, p, g, s, lr, h, step):
        rou, eps = self.conf.ada_rou, self.conf.ada_epsilon
        accum = rou * s["accum"] + (1 - rou) * jnp.square(g)
        upd = g * jnp.sqrt((s["accum_update"] + eps) / (accum + eps))
        accum_update = rou * s["accum_update"] + (1 - rou) * jnp.square(upd)
        return p - lr * upd, {"accum": accum, "accum_update": accum_update}


@OPTIMIZERS.register("rmsprop")
class RMSPropOptimizer(Optimizer):
    """g_accum = rou*g_accum + (1-rou)*g^2, with mean-removal term as in
    TrainingAlgorithmOp.h rmspropApply (tracks E[g] too)."""

    def _init_one(self, p):
        return {"g2": jnp.zeros_like(p), "g1": jnp.zeros_like(p)}

    def _apply_one(self, p, g, s, lr, h, step):
        rou, eps = self.conf.ada_rou, self.conf.ada_epsilon
        g2 = rou * s["g2"] + (1 - rou) * jnp.square(g)
        g1 = rou * s["g1"] + (1 - rou) * g
        denom = jnp.sqrt(g2 - jnp.square(g1) + eps)
        return p - lr * g / denom, {"g2": g2, "g1": g1}


@OPTIMIZERS.register("adam")
class AdamOptimizer(Optimizer):
    """(TrainingAlgorithmOp.h adamApply; FirstOrderOptimizer.h AdamOptimizer)."""

    def _init_one(self, p):
        return {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p)}

    def _apply_one(self, p, g, s, lr, h, step):
        b1, b2, eps = self.conf.adam_beta1, self.conf.adam_beta2, self.conf.adam_epsilon
        t = jnp.asarray(step, jnp.float32) + 1.0
        m = b1 * s["m"] + (1 - b1) * g
        v = b2 * s["v"] + (1 - b2) * jnp.square(g)
        mhat = m / (1 - jnp.power(b1, t))
        vhat = v / (1 - jnp.power(b2, t))
        return p - lr * mhat / (jnp.sqrt(vhat) + eps), {"m": m, "v": v}


@OPTIMIZERS.register("adamax")
class AdamaxOptimizer(Optimizer):
    """(TrainingAlgorithmOp.h adamaxApply)."""

    def _init_one(self, p):
        return {"m": jnp.zeros_like(p), "u": jnp.zeros_like(p)}

    def _apply_one(self, p, g, s, lr, h, step):
        b1, b2 = self.conf.adam_beta1, self.conf.adam_beta2
        t = jnp.asarray(step, jnp.float32) + 1.0
        m = b1 * s["m"] + (1 - b1) * g
        u = jnp.maximum(b2 * s["u"], jnp.abs(g))
        p_new = p - (lr / (1 - jnp.power(b1, t))) * m / (u + 1e-12)
        return p_new, {"m": m, "u": u}


# ---------------- parameter averaging ----------------

@dataclass
class AverageState:
    """Sliding parameter average (parameter/AverageOptimizer.h): keeps
    sum of recent params; `apply` swaps in the average for test, `restore`
    swaps back — we keep it functional: average() returns averaged params."""

    accum: dict
    count: int = 0


class ParameterAverager:
    """Sliding average via windowed restart: the accumulator is reset
    whenever it covers more than `window * total_updates` (capped at
    `max_window`) updates, so `average()` reflects recent parameters —
    matching AverageOptimizer's bounded-window intent."""

    def __init__(self, window: float, max_window: int):
        self.window = window
        self.max_window = max_window
        self._total = 0

    def init(self, params):
        return AverageState(
            accum=jax.tree_util.tree_map(jnp.zeros_like, params), count=0
        )

    def accumulate(self, st: AverageState, params) -> AverageState:
        self._total += 1
        limit = self.window * self._total if self.window > 0 else float("inf")
        if self.max_window > 0:
            limit = min(limit, self.max_window)
        if st.count >= max(limit, 1):
            st = AverageState(
                accum=jax.tree_util.tree_map(jnp.zeros_like, st.accum), count=0
            )
        return AverageState(
            accum=jax.tree_util.tree_map(lambda a, p: a + p, st.accum, params),
            count=st.count + 1,
        )

    def average(self, st: AverageState, params):
        if st.count == 0:
            return params
        return jax.tree_util.tree_map(lambda a: a / st.count, st.accum)


def create_optimizer(conf: OptimizationConf, param_confs: dict) -> Optimizer:
    hypers = {k: hyper_from_conf(pc, conf) for k, pc in param_confs.items()}
    cls = OPTIMIZERS.get(conf.learning_method)
    return cls(conf, hypers)
