"""CIFAR-10/100 (python/paddle/v2/dataset/cifar.py): samples are
(float32[3072] pixels scaled to [0, 1], int label); parses the cached
python-version tarballs when present (pickled batches under
cifar-10-batches-py / cifar-100-python), else synthetic."""

from __future__ import annotations

import pickle
import tarfile

import numpy as np

from paddle_tpu.data.dataset import common

__all__ = ["convert", "train10", "test10", "train100", "test100"]

CIFAR10_URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
CIFAR100_URL = "https://www.cs.toronto.edu/~kriz/cifar-100-python.tar.gz"


def _tar_reader(url, sub_name, label_key):
    path = common.download(url, "cifar")

    def reader():
        with tarfile.open(path, mode="r") as f:
            names = [
                n for n in f.getnames() if sub_name in n.split("/")[-1]
            ]
            for name in names:
                batch = pickle.load(f.extractfile(name), encoding="bytes")
                data = batch[b"data"]
                labels = batch.get(label_key)
                for i in range(len(labels)):
                    yield (
                        (data[i] / 255.0).astype(np.float32),
                        int(labels[i]),
                    )

    return reader


def _synth_reader(split_name, num_classes, n):
    def reader():
        rng = common.synthetic_rng("cifar", split_name)
        labels = rng.integers(0, num_classes, n)
        for i in range(n):
            x = rng.uniform(0, 1, 3072).astype(np.float32)
            c = int(labels[i])
            x[c * 30 : c * 30 + 20] += 0.8
            yield np.clip(x, 0, 1), c

    return reader


def _creator(url, sub_name, label_key, split_name, num_classes, n_synth):
    def reader():
        try:
            inner = _tar_reader(url, sub_name, label_key)
        except FileNotFoundError:
            inner = _synth_reader(split_name, num_classes, n_synth)
        yield from inner()

    return reader


def train10():
    return _creator(CIFAR10_URL, "data_batch", b"labels", "train10", 10, 512)


def test10():
    return _creator(CIFAR10_URL, "test_batch", b"labels", "test10", 10, 128)


def train100():
    return _creator(CIFAR100_URL, "train", b"fine_labels", "train100", 100,
                    512)


def test100():
    return _creator(CIFAR100_URL, "test", b"fine_labels", "test100", 100,
                    128)


def convert(path):
    """Write the dataset as chunked recordio files for the cloud/
    elastic-master input path (reference cifar.py convert;
    common.convert -> go/master RecordIO tasks).
    """
    common.convert(path, train100(), 1000, "cifar_train100")
    common.convert(path, test100(), 1000, "cifar_test100")
    common.convert(path, train10(), 1000, "cifar_train10")
    common.convert(path, test10(), 1000, "cifar_test10")
