"""Dataset cache plumbing.

Reference: python/paddle/v2/dataset/common.py (DATA_HOME, download with
md5 verification, split, cluster_files_reader, convert-to-recordio).

This environment has no network egress, so `download` only resolves
already-cached files; when a dataset file is absent the dataset modules
fall back to a DETERMINISTIC synthetic sample stream with the exact
reference schema (shapes, dtypes, vocabulary behavior) so pipelines,
trainers and tests exercise the same code paths. Set
`require_real_data(True)` to turn the fallback into an error instead.
"""

from __future__ import annotations

import hashlib
import os
import pickle

import numpy as np

__all__ = [
    "DATA_HOME",
    "cached_path",
    "download",
    "md5file",
    "split",
    "cluster_files_reader",
    "convert",
    "require_real_data",
    "synthetic_rng",
]

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset")
)

_REQUIRE_REAL = False


def require_real_data(flag: bool = True) -> None:
    global _REQUIRE_REAL
    _REQUIRE_REAL = flag


def md5file(fname: str) -> str:
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 16), b""):
            h.update(chunk)
    return h.hexdigest()


def cached_path(url: str, module_name: str, md5sum: str = None):
    """Path where `download` would store this url's file."""
    d = os.path.join(DATA_HOME, module_name)
    return os.path.join(d, url.split("/")[-1])


def download(url: str, module_name: str, md5sum: str = None) -> str:
    """Return the cached file for `url`, verifying md5 when given.
    No egress: if the file is not already in DATA_HOME, raises (caller
    modules catch this and emit synthetic data unless
    require_real_data(True))."""
    path = cached_path(url, module_name)
    if os.path.exists(path):
        if md5sum and md5file(path) != md5sum:
            raise IOError(f"md5 mismatch for cached {path}")
        return path
    raise FileNotFoundError(
        f"{path} not cached and downloads are disabled; place the file "
        f"there manually or rely on the synthetic fallback"
    )


def synthetic_rng(module_name: str, split_name: str) -> np.random.Generator:
    """Deterministic per-(dataset, split) generator for the fallback."""
    if _REQUIRE_REAL:
        raise FileNotFoundError(
            f"real data for {module_name}/{split_name} not cached and "
            f"require_real_data(True) is set"
        )
    seed = int.from_bytes(
        hashlib.md5(f"{module_name}:{split_name}".encode()).digest()[:4],
        "little",
    )
    return np.random.default_rng(seed)


def split(reader, line_count: int, suffix: str = "%05d.pickle",
          dumper=None):
    """Split a reader's samples into pickled chunk files
    (common.py split)."""
    dumper = dumper or (lambda obj, f: pickle.dump(obj, f, 2))
    buf, index = [], 0
    out = []
    for sample in reader():
        buf.append(sample)
        if len(buf) == line_count:
            fname = suffix % index
            with open(fname, "wb") as f:
                dumper(buf, f)
            out.append(fname)
            buf, index = [], index + 1
    if buf:
        fname = suffix % index
        with open(fname, "wb") as f:
            dumper(buf, f)
        out.append(fname)
    return out


def cluster_files_reader(files_pattern: str, trainer_count: int,
                         trainer_id: int, loader=None):
    """Round-robin shard chunk files across trainers
    (common.py cluster_files_reader)."""
    import glob

    loader = loader or (lambda f: pickle.load(f))

    def reader():
        file_list = sorted(glob.glob(files_pattern))
        my_files = [
            f
            for i, f in enumerate(file_list)
            if i % trainer_count == trainer_id
        ]
        for fn in my_files:
            with open(fn, "rb") as f:
                for sample in loader(f):
                    yield sample

    return reader


def convert(output_path: str, reader, line_count: int, name_prefix: str):
    """Serialize a reader into chunked recordio files for the elastic
    master (common.py convert; go/master RecordIO tasks) using the native
    chunked record writer."""
    from paddle_tpu.native.recordio import RecordWriter

    os.makedirs(output_path, exist_ok=True)
    buf, index = [], 0
    paths = []

    def flush(buf, index):
        path = os.path.join(
            output_path, f"{name_prefix}-{index:05d}.recordio"
        )
        w = RecordWriter(path)
        for sample in buf:
            w.write(pickle.dumps(sample, 2))
        w.close()
        paths.append(path)

    for sample in reader():
        buf.append(sample)
        if len(buf) == line_count:
            flush(buf, index)
            buf, index = [], index + 1
    if buf:
        flush(buf, index)
    return paths
