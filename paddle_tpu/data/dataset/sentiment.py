"""NLTK movie-reviews sentiment (python/paddle/v2/dataset/sentiment.py):
get_word_dict() -> token->id; train()/test() yield ([word ids],
label 0=neg 1=pos), 9:1 split."""

from __future__ import annotations

from paddle_tpu.data.dataset import common

__all__ = ["convert", "get_word_dict", "train", "test"]

_VOCAB = 180


def get_word_dict():
    d = {f"w{i}": i for i in range(_VOCAB)}
    return d


def _creator(split_name, n):
    def reader():
        rng = common.synthetic_rng("sentiment", split_name)
        for _ in range(n):
            label = int(rng.integers(0, 2))
            lean_lo = 20 if label else 100
            ln = int(rng.integers(6, 30))
            ids = [
                int(rng.integers(lean_lo, lean_lo + 40))
                if rng.random() < 0.6
                else int(rng.integers(0, _VOCAB))
                for _ in range(ln)
            ]
            yield ids, label

    return reader


def train():
    return _creator("train", 450)


def test():
    return _creator("test", 50)


def convert(path):
    """Write the dataset as chunked recordio files for the cloud/
    elastic-master input path (reference sentiment.py convert;
    common.convert -> go/master RecordIO tasks).
    """
    common.convert(path, train(), 1000, "sentiment_train")
    common.convert(path, test(), 1000, "sentiment_test")
