"""MovieLens-1M (python/paddle/v2/dataset/movielens.py): each sample is
user features + movie features + [[rating]]:
[user_id, gender_id, age_id, job_id, movie_id, category_ids(multi-hot
list), title_ids(list), [rating]] (movielens.py:159 usr.value() +
mov.value() + [[rating]]). Helpers: movie_categories, max_user_id,
max_movie_id, max_job_id, age_table."""

from __future__ import annotations

from paddle_tpu.data.dataset import common

__all__ = [
    "convert",
    "train",
    "test",
    "movie_categories",
    "max_user_id",
    "max_movie_id",
    "max_job_id",
    "age_table",
    "get_movie_title_dict",
]

_CATEGORIES = [
    "Action", "Adventure", "Animation", "Children's", "Comedy", "Crime",
    "Documentary", "Drama", "Fantasy", "Film-Noir", "Horror", "Musical",
    "Mystery", "Romance", "Sci-Fi", "Thriller", "War", "Western",
]
age_table = [1, 18, 25, 35, 45, 50, 56]

_N_USERS = 400
_N_MOVIES = 300
_N_JOBS = 21
_TITLE_VOCAB = 100


def movie_categories():
    return {c: i for i, c in enumerate(_CATEGORIES)}


def get_movie_title_dict():
    return {f"t{i}": i for i in range(_TITLE_VOCAB)}


def max_user_id():
    return _N_USERS


def max_movie_id():
    return _N_MOVIES


def max_job_id():
    return _N_JOBS - 1


def _creator(split_name, n):
    def reader():
        rng = common.synthetic_rng("movielens", split_name)
        for _ in range(n):
            user = int(rng.integers(1, _N_USERS + 1))
            gender = int(rng.integers(0, 2))
            age = int(rng.integers(0, len(age_table)))
            job = int(rng.integers(0, _N_JOBS))
            movie = int(rng.integers(1, _N_MOVIES + 1))
            cats = rng.choice(
                len(_CATEGORIES), size=int(rng.integers(1, 4)),
                replace=False,
            ).tolist()
            title = rng.integers(
                0, _TITLE_VOCAB, int(rng.integers(1, 6))
            ).tolist()
            # rating correlates with (user+movie) parity so models learn
            base = 3.0 + ((user + movie) % 3 - 1)
            rating = float(min(5, max(1, round(base + rng.normal(0, 0.5)))))
            yield [user, gender, age, job, movie, cats, title, [rating]]

    return reader


def train():
    return _creator("train", 1024)


def test():
    return _creator("test", 256)


def convert(path):
    """Write the dataset as chunked recordio files for the cloud/
    elastic-master input path (reference movielens.py convert;
    common.convert -> go/master RecordIO tasks).
    """
    common.convert(path, train(), 1000, "movielens_train")
    common.convert(path, test(), 1000, "movielens_test")
