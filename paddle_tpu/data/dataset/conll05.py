"""CoNLL-2005 semantic role labeling
(python/paddle/v2/dataset/conll05.py): test() yields 9 slots per
predicate instance — (word_ids, predicate_id, ctx_n2, ctx_n1, ctx_0,
ctx_p1, ctx_p2, mark, label_ids) (conll05.py:175). get_dict() returns
(word_dict, verb_dict, label_dict); get_embedding() the pretrained
emb matrix (synthetic here)."""

from __future__ import annotations

import numpy as np

from paddle_tpu.data.dataset import common

__all__ = ["convert", "get_dict", "get_embedding", "test"]

_WORDS = 150
_VERBS = 20
_LABELS = ["O", "B-A0", "I-A0", "B-A1", "I-A1", "B-V", "I-V"]


def get_dict():
    word_dict = {f"w{i}": i for i in range(_WORDS)}
    verb_dict = {f"v{i}": i for i in range(_VERBS)}
    label_dict = {l: i for i, l in enumerate(_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding(emb_dim: int = 32):
    rng = common.synthetic_rng("conll05", "emb")
    return rng.standard_normal((_WORDS, emb_dim)).astype(np.float32)


def test():
    word_dict, verb_dict, label_dict = get_dict()

    def reader():
        rng = common.synthetic_rng("conll05", "test")
        for _ in range(200):
            ln = int(rng.integers(5, 18))
            words = rng.integers(0, _WORDS, ln).tolist()
            vpos = int(rng.integers(0, ln))
            verb = int(rng.integers(0, _VERBS))

            def ctx(off):
                p = vpos + off
                return words[p] if 0 <= p < ln else 0

            mark = [1 if i == vpos else 0 for i in range(ln)]
            labels = []
            for i in range(ln):
                if i == vpos:
                    labels.append(label_dict["B-V"])
                elif i == vpos - 1 and i >= 0:
                    labels.append(label_dict["B-A0"])
                elif i == vpos + 1 and i < ln:
                    labels.append(label_dict["B-A1"])
                else:
                    labels.append(label_dict["O"])
            yield (
                words,
                verb,
                ctx(-2),
                ctx(-1),
                ctx(0),
                ctx(1),
                ctx(2),
                mark,
                labels,
            )

    return reader


def convert(path):
    """Write the dataset as chunked recordio files for the cloud/
    elastic-master input path (reference conll05.py convert;
    common.convert -> go/master RecordIO tasks).
    """
    # like the reference, only the test split is publicly
    # distributable; it feeds both prefixes
    common.convert(path, test(), 1000, "conll05_train")
    common.convert(path, test(), 1000, "conll05_test")
