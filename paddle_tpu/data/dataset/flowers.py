"""Oxford-102 flowers (python/paddle/v2/dataset/flowers.py): train/
test/valid readers yield (float32 CHW image flattened, label 0..101)
(flowers.py:119 yields label-1). Synthetic fallback: small 3x32x32
class-tinted images."""

from __future__ import annotations

import numpy as np

from paddle_tpu.data.dataset import common

__all__ = ["convert", "train", "test", "valid"]

_CLASSES = 102
_SHAPE = (3, 32, 32)


def _creator(split_name, n):
    def reader():
        rng = common.synthetic_rng("flowers", split_name)
        for _ in range(n):
            label = int(rng.integers(0, _CLASSES))
            img = rng.uniform(0, 1, _SHAPE).astype(np.float32)
            img[label % 3] += (label / _CLASSES) * 0.5
            yield np.clip(img, 0, 1).flatten(), label

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=False):
    return _creator("train", 408)


def test(mapper=None, buffered_size=1024, use_xmap=False):
    return _creator("test", 102)


def valid(mapper=None, buffered_size=1024, use_xmap=False):
    return _creator("valid", 102)


def convert(path):
    """Write the dataset as chunked recordio files for the cloud/
    elastic-master input path (no reference convert for this module; added so every dataset
    feeds the cloud input path uniformly; common.convert -> go/master
    RecordIO tasks).
    """
    common.convert(path, train(), 200, "flowers_train")
    common.convert(path, valid(), 200, "flowers_valid")
    common.convert(path, test(), 200, "flowers_test")
