"""Dataset package (reference: python/paddle/v2/dataset/__init__.py —
13 auto-downloading datasets). Zero-egress build: loaders parse cached
files under common.DATA_HOME when present and otherwise emit
deterministic synthetic streams with the reference schemas."""

from paddle_tpu.data.dataset import (  # noqa: F401
    cifar,
    common,
    conll05,
    flowers,
    imdb,
    imikolov,
    mnist,
    movielens,
    mq2007,
    sentiment,
    uci_housing,
    voc2012,
    wmt14,
)

__all__ = [
    "cifar",
    "common",
    "conll05",
    "flowers",
    "imdb",
    "imikolov",
    "mnist",
    "movielens",
    "mq2007",
    "sentiment",
    "uci_housing",
    "voc2012",
    "wmt14",
]
