"""IMDB sentiment (python/paddle/v2/dataset/imdb.py): word_dict() maps
token -> id sorted by frequency; train/test readers yield
([word ids], label 0/1). Parses the cached aclImdb tarball when present,
else a synthetic corpus with a class-informative vocabulary."""

from __future__ import annotations

import re
import string
import tarfile

from paddle_tpu.data.dataset import common

__all__ = ["convert", "word_dict", "train", "test"]

URL = (
    "http://ai.stanford.edu/%7Eamaas/data/sentiment/aclImdb_v1.tar.gz"
)

_VOCAB = 200
_POS_WORDS = list(range(10, 60))  # synthetic positive-leaning ids
_NEG_WORDS = list(range(60, 110))


def tokenize(s: str):
    return re.sub(
        f"[{string.punctuation}]", "", s.lower()
    ).split()


def _real_docs(pattern):
    path = common.download(URL, "imdb")
    qs = re.compile(pattern)
    with tarfile.open(path) as t:
        for member in t.getmembers():
            if qs.match(member.name):
                yield tokenize(t.extractfile(member).read().decode())


def _synth_docs(split_name, n=256):
    rng = common.synthetic_rng("imdb", split_name)
    for i in range(n):
        label = int(rng.integers(0, 2))
        # label convention matches the real path below: positive=0
        lean = _POS_WORDS if label == 0 else _NEG_WORDS
        ln = int(rng.integers(8, 40))
        words = [
            f"w{rng.choice(lean)}"
            if rng.random() < 0.6
            else f"w{rng.integers(0, _VOCAB)}"
            for _ in range(ln)
        ]
        yield words, label


def word_dict(cutoff: int = 150):
    """token -> id, most frequent first, from the LABELED train+test
    pos/neg docs with a frequency cutoff (imdb.py word_dict: build_dict
    over train|test/pos|neg, cutoff 150 — NOT train/unsup or the
    urls_*.txt index files). The synthetic corpus skips the cutoff (it
    is far smaller than the real 25k-review corpus)."""
    from collections import Counter

    cnt = Counter()
    try:
        for doc in _real_docs(
            "aclImdb/(train|test)/(pos|neg)/.*\\.txt$"
        ):
            cnt.update(doc)
        cnt = Counter(
            {w: c for w, c in cnt.items() if c >= cutoff}
        )
    except FileNotFoundError:
        for words, _ in _synth_docs("train"):
            cnt.update(words)
    items = sorted(cnt.items(), key=lambda kv: (-kv[1], kv[0]))
    d = {w: i for i, (w, _) in enumerate(items)}
    d["<unk>"] = len(d)
    return d


def _creator(split_name, pos_pattern, neg_pattern, word_idx):
    unk = word_idx.get("<unk>", len(word_idx) - 1)

    def reader():
        try:
            for doc in _real_docs(pos_pattern):
                yield [word_idx.get(w, unk) for w in doc], 0
            for doc in _real_docs(neg_pattern):
                yield [word_idx.get(w, unk) for w in doc], 1
        except FileNotFoundError:
            for words, label in _synth_docs(split_name):
                yield [word_idx.get(w, unk) for w in words], label

    return reader


def train(word_idx):
    return _creator(
        "train",
        "aclImdb/train/pos/.*\\.txt$",
        "aclImdb/train/neg/.*\\.txt$",
        word_idx,
    )


def test(word_idx):
    return _creator(
        "test",
        "aclImdb/test/pos/.*\\.txt$",
        "aclImdb/test/neg/.*\\.txt$",
        word_idx,
    )


def convert(path):
    """Write the dataset as chunked recordio files for the cloud/
    elastic-master input path (reference imdb.py convert;
    common.convert -> go/master RecordIO tasks).
    """
    w = word_dict()
    common.convert(path, train(w), 1000, "imdb_train")
    common.convert(path, test(w), 1000, "imdb_test")
