"""PASCAL VOC2012 segmentation (python/paddle/v2/dataset/voc2012.py):
train/test/val readers yield (float32 CHW image, int32 HW label map)
(voc2012.py:62). Synthetic fallback: blocky two-object scenes over 21
classes (20 + background)."""

from __future__ import annotations

import numpy as np

from paddle_tpu.data.dataset import common

__all__ = ["convert", "train", "test", "val"]

_CLASSES = 21
_HW = 32


def _creator(split_name, n):
    def reader():
        rng = common.synthetic_rng("voc2012", split_name)
        for _ in range(n):
            img = rng.uniform(0, 1, (3, _HW, _HW)).astype(np.float32)
            lbl = np.zeros((_HW, _HW), np.int32)
            for _ in range(int(rng.integers(1, 3))):
                c = int(rng.integers(1, _CLASSES))
                x, y = rng.integers(0, _HW - 8, 2)
                w, h = rng.integers(6, 12, 2)
                lbl[y : y + h, x : x + w] = c
                img[:, y : y + h, x : x + w] += c / _CLASSES
            yield np.clip(img, 0, 1.5), lbl

    return reader


def train():
    return _creator("train", 128)


def test():
    return _creator("test", 32)


def val():
    return _creator("val", 32)


def convert(path):
    """Write the dataset as chunked recordio files for the cloud/
    elastic-master input path (no reference convert for this module; added so every dataset
    feeds the cloud input path uniformly; common.convert -> go/master
    RecordIO tasks).
    """
    common.convert(path, train(), 200, "voc2012_train")
    common.convert(path, val(), 200, "voc2012_val")
    common.convert(path, test(), 200, "voc2012_test")
