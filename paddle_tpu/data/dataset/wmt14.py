"""WMT14 en-fr translation (python/paddle/v2/dataset/wmt14.py): train/
test(dict_size) readers yield (src_ids, trg_ids, trg_ids_next) with
<s>=0, <e>=1, <unk>=2 (wmt14.py:39-42,87-101). Synthetic fallback emits
an invertible toy translation task (target = reversed source over a
disjoint vocab half)."""

from __future__ import annotations

from paddle_tpu.data.dataset import common

__all__ = ["convert", "train", "test", "get_dict"]

START_ID, END_ID, UNK_IDX = 0, 1, 2


def get_dict(dict_size: int):
    """(src_dict, trg_dict): id -> token."""
    src = {0: "<s>", 1: "<e>", 2: "<unk>"}
    trg = dict(src)
    for i in range(3, dict_size):
        src[i] = f"src{i}"
        trg[i] = f"trg{i}"
    return src, trg


def _creator(split_name, dict_size, n):
    def reader():
        rng = common.synthetic_rng("wmt14", split_name)
        for _ in range(n):
            ln = int(rng.integers(3, 12))
            body = rng.integers(3, dict_size, ln).tolist()
            src_ids = [START_ID] + body + [END_ID]
            trg_body = list(reversed(body))
            trg_ids = [START_ID] + trg_body
            trg_ids_next = trg_body + [END_ID]
            yield src_ids, trg_ids, trg_ids_next

    return reader


def train(dict_size: int):
    return _creator("train", dict_size, n=512)


def test(dict_size: int):
    return _creator("test", dict_size, n=128)


def convert(path):
    """Write the dataset as chunked recordio files for the cloud/
    elastic-master input path (reference wmt14.py convert;
    common.convert -> go/master RecordIO tasks).
    """
    dict_size = 30000
    common.convert(path, train(dict_size), 1000, "wmt14_train")
    common.convert(path, test(dict_size), 1000, "wmt14_test")
