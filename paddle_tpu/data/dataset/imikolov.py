"""PTB language modeling (python/paddle/v2/dataset/imikolov.py):
build_dict(min_word_freq) -> token->id with <s>, <e>, <unk>;
train/test(word_idx, n, data_type) yields either n-gram id tuples
(DataType.NGRAM) or (src_seq, trg_seq) next-word pairs (DataType.SEQ)."""

from __future__ import annotations

import tarfile

from paddle_tpu.data.dataset import common

__all__ = ["convert", "build_dict", "train", "test", "DataType"]

URL = "http://www.fit.vutbr.cz/~imikolov/rnnlm/simple-examples.tgz"
_SYN_VOCAB = 120


class DataType:
    NGRAM = 1
    SEQ = 2


def _real_lines(file_name):
    path = common.download(URL, "imikolov")
    with tarfile.open(path) as t:
        for line in t.extractfile(file_name):
            yield line.decode().split()


def _synth_lines(split_name, n=400):
    rng = common.synthetic_rng("imikolov", split_name)
    for _ in range(n):
        ln = int(rng.integers(4, 20))
        # zipf-ish draw so min_word_freq filtering is meaningful
        yield [f"w{int(rng.zipf(1.3)) % _SYN_VOCAB}" for _ in range(ln)]


def _lines(split_name):
    fn = (
        "./simple-examples/data/ptb.train.txt"
        if split_name == "train"
        else "./simple-examples/data/ptb.valid.txt"
    )
    try:
        yield from _real_lines(fn)
    except FileNotFoundError:
        yield from _synth_lines(split_name)


def build_dict(min_word_freq: int = 50):
    from collections import Counter

    cnt = Counter()
    for words in _lines("train"):
        cnt.update(words)
    cnt = {k: v for k, v in cnt.items() if v >= min_word_freq}
    items = sorted(cnt.items(), key=lambda kv: (-kv[1], kv[0]))
    word_idx = {w: i for i, (w, _) in enumerate(items)}
    word_idx["<s>"] = len(word_idx)
    word_idx["<e>"] = len(word_idx)
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def _creator(split_name, word_idx, n, data_type):
    unk = word_idx["<unk>"]

    def reader():
        for words in _lines(split_name):
            if data_type == DataType.NGRAM:
                assert n > -1, "ngram needs n > 0"
                l = (
                    [word_idx["<s>"]]
                    + [word_idx.get(w, unk) for w in words]
                    + [word_idx["<e>"]]
                )
                if len(l) >= n:
                    for i in range(n, len(l) + 1):
                        yield tuple(l[i - n : i])
            elif data_type == DataType.SEQ:
                l = [word_idx.get(w, unk) for w in words]
                src = [word_idx["<s>"]] + l
                trg = l + [word_idx["<e>"]]
                yield src, trg
            else:
                raise AssertionError("unknown data type")

    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    return _creator("train", word_idx, n, data_type)


def test(word_idx, n, data_type=DataType.NGRAM):
    return _creator("test", word_idx, n, data_type)


def convert(path):
    """Write the dataset as chunked recordio files for the cloud/
    elastic-master input path (reference imikolov.py convert;
    common.convert -> go/master RecordIO tasks).
    """
    n = 5
    w = build_dict()
    common.convert(path, train(w, n), 1000, "imikolov_train")
    common.convert(path, test(w, n), 1000, "imikolov_test")
