"""UCI housing (python/paddle/v2/dataset/uci_housing.py): samples are
(float32[13] normalized features, float32[1] price). 80/20 train/test
split of the 506-row table, features normalized (x-avg)/(max-min) —
uci_housing.py:57-69."""

from __future__ import annotations

import numpy as np

from paddle_tpu.data.dataset import common

__all__ = ["convert", "train", "test", "feature_range"]

URL = (
    "https://archive.ics.uci.edu/ml/machine-learning-databases/housing/"
    "housing.data"
)
FEATURE_NUM = 14

UCI_TRAIN_DATA = None
UCI_TEST_DATA = None
_RANGES = None


def feature_range():
    return _RANGES


def _load():
    global UCI_TRAIN_DATA, UCI_TEST_DATA, _RANGES
    if UCI_TRAIN_DATA is not None:
        return
    try:
        path = common.download(URL, "uci_housing")
        data = np.fromfile(path, sep=" ")
        data = data.reshape(-1, FEATURE_NUM)
    except FileNotFoundError:
        rng = common.synthetic_rng("uci_housing", "all")
        x = rng.uniform(0, 100, (506, FEATURE_NUM - 1))
        w = rng.standard_normal(FEATURE_NUM - 1)
        y = x @ w / 50.0 + rng.normal(0, 1, 506)
        data = np.concatenate([x, y[:, None]], axis=1)
    mx, mn, avg = data.max(0), data.min(0), data.mean(0)
    _RANGES = (mn[:-1], mx[:-1])
    for i in range(FEATURE_NUM - 1):
        data[:, i] = (data[:, i] - avg[i]) / (mx[i] - mn[i])
    offset = int(data.shape[0] * 0.8)
    UCI_TRAIN_DATA = data[:offset].astype(np.float32)
    UCI_TEST_DATA = data[offset:].astype(np.float32)


def train():
    def reader():
        _load()
        for d in UCI_TRAIN_DATA:
            yield d[:-1], d[-1:]

    return reader


def test():
    def reader():
        _load()
        for d in UCI_TEST_DATA:
            yield d[:-1], d[-1:]

    return reader


def convert(path):
    """Write the dataset as chunked recordio files for the cloud/
    elastic-master input path (reference uci_housing.py convert;
    common.convert -> go/master RecordIO tasks).
    """
    common.convert(path, train(), 1000, "uci_housing_train")
    common.convert(path, test(), 1000, "uci_housing_test")
