"""MNIST (python/paddle/v2/dataset/mnist.py): samples are
(float32[784] pixels scaled to [-1, 1], int label 0-9); train 60k /
test 10k. Parses the cached idx-format gz files when present; otherwise
deterministic synthetic digits with the same schema."""

from __future__ import annotations

import gzip
import struct

import numpy as np

from paddle_tpu.data.dataset import common

__all__ = ["convert", "train", "test"]

TRAIN_IMAGE_URL = (
    "http://yann.lecun.com/exdb/mnist/train-images-idx3-ubyte.gz"
)
TRAIN_LABEL_URL = (
    "http://yann.lecun.com/exdb/mnist/train-labels-idx1-ubyte.gz"
)
TEST_IMAGE_URL = "http://yann.lecun.com/exdb/mnist/t10k-images-idx3-ubyte.gz"
TEST_LABEL_URL = "http://yann.lecun.com/exdb/mnist/t10k-labels-idx1-ubyte.gz"


def _parse_idx(image_path, label_path):
    with gzip.open(image_path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, "bad idx image magic"
        images = np.frombuffer(f.read(n * rows * cols), np.uint8)
        images = images.reshape(n, rows * cols).astype(np.float32)
        images = images / 255.0 * 2.0 - 1.0  # mnist.py:66 scaling
    with gzip.open(label_path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, "bad idx label magic"
        labels = np.frombuffer(f.read(n), np.uint8).astype(np.int64)
    return images, labels


def _reader_creator(image_url, label_url, split_name, n_synth):
    def reader():
        try:
            images, labels = _parse_idx(
                common.download(image_url, "mnist"),
                common.download(label_url, "mnist"),
            )
        except FileNotFoundError:
            rng = common.synthetic_rng("mnist", split_name)
            labels = rng.integers(0, 10, n_synth)
            images = rng.uniform(-1, 1, (n_synth, 784)).astype(np.float32)
            # make classes linearly separable-ish so training can learn
            for c in range(10):
                images[labels == c, c * 70 : c * 70 + 40] += 1.5
            images = np.clip(images, -1.0, 1.0)
        for i in range(len(labels)):
            yield images[i], int(labels[i])

    return reader


def train():
    return _reader_creator(
        TRAIN_IMAGE_URL, TRAIN_LABEL_URL, "train", n_synth=1024
    )


def test():
    return _reader_creator(
        TEST_IMAGE_URL, TEST_LABEL_URL, "test", n_synth=256
    )


def convert(path):
    """Write the dataset as chunked recordio files for the cloud/
    elastic-master input path (reference mnist.py convert;
    common.convert -> go/master RecordIO tasks).
    """
    common.convert(path, train(), 1000, "mnist_train")
    common.convert(path, test(), 1000, "mnist_test")
