"""MQ2007 LETOR learning-to-rank (python/paddle/v2/dataset/mq2007.py):
three formats — "pointwise" yields (relevance, feature[46]);
"pairwise" yields (label, better_feature, worse_feature);
"listwise" yields (relevance_list, feature_list) per query
(mq2007.py:164,184,227,247). Real files use the LETOR
`label qid:<id> 1:<v> 2:<v> ...` text format."""

from __future__ import annotations

import numpy as np

from paddle_tpu.data.dataset import common

__all__ = ["convert", "train", "test", "FEATURE_DIM"]

URL = (
    "http://research.microsoft.com/en-us/um/beijing/projects/letor/"
    "LETOR4.0/Data/MQ2007.rar"
)
FEATURE_DIM = 46


def _parse_letor(path):
    from collections import defaultdict

    by_q = defaultdict(list)
    with open(path) as f:
        for line in f:
            body = line.split("#")[0].split()
            if not body:
                continue
            rel = int(body[0])
            qid = body[1].split(":")[1]
            feats = np.zeros(FEATURE_DIM, np.float32)
            for kv in body[2:]:
                k, v = kv.split(":")
                feats[int(k) - 1] = float(v)
            by_q[qid].append((rel, feats))
    return by_q


def _synth_queries(split_name, n_queries):
    rng = common.synthetic_rng("mq2007", split_name)
    by_q = {}
    w = rng.standard_normal(FEATURE_DIM)
    for q in range(n_queries):
        docs = []
        for _ in range(int(rng.integers(4, 12))):
            f = rng.standard_normal(FEATURE_DIM).astype(np.float32)
            rel = int(np.clip(round(f @ w / 8.0 + 1), 0, 2))
            docs.append((rel, f))
        by_q[str(q)] = docs
    return by_q


def _queries(split_name):
    fn = "train.txt" if split_name == "train" else "test.txt"
    try:
        return _parse_letor(
            common.download(URL + "/" + fn, "mq2007")
        )
    except FileNotFoundError:
        return _synth_queries(split_name, 60 if split_name == "train" else 20)


def _creator(split_name, format):
    def reader():
        by_q = _queries(split_name)
        for qid in sorted(by_q):
            docs = by_q[qid]
            if format == "pointwise":
                for rel, f in docs:
                    yield rel, f
            elif format == "pairwise":
                for i, (ri, fi) in enumerate(docs):
                    for rj, fj in docs[i + 1 :]:
                        if ri == rj:
                            continue
                        hi, lo = (fi, fj) if ri > rj else (fj, fi)
                        yield np.asarray([1.0]), hi, lo
            elif format == "listwise":
                yield (
                    np.asarray([d[0] for d in docs], np.float32),
                    np.stack([d[1] for d in docs]),
                )
            else:
                raise ValueError(f"unknown format {format!r}")

    return reader


def train(format="pairwise"):
    return _creator("train", format)


def test(format="pairwise"):
    return _creator("test", format)


def convert(path):
    """Write the dataset as chunked recordio files for the cloud/
    elastic-master input path (no reference convert for this module; added so every dataset
    feeds the cloud input path uniformly; common.convert -> go/master
    RecordIO tasks).
    """
    common.convert(path, train(), 1000, "mq2007_train")
    common.convert(path, test(), 1000, "mq2007_test")
