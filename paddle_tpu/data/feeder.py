"""DataFeeder: python samples -> Arg batches (ragged -> dense packing).

Reference: python/paddle/v2/data_feeder.py + the input-type declarations of
PyDataProvider2.py:47-214 (dense_vector, integer_value, sparse_*, each ×
{no_sequence, sequence, sub_sequence}). The reference emits padding-free
flat buffers + start positions; we emit dense [B, T_bucket] + lengths
(see core/arg.py for why). Bucketing rounds T up to a power-of-two-ish
bucket so XLA recompiles only per bucket, not per batch.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from paddle_tpu.core.arg import Arg


@dataclass(frozen=True)
class InputType:
    kind: str  # dense | ids | sparse_binary | sparse_float
    shape: tuple  # feature shape
    seq: int  # 0 = none, 1 = sequence, 2 = sub-sequence
    vocab: int = 0  # ids slots: the value range (v1 slot "dim")

    @property
    def size(self) -> int:
        """Layer width this slot feeds (reference InputType.dim: vocab
        for integer slots, feature dim otherwise)."""
        if self.kind == "ids":
            return self.vocab
        n = 1
        for d in self.shape:
            n *= d
        return n

    # --- the reference InputType attribute surface
    #     (PyDataProvider2.py:47 InputType(dim, seq_type, type)) ---
    @property
    def dim(self) -> int:
        return self.size

    @property
    def seq_type(self) -> int:
        return self.seq

    @property
    def type(self) -> int:
        """DataType enum value (PyDataProvider2.py:32)."""
        return {
            "dense": 0,  # DataType.Dense
            "sparse_binary": 1,  # DataType.SparseNonValue
            "sparse_float": 2,  # DataType.SparseValue
            "ids": 3,  # DataType.Index
        }[self.kind]


def dense_vector(dim, seq_type=0):
    dim = tuple(dim) if isinstance(dim, (tuple, list)) else (dim,)
    return InputType("dense", dim, seq_type)


def integer_value(vocab, seq_type=0):
    return InputType("ids", (1,), seq_type, vocab=vocab)


def sparse_binary_vector(dim, seq_type=0):
    return InputType("sparse_binary", (dim,), seq_type)


def sparse_float_vector(dim, seq_type=0):
    return InputType("sparse_float", (dim,), seq_type)


# sequence variants, mirroring PyDataProvider2 naming
def dense_vector_sequence(dim):
    return dense_vector(dim, 1)


def integer_value_sequence(vocab):
    return integer_value(vocab, 1)


def integer_value_sub_sequence(vocab):
    return integer_value(vocab, 2)


def _bucket(n: int, buckets=None) -> int:
    """Round up to a bucket to bound recompilation."""
    if buckets:
        for b in buckets:
            if n <= b:
                return b
        raise ValueError(
            f"sequence of length {n} exceeds the largest bucket "
            f"{buckets[-1]}; add a larger bucket or truncate upstream"
        )
    b = 8
    while b < n:
        b *= 2 if b < 128 else 1
        if b >= 128:
            b = ((n + 127) // 128) * 128
            break
    return b


def _sparse_float_row(row):
    """Normalize a sparse-float row to (indices, values). Accepts the
    reference sample format — a sequence of (col, value) pairs
    (PyDataProvider2.py sparse_float slots; DataProviderConverter's
    SparseFloatScanner) — or the internal two-tuple of parallel LISTS
    (proto_provider). A tuple of exactly two pairs is ambiguous by
    shape; the parallel form is only recognized when both halves are
    lists/arrays, so reference pair data can never be misread as
    (indices, values)."""
    if (
        isinstance(row, tuple)
        and len(row) == 2
        and all(isinstance(e, (list, np.ndarray)) for e in row)
    ):
        return row  # internal parallel (indices, values)
    if len(row) == 0:
        return (), ()
    if all(hasattr(e, "__len__") and len(e) == 2 for e in row):
        return tuple(zip(*row))  # reference (col, value) pairs
    if isinstance(row, tuple) and len(row) == 2:
        return row  # parallel form with tuple storage
    return tuple(zip(*row))


class DataFeeder:
    """feeding maps data-layer name -> position in each sample tuple."""

    def __init__(self, feeding: dict, types: dict, buckets=None):
        self.feeding = feeding
        self.types = types
        self.buckets = buckets

    def __call__(self, batch: list) -> dict:
        return self.convert(batch)

    def convert(self, batch: list) -> dict:
        out = {}
        for name, pos in self.feeding.items():
            t = self.types[name]
            column = [sample[pos] for sample in batch]
            out[name] = self._column_to_arg(column, t)
        return out

    def _column_to_arg(self, column, t: InputType) -> Arg:
        b = len(column)
        if t.seq == 0:
            if t.kind == "dense":
                arr = np.asarray(column, np.float32)
                try:
                    v = arr.reshape((b,) + t.shape)
                except ValueError:
                    # dense_array: the declared dim is advisory — the
                    # actual sample shape wins (reference
                    # DenseScanner keeps multi-dim data as fed and
                    # only records frame height/width)
                    v = arr.reshape(b, -1)
                return Arg(value=v)
            if t.kind == "ids":
                ids = np.asarray(column, np.int64).reshape(b).astype(np.int32)
                return Arg(ids=ids)
            if t.kind in ("sparse_binary", "sparse_float"):
                v = np.zeros((b,) + t.shape, np.float32)
                for i, row in enumerate(column):
                    if t.kind == "sparse_binary":
                        v[i, np.asarray(row, np.int64)] = 1.0
                    else:
                        idx, vals = _sparse_float_row(row)
                        v[i, np.asarray(idx, np.int64)] = np.asarray(
                            vals, np.float32
                        )
                return Arg(value=v)
        if t.seq == 1:
            lens = np.asarray([len(s) for s in column], np.int32)
            tmax = _bucket(int(lens.max()) if b else 1, self.buckets)
            if t.kind == "ids":
                ids = np.zeros((b, tmax), np.int32)
                for i, s in enumerate(column):
                    ids[i, : len(s)] = np.asarray(s, np.int64)
                return Arg(ids=ids, seq_lens=lens)
            v = np.zeros((b, tmax) + t.shape, np.float32)
            if t.kind in ("sparse_binary", "sparse_float"):
                # sequence of sparse rows: each timestep is an index
                # list (or (indices, values)) — PyDataProvider2's
                # sparse_*_vector_sequence slots
                for i, s in enumerate(column):
                    for ti, row in enumerate(s):
                        if t.kind == "sparse_binary":
                            v[i, ti, np.asarray(row, np.int64)] = 1.0
                        else:
                            idx, vals = _sparse_float_row(row)
                            v[i, ti, np.asarray(idx, np.int64)] = (
                                np.asarray(vals, np.float32)
                            )
                return Arg(value=v, seq_lens=lens)
            for i, s in enumerate(column):
                v[i, : len(s)] = np.asarray(s, np.float32).reshape(
                    (len(s),) + t.shape
                )
            return Arg(value=v, seq_lens=lens)
        if t.seq == 2:
            # sub-sequences: sample = list of list of tokens/vectors
            sub_lens = [[len(ss) for ss in s] for s in column]
            smax = max(len(s) for s in sub_lens)
            flat_lens = np.asarray([sum(s) for s in sub_lens], np.int32)
            tmax = _bucket(int(flat_lens.max()), self.buckets)
            subl = np.zeros((b, smax), np.int32)
            for i, s in enumerate(sub_lens):
                subl[i, : len(s)] = s
            if t.kind == "ids":
                ids = np.zeros((b, tmax), np.int32)
                for i, s in enumerate(column):
                    flat = [tok for ss in s for tok in ss]
                    ids[i, : len(flat)] = flat
                return Arg(ids=ids, seq_lens=flat_lens, subseq_lens=subl)
            v = np.zeros((b, tmax) + t.shape, np.float32)
            for i, s in enumerate(column):
                flat = np.asarray(
                    [tok for ss in s for tok in ss], np.float32
                ).reshape(-1, *t.shape)
                v[i, : len(flat)] = flat
            return Arg(value=v, seq_lens=flat_lens, subseq_lens=subl)
        raise ValueError(f"unsupported input type {t}")
