"""ProtoDataProvider binary dataset format, wire-compatible reader.

Reference: proto/DataFormat.proto + gserver/dataproviders/
ProtoDataProvider.h:48 and ProtoReader.h:30-101 — a data file is a
stream of varint32-length-delimited proto2 messages (optionally gzip),
first a DataHeader (slot type/dim declarations), then one DataSample
per sample; consecutive samples with is_beginning=false continue the
previous sample's sequence (ProtoDataProvider.cpp:223 loop).

Hand-rolled proto2 wire codec (same approach as the ParameterConfig
sidecar in trainer/checkpoint.py) — no protobuf dependency. The writer
exists so tests (and users migrating away from the format) can
round-trip files; the reader yields samples in the DataFeeder's slot
conventions, so `proto_reader(paths)` drops into the same training
pipelines as every other reader.

Slot type mapping (SlotDef.SlotType -> feeder InputType):
  VECTOR_DENSE            -> dense_vector(dim)
  VECTOR_SPARSE_NON_VALUE -> sparse_binary_vector(dim)  (ids list)
  VECTOR_SPARSE_VALUE     -> sparse_float_vector(dim)   ((ids, vals))
  INDEX                   -> integer_value(dim)
Sequences (is_beginning grouping) wrap each slot value in a list —
the *_sequence flavor of the same types. VAR_MDIM_* and STRING slots
are accepted by the parser; they have no feeder slot and surface as
raw lists for user code.
"""

from __future__ import annotations

import gzip
import io
import struct

import numpy as np

from paddle_tpu.data import feeder as _feeder

# SlotDef.SlotType
VECTOR_DENSE = 0
VECTOR_SPARSE_NON_VALUE = 1
VECTOR_SPARSE_VALUE = 2
INDEX = 3
VAR_MDIM_DENSE = 4
VAR_MDIM_INDEX = 5
STRING = 6


# ---- proto2 wire primitives ----

def _read_varint(buf, i):
    v = s = 0
    while True:
        b = buf[i]
        i += 1
        v |= (b & 0x7F) << s
        if not b & 0x80:
            return v, i
        s += 7


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _fields(buf):
    """Yield (field_number, wire_type, value) over a message body."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        field, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i : i + ln]
            i += ln
        elif wt == 5:
            v = buf[i : i + 4]
            i += 4
        elif wt == 1:
            v = buf[i : i + 8]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, v


def _packed_u32(data: bytes):
    out, i = [], 0
    while i < len(data):
        v, i = _read_varint(data, i)
        out.append(v)
    return out


def _packed_f32(data: bytes):
    return list(struct.unpack(f"<{len(data) // 4}f", data))


# ---- message parsers ----

def _parse_slot_def(buf):
    t = dim = 0
    for f, wt, v in _fields(buf):
        if f == 1:
            t = v
        elif f == 2:
            dim = v
    return (t, dim)


def parse_header(buf):
    """DataHeader -> [(slot_type, dim)]."""
    return [
        _parse_slot_def(v) for f, wt, v in _fields(buf) if f == 1
    ]


def _parse_vector_slot(buf):
    values, ids, dims, strs = [], [], [], []
    for f, wt, v in _fields(buf):
        if f == 1:
            values.extend(
                _packed_f32(v) if wt == 2
                else struct.unpack("<f", v)
            )
        elif f == 2:
            ids.extend(_packed_u32(v) if wt == 2 else [v])
        elif f == 3:
            dims.extend(_packed_u32(v) if wt == 2 else [v])
        elif f == 4:
            strs.append(v.decode())
    return {"values": values, "ids": ids, "dims": dims, "strs": strs}


def _parse_sample(buf):
    s = {
        "is_beginning": True,
        "vector_slots": [],
        "id_slots": [],
        "var_id_slots": [],
        "subseq_slots": [],
    }
    for f, wt, v in _fields(buf):
        if f == 1:
            s["is_beginning"] = bool(v)
        elif f == 2:
            s["vector_slots"].append(_parse_vector_slot(v))
        elif f == 3:
            s["id_slots"].extend(
                _packed_u32(v) if wt == 2 else [v]
            )
        elif f == 4:
            s["var_id_slots"].append(_parse_vector_slot(v))
        elif f == 5:
            s["subseq_slots"].append(bytes(v))
    return s


def _iter_messages(raw: bytes):
    i = 0
    while i < len(raw):
        ln, i = _read_varint(raw, i)
        yield raw[i : i + ln]
        i += ln


def _vector_to_slot(slot_type, vs):
    if slot_type == VECTOR_DENSE:
        return np.asarray(vs["values"], np.float32)
    if slot_type == VECTOR_SPARSE_NON_VALUE:
        return list(vs["ids"])
    if slot_type == VECTOR_SPARSE_VALUE:
        return (list(vs["ids"]), list(vs["values"]))
    return vs  # VAR_MDIM/STRING: raw


def read_proto_data(path: str, compressed: bool | None = None):
    """Parse one ProtoDataProvider file.

    Returns (slot_defs, samples): slot_defs = [(type, dim)];
    samples = list of per-sample slot tuples in feeder conventions.
    Rows with is_beginning=false are returned as separate entries with
    a parallel `beginnings` bool list via the 3-tuple return of
    read_proto_data_raw; use `group_sequences` (or proto_reader) for
    the sequence-grouped view."""
    defs, rows, _ = read_proto_data_raw(path, compressed)
    return defs, rows


def read_proto_data_raw(path: str, compressed: bool | None = None,
                        skip_bad_records: int = 0):
    """`skip_bad_records=N`: up to N records that fail to parse
    (bit-flipped media, torn writes) are dropped with a counted
    warning instead of aborting the pass — the reader's half of the
    watchdog's bad-data story. A corrupted varint LENGTH can desync
    the frame stream; a desync surfaces as parse failures and is
    bounded by the same budget, so a rotten file still fails loudly
    once the budget is spent. 0 = strict (any bad record raises).
    The header must always parse — without slot types nothing after
    it is interpretable."""
    import logging

    with open(path, "rb") as f:
        raw = f.read()
    if compressed or (compressed is None and raw[:2] == b"\x1f\x8b"):
        raw = gzip.decompress(raw)
    msgs = _iter_messages(raw)
    try:
        header = parse_header(next(msgs))
    except StopIteration:
        return [], [], []
    rows, begins = [], []
    bad = 0
    while True:
        try:
            m = next(msgs)
        except StopIteration:
            break
        except Exception as e:
            # framing (varint) error: the rest of the stream is
            # unrecoverable — count it as ONE bad record and stop
            bad += 1
            if bad > skip_bad_records:
                raise ValueError(
                    f"{path}: corrupt record stream ({e}); "
                    f"{bad} bad record(s), budget {skip_bad_records}"
                ) from e
            logging.getLogger("paddle_tpu.data").warning(
                "%s: frame stream desynced (%s); dropping the tail "
                "(%d/%d skips used)", path, e, bad, skip_bad_records,
            )
            break
        try:
            s = _parse_sample(m)
            slots = []
            vi = ii = 0
            for t, dim in header:
                if t == INDEX:
                    slots.append(int(s["id_slots"][ii]))
                    ii += 1
                elif t == VAR_MDIM_INDEX:
                    slots.append(list(s["var_id_slots"][vi]["ids"]))
                    vi += 1
                else:
                    slots.append(
                        _vector_to_slot(t, s["vector_slots"][vi])
                    )
                    vi += 1
        except Exception as e:
            bad += 1
            if bad > skip_bad_records:
                raise ValueError(
                    f"{path}: undecodable record ({type(e).__name__}: "
                    f"{e}); {bad} bad record(s), budget "
                    f"{skip_bad_records}"
                ) from e
            logging.getLogger("paddle_tpu.data").warning(
                "%s: skipping undecodable record (%s) — %d/%d skips "
                "used", path, type(e).__name__, bad, skip_bad_records,
            )
            continue
        rows.append(tuple(slots))
        begins.append(s["is_beginning"])
    return header, rows, begins


def group_sequences(rows, begins):
    """ProtoDataProvider sequence semantics: consecutive rows with
    is_beginning=false extend the sequence opened by the last
    is_beginning=true row. Returns samples whose slots are LISTS of the
    member rows' slot values (the feeder's sequence flavor)."""
    out = []
    for row, b in zip(rows, begins):
        if b or not out:
            out.append(tuple([v] for v in row))
        else:
            for acc, v in zip(out[-1], row):
                acc.append(v)
    return out


def proto_reader(paths, compressed=None, skip_bad_records: int = 0):
    """Reader over ProtoDataProvider files (the reader-combinator
    entry): yields per-sample slot tuples; multi-row sequences arrive
    in the feeder's sequence shape. `skip_bad_records` bounds how many
    corrupt records per FILE are dropped (with a warning) before the
    pass aborts — see read_proto_data_raw."""
    if isinstance(paths, str):
        paths = [paths]

    def reader():
        for p in paths:
            _, rows, begins = read_proto_data_raw(
                p, compressed, skip_bad_records=skip_bad_records
            )
            if all(begins):
                yield from rows
            else:
                yield from group_sequences(rows, begins)

    return reader


def input_types(slot_defs, sequences=False):
    """[(type, dim)] -> feeder InputTypes (for DataFeeder wiring)."""
    seq = 1 if sequences else 0
    out = []
    for t, dim in slot_defs:
        if t == VECTOR_DENSE:
            out.append(_feeder.dense_vector(dim, seq))
        elif t == VECTOR_SPARSE_NON_VALUE:
            out.append(_feeder.sparse_binary_vector(dim, seq))
        elif t == VECTOR_SPARSE_VALUE:
            out.append(_feeder.sparse_float_vector(dim, seq))
        elif t == INDEX:
            out.append(_feeder.integer_value(dim, seq))
        else:
            raise ValueError(
                f"slot type {t} has no feeder input type"
            )
    return out


# ---- writer (round-trip tests + migration tooling) ----

def _emit_vector_slot(slot_type, value) -> bytes:
    out = bytearray()
    if slot_type == VECTOR_DENSE:
        data = struct.pack(f"<{len(value)}f", *value)
        out += b"\x0a" + _varint(len(data)) + data
    elif slot_type == VECTOR_SPARSE_NON_VALUE:
        data = b"".join(_varint(int(i)) for i in value)
        out += b"\x12" + _varint(len(data)) + data
    elif slot_type == VECTOR_SPARSE_VALUE:
        ids, vals = value
        data = struct.pack(f"<{len(vals)}f", *vals)
        out += b"\x0a" + _varint(len(data)) + data
        data = b"".join(_varint(int(i)) for i in ids)
        out += b"\x12" + _varint(len(data)) + data
    else:
        raise ValueError(f"writer does not support slot type {slot_type}")
    return bytes(out)


def write_proto_data(path, slot_defs, samples, beginnings=None,
                     compressed=False):
    """Emit a DataFormat.proto file the reference's ProtoDataProvider
    (and our reader) can load. samples: per-row slot tuples;
    beginnings: optional per-row is_beginning flags."""
    body = io.BytesIO()

    def put(msg: bytes):
        body.write(_varint(len(msg)) + msg)

    header = bytearray()
    for t, dim in slot_defs:
        sd = b"\x08" + _varint(t) + b"\x10" + _varint(dim)
        header += b"\x0a" + _varint(len(sd)) + sd
    put(bytes(header))

    for r, row in enumerate(samples):
        msg = bytearray()
        if beginnings is not None and not beginnings[r]:
            msg += b"\x08\x00"  # is_beginning = false
        for (t, dim), v in zip(slot_defs, row):
            if t == INDEX:
                msg += b"\x18" + _varint(int(v))
            else:
                vs = _emit_vector_slot(t, v)
                msg += b"\x12" + _varint(len(vs)) + vs
        put(bytes(msg))

    raw = body.getvalue()
    if compressed:
        raw = gzip.compress(raw)
    with open(path, "wb") as f:
        f.write(raw)
