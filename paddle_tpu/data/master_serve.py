"""Standalone networked-master process: `python -m
paddle_tpu.data.master_serve --port 8090 --snapshot /path/m.snap`.

The counterpart of the reference's master daemon
(go/cmd/master/master.go:36): owns the task queues, serves trainers over
TCP (native/src/master_server.cc), snapshots periodically and on
shutdown, and restores from its snapshot on restart so a master crash
does not lose the pass (go/master/service.go:166-207).

Prints `LISTENING <port>` on stdout once ready (ephemeral ports:
--port 0). Stops on SIGTERM/SIGINT or a client SHUTDOWN op.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--lease-seconds", type=float, default=60.0)
    ap.add_argument("--failure-max", type=int, default=3)
    ap.add_argument("--snapshot", default=None,
                    help="snapshot file; restored on start if it exists")
    ap.add_argument("--snapshot-every", type=float, default=10.0)
    args = ap.parse_args(argv)

    from paddle_tpu.native.master import Master

    if args.snapshot and os.path.exists(args.snapshot):
        master = Master.restore(args.snapshot)
        master.set_lease(args.lease_seconds)
        print(f"restored from {args.snapshot}: {master.counts}",
              file=sys.stderr, flush=True)
    else:
        master = Master(args.lease_seconds, args.failure_max)

    server = master.serve(
        port=args.port,
        snapshot_path=args.snapshot,
        snapshot_every=args.snapshot_every if args.snapshot else 0.0,
    )
    print(f"LISTENING {server.port}", flush=True)

    stopping = []
    signal.signal(signal.SIGTERM, lambda *_: stopping.append(1))
    signal.signal(signal.SIGINT, lambda *_: stopping.append(1))
    while not stopping and not server.stopped:
        time.sleep(0.1)
    server.stop()  # joins service threads; final snapshot if configured
    return 0


if __name__ == "__main__":
    sys.exit(main())
