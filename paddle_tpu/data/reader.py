"""Reader creators and combinators.

Reference: python/paddle/v2/reader/decorator.py:26-292 (map_readers,
buffered, compose, chain, shuffle, ComposeNotAligned, firstn) and
python/paddle/v2/reader/creator.py. A reader is a zero-arg callable
returning an iterator over samples; combinators wrap readers. The
double-buffer thread of the reference's C++ DataProvider
(gserver/dataproviders/DataProvider.h:249 DoubleBuffer) maps to
`buffered`, which prefetches on a background thread.
"""

from __future__ import annotations

import itertools
import queue
import random as _random
import threading


class ComposeNotAligned(ValueError):
    pass


def np_array(x):
    """reader from an in-memory array: yields rows."""

    def reader():
        for row in x:
            yield row

    return reader


def text_file(path):
    def reader():
        with open(path) as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


# ---- RecordIO reading (reader/creator.py:60 recordio) ----------------
#
# The PaddlePaddle recordio wire format (the Go master's chunk format,
# written by the `recordio` package): per chunk a 20-byte header
# [magic 0x01020304, crc32, compressor, compressed-len, num-records]
# followed by the payload — snappy FRAMING stream when compressor=1 —
# holding [len u32][bytes] records. Python-snappy isn't available, so
# the snappy framing + block formats are decoded here directly.


def _snappy_block_decode(buf: bytes) -> bytes:
    """Raw snappy block format (the framing format's COMPRESSED chunks;
    google/snappy format_description.txt)."""
    # uncompressed length varint
    n = shift = i = 0
    while True:
        b = buf[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    out = bytearray()
    while i < len(buf):
        tag = buf[i]
        i += 1
        kind = tag & 3
        if kind == 0:  # literal
            ln = tag >> 2
            if ln >= 60:
                nb = ln - 59
                ln = int.from_bytes(buf[i : i + nb], "little")
                i += nb
            ln += 1
            out += buf[i : i + ln]
            i += ln
            continue
        if kind == 1:  # copy, 1-byte offset
            ln = ((tag >> 2) & 0x7) + 4
            off = ((tag >> 5) << 8) | buf[i]
            i += 1
        elif kind == 2:  # copy, 2-byte offset
            ln = (tag >> 2) + 1
            off = int.from_bytes(buf[i : i + 2], "little")
            i += 2
        else:  # copy, 4-byte offset
            ln = (tag >> 2) + 1
            off = int.from_bytes(buf[i : i + 4], "little")
            i += 4
        for _ in range(ln):  # overlapping copies are the RLE trick
            out.append(out[-off])
    assert len(out) == n, f"snappy: got {len(out)} bytes, header said {n}"
    return bytes(out)


def _snappy_stream_decode(buf: bytes) -> bytes:
    """Snappy framing format (framing_format.txt): [type u8][len u24]
    chunks — 0xff stream id, 0x00 compressed (crc + block), 0x01
    uncompressed (crc + data), 0xfe padding."""
    out = bytearray()
    i = 0
    while i < len(buf):
        kind = buf[i]
        ln = int.from_bytes(buf[i + 1 : i + 4], "little")
        body = buf[i + 4 : i + 4 + ln]
        i += 4 + ln
        if kind == 0x00:
            out += _snappy_block_decode(body[4:])  # skip masked crc
        elif kind == 0x01:
            out += body[4:]
        # 0xff stream identifier / 0xfe padding / reserved: skip
    return bytes(out)


_RECORDIO_MAGIC = 0x01020304


def recordio_records(path: str):
    """Iterate raw record payloads of one recordio file."""
    import struct
    import zlib

    with open(path, "rb") as f:
        while True:
            head = f.read(20)
            if len(head) < 20:
                return
            magic, crc, comp, clen, _nrec = struct.unpack("<IIIII", head)
            if magic != _RECORDIO_MAGIC:
                raise ValueError(
                    f"{path}: bad recordio chunk magic {magic:#x}"
                )
            payload = f.read(clen)
            if comp == 1:
                data = _snappy_stream_decode(payload)
            elif comp == 2:
                data = zlib.decompress(payload, 31)  # gzip
            else:
                data = payload
            if crc and zlib.crc32(payload) & 0xFFFFFFFF != crc:
                raise ValueError(f"{path}: recordio chunk crc mismatch")
            i = 0
            while i < len(data):
                (rlen,) = struct.unpack_from("<I", data, i)
                i += 4
                yield data[i : i + rlen]
                i += rlen


def _file_records(path: str):
    """Raw records of one record file, sniffing the container: the
    reference recordio magic 0x01020304 decodes in-process; anything
    else goes through the native C++ prefetch reader (PTRC chunks)."""
    with open(path, "rb") as f:
        magic = f.read(4)
    if magic == b"\x04\x03\x02\x01":
        yield from recordio_records(path)
    else:
        from paddle_tpu.native.recordio import RecordReader

        with RecordReader([path]) as rd:
            yield from rd


def recordio_interop(paths, buf_size=100):
    """Reader over pickled records in recordio files; `paths` is a
    path, a comma-separated list, or a list (glob patterns allowed) —
    the reference reader/creator.py:60 surface, reading BOTH the
    reference wire format and this framework's native chunks."""
    import glob as _glob
    import pickle

    if isinstance(paths, str):
        paths = paths.split(",")
    files = []
    for p in paths:
        files.extend(sorted(_glob.glob(p)) or [p])

    def reader():
        for p in files:
            for rec in _file_records(p):
                yield pickle.loads(rec)

    return buffered(reader, buf_size)


def map_readers(func, *readers):
    """(decorator.py:26) new reader yielding func over outputs of readers."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader_fn, buf_size, seed=None):
    """(decorator.py:48) buffered shuffle."""

    def reader():
        rnd = _random.Random(seed)
        buf = []
        for e in reader_fn():
            buf.append(e)
            if len(buf) >= buf_size:
                rnd.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            rnd.shuffle(buf)
            yield from buf

    return reader


def chain(*readers):
    """(decorator.py:83) concatenate readers."""

    def reader():
        for r in readers:
            yield from r()

    return reader


def compose(*readers, check_alignment=True):
    """(decorator.py:115) zip readers into tuple samples."""

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if check_alignment:
            for items in itertools.zip_longest(*rs):
                if any(i is None for i in items):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned"
                    )
                yield sum((make_tuple(i) for i in items), ())
        else:
            for items in zip(*rs):
                yield sum((make_tuple(i) for i in items), ())

    return reader


def buffered(reader_fn, size):
    """(decorator.py:162) background-thread prefetch — the DoubleBuffer
    equivalent (DataProvider.h:249)."""

    class _End:
        pass

    class _Raise:
        def __init__(self, exc):
            self.exc = exc

    def reader():
        q = queue.Queue(maxsize=size)

        def producer():
            try:
                for e in reader_fn():
                    q.put(e)
            except BaseException as exc:  # propagate to the consumer
                q.put(_Raise(exc))
            else:
                q.put(_End)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            if isinstance(e, _Raise):
                raise e.exc
            yield e

    return reader


def firstn(reader_fn, n):
    """(decorator.py:233) limit to first n samples."""

    def reader():
        return itertools.islice(reader_fn(), n)

    return reader


def cache(reader_fn):
    """Materialize once, then replay from memory."""
    data = []
    filled = []

    def reader():
        if not filled:
            data.extend(reader_fn())
            filled.append(True)
        return iter(data)

    return reader


def batched(reader_fn, batch_size, drop_last=True):
    """Group samples into lists (python/paddle/v2/minibatch.py)."""

    def reader():
        buf = []
        for e in reader_fn():
            buf.append(e)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return reader


def recordio(paths, decode=None, start_chunk=0, step_chunk=1):
    """Stream records from chunked record files through the native C++
    async-prefetch reader (paddle_tpu/native/recordio.py — the
    DoubleBuffer analogue, gserver/dataproviders/DataProvider.h:249).
    `decode` maps raw bytes -> sample (default: pickle.loads)."""
    import pickle

    from paddle_tpu.native.recordio import RecordReader

    dec = decode if decode is not None else pickle.loads

    def reader():
        with RecordReader(
            paths, start_chunk=start_chunk, step_chunk=step_chunk
        ) as rd:
            for rec in rd:
                yield dec(rec)

    return reader


def elastic(master, decode=None):
    """Task-leased reading: pull (path, chunk) tasks from a
    paddle_tpu.native.master.Master and stream those chunks — the
    fault-tolerant input dispatch loop of the reference's Go master
    (go/master/service.go). On reader failure the task lease expires and
    another worker re-reads the chunk."""
    import json
    import pickle

    from paddle_tpu.native.recordio import RecordReader, count_chunks

    dec = decode if decode is not None else pickle.loads

    def reader():
        import time

        chunk_counts = {}
        while not master.pass_finished():
            t = master.get_task()
            if t is None:
                # nothing leasable *right now*, but a peer still holds a
                # lease — if it fails, the chunk returns to todo and we
                # must pick it up, so poll instead of exiting
                time.sleep(0.05)
                continue
            task_id, payload = t
            task = json.loads(payload)
            path = task["path"]
            if path not in chunk_counts:
                chunk_counts[path] = count_chunks(path)
            try:
                with RecordReader(
                    path,
                    start_chunk=task["chunk"],
                    step_chunk=chunk_counts[path],
                ) as rd:
                    for rec in rd:
                        yield dec(rec)
            except Exception:
                master.task_failed(task_id)
                raise
            if not master.task_done(task_id):
                # lease expired while we were yielding: the chunk was
                # requeued and will be re-read (duplicate records this
                # pass) — surface it so the operator can raise the lease
                import logging

                logging.getLogger("paddle_tpu.data").warning(
                    "task %d lease expired before completion; chunk will "
                    "be re-served (raise Master lease_seconds?)",
                    task_id,
                )

    return reader
