"""Reader creators and combinators.

Reference: python/paddle/v2/reader/decorator.py:26-292 (map_readers,
buffered, compose, chain, shuffle, ComposeNotAligned, firstn) and
python/paddle/v2/reader/creator.py. A reader is a zero-arg callable
returning an iterator over samples; combinators wrap readers. The
double-buffer thread of the reference's C++ DataProvider
(gserver/dataproviders/DataProvider.h:249 DoubleBuffer) maps to
`buffered`, which prefetches on a background thread.
"""

from __future__ import annotations

import itertools
import queue
import random as _random
import threading


class ComposeNotAligned(ValueError):
    pass


def np_array(x):
    """reader from an in-memory array: yields rows."""

    def reader():
        for row in x:
            yield row

    return reader


def text_file(path):
    def reader():
        with open(path) as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def map_readers(func, *readers):
    """(decorator.py:26) new reader yielding func over outputs of readers."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader_fn, buf_size, seed=None):
    """(decorator.py:48) buffered shuffle."""

    def reader():
        rnd = _random.Random(seed)
        buf = []
        for e in reader_fn():
            buf.append(e)
            if len(buf) >= buf_size:
                rnd.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            rnd.shuffle(buf)
            yield from buf

    return reader


def chain(*readers):
    """(decorator.py:83) concatenate readers."""

    def reader():
        for r in readers:
            yield from r()

    return reader


def compose(*readers, check_alignment=True):
    """(decorator.py:115) zip readers into tuple samples."""

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if check_alignment:
            for items in itertools.zip_longest(*rs):
                if any(i is None for i in items):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned"
                    )
                yield sum((make_tuple(i) for i in items), ())
        else:
            for items in zip(*rs):
                yield sum((make_tuple(i) for i in items), ())

    return reader


def buffered(reader_fn, size):
    """(decorator.py:162) background-thread prefetch — the DoubleBuffer
    equivalent (DataProvider.h:249)."""

    class _End:
        pass

    class _Raise:
        def __init__(self, exc):
            self.exc = exc

    def reader():
        q = queue.Queue(maxsize=size)

        def producer():
            try:
                for e in reader_fn():
                    q.put(e)
            except BaseException as exc:  # propagate to the consumer
                q.put(_Raise(exc))
            else:
                q.put(_End)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            if isinstance(e, _Raise):
                raise e.exc
            yield e

    return reader


def firstn(reader_fn, n):
    """(decorator.py:233) limit to first n samples."""

    def reader():
        return itertools.islice(reader_fn(), n)

    return reader


def cache(reader_fn):
    """Materialize once, then replay from memory."""
    data = []
    filled = []

    def reader():
        if not filled:
            data.extend(reader_fn())
            filled.append(True)
        return iter(data)

    return reader


def batched(reader_fn, batch_size, drop_last=True):
    """Group samples into lists (python/paddle/v2/minibatch.py)."""

    def reader():
        buf = []
        for e in reader_fn():
            buf.append(e)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return reader


def recordio(paths, decode=None, start_chunk=0, step_chunk=1):
    """Stream records from chunked record files through the native C++
    async-prefetch reader (paddle_tpu/native/recordio.py — the
    DoubleBuffer analogue, gserver/dataproviders/DataProvider.h:249).
    `decode` maps raw bytes -> sample (default: pickle.loads)."""
    import pickle

    from paddle_tpu.native.recordio import RecordReader

    dec = decode if decode is not None else pickle.loads

    def reader():
        with RecordReader(
            paths, start_chunk=start_chunk, step_chunk=step_chunk
        ) as rd:
            for rec in rd:
                yield dec(rec)

    return reader


def elastic(master, decode=None):
    """Task-leased reading: pull (path, chunk) tasks from a
    paddle_tpu.native.master.Master and stream those chunks — the
    fault-tolerant input dispatch loop of the reference's Go master
    (go/master/service.go). On reader failure the task lease expires and
    another worker re-reads the chunk."""
    import json
    import pickle

    from paddle_tpu.native.recordio import RecordReader, count_chunks

    dec = decode if decode is not None else pickle.loads

    def reader():
        import time

        chunk_counts = {}
        while not master.pass_finished():
            t = master.get_task()
            if t is None:
                # nothing leasable *right now*, but a peer still holds a
                # lease — if it fails, the chunk returns to todo and we
                # must pick it up, so poll instead of exiting
                time.sleep(0.05)
                continue
            task_id, payload = t
            task = json.loads(payload)
            path = task["path"]
            if path not in chunk_counts:
                chunk_counts[path] = count_chunks(path)
            try:
                with RecordReader(
                    path,
                    start_chunk=task["chunk"],
                    step_chunk=chunk_counts[path],
                ) as rd:
                    for rec in rd:
                        yield dec(rec)
            except Exception:
                master.task_failed(task_id)
                raise
            if not master.task_done(task_id):
                # lease expired while we were yielding: the chunk was
                # requeued and will be re-read (duplicate records this
                # pass) — surface it so the operator can raise the lease
                import logging

                logging.getLogger("paddle_tpu.data").warning(
                    "task %d lease expired before completion; chunk will "
                    "be re-served (raise Master lease_seconds?)",
                    task_id,
                )

    return reader
