"""Declarative data providers — the @provider decorator.

Reference: python/paddle/trainer/PyDataProvider2.py:329 (@provider with
input_types, cache modes, should_shuffle, init_hook, calc_batch_size)
driving the C++ PyDataProvider2 (gserver/dataproviders/
PyDataProvider2.cpp:70-235). Here the provider is a plain reader
factory: `process(file_list)` returns a reader over all files, with the
same per-pass in-memory cache and shuffle semantics; input types come
from data.feeder and the resulting samples feed DataFeeder directly.
"""

from __future__ import annotations

import random as _random
from typing import Callable, List, Optional, Sequence


class CacheType:
    NO_CACHE = 0
    CACHE_PASS_IN_MEM = 1  # cache samples after the first pass


class _Settings:
    """Mutable bag passed to init_hook and the process function
    (PyDataProvider2.py settings object): carries input_types plus
    whatever init_hook attaches (dictionaries, vocab sizes, ...)."""

    def __init__(self, input_types, kwargs):
        self.input_types = input_types
        self.logger = __import__("logging").getLogger("paddle_tpu.data")
        for k, v in kwargs.items():
            setattr(self, k, v)

    # older reference providers (benchmark/paddle/image/provider.py)
    # call the field `slots`; keep both names as aliases
    @property
    def slots(self):
        return self.input_types

    @slots.setter
    def slots(self, v):
        self.input_types = v


class DataProvider:
    def __init__(
        self,
        fn: Callable,
        input_types,
        should_shuffle: Optional[bool] = None,
        cache: int = CacheType.NO_CACHE,
        init_hook: Optional[Callable] = None,
        skip_faulty_files: int = 0,
        **kwargs,
    ):
        self.fn = fn
        self.input_types = input_types
        self.should_shuffle = should_shuffle
        self.cache = cache
        self.init_hook = init_hook
        # data-pipeline robustness: a file whose process() raises
        # (corrupt/undecodable) is SKIPPED with a counted warning, up
        # to this budget per reader pass, instead of killing the whole
        # pass. 0 = strict (any decode error aborts — the historical
        # behavior). The granularity is per FILE because a raised user
        # generator cannot be resumed mid-record.
        self.skip_faulty_files = skip_faulty_files
        self.faulty_files_skipped = 0  # running total, across passes
        self.kwargs = kwargs
        # per-file-list cache: one decorated fn commonly serves both a
        # train and a test reader (PyDataProvider2 caches per provider
        # instance, which the C++ side creates per data source)
        self._cache_store: dict = {}

    def __call__(self, file_list, **hook_kwargs) -> Callable:
        """Returns a reader creator over `file_list` (a path or list)."""
        if isinstance(file_list, str):
            file_list = [file_list]
        settings = _Settings(self.input_types, self.kwargs)
        if self.init_hook is not None:
            self.init_hook(settings, file_list=file_list, **hook_kwargs)
        # init_hook may declare the types (settings.input_types or the
        # older settings.slots), as in PyDataProvider2.py:150-214
        if settings.input_types is None:
            raise ValueError(
                "provider has no input_types: pass them to @provider or "
                "set settings.input_types/settings.slots in init_hook"
            )
        self.input_types = settings.input_types
        shuffle = (
            self.should_shuffle
            if self.should_shuffle is not None
            else True
        )

        # key includes the hook kwargs: the same files can legitimately
        # be re-read under different init_hook settings (e.g. another
        # vocabulary) and must not serve stale samples
        cache_key = (
            tuple(file_list),
            repr(sorted(hook_kwargs.items())),
        )
        pass_counter = [0]
        use_cache = self.cache == CacheType.CACHE_PASS_IN_MEM

        def generate():
            skipped = 0
            for path in file_list:
                try:
                    yield from self.fn(settings, path)
                except Exception as e:
                    if skipped >= self.skip_faulty_files:
                        raise
                    skipped += 1
                    self.faulty_files_skipped += 1
                    settings.logger.warning(
                        "provider: skipping faulty file %s (%s: %s) — "
                        "%d/%d skips used this pass",
                        path, type(e).__name__, e, skipped,
                        self.skip_faulty_files,
                    )

        def reader():
            if not use_cache and not shuffle:
                # stream: larger-than-RAM datasets in O(1) memory
                yield from generate()
                return
            if use_cache and cache_key in self._cache_store:
                samples = list(self._cache_store[cache_key])
            else:
                samples = list(generate())
                if use_cache:
                    self._cache_store[cache_key] = list(samples)
            if shuffle:
                # deterministic but DIFFERENT order each pass (the
                # reference reshuffles per pass)
                _random.Random(0xC0FFEE + pass_counter[0]).shuffle(
                    samples
                )
                pass_counter[0] += 1
            yield from samples

        return reader


def provider(
    input_types=None,
    should_shuffle=None,
    cache: int = CacheType.NO_CACHE,
    init_hook: Optional[Callable] = None,
    skip_faulty_files: int = 0,
    **kwargs,
):
    """Decorator (PyDataProvider2.py:329):

        @provider(input_types=[dense_vector(784), integer_value(10)],
                  cache=CacheType.CACHE_PASS_IN_MEM)
        def process(settings, filename):
            for img, lbl in read(filename):
                yield img, lbl

    `skip_faulty_files=N` lets a pass survive up to N corrupt/
    undecodable files (counted warning per skip) instead of aborting.
    """
    assert input_types is not None or init_hook is not None, (
        "provider needs input_types (directly or set by init_hook)"
    )

    def deco(fn):
        return DataProvider(
            fn,
            input_types,
            should_shuffle=should_shuffle,
            cache=cache,
            init_hook=init_hook,
            skip_faulty_files=skip_faulty_files,
            **kwargs,
        )

    return deco
