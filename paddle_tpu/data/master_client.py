"""Client for the networked elastic master (native/src/master_server.cc).

The counterpart of the reference's Go master client
(go/master/client.go, consumed from Python via ctypes in
python/paddle/v2/master/client.py): trainer processes connect over TCP,
lease chunk tasks, and report done/failed. `MasterClient` duck-types
`paddle_tpu.native.master.Master`, so `paddle_tpu.data.reader.elastic`
works with either — in-process for single-host, networked for
multi-host fault tolerance.

Resilience: every call reconnects and retries for up to
`retry_seconds` with capped exponential backoff and FULL JITTER
(delay ~ U(0, min(cap, base*2^attempt)) — decorrelates a thundering
herd of trainers hammering a restarting master). Connection-shaped
errors (refused/reset/EOF/timeout: the master is restarting from its
snapshot, go/master/service.go:166-207) retry; malformed frames are a
`MasterProtocolError` and fail fast — retrying a peer that speaks the
wrong protocol only hides a real bug. Every recv is bounded by the
remaining retry budget, so a master that ACCEPTS but never answers (a
black hole) still trips the deadline instead of hanging the trainer
on an unbounded read. When the deadline expires the caller gets a
`MasterRetryTimeout` naming the address, elapsed time and attempt
count instead of a generic socket error. Lease state
lives on the server, so a client reconnect does not lose or duplicate
tasks.
"""

from __future__ import annotations

import random
import socket
import struct
import time
from typing import Optional

from paddle_tpu.obs import metrics as _obs
from paddle_tpu.obs import tracing as _tracing

_BACKOFF_BASE = 0.05
_BACKOFF_CAP = 1.0
_MAX_FRAME = 1 << 30  # >1GiB response length = garbage, not a frame


class MasterError(Exception):
    """Base for master-client failures."""


class MasterProtocolError(MasterError):
    """The peer answered with a malformed frame. NOT retried: the
    master is alive but speaking garbage (version skew, wrong port) —
    reconnecting cannot fix it."""


class MasterRetryTimeout(MasterError, ConnectionError):
    """The master stayed unreachable for the whole retry budget.
    Subclasses ConnectionError so pre-existing `except ConnectionError`
    callers (ping, elastic readers) keep working."""

_OP_ADD_TASK = 1
_OP_GET_TASK = 2
_OP_TASK_DONE = 3
_OP_TASK_FAILED = 4
_OP_PASS_FINISHED = 5
_OP_START_PASS = 6
_OP_COUNT = 7
_OP_SET_LEASE = 8
_OP_SNAPSHOT = 9
_OP_REQUEST_SAVE = 10
_OP_PING = 11
_OP_SHUTDOWN = 12

_OP_NAMES = {
    _OP_ADD_TASK: "add_task", _OP_GET_TASK: "get_task",
    _OP_TASK_DONE: "task_done", _OP_TASK_FAILED: "task_failed",
    _OP_PASS_FINISHED: "pass_finished", _OP_START_PASS: "start_pass",
    _OP_COUNT: "count", _OP_SET_LEASE: "set_lease",
    _OP_SNAPSHOT: "snapshot", _OP_REQUEST_SAVE: "request_save",
    _OP_PING: "ping", _OP_SHUTDOWN: "shutdown",
}


class MasterClient:
    def __init__(
        self,
        addr: str,
        retry_seconds: float = 30.0,
        connect_timeout: float = 5.0,
        trace_carrier: Optional[dict] = None,
    ):
        """`addr` is "host:port". `trace_carrier`: an explicit tracing
        carrier ({"trace_id", "span_id"}, obs/tracing.py) this
        client's RPC spans join — how a trainer's lease/save path
        stays one trace across the master boundary even when the
        calling thread carries no tracing context (e.g. a reader
        thread). With neither a carrier nor an active context, RPCs
        are untraced (zero overhead)."""
        host, _, port = addr.rpartition(":")
        self._host = host or "127.0.0.1"
        self._port = int(port)
        self._retry = retry_seconds
        self._timeout = connect_timeout
        self._trace_carrier = trace_carrier
        self._sock: Optional[socket.socket] = None

    # ---- wire ----
    def _connect(self):
        s = socket.create_connection(
            (self._host, self._port), timeout=self._timeout
        )
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s

    def _recv_full(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self._sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("master closed connection")
            out += chunk
        return out

    def _call_once(self, op: int, body: bytes,
                   timeout: Optional[float] = None) -> tuple:
        """One framed request/response. EVERY send/recv is bounded by
        `timeout` (default: the connect timeout) — a master that
        accepts but never answers (a black-hole failure: alive at TCP,
        dead at the protocol layer) surfaces as socket.timeout and
        enters the normal retry path instead of hanging the trainer
        forever past its retry deadline."""
        if self._sock is None:
            self._connect()
        self._sock.settimeout(
            self._timeout if timeout is None else timeout
        )
        frame = struct.pack("<IB", 1 + len(body), op) + body
        self._sock.sendall(frame)
        (rlen,) = struct.unpack("<I", self._recv_full(4))
        if rlen < 8 or rlen > _MAX_FRAME:
            # too short to carry a status / absurdly long: not our
            # protocol — poison the connection and fail fast
            self.close()
            raise MasterProtocolError(
                f"master at {self._host}:{self._port} sent a malformed "
                f"frame (length {rlen})"
            )
        resp = self._recv_full(rlen)
        (status,) = struct.unpack("<q", resp[:8])
        return status, resp[8:]

    def _call(self, op: int, body: bytes = b"") -> tuple:
        """AT-LEAST-ONCE delivery: a request retried after a connection
        error may have already been processed by the server. The
        protocol is designed so every duplicate is safe-by-semantics:
        duplicate TASK_DONE/TASK_FAILED return -1 (same as an expired
        lease — the caller path already treats that as lease-lost, and
        lease-timeout requeue makes task execution at-least-once anyway,
        exactly like the reference's Go master, go/master/service.go:313);
        duplicate GET_TASK just leases another task; a duplicate
        ADD_TASK can enqueue a chunk twice, which costs one redundant
        task but never corrupts pass accounting (the duplicate is its
        own task with its own done entry).

        Connection errors retry with capped full-jitter backoff until
        `retry_seconds`, then raise MasterRetryTimeout; malformed
        frames raise MasterProtocolError immediately. Each attempt's
        recv is bounded by the REMAINING retry budget (never less than
        the connect timeout, so a late first attempt still gets a fair
        read window) — the deadline fires even against a master that
        accepts and then goes silent. `min_timeout` raises the
        per-attempt floor for ops the server legitimately parks
        (save-model election blocks up to its block_seconds).

        Tracing: when a context or `trace_carrier` is active, the
        whole retried RPC is ONE parent span `master.<op>` whose
        attempts are sibling child spans `master.attempt` — a retry
        storm reads as N short failed attempts under one RPC, not N
        unrelated traces."""
        if self._trace_carrier is not None or \
                _tracing.current() is not None:
            name = _OP_NAMES.get(op, str(op))
            with _tracing.attach(self._trace_carrier):
                with _tracing.span(f"master.{name}", op=op) as sp:
                    try:
                        return self._call_retrying(op, body, sp)
                    except MasterError as e:
                        sp.status = type(e).__name__
                        raise
        return self._call_retrying(op, body, None)

    def _call_retrying(self, op: int, body: bytes, rpc_span) -> tuple:
        start = time.monotonic()
        deadline = start + self._retry
        attempt = 0
        min_timeout = self._timeout
        if op == _OP_REQUEST_SAVE:
            (block_s,) = struct.unpack("<d", body[:8])
            min_timeout = max(min_timeout, block_s + 5.0)
        reg = _obs.get_registry()
        while True:
            att = (
                _tracing.start_span(
                    "master.attempt", trace_id=rpc_span.trace_id,
                    parent_id=rpc_span.span_id, attempt=attempt,
                ) if rpc_span is not None else None
            )
            try:
                remaining = deadline - time.monotonic()
                result = self._call_once(
                    op, body, timeout=max(remaining, min_timeout)
                )
                if att is not None:
                    att.finish("ok")
                return result
            except MasterProtocolError:
                if att is not None:
                    att.finish("protocol_error")
                reg.counter("master_client.protocol_errors").inc()
                raise  # alive-but-wrong peer: retrying hides the bug
            except (OSError, ConnectionError) as e:
                if att is not None:
                    att.finish(type(e).__name__)
                self.close()
                reg.counter("master_client.retries").inc(op=op)
                now = time.monotonic()
                if now >= deadline:
                    reg.counter(
                        "master_client.retry_timeouts"
                    ).inc(op=op)
                    raise MasterRetryTimeout(
                        f"master at {self._host}:{self._port} "
                        f"unreachable for {now - start:.1f}s "
                        f"({attempt + 1} attempts, retry_seconds="
                        f"{self._retry}); last error: "
                        f"{type(e).__name__}: {e}"
                    ) from e
                # full jitter: U(0, min(cap, base*2^attempt)), clipped
                # to the remaining budget so the deadline is honored
                ceil = min(_BACKOFF_CAP, _BACKOFF_BASE * (2 ** attempt))
                delay = min(random.uniform(0, ceil), deadline - now)
                reg.counter("master_client.backoff_s").inc(delay)
                time.sleep(delay)
                attempt += 1

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    # ---- Master-compatible API ----
    def add_task(self, payload) -> int:
        if isinstance(payload, str):
            payload = payload.encode()
        status, _ = self._call(_OP_ADD_TASK, payload)
        return status

    def add_chunk_tasks(self, path: str, num_chunks: int) -> None:
        import json

        for i in range(num_chunks):
            self.add_task(json.dumps({"path": path, "chunk": i}).encode())

    def get_task(self) -> Optional[tuple]:
        """Lease a task: (task_id, payload) or None if nothing leasable."""
        status, body = self._call(_OP_GET_TASK)
        if status == -3:
            return None
        if status < 0:
            raise RuntimeError(f"get_task failed (code {status})")
        (lease,) = struct.unpack("<q", body[:8])
        return lease, body[8 : 8 + status]

    def task_done(self, task_id: int) -> bool:
        status, _ = self._call(_OP_TASK_DONE, struct.pack("<q", task_id))
        return status == 0

    def task_failed(self, task_id: int) -> bool:
        status, _ = self._call(_OP_TASK_FAILED, struct.pack("<q", task_id))
        return status == 0

    def pass_finished(self) -> bool:
        status, _ = self._call(_OP_PASS_FINISHED)
        return status == 1

    def start_pass(self) -> int:
        status, _ = self._call(_OP_START_PASS)
        return status

    @property
    def counts(self) -> dict:
        out = {}
        for i, k in enumerate(("todo", "pending", "done", "discarded")):
            status, _ = self._call(_OP_COUNT, struct.pack("<i", i))
            out[k] = status
        return out

    def set_lease(self, seconds: float) -> None:
        self._call(_OP_SET_LEASE, struct.pack("<d", seconds))

    def snapshot(self) -> None:
        status, _ = self._call(_OP_SNAPSHOT)
        if status != 0:
            raise IOError(
                "snapshot failed"
                + (" (server has no snapshot path)" if status == -2 else "")
            )

    def request_save_model(
        self, trainer_id: str, block_seconds: float = 60.0
    ) -> bool:
        """Save-model election (go/master/service.go:467-495)."""
        status, _ = self._call(
            _OP_REQUEST_SAVE,
            struct.pack("<d", block_seconds) + trainer_id.encode(),
        )
        if status < 0:
            raise ValueError("trainer_id must be non-empty")
        return status == 1

    def ping(self) -> bool:
        try:
            return self._call_once(_OP_PING, b"")[0] == 0
        except (OSError, ConnectionError):
            self.close()
            return False

    def shutdown(self) -> None:
        """Ask the serving process to stop (it snapshots first if
        configured)."""
        try:
            self._call_once(_OP_SHUTDOWN, b"")
        except (OSError, ConnectionError):
            pass
        finally:
            self.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
