"""Inference API + AOT-compiled export.

Reference: python/paddle/v2/inference.py:9,93 (Inference wrapping a
GradientMachine in test mode; module-level `infer(output_layer=...,
input=...)`) and the C-API's merged-model deployment flow
(capi/gradient_machine.h:52, trainer/MergeModel.cpp). The runner itself
is trainer.Inferencer; this module adds the v2-style front door and the
TPU-native deployment artifact: `export_compiled` serializes the
jit-compiled forward as a portable StableHLO blob via jax.export — the
analogue of shipping the merged binary to the pure-C runtime — and
`load_compiled` runs it without the model-building code present.
"""

from __future__ import annotations

from paddle_tpu.core.arg import Arg
from paddle_tpu.trainer.trainer import Inferencer

Inference = Inferencer  # v2 name

__all__ = ["Inference", "Inferencer", "infer", "export_compiled",
           "load_compiled"]


_ARG_SERIALIZATION_REGISTERED = False


def _register_arg_serialization():
    """jax.export needs (de)serializers for custom pytree nodes; Arg is
    a register_dataclass pytree, so auxdata is its static field tuple."""
    global _ARG_SERIALIZATION_REGISTERED
    if _ARG_SERIALIZATION_REGISTERED:
        return
    import json

    from jax import export as jexport

    try:
        jexport.register_pytree_node_serialization(
            Arg,
            serialized_name="paddle_tpu.core.arg.Arg",
            serialize_auxdata=lambda aux: json.dumps(aux).encode(),
            deserialize_auxdata=lambda b: tuple(json.loads(b.decode())),
        )
    except ValueError:
        pass  # already registered in this process
    _ARG_SERIALIZATION_REGISTERED = True


# export envelope: magic + sha256(payload) + payload. The digest lets
# load_compiled reject a torn or bit-flipped artifact with a clear
# ValueError BEFORE the bytes reach XLA's deserializer (whose failure
# mode on corrupt input ranges from cryptic to process-fatal).
_EXPORT_MAGIC = b"PTPUXP1\x00"


def export_compiled(inferencer: Inferencer, example_feed: dict) -> bytes:
    """Serialize the jitted forward specialized to `example_feed`'s
    shapes/dtypes as a checksummed StableHLO artifact (bytes)."""
    import hashlib

    from jax import export as jexport

    _register_arg_serialization()
    exp = jexport.export(inferencer._fwd)(
        inferencer.params, inferencer.state, example_feed
    )
    payload = exp.serialize()
    return _EXPORT_MAGIC + hashlib.sha256(payload).digest() + payload


def load_compiled(blob: bytes, source: str = "<compiled blob>"):
    """Rehydrate an export_compiled artifact; returns
    fn(params, state, feed) -> {name: Arg}. Runs without the
    model-building code (config/layers) present. `source` names the
    artifact (e.g. its path) in error messages. A truncated or
    corrupted blob raises ValueError naming the artifact instead of
    crashing inside XLA."""
    import hashlib

    from jax import export as jexport

    _register_arg_serialization()
    blob = bytes(blob)
    if blob.startswith(_EXPORT_MAGIC):
        head = len(_EXPORT_MAGIC)
        digest, payload = blob[head:head + 32], blob[head + 32:]
        if len(digest) < 32 or hashlib.sha256(payload).digest() != digest:
            kind = "truncated" if len(blob) < head + 33 else "corrupt"
            raise ValueError(
                f"compiled StableHLO artifact {source!r} is {kind}: "
                f"checksum mismatch over {len(payload)} payload bytes "
                f"— re-run export_compiled"
            )
    else:
        payload = blob  # pre-envelope artifact: best-effort load
    try:
        exp = jexport.deserialize(payload)
    except Exception as e:
        raise ValueError(
            f"compiled StableHLO artifact {source!r} failed to "
            f"deserialize (truncated/corrupt or version-skewed): "
            f"{type(e).__name__}: {e}"
        ) from e
    return exp.call


def infer(output=None, parameters=None, input=None, network=None,
          feeder=None):
    """One-shot inference (v2/inference.py:93 infer()). `input` is a
    feed dict of Args (or raw arrays, wrapped as dense Args; use
    `feeder` for sequence/ids packing). Returns one ndarray for a
    single output, else a list in `output` order."""
    outs = (
        None
        if output is None
        else [output] if isinstance(output, str) else list(output)
    )
    inf = Inferencer(network, parameters, outputs=outs)
    outs = inf.output_names
    feed = feeder(input) if feeder is not None else input
    feed = {
        k: (v if isinstance(v, Arg) else Arg(value=v))
        for k, v in feed.items()
    }
    res = inf.infer(feed)
    vals = [res[n] for n in outs]
    return vals[0] if len(vals) == 1 else vals
