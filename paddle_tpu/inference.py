"""Inference API + AOT-compiled export + the verified program cache.

Reference: python/paddle/v2/inference.py:9,93 (Inference wrapping a
GradientMachine in test mode; module-level `infer(output_layer=...,
input=...)`) and the C-API's merged-model deployment flow
(capi/gradient_machine.h:52, trainer/MergeModel.cpp). The runner itself
is trainer.Inferencer; this module adds the v2-style front door and the
TPU-native deployment artifacts:

- `export_compiled` serializes the jit-compiled forward as a portable
  StableHLO blob via jax.export — the analogue of shipping the merged
  binary to the pure-C runtime — and `load_compiled` runs it without
  the model-building code present.
- `store_verified` / `load_verified`: the **verified AOT program
  cache** (ISSUE 16). The stock persistent XLA compilation cache was
  observed deserializing *corrupt* executables on this runtime
  (tests/conftest.py documents the heap corruption), so the only
  trustworthy fast-boot path is one we verify ourselves: every cache
  entry carries sha256 digests over all of its files, the compiled
  program's HLO text, and a policy audited by `analysis/hlo_audit` —
  a replica may only boot from an entry whose digests match AND whose
  HLO passes the audit gate. Entries are published atomically (write
  to a temp dir, rename), so a writer SIGKILLed mid-store can never
  leave a half-visible entry.
"""

from __future__ import annotations

from paddle_tpu.core.arg import Arg
from paddle_tpu.trainer.trainer import Inferencer

Inference = Inferencer  # v2 name

__all__ = ["Inference", "Inferencer", "infer", "export_compiled",
           "load_compiled", "CompiledArtifactError", "VerifiedCacheError",
           "store_verified", "load_verified", "has_verified",
           "CACHE_META_SCHEMA"]


class CompiledArtifactError(ValueError):
    """Typed envelope failure for export_compiled artifacts. `reason`
    is one of: truncated, corrupt, version, no_envelope, deserialize.
    Subclasses ValueError so pre-existing `except ValueError` handlers
    keep catching it."""

    def __init__(self, source: str, reason: str, detail: str):
        super().__init__(
            f"compiled StableHLO artifact {source!r} is {reason}: "
            f"{detail}"
        )
        self.source = source
        self.reason = reason


class VerifiedCacheError(RuntimeError):
    """The verified AOT cache refused an entry at boot. `reason` is
    one of: missing, meta, digest, audit."""

    def __init__(self, reason: str, detail: str):
        super().__init__(f"verified cache refused ({reason}): {detail}")
        self.reason = reason


_ARG_SERIALIZATION_REGISTERED = False


def _register_arg_serialization():
    """jax.export needs (de)serializers for custom pytree nodes; Arg is
    a register_dataclass pytree, so auxdata is its static field tuple."""
    global _ARG_SERIALIZATION_REGISTERED
    if _ARG_SERIALIZATION_REGISTERED:
        return
    import json

    from jax import export as jexport

    try:
        jexport.register_pytree_node_serialization(
            Arg,
            serialized_name="paddle_tpu.core.arg.Arg",
            serialize_auxdata=lambda aux: json.dumps(aux).encode(),
            deserialize_auxdata=lambda b: tuple(json.loads(b.decode())),
        )
    except ValueError:
        pass  # already registered in this process
    _ARG_SERIALIZATION_REGISTERED = True


# export envelope: magic + version byte + sha256(payload) + payload.
# The digest lets load_compiled reject a torn or bit-flipped artifact
# with a typed CompiledArtifactError BEFORE the bytes reach XLA's
# deserializer (whose failure mode on corrupt input ranges from
# cryptic to process-fatal). The explicit version byte (ISSUE 16)
# lets the envelope itself evolve without a magic collision; v1
# envelopes (magic "PTPUXP1\x00", no version byte) still load.
_EXPORT_MAGIC = b"PTPUXP\x00"
_EXPORT_VERSION = 2
_LEGACY_MAGIC_V1 = b"PTPUXP1\x00"
_DIGEST_LEN = 32  # sha256


def _wrap_envelope(payload: bytes) -> bytes:
    import hashlib

    return (_EXPORT_MAGIC + bytes([_EXPORT_VERSION])
            + hashlib.sha256(payload).digest() + payload)


def _unwrap_envelope(blob: bytes, source: str,
                     require_envelope: bool = False):
    """Return the digest-verified payload, or raise
    CompiledArtifactError. Without `require_envelope`, a blob carrying
    no recognizable magic passes through untouched (pre-envelope
    artifact: best-effort)."""
    import hashlib

    blob = bytes(blob)
    if blob.startswith(_EXPORT_MAGIC):
        vpos = len(_EXPORT_MAGIC)
        if len(blob) < vpos + 1:
            raise CompiledArtifactError(
                source, "truncated", "envelope ends before the "
                "version byte — re-run export")
        version = blob[vpos]
        if version != _EXPORT_VERSION:
            raise CompiledArtifactError(
                source, "version",
                f"envelope version {version} != {_EXPORT_VERSION} "
                f"(or a corrupted version byte)")
        head = vpos + 1
    elif blob.startswith(_LEGACY_MAGIC_V1):
        head = len(_LEGACY_MAGIC_V1)
    else:
        if require_envelope:
            raise CompiledArtifactError(
                source, "corrupt",
                "no envelope magic found (corrupted header, or not "
                "an export_compiled artifact)")
        return blob  # pre-envelope artifact: best-effort load
    digest = blob[head:head + _DIGEST_LEN]
    payload = blob[head + _DIGEST_LEN:]
    if len(digest) < _DIGEST_LEN or not payload:
        raise CompiledArtifactError(
            source, "truncated",
            f"{len(blob)} bytes is shorter than the envelope header "
            f"— re-run export")
    if hashlib.sha256(payload).digest() != digest:
        raise CompiledArtifactError(
            source, "corrupt",
            f"checksum mismatch over {len(payload)} payload bytes "
            f"— re-run export")
    return payload


def export_compiled(inferencer: Inferencer, example_feed: dict) -> bytes:
    """Serialize the jitted forward specialized to `example_feed`'s
    shapes/dtypes as a checksummed StableHLO artifact (bytes)."""
    from jax import export as jexport

    _register_arg_serialization()
    exp = jexport.export(inferencer._fwd)(
        inferencer.params, inferencer.state, example_feed
    )
    return _wrap_envelope(exp.serialize())


def load_compiled(blob: bytes, source: str = "<compiled blob>",
                  require_envelope: bool = False):
    """Rehydrate an export_compiled artifact; returns
    fn(params, state, feed) -> {name: Arg}. Runs without the
    model-building code (config/layers) present. `source` names the
    artifact (e.g. its path) in error messages. A truncated or
    corrupted blob raises CompiledArtifactError (a ValueError) naming
    the artifact instead of crashing inside XLA; `require_envelope`
    additionally rejects blobs with no recognizable envelope (the
    verified-cache boot path sets it)."""
    from jax import export as jexport

    _register_arg_serialization()
    payload = _unwrap_envelope(blob, source,
                               require_envelope=require_envelope)
    try:
        exp = jexport.deserialize(payload)
    except Exception as e:
        raise CompiledArtifactError(
            source, "deserialize",
            f"payload failed to deserialize (truncated/corrupt or "
            f"version-skewed): {type(e).__name__}: {e}"
        ) from e
    return exp.call


# ---------------------------------------------------------------------
# verified AOT program cache (ISSUE 16)
#
# Entry layout (one directory per key under cache_dir):
#     <key>/program.exec     enveloped pickle of the serialized XLA
#                            executable (+ in/out tree defs) — the
#                            fast-boot path: deserialize_and_load,
#                            no trace/lower/compile
#     <key>/program.shlo     enveloped jax.export StableHLO — the
#                            portable fallback when the executable is
#                            version-skewed (recompiles on first call)
#     <key>/program.hlo.txt  the compiled program's HLO text — what
#                            the hlo_audit boot gate reads
#     <key>/meta.json        schema + sha256 per file + the audit
#                            policy the entry was stored under
#
# Publication is atomic: everything is written into a ".tmp-*" sibling
# and renamed into place, so a SIGKILL mid-store leaves only ignored
# temp garbage, never a half-visible entry.

CACHE_META_SCHEMA = "paddle-tpu-verified-cache/v1"
_CACHE_FILES = ("program.exec", "program.shlo", "program.hlo.txt")


def _sha256_file(path: str) -> str:
    import hashlib

    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def has_verified(cache_dir: str, key: str) -> bool:
    import os

    return os.path.exists(os.path.join(cache_dir, key, "meta.json"))


def store_verified(cache_dir: str, key: str, fn, example_args: tuple,
                   policy: dict = None) -> dict:
    """Compile `fn` (a jax-traceable callable over plain arrays)
    specialized to `example_args`, audit its HLO against `policy`
    (analysis/hlo_audit keys: host_transfer_budget, total_bytes_max,
    forbid_tt_materialization, ...), and publish the verified cache
    entry. Raises VerifiedCacheError("audit") — and publishes nothing
    — when the program already violates the policy at store time.
    Returns the entry's meta dict."""
    import json
    import os
    import pickle
    import shutil
    import tempfile
    import time

    import jax
    from jax import export as jexport
    from jax.experimental.serialize_executable import serialize

    from paddle_tpu.analysis import hlo_audit as _audit

    policy = dict(policy or {})
    _register_arg_serialization()
    jitted = jax.jit(fn)
    compiled = jitted.lower(*example_args).compile()
    hlo_text = compiled.as_text()
    exec_payload = pickle.dumps(serialize(compiled))
    shlo_payload = jexport.export(jitted)(*example_args).serialize()

    os.makedirs(cache_dir, exist_ok=True)
    tmp = tempfile.mkdtemp(prefix=f".tmp-{key}-", dir=cache_dir)
    try:
        with open(os.path.join(tmp, "program.exec"), "wb") as f:
            f.write(_wrap_envelope(exec_payload))
        with open(os.path.join(tmp, "program.shlo"), "wb") as f:
            f.write(_wrap_envelope(shlo_payload))
        hlo_path = os.path.join(tmp, "program.hlo.txt")
        with open(hlo_path, "w") as f:
            f.write(hlo_text)
        report = _audit.audit_capture(hlo_path, policy, report={})
        if not report["ok"]:
            bad = "; ".join(
                f"[{c['name']}] {c['detail']}"
                for c in report["checks"] if not c["ok"]
            )
            raise VerifiedCacheError(
                "audit", f"program for key {key!r} violates the "
                f"store policy: {bad}")
        meta = {
            "schema": CACHE_META_SCHEMA,
            "key": key,
            "created_unix": time.time(),
            "jax_version": jax.__version__,
            "policy": policy,
            "files": {
                name: _sha256_file(os.path.join(tmp, name))
                for name in _CACHE_FILES
            },
            "n_instructions": report["n_instructions"],
            "total_bytes": report["total_bytes"],
            "example_args": [
                {"shape": list(getattr(a, "shape", ())),
                 "dtype": str(getattr(a, "dtype", ""))}
                for a in example_args
            ],
        }
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        final = os.path.join(cache_dir, key)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)
        return meta
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


class VerifiedProgram:
    """A booted cache entry: `call(*args)` runs the program; `via` is
    "exec" (deserialized executable, no compile) or "shlo" (portable
    export fallback — compiles on first call); `meta` is the entry's
    verified meta dict; `audit` the boot-gate report."""

    def __init__(self, call, via: str, meta: dict, audit: dict):
        self.call = call
        self.via = via
        self.meta = meta
        self.audit = audit

    def __call__(self, *args):
        return self.call(*args)


def load_verified(cache_dir: str, key: str,
                  policy: dict = None) -> VerifiedProgram:
    """Boot a program from the verified cache: digests first, then the
    hlo_audit policy gate, and only then XLA deserialization — the
    integrity check the stock persistent cache lacks. Extra `policy`
    keys tighten (merge over) the stored policy. Raises
    VerifiedCacheError before any unverified byte reaches XLA."""
    import json
    import os
    import pickle

    from paddle_tpu.analysis import hlo_audit as _audit

    entry = os.path.join(cache_dir, key)
    meta_path = os.path.join(entry, "meta.json")
    if not os.path.exists(meta_path):
        raise VerifiedCacheError(
            "missing", f"no entry for key {key!r} under {cache_dir}")
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except (OSError, ValueError) as e:
        raise VerifiedCacheError(
            "meta", f"{meta_path}: unreadable ({e})") from e
    if meta.get("schema") != CACHE_META_SCHEMA:
        raise VerifiedCacheError(
            "meta", f"{meta_path}: schema {meta.get('schema')!r} != "
                    f"{CACHE_META_SCHEMA!r}")
    files = meta.get("files") or {}
    for name in _CACHE_FILES:
        path = os.path.join(entry, name)
        want = files.get(name)
        if not want or not os.path.exists(path):
            raise VerifiedCacheError(
                "digest", f"{name}: missing from the entry or its "
                          f"meta — torn or tampered entry")
        got = _sha256_file(path)
        if got != want:
            raise VerifiedCacheError(
                "digest", f"{name}: sha256 {got[:12]}… != recorded "
                          f"{want[:12]}… — corrupt or tampered entry")
    merged = dict(meta.get("policy") or {})
    if policy:
        merged.update(policy)
    hlo_path = os.path.join(entry, "program.hlo.txt")
    try:
        audit = _audit.audit_capture(hlo_path, merged, report={})
    except VerifiedCacheError:
        raise
    except BaseException as e:  # SystemExit from an unparseable capture
        raise VerifiedCacheError(
            "audit", f"audit could not run over {hlo_path}: "
                     f"{type(e).__name__}: {e}") from e
    if not audit["ok"]:
        bad = "; ".join(
            f"[{c['name']}] {c['detail']}"
            for c in audit["checks"] if not c["ok"]
        )
        raise VerifiedCacheError(
            "audit", f"entry {key!r} fails the boot policy gate: {bad}")
    # digests + audit passed: the bytes may now reach XLA. Fast path =
    # the serialized executable; version skew falls back to the
    # portable StableHLO export (which recompiles on first call).
    exec_path = os.path.join(entry, "program.exec")
    with open(exec_path, "rb") as f:
        exec_blob = f.read()
    try:
        from jax.experimental.serialize_executable import (
            deserialize_and_load,
        )

        payload = _unwrap_envelope(exec_blob, exec_path,
                                   require_envelope=True)
        exe, in_tree, out_tree = pickle.loads(payload)
        compiled = deserialize_and_load(exe, in_tree, out_tree)
        return VerifiedProgram(compiled, "exec", meta, audit)
    except CompiledArtifactError:
        raise  # digest said clean but the envelope didn't: refuse
    except Exception:
        with open(os.path.join(entry, "program.shlo"), "rb") as f:
            shlo_blob = f.read()
        call = load_compiled(shlo_blob,
                             source=os.path.join(entry, "program.shlo"),
                             require_envelope=True)
        return VerifiedProgram(call, "shlo", meta, audit)


def infer(output=None, parameters=None, input=None, network=None,
          feeder=None):
    """One-shot inference (v2/inference.py:93 infer()). `input` is a
    feed dict of Args (or raw arrays, wrapped as dense Args; use
    `feeder` for sequence/ids packing). Returns one ndarray for a
    single output, else a list in `output` order."""
    outs = (
        None
        if output is None
        else [output] if isinstance(output, str) else list(output)
    )
    inf = Inferencer(network, parameters, outputs=outs)
    outs = inf.output_names
    feed = feeder(input) if feeder is not None else input
    feed = {
        k: (v if isinstance(v, Arg) else Arg(value=v))
        for k, v in feed.items()
    }
    res = inf.infer(feed)
    vals = [res[n] for n in outs]
    return vals[0] if len(vals) == 1 else vals
