import json
import bench
bench._setup()
import numpy as np
from paddle_tpu.core import flags as _flags
from paddle_tpu.core.arg import id_arg
from paddle_tpu.core.config import OptimizationConf
from paddle_tpu.models import stacked_lstm_classifier

bs, T, hidden = 128, 100, 256
rng = np.random.default_rng(0)
feed = {"words": id_arg(rng.integers(0, 30000, (bs, T)).astype(np.int32), np.full((bs,), T, np.int32)),
        "label": id_arg(rng.integers(0, 2, bs).astype(np.int32))}
opt = OptimizationConf(learning_method="adam", learning_rate=2e-3)

def run(use_fused):
    _flags.set_flag("use_pallas_rnn", use_fused)
    try:
        conf = stacked_lstm_classifier(vocab_size=30000, emb_dim=128, hidden=hidden, num_layers=2, num_classes=2)
        return bench._time_train(conf, feed, opt, iters=30, warmup=30)
    finally:
        _flags.set_flag("use_pallas_rnn", None)

res = {"scan": [], "fused": []}
for rep in range(3):
    res["scan"].append(round(run(False), 3))
    res["fused"].append(round(run(True), 3))
print(json.dumps({"hidden": hidden, **res,
                  "speedup_min": round(min(res["scan"]) / min(res["fused"]), 3)}))
